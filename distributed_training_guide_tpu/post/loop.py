"""The on-policy post-training loop: rollout → score → update → publish.

One iteration drives both runtimes this package already owns, end to
end:

1. **Rollout** — the co-resident serve engine generates a batch of
   variable-length samples under the paged pool (speculative decoding
   composes: early policies emit repetitive text, exactly what the
   n-gram drafter accelerates), reproducible per derived seed
   (``post/rollout.py``), ledgered as each sample completes.
2. **Score** — a pluggable scorer (``post/score.py``): programmatic
   reward, reward-model forward, or full teacher distributions.
3. **Update** — the masked ragged post step (``train/step.py
   make_post_step``): rollouts pack by ``group_sizes`` through the
   ``ops/grouped_matmul.py`` machinery, prompt tokens masked, only
   sampled continuations carry gradient; REINFORCE-with-baseline or
   distillation-KL behind the one ``post_loss`` seam; LoRA
   (``lora_only``) keeps the update adapter-sized.
4. **Publish** — the refreshed params land in the engine via
   ``ModelPrograms.publish_params``: a donated buffer swap into the
   already-compiled programs, retrace-free by design (the acceptance pin:
   jit cache sizes flat across publishes; decode-after-publish bitwise
   equal to a fresh engine built from the published params). A NaN
   update never reaches the engine: the in-jit guard
   (``--guard-policy skip``) reverts the state and the loop GATES the
   publish on the step's ``notfinite`` flag.

``publish_every`` is the staleness knob: publishing every iteration is
fully on-policy; larger values trade policy freshness for fewer
merge+publish walls (the related-topics/post-training chapter has the
tradeoff discussion). ``frozen=True`` runs rollout+score only — the
one-new-variable control the bench rung measures against.
"""
from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

from .rollout import RolloutLedger, generate_rollouts, pad_bucket
from .score import Scorer


def pack_rollouts(rollouts, scores, *, pad_to: int,
                  vocab_size: Optional[int] = None,
                  with_teacher: bool = False) -> dict:
    """Pack B ragged rollouts into the post step's fixed-shape batch:
    ``tokens [B, pad_to]`` (prompt + continuation, zero pad),
    ``prompt_lens``/``total_lens`` (the per-token loss mask's raw
    material — ``group_sizes = total - prompt`` is derived in-step),
    ``rewards``, ``group_ids``, and under ``with_teacher`` the
    ``teacher_logprobs [B, pad_to, V]`` scattered at SOURCE positions
    (row p = the teacher's distribution for predicting token p+1). The
    shape is static per loop, so the compiled post step never retraces
    across iterations of differing raggedness."""
    b = len(rollouts)
    tokens = np.zeros((b, pad_to), np.int32)
    prompt_lens = np.zeros((b,), np.int32)
    total_lens = np.zeros((b,), np.int32)
    rewards = np.zeros((b,), np.float32)
    group_ids = np.zeros((b,), np.int32)
    teacher = None
    if with_teacher:
        if vocab_size is None:
            raise ValueError("with_teacher packing needs vocab_size")
        teacher = np.zeros((b, pad_to, vocab_size), np.float32)
    for i, (r, s) in enumerate(zip(rollouts, scores)):
        seq = list(r.prompt_ids) + list(r.generated_ids)
        if len(seq) > pad_to:
            raise ValueError(
                f"rollout {i} is {len(seq)} tokens but the packed batch "
                f"is {pad_to} wide — size pad_to to prompt+max_new")
        tokens[i, :len(seq)] = seq
        prompt_lens[i] = len(r.prompt_ids)
        total_lens[i] = len(seq)
        rewards[i] = s.reward
        group_ids[i] = r.group_id
        if with_teacher:
            if s.teacher_logprobs is None:
                raise ValueError(
                    f"rollout {i} has no teacher_logprobs — the "
                    f"distill_kl objective needs a teacher-providing "
                    f"scorer (TeacherScorer)")
            g = len(r.generated_ids)
            pl = len(r.prompt_ids)
            teacher[i, pl - 1:pl - 1 + g] = s.teacher_logprobs
    out = {"tokens": tokens, "prompt_lens": prompt_lens,
           "total_lens": total_lens, "rewards": rewards,
           "group_ids": group_ids}
    if with_teacher:
        out["teacher_logprobs"] = teacher
    return out


class PostTrainingLoop:
    """Drives rollout → score → update → publish against a Trainer and a
    live serve engine that SHARE the policy weights.

    The caller builds the engine from the trainer state's MERGED params
    (``merged_params(trainer, state)`` below) so iteration 0's rollouts
    run the exact step-0 policy; after every update the loop merges (one
    compiled program for LoRA bundles) and publishes.

    ``state`` is the TrainState the updates thread through; ``ledger``
    makes rollout batches crash-recoverable (see ``post/rollout.py``).
    ``frozen=True`` disables update AND publish — the control loop.
    """

    def __init__(self, trainer, engine, scorer: Scorer,
                 prompts: Sequence, *, state,
                 objective: str = "reinforce", baseline: str = "batch",
                 max_new_tokens: int = 16, temperature: float = 0.7,
                 top_k: int = 0, top_p: float = 1.0, base_seed: int = 0,
                 publish_every: int = 1, publish_mode: str = "merged",
                 ledger: Optional[RolloutLedger] = None,
                 group_ids=None, frozen: bool = False,
                 gmm_impl: str = "auto"):
        from ..train.step import make_post_step

        self.trainer = trainer
        self.engine = engine
        self.scorer = scorer
        self.prompts = [list(p) for p in prompts]
        self.state = state
        self.objective = objective
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k, self.top_p = top_k, top_p
        self.base_seed = base_seed
        self.publish_every = publish_every
        if publish_mode not in ("merged", "adapter"):
            raise ValueError(f"publish_mode must be 'merged' or "
                             f"'adapter', got {publish_mode!r}")
        if publish_mode == "adapter" and not frozen:
            # fail at construction, not at the first publish boundary:
            # adapter mode needs a LoRA-shaped state AND a pooled engine
            adapter_payload(state.params)
            if getattr(engine, "adapter_pool", None) is None:
                raise ValueError(
                    "publish_mode='adapter' needs an engine built with "
                    "max_adapters= (an adapter pool to insert into)")
        self.publish_mode = publish_mode
        # the tenant's pool slot; allocated by the first boundary
        # publish, then republished in place. Iteration 0 rolls out on
        # adapter 0 (the base policy) — identical to the merged policy
        # because LoRA's B factor initializes to zero.
        self.adapter_slot: Optional[int] = None
        self.ledger = ledger
        self.group_ids = group_ids
        self.frozen = frozen
        self._needs_teacher = objective == "distill_kl"
        if self._needs_teacher and not scorer.provides_teacher_logprobs:
            raise ValueError(
                f"objective='distill_kl' needs a scorer that provides "
                f"teacher logprobs (TeacherScorer); "
                f"{type(scorer).__name__} does not")
        if baseline == "group":
            gids = list(group_ids) if group_ids is not None else []
            if not gids or max(gids.count(g) for g in set(gids)) < 2:
                raise ValueError(
                    "baseline='group' needs group_ids with at least one "
                    "group of >= 2 rollouts: singleton groups (the "
                    "default group_id=index) make every advantage "
                    "(r - mean_g)/std_g exactly zero, so the loop would "
                    "train nothing while looking busy — repeat each "
                    "prompt group-size times and tag the copies")
        self.pad_to = pad_bucket(max(len(p) for p in self.prompts)
                                 + max_new_tokens)
        self._merge = merge_fn(trainer.bundle)
        self.post_step = None if frozen else make_post_step(
            trainer, objective=objective, baseline=baseline,
            gmm_impl=gmm_impl)
        self.iteration = 0
        self.publishes = 0
        self.publishes_skipped = 0
        self._publish_due = False
        self.history: list = []

    def run_iteration(self) -> dict:
        """One rollout → score → update → publish pass. Returns (and
        appends to ``history``) the iteration's metric dict."""
        i = self.iteration
        rollouts, rstats = generate_rollouts(
            self.engine, self.prompts, iteration=i,
            base_seed=self.base_seed, max_new_tokens=self.max_new_tokens,
            temperature=self.temperature, top_k=self.top_k,
            top_p=self.top_p, group_ids=self.group_ids,
            ledger=self.ledger,
            adapter_id=(self.adapter_slot or 0))
        scores = self.scorer.score(rollouts)
        metrics = {"iteration": i, **rstats,
                   "reward_mean": float(np.mean([s.reward
                                                 for s in scores])),
                   "publish_ms": 0.0, "published": False,
                   "publish_skipped_nonfinite": False,
                   "step_s": 0.0}
        if not self.frozen:
            batch = pack_rollouts(
                rollouts, scores, pad_to=self.pad_to,
                vocab_size=self.trainer.bundle.config.vocab_size,
                with_teacher=self._needs_teacher)
            t0 = time.perf_counter()
            self.state, m = self.post_step(self.state, batch)
            m = {k: float(v) for k, v in m.items()}
            metrics["step_s"] = round(time.perf_counter() - t0, 4)
            metrics.update(loss=m["loss"], grad_norm=m["grad_norm"],
                           post_tokens=m["post_tokens"],
                           post_logprob_mean=m["post_logprob_mean"])
            # a NaN/Inf update must not poison the publishing engine:
            # under --guard-policy skip the in-jit guard already reverted
            # params/opt state to the pre-step values — gating here means
            # the engine keeps serving the last GOOD policy. A skipped
            # boundary publish stays DUE (not dropped): the next finite
            # step publishes, so a NaN never doubles the staleness
            # window on publish_every > 1 schedules.
            nonfinite = m.get("notfinite", 0.0) > 0.0
            if (self.publish_every
                    and (i + 1) % self.publish_every == 0):
                self._publish_due = True
            if nonfinite:
                if self._publish_due:
                    self.publishes_skipped += 1
                    metrics["publish_skipped_nonfinite"] = True
            elif self._publish_due:
                t0 = time.perf_counter()
                if self.publish_mode == "adapter":
                    # adapter-sized publish: insert (then republish in
                    # place) the trained factors as a pool tenant — the
                    # engine keeps serving base traffic on adapter 0
                    # while the policy rides its own slot
                    self.adapter_slot = self.engine.publish_adapter(
                        adapter_payload(self.state.params),
                        name="post-policy", slot=self.adapter_slot)
                else:
                    self.engine.publish_params(
                        self._merge(self.state.params))
                metrics["publish_ms"] = round(
                    1000 * (time.perf_counter() - t0), 2)
                metrics["published"] = True
                self.publishes += 1
                self._publish_due = False
        self.iteration += 1
        self.history.append(metrics)
        return metrics

    def run(self, n_iterations: int) -> list:
        """``n_iterations`` full passes; returns the history slice."""
        for _ in range(n_iterations):
            self.run_iteration()
        # NOT [-n:]: [-0:] would hand back the ENTIRE past history
        return self.history[len(self.history) - n_iterations:]


def adapter_payload(params) -> dict:
    """The trained LoRA factors in the EXACT layout the serve plane's
    adapter pool ingests (``{target: {"a": [L, in, r], "b": [L, r, out]}}``
    — the ``params["lora"]`` subtree as the trainer threads it, no
    reshaping). Raises when the state carries no LoRA subtree: a dense
    policy has no adapter-sized publish, use ``publish_params``."""
    if not isinstance(params, dict) or "lora" not in params:
        raise ValueError(
            "state.params has no 'lora' subtree — adapter publishing "
            "needs a lora_bundle-wrapped trainer (dense policies "
            "publish merged weights via publish_params)")
    return params["lora"]


def publish_trained_adapter(target, state, *, name=None, slot=None,
                            force: bool = False) -> int:
    """Publish a trainer state's LoRA adapter into a serving target's
    adapter pool — ``target`` is a ServeEngine, DisaggEngine, or Router
    (same ``publish_adapter`` facade on all three; the router makes it
    fleet-wide all-or-nothing). The payload is adapter-sized: for a
    rank-8 two-target debug model that's ~100x smaller than a full
    ``publish_params``, and the insert is one cached jit with a traced
    slot index, so pushing every boundary never retraces. Returns the
    pool slot the tenant landed in (pass it back as ``slot=`` to
    republish in place)."""
    return target.publish_adapter(adapter_payload(state.params),
                                  name=name, slot=slot, force=force)


def merge_fn(bundle):
    """params -> engine-layout params for the PUBLISH path: the compiled
    LoRA merge for wrapped bundles (one program, reused every publish),
    identity otherwise — ``ModelPrograms.publish_params`` snapshots the
    incoming leaves itself, so a pre-copy here would just double the
    per-publish param traffic. Engine CONSTRUCTION must not use the
    identity directly (``merged_params`` below adds the copy there: the
    trainer donates its state into the next update step, and an engine
    built on the trainer's own buffers would read deleted memory one
    step later)."""
    if getattr(bundle, "lora_base", None) is not None:
        from ..models.lora import jit_merge

        return jit_merge(bundle)
    return lambda params: params


def merged_params(trainer, state):
    """The engine-construction helper: the CURRENT policy in the serve
    engine's (base) layout, in buffers the ENGINE will own — what a
    co-resident engine must be built from so rollout 0 runs the exact
    initial policy and survives the trainer donating its state."""
    merged = merge_fn(trainer.bundle)(state.params)
    if merged is state.params:      # identity merge: snapshot for the
        import jax                  # engine (jit output = fresh buffers)
        import jax.numpy as jnp

        merged = jax.jit(lambda t: jax.tree.map(jnp.copy, t))(merged)
    return merged


def qlora_base(base_params, *, family: str = "llama"):
    """The QLoRA frozen-base snap (arXiv:2305.14314): round the base
    params onto the serve plane's int8 grid — quantize → dequantize of
    exactly the leaves ``serve/weights.py`` quantizes, block size and
    all — BEFORE wrapping with ``lora_bundle``.

    QLoRA's trade is a quantized frozen base plus fp LoRA updates. With
    the base snapped here, the ``lora_only`` trainer computes gradients
    against the SAME base a ``weight_dtype='int8'`` engine dequantizes
    (block quantization is idempotent: re-quantizing a snapped base
    reproduces its own grid), so the adapters learn residuals of the
    policy actually being served rather than of an fp base the serve
    plane never sees. Publishing stays the normal fp merge —
    ``publish_params`` re-quantizes through its one compiled program,
    retrace-free. Norms/biases pass through untouched, like serving."""
    import jax

    from ..serve.weights import store_weights
    from ..train.precision import _is_quantized, dequantize_blockwise

    snapped = store_weights(base_params, "int8", family=family)
    return jax.tree.map(
        lambda orig, snap: (dequantize_blockwise(snap, dtype=orig.dtype)
                            if _is_quantized(snap) else snap),
        base_params, snapped)
