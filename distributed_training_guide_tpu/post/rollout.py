"""Rollout generation: the trainer-driven side of the serve engine.

The loop's sampling contract rides entirely on the engine's
position-keyed sampling streams (``serve/engine.py``): every rollout
carries a seed that is a pure function of ``(base_seed, iteration,
sample index)``, and the engine samples token t from
``fold_in(key(seed), absolute position)`` — so a rollout's tokens are a
pure function of (weights, prompt, seed). That single property is what
makes the whole post-training loop reproducible: same seed + same
publish schedule ⇒ token-identical rollouts across engine restarts,
across admission order, across co-residents, and across
spec-on/spec-off (speculative acceptance is exact — serve/spec.py).

The **rollout ledger** is the crash-recovery half: each completed sample
appends one fsynced JSONL line as it finishes, so an engine killed
mid-rollout-batch loses only its in-flight sequences. On resume the loop
reads the ledger and generates ONLY the missing samples — no
double-counting (each (iteration, index) pair is generated exactly once)
— and because seeds are derived, the regenerated samples are bitwise the
ones the dead engine would have produced (chaos-pinned in
tests/test_post.py).
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from pathlib import Path
from typing import Optional

from ..serve.scheduler import Request


@dataclasses.dataclass
class Rollout:
    """One completed policy sample: the unit the scorer and the packed
    update step consume, and the unit the ledger records."""
    iteration: int
    index: int                      # sample index within the iteration
    prompt_ids: list
    generated_ids: list
    seed: int
    finish_reason: str
    group_id: int = 0               # prompt group (GRPO group baseline)

    def to_json(self) -> dict:
        return {"iteration": self.iteration, "index": self.index,
                "prompt_ids": list(map(int, self.prompt_ids)),
                "generated_ids": list(map(int, self.generated_ids)),
                "seed": int(self.seed),
                "finish_reason": self.finish_reason,
                "group_id": int(self.group_id)}

    @classmethod
    def from_json(cls, d: dict) -> "Rollout":
        return cls(iteration=d["iteration"], index=d["index"],
                   prompt_ids=d["prompt_ids"],
                   generated_ids=d["generated_ids"], seed=d["seed"],
                   finish_reason=d["finish_reason"],
                   group_id=d.get("group_id", 0))


def pad_bucket(n: int, lo: int = 16) -> int:
    """Power-of-two padded length — ONE helper for the packed update
    batch (post/loop.py) and the scorer forwards (post/score.py), so
    the two pads cannot silently diverge."""
    b = lo
    while b < n:
        b *= 2
    return b


def rollout_seed(base_seed: int, iteration: int, index: int) -> int:
    """Deterministic per-sample seed — a pure int function so the seed
    survives process restarts (no RNG state to lose). Mixed over distinct
    primes so (iteration, index) collisions need ~2^31 samples."""
    return (int(base_seed) * 1_000_003 + int(iteration) * 8_191
            + int(index) * 127 + 1) % (2 ** 31 - 1)


class RolloutLedger:
    """Crash-safe completed-rollout record (append-only JSONL).

    ``record`` appends + flushes + fsyncs ONE line per completed sample —
    the durability point is the sample, not the batch, so a crash loses
    at most in-flight sequences. ``completed(iteration)`` returns what
    already finished; a torn trailing line (crash mid-write) parses as
    garbage and is skipped, never fatal. The ledger is also the loop's
    restart cursor: ``last_iteration()`` tells a resumed loop where the
    schedule stood."""

    def __init__(self, path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        # incremental parse cache: ``completed`` runs once per loop
        # iteration, and re-parsing the WHOLE file each time is O(n^2)
        # over a long ledgered run — only bytes past ``_parsed_to`` are
        # read; a complete line is consumed once, ever
        self._parsed: list = []
        self._parsed_to = 0

    def record(self, rollout: Rollout) -> None:
        line = json.dumps(rollout.to_json(), separators=(",", ":"))
        with open(self.path, "a") as fp:
            fp.write(line + "\n")
            fp.flush()
            os.fsync(fp.fileno())

    def _lines(self) -> list:
        if not self.path.exists():
            return []
        size = os.path.getsize(self.path)
        if size < self._parsed_to:          # file replaced/truncated
            self._parsed, self._parsed_to = [], 0
        if size > self._parsed_to:
            with open(self.path, "rb") as fp:
                fp.seek(self._parsed_to)
                chunk = fp.read()
            # consume only COMPLETE lines; a torn trailing fragment (a
            # crash mid-write, no newline yet) stays unconsumed — if the
            # next record() glues onto it the merged line parses as
            # garbage and is skipped, never fatal (the missing sample
            # regenerates; later duplicates win in ``completed``)
            end = chunk.rfind(b"\n") + 1
            for raw in chunk[:end].splitlines():
                try:
                    self._parsed.append(json.loads(raw))
                except json.JSONDecodeError:
                    continue
            self._parsed_to += end
        return self._parsed

    def completed(self, iteration: int) -> dict:
        """index -> Rollout for every sample of ``iteration`` already on
        disk. Later duplicates win (there are none unless a caller
        replays history; exactly-once generation relies on this map, not
        on the file being duplicate-free)."""
        return {d["index"]: Rollout.from_json(d)
                for d in self._lines() if d["iteration"] == iteration}

    def last_iteration(self) -> int:
        """Highest iteration with any completed sample (-1 = empty)."""
        return max((d["iteration"] for d in self._lines()), default=-1)


def generate_rollouts(engine, prompts, *, iteration: int, base_seed: int,
                      max_new_tokens: int, temperature: float = 0.7,
                      top_k: int = 0, top_p: float = 1.0,
                      group_ids=None, eos_id: Optional[int] = None,
                      ledger: Optional[RolloutLedger] = None,
                      max_iterations: Optional[int] = 20000,
                      adapter_id: int = 0) -> tuple:
    """One rollout batch through the serve engine: submit every sample
    of ``iteration`` not already in the ledger, step the engine to
    completion, and return ``(rollouts in index order, stats)``.

    Samples record to the ledger AS THEY FINISH, so a crash between two
    ``engine.step()`` calls is recoverable by calling this again with a
    fresh engine (same weights — the publish schedule is the caller's
    contract) and the same ledger: completed indices are skipped, missing
    ones regenerate bitwise (derived seeds + position-keyed sampling).

    ``stats``: generated token count, wall seconds, tokens/s — the
    rollout-throughput numbers the bench rung records."""
    done = ledger.completed(iteration) if ledger is not None else {}
    resumed_idx = frozenset(done)
    pending: dict[int, int] = {}
    t0 = time.perf_counter()
    for idx, prompt in enumerate(prompts):
        if idx in done:
            continue
        rid = engine.submit(Request(
            prompt_ids=list(prompt), max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_id=eos_id, seed=rollout_seed(base_seed, iteration, idx),
            adapter_id=adapter_id))
        pending[rid] = idx
    iters = 0
    while pending:
        for res in engine.step():
            idx = pending.pop(res.request_id, None)
            if idx is None:
                continue            # a pre-crash stray finishing late
            rollout = Rollout(
                iteration=iteration, index=idx,
                prompt_ids=list(prompts[idx]),
                generated_ids=list(res.generated_ids),
                seed=rollout_seed(base_seed, iteration, idx),
                finish_reason=res.finish_reason,
                group_id=int(group_ids[idx]) if group_ids is not None
                else idx)
            if ledger is not None:
                ledger.record(rollout)
            done[idx] = rollout
        iters += 1
        if max_iterations is not None and iters > max_iterations:
            raise RuntimeError(
                f"rollout batch exceeded {max_iterations} engine "
                f"iterations with {len(pending)} samples unfinished — "
                f"scheduler stall, not load")
    wall = time.perf_counter() - t0
    rollouts = [done[i] for i in range(len(prompts))]
    # throughput counts only tokens THIS call generated — resumed
    # samples came off the ledger, and counting them would report a
    # resumed iteration at millions of tok/s (poisoning every bench
    # mean the number lands in)
    gen = sum(len(r.generated_ids) for i, r in enumerate(rollouts)
              if i not in resumed_idx)
    stats = {"rollout_tokens": gen,
             "rollout_wall_s": round(wall, 4),
             "rollout_tokens_per_s": round(gen / wall, 2) if wall else 0.0,
             # samples already on disk when this call started (generated
             # by a previous incarnation — the no-double-count meter)
             "resumed_from_ledger": len(resumed_idx)}
    return rollouts, stats
