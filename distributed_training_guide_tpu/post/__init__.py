"""On-policy post-training runtime: the trainer drives the serve engine.

The loop this package closes (ROADMAP item 4 — the reference guide
stops at pretraining):

    rollout  — a co-resident ServeEngine generates variable-length
               samples under the paged pool, reproducible per derived
               seed, ledgered per sample (post/rollout.py);
    score    — programmatic rewards, reward-model forwards, or teacher
               distributions behind one Scorer interface (post/score.py);
    update   — the masked ragged post step: rollouts packed by
               group_sizes through ops/grouped_matmul.py, prompt tokens
               masked, REINFORCE-with-baseline / distillation-KL behind
               the post_loss seam, LoRA-sized updates (train/step.py);
    publish  — refreshed params swap into the engine's already-compiled
               programs without a retrace (ModelPrograms.publish_params,
               serve/engine.py), gated on the step guard so a NaN update
               never poisons the serving policy (post/loop.py).

CLI: ``python -m distributed_training_guide_tpu.post`` (post/cli.py).
Chapter: ``related-topics/post-training/``.
"""
from .loop import (PostTrainingLoop, merged_params, pack_rollouts,
                   qlora_base)
from .rollout import (Rollout, RolloutLedger, generate_rollouts,
                      rollout_seed)
from .score import (band_reward, match_reward, ProgrammaticScorer,
                    RewardModelScorer, Score, Scorer, TeacherScorer)

__all__ = [
    "PostTrainingLoop", "merged_params", "pack_rollouts", "qlora_base",
    "Rollout", "RolloutLedger", "generate_rollouts", "rollout_seed",
    "ProgrammaticScorer", "RewardModelScorer", "Score", "Scorer",
    "TeacherScorer", "band_reward", "match_reward",
]
