"""Process-ordered I/O guards.

Parity with the reference's ``rank0_first`` / ``rank_ordered`` context managers
(``02-distributed-data-parallel/train_llm.py:272-280``,
``06-tensor-parallel/train_llm.py:346-353``) used so only one worker downloads
a dataset/model while the others wait, then read the warm cache.

JAX runs one process per host, so "rank" collapses to ``jax.process_index()``
and the barrier is a global-device sync. In single-process mode (including the
pytest CPU mesh) the guards are no-ops.
"""
from __future__ import annotations

from contextlib import contextmanager

import jax


def is_process0() -> bool:
    return jax.process_index() == 0


def sync_processes(name: str = "barrier") -> None:
    """Barrier across all hosts (no-op single-process)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


@contextmanager
def process_ordered(should_go_first: bool):
    """First the processes with ``should_go_first``, then the rest."""
    if should_go_first:
        yield
        sync_processes("process_ordered_first")
        sync_processes("process_ordered_second")
    else:
        sync_processes("process_ordered_first")
        yield
        sync_processes("process_ordered_second")


@contextmanager
def process0_first():
    with process_ordered(is_process0()):
        yield
