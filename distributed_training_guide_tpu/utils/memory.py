"""Device memory statistics.

Parity with the reference's ``get_mem_stats`` (``01-single-gpu/train_llm.py:248-257``),
which reports current/peak allocated+reserved GB from the CUDA caching
allocator. On TPU the runtime exposes ``Device.memory_stats()``; CPU backends
may expose nothing, in which case we report zeros so the log dict stays stable.
"""
from __future__ import annotations

from typing import Optional

import jax


def get_mem_stats(device: Optional[jax.Device] = None) -> dict:
    device = device or jax.local_devices()[0]
    try:
        stats = device.memory_stats() or {}
    except Exception:
        stats = {}
    gb = 1e-9
    return {
        "total_gb": gb * stats.get("bytes_limit", 0),
        "curr_alloc_gb": gb * stats.get("bytes_in_use", 0),
        "peak_alloc_gb": gb * stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0)),
        "curr_resv_gb": gb * stats.get("bytes_reserved", 0),
        "peak_resv_gb": gb * stats.get("peak_bytes_reserved", 0),
    }
