from .timers import LocalTimer
from .memory import get_mem_stats
from .logging import init_logging, log_dict
from .procguards import process0_first, process_ordered, is_process0, sync_processes
from .mfu import transformer_flops_per_token, device_peak_flops, compute_mfu
from .faults import FaultSpec, active_faults
from .heartbeat import HeartbeatWriter, heartbeat_path, read_heartbeat

__all__ = [
    "LocalTimer",
    "get_mem_stats",
    "init_logging",
    "log_dict",
    "process0_first",
    "process_ordered",
    "is_process0",
    "sync_processes",
    "transformer_flops_per_token",
    "device_peak_flops",
    "compute_mfu",
    "FaultSpec",
    "active_faults",
    "HeartbeatWriter",
    "heartbeat_path",
    "read_heartbeat",
]
