"""Experiment tracking (wandb) — live integration, soft dependency.

The reference ships working wandb code in its DeepSpeed variant
(``alternative-frameworks/deepspeed/train_llm.py:110-124,185-186``) and
documents three deployment patterns
(``related-topics/wandb-configurations/README.md:9-63``). This module is the
TPU build's live implementation of those patterns, with "rank" mapped to the
JAX *process* (one per host):

- ``mode="process0"`` — one run, logged by process 0 only (the default);
- ``mode="per-host"`` — grouped runs, one per host, named ``proc-<i>``;
- resume — the run id is persisted next to ``state.json`` in the experiment
  dir, and re-used with ``resume="allow"`` so a restarted job continues the
  same curve (reference pattern 3).

wandb stays a *soft* dependency (zero-egress testbeds run without it):
``make_tracker`` returns a no-op tracker when the import fails, and the run
never touches the network when ``WANDB_MODE=offline``.
"""
from __future__ import annotations

import logging
from pathlib import Path
from typing import Optional

import jax

LOGGER = logging.getLogger(__name__)


class _NoopTracker:
    enabled = False

    def log(self, info: dict, step: Optional[int] = None) -> None:
        pass

    def finish(self) -> None:
        pass


class WandbTracker:
    """Thin wrapper owning the wandb run for this process (if any)."""

    enabled = True

    def __init__(self, wandb, run):
        self._wandb = wandb
        self._run = run

    def log(self, info: dict, step: Optional[int] = None) -> None:
        if self._run is not None:
            self._wandb.log(info, step=step)

    def finish(self) -> None:
        if self._run is not None:
            self._wandb.finish()


def _resume_id(exp_dir: Optional[Path], wandb) -> tuple:
    """(id, resume) — persist the run id beside state.json (pattern 3)."""
    if exp_dir is None:
        return None, None
    id_file = Path(exp_dir) / "wandb_id.txt"
    if id_file.exists():
        return id_file.read_text().strip(), "allow"
    run_id = wandb.util.generate_id()
    id_file.parent.mkdir(parents=True, exist_ok=True)
    id_file.write_text(run_id)
    return run_id, "allow"


def make_tracker(args, *, mode: str = "process0",
                 exp_dir: Optional[Path] = None, config: Optional[dict] = None):
    """Build the tracker for this process. Returns a no-op tracker when
    tracking is disabled or wandb is not installed."""
    if not getattr(args, "wandb", False):
        return _NoopTracker()
    try:
        import wandb
    except ImportError:
        LOGGER.warning("--wandb requested but wandb is not installed; "
                       "continuing without experiment tracking")
        return _NoopTracker()

    project = getattr(args, "wandb_project", None) or "distributed-training-guide-tpu"
    name = getattr(args, "experiment_name", None)
    if mode == "per-host":
        # pattern 2: grouped per-host runs (per-host HBM/throughput curves)
        run = wandb.init(project=project, group=name or "ungrouped",
                         name=f"proc-{jax.process_index()}", config=config)
    elif jax.process_index() == 0:
        # pattern 1 (+3): single resumable run on process 0
        run_id, resume = _resume_id(exp_dir, wandb)
        run = wandb.init(project=project, name=name, id=run_id, resume=resume,
                         config=config)
    else:
        return _NoopTracker()
    return WandbTracker(wandb, run)
