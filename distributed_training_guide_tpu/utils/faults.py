"""Deterministic fault injection for failure drills.

The reference teaches failure *diagnosis* (``diagnosing-errors/README.md``)
but gives no way to rehearse a failure on purpose; every restart/resume path
in this repo would otherwise only be exercised by real crashes. These env-var
driven faults make failures reproducible — the chaos tests
(``tests/test_chaos.py``) and operators running fire drills on a real pod use
the same switches:

- ``DTG_FAULT_CRASH_STEP=N`` [+ ``DTG_FAULT_CRASH_MODE=kill|exc``]: die at
  the end of global step N — SIGKILL (default; no cleanup, the supervisor's
  worst case) or a raised exception (exercises the ``@record`` error file).
- ``DTG_FAULT_NAN_LOSS_STEP=N``: overwrite the loss with NaN inside the
  jitted step when ``state.step == N`` (drives ``train/guards.py`` policies).
- ``DTG_FAULT_CORRUPT_CKPT_STEP=N``: after the step-N checkpoint publishes,
  flip bytes in its largest shard file — the manifest then catches it and
  restore falls back through the retention chain.
- ``DTG_FAULT_SAVE_LATENCY_S=X``: sleep X seconds inside every checkpoint
  save (slow-NFS simulation; exercises async-save overlap and heartbeats).

Serve-plane faults (the multi-host fabric's drills — serve/transport.py
and serve/router.py consume these; ``tests/test_chaos_serve.py`` is the
executable documentation):

- ``DTG_FAULT_HANDOFF_CRASH_XFER=N``: the Nth cross-host page handoff
  (0-indexed transfer id) tears mid-flight — the payload bytes on the
  wire are corrupted the way a sender crash mid-write leaves them, the
  receiver's CRC rejects the frame, and the protocol's only outcome is
  "payload dropped, sender pages freed, request requeued at the prefill
  queue's head".
- ``DTG_FAULT_HANDOFF_TIMEOUT_XFER=N``: the receiver sits on transfer N
  past the sender's ack timeout; the sender aborts the transfer with the
  same drop-free-requeue outcome (the late ack is discarded by id).
- ``DTG_FAULT_REPLICA_KILL=<name>@<step>``: SIGKILL-shaped replica death
  — at router iteration ``step``, replica ``name`` stops instantly with
  NO cleanup (no drain, no handoff); the router fences it on the next
  health check and resubmits its in-flight requests.
- ``DTG_FAULT_REPLICA_WEDGE=<name>@<step>``: the wedged-but-alive case —
  the replica stops stepping AND stops heartbeating (a stuck device op),
  while its process would still answer liveness; only the heartbeat-age
  fence catches it.
- ``DTG_FAULT_ARRIVAL_BURST=<mult>@<start>:<end>``: traffic-shape fault
  for the open-loop load harness — the arrival rate is multiplied by
  ``mult`` for offsets in ``[start, end)`` seconds from the start of the
  trace (``serve/loadgen.py`` consumes it when building Poisson
  schedules). A flash crowd on demand, deterministic per seed.
- ``DTG_FAULT_REPLICA_SLOW=<name>@<delay_s>``: the gray-failure case the
  kill/wedge drills cannot produce — replica ``name`` keeps stepping and
  heartbeating but every iteration is inflated by ``delay_s`` seconds (a
  thermally throttled chip, a noisy co-tenant). Nothing fences it; only
  load-aware routing and the controller's SLO loop notice.

Elastic-fleet faults (the renegotiation and generation-swap drills —
``launch/elastic.py`` members and ``serve/elastic.py`` swaps consume
these; ``tests/test_elastic_train.py`` / ``test_elastic_serve.py``):

- ``DTG_FAULT_SLICE_LOSS=<member>@<beat>``: slice loss — the named
  elastic member stops writing its membership heartbeat after its Nth
  beat and exits without retiring its file (the no-cleanup death of a
  whole slice); the surviving supervisors' liveness scan ages it out and
  the leader renegotiates the world without it.
- ``DTG_FAULT_SWAP_DROP_SEQ=<n>``: during an engine-generation swap, the
  Nth resident sequence's gathered k/v payload is dropped (a torn
  device-to-host read); the swap falls back to requeue-and-replay for
  that sequence — recompute through the prefill path plus the bitwise
  decode replay, so the continuation is still token-identical.

All faults are deterministic functions of (env, step): a drill that kills a
run at step N kills every rerun at step N too, so kill -> restart -> resume
trajectories can be compared bit-for-bit against an uninterrupted run.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import signal
from pathlib import Path
from typing import Optional

LOGGER = logging.getLogger(__name__)

ENV_CRASH_STEP = "DTG_FAULT_CRASH_STEP"
ENV_CRASH_MODE = "DTG_FAULT_CRASH_MODE"
ENV_NAN_LOSS_STEP = "DTG_FAULT_NAN_LOSS_STEP"
ENV_CORRUPT_CKPT_STEP = "DTG_FAULT_CORRUPT_CKPT_STEP"
ENV_SAVE_LATENCY_S = "DTG_FAULT_SAVE_LATENCY_S"
ENV_HANDOFF_CRASH_XFER = "DTG_FAULT_HANDOFF_CRASH_XFER"
ENV_HANDOFF_TIMEOUT_XFER = "DTG_FAULT_HANDOFF_TIMEOUT_XFER"
ENV_REPLICA_KILL = "DTG_FAULT_REPLICA_KILL"
ENV_REPLICA_WEDGE = "DTG_FAULT_REPLICA_WEDGE"
ENV_ARRIVAL_BURST = "DTG_FAULT_ARRIVAL_BURST"
ENV_REPLICA_SLOW = "DTG_FAULT_REPLICA_SLOW"
ENV_SLICE_LOSS = "DTG_FAULT_SLICE_LOSS"
ENV_SWAP_DROP_SEQ = "DTG_FAULT_SWAP_DROP_SEQ"

_CORRUPT_BYTES = 256


def _env_int(name: str) -> Optional[int]:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return None
    try:
        return int(raw)
    except ValueError:
        LOGGER.warning("ignoring non-integer %s=%r", name, raw)
        return None


def _env_target(name: str) -> Optional[tuple[str, int]]:
    """Parse a ``<replica_name>@<step>`` fault target."""
    raw = os.environ.get(name)
    if not raw:
        return None
    target, _, step = raw.partition("@")
    try:
        return (target, int(step))
    except ValueError:
        LOGGER.warning("ignoring malformed %s=%r (want <name>@<step>)",
                       name, raw)
        return None


def _env_burst(name: str) -> Optional[tuple[float, float, float]]:
    """Parse a ``<mult>@<start>:<end>`` arrival-burst window."""
    raw = os.environ.get(name)
    if not raw:
        return None
    mult, _, window = raw.partition("@")
    start, _, end = window.partition(":")
    try:
        return (float(mult), float(start), float(end))
    except ValueError:
        LOGGER.warning("ignoring malformed %s=%r (want <mult>@<start>:<end>)",
                       name, raw)
        return None


def _env_slow(name: str) -> Optional[tuple[str, float]]:
    """Parse a ``<replica_name>@<delay_s>`` slow-replica target."""
    raw = os.environ.get(name)
    if not raw:
        return None
    target, _, delay = raw.partition("@")
    try:
        return (target, float(delay))
    except ValueError:
        LOGGER.warning("ignoring malformed %s=%r (want <name>@<delay_s>)",
                       name, raw)
        return None


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    crash_step: Optional[int] = None
    crash_mode: str = "kill"          # "kill" (SIGKILL) or "exc" (raise)
    nan_loss_step: Optional[int] = None
    corrupt_ckpt_step: Optional[int] = None
    save_latency_s: float = 0.0
    handoff_crash_xfer: Optional[int] = None
    handoff_timeout_xfer: Optional[int] = None
    replica_kill: Optional[tuple[str, int]] = None    # (name, router step)
    replica_wedge: Optional[tuple[str, int]] = None
    arrival_burst: Optional[tuple[float, float, float]] = None  # (mult, t0, t1)
    replica_slow: Optional[tuple[str, float]] = None  # (name, delay seconds)
    slice_loss: Optional[tuple[str, int]] = None      # (member, beat count)
    swap_drop_seq: Optional[int] = None               # resident index in swap


def active_faults() -> FaultSpec:
    """Parse the fault env vars (re-read on every call: cheap, and lets tests
    monkeypatch the environment without import-order games)."""
    try:
        latency = float(os.environ.get(ENV_SAVE_LATENCY_S, 0) or 0)
    except ValueError:
        latency = 0.0
    return FaultSpec(
        crash_step=_env_int(ENV_CRASH_STEP),
        crash_mode=os.environ.get(ENV_CRASH_MODE, "kill"),
        nan_loss_step=_env_int(ENV_NAN_LOSS_STEP),
        corrupt_ckpt_step=_env_int(ENV_CORRUPT_CKPT_STEP),
        save_latency_s=latency,
        handoff_crash_xfer=_env_int(ENV_HANDOFF_CRASH_XFER),
        handoff_timeout_xfer=_env_int(ENV_HANDOFF_TIMEOUT_XFER),
        replica_kill=_env_target(ENV_REPLICA_KILL),
        replica_wedge=_env_target(ENV_REPLICA_WEDGE),
        arrival_burst=_env_burst(ENV_ARRIVAL_BURST),
        replica_slow=_env_slow(ENV_REPLICA_SLOW),
        slice_loss=_env_target(ENV_SLICE_LOSS),
        swap_drop_seq=_env_int(ENV_SWAP_DROP_SEQ),
    )


def handoff_fault(xfer_id: int) -> Optional[str]:
    """The injected failure for cross-host handoff transfer ``xfer_id``
    (a monotone 0-indexed id shared by sender and receiver — it IS the
    wire frame's id, so both ends agree on which transfer to break):
    "crash" (torn payload on the wire), "timeout" (receiver sits past the
    sender's ack window), or None."""
    spec = active_faults()
    if spec.handoff_crash_xfer is not None \
            and xfer_id == spec.handoff_crash_xfer:
        return "crash"
    if spec.handoff_timeout_xfer is not None \
            and xfer_id == spec.handoff_timeout_xfer:
        return "timeout"
    return None


def replica_fault(name: str, step: int) -> Optional[str]:
    """The injected failure for replica ``name`` at router iteration
    ``step``: "kill" (instant death, no cleanup), "wedge" (stops stepping
    and heartbeating but stays 'alive'), or None."""
    spec = active_faults()
    if spec.replica_kill is not None and spec.replica_kill == (name, step):
        return "kill"
    if spec.replica_wedge is not None and spec.replica_wedge == (name, step):
        return "wedge"
    return None


def arrival_burst(offset_s: float) -> float:
    """The arrival-rate multiplier at trace offset ``offset_s`` seconds —
    1.0 outside the injected burst window, ``mult`` inside it. The load
    generator folds this into its Poisson gap draws, so the burst is as
    deterministic as the schedule's seed."""
    spec = active_faults()
    if spec.arrival_burst is None:
        return 1.0
    mult, start, end = spec.arrival_burst
    return mult if start <= offset_s < end else 1.0


def replica_slow(name: str) -> float:
    """Injected per-iteration latency inflation (seconds) for replica
    ``name`` — 0.0 unless the slow-replica fault targets it. Unlike
    kill/wedge this is not a one-shot event: the drag applies to every
    iteration while the env var is set (gray failure, not death)."""
    spec = active_faults()
    if spec.replica_slow is not None and spec.replica_slow[0] == name:
        return max(0.0, spec.replica_slow[1])
    return 0.0


def slice_fault(member: str, beat: int) -> bool:
    """True when elastic member ``member`` should die (stop beating, no
    cleanup) at its ``beat``-th membership heartbeat — the slice-loss
    drill. Deterministic in (env, beat count), like every fault here."""
    spec = active_faults()
    return (spec.slice_loss is not None
            and spec.slice_loss[0] == member
            and beat >= spec.slice_loss[1])


def swap_fault(resident_index: int) -> bool:
    """True when the ``resident_index``-th resident sequence exported by
    an engine-generation swap should lose its gathered k/v payload (torn
    device-to-host read) and take the requeue-and-replay path instead."""
    spec = active_faults()
    return (spec.swap_drop_seq is not None
            and resident_index == spec.swap_drop_seq)


def maybe_crash(global_step: int) -> None:
    """Host-side crash fault, called at the end of each loop iteration (after
    any checkpoint for this step has published, so 'crash at step N' leaves
    the step-N checkpoint on disk when N is a checkpoint step)."""
    spec = active_faults()
    if spec.crash_step is None or global_step != spec.crash_step:
        return
    if spec.crash_mode == "exc":
        raise RuntimeError(
            f"injected fault: crash at global step {global_step} "
            f"({ENV_CRASH_STEP}={spec.crash_step})")
    LOGGER.warning("injected fault: SIGKILL at global step %d", global_step)
    os.kill(os.getpid(), signal.SIGKILL)


def maybe_save_latency() -> None:
    spec = active_faults()
    if spec.save_latency_s > 0:
        import time

        LOGGER.warning("injected fault: %.3fs checkpoint save latency",
                       spec.save_latency_s)
        time.sleep(spec.save_latency_s)


def corrupt_checkpoint_dir(ckpt_dir: Path) -> Optional[str]:
    """Flip the leading bytes of the largest file under ``ckpt_dir`` (the
    biggest TensorStore chunk — the array data, not tiny metadata). Returns
    the corrupted file's relative path, or None if the dir has no files."""
    ckpt_dir = Path(ckpt_dir)
    files = [p for p in ckpt_dir.rglob("*") if p.is_file()]
    if not files:
        return None
    victim = max(files, key=lambda p: p.stat().st_size)
    with open(victim, "r+b") as fp:
        chunk = fp.read(_CORRUPT_BYTES)
        fp.seek(0)
        fp.write(bytes(b ^ 0xFF for b in chunk))
    return str(victim.relative_to(ckpt_dir))


def maybe_corrupt_checkpoint(ckpt_dir: Path, step: int) -> None:
    """Checkpoint-corruption fault, applied AFTER the manifest + state.json
    published: the manifest holds the good checksums, the dir holds bad bytes
    — exactly what a post-publish partial write looks like to a restart."""
    spec = active_faults()
    if spec.corrupt_ckpt_step is None or step != spec.corrupt_ckpt_step:
        return
    victim = corrupt_checkpoint_dir(ckpt_dir)
    LOGGER.warning("injected fault: corrupted %s in checkpoint %s",
                   victim, Path(ckpt_dir).name)
