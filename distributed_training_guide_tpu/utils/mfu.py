"""Model-FLOPs-utilization accounting.

The reference only reports tokens/s (``01-single-gpu/train_llm.py:166``); the
TPU build's north-star metric is MFU, so we add the standard accounting:
``6 * n_params`` matmul FLOPs per token for fwd+bwd, plus the attention
quadratic term ``12 * n_layers * hidden * seq`` (fwd+bwd, causal halves the
scores but flash kernels still compute block-wise — we use the conventional
dense count so numbers are comparable with published MFU figures).
"""
from __future__ import annotations

import jax


def transformer_flops_per_token(
    n_params: int,
    n_layers: int,
    hidden_size: int,
    seq_len: int,
    include_embedding: bool = False,
    vocab_size: int = 0,
    attn_kv_len: float | None = None,
) -> float:
    """Training FLOPs (fwd+bwd) per token.

    ``attn_kv_len``: mean keys each query actually attends (defaults to
    ``seq_len``, the conventional dense-causal count). Banded attention
    (sliding windows, per-layer schedules) computes O(S*window), not
    O(S^2) — pass ``banded_attention_kv_length(cfg, seq_len)`` for the
    honest roofline; published-MFU comparisons keep the dense default."""
    params = n_params
    if not include_embedding and vocab_size:
        params = n_params - vocab_size * hidden_size
    matmul = 6.0 * params
    attention = 12.0 * n_layers * hidden_size * (
        seq_len if attn_kv_len is None else attn_kv_len)
    return matmul + attention


def banded_attention_kv_length(cfg, seq_len: int) -> float:
    """Mean effective kv context per query across layers under the config's
    window schedule — ``min(seq, window)`` per layer, averaged over a
    per-layer pattern (``layer_windows``, 0 = full attention that layer) or
    taken from the uniform ``sliding_window``; ``seq_len`` when unwindowed.
    This is the O(S*window) attention cost the banded flash kernel (and the
    matching xla mask's useful work) actually pays once S >> window."""
    lw = getattr(cfg, "layer_windows", None)
    if lw:
        return sum(min(seq_len, w) if w else seq_len for w in lw) / len(lw)
    w = getattr(cfg, "sliding_window", None)
    if w:
        return float(min(seq_len, w))
    return float(seq_len)


# Peak bf16 dense FLOP/s per chip by device kind substring.
_PEAK_FLOPS = [
    ("v6e", 918e12),
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def device_peak_flops(device: "jax.Device | None" = None,
                      device_kind: str | None = None) -> float:
    """``device_kind`` names a TARGET chip (e.g. "v5p") without probing a
    local device — the preflight roofline prices pod plans from CPU hosts."""
    if device_kind is not None:
        kind = device_kind.lower()
        for key, flops in _PEAK_FLOPS:
            if key in kind:
                return flops
        return 459e12  # v5p, the 405B recipe's stated target
    device = device or jax.local_devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, flops in _PEAK_FLOPS:
        if key in kind:
            return flops
    if device.platform == "cpu":
        return 1e12  # nominal, so CPU tests produce finite MFU
    return 197e12


def compute_mfu(tokens_per_s: float, flops_per_token: float, n_chips: int = 1,
                peak_flops_per_chip: float | None = None) -> float:
    peak = peak_flops_per_chip or device_peak_flops()
    return (tokens_per_s * flops_per_token) / (peak * n_chips)


# Aggregate ICI bandwidth per chip (bytes/s, all links, one direction) by
# device kind substring — public spec-sheet numbers (v5p: 4800 Gbit/s ICI
# per chip; v5e: 1600; v4: 2400; v6e: 3584). The preflight roofline
# (train/preflight.py) divides ring-collective bytes by this, the standard
# scaling-book first-order model; real meshes split it over links/axes, so
# treat results as a best-case bound, not a simulator.
_ICI_BYTES_PER_S = [
    ("v6e", 3584e9 / 8),
    ("v6", 3584e9 / 8),
    ("v5p", 4800e9 / 8),
    ("v5e", 1600e9 / 8),
    ("v5 lite", 1600e9 / 8),
    ("v5litepod", 1600e9 / 8),
    ("v4", 2400e9 / 8),
    ("v3", 1400e9 / 8),
]


def device_ici_bandwidth(device: "jax.Device | None" = None,
                         device_kind: str | None = None) -> float:
    """Bytes/s of ICI egress per chip; ``device_kind`` overrides probing so
    a CPU login host can run the roofline for a target pod (preflight)."""
    kind = (device_kind if device_kind is not None
            else getattr(device or jax.local_devices()[0], "device_kind", "")
            ).lower()
    for key, bw in _ICI_BYTES_PER_S:
        if key in kind:
            return bw
    return 4800e9 / 8  # default to the v5p target the 405B recipe names
