"""Phase timers with honest device synchronization.

Capability parity with the reference's ``LocalTimer``
(``01-single-gpu/train_llm.py:260-286``): a context manager that measures
wall-time of a phase, forcing a device sync on entry and exit so the
measurement is not polluted by async dispatch. On TPU the sync primitive is
``jax.block_until_ready`` on the arrays the phase produced (CUDA's
``torch.cuda.synchronize`` has no direct analogue — JAX dispatch is async per
array, so we block on outputs rather than a global device fence).
"""
from __future__ import annotations

import time
from typing import Callable, Optional

import jax


def device_sync() -> None:
    """Device fence for ``LocalTimer(sync_fn=...)`` — the reference C17
    semantics (``01-single-gpu/train_llm.py:260-286``, cuda.synchronize).

    Enqueues a trivial computation on every local device and blocks on it:
    the runtime executes programs in launch order per device, so the fence
    completes only after all previously dispatched work. Default timers use
    the loss host-read instead (see ``_default_sync``) because on some
    remote TPU pools ``block_until_ready`` returns early (BENCH.md "pool
    timeline"); ``--timer-sync`` restores this per-phase mode on healthy
    hardware."""
    import jax.numpy as jnp

    jax.block_until_ready([jnp.zeros((), jnp.int32, device=d) + 1
                           for d in jax.local_devices()])


def _default_sync() -> None:
    # Intentionally a no-op. JAX has no global device fence (dispatch queues
    # are per-array, and on some remote TPU platforms even block_until_ready
    # returns early), so honest phase timing requires the measured region
    # itself to end with a host read of its outputs — the training loop's
    # ``float(metrics["loss"])`` is that read, exactly like the reference's
    # ``loss.item()`` (``02-distributed-data-parallel/train_llm.py:163``).
    # Callers measuring raw dispatch can pass an explicit sync_fn.
    return None


class LocalTimer:
    """Measures average wall-time of a repeated phase (data/forward/step/...).

    Usage::

        timers = {k: LocalTimer() for k in ["data", "step"]}
        with timers["step"]:
            loss = train_step(state, batch)   # async dispatch
            # sync happens on __exit__
    """

    def __init__(self, sync_fn: Optional[Callable[[], None]] = None):
        self.synchronize = sync_fn or _default_sync
        self.measurements: list[float] = []
        self.start_time: Optional[float] = None

    def __enter__(self) -> "LocalTimer":
        self.synchronize()
        self.start_time = time.perf_counter()
        return self

    def __exit__(self, exc_type, value, traceback) -> None:
        if traceback is None:
            self.synchronize()
            self.measurements.append(time.perf_counter() - self.start_time)
        self.start_time = None

    def avg_elapsed_ms(self) -> float:
        if not self.measurements:
            return 0.0
        return 1000.0 * (sum(self.measurements) / len(self.measurements))

    def total_elapsed_ms(self) -> float:
        return 1000.0 * sum(self.measurements)

    def reset(self) -> None:
        self.measurements = []
        self.start_time = None
