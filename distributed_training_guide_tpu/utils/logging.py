"""Process-prefixed logging.

Parity with the reference's rank-prefixed stdlib logging
(``02-distributed-data-parallel/train_llm.py:43-46``). JAX is one process per
*host* (not per chip), so the prefix is ``jax.process_index()``.
"""
from __future__ import annotations

import logging


def init_logging(process_index: int = 0, process_count: int = 1, level=logging.INFO) -> None:
    logging.basicConfig(
        format=f"[%(asctime)s] [proc {process_index}/{process_count}] %(levelname)s:%(message)s",
        level=level,
        force=True,
    )


def log_dict(logger: logging.Logger, info: dict) -> None:
    logger.info({k: (round(v, 6) if isinstance(v, float) else v) for k, v in info.items()})
