"""HLO inspection helpers: collective schedules and tensor-shape pins.

Tests in this repo pin two kinds of compiled-program properties:

- *shape pins* — a tensor of a given dtype/shape must (not) exist in the
  lowered or compiled text ("the fused loss never materializes [B*S, V]
  fp32 logits", "no device holds the full-E expert stack"). Lowered
  StableHLO spells avals ``tensor<8x16xf32>``; compiled HLO spells them
  ``f32[8,16]``. ``has_aval`` matches both so a pin survives the
  lowered/compiled choice.
- *schedule pins* — the latency-hiding schedules (ops/overlap.py) are only
  real if their collectives can overlap compute: on TPU the compiled module
  shows async ``all-gather-start``/``all-gather-done`` pairs with compute
  scheduled between them; everywhere, the collectives must sit in the FLAT
  entry program, not trapped inside a ``while`` body (a ``lax.scan`` over
  layers structurally cannot issue layer i+1's gather during layer i —
  that is exactly what the schedules replace).

Shared by tests/test_overlap.py, test_moe.py, test_serve.py,
test_paged_decode.py, test_405b_recipe.py.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Iterable, Optional, Sequence

COLLECTIVE_KINDS = ("all-gather", "reduce-scatter", "all-reduce",
                    "collective-permute", "all-to-all")

# ops that count as "compute" when asserting an async pair spans work
COMPUTE_OPS = ("fusion", "dot", "convolution", "custom-call")


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    kind: str           # e.g. "all-gather"
    name: str           # e.g. "%all-gather-start.3"
    computation: str    # enclosing HLO computation name
    line: int           # line index into the module text
    is_start: bool
    is_done: bool


_COMPUTATION_RE = re.compile(  # params may be tuple-typed (nested parens)
    r"^\s*(?:ENTRY\s+)?(%[\w.\-]+)\s*(?:\(.*\))?\s*->.*\{\s*$")
# the result type may be tuple-shaped with spaces — async collective
# -start ops always are on TPU: "%ag-start = (f32[8], f32[32]) all-gather-start(..."
_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?(%[\w.\-]+)\s*=\s*(?:\([^)]*\)|\S+)\s+([\w\-]+)\(")


def _iter_ops(text: str):
    """Yield (op_name, op_kind, computation, line_no, line_text) over an HLO
    module's text (compiled ``as_text()`` form)."""
    comp = ""
    for i, line in enumerate(text.splitlines()):
        m = _COMPUTATION_RE.match(line)
        if m:
            comp = m.group(1)
            continue
        m = _OP_RE.match(line)
        if m:
            yield m.group(1), m.group(2), comp, i, line


def find_collectives(text: str, kinds: Sequence[str] = COLLECTIVE_KINDS
                     ) -> list[CollectiveOp]:
    """Every collective op in the module, with its enclosing computation."""
    out = []
    for name, op, comp, line, _ in _iter_ops(text):
        base = op
        is_start = op.endswith("-start")
        is_done = op.endswith("-done")
        if is_start or is_done:
            base = op.rsplit("-", 1)[0]
        if base in kinds:
            out.append(CollectiveOp(kind=base, name=name, computation=comp,
                                    line=line, is_start=is_start,
                                    is_done=is_done))
    return out


_CALLEE_RE = re.compile(
    r"(?:calls|to_apply|body|condition|branch_computations)="
    r"\{?(%[\w.\-]+(?:,\s*%[\w.\-]+)*)\}?")


def _call_graph(text: str) -> dict[str, set[str]]:
    """computation -> computations its ops reference (fusions, loop bodies,
    reducers, conditionals)."""
    graph: dict[str, set[str]] = {}
    comp = ""
    for line in text.splitlines():
        m = _COMPUTATION_RE.match(line)
        if m:
            comp = m.group(1)
            graph.setdefault(comp, set())
            continue
        for m in _CALLEE_RE.finditer(line):
            graph.setdefault(comp, set()).update(
                c.strip() for c in m.group(1).split(","))
    return graph


def while_body_computations(text: str) -> set[str]:
    """Computations reachable from any ``while`` op's body/condition —
    TRANSITIVELY, because XLA outlines collectives into helper computations
    (fusions, parallel thunks) called from the loop body."""
    graph = _call_graph(text)
    roots = set()
    for m in re.finditer(r"=[^\n]*?\swhile\([^\n]*?"
                         r"condition=(%[\w.\-]+)[^\n]*?body=(%[\w.\-]+)",
                         text):
        roots.update(m.groups())
    seen = set()
    stack = list(roots)
    while stack:
        c = stack.pop()
        if c in seen:
            continue
        seen.add(c)
        stack.extend(graph.get(c, ()))
    return seen


def collectives_outside_loops(text: str,
                              kinds: Sequence[str] = COLLECTIVE_KINDS
                              ) -> list[CollectiveOp]:
    """Collectives NOT (transitively) inside a while body — the ones a
    latency-hiding scheduler is free to slide across layer boundaries. A
    scan-over-layers program reports its per-layer collectives as inside
    the loop; the unrolled overlap schedule reports them all free."""
    loops = while_body_computations(text)
    return [c for c in find_collectives(text, kinds)
            if c.computation not in loops]


def async_collective_pairs(text: str,
                           kinds: Sequence[str] = COLLECTIVE_KINDS
                           ) -> list[tuple[CollectiveOp, CollectiveOp]]:
    """(start, done) pairs, matched by the done op referencing the start op
    by name (the HLO async-pair contract). Sync spellings yield no pairs —
    CPU lowers collectives synchronously, TPU's latency-hiding scheduler
    emits the async form."""
    cols = find_collectives(text, kinds)
    starts = {c.name: c for c in cols if c.is_start}
    pairs = []
    lines = text.splitlines()
    for done in cols:
        if not done.is_done:
            continue
        # the done op references its start by name somewhere in its operand
        # list (which may carry a spaced tuple type — don't try to parse the
        # grammar, just scan the references; [0] is the done's own name)
        refs = re.findall(r"%[\w.\-]+", lines[done.line])
        start = next((starts[r] for r in refs[1:] if r in starts), None)
        if start is None:  # fall back: same kind, same computation, before it
            cands = [s for s in starts.values()
                     if s.kind == done.kind and s.computation == done.computation
                     and s.line < done.line]
            start = max(cands, key=lambda s: s.line) if cands else None
        if start is not None:
            pairs.append((start, done))
    return pairs


def assert_async_pairs_span_compute(text: str, *, min_pairs: int = 1,
                                    kinds: Sequence[str] = COLLECTIVE_KINDS,
                                    compute_ops: Sequence[str] = COMPUTE_OPS
                                    ) -> int:
    """Assert >= ``min_pairs`` async collective pairs exist and at least one
    of them brackets compute (an op from ``compute_ops`` scheduled between
    start and done) — the literal "collective in flight while the chip
    works" property. Returns the number of compute-spanning pairs."""
    pairs = async_collective_pairs(text, kinds)
    assert len(pairs) >= min_pairs, (
        f"expected >= {min_pairs} async collective pairs, found {len(pairs)}")
    lines = text.splitlines()
    spanning = 0
    for start, done in pairs:
        if start.computation != done.computation:
            continue
        for i in range(start.line + 1, done.line):
            m = _OP_RE.match(lines[i])
            if m and m.group(2) in compute_ops:
                spanning += 1
                break
    assert spanning >= 1, "no async collective pair spans any compute op"
    return spanning


# ---------------------------------------------------------------------------
# tensor-shape pins
# ---------------------------------------------------------------------------

def aval_patterns(dtype: str, shape: Iterable[int]) -> tuple[str, str]:
    """The two textual spellings of an aval: compiled HLO ``f32[8,16]`` and
    lowered StableHLO ``tensor<8x16xf32>``."""
    dims = [str(int(d)) for d in shape]
    return (f"{dtype}[{','.join(dims)}]",
            f"tensor<{'x'.join(dims)}x{dtype}>")


def has_aval(text: str, dtype: str, shape: Iterable[int]) -> bool:
    """True if a tensor of exactly this dtype/shape appears in the module
    text (either spelling)."""
    return any(p in text for p in aval_patterns(dtype, shape))


def has_shape_run(text: str, shape: Iterable[int]) -> bool:
    """True if some tensor's dims contain this CONTIGUOUS run (any dtype,
    any position) — for pins of the form "no [.., E, kT, ..] buffer of any
    width". Dim runs are boundary-delimited so 8192 can't match inside
    18192."""
    dims = [str(int(d)) for d in shape]
    return bool(re.search(r"[\[,]" + ",".join(dims) + r"[,\]]", text)
                or re.search(r"[<x]" + "x".join(dims) + r"[x>]", text))
