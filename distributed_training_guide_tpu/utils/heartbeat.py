"""Worker-written heartbeat files for hang detection.

The supervisor's original hang heuristic watches the worker's *log sizes* —
the process form of the reference's "power draw dropped" signal
(``diagnosing-errors/README.md``). That heuristic false-positives on healthy
quiet phases (``--log-freq 100`` at a slow step time looks exactly like a
hang) and false-negatives on chatty death spirals. The heartbeat file is the
positive signal: the training loop writes ``{"step", "time"}`` to
``$HEARTBEAT_FILE`` every iteration (throttled), so "file stopped changing"
means "the loop stopped", not "the loop went quiet".

``launch/supervisor.py`` points ``HEARTBEAT_FILE`` at
``<attempt_dir>/heartbeat.json`` and prefers it over log sizes as soon as it
appears; workers that predate the heartbeat (or crash before the first beat)
fall back to the log-size heuristic automatically.
"""
from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Optional

HEARTBEAT_ENV = "HEARTBEAT_FILE"


def heartbeat_path() -> Optional[str]:
    return os.environ.get(HEARTBEAT_ENV) or None


def read_heartbeat(path: Path) -> Optional[dict]:
    try:
        with open(path) as fp:
            payload = json.load(fp)
    except (OSError, json.JSONDecodeError):
        return None
    return payload if isinstance(payload, dict) else None


class HeartbeatMonitor:
    """Reader side of the heartbeat file: the age of the most recent
    beat, for anyone deciding whether a worker is wedged. The training
    supervisor open-codes this check against attempt dirs; the serving
    fabric's router (``serve/router.py``) consumes it through this class
    — one definition of "stale" per file, not per caller."""

    def __init__(self, path):
        self.path = Path(path)

    def last_beat(self) -> Optional[dict]:
        return read_heartbeat(self.path)

    def age_s(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds since the last beat, or None when no beat has ever
        been written (a replica that died before its first beat falls to
        the caller's no-beat-yet grace policy, not to a fake huge age)."""
        payload = self.last_beat()
        if payload is None or "time" not in payload:
            return None
        return (time.time() if now is None else now) - float(payload["time"])


class HeartbeatWriter:
    """Throttled heartbeat writer; a no-op unless ``HEARTBEAT_FILE`` is set
    (or a path is given), so the train loop calls it unconditionally."""

    def __init__(self, path: Optional[str] = None, min_interval_s: float = 1.0):
        self.path = Path(path) if path else (
            Path(heartbeat_path()) if heartbeat_path() else None)
        self.min_interval_s = min_interval_s
        self._last = 0.0

    @property
    def enabled(self) -> bool:
        return self.path is not None

    def beat(self, step: int, force: bool = False) -> bool:
        """Write the heartbeat if due; returns whether a write happened."""
        if self.path is None:
            return False
        now = time.time()
        if not force and now - self._last < self.min_interval_s:
            return False
        tmp = self.path.with_name(self.path.name + ".tmp")
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            with open(tmp, "w") as fp:
                json.dump({"step": int(step), "time": now, "pid": os.getpid()}, fp)
            os.replace(tmp, self.path)  # atomic: readers never see torn JSON
        except OSError:
            return False  # heartbeat is advisory; never take the loop down
        self._last = now
        return True
