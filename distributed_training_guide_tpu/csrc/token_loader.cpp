// Native token-batch loader: mmap + background prefetch assembly.
//
// The reference leans on torch's native DataLoader machinery (C++ pin-memory
// threads, reference C13/C26 — `related-topics/optimizing-data-loading`).
// This is the TPU-framework's native equivalent: a small C++ core that
//   - mmaps a flat int32 token file (zero-copy, page-cache backed),
//   - views it as [n_sequences, seq_len],
//   - deterministically shuffles sequence order per (seed, epoch)
//     (Fisher-Yates over mt19937_64 — stable across platforms),
//   - assembles [batch, seq_len] batches on worker threads *ahead* of the
//     consumer (bounded prefetch), releasing the GIL entirely (caller is
//     ctypes), so host-side batch assembly overlaps device compute.
//
// C ABI for ctypes (see ../data/native_loader.py). Single-consumer.
//
// Build (done on demand by ../data/native_loader.py):
//   g++ -O3 -shared -fPIC -std=c++17 -o libtokenloader.so token_loader.cpp -lpthread

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <map>
#include <mutex>
#include <numeric>
#include <random>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Loader {
  int fd = -1;
  const int32_t* data = nullptr;
  size_t file_bytes = 0;
  int64_t seq_len = 0;
  int64_t batch = 0;
  size_t n_seqs = 0;
  size_t n_batches = 0;
  uint64_t seed = 0;
  int64_t epoch = -1;
  int n_threads = 2;
  size_t prefetch_depth = 4;

  std::vector<uint32_t> perm;

  // prefetch machinery
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_ready;   // consumer waits for its batch
  std::condition_variable cv_space;   // workers wait for queue space
  std::map<size_t, std::vector<int32_t>> ready;  // batch idx -> tokens
  std::atomic<size_t> next_claim{0};  // next batch index a worker builds
  size_t next_consume = 0;            // next batch index consumer takes
  bool stopping = false;

  void shuffle_for_epoch(int64_t e) {
    perm.resize(n_seqs);
    std::iota(perm.begin(), perm.end(), 0u);
    std::mt19937_64 rng(seed * 0x9E3779B97F4A7C15ULL + (uint64_t)e + 1);
    for (size_t i = n_seqs - 1; i > 0; --i) {
      size_t j = rng() % (i + 1);
      std::swap(perm[i], perm[j]);
    }
    epoch = e;
  }

  void worker_loop() {
    for (;;) {
      size_t idx = next_claim.fetch_add(1);
      if (idx >= n_batches) return;
      std::vector<int32_t> buf((size_t)batch * seq_len);
      for (int64_t b = 0; b < batch; ++b) {
        size_t seq = perm[idx * batch + b];
        std::memcpy(buf.data() + b * seq_len, data + (size_t)seq * seq_len,
                    sizeof(int32_t) * seq_len);
      }
      std::unique_lock<std::mutex> lk(mu);
      cv_space.wait(lk, [&] {
        return stopping || ready.size() < prefetch_depth ||
               idx < next_consume + prefetch_depth;
      });
      if (stopping) return;
      ready.emplace(idx, std::move(buf));
      cv_ready.notify_all();
    }
  }

  void start_epoch(int64_t e, size_t start_batch) {
    stop_workers();
    if (epoch != e) shuffle_for_epoch(e);
    {
      std::lock_guard<std::mutex> lk(mu);
      ready.clear();
      next_consume = start_batch;
      stopping = false;
    }
    next_claim.store(start_batch);
    for (int t = 0; t < n_threads; ++t)
      workers.emplace_back([this] { worker_loop(); });
  }

  void stop_workers() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
    }
    cv_space.notify_all();
    cv_ready.notify_all();
    for (auto& w : workers)
      if (w.joinable()) w.join();
    workers.clear();
    std::lock_guard<std::mutex> lk(mu);
    stopping = false;
  }

  // returns 1 on success, 0 at end of epoch
  int next(int32_t* out) {
    std::unique_lock<std::mutex> lk(mu);
    if (next_consume >= n_batches) return 0;
    size_t want = next_consume;
    cv_ready.wait(lk, [&] { return stopping || ready.count(want); });
    if (stopping) return 0;
    auto node = ready.extract(want);
    next_consume = want + 1;
    lk.unlock();
    cv_space.notify_all();
    std::memcpy(out, node.mapped().data(),
                sizeof(int32_t) * (size_t)batch * seq_len);
    return 1;
  }
};

}  // namespace

extern "C" {

void* tl_open(const char* path, int64_t seq_len, int64_t batch, uint64_t seed,
              int n_threads, int prefetch_depth) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  madvise(map, st.st_size, MADV_SEQUENTIAL);
  auto* L = new Loader();
  L->fd = fd;
  L->data = static_cast<const int32_t*>(map);
  L->file_bytes = st.st_size;
  L->seq_len = seq_len;
  L->batch = batch;
  L->n_seqs = (size_t)(st.st_size / sizeof(int32_t)) / seq_len;
  L->n_batches = L->n_seqs / batch;
  L->seed = seed;
  L->n_threads = n_threads > 0 ? n_threads : 2;
  L->prefetch_depth = prefetch_depth > 0 ? prefetch_depth : 4;
  return L;
}

int64_t tl_num_batches(void* h) { return ((Loader*)h)->n_batches; }
int64_t tl_num_sequences(void* h) { return ((Loader*)h)->n_seqs; }

void tl_start_epoch(void* h, int64_t epoch, int64_t start_batch) {
  ((Loader*)h)->start_epoch(epoch, (size_t)start_batch);
}

int tl_next_batch(void* h, int32_t* out) { return ((Loader*)h)->next(out); }

void tl_close(void* h) {
  auto* L = (Loader*)h;
  L->stop_workers();
  munmap((void*)L->data, L->file_bytes);
  ::close(L->fd);
  delete L;
}

}  // extern "C"
