"""Sharded checkpoint + resume via Orbax/TensorStore.

Covers all three reference checkpoint formats (C15, SURVEY.md section 2) with
one mechanism:

- whole-tensor ``torch.save`` (``01-single-gpu/train_llm.py:181-187``),
- sharded DCP save on all ranks (``04-fully-sharded-data-parallel/train_llm.py:241-255``),
- stateful DCP (``06-tensor-parallel/train_llm.py:261-273``)

are all "write the sharded TrainState pytree": every host writes only its
shards (parallel filesystem I/O), restore reads directly into the target
shardings — so there is no rank-0 broadcast on load (the reference needs one
for pretrained weights, ``05:118-139``). Resume trigger stays the reference's
``state.json`` contract (``01:94``): resumable iff ``<exp_dir>/state.json``
exists. RNG state persists inside the TrainState (determinism recipe,
``related-topics/determinism/README.md:46-68``).
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Optional

import jax

from ..utils.procguards import is_process0, sync_processes


class CheckpointIO:
    """``async_save=True`` overlaps the TensorStore writes with subsequent
    training steps (the device arrays are snapshotted by Orbax before save
    returns): the state.json swing + pruning for a save are deferred until
    the write commits — finalized lazily at the *next* save or ``close()`` —
    so crash-safety is preserved (an unfinalized save is invisible to
    resume; the previous checkpoint stays referenced)."""

    def __init__(self, exp_dir: str | Path, *, async_save: bool = False):
        self.exp_dir = Path(exp_dir)
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.async_save = async_save
        if async_save:
            self._checkpointer = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        else:
            self._checkpointer = ocp.StandardCheckpointer()
        self._pending: Optional[tuple[Path, dict, Optional[Path]]] = None

    # ---- paths -------------------------------------------------------------
    @property
    def state_json(self) -> Path:
        return self.exp_dir / "state.json"

    def _ckpt_dir(self, step: int) -> Path:
        return (self.exp_dir / f"checkpoint-{step}").absolute()

    def _current_ckpt_dir(self) -> Optional[Path]:
        if not self.state_json.exists():
            return None
        try:
            with open(self.state_json) as fp:
                name = json.load(fp).get("checkpoint")
        except (json.JSONDecodeError, OSError):
            return None
        if not name:
            return None
        path = (self.exp_dir / name).absolute()
        return path if path.exists() else None

    def can_resume(self) -> bool:
        return self._current_ckpt_dir() is not None

    # ---- save --------------------------------------------------------------
    def _finalize(self, path: Path, host_state: dict, old: Optional[Path]) -> None:
        """Wait for the write, then atomically publish + prune."""
        self._checkpointer.wait_until_finished()
        sync_processes("ckpt_saved")
        if is_process0():
            tmp = self.state_json.with_suffix(".json.tmp")
            with open(tmp, "w") as fp:
                json.dump({**host_state, "checkpoint": path.name}, fp)
            tmp.replace(self.state_json)  # atomic on POSIX
            if old is not None and old != path:
                import shutil

                shutil.rmtree(old, ignore_errors=True)
        sync_processes("ckpt_state_json")

    def flush(self) -> None:
        """Finalize any in-flight async save (publishes its state.json)."""
        if self._pending is not None:
            self._finalize(*self._pending)
            self._pending = None

    def close(self) -> None:
        self.flush()
        close_fn = getattr(self._checkpointer, "close", None)
        if close_fn:  # release the AsyncCheckpointer thread pool / barriers
            close_fn()

    def save(self, train_state: Any, host_state: dict) -> None:
        """Crash-safe save: each step writes a fresh ``checkpoint-<step>`` dir
        (all hosts write their own shards in parallel; Orbax finalizes the dir
        atomically), then process 0 atomically swings state.json to it, then
        older checkpoints are pruned. A crash at any point leaves the previous
        checkpoint referenced by a valid state.json."""
        self.flush()
        self.exp_dir.mkdir(parents=True, exist_ok=True)
        step = int(host_state.get("global_step", 0))
        path = self._ckpt_dir(step)
        old = self._current_ckpt_dir()
        self._checkpointer.save(path, train_state, force=True)
        if self.async_save:
            self._pending = (path, dict(host_state), old)
        else:
            self._finalize(path, host_state, old)

    # ---- restore -----------------------------------------------------------
    def restore(self, abstract_state: Any) -> tuple[Any, dict]:
        """abstract_state: pytree of jax.ShapeDtypeStruct *with shardings* —
        each host reads exactly its shards from TensorStore."""
        self.flush()
        path = self._current_ckpt_dir()
        if path is None:
            raise FileNotFoundError(f"no resumable checkpoint in {self.exp_dir}")
        train_state = self._checkpointer.restore(path, abstract_state)
        with open(self.state_json) as fp:
            host_state = json.load(fp)
        host_state.pop("checkpoint", None)
        return train_state, host_state


def abstract_train_state(trainer):
    """Sharded abstract TrainState (restore target) for a Trainer."""
    import jax.numpy as jnp

    from ..train.state import TrainState

    def shape_fn(seed):
        init_rng, train_rng = jax.random.split(jax.random.key(seed))
        params = trainer.bundle.init(trainer.bundle.config, init_rng)
        opt_state = trainer.optimizer.init(params)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt_state, rng=jax.random.key_data(train_rng))

    state_shapes = jax.eval_shape(shape_fn, jnp.zeros((), jnp.uint32))
    return jax.tree.map(
        lambda sd, sh: jax.ShapeDtypeStruct(sd.shape, sd.dtype, sharding=sh),
        state_shapes, trainer.state_shardings)
