"""Sharded checkpoint + resume via Orbax/TensorStore, hardened.

Covers all three reference checkpoint formats (C15, SURVEY.md section 2) with
one mechanism:

- whole-tensor ``torch.save`` (``01-single-gpu/train_llm.py:181-187``),
- sharded DCP save on all ranks (``04-fully-sharded-data-parallel/train_llm.py:241-255``),
- stateful DCP (``06-tensor-parallel/train_llm.py:261-273``)

are all "write the sharded TrainState pytree": every host writes only its
shards (parallel filesystem I/O), restore reads directly into the target
shardings — so there is no rank-0 broadcast on load (the reference needs one
for pretrained weights, ``05:118-139``). Resume trigger stays the reference's
``state.json`` contract (``01:94``): resumable iff ``<exp_dir>/state.json``
exists. RNG state persists inside the TrainState (determinism recipe,
``related-topics/determinism/README.md:46-68``).

Fault-tolerance layer on top of that contract:

- every published checkpoint gets an integrity manifest (sizes + CRC32 +
  the host loop state, ``manifest.py``), written before state.json swings;
- ``keep_n`` checkpoints are retained (state.json carries the chain,
  newest first) instead of delete-all-but-latest;
- restore verifies the manifest and falls back through the retention chain
  past corrupt/missing checkpoints, logging what it skipped;
- transient filesystem errors during save are retried with bounded
  exponential backoff (single-process sync saves; with ``async_save`` the
  retry covers the blocking snapshot/enqueue phase only, and multi-host
  saves propagate instead of retrying — recovery there belongs to the
  supervisor restart layer);
- unreferenced ``checkpoint-*`` orphans (a crash between the Orbax commit
  and the state.json swing) are swept by the WRITER at its first ``save()``
  and at every publish. Restore-only consumers (hf_export, engine loads)
  never delete anything: a sweep on open could collect a live writer's
  committed-but-unpublished checkpoint.
"""
from __future__ import annotations

import json
import logging
import re
import shutil
import time
from pathlib import Path
from typing import Any, Optional

import jax

from ..utils.procguards import is_process0, sync_processes
from . import manifest as manifest_mod

LOGGER = logging.getLogger(__name__)

_CKPT_RE = re.compile(r"^checkpoint-\d+$")


class CheckpointCorruptionError(RuntimeError):
    """Every checkpoint in the retention chain failed verification/restore."""


class CheckpointIO:
    """``async_save=True`` overlaps the TensorStore writes with subsequent
    training steps (the device arrays are snapshotted by Orbax before save
    returns): the state.json swing + pruning for a save are deferred until
    the write commits — finalized lazily at the *next* save or ``close()`` —
    so crash-safety is preserved (an unfinalized save is invisible to
    resume; the previous checkpoint stays referenced)."""

    def __init__(self, exp_dir: str | Path, *, async_save: bool = False,
                 keep_n: int = 2, save_retries: int = 2,
                 retry_backoff_s: float = 0.5, full_crc: bool = False):
        self.exp_dir = Path(exp_dir)
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.async_save = async_save
        # full_crc: exhaustively CRC every file in the integrity manifest
        # (default: size-capped sampled CRC for multi-GB TensorStore shards
        # — see manifest.SAMPLE_THRESHOLD)
        self.full_crc = bool(full_crc)
        self.keep_n = max(1, int(keep_n))
        self.save_retries = max(0, int(save_retries))
        self.retry_backoff_s = retry_backoff_s
        if async_save:
            self._checkpointer = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        else:
            self._checkpointer = ocp.StandardCheckpointer()
        self._pending: Optional[tuple[Path, dict, list[str]]] = None
        self._swept = False

    # ---- paths -------------------------------------------------------------
    @property
    def state_json(self) -> Path:
        return self.exp_dir / "state.json"

    def _ckpt_dir(self, step: int) -> Path:
        return (self.exp_dir / f"checkpoint-{step}").absolute()

    def _read_state_json(self) -> Optional[dict]:
        if not self.state_json.exists():
            return None
        try:
            with open(self.state_json) as fp:
                return json.load(fp)
        except (json.JSONDecodeError, OSError):
            return None

    def _retained_names(self) -> list[str]:
        """Retention chain (newest first) from state.json; legacy files
        (pre-retention) carry only ``checkpoint``, a one-entry chain."""
        payload = self._read_state_json()
        if not payload:
            return []
        names = payload.get("retained")
        if not isinstance(names, list) or not names:
            names = [payload.get("checkpoint")]
        return [n for n in names if n]

    def _retention_chain(self) -> list[Path]:
        return [p for n in self._retained_names()
                if (p := (self.exp_dir / n).absolute()).exists()]

    def can_resume(self) -> bool:
        return bool(self._retention_chain())

    # ---- orphan sweep ------------------------------------------------------
    def _sweep_orphans(self) -> None:
        """Collect ``checkpoint-*`` dirs (and stray manifests) that no
        state.json references — the leak left by a crash between the Orbax
        dir commit and ``_finalize``. Called only from the WRITE path
        (first ``save()``): calling save() asserts exclusive ownership of
        the exp_dir, so anything unreferenced is a dead prior incarnation's
        leftovers. Restore-only consumers never sweep — a reader opening a
        live writer's exp_dir must not collect its committed-but-unpublished
        checkpoint."""
        if not self.exp_dir.is_dir() or not is_process0():
            return
        referenced = set(self._retained_names())
        for entry in self.exp_dir.iterdir():
            if entry.is_dir() and _CKPT_RE.match(entry.name):
                name = entry.name
            elif entry.is_file() and entry.name.endswith(".manifest.json"):
                name = entry.name[:-len(".manifest.json")]
                if (self.exp_dir / name).exists():
                    continue  # its dir decides; swept together below
            else:
                continue
            if name in referenced:
                continue
            LOGGER.warning("sweeping orphaned checkpoint artifact %s "
                           "(unreferenced by state.json)", entry.name)
            self._remove_checkpoint(name)

    def _remove_checkpoint(self, name: str) -> None:
        shutil.rmtree(self.exp_dir / name, ignore_errors=True)
        try:
            manifest_mod.manifest_path(self.exp_dir, name).unlink()
        except OSError:
            pass

    # ---- save --------------------------------------------------------------
    def _finalize(self, path: Path, host_state: dict,
                  retained_before: list[str]) -> None:
        """Wait for the write, then atomically publish + prune."""
        self._checkpointer.wait_until_finished()
        sync_processes("ckpt_saved")
        if is_process0():
            step = int(host_state.get("global_step", 0))
            # manifest before state.json: a crash in between leaves an
            # unreferenced dir+manifest pair (swept later), never a
            # referenced checkpoint without integrity data
            manifest_mod.write_manifest(path, step, host_state,
                                        full_crc=self.full_crc)
            retained = [path.name] + [n for n in retained_before
                                      if n != path.name]
            keep = retained[:self.keep_n]
            tmp = self.state_json.with_suffix(".json.tmp")
            with open(tmp, "w") as fp:
                json.dump({**host_state, "checkpoint": path.name,
                           "retained": keep}, fp)
            tmp.replace(self.state_json)  # atomic on POSIX
            # prune EVERYTHING outside the new chain, not just the names we
            # know we dropped — also collects orphans the startup sweep
            # spared for being too young (the writer is exclusive here)
            keep_set = set(keep)
            for entry in self.exp_dir.iterdir():
                if (entry.is_dir() and _CKPT_RE.match(entry.name)
                        and entry.name not in keep_set):
                    self._remove_checkpoint(entry.name)
            from ..utils import faults

            faults.maybe_corrupt_checkpoint(path, step)
        sync_processes("ckpt_state_json")

    def flush(self) -> None:
        """Finalize any in-flight async save (publishes its state.json)."""
        if self._pending is not None:
            self._finalize(*self._pending)
            self._pending = None

    def close(self) -> None:
        self.flush()
        close_fn = getattr(self._checkpointer, "close", None)
        if close_fn:  # release the AsyncCheckpointer thread pool / barriers
            close_fn()

    def _write_with_retry(self, path: Path, train_state: Any) -> None:
        """Bounded-backoff retry around the Orbax write for transient
        filesystem errors (partial output from a failed attempt is removed
        so the retry starts clean). Covers the full write for sync saves;
        for ``async_save`` only the blocking snapshot/enqueue phase — a
        background-write failure raises at the next finalize, un-retried
        (the state snapshot is gone by then), with the previous checkpoint
        still the referenced one. SINGLE-PROCESS only: with multiple hosts
        the error propagates instead — one host retrying would rmtree the
        shared tmp dir peers are still writing into and re-enter Orbax's
        commit barrier alone; recovery there belongs to the restart layer."""
        delay = self.retry_backoff_s
        for attempt in range(self.save_retries + 1):
            try:
                self._checkpointer.save(path, train_state, force=True)
                return
            except OSError as exc:
                if attempt >= self.save_retries or jax.process_count() > 1:
                    raise
                LOGGER.warning(
                    "checkpoint save attempt %d/%d failed (%s); retrying "
                    "in %.2fs", attempt + 1, self.save_retries + 1, exc,
                    delay)
                shutil.rmtree(path, ignore_errors=True)
                for tmp in self.exp_dir.glob(f"{path.name}.orbax-checkpoint-tmp-*"):
                    shutil.rmtree(tmp, ignore_errors=True)
                time.sleep(delay)
                delay *= 2

    def save(self, train_state: Any, host_state: dict) -> None:
        """Crash-safe save: each step writes a fresh ``checkpoint-<step>`` dir
        (all hosts write their own shards in parallel; Orbax finalizes the dir
        atomically), then process 0 writes the integrity manifest, atomically
        swings state.json to the new retention chain, and prunes beyond
        ``keep_n``. A crash at any point leaves the previous chain referenced
        by a valid state.json."""
        self.flush()
        self.exp_dir.mkdir(parents=True, exist_ok=True)
        if not self._swept:
            self._sweep_orphans()
            self._swept = True
        from ..utils import faults

        faults.maybe_save_latency()
        step = int(host_state.get("global_step", 0))
        path = self._ckpt_dir(step)
        retained_before = self._retained_names()
        self._write_with_retry(path, train_state)
        if self.async_save:
            self._pending = (path, dict(host_state), retained_before)
        else:
            self._finalize(path, host_state, retained_before)

    # ---- restore -----------------------------------------------------------
    def _rebase_restored(self, tree: Any) -> Any:
        """Copy restored leaves onto fresh XLA-allocated buffers.

        Donating a TensorStore-backed restored buffer into a jitted step
        whose executable came from the persistent compilation cache corrupts
        the allocator heap on the CPU backend (glibc "double free /
        smallbin corrupted" aborts — found by this repo's chaos drills, jax
        0.4.37). The copy costs one pass over the state at resume time and
        makes every restored leaf an ordinary XLA buffer. Leaves living in
        non-default memory (pinned_host offload) keep their storage: a plain
        copy would not preserve the memory kind, and the offload step path
        device-puts them before any donation anyway."""
        try:
            default_kind = jax.local_devices()[0].default_memory().kind
        except Exception:  # backends without memory-kind support
            default_kind = None

        def copy_leaf(x):
            kind = getattr(getattr(x, "sharding", None), "memory_kind", None)
            if (default_kind is not None and kind is not None
                    and kind != default_kind):
                return x
            return x.copy()

        return jax.tree.map(copy_leaf, tree)

    def _host_state_for(self, path: Path, manifest: Optional[dict]) -> dict:
        if manifest is not None and isinstance(manifest.get("host_state"), dict):
            return dict(manifest["host_state"])
        # legacy checkpoint (pre-manifest): state.json's counters describe
        # the NEWEST checkpoint; warn when we restored an older one
        host_state = dict(self._read_state_json() or {})
        host_state.pop("checkpoint", None)
        host_state.pop("retained", None)
        if path.name != (self._retained_names() or [path.name])[0]:
            LOGGER.warning(
                "restored %s without a manifest; host counters from "
                "state.json may describe a newer checkpoint", path.name)
        return host_state

    def _verified_candidate(self, chain: list[Path],
                            failures: list[str]) -> int:
        """Index of the newest chain entry whose manifest verifies (legacy
        no-manifest entries are trusted with a warning), or -1."""
        for i, path in enumerate(chain):
            if not path.exists():
                LOGGER.warning("skipping checkpoint %s: referenced by "
                               "state.json but missing on disk", path.name)
                failures.append(f"{path.name}: missing")
                continue
            manifest = manifest_mod.load_manifest(self.exp_dir, path.name)
            if manifest is None:
                LOGGER.warning("checkpoint %s has no manifest (legacy "
                               "save?); restoring unverified", path.name)
                return i
            problems = manifest_mod.verify_manifest(path, manifest)
            if not problems:
                return i
            LOGGER.warning("skipping checkpoint %s: failed integrity check "
                           "(%s)", path.name, "; ".join(problems[:3]))
            failures.append(f"{path.name}: {problems[0]}")
        return -1

    def restore(self, abstract_state: Any) -> tuple[Any, dict]:
        """abstract_state: pytree of jax.ShapeDtypeStruct *with shardings* —
        each host reads exactly its shards from TensorStore.

        Walks the retention chain newest-first; a checkpoint whose manifest
        fails verification (single-process: or whose TensorStore read
        raises) is skipped with a warning and the next-older one is tried.
        Multi-host, the fallback decision must be one decision: process 0
        verifies the manifests and broadcasts the chosen candidate, so hosts
        can never restore different checkpoints (per-host verdicts could
        diverge on a flaky shared FS — half the pod resuming step N and half
        step N-1 hangs collectives or silently forks the run). A TensorStore
        read error then fails the whole gang loudly instead of falling back
        on one host only; the supervisor's restart retries the same agreed
        candidate. Raises ``CheckpointCorruptionError`` when candidates
        existed but none survived, ``FileNotFoundError`` when there was
        nothing to resume."""
        self.flush()
        names = self._retained_names()
        if not names:
            raise FileNotFoundError(f"no resumable checkpoint in {self.exp_dir}")
        failures: list[str] = []
        if jax.process_count() > 1:
            # the broadcast index must mean the same checkpoint on every
            # host, so the index space is the state.json name list itself —
            # NOT each host's existence-filtered view of the shared FS
            # (hosts seeing different subsets would resolve the same index
            # to different checkpoints: a silent fork of the run)
            chain = [(self.exp_dir / n).absolute() for n in names]
            import numpy as np
            from jax.experimental import multihost_utils

            idx = (self._verified_candidate(chain, failures)
                   if is_process0() else 0)
            idx = int(multihost_utils.broadcast_one_to_all(
                np.int32(idx), is_source=is_process0()))
            if idx < 0:
                raise CheckpointCorruptionError(
                    f"no checkpoint in {self.exp_dir} survived verification: "
                    + "; ".join(failures))
            path = chain[idx]
            if idx > 0:
                LOGGER.warning("process 0 chose fallback checkpoint %s",
                               path.name)
            train_state = self._checkpointer.restore(path, abstract_state)
            manifest = manifest_mod.load_manifest(self.exp_dir, path.name)
            return (self._rebase_restored(train_state),
                    self._host_state_for(path, manifest))
        chain = self._retention_chain()
        if not chain:
            raise FileNotFoundError(f"no resumable checkpoint in {self.exp_dir}")
        start = 0
        while True:
            idx = self._verified_candidate(chain[start:], failures)
            if idx < 0:
                raise CheckpointCorruptionError(
                    f"no checkpoint in {self.exp_dir} survived verification: "
                    + "; ".join(failures))
            path = chain[start + idx]
            manifest = manifest_mod.load_manifest(self.exp_dir, path.name)
            try:
                train_state = self._checkpointer.restore(path, abstract_state)
            except Exception as exc:  # noqa: BLE001 — any reader error falls back
                LOGGER.warning("skipping checkpoint %s: restore failed (%s)",
                               path.name, exc)
                failures.append(f"{path.name}: {exc}")
                start += idx + 1
                continue
            if failures:
                LOGGER.warning("fell back to checkpoint %s after skipping: %s",
                               path.name, "; ".join(failures))
            return (self._rebase_restored(train_state),
                    self._host_state_for(path, manifest))


def abstract_train_state(trainer, *, fp32_reference: bool = False):
    """Sharded abstract TrainState (restore target) for a Trainer.

    ``fp32_reference=True`` builds the PRE-precision-policy layout (fp32
    params, the unwrapped optimizer's fp32 moments) — the restore target for
    checkpoints written before a run adopted a storage policy."""
    import jax.numpy as jnp

    from ..train.precision import cast_floats
    from ..train.state import TrainState

    def shape_fn(seed):
        init_rng, train_rng = jax.random.split(jax.random.key(seed))
        params = trainer.bundle.init(trainer.bundle.config, init_rng)
        if fp32_reference:
            params = cast_floats(params, jnp.float32)
            opt_state = trainer.base_optimizer.init(params)
        else:
            params = trainer.precision.cast_params(params)
            opt_state = trainer.optimizer.init(params)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt_state, rng=jax.random.key_data(train_rng))

    state_shapes = jax.eval_shape(shape_fn, jnp.zeros((), jnp.uint32))
    shardings = (trainer.fp32_state_shardings if fp32_reference
                 else trainer.state_shardings)
    return jax.tree.map(
        lambda sd, sh: jax.ShapeDtypeStruct(sd.shape, sd.dtype, sharding=sh),
        state_shapes, shardings)


def _recorded_host_state(io: CheckpointIO) -> dict:
    """The newest retained checkpoint's manifest host_state — read ONCE
    per restore (it carries every stamp restore checks: precision
    policy, mesh descriptor). Empty for legacy/pre-stamp saves."""
    for path in io._retention_chain()[:1]:
        manifest = manifest_mod.load_manifest(io.exp_dir, path.name)
        if manifest and isinstance(manifest.get("host_state"), dict):
            return manifest["host_state"]
    return {}


def _recorded_precision_policy(io: CheckpointIO) -> Optional[str]:
    return _recorded_host_state(io).get("precision_policy")


def stamp_host_state(host_state: dict, trainer) -> dict:
    """Stamp the layout facts ``restore_train_state`` verifies into a
    host_state dict (mutates and returns it): the precision-policy name
    (policy-mismatch loud failures) and the mesh descriptor
    (reshard-compatibility — ``checkpoint/reshard.py``). One helper so
    every save site (train CLI, engine facade, tests) stamps identically."""
    from .reshard import mesh_descriptor

    host_state["precision_policy"] = trainer.precision.name
    host_state["mesh"] = mesh_descriptor(trainer)
    return host_state


def restore_train_state(io: CheckpointIO, trainer) -> tuple[Any, dict]:
    """Policy-aware restore: the one entry point train loops should use.

    Restores into the trainer's precision-policy storage layout. An fp32
    (pre-policy) checkpoint restored into a policy run is re-encoded —
    params cast and optimizer moments (re)quantized into policy storage —
    with a logged warning, since requantized moments are not bit-identical
    to ones carried through a quantized checkpoint. Every OTHER layout
    mismatch is a loud failure, not a fallback: the save path stamps the
    policy name into the manifest host_state, so restoring a quantized
    checkpoint into a run that dropped (or changed) its --precision-policy
    raises naming both policies instead of silently resuming an older
    checkpoint from the retention chain and masking the config regression.
    Unstamped (pre-stamp) checkpoints keep the try-then-fall-back behavior.

    Mesh changes are first-class (the elastic-restart path): the save side
    stamps a mesh descriptor (``stamp_host_state``), and a restore whose
    trainer sits on a DIFFERENT mesh is checked for reshard compatibility
    (``checkpoint/reshard.py``) before any TensorStore read — a benign
    dp/fsdp/tp refactorization logs one loud "resharding A -> B" line and
    restores into the new shardings; a pipeline-stage-split or
    quantized-block-tiling change raises ``ReshardIncompatibleError``
    naming both layouts instead of dying inside TensorStore or silently
    falling back through the retention chain."""
    from .reshard import (check_reshard_compatibility, describe_layout,
                          mesh_descriptor)

    policy = trainer.precision
    stamps = _recorded_host_state(io)
    target_layout = mesh_descriptor(trainer)
    recorded_layout = stamps.get("mesh")
    if check_reshard_compatibility(recorded_layout, target_layout):
        LOGGER.warning(
            "cross-mesh restore: resharding checkpoint saved on [%s] onto "
            "[%s] — the abstract target carries the new shardings, each "
            "host reads exactly its new shards",
            describe_layout(recorded_layout),
            describe_layout(target_layout))
    recorded = stamps.get("precision_policy")
    if recorded and recorded != policy.name:
        if recorded == "fp32" and not policy.is_noop:
            # known-fp32 checkpoint into a policy run: skip the doomed
            # policy-layout attempt and go straight to the re-encode path
            state32, host = io.restore(
                abstract_train_state(trainer, fp32_reference=True))
            LOGGER.warning(
                "checkpoint in %s holds fp32 (pre-policy) state; re-encoding "
                "into precision policy '%s' — quantized moments are "
                "re-quantized, so they will not be bit-identical to a native "
                "policy checkpoint", io.exp_dir, policy.name)
            return trainer.encode_fp32_state(state32), host
        raise ValueError(
            f"checkpoint in {io.exp_dir} was written under precision policy "
            f"{recorded!r} but this run is configured for {policy.name!r}; "
            f"restore with the matching --precision-policy / "
            f"optimizer.params.precision (fp32 checkpoints re-encode into "
            f"policy runs automatically; other conversions are not "
            f"performed silently)")
    try:
        return io.restore(abstract_train_state(trainer))
    except FileNotFoundError:
        raise
    except Exception as exc:  # noqa: BLE001 — unstamped layout mismatch
        if policy.is_noop:
            raise
        try:
            state32, host = io.restore(
                abstract_train_state(trainer, fp32_reference=True))
        except Exception:
            raise exc  # the original (policy-layout) failure is the story
        LOGGER.warning(
            "checkpoint in %s holds fp32 (pre-policy) state; re-encoding "
            "into precision policy '%s' — quantized moments are "
            "re-quantized, so they will not be bit-identical to a native "
            "policy checkpoint", io.exp_dir, policy.name)
        return trainer.encode_fp32_state(state32), host
