"""Mesh-layout stamps and the reshard-compatibility check for restore.

A checkpoint is global arrays addressed by index ranges (TensorStore), so
restoring onto a DIFFERENT mesh is, mechanically, a pure layout problem:
``abstract_train_state(trainer)`` already carries the TARGET shardings and
Orbax re-slices each host's reads into them (ZeRO's observation — state is
a global tensor, the partitioning is bookkeeping; Rajbhandari et al.,
arXiv:1910.02054). Elastic restarts lean on exactly that: lose half the
pod, rebuild the mesh from the live devices, restore, continue
(``related-topics/elastic-training``).

What mechanics can NOT express is whether the resulting run is the same
TRAINING RUN. Two layout families genuinely break across a mesh change
and previously failed deep inside TensorStore (shape mismatch walls of
text) or — worse — fell back through the retention chain to an older
checkpoint, silently rewinding the run:

- **pipeline stage splits**: the pp schedule's manual regions and the
  stage-owned layer ranges are a function of ``pp``; a checkpoint written
  under one stage split restored into another has never been validated
  here and must not be guessed at.
- **quantized opt-state block tilings**: adam8bit moments are int8
  payloads + one fp32 scale per block of the trailing axis
  (``train/precision.py``); the scale SHAPES encode the block size, so a
  checkpoint written at block 64 cannot restore into a block-128 layout
  — the abstract target simply has different arrays.

So every save stamps a small **mesh descriptor** into the manifest's
host_state (next to the precision-policy stamp), and
``restore_train_state`` compares it against the restoring trainer's
descriptor: benign refactorizations (dp/fsdp/tp factor changes, fewer or
more devices) log a loud "resharding A -> B" line and proceed;
genuinely incompatible layouts raise :class:`ReshardIncompatibleError`
NAMING BOTH LAYOUTS and the knob to change. Unstamped (pre-stamp)
checkpoints keep the old behavior.
"""
from __future__ import annotations

import math
from typing import Optional


class ReshardIncompatibleError(ValueError):
    """The checkpoint's recorded layout cannot restore into the target
    trainer's layout by resharding alone (pp stage split or quantized
    block tiling changed). Carries both descriptors."""

    def __init__(self, message: str, *, saved: dict, target: dict):
        super().__init__(message)
        self.saved = dict(saved)
        self.target = dict(target)


def mesh_descriptor(trainer) -> dict:
    """The layout stamp for one trainer: the mesh's non-trivial axes, the
    device count, the sharding strategy, the pipeline stage split, and the
    quantized-moment block size (None for unquantized policies). Small,
    JSON-safe, and sufficient for :func:`check_reshard_compatibility` —
    NOT a full sharding spec (the abstract restore target owns that)."""
    mesh = trainer.plan.mesh
    shape = dict(mesh.shape)
    policy = trainer.precision
    return {
        "axes": {k: int(v) for k, v in shape.items() if int(v) > 1},
        "device_count": int(math.prod(int(v) for v in shape.values())),
        "strategy": trainer.plan.strategy,
        "pp_stages": int(shape.get("pp", 1)),
        "quant_block": (int(policy.block_size)
                        if policy.quantize_moments else None),
    }


def describe_layout(desc: dict) -> str:
    """One human line for a descriptor (error messages and reshard logs)."""
    axes = desc.get("axes") or {}
    axes_s = ("x".join(f"{k}={v}" for k, v in sorted(axes.items()))
              or "single")
    parts = [f"{desc.get('strategy', '?')}[{axes_s}]",
             f"{desc.get('device_count', '?')} devices"]
    if desc.get("pp_stages", 1) > 1:
        parts.append(f"pp_stages={desc['pp_stages']}")
    if desc.get("quant_block") is not None:
        parts.append(f"quant_block={desc['quant_block']}")
    return ", ".join(parts)


def check_reshard_compatibility(saved: Optional[dict], target: dict) -> bool:
    """True when restoring ``saved`` -> ``target`` is a mesh CHANGE that
    plain resharding covers (the caller logs it); False when the layouts
    match (nothing to say). Raises :class:`ReshardIncompatibleError` for
    the two known-breaking families, naming both layouts.

    ``saved=None`` (pre-stamp checkpoint) is treated as unknown-but-
    allowed — exactly the old behavior."""
    if not saved:
        return False
    saved_pp = int(saved.get("pp_stages", 1))
    target_pp = int(target.get("pp_stages", 1))
    if saved_pp != target_pp:
        raise ReshardIncompatibleError(
            f"checkpoint was saved under a {saved_pp}-stage pipeline split "
            f"({describe_layout(saved)}) but this run uses {target_pp} "
            f"stage(s) ({describe_layout(target)}); pipeline stage splits "
            f"are not reshard-compatible — restore with the matching "
            f"pipeline_parallel, or export through the fp32/HF path and "
            f"re-import", saved=saved, target=target)
    saved_block = saved.get("quant_block")
    target_block = target.get("quant_block")
    if (saved_block is not None and target_block is not None
            and int(saved_block) != int(target_block)):
        raise ReshardIncompatibleError(
            f"checkpoint holds quantized optimizer moments tiled at block "
            f"size {saved_block} ({describe_layout(saved)}) but this run's "
            f"precision policy tiles at block size {target_block} "
            f"({describe_layout(target)}); the per-block scale arrays have "
            f"different shapes, so this cannot restore by resharding — use "
            f"a policy with block_size={saved_block}, or restore with the "
            f"original policy and re-encode", saved=saved, target=target)
    return (saved.get("axes") != target.get("axes")
            or saved.get("device_count") != target.get("device_count"))
