"""Per-checkpoint integrity manifests.

The reference's resume contract trusts the filesystem completely: ``state.json``
names a checkpoint dir and restore reads whatever bytes are there
(``01-single-gpu/train_llm.py:94-110``). At pod scale that trust is misplaced —
a host that dies mid-write, a flaky NFS close, or a partially-evicted page
cache can leave a checkpoint that *restores without error* into garbage
weights (TensorStore happily reads corrupted chunk bytes as float data).

A manifest is written next to every published checkpoint dir
(``checkpoint-<step>.manifest.json``) recording the step, the host-side loop
state, and every file's size + CRC32. Restore verifies the manifest before
trusting a checkpoint and falls back through the retention chain
(``orbax_io.CheckpointIO``) when verification fails.

CRC32 (zlib) rather than sha256: the point is detecting torn/partial/bit-rotted
writes, not adversarial tampering, and CRC streams at memory bandwidth so
manifest verification stays negligible next to the TensorStore read itself.

Cost scaling: a full-file CRC on process 0 is O(checkpoint bytes) over the
shared filesystem every save — at pod scale (multi-GB TensorStore shards)
that read dominates the save. Files beyond ``SAMPLE_THRESHOLD`` therefore
get a *sampled* CRC by default: head + tail + evenly strided interior
windows (deterministic in the file size, so verification recomputes the
identical byte set), capping per-file manifest I/O at a few MiB while still
catching truncation (size check), torn head/tail writes, and stride-scale
corruption. ``full_crc=True`` (CLI ``--checkpoint-full-crc``) restores the
exhaustive scan.
"""
from __future__ import annotations

import json
import logging
import os
import time
import zlib
from pathlib import Path
from typing import Optional

LOGGER = logging.getLogger(__name__)

MANIFEST_FORMAT = 1
# manifests containing sampled-CRC entries declare format 2: their crc32
# values cover only the sampled windows, which a format-1 verifier would
# full-scan and misread as corruption. (A pre-sampling release rolled back
# onto format-2 manifests still fails verification — loudly, via the
# retention-chain fallback — since it never reads the format field; that
# one-way hazard is inherent to any manifest extension.)
MANIFEST_FORMAT_SAMPLED = 2
_CHUNK = 1 << 20
# files larger than this get the sampled CRC (unless full_crc); the cap
# bounds a sampled file's manifest read at _SAMPLE_WINDOWS * _CHUNK bytes
SAMPLE_THRESHOLD = 64 << 20
_SAMPLE_WINDOWS = 8  # head + tail + up to 6 strided interior windows


def manifest_path(exp_dir: Path, ckpt_name: str) -> Path:
    """Manifest lives BESIDE the checkpoint dir, not inside it: it must
    survive the dir being corrupted, and Orbax owns the dir's contents."""
    return Path(exp_dir) / f"{ckpt_name}.manifest.json"


def _crc32_file(path: Path) -> int:
    crc = 0
    with open(path, "rb") as fp:
        while True:
            chunk = fp.read(_CHUNK)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def _sample_offsets(size: int, chunk: int = _CHUNK,
                    windows: int = _SAMPLE_WINDOWS) -> list[int]:
    """Window start offsets for the sampled CRC — a pure function of the
    file SIZE and the (chunk, windows) parameters, so verification
    recomputes the exact byte set: first and last ``chunk`` plus evenly
    strided interior windows. The parameters are recorded in the manifest
    (``sample_params``) so manifests stay verifiable if the module
    defaults ever change."""
    last = max(size - chunk, 0)
    offsets = {0, last}
    interior = windows - 2
    for i in range(1, interior + 1):
        offsets.add(min((size * i) // (interior + 1), last))
    return sorted(offsets)


def _crc32_file_sampled(path: Path, size: int, chunk: int = _CHUNK,
                        windows: int = _SAMPLE_WINDOWS) -> tuple[int, int]:
    """(crc, bytes_read) over the deterministic sample windows."""
    crc = 0
    read = 0
    with open(path, "rb") as fp:
        for off in _sample_offsets(size, chunk, windows):
            fp.seek(off)
            data = fp.read(chunk)
            crc = zlib.crc32(data, crc)
            read += len(data)
    return crc, read


def _entry_crc(path: Path, size: int, full_crc: bool) -> dict:
    if full_crc or size <= SAMPLE_THRESHOLD:
        return {"crc32": _crc32_file(path)}
    crc, read = _crc32_file_sampled(path, size)
    return {"crc32": crc, "crc_mode": "sampled", "sampled_bytes": read}


def _walk_files(ckpt_dir: Path) -> list[Path]:
    return sorted(p for p in Path(ckpt_dir).rglob("*") if p.is_file())


def write_manifest(ckpt_dir: Path, step: int, host_state: dict, *,
                   full_crc: bool = False) -> Path:
    """Checksum every file under ``ckpt_dir`` (as it is enumerated — one
    pass) and write the manifest. Files beyond ``SAMPLE_THRESHOLD`` get the
    size-capped sampled CRC unless ``full_crc``.

    Called by process 0 after the Orbax write committed (the dir rename) and
    before state.json publishes the checkpoint — a crash in between leaves an
    orphan (dir + manifest) that the startup sweep collects, never a published
    checkpoint without a manifest.
    """
    ckpt_dir = Path(ckpt_dir)
    files = []
    for p in _walk_files(ckpt_dir):
        size = p.stat().st_size
        files.append({"path": str(p.relative_to(ckpt_dir)), "size": size,
                      **_entry_crc(p, size, full_crc)})
    sampled = any(f.get("crc_mode") == "sampled" for f in files)
    payload = {
        "format": MANIFEST_FORMAT_SAMPLED if sampled else MANIFEST_FORMAT,
        # the window schedule the sampled entries were computed with —
        # verification uses THESE, not the module defaults, so changing
        # the defaults never invalidates existing manifests
        **({"sample_params": {"chunk": _CHUNK,
                              "windows": _SAMPLE_WINDOWS}} if sampled
           else {}),
        "checkpoint": ckpt_dir.name,
        "step": int(step),
        "host_state": dict(host_state),
        "files": files,
        "created": int(time.time()),
    }
    path = manifest_path(ckpt_dir.parent, ckpt_dir.name)
    tmp = path.with_suffix(".json.tmp")
    with open(tmp, "w") as fp:
        json.dump(payload, fp)
    os.replace(tmp, path)  # atomic on POSIX
    return path


def load_manifest(exp_dir: Path, ckpt_name: str) -> Optional[dict]:
    """The manifest for ``ckpt_name``, or None if absent/unreadable (legacy
    checkpoints predate manifests; an unreadable one reads as absent so the
    caller decides whether to trust the checkpoint anyway)."""
    path = manifest_path(exp_dir, ckpt_name)
    try:
        with open(path) as fp:
            payload = json.load(fp)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(payload, dict) or "files" not in payload:
        return None
    return payload


def verify_manifest(ckpt_dir: Path, manifest: dict) -> list[str]:
    """Problems found checking ``ckpt_dir`` against ``manifest`` (empty list
    = intact). Reports every divergence, cheapest checks first: existence and
    size before CRC, so a missing shard is named without reading gigabytes."""
    ckpt_dir = Path(ckpt_dir)
    problems: list[str] = []
    if not ckpt_dir.is_dir():
        return [f"checkpoint dir missing: {ckpt_dir}"]
    expected = {e["path"]: e for e in manifest.get("files", [])}
    for rel, entry in expected.items():
        p = ckpt_dir / rel
        if not p.is_file():
            problems.append(f"missing file: {rel}")
            continue
        size = p.stat().st_size
        if size != entry["size"]:
            problems.append(f"size mismatch: {rel} ({size} != {entry['size']})")
            continue
        if entry.get("crc_mode") == "sampled":
            # recompute over the identical window set: offsets derive from
            # the recorded size (which just matched) and the manifest's own
            # recorded sample parameters (module defaults may have moved)
            sp = manifest.get("sample_params", {})
            crc, _ = _crc32_file_sampled(
                p, size, sp.get("chunk", _CHUNK),
                sp.get("windows", _SAMPLE_WINDOWS))
        else:
            crc = _crc32_file(p)
        if crc != entry["crc32"]:
            problems.append(f"checksum mismatch: {rel}")
    extra = {str(p.relative_to(ckpt_dir)) for p in _walk_files(ckpt_dir)} - set(expected)
    if extra:
        # extra files are logged but not fatal: Orbax may add metadata across
        # versions, and restore ignores files it doesn't know
        LOGGER.info("checkpoint %s has %d file(s) not in manifest: %s",
                    ckpt_dir.name, len(extra), sorted(extra)[:5])
    return problems
