from .orbax_io import (CheckpointCorruptionError, CheckpointIO,
                       abstract_train_state, restore_train_state)
from .manifest import load_manifest, manifest_path, verify_manifest, write_manifest

__all__ = [
    "CheckpointIO",
    "CheckpointCorruptionError",
    "abstract_train_state",
    "restore_train_state",
    "write_manifest",
    "load_manifest",
    "verify_manifest",
    "manifest_path",
]
