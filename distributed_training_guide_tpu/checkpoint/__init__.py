from .orbax_io import CheckpointIO, abstract_train_state

__all__ = ["CheckpointIO", "abstract_train_state"]
