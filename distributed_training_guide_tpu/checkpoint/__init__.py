from .orbax_io import (CheckpointCorruptionError, CheckpointIO,
                       abstract_train_state, restore_train_state,
                       stamp_host_state)
from .manifest import load_manifest, manifest_path, verify_manifest, write_manifest
from .reshard import (ReshardIncompatibleError, check_reshard_compatibility,
                      describe_layout, mesh_descriptor)

__all__ = [
    "CheckpointIO",
    "CheckpointCorruptionError",
    "abstract_train_state",
    "restore_train_state",
    "stamp_host_state",
    "ReshardIncompatibleError",
    "check_reshard_compatibility",
    "describe_layout",
    "mesh_descriptor",
    "write_manifest",
    "load_manifest",
    "verify_manifest",
    "manifest_path",
]
