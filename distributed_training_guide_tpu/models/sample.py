"""Minimal text sampling from any model in the zoo — a qualitative check
for trained / converted checkpoints.

Default mode re-runs the FULL forward over a fixed-size buffer per token
(any family, one compile) — the hermetic numerics reference. ``--kv-cache``
delegates to the serving runtime (``serve/``): the continuous-batching
paged-KV engine at n_slots=1 — prefill + cached one-token decode steps for
the llama family incl. qwen3/olmo2/gemma2 wirings, gpt2, neox, and moe
(routed FFN drop-free per decoded token; same greedy tokens, pinned per
family by test). The real serving path (multi-request, HTTP) lives at
``python -m distributed_training_guide_tpu.serve``.

    # hermetic (no tokenizer): raw token ids in, ids out
    python -m distributed_training_guide_tpu.models.sample \\
        -m llama-debug --prompt-ids 3,17,42 --steps 16
    # with a tokenizer cache: text in, text out
    python -m distributed_training_guide_tpu.models.sample \\
        -m gpt2 --pretrained /ckpts/gpt2-conv --prompt "The TPU" --steps 32
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


def make_sampler(bundle, temperature: float = 0.0, kv_cache: bool = False):
    """One compiled decode step per generation. Two modes:

    - recompute (default, any family): the full forward re-runs over a
      fixed buffer and the token at ``pos`` is written — O(steps x
      forward(prompt+steps));
    - ``kv_cache=True`` (families exporting ``init_cache``/``prefill``/
      ``paged_decode_step`` — the llama family, gpt2, neox, moe): the
      serving engine (serve/engine.py) at n_slots=1 — one bucketed prefill
      over the prompt, then one single-token program per step attending
      over the paged cache — O(forward(prompt) + steps x token). Same
      greedy tokens as recompute (pinned per family by tests/test_sample.py);
      at temperature > 0 draws come from the engine's per-request
      fold_in(seed, position) stream (deterministic in ``rng``).

    Greedy when ``temperature == 0`` (a Python constant — each mode is its
    own single compile)."""

    def pick(logit, key):
        if temperature == 0.0:
            return jnp.argmax(logit)
        return jax.random.categorical(key, logit / temperature)

    max_pos = getattr(bundle.config, "max_position_embeddings", None)

    def check_length(n_prompt: int, steps: int) -> None:
        # the guard lives HERE, not only in the CLI main(): as a library,
        # an over-long generation would silently clamp gpt2's learned
        # position table (and the cache's dynamic_update_slice) under jit —
        # garbage tokens with no error
        if max_pos and n_prompt + steps > max_pos:
            raise ValueError(
                f"prompt ({n_prompt}) + steps ({steps}) exceeds the model's "
                f"max_position_embeddings ({max_pos})")

    if kv_cache:
        from .registry import family_module

        mod = family_module(bundle.family)
        if not hasattr(mod, "decode_step"):
            raise ValueError(f"family {bundle.family!r} has no KV-cached "
                             f"decode; use kv_cache=False")
        engines: dict = {}

        def sample(params, prompt_ids, steps: int,
                   rng: Optional[jax.Array] = None) -> list[int]:
            from ..serve.api import generate_many
            from ..serve.engine import ServeEngine
            from ..serve.scheduler import Request

            rng = rng if rng is not None else jax.random.key(0)
            n = len(prompt_ids)
            check_length(n, steps)
            page = 16
            capacity = -(-(n + steps) // page) * page
            # one engine (== one compiled prefill/decode pair) per page-
            # rounded capacity; the engine holds its params so the id key
            # stays pinned to the live object
            eng = engines.get((id(params), capacity))
            if eng is None:
                eng = ServeEngine(bundle, params, n_slots=1, page_size=page,
                                  max_len=capacity)
                engines[(id(params), capacity)] = eng
            seed = int(jax.random.randint(rng, (), 0, 2**31 - 1))
            res = generate_many(eng, [Request(
                prompt_ids=[int(t) for t in prompt_ids],
                max_new_tokens=steps, temperature=temperature, seed=seed)])
            return res[0].token_ids

        return sample

    @partial(jax.jit, donate_argnums=(1,))
    def decode_step(params, buf, pos, key):
        logits = bundle.apply(bundle.config, params, buf)
        logit = jax.lax.dynamic_index_in_dim(logits[0], pos - 1, axis=0,
                                             keepdims=False)
        return jax.lax.dynamic_update_index_in_dim(
            buf, pick(logit, key).astype(buf.dtype)[None], pos, axis=1)

    def sample(params, prompt_ids, steps: int,
               rng: Optional[jax.Array] = None) -> list[int]:
        rng = rng if rng is not None else jax.random.key(0)
        n = len(prompt_ids)
        check_length(n, steps)
        buf = jnp.zeros((1, n + steps), jnp.int32)
        buf = buf.at[0, :n].set(jnp.asarray(prompt_ids, jnp.int32))
        for t in range(n, n + steps):
            rng, key = jax.random.split(rng)
            buf = decode_step(params, buf, jnp.asarray(t), key)
        return [int(x) for x in buf[0]]

    return sample


def main(argv=None) -> None:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("-m", "--model-name", required=True)
    parser.add_argument("--prompt", default=None,
                        help="text prompt (needs the model's HF tokenizer "
                             "in the local cache)")
    parser.add_argument("--prompt-ids", default=None,
                        help="comma-separated token ids — the hermetic path")
    parser.add_argument("--steps", type=int, default=32)
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--kv-cache", action="store_true",
                        help="prefill + cached one-token decode steps "
                             "(dense families) instead of full recompute")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--pretrained", default=None, metavar="DIR",
                        help="converted checkpoint dir (models/hf_convert); "
                             "random init otherwise")
    args = parser.parse_args(argv)
    if (args.prompt is None) == (args.prompt_ids is None):
        raise SystemExit("pass exactly one of --prompt / --prompt-ids")

    from ..parallel import make_mesh, make_plan
    from .registry import get_model

    bundle = get_model(args.model_name, dtype=jnp.float32)
    tokenizer = None
    if args.prompt is not None:
        from ..data import get_tokenizer

        tokenizer = get_tokenizer(args.model_name)
        prompt_ids = tokenizer(args.prompt)["input_ids"]
        if prompt_ids and isinstance(prompt_ids[0], list):
            prompt_ids = prompt_ids[0]  # batched tokenizers (ByteTokenizer)
    else:
        prompt_ids = [int(t) for t in args.prompt_ids.split(",")]

    # over-long generations are refused by check_length inside the sampler
    # (the library guard) — no CLI copy to drift out of sync

    if args.pretrained:
        from .hf_convert import load_pretrained

        plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
        shapes = jax.eval_shape(
            lambda: bundle.init(bundle.config, jax.random.key(0)))
        shardings = plan.param_shardings(
            bundle.param_logical_axes(bundle.config), shapes)
        params = load_pretrained(bundle, shardings, args.pretrained)
    else:
        params = bundle.init(bundle.config, jax.random.key(args.seed))

    sample = make_sampler(bundle, temperature=args.temperature,
                          kv_cache=args.kv_cache)
    out = sample(params, prompt_ids, args.steps,
                 rng=jax.random.key(args.seed))
    if tokenizer is not None:
        print(tokenizer.decode(out))
    else:
        print(",".join(str(t) for t in out))


if __name__ == "__main__":
    main()
