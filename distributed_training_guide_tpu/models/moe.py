"""Mixture-of-Experts Llama variant with expert parallelism.

The reference has no MoE/expert parallelism at all (SURVEY.md §2 scorecard:
"EP: absent entirely"); this adds the capability TPU-first:

- every layer's FFN is replaced by a router + E experts whose weights are
  *stacked* on an expert dim ``[L, E, ...]`` carrying the logical axis
  ``experts``; the "ep" plan maps it to the ``ep`` mesh axis. GSPMD
  partitions the index-based dispatch scatter and the expert einsums over
  ep WITHOUT replicating either the [E, C, D] buffers or the expert
  weights: each device computes only its E/ep experts and token movement
  lowers to collective-permutes — verified at the compiled-HLO level by
  ``tests/test_moe.py::test_ep_dispatch_stays_local`` (no hand-written
  collectives needed);
- routing is top-k (default 2) with a static per-expert capacity
  ``C = ceil(capacity_factor * k * tokens / E)`` — static shapes (XLA
  requirement), overflow tokens drop to the residual path (standard
  Switch/GShard behavior);
- a load-balance auxiliary loss (Switch-style: E * sum_e fraction_e * prob_e)
  is returned alongside the logits; the Trainer adds
  ``router_aux_coef * aux`` to the training loss.

Attention/norms/embedding reuse the dense Llama pieces so the families cannot
drift.

Dispatch is index-based (stable sort by expert + positional rank within the
group): O(k*T) index arrays and [E, C, D] expert buffers instead of the
GShard one-hot [T, E, C] dispatch/combine tensors, whose memory grows
O(T^2 * k / E * E) = O(T^2 * k) at fixed capacity factor. The router also
reports the dropped-(token, choice) fraction, surfaced as the
``moe_dropped_frac`` train metric.

``moe_dispatch="ragged"`` swaps the capacity buffers for MegaBlocks-style
DROPLESS dispatch (Gale et al., arXiv:2211.15841): sort the kT pairs by
expert id and run the three expert matmuls as grouped GEMMs over the ragged
[kT, D] sorted buffer (``ops/grouped_matmul.py``) — no padding compute, no
capacity/quality trade, ``moe_dropped_frac`` identically 0. On sharded
meshes the Trainer threads ``make_ragged_ep_dispatch`` (a manual shard_map
over the data axes: ep > 1 exchanges sorted groups by all-gather +
reduce-scatter; plain dp/fsdp bodies are collective-free). The decode
``no_drop`` path always runs ragged — O(t*k*d) transients instead of the
old worst-case O(E*k*t*d) capacity buffers.
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from . import llama
from .llama import _rmsnorm, attention_sublayer
from ..ops.collectives import ppermute as _ppermute
from ..ops.collectives import psum as _psum
from ..ops.collectives import psum_scatter as _psum_scatter
from ..ops.grouped_matmul import grouped_matmul

MOE_DISPATCH_MODES = ("dense", "ragged")


@dataclasses.dataclass(frozen=True)
class MoELlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632      # per-expert FFN width
    num_layers: int = 22
    num_heads: int = 32
    num_kv_heads: int = 4
    num_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01
    # renormalize the chosen top-k weights (Mixtral: always; Qwen3-MoE:
    # the norm_topk_prob config flag)
    norm_topk_prob: bool = True
    # per-head RMSNorm on q/k pre-rope (Qwen3-MoE); shares
    # llama.attention_sublayer's contract. Only the per-head (True) form
    # exists in MoE checkpoints — no flat variant here
    qk_norm: bool = False
    # QKV projection biases (Qwen2-MoE attention is Qwen2-style)
    attn_bias: bool = False
    # Qwen2-MoE shared expert: a dense gated MLP of this width runs on
    # EVERY token, its output scaled by sigmoid(x @ shared_gate) and added
    # to the routed combine. None = no shared expert (Mixtral/Qwen3-MoE)
    shared_expert_intermediate: Optional[int] = None
    # expert-dispatch backend: "dense" = static [E, C, D] capacity buffers
    # (Switch/GShard; overflow drops to the residual), "ragged" = dropless
    # sort-based dispatch + grouped GEMMs over the [kT, D] sorted buffer
    # (MegaBlocks, arXiv:2211.15841) — no padding compute, no capacity knob,
    # dropped_frac identically 0. The decode/no_drop path always runs ragged
    moe_dispatch: str = "dense"
    head_dim: Optional[int] = None
    max_position_embeddings: int = 4096
    rope_theta: float = 10000.0
    rope_scaling: Optional[tuple] = None  # frozen HF rope_scaling (ops/rope.py)
    sliding_window: Optional[int] = None  # SWA band (Mixtral 8x7B ships 4096)
    # per-layer window pattern (an L-tuple, 0 = full attention that layer) —
    # same contract as the dense family's Gemma-2 schedule; rides the layer
    # scans as a traced column (llama._layer_window_column)
    layer_windows: Optional[tuple] = None
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def head_size(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    def num_params(self) -> int:
        e, f, v = self.hidden_size, self.intermediate_size, self.vocab_size
        d = self.head_size
        hq, hkv = self.num_heads * d, self.num_kv_heads * d
        attn = e * hq + 2 * e * hkv + hq * e
        if self.qk_norm:
            attn += 2 * d
        if self.attn_bias:
            attn += hq + 2 * hkv
        moe = e * self.num_experts + self.num_experts * 3 * e * f
        if self.shared_expert_intermediate:
            moe += 3 * e * self.shared_expert_intermediate + e
        per_layer = attn + moe + 2 * e
        head = 0 if self.tie_word_embeddings else e * v
        return v * e + self.num_layers * per_layer + e + head

    def num_active_params(self) -> int:
        """Params a token actually flows through (k of E experts) — the right
        N for FLOPs/MFU accounting (total params would overstate ~E/k x)."""
        e, f, v = self.hidden_size, self.intermediate_size, self.vocab_size
        d = self.head_size
        hq, hkv = self.num_heads * d, self.num_kv_heads * d
        attn = e * hq + 2 * e * hkv + hq * e
        if self.qk_norm:
            attn += 2 * d
        if self.attn_bias:
            attn += hq + 2 * hkv
        moe = e * self.num_experts + self.experts_per_token * 3 * e * f
        if self.shared_expert_intermediate:   # always active
            moe += 3 * e * self.shared_expert_intermediate + e
        per_layer = attn + moe + 2 * e
        head = 0 if self.tie_word_embeddings else e * v
        return v * e + self.num_layers * per_layer + e + head


def init(config: MoELlamaConfig, rng: jax.Array) -> dict:
    e, f, v, l = (config.hidden_size, config.intermediate_size,
                  config.vocab_size, config.num_layers)
    ex = config.num_experts
    d = config.head_size
    hq, hkv = config.num_heads * d, config.num_kv_heads * d
    keys = iter(jax.random.split(rng, 16))

    def dense(key, shape):
        return (0.02 * jax.random.normal(key, shape, jnp.float32)).astype(config.param_dtype)

    # key-consumption ORDER is part of the determinism contract (same seed
    # -> same params across versions): embed draws first, as it always has
    embed = dense(next(keys), (v, e))
    attn = {
        "wq": dense(next(keys), (l, e, hq)),
        "wk": dense(next(keys), (l, e, hkv)),
        "wv": dense(next(keys), (l, e, hkv)),
        "wo": dense(next(keys), (l, hq, e)),
    }
    if config.qk_norm:     # Qwen3-MoE per-head q/k RMSNorm scales
        attn.update(q_norm=jnp.ones((l, d), config.param_dtype),
                    k_norm=jnp.ones((l, d), config.param_dtype))
    if config.attn_bias:   # Qwen2-MoE QKV biases (zeros, like HF init)
        attn.update(bq=jnp.zeros((l, hq), config.param_dtype),
                    bk=jnp.zeros((l, hkv), config.param_dtype),
                    bv=jnp.zeros((l, hkv), config.param_dtype))
    moe_leaves = {
        "router": dense(next(keys), (l, e, ex)),
        "gate": dense(next(keys), (l, ex, e, f)),
        "up": dense(next(keys), (l, ex, e, f)),
        "down": dense(next(keys), (l, ex, f, e)),
    }
    if config.shared_expert_intermediate:   # Qwen2-MoE shared expert
        fs = config.shared_expert_intermediate
        moe_leaves.update(
            shared_gate_proj=dense(next(keys), (l, e, fs)),
            shared_up=dense(next(keys), (l, e, fs)),
            shared_down=dense(next(keys), (l, fs, e)),
            shared_gate=dense(next(keys), (l, e)),
        )
    params = {
        "embed": {"embedding": embed},
        "layers": {
            "attn": attn,
            "moe": moe_leaves,
            "input_norm": jnp.ones((l, e), config.param_dtype),
            "post_attn_norm": jnp.ones((l, e), config.param_dtype),
        },
        "final_norm": jnp.ones((e,), config.param_dtype),
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = dense(next(keys), (e, v))
    return params


def param_logical_axes(config: MoELlamaConfig) -> dict:
    attn_axes = {
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv"),
        "wv": ("layers", "embed", "kv"),
        "wo": ("layers", "heads", "embed"),
    }
    if config.qk_norm:
        attn_axes.update(q_norm=("layers", "head_dim_vector"),
                         k_norm=("layers", "head_dim_vector"))
    if config.attn_bias:
        attn_axes.update(bq=("layers", "heads"), bk=("layers", "kv"),
                         bv=("layers", "kv"))
    moe_axes = {
        "router": ("layers", "embed", "experts_vector"),
        "gate": ("layers", "experts", "embed", "mlp"),
        "up": ("layers", "experts", "embed", "mlp"),
        "down": ("layers", "experts", "mlp", "embed"),
    }
    if config.shared_expert_intermediate:
        # the shared expert is a plain dense MLP: megatron mlp-dim shards
        # under tp, no expert dim (replicated over ep); the scalar gate
        # vector is never sharded
        moe_axes.update(shared_gate_proj=("layers", "embed", "mlp"),
                        shared_up=("layers", "embed", "mlp"),
                        shared_down=("layers", "mlp", "embed"),
                        shared_gate=("layers", "embed_vector"))
    axes = {
        "embed": {"embedding": ("vocab", "embed")},
        "layers": {
            "attn": attn_axes,
            "moe": moe_axes,
            "input_norm": ("layers", "embed_vector"),
            "post_attn_norm": ("layers", "embed_vector"),
        },
        "final_norm": ("embed_vector",),
    }
    if not config.tie_word_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def _ragged_expert_compute(x_rows: jnp.ndarray, gate, up, down,
                           group_sizes: jnp.ndarray, cdt) -> jnp.ndarray:
    """The three expert matmuls as grouped GEMMs over a group-sorted row
    buffer (rows beyond ``sum(group_sizes)`` come back zero — the EP local
    slice rides that contract)."""
    h = jax.nn.silu(grouped_matmul(x_rows, gate.astype(cdt), group_sizes))
    h = h * grouped_matmul(x_rows, up.astype(cdt), group_sizes)
    # tagged for REMAT_POLICIES["attn_mlp"] (the [kT, F] inner activation;
    # same role as the dense path's [E, C, F] / llama's mlp_act)
    h = checkpoint_name(h, "mlp_act")
    return grouped_matmul(h, down.astype(cdt), group_sizes)


def _ragged_sort(xt: jnp.ndarray, topk_idx, topk_probs, ex: int, k: int, cdt):
    """Flatten (token, choice) pairs choice-rank-major, sort by expert id.
    Returns (order, group_sizes, x_sorted [kT, D], weight_flat [kT]).

    Pair i is token (i mod t): sorted rows gather straight from xt — row
    movement is gather-only, like the dense path; the one int32 scatter
    lives in ``_ragged_combine``'s permutation inversion."""
    t = xt.shape[0]
    expert_flat = topk_idx.T.reshape(k * t)                      # [kT]
    weight_flat = topk_probs.T.reshape(k * t)
    order = jnp.argsort(expert_flat, stable=True)
    group_sizes = jnp.bincount(expert_flat, length=ex).astype(jnp.int32)
    x_sorted = xt[order % t].astype(cdt)                         # [kT, D]
    return order, group_sizes, x_sorted, weight_flat


def _ragged_combine(out_sorted: jnp.ndarray, order, weight_flat,
                    k: int, t: int, cdt) -> jnp.ndarray:
    """Unsort (int32 inversion scatter + row gather), weight, and combine
    the k contributions of each token (adjacent in the choice-rank-major
    layout — a reshape and a dense sum, no scatter-add). -> [t, D]."""
    m, d = k * t, out_sorted.shape[1]
    inv = (jnp.zeros((m,), jnp.int32)
           .at[order].set(jnp.arange(m, dtype=jnp.int32)))
    y_choice = out_sorted[inv]                                   # pair order
    return jnp.sum((y_choice * weight_flat[:, None].astype(cdt))
                   .reshape(k, t, d), axis=0)


def _ragged_dispatch(config: MoELlamaConfig, xt: jnp.ndarray, topk_idx,
                     topk_probs, moe: dict, cdt) -> jnp.ndarray:
    """Dropless sorted dispatch (single-shard form): sort (token, choice)
    pairs by expert id, run the experts as grouped GEMMs over the sorted
    [kT, D] buffer, unsort, weight, combine. No capacity buffers, no drops;
    transients are O(k*T*D) — at decode (t == 1..few) that is O(t*k*d) vs
    the dense no_drop path's O(E*k*t*d) worst-case buffers."""
    t = xt.shape[0]
    ex, k = config.num_experts, config.experts_per_token
    order, group_sizes, x_sorted, weight_flat = _ragged_sort(
        xt, topk_idx, topk_probs, ex, k, cdt)
    out_sorted = _ragged_expert_compute(x_sorted, moe["gate"], moe["up"],
                                        moe["down"], group_sizes, cdt)
    return _ragged_combine(out_sorted, order, weight_flat, k, t, cdt)


def _moe_ffn(config: MoELlamaConfig, x: jnp.ndarray, moe: dict,
             tp_axis: Optional[str] = None, no_drop: bool = False,
             moe_ep=None):
    """Top-k routed FFN. x: [B, S, D]. Returns (y, aux_loss, dropped_frac).

    Two dispatch backends, selected by ``config.moe_dispatch``:

    - ``"dense"`` (default, the parity reference): index-based gather-only
      dispatch into static [E, C, D] capacity buffers + batched expert
      einsums. O(k*T) index arrays; overflow pairs drop to the residual
      (Switch/GShard). Row data moves by GATHER only (the single scatter is
      the int32 slot-map inversion; the combine is a reshape+sum over the
      choice-rank-major pair layout) — TPU scatters serialize on write
      hazards and dominated the first on-chip MoE measurement (BENCH.md,
      20% MFU). Capacity priority is greedy by choice rank then token order.
    - ``"ragged"``: dropless sorted dispatch + grouped GEMMs over the
      [kT, D] sorted buffer (MegaBlocks, arXiv:2211.15841) — no padding
      compute, no capacity/quality trade, ``dropped_frac`` identically 0.

    ``no_drop`` (the decode path) always runs ragged: it is dropless by
    construction at O(t*k*d) transients, where the old dense no_drop
    allocated worst-case ``k*t`` capacity per expert — O(E*k*t*d), ~2 GiB a
    layer on a 2k-token qwen1.5-moe prompt.

    ``tp_axis``: set inside a shard_map region where tp is a *manual* axis
    (the pipeline schedule). The router is replicated over tp, so every
    member computes identical dispatch indices; gate/up/down arrive as
    megatron mlp-dim shards and the combined output is a partial sum —
    combine is linear in the expert outputs, so one psum of y at the end is
    exact for both backends (it commutes with gathers and the reshape+sum
    combine, and grouped GEMMs contract the mlp dim only in ``down``).

    ``moe_ep``: expert-parallel ragged dispatch callable built by
    ``make_ragged_ep_dispatch`` (threaded in by the Trainer when the plan
    has ep > 1 and the config says ragged); replaces the local sorted
    dispatch with the shard_map'd sorted-group exchange.
    """
    b, s, d = x.shape
    t = b * s
    ex, k = config.num_experts, config.experts_per_token
    dispatch = getattr(config, "moe_dispatch", "dense")
    if dispatch not in MOE_DISPATCH_MODES:
        raise ValueError(f"unknown moe_dispatch {dispatch!r}; choose from "
                         f"{MOE_DISPATCH_MODES}")
    if no_drop:
        dispatch = "ragged"
    cdt = config.dtype

    xt = x.reshape(t, d)
    router_logits = (xt.astype(jnp.float32)
                     @ moe["router"].astype(jnp.float32))       # [T, E]
    probs = jax.nn.softmax(router_logits, axis=-1)

    topk_probs, topk_idx = jax.lax.top_k(probs, k)               # [T, k]
    if getattr(config, "norm_topk_prob", True):
        # renormalize the chosen weights (Mixtral: always; Qwen3-MoE: the
        # norm_topk_prob flag — off, the raw softmax mass is the weight)
        topk_probs = topk_probs / jnp.sum(topk_probs, axis=-1, keepdims=True)

    if dispatch == "ragged":
        if moe_ep is not None:
            y = moe_ep(xt, topk_idx, topk_probs,
                       moe["gate"], moe["up"], moe["down"])
        else:
            y = _ragged_dispatch(config, xt, topk_idx, topk_probs, moe, cdt)
        dropped_frac = jnp.zeros((), jnp.float32)  # dropless by construction
    else:
        capacity = max(int(math.ceil(config.capacity_factor * k * t / ex)), 1)

        # flatten (token, choice) pairs choice-rank-major -> greedy priority
        expert_flat = topk_idx.T.reshape(k * t)                  # [kT]
        weight_flat = topk_probs.T.reshape(k * t)

        # slot within each expert's buffer = rank of this pair among
        # same-expert pairs (stable sort keeps greedy priority in-group)
        order = jnp.argsort(expert_flat, stable=True)
        sorted_e = expert_flat[order]
        group_start = jnp.searchsorted(sorted_e, sorted_e, side="left")
        pos_sorted = (jnp.arange(k * t, dtype=jnp.int32)
                      - group_start.astype(jnp.int32))
        pos_flat = jnp.zeros((k * t,), jnp.int32).at[order].set(pos_sorted)

        keep = pos_flat < capacity
        dropped_frac = 1.0 - jnp.mean(keep.astype(jnp.float32))
        # overflow pairs target a sacrificial slot that is sliced off
        dest = jnp.where(keep, expert_flat * capacity + pos_flat, ex * capacity)

        # Fill the [E, C, D] buffers by GATHER, not by scattering rows: the
        # only scatter is int32 — invert the slot map (which pair fills slot
        # (e, c)?), then gather rows. Slots nobody fills keep the sentinel
        # kT and gather the appended zero row.
        inv = (jnp.full((ex * capacity + 1,), k * t, jnp.int32)
               .at[dest].set(jnp.arange(k * t, dtype=jnp.int32),
                             mode="drop")[:-1])
        # pair i is token (i mod t): gather straight from xt — no k-fold
        # tiled copy — and mask empty slots to reproduce zero-filled buffers
        expert_in = jnp.where((inv < k * t)[:, None],
                              xt[inv % t].astype(cdt), 0).reshape(ex, capacity, d)

        h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in,
                                   moe["gate"].astype(cdt)))
        h = h * jnp.einsum("ecd,edf->ecf", expert_in, moe["up"].astype(cdt))
        # tagged for REMAT_POLICIES["attn_mlp"] (the [E,C,F] inner
        # activation; same role as llama's mlp_act)
        h = checkpoint_name(h, "mlp_act")
        expert_out = jnp.einsum("ecf,efd->ecd", h, moe["down"].astype(cdt))

        out_flat = expert_out.reshape(ex * capacity, d)
        y_choice = out_flat[jnp.clip(dest, 0, ex * capacity - 1)]
        y_choice = jnp.where(keep[:, None], y_choice, 0)
        # un-route without a scatter-add: pair i is token (i mod t), so the
        # k contributions of each token are exactly the k rows of the
        # choice-rank-major layout — a reshape and a dense sum
        y = jnp.sum((y_choice * weight_flat[:, None].astype(cdt))
                    .reshape(k, t, d), axis=0)
    if "shared_gate" in moe:   # Qwen2-MoE shared expert: dense gated MLP on
        # every token, output scaled by a sigmoid scalar gate and ADDED to
        # the routed combine. Under manual tp its mlp-dim-sharded down-proj
        # is a partial sum like the routed one — the single psum below
        # covers both (addition commutes with psum)
        xs = xt.astype(cdt)
        hs = jax.nn.silu(xs @ moe["shared_gate_proj"].astype(cdt))
        hs = hs * (xs @ moe["shared_up"].astype(cdt))
        shared_out = hs @ moe["shared_down"].astype(cdt)
        sgate = jax.nn.sigmoid(
            (xt.astype(jnp.float32) @ moe["shared_gate"].astype(jnp.float32)
             )[:, None])
        y = y + sgate.astype(cdt) * shared_out
    if tp_axis is not None:
        y = _psum(y, tp_axis)

    # Switch load-balance loss over ALL k dispatched choices (normalized by
    # k): E * sum_e (choice fraction)_e * (mean prob)_e — counting only the
    # first choice would never penalize second-choice hot spots
    token_frac = jnp.mean(jax.nn.one_hot(topk_idx, ex, dtype=jnp.float32),
                          axis=(0, 1))
    prob_frac = jnp.mean(probs, axis=0)
    aux = ex * jnp.sum(token_frac * prob_frac)
    return y.reshape(b, s, d), aux, dropped_frac


def _local_groups_compute(x_sorted: jnp.ndarray, sizes: jnp.ndarray, gate,
                          up, down, e0, e_local: int, cdt) -> jnp.ndarray:
    """Grouped-GEMM the ``e_local`` experts starting at (traced) expert
    ``e0`` over their contiguous run of a group-sorted row buffer; rows
    outside those groups come back zero. The run starts at the sum of
    earlier group sizes — a worst-case-static window is sliced from a
    zero-padded copy (the tail past the local groups is garbage the
    grouped-matmul contract zeroes out). Shared by the bulk (all-gather)
    and ring (double-buffered) EP bodies."""
    m, d = x_sorted.shape
    ex = sizes.shape[0]
    local_sizes = jax.lax.dynamic_slice(sizes, (e0,), (e_local,))
    start = jnp.sum(jnp.where(jnp.arange(ex) < e0, sizes, 0))
    x_pad = jnp.concatenate([x_sorted, jnp.zeros_like(x_sorted)], axis=0)
    x_local = jax.lax.dynamic_slice(x_pad, (start, 0), (m, d))
    out_local = _ragged_expert_compute(x_local, gate, up, down,
                                       local_sizes, cdt)
    out_pad = jnp.zeros((2 * m, d), out_local.dtype)
    out_pad = jax.lax.dynamic_update_slice(out_pad, out_local, (start, 0))
    return out_pad[:m]  # zeros outside this shard's groups


def make_ragged_ep_dispatch(mesh, config: MoELlamaConfig, *,
                            data_axes=("dp", "fsdp", "ep"), ep_axis="ep",
                            embed_axis: Optional[str] = None,
                            overlap: bool = False):
    """Sharded dropless dispatch: a shard_map over the data axes that
    exchanges *sorted expert groups* instead of the dense path's [E, C, D]
    capacity buffer.

    Each (dp, fsdp) row all-gathers its token rows + routing over ``ep``,
    sorts (token, choice) pairs by expert id, and runs the grouped GEMMs on
    the slice of the sorted buffer belonging to its E/ep local experts (a
    worst-case-static [kT, D] window whose garbage tail the grouped-matmul
    contract zeroes); per-shard partial outputs reduce-scatter back to the
    local token rows. The gather + reduce-scatter pair carries the same
    O(T*D) bytes as the dense path's two GSPMD all-to-alls — what it removes
    is the E/ep-fold capacity-padding compute and the drop/quality trade.

    Also used WITHOUT an ep axis (plain dp/fsdp data sharding, ep == 1):
    every shard then owns all experts and the body is collective-free —
    local sort + grouped GEMMs over local tokens. Keeping the region manual
    matters twice: GSPMD cannot partition the data-dependent sort/gather the
    way it partitions the dense path's static einsums (on jax<0.5 CPU it
    aborts outright with "PartitionId instruction is not supported"), and
    on TPU the manual body guarantees zero cross-chip traffic for the
    dp-only case instead of whatever the partitioner falls back to.
    Returns None on a single-shard mesh (the plain local path IS the
    program).

    Autodiff works through the map because every collective is an
    all_gather/psum_scatter pair (clean transposes of each other) and the
    router math stays OUTSIDE the map (no replicated differentiable inputs).

    ``embed_axis``: mesh axis sharding the weights' embed dim (ep_fsdp
    plans pass "fsdp"); the body all-gathers that dim before compute and the
    transpose reduce-scatters the weight cotangent — exactly FSDP semantics,
    hand-spelled because the region is manual. (This stays true under
    ``--overlap-schedule``: expert weights are excluded from the layer
    schedule's gathers — feeding one partial-manual region's output into
    another trips the jax 0.4.37 partitioner.)

    ``overlap=True`` (the latency-hiding schedule, ops/overlap.py) swaps the
    bulk all-gather + global sort for a DOUBLE-BUFFERED RING: token blocks
    rotate around ``ep`` one hop per step, each visiting block is sorted and
    run through this member's experts while the ppermute bringing hop j+1's
    block is already in flight, and each partial output ppermutes straight
    back to its owner (the return hop of step j rides behind step j+1's
    compute). Same O(T*D) wire bytes as the bulk form, same math (per-row
    expert results are sort-granularity independent; owners sum the ep
    partials), but every transfer has compute to hide behind — and peak
    transients drop from O(ep*t_loc) sorted rows to O(t_loc) per hop.
    """
    from jax.sharding import PartitionSpec as P

    ex, k = config.num_experts, config.experts_per_token
    ep = mesh.shape.get(ep_axis, 1)
    if ep > 1 and ex % ep:
        raise ValueError(
            f"moe_dispatch='ragged' under expert parallelism needs "
            f"num_experts divisible by the ep axis; got E={ex}, ep={ep} — "
            f"change the mesh or use moe_dispatch='dense' (which falls back "
            f"to replication on non-divisible dims)")
    e_local = ex // ep
    axes = tuple(a for a in data_axes if mesh.shape.get(a, 1) > 1)
    if embed_axis is not None and mesh.shape.get(embed_axis, 1) <= 1:
        embed_axis = None
    if not axes and embed_axis is None:
        return None  # single-shard mesh: the plain local path is the program
    manual = set(axes) | ({embed_axis} if embed_axis else set())
    cdt = config.dtype
    row_spec = P(axes if axes else None, None)
    gu_spec = P(ep_axis if ep > 1 else None, embed_axis, None)
    down_spec = P(ep_axis if ep > 1 else None, None, embed_axis)

    def _member_partial(xt_blk, idx_blk, probs_blk, gate, up, down):
        """This member's experts applied to one block of rows -> the block's
        partial combine [t_blk, D] (zeros for rows routed elsewhere)."""
        e0 = jax.lax.axis_index(ep_axis) * e_local
        order, sizes, x_sorted, weight_flat = _ragged_sort(
            xt_blk, idx_blk, probs_blk, ex, k, cdt)
        out_sorted = _local_groups_compute(x_sorted, sizes, gate, up, down,
                                           e0, e_local, cdt)
        return _ragged_combine(out_sorted, order, weight_flat, k,
                               xt_blk.shape[0], cdt)

    def body(xt, topk_idx, topk_probs, gate, up, down):
        if embed_axis is not None:
            gate = jax.lax.all_gather(gate, embed_axis, axis=1, tiled=True)
            up = jax.lax.all_gather(up, embed_axis, axis=1, tiled=True)
            down = jax.lax.all_gather(down, embed_axis, axis=2, tiled=True)
        if ep == 1:
            # no expert axis: every shard owns all experts and just runs
            # its own tokens — purely local, no collectives at all
            order, sizes, x_sorted, weight_flat = _ragged_sort(
                xt, topk_idx, topk_probs, ex, k, cdt)
            out_sorted = _ragged_expert_compute(x_sorted, gate, up, down,
                                                sizes, cdt)
            return _ragged_combine(out_sorted, order, weight_flat, k,
                                   xt.shape[0], cdt)
        if overlap:
            # double-buffered ring: blocks of rows rotate +1 per hop; while
            # hop j's block computes, the ppermute bringing hop j+1's block
            # is in flight, and hop j's partial output permutes straight
            # back to its owner behind hop j+1's compute
            fwd_perm = [(i, (i + 1) % ep) for i in range(ep)]
            blk = (xt, topk_idx, topk_probs)
            acc = jnp.zeros_like(xt, dtype=cdt)
            for j in range(ep):
                nxt = (jax.tree.map(
                    lambda a: _ppermute(a, ep_axis, perm=fwd_perm), blk)
                    if j + 1 < ep else None)
                y_blk = _member_partial(*blk, gate, up, down)
                if j:  # return the visiting block's partial to its owner
                    back = [(i, (i - j) % ep) for i in range(ep)]
                    y_blk = _ppermute(y_blk, ep_axis, perm=back)
                acc = acc + y_blk
                blk = nxt
            return acc
        # bulk form: pull the whole (dp, fsdp) row's tokens + routing in,
        # sort once globally, compute the local experts' contiguous window,
        # reduce-scatter the partials back to each token's home shard
        xt = jax.lax.all_gather(xt, ep_axis, axis=0, tiled=True)
        topk_idx = jax.lax.all_gather(topk_idx, ep_axis, axis=0, tiled=True)
        topk_probs = jax.lax.all_gather(topk_probs, ep_axis, axis=0,
                                        tiled=True)
        y = _member_partial(xt, topk_idx, topk_probs, gate, up, down)
        return _psum_scatter(y, ep_axis)

    sm = jax.shard_map(body, mesh=mesh, axis_names=manual, check_vma=False,
                       in_specs=(row_spec, row_spec, row_spec,
                                 gu_spec, gu_spec, down_spec),
                       out_specs=row_spec)

    def dispatch(xt, topk_idx, topk_probs, gate, up, down):
        return sm(xt, topk_idx, topk_probs, gate, up, down)

    return dispatch


def _block(config: MoELlamaConfig, carry, layer: dict, positions, attn_impl,
           standard_layout=True, tp_axis=None, moe_ep=None,
           window_override=None):
    x, aux_acc, dropped_acc = carry
    attn = attention_sublayer(config, x, layer["attn"], layer["input_norm"],
                              positions, attn_impl, standard_layout, tp_axis,
                              window_override=window_override)
    x = x + attn

    h = _rmsnorm(x, layer["post_attn_norm"], config.rms_norm_eps)
    y, aux, dropped = _moe_ffn(config, h, layer["moe"], tp_axis,
                               moe_ep=moe_ep)
    return (x + y, aux_acc + aux, dropped_acc + dropped)


def apply_with_aux(
    config: MoELlamaConfig,
    params: dict,
    input_ids: jnp.ndarray,
    positions: Optional[jnp.ndarray] = None,
    *,
    remat: bool = False,
    remat_policy: Optional[Any] = None,
    attn_impl: str = "auto",
    activation_sharding: Optional[Any] = None,
    return_metrics: bool = False,
    return_hidden: bool = False,
    moe_ep=None,
    layer_schedule=None,
):
    """Forward -> (logits [B,S,V] fp32, mean router aux loss[, metrics]).

    ``return_metrics`` adds a dict of routing observability scalars
    (currently ``dropped_frac``: mean fraction of (token, choice) pairs that
    overflowed expert capacity — identically 0 under ragged dispatch)
    without changing the stable 2-tuple API. ``return_hidden`` swaps the
    logits for the final-normed hidden states [B, S, E] (chunked-loss path —
    pair with ``output_weights``). ``moe_ep``: expert-parallel ragged
    dispatch callable (``make_ragged_ep_dispatch``), threaded to every
    layer's routed FFN. ``layer_schedule`` (ops/overlap.py): replaces the
    layer scan with the explicit latency-hiding schedule, which owns remat
    per cell (``remat``/``remat_policy`` are then unused here)."""
    standard_layout = positions is None
    if positions is None:
        positions = jnp.arange(input_ids.shape[1])[None, :]
    positions = jnp.broadcast_to(positions, input_ids.shape)

    x = llama.embed_tokens(config, params, input_ids, positions)

    block = partial(_block, config, positions=positions, attn_impl=attn_impl,
                    standard_layout=standard_layout, moe_ep=moe_ep)

    wins = llama._layer_window_column(config)
    zero = jnp.zeros((), jnp.float32)

    if layer_schedule is not None:
        def sched_block(carry, layer_params, window_override=None):
            new_carry = block(carry, layer_params,
                              window_override=window_override)
            if activation_sharding is not None:
                new_carry = (jax.lax.with_sharding_constraint(
                    new_carry[0], activation_sharding), *new_carry[1:])
            return new_carry

        x, aux, dropped = layer_schedule(sched_block, (x, zero, zero),
                                         params["layers"], wins)
    else:
        def scan_body(carry, xs):
            if wins is not None:   # per-layer window column rides the scan
                layer_params, w = xs
                new_carry = block(carry, layer_params, window_override=w)
            else:
                new_carry = block(carry, xs)
            if activation_sharding is not None:
                new_carry = (jax.lax.with_sharding_constraint(
                    new_carry[0], activation_sharding), *new_carry[1:])
            return new_carry, None

        if remat:
            policy = remat_policy or jax.checkpoint_policies.nothing_saveable
            scan_body = jax.checkpoint(scan_body, policy=policy,
                                       prevent_cse=False)

        scan_xs = (params["layers"] if wins is None
                   else (params["layers"], wins))
        (x, aux, dropped), _ = jax.lax.scan(scan_body, (x, zero, zero),
                                            scan_xs)

    out = (llama.final_hidden(config, params, x) if return_hidden
           else llama.lm_head_logits(config, params, x))
    aux = aux / config.num_layers
    if return_metrics:
        return out, aux, {"moe_dropped_frac": dropped / config.num_layers}
    return out, aux


def apply(config, params, input_ids, positions=None, **kw):
    logits, _ = apply_with_aux(config, params, input_ids, positions, **kw)
    return logits


# embedding/head sub-forwards are shared with the dense family (identical
# params layout) — re-exported for the pipeline schedule's stage-0/last-stage
# entry points and the chunked loss
embed_tokens = llama.embed_tokens
output_weights = llama.output_weights
final_hidden = llama.final_hidden
lm_head_logits = llama.lm_head_logits
tp_embed = llama.tp_embed


# ---------------------------------------------------------------------------
# KV-cached decode (models/sample.py --kv-cache): same functional-cache
# contract as the dense families (llama.init_cache shape math is duck-typed
# on num_layers/num_kv_heads/head_size/dtype), with the routed FFN in the
# block body. Expert dispatch runs with ``no_drop=True`` — a single decode
# token's k choices can exceed a capacity_factor-derived capacity of 1
# (both choices on one expert), and a qualitative sampling path must be
# routing-exact vs the full recompute, not throughput-shaped. no_drop
# resolves to the RAGGED backend: dropless by construction at O(t*k*d)
# transients (the old dense no_drop allocated worst-case C = k*t per-expert
# buffers — O(E*k*t*d), ~2 GiB/layer on a 2k-token qwen1.5-moe prompt).
# ---------------------------------------------------------------------------

init_cache = llama.init_cache


def prefill(config: MoELlamaConfig, params: dict, input_ids: jnp.ndarray,
            cache: dict, last_pos=None):
    """Causal forward over the prompt, writing each layer's rope'd k/v into
    the cache. Returns (logits [B, V] at ``last_pos``, default final
    position, and the cache)."""
    b, p = input_ids.shape
    positions = jnp.broadcast_to(jnp.arange(p)[None, :], (b, p))
    x = embed_tokens(config, params, input_ids, positions)

    wins = llama._layer_window_column(config)

    def body(x, inputs):
        layer, ck, cv, w = inputs
        attn, (k, v) = attention_sublayer(
            config, x, layer["attn"], layer["input_norm"], positions,
            "xla", return_kv=True, window_override=w)
        x = x + attn
        h = _rmsnorm(x, layer["post_attn_norm"], config.rms_norm_eps)
        y, _, _ = _moe_ffn(config, h, layer["moe"], no_drop=True)
        x = x + y
        nk = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, 0, 0, 0))
        nv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, 0, 0, 0))
        return x, (nk, nv)

    x, (ks, vs) = llama._scan_kv_layers(body, x, params, cache, wins)
    # slice BEFORE the head (llama.prefill rationale: don't project all P
    # positions to [B, P, V] fp32 to keep one row)
    x_last = (x[:, -1:] if last_pos is None
              else jax.lax.dynamic_slice_in_dim(x, last_pos, 1, axis=1))
    return (lm_head_logits(config, params, x_last)[:, 0],
            {"k": ks, "v": vs})


def decode_step(config: MoELlamaConfig, params: dict, token_ids: jnp.ndarray,
                pos, cache: dict):
    """One cached decode step (``token_ids`` [B, 1] at traced position
    ``pos``): attention over the full cache, routed FFN on the one token.
    Returns (logits [B, V], updated cache)."""
    b = token_ids.shape[0]
    positions = jnp.broadcast_to(jnp.asarray(pos)[None, None], (b, 1))
    x = embed_tokens(config, params, token_ids, positions)

    wins = llama._layer_window_column(config)

    def body(x, inputs):
        layer, ck, cv, w = inputs
        attn, (nk, nv) = attention_sublayer(
            config, x, layer["attn"], layer["input_norm"], positions,
            "xla", kv_cache=(ck, cv, pos), return_kv=True, window_override=w)
        x = x + attn
        h = _rmsnorm(x, layer["post_attn_norm"], config.rms_norm_eps)
        y, _, _ = _moe_ffn(config, h, layer["moe"], no_drop=True)
        x = x + y
        return x, (nk, nv)

    x, (ks, vs) = llama._scan_kv_layers(body, x, params, cache, wins)
    return lm_head_logits(config, params, x)[:, -1], {"k": ks, "v": vs}


def paged_decode_step(config: MoELlamaConfig, params: dict,
                      token_ids: jnp.ndarray, positions: jnp.ndarray,
                      cache: dict, attend, last_index=None,
                      all_logits=False):
    """Paged multi-request decode/chunk step (llama.paged_decode_step
    contract): the routed FFN runs drop-free (ragged backend) on the
    [S, T] tokens — per-token routing is independent of the co-resident
    slots, so continuous batching cannot perturb a request's expert
    choices (and a speculative verification chunk cannot perturb the
    tokens it verifies). ``all_logits=True`` keeps every position's
    logits (speculative verification)."""
    pos2d = llama.paged_positions(token_ids, positions)
    x = embed_tokens(config, params, token_ids, pos2d)

    wins = llama._layer_window_column(config)

    def body(x, inputs):
        layer, kp, vp, w = inputs

        def override(q, k, v, *, window, scale, softcap):
            return attend(q, k, v, kp, vp, window=window, scale=scale,
                          softcap=softcap)

        attn, (nkp, nvp) = attention_sublayer(
            config, x, layer["attn"], layer["input_norm"], pos2d,
            "xla", return_kv=True, window_override=w,
            attend_override=override)
        x = x + attn
        h = _rmsnorm(x, layer["post_attn_norm"], config.rms_norm_eps)
        y, _, _ = _moe_ffn(config, h, layer["moe"], no_drop=True)
        x = x + y
        return x, (nkp, nvp)

    x, (ks, vs) = llama._scan_kv_layers(body, x, params, cache, wins)
    return (llama.paged_logits_at(lm_head_logits, config, params, x,
                                  last_index, all_logits),
            {"k": ks, "v": vs})


PRESETS = {
    "moe-debug": MoELlamaConfig(vocab_size=512, hidden_size=64, intermediate_size=128,
                                num_layers=2, num_heads=4, num_kv_heads=2,
                                num_experts=4, max_position_embeddings=256),
    # single-chip benchable MoE: ~0.9B total / ~0.3B active (top-2 of 8),
    # llama-650m-family dims scaled so fp32 state + remat fits 16 GB HBM
    "moe-1b-8e": MoELlamaConfig(vocab_size=32000, hidden_size=1024,
                                intermediate_size=2816, num_layers=12,
                                num_heads=16, num_kv_heads=4, num_experts=8,
                                experts_per_token=2,
                                max_position_embeddings=4096),
    # Mixtral-8x7B-shaped (public model card dims)
    "mixtral-8x7b": MoELlamaConfig(vocab_size=32000, hidden_size=4096,
                                   intermediate_size=14336, num_layers=32,
                                   num_heads=32, num_kv_heads=8, num_experts=8,
                                   experts_per_token=2, rope_theta=1e6,
                                   max_position_embeddings=32768),
    # Qwen1.5-MoE-A2.7B-shaped (public card): Qwen2 attention (QKV biases)
    # + 60 experts top-4 at width 1408 + the 5632-wide shared expert
    "qwen1.5-moe-a2.7b": MoELlamaConfig(vocab_size=151936, hidden_size=2048,
                                        intermediate_size=1408, num_layers=24,
                                        num_heads=16, num_kv_heads=16,
                                        num_experts=60, experts_per_token=4,
                                        attn_bias=True, norm_topk_prob=False,
                                        shared_expert_intermediate=5632,
                                        rope_theta=1e6, rms_norm_eps=1e-6,
                                        max_position_embeddings=8192),
    # Qwen3-MoE 30B-A3B-shaped (public card): Qwen3 attention (qk_norm,
    # head_dim 128) + 128 experts top-8 at per-expert width 768
    "qwen3-30b-a3b": MoELlamaConfig(vocab_size=151936, hidden_size=2048,
                                    intermediate_size=768, num_layers=48,
                                    num_heads=32, num_kv_heads=4, head_dim=128,
                                    num_experts=128, experts_per_token=8,
                                    qk_norm=True, norm_topk_prob=True,
                                    rope_theta=1e6, rms_norm_eps=1e-6,
                                    max_position_embeddings=40960),
}
