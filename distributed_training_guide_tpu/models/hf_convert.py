"""Pretrained-weight logistics: HF safetensors -> stream-convert -> sharded load.

The reference's 405B recipe needs a 764 GB download, a rank-0 full CPU state
dict, and an NCCL broadcast to all ranks (``05-training-llama-405b/
train_llm.py:74-146``, ``download.py``; init cost 50 min on a shared drive,
``05/README.md:55``). The TPU-native pipeline removes both the full-RAM
materialization and the broadcast:

1. ``convert_hf_checkpoint`` streams tensor-by-tensor out of the safetensors
   shards into one ``.npy`` memmap per parameter leaf (stacked [L, ...] layer
   arrays are filled slice-by-slice), so peak host RAM is one tensor, not one
   model. Run once, anywhere.
2. ``load_pretrained`` memmaps each leaf and materializes it directly into
   the training shardings via ``jax.make_array_from_callback`` — every host
   reads only the bytes its devices own. No rank-0, no broadcast, no
   all-buffer special case (the reference must hand-broadcast non-persistent
   buffers, ``05:131-139``; we have no buffers outside the pytree).

Name mapping covers the Llama, GPT-2, MoE, and GPT-NeoX families (HF
``LlamaForCausalLM`` / ``GPT2LMHeadModel`` / ``MixtralForCausalLM`` /
``GPTNeoXForCausalLM`` conventions; torch Linear stores [out, in] so most
leaves transpose, GPT-2's Conv1D stores [in, out] so they don't; Mixtral's
per-expert Linears stack onto the [L, E, ...] expert dim; NeoX's fused QKV
de-interleaves from per-head [h, 3, d] to the tp-shardable [E, 3, h*d]). Mistral, Qwen2, and Gemma
checkpoints ride the Llama map unchanged — Mistral shares the tensor
names exactly, Qwen2 adds the QKV bias rows, Gemma's differences (GeGLU,
(1+w) norms, sqrt(E)-scaled embeddings, MQA, explicit head_dim, tied
head) are all config knobs, not tensor-layout changes (narrowing the
reference's ``AutoModelForCausalLM`` any-architecture surface,
``01-single-gpu/train_llm.py:57``, one real family at a time).
"""
from __future__ import annotations

import json
import logging
import re
from pathlib import Path
from typing import Callable, Optional

import numpy as np

LOGGER = logging.getLogger(__name__)

LEAF_SEP = "."


# ---------------------------------------------------------------------------
# family-specific name maps: HF tensor name -> (leaf_path, layer_idx|None, transpose)
# ---------------------------------------------------------------------------

def _map_llama(name: str):
    name = name.removeprefix("model.")
    m = re.match(r"layers\.(\d+)\.(.+)", name)
    if m:
        idx, rest = int(m.group(1)), m.group(2)
        table = {
            "self_attn.q_proj.weight": ("layers.attn.wq", True),
            "self_attn.k_proj.weight": ("layers.attn.wk", True),
            "self_attn.v_proj.weight": ("layers.attn.wv", True),
            "self_attn.o_proj.weight": ("layers.attn.wo", True),
            "mlp.gate_proj.weight": ("layers.mlp.gate", True),
            "mlp.up_proj.weight": ("layers.mlp.up", True),
            "mlp.down_proj.weight": ("layers.mlp.down", True),
            "input_layernorm.weight": ("layers.input_norm", False),
            "post_attention_layernorm.weight": ("layers.post_attn_norm", False),
            # Qwen2-style QKV biases (absent in Llama/Mistral checkpoints)
            "self_attn.q_proj.bias": ("layers.attn.bq", False),
            "self_attn.k_proj.bias": ("layers.attn.bk", False),
            "self_attn.v_proj.bias": ("layers.attn.bv", False),
            # Qwen3-style per-head q/k RMSNorm scales ([head_dim] vectors)
            "self_attn.q_norm.weight": ("layers.attn.q_norm", False),
            "self_attn.k_norm.weight": ("layers.attn.k_norm", False),
        }
        if rest in table:
            leaf, t = table[rest]
            return leaf, idx, t
        return None
    table = {
        "embed_tokens.weight": ("embed.embedding", False),
        "norm.weight": ("final_norm", False),
        "lm_head.weight": ("lm_head", True),
    }
    if name in table:
        leaf, t = table[name]
        return leaf, None, t
    return None


def _map_gpt2(name: str):
    name = name.removeprefix("transformer.")
    m = re.match(r"h\.(\d+)\.(.+)", name)
    if m:
        idx, rest = int(m.group(1)), m.group(2)
        table = {  # Conv1D stores [in, out] -> no transpose
            "ln_1.weight": ("layers.ln1.scale", False),
            "ln_1.bias": ("layers.ln1.bias", False),
            "attn.c_attn.weight": ("layers.attn.wqkv", False),
            "attn.c_attn.bias": ("layers.attn.bqkv", False),
            "attn.c_proj.weight": ("layers.attn.wo", False),
            "attn.c_proj.bias": ("layers.attn.bo", False),
            "ln_2.weight": ("layers.ln2.scale", False),
            "ln_2.bias": ("layers.ln2.bias", False),
            "mlp.c_fc.weight": ("layers.mlp.wi", False),
            "mlp.c_fc.bias": ("layers.mlp.bi", False),
            "mlp.c_proj.weight": ("layers.mlp.wo", False),
            "mlp.c_proj.bias": ("layers.mlp.bo", False),
        }
        if rest in table:
            leaf, t = table[rest]
            return leaf, idx, t
        return None
    table = {
        "wte.weight": ("wte", False),
        "wpe.weight": ("wpe", False),
        "ln_f.weight": ("lnf.scale", False),
        "ln_f.bias": ("lnf.bias", False),
    }
    if name in table:
        leaf, t = table[name]
        return leaf, None, t
    return None


def _map_mixtral(name: str):
    """HF ``MixtralForCausalLM`` -> the MoE family layout (models/moe.py).
    Only the MoE-specific tensors are handled here — per-expert Linears
    stack onto the [L, E, ...] expert dim via a (layer, expert) index pair
    (w1=gate, w3=up, w2=down in HF's SwiGLU naming), plus the router
    Linear. Everything else (attention, norms, embed/head) shares Llama's
    names and layout, so it delegates to ``_map_llama`` — one copy of the
    shared table."""
    m = re.match(r"model\.layers\.(\d+)\.block_sparse_moe\.(.+)", name)
    if m:
        idx, rest = int(m.group(1)), m.group(2)
        e = re.match(r"experts\.(\d+)\.(w[123])\.weight", rest)
        if e:
            leaf = {"w1": "layers.moe.gate", "w2": "layers.moe.down",
                    "w3": "layers.moe.up"}[e.group(2)]
            return leaf, (idx, int(e.group(1))), True
        if rest == "gate.weight":
            return "layers.moe.router", idx, True
        return None
    # Qwen2/3-MoE spell the same block `mlp.` with llama-style expert names
    # (gate_proj/up_proj/down_proj) and `mlp.gate` as the router; Qwen2-MoE
    # adds the shared expert + its scalar gate
    m = re.match(r"model\.layers\.(\d+)\.mlp\.(.+)", name)
    if m:
        idx, rest = int(m.group(1)), m.group(2)
        e = re.match(r"experts\.(\d+)\.(gate_proj|up_proj|down_proj)\.weight",
                     rest)
        if e:
            leaf = {"gate_proj": "layers.moe.gate",
                    "up_proj": "layers.moe.up",
                    "down_proj": "layers.moe.down"}[e.group(2)]
            return leaf, (idx, int(e.group(1))), True
        if rest == "gate.weight":
            return "layers.moe.router", idx, True
        shared = {"shared_expert.gate_proj.weight": "layers.moe.shared_gate_proj",
                  "shared_expert.up_proj.weight": "layers.moe.shared_up",
                  "shared_expert.down_proj.weight": "layers.moe.shared_down"}
        if rest in shared:
            return shared[rest], idx, True
        if rest == "shared_expert_gate.weight":   # [1, E] Linear -> [E]
            return "layers.moe.shared_gate", idx, lambda w: w[0]
        return None
    return _map_llama(name)


def _make_map_neox(config):
    """HF ``GPTNeoXForCausalLM`` -> the NeoX family layout (models/neox.py).

    The fused ``query_key_value`` Linear interleaves PER HEAD on its out
    dim — ``[heads, 3, head_dim]`` flattened — while the native layout is
    ``[E, 3, heads*head_dim]`` (trailing head dim shards over tp, see
    models/gpt2.py). The mapper therefore returns a *callable* transform
    (not just a transpose flag) that de-interleaves; it needs the head
    shape, hence the config-taking factory."""
    h, d = config.num_heads, config.head_size

    def deinterleave_qkv_w(w):   # [3e, e] Linear [out, in], out = (h, 3, d)
        e = w.shape[1]
        return w.reshape(h, 3, d, e).transpose(3, 1, 0, 2).reshape(e, 3, h * d)

    def deinterleave_qkv_b(b):   # [3e] = (h, 3, d)
        return b.reshape(h, 3, d).transpose(1, 0, 2).reshape(3, h * d)

    def mapper(name: str):
        if name == "embed_out.weight":   # untied head, outside gpt_neox.*
            return "embed_out", None, True
        name = name.removeprefix("gpt_neox.")
        m = re.match(r"layers\.(\d+)\.(.+)", name)
        if m:
            idx, rest = int(m.group(1)), m.group(2)
            table = {
                "input_layernorm.weight": ("layers.ln1.scale", False),
                "input_layernorm.bias": ("layers.ln1.bias", False),
                "post_attention_layernorm.weight": ("layers.ln2.scale", False),
                "post_attention_layernorm.bias": ("layers.ln2.bias", False),
                "attention.query_key_value.weight":
                    ("layers.attn.wqkv", deinterleave_qkv_w),
                "attention.query_key_value.bias":
                    ("layers.attn.bqkv", deinterleave_qkv_b),
                "attention.dense.weight": ("layers.attn.wo", True),
                "attention.dense.bias": ("layers.attn.bo", False),
                "mlp.dense_h_to_4h.weight": ("layers.mlp.wi", True),
                "mlp.dense_h_to_4h.bias": ("layers.mlp.bi", False),
                "mlp.dense_4h_to_h.weight": ("layers.mlp.wo", True),
                "mlp.dense_4h_to_h.bias": ("layers.mlp.bo", False),
            }
            if rest in table:
                leaf, t = table[rest]
                return leaf, idx, t
            return None   # attention.bias mask buffers, rotary inv_freq
        table = {
            "embed_in.weight": ("embed_in", False),
            "final_layer_norm.weight": ("lnf.scale", False),
            "final_layer_norm.bias": ("lnf.bias", False),
        }
        if name in table:
            leaf, t = table[name]
            return leaf, None, t
        return None

    return mapper


def _make_map_llama(config):
    """Llama-family mapper, extended with the Phi-3 fused layouts: HF
    ``Phi3ForCausalLM`` stores QKV as one ``qkv_proj`` ([hq+2*hkv, E] rows)
    and the SwiGLU gate/up as one ``gate_up_proj`` ([2F, E]) — a mapper may
    therefore return a LIST of (leaf, layer, transform) entries, one fused
    source tensor filling several native leaves. Plain Llama/Mistral/Qwen2/
    Gemma names fall through to the shared table."""
    d = config.head_size
    hq, hkv = config.num_heads * d, config.num_kv_heads * d
    f = config.intermediate_size

    post_norm = getattr(config, "post_norm", False)
    sandwich = getattr(config, "sandwich_norm", False)

    def mapper(name: str):
        m = re.match(r"model\.layers\.(\d+)\.(.+)", name)
        if m:
            idx, rest = int(m.group(1)), m.group(2)
            if rest == "self_attn.qkv_proj.weight":
                return [("layers.attn.wq", idx, lambda w: w[:hq].T),
                        ("layers.attn.wk", idx, lambda w: w[hq:hq + hkv].T),
                        ("layers.attn.wv", idx, lambda w: w[hq + hkv:].T)]
            if rest == "mlp.gate_up_proj.weight":
                return [("layers.mlp.gate", idx, lambda w: w[:f].T),
                        ("layers.mlp.up", idx, lambda w: w[f:].T)]
            if post_norm or sandwich:
                # OLMo-2/Gemma-2 reuse llama's post_attention_layernorm
                # NAME but apply it to the attention OUTPUT; plus a
                # post_feedforward_layernorm on the MLP output
                if rest == "post_attention_layernorm.weight":
                    return "layers.attn_out_norm", idx, False
                if rest == "post_feedforward_layernorm.weight":
                    return "layers.mlp_out_norm", idx, False
            if sandwich and rest == "pre_feedforward_layernorm.weight":
                # Gemma-2's pre-FFN norm fills llama's post_attn_norm slot
                # (the leaf mlp_sublayer pre-norms with)
                return "layers.post_attn_norm", idx, False
        return _map_llama(name)

    return mapper


# family -> mapper factory(config). gpt2/moe don't need the config; NeoX
# does (head shape for the QKV de-interleave), llama does (split points of
# Phi-3's fused tensors).
_FAMILY_MAPS: dict[str, Callable] = {"llama": _make_map_llama,
                                     "gpt2": lambda cfg: _map_gpt2,
                                     "moe": lambda cfg: _map_mixtral,
                                     "neox": _make_map_neox}


# ---------------------------------------------------------------------------
# conversion (streaming)
# ---------------------------------------------------------------------------

def _flatten_with_paths(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_with_paths(v, f"{prefix}{k}{LEAF_SEP}"))
    else:
        out[prefix.rstrip(LEAF_SEP)] = tree
    return out


def convert_hf_checkpoint(hf_dir: str | Path, out_dir: str | Path,
                          model_name: Optional[str] = None, *, bundle=None,
                          dtype: str = "float32") -> Path:
    """Stream every safetensors shard in ``hf_dir`` into per-leaf ``.npy``
    memmaps under ``out_dir``. Peak RAM = one tensor. Pass either a registry
    ``model_name`` or an explicit ``bundle`` (for config overrides)."""
    from safetensors import safe_open

    from .registry import get_model

    if bundle is None:
        bundle = get_model(model_name)
    model_name = model_name or bundle.name
    mapper = _FAMILY_MAPS[bundle.family](bundle.config)
    shapes = _flatten_with_paths(
        __import__("jax").eval_shape(lambda: bundle.init(bundle.config,
                                                         __import__("jax").random.key(0))))
    hf_dir, out_dir = Path(hf_dir), Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)

    memmaps: dict[str, np.memmap] = {}

    def leaf_mm(leaf: str) -> np.memmap:
        if leaf not in memmaps:
            shape = tuple(shapes[leaf].shape)
            memmaps[leaf] = np.lib.format.open_memmap(
                out_dir / f"{leaf}.npy", mode="w+", dtype=np.dtype(dtype), shape=shape)
        return memmaps[leaf]

    seen = set()
    files = sorted(hf_dir.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files in {hf_dir}")
    for f in files:
        with safe_open(str(f), framework="numpy") as sf:
            for name in sf.keys():
                mapped = mapper(name)
                if mapped is None:
                    LOGGER.info(f"skipping unmapped tensor {name}")
                    continue
                # a fused source tensor (Phi-3 qkv_proj/gate_up_proj) maps
                # to SEVERAL leaves: normalize to a list of triples
                entries = mapped if isinstance(mapped, list) else [mapped]
                source = sf.get_tensor(name)
                if source.dtype == np.dtype("uint16"):  # bf16 via numpy view
                    source = _bf16_to_f32(source)
                for leaf, layer, transpose in entries:
                    if leaf not in shapes:
                        continue  # e.g. lm_head when tied
                    tensor = source
                    if callable(transpose):  # family layout transform
                        tensor = transpose(tensor)
                    elif transpose:
                        tensor = tensor.T
                    _write_leaf(name, tensor, leaf, layer, leaf_mm, seen)
                del source
    for mm in memmaps.values():
        mm.flush()
    with open(out_dir / "manifest.json", "w") as fp:
        json.dump({"model_name": model_name, "dtype": dtype,
                   "leaves": sorted(memmaps)}, fp, indent=2)
    LOGGER.info(f"converted {len(seen)} tensors -> {out_dir}")
    return out_dir


def _write_leaf(name: str, tensor: np.ndarray, leaf: str, layer,
                leaf_mm, seen: set) -> None:
    """Place one (possibly transformed) tensor into its leaf memmap slot."""
    mm = leaf_mm(leaf)
    # layer is None (whole leaf), an int (stacked [L, ...] leaf), or an
    # index tuple (e.g. Mixtral's (layer, expert) into a [L, E, ...] stack)
    if layer is not None and not isinstance(layer, tuple):
        layer = (layer,)
    target = mm.shape if layer is None else mm.shape[len(layer):]
    if tensor.shape != tuple(target):
        # only re-factor TRAILING dims (same data, finer factoring — e.g.
        # gpt2's fused QKV is [E, 3E] in HF but [E, 3, E] here so the head
        # dim shards on its own, models/gpt2.py). Leading-dim mismatches
        # (e.g. a transposed Linear-vs-Conv1D layout) must stay loud: an
        # unconditional reshape would silently scramble them.
        if tensor.ndim > 1 and tensor.shape[:1] != tuple(target[:1]):
            raise ValueError(
                f"{name}: shape {tensor.shape} does not match "
                f"target {tuple(target)} for leaf {leaf!r} "
                f"(transposed source layout?)")
        tensor = tensor.reshape(target)
    if layer is None:
        mm[...] = tensor.astype(mm.dtype)
    else:
        mm[layer] = tensor.astype(mm.dtype)
    seen.add((leaf, layer))


def _bf16_to_f32(arr: np.ndarray) -> np.ndarray:
    out = np.zeros(arr.shape, dtype=np.uint32)
    out[...] = arr.astype(np.uint32) << 16
    return out.view(np.float32)


# ---------------------------------------------------------------------------
# sharded load
# ---------------------------------------------------------------------------

def load_pretrained(bundle, param_shardings, out_dir: str | Path,
                    param_dtype: Optional[str] = None):
    """Materialize a converted checkpoint directly into ``param_shardings``.

    Each host/device reads only its shard's slice of the leaf memmap."""
    import jax

    out_dir = Path(out_dir)
    shapes = _flatten_with_paths(
        jax.eval_shape(lambda: bundle.init(bundle.config, jax.random.key(0))))
    flat_shardings = _flatten_with_paths(param_shardings)

    leaves = {}
    for leaf, sd in shapes.items():
        path = out_dir / f"{leaf}.npy"
        if not path.exists():
            raise FileNotFoundError(f"missing converted leaf {path}")
        mm = np.load(path, mmap_mode="r")
        if tuple(mm.shape) != tuple(sd.shape):
            raise ValueError(f"{leaf}: converted shape {mm.shape} != model {sd.shape}")
        dtype = np.dtype(param_dtype) if param_dtype else sd.dtype
        leaves[leaf] = jax.make_array_from_callback(
            tuple(sd.shape), flat_shardings[leaf],
            lambda idx, mm=mm, dtype=dtype: np.asarray(mm[idx], dtype=dtype))

    def unflatten(flat):
        tree: dict = {}
        for path, v in flat.items():
            parts = path.split(LEAF_SEP)
            node = tree
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = v
        return tree

    return unflatten(leaves)
