"""Publish a native checkpoint back to HF format: the inverse of
``hf_convert``.

The reference only CONSUMES HF checkpoints (``05-training-llama-405b/
train_llm.py:74-146``); anything it trains stays in torch-DCP format.
Models trained here go back to the ecosystem: ``export_hf_checkpoint``
writes a ``model.safetensors`` + ``config.json`` that
``transformers.AutoModelForCausalLM.from_pretrained`` loads directly —
round-trip logits parity is pinned per family in
``tests/test_hf_export.py``.

Layout inversions mirror ``hf_convert``'s family maps exactly:

- llama family (covers Mistral/Qwen2/Gemma by config): torch Linear is
  [out, in], so 2-D mats transpose; stacked [L, ...] leaves unstack into
  per-layer tensors; the Qwen2 QKV bias rows export when present; tied
  embeddings simply omit ``lm_head``.
- gpt2: Conv1D stores [in, out] — no transposes; the [L, E, 3, E] fused
  QKV flattens back to Conv1D's [E, 3E].
- neox: the tp-shardable [E, 3, h*d] fused QKV re-interleaves to HF's
  per-head [h, 3, d] out-dim layout (inverse of
  ``hf_convert._make_map_neox``).
- moe: the [L, E, ...] expert stacks unstack into Mixtral's per-expert
  ``w1/w2/w3`` Linears, the router back to ``gate``.
"""
from __future__ import annotations

import json
from pathlib import Path

import numpy as np


def _to_np(leaf, dtype: str) -> np.ndarray:
    """Materialize one (possibly sharded) param leaf on host."""
    import jax

    arr = np.asarray(jax.device_get(leaf))
    return arr.astype(np.dtype(dtype))


# ---------------------------------------------------------------------------
# family emitters: (config, flat native leaves) -> {hf_name: np.ndarray}
#
# Memory honesty: unlike hf_convert's one-tensor-at-a-time streaming IMPORT,
# export materializes the full model on host (~2x model bytes at peak: the
# gathered leaves plus the contiguous per-tensor copies) and writes one
# monolithic safetensors file. That is fine through the ~10B-class on a
# normal host; a sharded-index streaming writer is the scale-up path if a
# pod-scale export is ever needed.
# ---------------------------------------------------------------------------

def _emit_llama(config, leaves: dict) -> dict:
    out = {"model.embed_tokens.weight": leaves["embed.embedding"],
           "model.norm.weight": leaves["final_norm"]}
    if not config.tie_word_embeddings:
        out["lm_head.weight"] = leaves["lm_head"].T
    per_layer = {
        "layers.attn.wq": ("self_attn.q_proj.weight", True),
        "layers.attn.wk": ("self_attn.k_proj.weight", True),
        "layers.attn.wv": ("self_attn.v_proj.weight", True),
        "layers.attn.wo": ("self_attn.o_proj.weight", True),
        "layers.mlp.gate": ("mlp.gate_proj.weight", True),
        "layers.mlp.up": ("mlp.up_proj.weight", True),
        "layers.mlp.down": ("mlp.down_proj.weight", True),
        "layers.input_norm": ("input_layernorm.weight", False),
        "layers.post_attn_norm": ("post_attention_layernorm.weight", False),
        "layers.attn.bq": ("self_attn.q_proj.bias", False),
        "layers.attn.bk": ("self_attn.k_proj.bias", False),
        "layers.attn.bv": ("self_attn.v_proj.bias", False),
        "layers.attn.q_norm": ("self_attn.q_norm.weight", False),
        "layers.attn.k_norm": ("self_attn.k_norm.weight", False),
        # OLMo-2 post-norm wiring (note: HF reuses the
        # post_attention_layernorm NAME for the attn-OUTPUT norm)
        "layers.attn_out_norm": ("post_attention_layernorm.weight", False),
        "layers.mlp_out_norm": ("post_feedforward_layernorm.weight", False),
    }
    if getattr(config, "sandwich_norm", False):
        # Gemma-2: the post_attn_norm leaf is the PRE-FFN norm
        per_layer["layers.post_attn_norm"] = ("pre_feedforward_layernorm.weight",
                                              False)
    for leaf, (hf, transpose) in per_layer.items():
        if leaf not in leaves:
            continue   # e.g. biases on a no-attn_bias config
        stack = leaves[leaf]
        for i in range(config.num_layers):
            t = stack[i]
            out[f"model.layers.{i}.{hf}"] = t.T if transpose else t
    return out


def _emit_gpt2(config, leaves: dict) -> dict:
    e = config.hidden_size
    out = {"transformer.wte.weight": leaves["wte"],
           "transformer.wpe.weight": leaves["wpe"],
           "transformer.ln_f.weight": leaves["lnf.scale"],
           "transformer.ln_f.bias": leaves["lnf.bias"],
           # HF ties lm_head to wte; emit it explicitly so from_pretrained
           # never warns about a missing head
           "lm_head.weight": leaves["wte"]}
    per_layer = {   # Conv1D stores [in, out]: no transposes anywhere
        "layers.ln1.scale": "ln_1.weight", "layers.ln1.bias": "ln_1.bias",
        "layers.attn.wqkv": "attn.c_attn.weight",
        "layers.attn.bqkv": "attn.c_attn.bias",
        "layers.attn.wo": "attn.c_proj.weight",
        "layers.attn.bo": "attn.c_proj.bias",
        "layers.ln2.scale": "ln_2.weight", "layers.ln2.bias": "ln_2.bias",
        "layers.mlp.wi": "mlp.c_fc.weight", "layers.mlp.bi": "mlp.c_fc.bias",
        "layers.mlp.wo": "mlp.c_proj.weight", "layers.mlp.bo": "mlp.c_proj.bias",
    }
    for leaf, hf in per_layer.items():
        stack = leaves[leaf]
        for i in range(config.num_layers):
            t = stack[i]
            if leaf == "layers.attn.wqkv":     # [e, 3, e] -> Conv1D [e, 3e]
                t = t.reshape(e, 3 * e)
            elif leaf == "layers.attn.bqkv":   # [3, e] -> [3e]
                t = t.reshape(3 * e)
            out[f"transformer.h.{i}.{hf}"] = t
    return out


def _emit_neox(config, leaves: dict) -> dict:
    h, d = config.num_heads, config.head_size
    out = {"gpt_neox.embed_in.weight": leaves["embed_in"],
           "gpt_neox.final_layer_norm.weight": leaves["lnf.scale"],
           "gpt_neox.final_layer_norm.bias": leaves["lnf.bias"],
           "embed_out.weight": leaves["embed_out"].T}
    per_layer = {
        "layers.ln1.scale": "input_layernorm.weight",
        "layers.ln1.bias": "input_layernorm.bias",
        "layers.ln2.scale": "post_attention_layernorm.weight",
        "layers.ln2.bias": "post_attention_layernorm.bias",
        "layers.attn.wo": "attention.dense.weight",
        "layers.attn.bo": "attention.dense.bias",
        "layers.mlp.wi": "mlp.dense_h_to_4h.weight",
        "layers.mlp.bi": "mlp.dense_h_to_4h.bias",
        "layers.mlp.wo": "mlp.dense_4h_to_h.weight",
        "layers.mlp.bo": "mlp.dense_4h_to_h.bias",
    }
    transposed = {"layers.attn.wo", "layers.mlp.wi", "layers.mlp.wo"}
    for leaf, hf in per_layer.items():
        stack = leaves[leaf]
        for i in range(config.num_layers):
            t = stack[i]
            out[f"gpt_neox.layers.{i}.{hf}"] = (t.T if leaf in transposed
                                                else t)
    for i in range(config.num_layers):
        # inverse of _make_map_neox's de-interleave: [e, 3, h*d] -> HF's
        # per-head-interleaved Linear [3e(out=(h,3,d)), e]
        w = leaves["layers.attn.wqkv"][i]          # [e, 3, h*d]
        e = w.shape[0]
        w = w.reshape(e, 3, h, d).transpose(2, 1, 3, 0).reshape(3 * h * d, e)
        b = leaves["layers.attn.bqkv"][i]          # [3, h*d]
        b = b.reshape(3, h, d).transpose(1, 0, 2).reshape(3 * h * d)
        out[f"gpt_neox.layers.{i}.attention.query_key_value.weight"] = w
        out[f"gpt_neox.layers.{i}.attention.query_key_value.bias"] = b
    return out


def _emit_moe(config, leaves: dict) -> dict:
    out = {"model.embed_tokens.weight": leaves["embed.embedding"],
           "model.norm.weight": leaves["final_norm"]}
    if not config.tie_word_embeddings:
        out["lm_head.weight"] = leaves["lm_head"].T
    attn = {
        "layers.attn.wq": "self_attn.q_proj.weight",
        "layers.attn.wk": "self_attn.k_proj.weight",
        "layers.attn.wv": "self_attn.v_proj.weight",
        "layers.attn.wo": "self_attn.o_proj.weight",
    }
    # qk_norm (Qwen3-MoE) or a shared expert (Qwen2-MoE) selects the qwen
    # spelling (mlp.experts.N.gate_proj...); plain configs keep Mixtral's
    # (block_sparse_moe.experts.N.w1...)
    qwen = bool(getattr(config, "qk_norm", False)
                or getattr(config, "shared_expert_intermediate", None))
    expert_names = ({"gate": "gate_proj", "up": "up_proj", "down": "down_proj"}
                    if qwen else {"gate": "w1", "up": "w3", "down": "w2"})
    for i in range(config.num_layers):
        for leaf, hf in attn.items():
            out[f"model.layers.{i}.{hf}"] = leaves[leaf][i].T
        if "layers.attn.bq" in leaves:   # Qwen2-MoE QKV biases
            for b, hf in (("bq", "q_proj"), ("bk", "k_proj"), ("bv", "v_proj")):
                out[f"model.layers.{i}.self_attn.{hf}.bias"] = \
                    leaves[f"layers.attn.{b}"][i]
        if getattr(config, "qk_norm", False):
            out[f"model.layers.{i}.self_attn.q_norm.weight"] = \
                leaves["layers.attn.q_norm"][i]
            out[f"model.layers.{i}.self_attn.k_norm.weight"] = \
                leaves["layers.attn.k_norm"][i]
        out[f"model.layers.{i}.input_layernorm.weight"] = \
            leaves["layers.input_norm"][i]
        out[f"model.layers.{i}.post_attention_layernorm.weight"] = \
            leaves["layers.post_attn_norm"][i]
        moe_prefix = (f"model.layers.{i}.mlp" if qwen
                      else f"model.layers.{i}.block_sparse_moe")
        out[f"{moe_prefix}.gate.weight"] = leaves["layers.moe.router"][i].T
        for x in range(config.num_experts):
            for ours, theirs in expert_names.items():
                out[f"{moe_prefix}.experts.{x}.{theirs}.weight"] = \
                    leaves[f"layers.moe.{ours}"][i, x].T
        if "layers.moe.shared_gate" in leaves:   # Qwen2-MoE shared expert
            for ours, theirs in (("shared_gate_proj", "gate_proj"),
                                 ("shared_up", "up_proj"),
                                 ("shared_down", "down_proj")):
                out[f"{moe_prefix}.shared_expert.{theirs}.weight"] = \
                    leaves[f"layers.moe.{ours}"][i].T
            out[f"{moe_prefix}.shared_expert_gate.weight"] = \
                leaves["layers.moe.shared_gate"][i][None, :]
    return out


_EMITTERS = {"llama": _emit_llama, "gpt2": _emit_gpt2, "neox": _emit_neox,
             "moe": _emit_moe}


# ---------------------------------------------------------------------------
# config.json emitters (inverse of models/auto.py's builders)
# ---------------------------------------------------------------------------

def _qwen_window_out(c) -> dict:
    """Qwen2/3 (dense and MoE) sliding-window keys for export. A uniform
    window maps to use_sliding_window; a full-then-sliding ``layer_windows``
    pattern (ingested from max_window_layers) maps back to that key —
    dropping either would reload as full attention: silent divergence."""
    lw = getattr(c, "layer_windows", None)
    if lw:
        mwl = next((i for i, w in enumerate(lw) if w), len(lw))
        w = max(lw)
        if lw != tuple(0 if i < mwl else w for i in range(len(lw))):
            # anything but leading-zeros-then-constant (e.g. a Gemma-style
            # alternating pattern forced onto a qwen config) is not
            # expressible as max_window_layers — refuse rather than export
            # a config that reloads with different attention
            raise ValueError(
                f"layer_windows {lw} is not a full-then-sliding "
                f"(max_window_layers) pattern and cannot be exported as a "
                f"Qwen config")
        return {"sliding_window": w, "use_sliding_window": True,
                "max_window_layers": mwl}
    if getattr(c, "sliding_window", None):
        return {"sliding_window": c.sliding_window, "use_sliding_window": True}
    return {}


def _rope_scaling_out(c) -> dict:
    """Round-trip the frozen rope_scaling tuple back to HF's dict form —
    dropping it would reload as plain RoPE: silently divergent long-context
    logits (the exact failure the frozen field exists to prevent)."""
    rs = getattr(c, "rope_scaling", None)
    if not rs:
        return {}
    d = {k: list(v) if isinstance(v, tuple) else v for k, v in dict(rs).items()}
    out = {"rope_scaling": d}
    rope_type = d.get("rope_type") or d.get("type")
    if rope_type == "longrope" and "original_max_position_embeddings" in d:
        # HF's longrope init reads original_max from the CONFIG TOP LEVEL
        # (Phi-3 style); leaving it only in-dict makes the exported config
        # crash on reload (factor stays None in _compute_longrope_parameters)
        out["original_max_position_embeddings"] = (
            d["original_max_position_embeddings"])
    return out


def _hf_config(bundle) -> dict:
    c = bundle.config
    if bundle.family == "gpt2":
        return {"architectures": ["GPT2LMHeadModel"], "model_type": "gpt2",
                "vocab_size": c.vocab_size, "n_embd": c.hidden_size,
                "n_layer": c.num_layers, "n_head": c.num_heads,
                "n_positions": c.max_position_embeddings,
                "n_ctx": c.max_position_embeddings,
                "layer_norm_epsilon": c.layer_norm_eps}
    if bundle.family == "neox":
        return {"architectures": ["GPTNeoXForCausalLM"],
                "model_type": "gpt_neox",
                "vocab_size": c.vocab_size, "hidden_size": c.hidden_size,
                "intermediate_size": c.intermediate_size,
                "num_hidden_layers": c.num_layers,
                "num_attention_heads": c.num_heads,
                "max_position_embeddings": c.max_position_embeddings,
                "rotary_pct": c.rotary_pct, "rotary_emb_base": c.rope_theta,
                "layer_norm_eps": c.layer_norm_eps,
                "use_parallel_residual": c.use_parallel_residual,
                "hidden_act": {"gelu": "gelu", "gelu_tanh": "gelu_new"}[c.act_fn],
                "tie_word_embeddings": False,
                **_rope_scaling_out(c)}
    base = {"vocab_size": c.vocab_size, "hidden_size": c.hidden_size,
            "intermediate_size": c.intermediate_size,
            "num_hidden_layers": c.num_layers,
            "num_attention_heads": c.num_heads,
            "num_key_value_heads": c.num_kv_heads,
            "max_position_embeddings": c.max_position_embeddings,
            "rope_theta": c.rope_theta, "rms_norm_eps": c.rms_norm_eps,
            "tie_word_embeddings": c.tie_word_embeddings,
            **_rope_scaling_out(c)}
    if bundle.family == "moe":
        if getattr(c, "shared_expert_intermediate", None):
            # Qwen gates SWA on use_sliding_window (_qwen_window_out); a
            # bare sliding_window key would reload as FULL attention
            return {**base, "architectures": ["Qwen2MoeForCausalLM"],
                    "model_type": "qwen2_moe",
                    "num_experts": c.num_experts,
                    "num_experts_per_tok": c.experts_per_token,
                    "moe_intermediate_size": c.intermediate_size,
                    "shared_expert_intermediate_size":
                        c.shared_expert_intermediate,
                    "norm_topk_prob": c.norm_topk_prob,
                    "router_aux_loss_coef": c.router_aux_coef,
                    "decoder_sparse_step": 1, "mlp_only_layers": [],
                    **_qwen_window_out(c)}
        if getattr(c, "qk_norm", False):
            return {**base, "architectures": ["Qwen3MoeForCausalLM"],
                    "model_type": "qwen3_moe",
                    "num_experts": c.num_experts,
                    "num_experts_per_tok": c.experts_per_token,
                    "moe_intermediate_size": c.intermediate_size,
                    "norm_topk_prob": c.norm_topk_prob,
                    "router_aux_loss_coef": c.router_aux_coef,
                    "head_dim": c.head_size,
                    "decoder_sparse_step": 1, "mlp_only_layers": [],
                    **_qwen_window_out(c)}
        out = {**base, "architectures": ["MixtralForCausalLM"],
               "model_type": "mixtral",
               "num_local_experts": c.num_experts,
               "num_experts_per_tok": c.experts_per_token,
               "router_aux_loss_coef": c.router_aux_coef}
        if getattr(c, "sliding_window", None):  # Mixtral's key is always live
            out["sliding_window"] = c.sliding_window
        return out
    # llama family: the config knobs decide which architecture this is
    if getattr(c, "sandwich_norm", False):
        base.update(architectures=["Gemma2ForCausalLM"], model_type="gemma2",
                    head_dim=c.head_size,
                    hidden_act="gelu_pytorch_tanh",
                    hidden_activation="gelu_pytorch_tanh",
                    query_pre_attn_scalar=c.query_pre_attn_scalar,
                    attn_logit_softcapping=c.attn_logit_softcap,
                    final_logit_softcapping=c.final_logit_softcap)
        if getattr(c, "layer_windows", None):
            base["sliding_window"] = max(c.layer_windows)
            base["layer_types"] = ["sliding_attention" if w else
                                   "full_attention" for w in c.layer_windows]
    elif getattr(c, "post_norm", False):
        base.update(architectures=["Olmo2ForCausalLM"], model_type="olmo2",
                    attention_bias=False)
    elif getattr(c, "qk_norm", False):
        base.update(architectures=["Qwen3ForCausalLM"], model_type="qwen3",
                    head_dim=c.head_size, attention_bias=False)
        base.update(_qwen_window_out(c))
    elif getattr(c, "norm_plus_one", False):
        base.update(architectures=["GemmaForCausalLM"], model_type="gemma",
                    head_dim=c.head_size,
                    hidden_act="gelu_pytorch_tanh",
                    hidden_activation="gelu_pytorch_tanh")
    elif getattr(c, "attn_bias", False):
        base.update(architectures=["Qwen2ForCausalLM"], model_type="qwen2")
        if c.head_dim:  # same silent-divergence risk as the llama branch:
            base["head_dim"] = c.head_dim  # default is hidden/heads on reload
        base.update(_qwen_window_out(c))
    elif getattr(c, "sliding_window", None):
        # plain-llama math + a live window == Mistral (HF LlamaConfig has no
        # sliding_window; exporting it as llama would silently drop the band)
        base.update(architectures=["MistralForCausalLM"], model_type="mistral",
                    sliding_window=c.sliding_window)
        if c.head_dim:
            base["head_dim"] = c.head_dim
    else:
        base.update(architectures=["LlamaForCausalLM"], model_type="llama",
                    attention_bias=False)
        if c.head_dim:
            base["head_dim"] = c.head_dim
    # without this a gelu-gated llama-family model reloads with transformers'
    # default silu MLP — silently divergent logits
    if "hidden_act" not in base:
        base["hidden_act"] = {"silu": "silu",
                              "gelu_tanh": "gelu_pytorch_tanh"}[
                                  getattr(c, "act_fn", "silu")]
    return base


def export_hf_checkpoint(bundle, params, out_dir: str | Path,
                         dtype: str = "float32") -> Path:
    """Write ``params`` as an HF checkpoint (``model.safetensors`` +
    ``config.json``) that ``AutoModelForCausalLM.from_pretrained`` loads."""
    from safetensors.numpy import save_file

    from .hf_convert import _flatten_with_paths

    if bundle.family not in _EMITTERS:
        raise ValueError(f"no HF export for family {bundle.family!r} "
                         f"(supported: {sorted(_EMITTERS)})")
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    leaves = {k: _to_np(v, dtype)
              for k, v in _flatten_with_paths(params).items()}
    tensors = _EMITTERS[bundle.family](bundle.config, leaves)
    # np views from transposes/slices must be contiguous for safetensors
    tensors = {k: np.ascontiguousarray(v) for k, v in tensors.items()}
    # transformers only accepts pt/tf/flax/mlx in the format tag; the tensor
    # bytes are framework-neutral, "pt" is what torch's loader expects
    save_file(tensors, str(out_dir / "model.safetensors"),
              metadata={"format": "pt"})
    with open(out_dir / "config.json", "w") as fp:
        json.dump(_hf_config(bundle), fp, indent=2)
    return out_dir


def main(argv=None) -> None:
    """CLI: restore a training experiment's latest Orbax checkpoint and
    publish it as an HF checkpoint.

        python -m distributed_training_guide_tpu.models.hf_export \\
            -m llama-650m -e outputs/my-run -o /ckpts/my-run-hf

    ``--optimizer`` must match what the run trained with — the checkpoint
    holds the optimizer state tree, and restore needs its structure (the
    params it wraps are what get exported)."""
    import argparse

    parser = argparse.ArgumentParser(description=main.__doc__)
    parser.add_argument("-m", "--model-name", required=True)
    parser.add_argument("-e", "--exp-dir", required=True,
                        help="experiment dir holding checkpoint-*/ + state.json")
    parser.add_argument("-o", "--out-dir", required=True)
    parser.add_argument("--optimizer", default="adamw",
                        choices=["adamw", "adafactor", "lion"],
                        help="optimizer the run used")
    parser.add_argument("--precision-policy", default="fp32",
                        help="precision policy the run trained with (must "
                             "match, like --optimizer: the checkpoint holds "
                             "the policy's storage layout — e.g. int8 "
                             "quantized moments for adam8bit)")
    parser.add_argument("--dtype", default="float32",
                        choices=["float32", "bfloat16", "float16"])
    args = parser.parse_args(argv)

    import jax

    from ..checkpoint import CheckpointIO, restore_train_state
    from ..parallel import make_mesh, make_plan
    from ..train import Trainer
    from ..train.optimizer import OPTIMIZERS
    from .registry import get_model

    bundle = get_model(args.model_name)
    # restore sharded over ALL local devices (fsdp plan): per-device HBM is
    # model/N instead of the whole state on one chip. Scale honesty: the
    # restore pulls params + optimizer state, and the export then gathers
    # the params to host — run this somewhere with HBM for state/N per
    # device and host RAM for ~2x the params (fine through ~10B-class;
    # pod-scale checkpoints need a multi-host run of this same CLI).
    n = len(jax.devices())
    plan = (make_plan("fsdp", make_mesh(fsdp=n)) if n > 1
            else make_plan("single", make_mesh(devices=jax.devices()[:1])))
    trainer = Trainer(bundle=bundle,
                      optimizer=OPTIMIZERS[args.optimizer](1e-4),
                      plan=plan, donate=False,
                      precision=args.precision_policy)
    io = CheckpointIO(args.exp_dir)
    state, host_state = restore_train_state(io, trainer)
    out = export_hf_checkpoint(bundle, state.params, args.out_dir,
                               dtype=args.dtype)
    print(f"exported step-{host_state.get('global_step', '?')} params of "
          f"{args.model_name} -> {out}")


if __name__ == "__main__":
    main()
