"""LoRA adapters for the llama family — parameter-efficient fine-tuning.

Beyond the reference (it trains full parameters only): freeze the base
checkpoint and train low-rank deltas ``W_eff = W + (alpha/r) * A @ B`` on
chosen projection matrices. TPU-first formulation:

- the base family keeps layers STACKED on a leading axis and scanned
  (``models/llama.py``), so each adapter is one pair of stacked tensors
  ``A [L, in, r]`` / ``B [L, r, out]`` — the merge is a single einsum per
  target, inside the same scan-compiled block;
- the merge happens at APPLY time (``W + scale * A@B`` materialized per
  step): on TPU the delta einsum is tiny (r << in/out) and XLA fuses the
  add into the consumer matmul's operand stream. Serving-style "merge once,
  keep two weight copies" is ``merge_lora`` (export path);
- adapters get their own leaves under ``params["lora"]`` with logical axes
  derived from the base leaf's axes (A inherits the IN axis, B the OUT
  axis, the rank dim is never sharded) — so fsdp/tp plans shard adapters
  consistently with their base matrices and the optimizer-state rules
  apply unchanged;
- freezing is an optax mask (``lora_mask`` / ``mask_optimizer``), not a
  separate code path: the Trainer still differentiates the whole tree, and
  the masked transform zeroes base updates while keeping moments only for
  the adapter leaves (MaskedNode elsewhere — ZeRO sharding rules still
  structurally match).

Usage (any chapter CLI): ``--lora-rank 8 [--lora-alpha 16]
[--lora-targets wq,wv]`` — composes with ``--pretrained`` for the standard
finetune-a-checkpoint flow, and with every sharding plan.
"""
from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp

from .registry import ModelBundle

# target short-name -> key path into the llama-family params tree. All are
# stacked [L, in, out] matmuls (biases/norms are not LoRA targets).
TARGET_PATHS = {
    "wq": ("layers", "attn", "wq"),
    "wk": ("layers", "attn", "wk"),
    "wv": ("layers", "attn", "wv"),
    "wo": ("layers", "attn", "wo"),
    "gate": ("layers", "mlp", "gate"),
    "up": ("layers", "mlp", "up"),
    "down": ("layers", "mlp", "down"),
}

DEFAULT_TARGETS = ("wq", "wv")   # the classic LoRA-paper pair


def _get(tree, path):
    for p in path:
        tree = tree[p]
    return tree


def _set(tree, path, value):
    """Return a copy of ``tree`` with ``path`` replaced by ``value``
    (shallow-copies only the spine — other leaves stay shared)."""
    if not path:
        return value
    out = dict(tree)
    out[path[0]] = _set(tree[path[0]], path[1:], value)
    return out


def lora_bundle(base: ModelBundle, *, rank: int = 8, alpha: float = 16.0,
                targets: Sequence[str] = DEFAULT_TARGETS) -> ModelBundle:
    """Wrap ``base`` so params = {"base": <frozen>, "lora": {t: {"a","b"}}}.

    B starts at zero, so step-0 logits are EXACTLY the base model's (pinned
    by test). Only the llama family is supported — its targets cover seven
    of the eleven HF architectures (llama/mistral/qwen2/qwen3/gemma/phi-3/
    olmo-2)."""
    if base.family != "llama":
        raise ValueError(
            f"LoRA targets are defined for the llama family only (got "
            f"{base.family!r}); gpt2/neox fuse QKV and moe stacks experts — "
            f"extend TARGET_PATHS if you need them")
    if rank < 1:
        raise ValueError(f"lora rank must be >= 1, got {rank}")
    unknown = [t for t in targets if t not in TARGET_PATHS]
    if unknown:
        raise ValueError(f"unknown lora targets {unknown}; "
                         f"choose from {sorted(TARGET_PATHS)}")
    targets = tuple(targets)
    scale = alpha / rank
    config = base.config

    def init_adapters(cfg, rng):
        """Adapter leaves only (shapes from an abstract base init — the
        pretrained-load path must not materialize a random base model)."""
        shapes = jax.eval_shape(lambda: base.init(cfg, jax.random.key(0)))
        keys = iter(jax.random.split(rng, len(targets)))
        lora = {}
        for t in targets:
            l, fan_in, fan_out = _get(shapes, TARGET_PATHS[t]).shape
            lora[t] = {
                # A ~ N(0, 0.02) like every other dense init here; B = 0 so
                # the wrapped model starts exactly at the base function
                "a": (0.02 * jax.random.normal(
                    next(keys), (l, fan_in, rank), jnp.float32)
                ).astype(cfg.param_dtype),
                "b": jnp.zeros((l, rank, fan_out), cfg.param_dtype),
            }
        return lora

    def init(cfg, rng):
        return {"base": base.init(cfg, rng),
                "lora": init_adapters(cfg, jax.random.fold_in(rng, 0x10FA))}

    def merge(cfg, params):
        merged = params["base"]
        for t in targets:
            pair = params["lora"][t]
            w = _get(merged, TARGET_PATHS[t])
            delta = jnp.einsum("lir,lro->lio", pair["a"].astype(w.dtype),
                               pair["b"].astype(w.dtype))
            merged = _set(merged, TARGET_PATHS[t],
                          w + jnp.asarray(scale, w.dtype) * delta)
        return merged

    def apply(cfg, params, *args, **kwargs):
        return base.apply(cfg, merge(cfg, params), *args, **kwargs)

    def param_logical_axes(cfg):
        base_axes = base.param_logical_axes(cfg)
        lora_axes = {}
        for t in targets:
            layers_ax, in_ax, out_ax = _get(base_axes, TARGET_PATHS[t])
            # the rank dim is tiny and never sharded; A/B inherit the base
            # leaf's in/out axes so tp/fsdp plans place them with their matrix
            lora_axes[t] = {"a": (layers_ax, in_ax, None),
                            "b": (layers_ax, None, out_ax)}
        return {"base": base_axes, "lora": lora_axes}

    apply_with_aux = None
    if base.apply_with_aux is not None:     # unreachable today (llama-only)
        def apply_with_aux(cfg, params, *args, **kwargs):  # pragma: no cover
            return base.apply_with_aux(cfg, merge(cfg, params), *args, **kwargs)

    bundle = ModelBundle(
        name=f"{base.name}+lora(r={rank},alpha={alpha:g},{','.join(targets)})",
        config=config, init=init, apply=apply,
        param_logical_axes=param_logical_axes, family=base.family,
        apply_with_aux=apply_with_aux)
    # non-dataclass attributes for tooling (merge_lora, the CLI loader)
    object.__setattr__(bundle, "lora_base", base)
    object.__setattr__(bundle, "lora_merge", merge)
    object.__setattr__(bundle, "lora_init_adapters", init_adapters)
    object.__setattr__(bundle, "lora_targets", targets)
    object.__setattr__(bundle, "lora_rank", rank)
    return bundle


def load_pretrained_lora(bundle: ModelBundle, param_shardings, out_dir,
                         seed: int = 0, param_dtype=None) -> dict:
    """Pretrained BASE weights (converted checkpoint, sharded streaming
    load) + fresh adapters placed on their plan shardings — the standard
    finetune-a-checkpoint entry."""
    from .hf_convert import load_pretrained

    base = getattr(bundle, "lora_base", None)
    if base is None:
        raise ValueError("load_pretrained_lora needs a lora_bundle")
    base_params = load_pretrained(base, param_shardings["base"], out_dir,
                                  param_dtype)
    init_ad = jax.jit(partial(bundle.lora_init_adapters, bundle.config),
                      out_shardings=param_shardings["lora"])
    return {"base": base_params, "lora": init_ad(jax.random.key(seed))}


def num_trainable_params(bundle: ModelBundle) -> int:
    shapes = jax.eval_shape(
        lambda: bundle.lora_init_adapters(bundle.config, jax.random.key(0)))
    return sum(int(jnp.prod(jnp.asarray(s.shape)))
               for s in jax.tree.leaves(shapes))


def merge_lora(bundle: ModelBundle, params: dict) -> dict:
    """Fold the trained deltas into base-layout params (for ``hf_export``,
    sampling via the base bundle, or publishing a plain checkpoint)."""
    merge = getattr(bundle, "lora_merge", None)
    if merge is None:
        raise ValueError("merge_lora needs a bundle built by lora_bundle")
    return merge(bundle.config, params)


def jit_merge(bundle: ModelBundle):
    """ONE compiled merge program ``{"base","lora"} -> base-layout`` —
    the post-training publish path (post/loop.py) merges after every
    policy update, so the W + scale*A@B einsum-and-add must not retrace
    per publish. The output layout matches the base bundle's params
    exactly, which is what ``ModelPrograms.publish_params`` validates
    against."""
    merge = getattr(bundle, "lora_merge", None)
    if merge is None:
        raise ValueError("jit_merge needs a bundle built by lora_bundle")
    return jax.jit(partial(merge, bundle.config))


def lora_labels(params: dict) -> dict:
    """"trainable" for adapter leaves, "frozen" for the base — the
    optax.multi_transform label tree matching the params."""
    return {
        "base": jax.tree.map(lambda _: "frozen", params["base"]),
        "lora": jax.tree.map(lambda _: "trainable", params["lora"]),
    }


def mask_optimizer(inner):
    """Wrap any optax transform so it updates ONLY the adapters and ZEROES
    the base updates. NOT ``optax.masked``: masked passes the RAW gradient
    through for masked-out leaves (they would train unregularized — the
    opposite of frozen). The callable label form works with abstract shapes
    (eval_shape in the Trainer's sharding derivation)."""
    import optax

    return optax.multi_transform(
        {"trainable": inner, "frozen": optax.set_to_zero()}, lora_labels)
