"""GPT-2 decoder, TPU-first.

The reference's chapter-1 smoke model is HF ``gpt2`` (124M)
(``01-single-gpu/README.md:11``). Same scan-over-layers / logical-axes design
as ``llama.py``; differences: learned position embeddings, LayerNorm with
bias, fused-QKV projection, gelu MLP, tied LM head.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ..ops.attention import multihead_attention
from ..ops.collectives import psum as _psum


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads

    def num_params(self) -> int:
        e, v, p, l = (self.hidden_size, self.vocab_size,
                      self.max_position_embeddings, self.num_layers)
        per_layer = 3 * e * e + 3 * e + e * e + e + 8 * e * e + 5 * e + 4 * e
        return v * e + p * e + l * per_layer + 2 * e


def init(config: GPT2Config, rng: jax.Array) -> dict:
    e, v, p, l = (config.hidden_size, config.vocab_size,
                  config.max_position_embeddings, config.num_layers)
    keys = iter(jax.random.split(rng, 8))

    def dense(key, shape):
        return (0.02 * jax.random.normal(key, shape, jnp.float32)).astype(config.param_dtype)

    def ln(shape):
        return {"scale": jnp.ones(shape, config.param_dtype),
                "bias": jnp.zeros(shape, config.param_dtype)}

    return {
        "wte": dense(next(keys), (v, e)),
        "wpe": dense(next(keys), (p, e)),
        "layers": {
            "ln1": ln((l, e)),
            "attn": {
                # fused QKV as [l, e, 3, e] (not [l, e, 3e]) so the head dim
                # is the trailing axis: sharding it over tp gives each member
                # the q/k/v columns of ITS heads — a contiguous slice of the
                # flat 3e dim would instead split q/k/v unevenly
                "wqkv": dense(next(keys), (l, e, 3, e)),
                "bqkv": jnp.zeros((l, 3, e), config.param_dtype),
                "wo": dense(next(keys), (l, e, e)),
                "bo": jnp.zeros((l, e), config.param_dtype),
            },
            "ln2": ln((l, e)),
            "mlp": {
                "wi": dense(next(keys), (l, e, 4 * e)),
                "bi": jnp.zeros((l, 4 * e), config.param_dtype),
                "wo": dense(next(keys), (l, 4 * e, e)),
                "bo": jnp.zeros((l, e), config.param_dtype),
            },
        },
        "lnf": ln((e,)),
    }


def param_logical_axes(config: GPT2Config) -> dict:
    del config
    ln_l = {"scale": ("layers", "embed_vector"), "bias": ("layers", "embed_vector")}
    return {
        "wte": ("vocab", "embed"),
        "wpe": ("pos", "embed"),
        "layers": {
            "ln1": ln_l,
            "attn": {
                "wqkv": ("layers", "embed", "qkv", "heads"),
                "bqkv": ("layers", "qkv", "heads_vector"),
                "wo": ("layers", "heads", "embed"),
                "bo": ("layers", "embed_vector"),
            },
            "ln2": ln_l,
            "mlp": {
                "wi": ("layers", "embed", "mlp"),
                "bi": ("layers", "mlp_vector"),
                "wo": ("layers", "mlp", "embed"),
                "bo": ("layers", "embed_vector"),
            },
        },
        "lnf": {"scale": ("embed_vector",), "bias": ("embed_vector",)},
    }


def _layernorm(x, p, eps):
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = x.mean(axis=-1, keepdims=True)
    var = ((x - mean) ** 2).mean(axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)).astype(dtype)


def _attn_sublayer(config, y, layer, positions, attn_impl,
                   standard_layout=True, kv_cache=None, return_kv=False,
                   attend_override=None):
    """ln'd input -> fused QKV -> attention -> out proj (no residual, no
    psum, no row bias — the block owns those). ``kv_cache``/``return_kv``/
    ``attend_override`` follow llama.attention_sublayer's decode contract
    (no rope here: gpt2's positions are the learned table applied at embed
    time)."""
    b, s, e = y.shape
    d = config.head_size
    cdt = config.dtype
    wqkv = layer["attn"]["wqkv"]          # [e, 3, e/tp] under manual tp
    e_loc = wqkv.shape[-1]
    h_loc = e_loc // d
    # project WITHOUT flattening [3, e_loc] into 3*e_loc: the trailing head
    # dim may be tp-sharded, and GSPMD cannot represent the strided tiling a
    # merged 3e dim would need — it would all-gather the QKV weight on the
    # auto tp/tp_fsdp paths (the layout's whole point is that it shards)
    qkv = (jnp.einsum("bse,eqh->bsqh", y, wqkv.astype(cdt))
           + layer["attn"]["bqkv"].astype(cdt))
    q = qkv[:, :, 0].reshape(b, s, h_loc, d)
    k = qkv[:, :, 1].reshape(b, s, h_loc, d)
    v = qkv[:, :, 2].reshape(b, s, h_loc, d)
    if attend_override is not None:
        attn, aux = attend_override(q, k, v, window=None, scale=None,
                                    softcap=None)
        out = attn.reshape(b, s, e_loc) @ layer["attn"]["wo"].astype(cdt)
        return (out, aux) if return_kv else out
    if kv_cache is not None:
        ck, cv, pos = kv_cache
        k = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, pos, 0, 0))
        v = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, pos, 0, 0))
        kv_pos = jnp.broadcast_to(jnp.arange(ck.shape[1])[None, :],
                                  (b, ck.shape[1]))
        attn = multihead_attention(q, k, v, causal=True, positions=positions,
                                   kv_positions=kv_pos, impl="xla",
                                   standard_layout=False)
    elif callable(attn_impl):  # e.g. ring attention under context parallelism
        attn = attn_impl(q, k, v, standard_layout=standard_layout)
    else:
        attn = multihead_attention(q, k, v, causal=True, positions=positions,
                                   kv_positions=positions, impl=attn_impl,
                                   standard_layout=standard_layout)
    out = attn.reshape(b, s, e_loc) @ layer["attn"]["wo"].astype(cdt)
    if return_kv:
        return out, (k, v)
    return out


def _mlp_sublayer(config, y, layer):
    """ln2'd input -> gelu MLP (no residual, no psum, no row bias)."""
    cdt = config.dtype
    y = jax.nn.gelu(y @ layer["mlp"]["wi"].astype(cdt)
                    + layer["mlp"]["bi"].astype(cdt), approximate=True)
    # tagged for REMAT_POLICIES["attn_mlp"] (same role as llama's mlp_act)
    y = checkpoint_name(y, "mlp_act")
    return y @ layer["mlp"]["wo"].astype(cdt)


def _block(config: GPT2Config, x, layer, positions, attn_impl,
           standard_layout=True, tp_axis=None):
    """One pre-LN transformer block.

    ``tp_axis``: set inside a shard_map region where tp is a *manual* axis
    (the pipeline schedule, ``parallel/pipeline.py``): wqkv/bqkv/wi/bi arrive
    column-sharded (local head / mlp slices, inferred from shapes), wo / mlp
    wo row-sharded with an explicit psum of the partial sums, and the
    replicated row biases are added once, after the psum."""
    cdt = config.dtype

    y = _layernorm(x, layer["ln1"], config.layer_norm_eps)
    attn = _attn_sublayer(config, y, layer, positions, attn_impl,
                          standard_layout)
    if tp_axis is not None:  # megatron Rowwise: out-proj partial sums
        attn = _psum(attn, tp_axis)
    x = x + attn + layer["attn"]["bo"].astype(cdt)

    y = _mlp_sublayer(config, _layernorm(x, layer["ln2"],
                                         config.layer_norm_eps), layer)
    if tp_axis is not None:
        y = _psum(y, tp_axis)
    return x + y + layer["mlp"]["bo"].astype(cdt)


def embed_tokens(config: GPT2Config, params: dict, input_ids: jnp.ndarray,
                 positions: jnp.ndarray) -> jnp.ndarray:
    """Token + learned-position embedding (pipeline stage-0 entry)."""
    tok = jnp.take(params["wte"], input_ids, axis=0)
    pos = jnp.take(params["wpe"], positions, axis=0)
    return (tok + pos).astype(config.dtype)


def output_weights(config: GPT2Config, params: dict) -> jnp.ndarray:
    """[E, V] tied output projection in compute dtype."""
    return params["wte"].T.astype(config.dtype)


def tp_embed(config: GPT2Config, params: dict, input_ids: jnp.ndarray,
             positions: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Stage-0 embedding when tp is a manual axis: vocab-sharded token table
    (megatron vocab parallelism) + the replicated learned-position table."""
    from ..ops.vocab_parallel import vocab_parallel_embed

    tok = vocab_parallel_embed(params["wte"].astype(config.dtype),
                               input_ids, axis)
    pos = jnp.take(params["wpe"], positions, axis=0).astype(config.dtype)
    return tok + pos


def final_hidden(config: GPT2Config, params: dict, x: jnp.ndarray) -> jnp.ndarray:
    return _layernorm(x, params["lnf"], config.layer_norm_eps)


def lm_head_logits(config: GPT2Config, params: dict, x: jnp.ndarray) -> jnp.ndarray:
    """Final LN + tied output projection (pipeline last-stage exit)."""
    return jnp.dot(final_hidden(config, params, x), output_weights(config, params),
                   preferred_element_type=jnp.float32)


def apply(
    config: GPT2Config,
    params: dict,
    input_ids: jnp.ndarray,
    positions: Optional[jnp.ndarray] = None,
    *,
    remat: bool = False,
    remat_policy: Optional[Any] = None,
    attn_impl: str = "auto",
    activation_sharding: Optional[Any] = None,
    return_hidden: bool = False,
    layer_schedule=None,
) -> jnp.ndarray:
    del activation_sharding  # gpt2 path is small; SP constraint not needed
    standard_layout = positions is None
    if positions is None:
        positions = jnp.arange(input_ids.shape[1])[None, :]
    positions = jnp.broadcast_to(positions, input_ids.shape)

    x = embed_tokens(config, params, input_ids, positions)

    block = partial(_block, config, positions=positions, attn_impl=attn_impl,
                    standard_layout=standard_layout)

    if layer_schedule is not None:  # explicit latency-hiding schedule
        x = layer_schedule(block, x, params["layers"])  # (ops/overlap.py)
    else:
        def scan_body(carry, layer_params):
            return block(carry, layer_params), None

        if remat:
            policy = remat_policy or jax.checkpoint_policies.nothing_saveable
            scan_body = jax.checkpoint(scan_body, policy=policy,
                                       prevent_cse=False)

        x, _ = jax.lax.scan(scan_body, x, params["layers"])
    if return_hidden:
        return final_hidden(config, params, x)
    return lm_head_logits(config, params, x)


# ---------------------------------------------------------------------------
# KV-cached decode (models/sample.py fast path) — same functional-cache
# contract as llama/neox. The simplest case of the three: no rope, the
# learned position row is added at embed time, so cached k/v are exactly
# the projections.
# ---------------------------------------------------------------------------

def init_cache(config: GPT2Config, batch: int, max_len: int) -> dict:
    shape = (config.num_layers, batch, max_len, config.num_heads,
             config.head_size)
    return {"k": jnp.zeros(shape, config.dtype),
            "v": jnp.zeros(shape, config.dtype)}


def _cached_block(config, x, layer, positions, kv_cache, attend_override=None):
    cdt = config.dtype
    y = _layernorm(x, layer["ln1"], config.layer_norm_eps)
    attn, kv = _attn_sublayer(config, y, layer, positions, "xla",
                              kv_cache=kv_cache, return_kv=True,
                              attend_override=attend_override)
    x = x + attn + layer["attn"]["bo"].astype(cdt)
    y = _mlp_sublayer(config, _layernorm(x, layer["ln2"],
                                         config.layer_norm_eps), layer)
    return x + y + layer["mlp"]["bo"].astype(cdt), kv


def prefill(config: GPT2Config, params: dict, input_ids: jnp.ndarray,
            cache: dict, last_pos=None):
    """Causal forward over the prompt, filling cache[:, :, :prompt_len];
    returns (logits [B, V] at ``last_pos``, default final position, and the
    cache)."""
    b, p = input_ids.shape
    positions = jnp.broadcast_to(jnp.arange(p)[None, :], (b, p))
    x = embed_tokens(config, params, input_ids, positions)

    def body(x, inputs):
        layer, ck, cv = inputs
        x, (k, v) = _cached_block(config, x, layer, positions, None)
        nk = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, 0, 0, 0))
        nv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, 0, 0, 0))
        return x, (nk, nv)

    x, (ks, vs) = jax.lax.scan(body, x, (params["layers"],
                                         cache["k"], cache["v"]))
    x_last = (x[:, -1:] if last_pos is None
              else jax.lax.dynamic_slice_in_dim(x, last_pos, 1, axis=1))
    return (lm_head_logits(config, params, x_last)[:, 0],
            {"k": ks, "v": vs})


def decode_step(config: GPT2Config, params: dict, token_ids: jnp.ndarray,
                pos, cache: dict):
    """One cached decode step (traced ``pos`` — one compile per generation);
    returns (logits [B, V], updated cache)."""
    b = token_ids.shape[0]
    positions = jnp.broadcast_to(jnp.asarray(pos)[None, None], (b, 1))
    x = embed_tokens(config, params, token_ids, positions)

    def body(x, inputs):
        layer, ck, cv = inputs
        x, (nk, nv) = _cached_block(config, x, layer, positions,
                                    (ck, cv, pos))
        return x, (nk, nv)

    x, (ks, vs) = jax.lax.scan(body, x, (params["layers"],
                                         cache["k"], cache["v"]))
    return lm_head_logits(config, params, x)[:, -1], {"k": ks, "v": vs}


def paged_decode_step(config: GPT2Config, params: dict,
                      token_ids: jnp.ndarray, positions: jnp.ndarray,
                      cache: dict, attend, last_index=None,
                      all_logits=False):
    """Paged multi-request decode/chunk step (llama.paged_decode_step
    contract): ``token_ids`` [S, T] starting at per-slot ``positions``
    [S] index the learned position table at embed time; ``attend`` owns
    the page scatter + block-table attend; ``last_index`` selects the
    logits row for a padded chunk, ``all_logits=True`` keeps every row
    (speculative verification). The block wiring is ``_cached_block``
    — the same body the contiguous decode runs."""
    from .llama import paged_logits_at, paged_positions

    pos2d = paged_positions(token_ids, positions)
    x = embed_tokens(config, params, token_ids, pos2d)

    def body(x, inputs):
        layer, kp, vp = inputs

        def override(q, k, v, *, window, scale, softcap):
            del window, scale, softcap  # no gpt2 attention extras
            return attend(q, k, v, kp, vp)

        return _cached_block(config, x, layer, pos2d, None,
                             attend_override=override)

    x, (ks, vs) = jax.lax.scan(body, x, (params["layers"],
                                         cache["k"], cache["v"]))
    return (paged_logits_at(lm_head_logits, config, params, x, last_index,
                            all_logits),
            {"k": ks, "v": vs})


PRESETS = {
    "gpt2-debug": GPT2Config(vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
                             max_position_embeddings=256),
    "gpt2": GPT2Config(),
    "gpt2-medium": GPT2Config(hidden_size=1024, num_layers=24, num_heads=16),
    "gpt2-large": GPT2Config(hidden_size=1280, num_layers=36, num_heads=20),
    "gpt2-xl": GPT2Config(hidden_size=1600, num_layers=48, num_heads=25),
}
