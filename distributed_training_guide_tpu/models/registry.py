"""Model registry: name -> (config, init, apply, logical axes).

The reference instantiates models by HF hub name through
``AutoModelForCausalLM.from_config`` (``01-single-gpu/train_llm.py:48-49``).
The TPU build keeps the by-name surface but resolves to the in-repo pure-JAX
zoo; HF hub names alias to the matching preset so reference commands port
unchanged (e.g. ``--model-name gpt2`` or ``meta-llama/Llama-3.1-405B``).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

from . import gpt2, llama, moe, neox


@dataclasses.dataclass(frozen=True)
class ModelBundle:
    name: str
    config: Any
    init: Callable          # (config, rng) -> params
    apply: Callable         # (config, params, input_ids, ...) -> logits
    param_logical_axes: Callable  # (config,) -> axes pytree
    family: str
    # MoE models: (config, params, ids, ...) -> (logits, aux_loss); the
    # trainer adds config.router_aux_coef * aux to the loss
    apply_with_aux: Optional[Callable] = None

    def num_params(self) -> int:
        return self.config.num_params()

    def num_active_params(self) -> int:
        """Per-token active params (MoE: k of E experts) for FLOPs/MFU math."""
        fn = getattr(self.config, "num_active_params", None)
        return fn() if fn else self.config.num_params()


_HF_ALIASES = {
    "openai-community/gpt2": "gpt2",
    "tinyllama/tinyllama-1.1b-chat-v1.0": "tinyllama-1.1b",
    "tinyllama/tinyllama_v1.1": "tinyllama-1.1b",
    "meta-llama/llama-3.2-1b": "llama-3.2-1b",
    "meta-llama/llama-3.2-3b": "llama-3.2-3b",
    "meta-llama/llama-3.1-8b": "llama-3.1-8b",
    "meta-llama/meta-llama-3.1-8b": "llama-3.1-8b",
    "meta-llama/llama-3.1-70b": "llama-3.1-70b",
    "meta-llama/llama-3.1-405b": "llama-3.1-405b",
    "meta-llama/meta-llama-3.1-405b": "llama-3.1-405b",
    "eleutherai/pythia-70m": "pythia-70m",
    "eleutherai/pythia-160m": "pythia-160m",
    "eleutherai/pythia-410m": "pythia-410m",
    "eleutherai/pythia-1.4b": "pythia-1.4b",
    "eleutherai/pythia-6.9b": "pythia-6.9b",
    "eleutherai/gpt-neox-20b": "gpt-neox-20b",
}


def family_module(family: str):
    """The module implementing a model family (block/embed/head helpers used
    by the pipeline schedule and chunked losses)."""
    mods = {"llama": llama, "gpt2": gpt2, "moe": moe, "neox": neox}
    if family not in mods:
        raise KeyError(f"unknown model family {family!r}")
    return mods[family]


def list_models() -> list[str]:
    return (sorted(gpt2.PRESETS) + sorted(llama.PRESETS) + sorted(moe.PRESETS)
            + sorted(neox.PRESETS))


def get_model(name: str, **overrides) -> ModelBundle:
    if name.startswith("hf:"):
        # AutoModelForCausalLM analogue (reference 01:57): build the family
        # config from the checkpoint's own config.json (models/auto.py)
        from .auto import config_from_hf

        family, config = config_from_hf(name[3:])
        if overrides:
            config = dataclasses.replace(config, **overrides)
        mod = family_module(family)
        return ModelBundle(
            name, config, mod.init, mod.apply, mod.param_logical_axes,
            family=family,
            **({"apply_with_aux": moe.apply_with_aux} if family == "moe" else {}))
    key = _HF_ALIASES.get(name.lower(), name.lower())
    if key in gpt2.PRESETS:
        config = gpt2.PRESETS[key]
        if overrides:
            config = dataclasses.replace(config, **overrides)
        return ModelBundle(key, config, gpt2.init, gpt2.apply,
                           gpt2.param_logical_axes, family="gpt2")
    if key in llama.PRESETS:
        config = llama.PRESETS[key]
        if overrides:
            config = dataclasses.replace(config, **overrides)
        return ModelBundle(key, config, llama.init, llama.apply,
                           llama.param_logical_axes, family="llama")
    if key in moe.PRESETS:
        config = moe.PRESETS[key]
        if overrides:
            config = dataclasses.replace(config, **overrides)
        return ModelBundle(key, config, moe.init, moe.apply,
                           moe.param_logical_axes, family="moe",
                           apply_with_aux=moe.apply_with_aux)
    if key in neox.PRESETS:
        config = neox.PRESETS[key]
        if overrides:
            config = dataclasses.replace(config, **overrides)
        return ModelBundle(key, config, neox.init, neox.apply,
                           neox.param_logical_axes, family="neox")
    raise ValueError(
        f"Unknown model {name!r}. Available: {', '.join(list_models())} "
        f"(HF aliases: {', '.join(sorted(_HF_ALIASES))})"
    )
