"""GPT-NeoX / Pythia decoder, TPU-first.

Seventh HF family, and the first with the *parallel-residual* block:
``x + attn(ln1(x)) + mlp(ln2(x))`` — attention and MLP read the same
input and their outputs sum into one residual update (the GPT-J/NeoX
design). The reference would train these through ``AutoModelForCausalLM``
(``01-single-gpu/train_llm.py:57``); here the family is native, with the
same scan-over-layers / logical-axes design as ``llama.py`` / ``gpt2.py``
so every sharding plan (ddp/fsdp/tp/2D/pp/cp) applies unchanged.

Architectural deltas vs the in-repo families:

- **parallel residual** (``use_parallel_residual``): under manual tensor
  parallelism this is a real communication win — the attention out-proj
  and MLP down-proj partial sums are added *before* a single ``psum``,
  one all-reduce per layer where the sequential block needs two;
- **partial rotary** (``rotary_pct``, 0.25 for Pythia): RoPE rotates only
  the first ``rotary_pct * head_dim`` dims of each head, the rest pass
  through position-free;
- LayerNorm (scale+bias) everywhere, exact (erf) GELU MLP with biases,
  fused QKV, MHA (no GQA), untied ``embed_in`` / ``embed_out``.

The fused QKV is stored ``[L, E, 3, H*D]`` (gpt2's layout) so the
trailing head dim shards over tp as one named axis; the HF checkpoint's
per-head-interleaved ``query_key_value`` layout is de-interleaved at
conversion time (``hf_convert._map_neox``).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ..ops.attention import multihead_attention
from ..ops.collectives import psum as _psum
from ..ops.rope import apply_rope
from .gpt2 import _layernorm


@dataclasses.dataclass(frozen=True)
class NeoXConfig:
    vocab_size: int = 50304
    hidden_size: int = 2048
    intermediate_size: int = 8192
    num_layers: int = 24
    num_heads: int = 16
    max_position_embeddings: int = 2048
    rotary_pct: float = 0.25
    rope_theta: float = 10000.0
    rope_scaling: Optional[tuple] = None  # frozen HF rope_scaling (ops/rope.py)
    layer_norm_eps: float = 1e-5
    use_parallel_residual: bool = True
    act_fn: str = "gelu"            # exact erf gelu (HF hidden_act="gelu")
    dtype: Any = jnp.bfloat16       # activation/compute dtype
    param_dtype: Any = jnp.float32  # storage dtype

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def rotary_ndims(self) -> int:
        n = int(self.head_size * self.rotary_pct)
        return n - (n % 2)  # the half-rotation needs an even count

    def num_params(self) -> int:
        e, f, v, l = (self.hidden_size, self.intermediate_size,
                      self.vocab_size, self.num_layers)
        per_layer = (3 * e * e + 3 * e        # fused qkv
                     + e * e + e              # out proj
                     + e * f + f + f * e + e  # mlp
                     + 4 * e)                 # two layernorms
        return 2 * v * e + l * per_layer + 2 * e  # embed_in/out + final ln


def init(config: NeoXConfig, rng: jax.Array) -> dict:
    e, f, v, l = (config.hidden_size, config.intermediate_size,
                  config.vocab_size, config.num_layers)
    keys = iter(jax.random.split(rng, 8))

    def dense(key, shape):
        return (0.02 * jax.random.normal(key, shape, jnp.float32)).astype(config.param_dtype)

    def ln(shape):
        return {"scale": jnp.ones(shape, config.param_dtype),
                "bias": jnp.zeros(shape, config.param_dtype)}

    return {
        "embed_in": dense(next(keys), (v, e)),
        "layers": {
            "ln1": ln((l, e)),
            "attn": {
                # [l, e, 3, e]: trailing fused-head dim shards over tp as
                # one axis (see gpt2.py's wqkv layout rationale)
                "wqkv": dense(next(keys), (l, e, 3, e)),
                "bqkv": jnp.zeros((l, 3, e), config.param_dtype),
                "wo": dense(next(keys), (l, e, e)),
                "bo": jnp.zeros((l, e), config.param_dtype),
            },
            "ln2": ln((l, e)),
            "mlp": {
                "wi": dense(next(keys), (l, e, f)),
                "bi": jnp.zeros((l, f), config.param_dtype),
                "wo": dense(next(keys), (l, f, e)),
                "bo": jnp.zeros((l, e), config.param_dtype),
            },
        },
        "lnf": ln((e,)),
        "embed_out": dense(next(keys), (e, v)),
    }


def param_logical_axes(config: NeoXConfig) -> dict:
    del config
    ln_l = {"scale": ("layers", "embed_vector"), "bias": ("layers", "embed_vector")}
    return {
        "embed_in": ("vocab", "embed"),
        "layers": {
            "ln1": ln_l,
            "attn": {
                "wqkv": ("layers", "embed", "qkv", "heads"),
                "bqkv": ("layers", "qkv", "heads_vector"),
                "wo": ("layers", "heads", "embed"),
                "bo": ("layers", "embed_vector"),
            },
            "ln2": ln_l,
            "mlp": {
                "wi": ("layers", "embed", "mlp"),
                "bi": ("layers", "mlp_vector"),
                "wo": ("layers", "mlp", "embed"),
                "bo": ("layers", "embed_vector"),
            },
        },
        "lnf": {"scale": ("embed_vector",), "bias": ("embed_vector",)},
        "embed_out": ("embed", "vocab"),
    }


ACT_FNS = {
    "gelu": partial(jax.nn.gelu, approximate=False),      # HF "gelu" (erf)
    "gelu_tanh": partial(jax.nn.gelu, approximate=True),  # HF gelu_new
}


def _rope_partial(x: jnp.ndarray, positions: jnp.ndarray, config) -> jnp.ndarray:
    """NeoX partial rotary: rotate the first ``rotary_ndims`` dims of each
    head (frequencies computed over ``rotary_ndims``, matching HF
    ``GPTNeoXRotaryEmbedding`` — which also computes any rope_scaling at the
    partial dim, HF's partial_rotary_factor), pass the rest through."""
    theta, rotary_dim = config.rope_theta, config.rotary_ndims
    rs = getattr(config, "rope_scaling", None)
    mp = config.max_position_embeddings
    if rotary_dim >= x.shape[-1]:
        return apply_rope(x, positions, theta, rs, mp)
    rot, passthrough = x[..., :rotary_dim], x[..., rotary_dim:]
    return jnp.concatenate([apply_rope(rot, positions, theta, rs, mp),
                            passthrough], axis=-1)


def _attn_branch(config, y, layer, positions, attn_impl,
                 standard_layout=True, kv_cache=None, return_kv=False,
                 attend_override=None):
    """ln'd input -> fused QKV -> partial rope -> attention -> out proj
    (no residual, no psum — the block owns those). ``kv_cache``/
    ``return_kv``/``attend_override`` follow llama.attention_sublayer's
    decode contract."""
    b, s, e = y.shape
    d = config.head_size
    cdt = config.dtype
    wqkv = layer["attn"]["wqkv"]          # [e, 3, e/tp] under manual tp
    e_loc = wqkv.shape[-1]
    h_loc = e_loc // d
    qkv = (jnp.einsum("bse,eqh->bsqh", y, wqkv.astype(cdt))
           + layer["attn"]["bqkv"].astype(cdt))
    q = qkv[:, :, 0].reshape(b, s, h_loc, d)
    k = qkv[:, :, 1].reshape(b, s, h_loc, d)
    v = qkv[:, :, 2].reshape(b, s, h_loc, d)
    q = _rope_partial(q, positions, config)
    k = _rope_partial(k, positions, config)
    if attend_override is not None:
        attn, aux = attend_override(q, k, v, window=None, scale=None,
                                    softcap=None)
        out = attn.reshape(b, s, e_loc) @ layer["attn"]["wo"].astype(cdt)
        return (out, aux) if return_kv else out
    if kv_cache is not None:
        ck, cv, pos = kv_cache
        k = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, pos, 0, 0))
        v = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, pos, 0, 0))
        kv_pos = jnp.broadcast_to(jnp.arange(ck.shape[1])[None, :],
                                  (b, ck.shape[1]))
        attn = multihead_attention(q, k, v, causal=True, positions=positions,
                                   kv_positions=kv_pos, impl="xla",
                                   standard_layout=False)
    elif callable(attn_impl):  # e.g. ring attention under context parallelism
        attn = attn_impl(q, k, v, standard_layout=standard_layout)
    else:
        attn = multihead_attention(q, k, v, causal=True, positions=positions,
                                   kv_positions=positions, impl=attn_impl,
                                   standard_layout=standard_layout)
    out = attn.reshape(b, s, e_loc) @ layer["attn"]["wo"].astype(cdt)
    if return_kv:
        return out, (k, v)
    return out


def _mlp_branch(config, y, layer):
    """ln'd input -> gelu MLP (no residual, no psum, no row bias)."""
    cdt = config.dtype
    act_fn = ACT_FNS[config.act_fn]
    y = act_fn(y @ layer["mlp"]["wi"].astype(cdt)
               + layer["mlp"]["bi"].astype(cdt))
    # tagged for REMAT_POLICIES["attn_mlp"] (same role as llama's mlp_act)
    y = checkpoint_name(y, "mlp_act")
    return y @ layer["mlp"]["wo"].astype(cdt)


def _block(config: NeoXConfig, x, layer, positions, attn_impl,
           standard_layout=True, tp_axis=None):
    """One parallel-residual block (or sequential when the config says so).

    ``tp_axis``: set inside a shard_map region where tp is a *manual* axis
    (the pipeline schedule): wqkv/bqkv/wi/bi arrive column-sharded (local
    head / mlp slices, inferred from shapes), wo / mlp wo row-sharded. In
    the parallel-residual case the two row-parallel partial sums are added
    BEFORE one psum — the block's structural communication advantage."""
    cdt = config.dtype

    def attn_branch(y):
        return _attn_branch(config, y, layer, positions, attn_impl,
                            standard_layout)

    def mlp_branch(y):
        return _mlp_branch(config, y, layer)

    biases = (layer["attn"]["bo"].astype(cdt) + layer["mlp"]["bo"].astype(cdt))
    if config.use_parallel_residual:
        # x + attn(ln1 x) + mlp(ln2 x): one residual update; under manual tp
        # the two partial sums share ONE all-reduce (row biases, replicated,
        # are added after it)
        update = (attn_branch(_layernorm(x, layer["ln1"], config.layer_norm_eps))
                  + mlp_branch(_layernorm(x, layer["ln2"], config.layer_norm_eps)))
        if tp_axis is not None:
            update = _psum(update, tp_axis)
        return x + update + biases
    # sequential (use_parallel_residual=False checkpoints): gpt2-shaped
    attn = attn_branch(_layernorm(x, layer["ln1"], config.layer_norm_eps))
    if tp_axis is not None:
        attn = _psum(attn, tp_axis)
    x = x + attn + layer["attn"]["bo"].astype(cdt)
    mlp = mlp_branch(_layernorm(x, layer["ln2"], config.layer_norm_eps))
    if tp_axis is not None:
        mlp = _psum(mlp, tp_axis)
    return x + mlp + layer["mlp"]["bo"].astype(cdt)


def embed_tokens(config: NeoXConfig, params: dict, input_ids: jnp.ndarray,
                 positions: jnp.ndarray) -> jnp.ndarray:
    """Token embedding (pipeline stage-0 entry); rope happens inside blocks."""
    del positions
    return jnp.take(params["embed_in"], input_ids, axis=0).astype(config.dtype)


def output_weights(config: NeoXConfig, params: dict) -> jnp.ndarray:
    """[E, V] untied output projection in compute dtype."""
    return params["embed_out"].astype(config.dtype)


def tp_embed(config: NeoXConfig, params: dict, input_ids: jnp.ndarray,
             positions: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Stage-0 embedding when tp is a manual axis: megatron vocab
    parallelism over the sharded ``embed_in`` table."""
    del positions
    from ..ops.vocab_parallel import vocab_parallel_embed

    return vocab_parallel_embed(params["embed_in"].astype(config.dtype),
                                input_ids, axis)


def final_hidden(config: NeoXConfig, params: dict, x: jnp.ndarray) -> jnp.ndarray:
    return _layernorm(x, params["lnf"], config.layer_norm_eps)


def lm_head_logits(config: NeoXConfig, params: dict, x: jnp.ndarray) -> jnp.ndarray:
    """Final LN + untied output projection (pipeline last-stage exit)."""
    return jnp.dot(final_hidden(config, params, x), output_weights(config, params),
                   preferred_element_type=jnp.float32)


def apply(
    config: NeoXConfig,
    params: dict,
    input_ids: jnp.ndarray,
    positions: Optional[jnp.ndarray] = None,
    *,
    remat: bool = False,
    remat_policy: Optional[Any] = None,
    attn_impl: str = "auto",
    activation_sharding: Optional[Any] = None,
    return_hidden: bool = False,
    layer_schedule=None,
) -> jnp.ndarray:
    """Forward -> float32 logits [B, S, V] (or final-normed hiddens [B, S, E]
    when ``return_hidden``, for chunked losses). Same contract as
    ``llama.apply`` — explicit ``positions`` required when the sequence dim
    is sharded (context parallelism); ``layer_schedule`` (ops/overlap.py)
    replaces the layer scan with the explicit latency-hiding schedule."""
    standard_layout = positions is None
    if positions is None:
        positions = jnp.arange(input_ids.shape[1])[None, :]
    positions = jnp.broadcast_to(positions, input_ids.shape)

    x = embed_tokens(config, params, input_ids, positions)

    block = partial(_block, config, positions=positions, attn_impl=attn_impl,
                    standard_layout=standard_layout)

    def constrained_block(carry, layer_params):
        y = block(carry, layer_params)
        if activation_sharding is not None:
            y = jax.lax.with_sharding_constraint(y, activation_sharding)
        return y

    if layer_schedule is not None:  # explicit latency-hiding schedule
        x = layer_schedule(constrained_block, x, params["layers"])
    else:
        def scan_body(carry, layer_params):
            return constrained_block(carry, layer_params), None

        if remat:
            policy = remat_policy or jax.checkpoint_policies.nothing_saveable
            scan_body = jax.checkpoint(scan_body, policy=policy,
                                       prevent_cse=False)

        x, _ = jax.lax.scan(scan_body, x, params["layers"])
    if return_hidden:
        return final_hidden(config, params, x)
    return lm_head_logits(config, params, x)


# ---------------------------------------------------------------------------
# KV-cached decode (models/sample.py fast path) — same functional-cache
# contract as llama.init_cache/prefill/decode_step; the block math here is
# the parallel residual (x + attn + mlp in ONE update) with partial rope.
# ---------------------------------------------------------------------------

def init_cache(config: NeoXConfig, batch: int, max_len: int) -> dict:
    shape = (config.num_layers, batch, max_len, config.num_heads,
             config.head_size)
    return {"k": jnp.zeros(shape, config.dtype),
            "v": jnp.zeros(shape, config.dtype)}


def _cached_block(config, x, layer, positions, kv_cache, attend_override=None):
    """Parallel- or sequential-residual block through the cache path;
    returns (x, (k, v))."""
    eps = config.layer_norm_eps
    cdt = config.dtype
    attn, kv = _attn_branch(config, _layernorm(x, layer["ln1"], eps),
                            layer, positions, "xla", kv_cache=kv_cache,
                            return_kv=True, attend_override=attend_override)
    if config.use_parallel_residual:
        update = attn + _mlp_branch(config, _layernorm(x, layer["ln2"], eps),
                                    layer)
        biases = (layer["attn"]["bo"].astype(cdt)
                  + layer["mlp"]["bo"].astype(cdt))
        return x + update + biases, kv
    x = x + attn + layer["attn"]["bo"].astype(cdt)
    mlp = _mlp_branch(config, _layernorm(x, layer["ln2"], eps), layer)
    return x + mlp + layer["mlp"]["bo"].astype(cdt), kv


def prefill(config: NeoXConfig, params: dict, input_ids: jnp.ndarray,
            cache: dict, last_pos=None):
    """Causal forward over the prompt, filling cache[:, :, :prompt_len];
    returns (logits [B, V] at ``last_pos``, default final position, and the
    cache)."""
    b, p = input_ids.shape
    positions = jnp.broadcast_to(jnp.arange(p)[None, :], (b, p))
    x = embed_tokens(config, params, input_ids, positions)

    def body(x, inputs):
        layer, ck, cv = inputs
        x, (k, v) = _cached_block(config, x, layer, positions, None)
        nk = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, 0, 0, 0))
        nv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, 0, 0, 0))
        return x, (nk, nv)

    x, (ks, vs) = jax.lax.scan(body, x, (params["layers"],
                                         cache["k"], cache["v"]))
    x_last = (x[:, -1:] if last_pos is None
              else jax.lax.dynamic_slice_in_dim(x, last_pos, 1, axis=1))
    return (lm_head_logits(config, params, x_last)[:, 0],
            {"k": ks, "v": vs})


def decode_step(config: NeoXConfig, params: dict, token_ids: jnp.ndarray,
                pos, cache: dict):
    """One cached decode step (traced ``pos`` — one compile per generation);
    returns (logits [B, V], updated cache)."""
    b = token_ids.shape[0]
    positions = jnp.broadcast_to(jnp.asarray(pos)[None, None], (b, 1))
    x = embed_tokens(config, params, token_ids, positions)

    def body(x, inputs):
        layer, ck, cv = inputs
        x, (nk, nv) = _cached_block(config, x, layer, positions,
                                    (ck, cv, pos))
        return x, (nk, nv)

    x, (ks, vs) = jax.lax.scan(body, x, (params["layers"],
                                         cache["k"], cache["v"]))
    return lm_head_logits(config, params, x)[:, -1], {"k": ks, "v": vs}


def paged_decode_step(config: NeoXConfig, params: dict,
                      token_ids: jnp.ndarray, positions: jnp.ndarray,
                      cache: dict, attend, last_index=None,
                      all_logits=False):
    """Paged multi-request decode/chunk step (llama.paged_decode_step
    contract) through ``_cached_block`` — the same parallel-/sequential-
    residual body the contiguous decode runs. ``all_logits=True`` keeps
    every position's logits (speculative verification)."""
    from .llama import paged_logits_at, paged_positions

    pos2d = paged_positions(token_ids, positions)
    x = embed_tokens(config, params, token_ids, pos2d)

    def body(x, inputs):
        layer, kp, vp = inputs

        def override(q, k, v, *, window, scale, softcap):
            del window, scale, softcap  # no neox attention extras
            return attend(q, k, v, kp, vp)

        return _cached_block(config, x, layer, pos2d, None,
                             attend_override=override)

    x, (ks, vs) = jax.lax.scan(body, x, (params["layers"],
                                         cache["k"], cache["v"]))
    return (paged_logits_at(lm_head_logits, config, params, x, last_index,
                            all_logits),
            {"k": ks, "v": vs})


# ---------------------------------------------------------------------------
# Presets (shapes from the Pythia suite / NeoX-20B model cards; the
# reference reaches these via AutoModelForCausalLM, `01:57`).
# ---------------------------------------------------------------------------

PRESETS = {
    "neox-debug": NeoXConfig(vocab_size=512, hidden_size=64, intermediate_size=256,
                             num_layers=2, num_heads=4, max_position_embeddings=256),
    "pythia-70m": NeoXConfig(vocab_size=50304, hidden_size=512, intermediate_size=2048,
                             num_layers=6, num_heads=8),
    "pythia-160m": NeoXConfig(vocab_size=50304, hidden_size=768, intermediate_size=3072,
                              num_layers=12, num_heads=12),
    "pythia-410m": NeoXConfig(vocab_size=50304, hidden_size=1024, intermediate_size=4096,
                              num_layers=24, num_heads=16),
    "pythia-1.4b": NeoXConfig(vocab_size=50304, hidden_size=2048, intermediate_size=8192,
                              num_layers=24, num_heads=16),
    "pythia-6.9b": NeoXConfig(vocab_size=50432, hidden_size=4096, intermediate_size=16384,
                              num_layers=32, num_heads=32),
    "gpt-neox-20b": NeoXConfig(vocab_size=50432, hidden_size=6144, intermediate_size=24576,
                               num_layers=44, num_heads=64),
}
