from .registry import get_model, list_models, ModelBundle
from . import llama, gpt2

__all__ = ["get_model", "list_models", "ModelBundle", "llama", "gpt2"]
