from .registry import ModelBundle, family_module, get_model, list_models
from . import gpt2, llama, moe

__all__ = ["get_model", "list_models", "family_module", "ModelBundle",
           "gpt2", "llama", "moe"]
