"""Llama-family decoder, TPU-first.

Capability parity with the reference's use of HF ``LlamaForCausalLM``
(``05-training-llama-405b/train_llm.py``, ``06-tensor-parallel/train_llm.py``)
but designed for XLA rather than translated from torch:

- parameters are a plain pytree with layers *stacked* on a leading axis and the
  forward is a ``lax.scan`` over layers — one compiled block body instead of L
  unrolled copies (compile time and HLO size stay flat as L grows to 126 for
  405B);
- every leaf carries *logical axis names* (``param_logical_axes``); the
  parallel layer maps logical axes -> mesh axes to produce NamedShardings, so
  DDP/FSDP/TP/2D are pure sharding-plan changes (the torch reference needs a
  different wrapper API per chapter);
- activation checkpointing is ``jax.checkpoint`` around the scanned block
  (reference C20, ``05:163-178``);
- attention dispatches to the Pallas flash kernel on TPU (reference uses the
  flash-attn CUDA wheel, ``05:93``).

Weights are kept 2-D ([in, out]) with fused head dims so TP shardings are a
single named axis on one dimension.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ..ops.attention import multihead_attention
from ..ops.collectives import psum as _psum
from ..ops.quantized_matmul import quantized_matmul, quantized_take
from ..ops.rope import apply_rope, freeze_rope_scaling


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632
    num_layers: int = 22
    num_heads: int = 32
    num_kv_heads: int = 4
    head_dim: Optional[int] = None
    max_position_embeddings: int = 4096
    rope_theta: float = 10000.0
    # HF rope_scaling in frozen-tuple form (ops.rope.freeze_rope_scaling);
    # None = plain RoPE. All six HF rope types are supported (ops/rope.py)
    rope_scaling: Optional[tuple] = None
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    # sliding-window attention (Mistral/Qwen2/Phi-3 checkpoints): query i
    # attends keys with 0 <= i - j < window; None = full causal
    sliding_window: Optional[int] = None
    attn_bias: bool = False         # QKV projection biases (Qwen2-style)
    # RMSNorm on q/k pre-rope: False | True (per-head [head_dim], Qwen3) |
    # "flat" (full-width [heads*head_dim], applied before the head reshape,
    # OLMo-2)
    qk_norm: Any = False
    # OLMo-2 block wiring: NO pre-norms; RMSNorm applied to each sublayer's
    # OUTPUT before the residual add (x = x + norm(attn(x)))
    post_norm: bool = False
    # Gemma-2 block wiring: norms on BOTH sides of each sublayer
    # (x = x + norm(attn(norm(x))); x = x + norm(mlp(norm(x))))
    sandwich_norm: bool = False
    # Gemma-2 attention extras: tanh capping of attention scores / final
    # logits, score scale override (query_pre_attn_scalar ** -0.5), and the
    # per-layer window pattern (an L-tuple, 0 = full attention that layer —
    # Gemma-2 alternates sliding/full). All run on the xla attention path.
    attn_logit_softcap: Optional[float] = None
    final_logit_softcap: Optional[float] = None
    query_pre_attn_scalar: Optional[float] = None
    layer_windows: Optional[tuple] = None
    act_fn: str = "silu"            # MLP gate activation: silu | gelu_tanh (Gemma)
    norm_plus_one: bool = False     # RMSNorm scales by (1 + w) (Gemma)
    scale_embed: bool = False       # multiply embeddings by sqrt(hidden) (Gemma)
    dtype: Any = jnp.bfloat16       # activation/compute dtype
    param_dtype: Any = jnp.float32  # storage dtype

    @property
    def head_size(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    def num_params(self) -> int:
        e, f, v = self.hidden_size, self.intermediate_size, self.vocab_size
        hq = self.num_heads * self.head_size
        hkv = self.num_kv_heads * self.head_size
        per_layer = e * hq + 2 * e * hkv + hq * e + 3 * e * f + 2 * e
        if self.attn_bias:
            per_layer += hq + 2 * hkv
        if self.qk_norm == "flat":
            per_layer += hq + hkv
        elif self.qk_norm:
            per_layer += 2 * self.head_size
        head = 0 if self.tie_word_embeddings else e * v
        return v * e + self.num_layers * per_layer + e + head


def init(config: LlamaConfig, rng: jax.Array) -> dict:
    """Random init (normal(0.02), zeros-free — matches HF from_config init scale)."""
    e, f, v, l = (config.hidden_size, config.intermediate_size,
                  config.vocab_size, config.num_layers)
    d = config.head_size
    hq, hkv = config.num_heads * d, config.num_kv_heads * d
    keys = iter(jax.random.split(rng, 16))

    def dense(key, shape):
        return (0.02 * jax.random.normal(key, shape, jnp.float32)).astype(config.param_dtype)

    attn = {
        "wq": dense(next(keys), (l, e, hq)),
        "wk": dense(next(keys), (l, e, hkv)),
        "wv": dense(next(keys), (l, e, hkv)),
        "wo": dense(next(keys), (l, hq, e)),
    }
    if config.attn_bias:  # Qwen2-style QKV biases (zeros, like HF init)
        attn.update(bq=jnp.zeros((l, hq), config.param_dtype),
                    bk=jnp.zeros((l, hkv), config.param_dtype),
                    bv=jnp.zeros((l, hkv), config.param_dtype))
    if config.qk_norm == "flat":  # OLMo-2 full-width q/k RMSNorm scales
        attn.update(q_norm=jnp.ones((l, hq), config.param_dtype),
                    k_norm=jnp.ones((l, hkv), config.param_dtype))
    elif config.qk_norm:  # Qwen3 per-head q/k RMSNorm scales (ones, HF init)
        attn.update(q_norm=jnp.ones((l, d), config.param_dtype),
                    k_norm=jnp.ones((l, d), config.param_dtype))
    # key-consumption ORDER is part of the determinism contract (same seed
    # -> same params across versions): embed draws before the MLP leaves,
    # exactly as in every prior release
    embed = dense(next(keys), (v, e))
    layers = {
        "attn": attn,
        "mlp": {
            "gate": dense(next(keys), (l, e, f)),
            "up": dense(next(keys), (l, e, f)),
            "down": dense(next(keys), (l, f, e)),
        },
    }
    if config.post_norm:   # OLMo-2: norms sit on the sublayer OUTPUTS
        layers.update(attn_out_norm=jnp.ones((l, e), config.param_dtype),
                      mlp_out_norm=jnp.ones((l, e), config.param_dtype))
    elif config.sandwich_norm:   # Gemma-2: norms on BOTH sides
        layers.update(input_norm=jnp.ones((l, e), config.param_dtype),
                      attn_out_norm=jnp.ones((l, e), config.param_dtype),
                      post_attn_norm=jnp.ones((l, e), config.param_dtype),
                      mlp_out_norm=jnp.ones((l, e), config.param_dtype))
    else:
        layers.update(input_norm=jnp.ones((l, e), config.param_dtype),
                      post_attn_norm=jnp.ones((l, e), config.param_dtype))
    params = {
        "embed": {"embedding": embed},
        "layers": layers,
        "final_norm": jnp.ones((e,), config.param_dtype),
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = dense(next(keys), (e, v))
    return params


def param_logical_axes(config: LlamaConfig) -> dict:
    """Logical axis names for every leaf, mirroring ``init``'s structure.

    Names: vocab, embed, heads (fused q-heads x head_dim), kv (fused kv-heads),
    mlp, layers (the scan axis). ``None`` = never sharded on that dim.
    """
    attn_axes = {
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv"),
        "wv": ("layers", "embed", "kv"),
        "wo": ("layers", "heads", "embed"),
    }
    if config.attn_bias:  # biases shard with the head dim they add onto
        attn_axes.update(bq=("layers", "heads"), bk=("layers", "kv"),
                         bv=("layers", "kv"))
    if config.qk_norm == "flat":  # full-width scales shard with their heads
        attn_axes.update(q_norm=("layers", "heads_vector"),
                         k_norm=("layers", "kv_vector"))
    elif config.qk_norm:  # one [head_dim] scale shared by every head: never
        attn_axes.update(q_norm=("layers", "head_dim_vector"),  # sharded
                         k_norm=("layers", "head_dim_vector"))
    layer_axes = {
        "attn": attn_axes,
        "mlp": {
            "gate": ("layers", "embed", "mlp"),
            "up": ("layers", "embed", "mlp"),
            "down": ("layers", "mlp", "embed"),
        },
    }
    if config.post_norm:
        layer_axes.update(attn_out_norm=("layers", "embed_vector"),
                          mlp_out_norm=("layers", "embed_vector"))
    elif config.sandwich_norm:
        layer_axes.update(input_norm=("layers", "embed_vector"),
                          attn_out_norm=("layers", "embed_vector"),
                          post_attn_norm=("layers", "embed_vector"),
                          mlp_out_norm=("layers", "embed_vector"))
    else:
        layer_axes.update(input_norm=("layers", "embed_vector"),
                          post_attn_norm=("layers", "embed_vector"))
    axes = {
        "embed": {"embedding": ("vocab", "embed")},
        "layers": layer_axes,
        "final_norm": ("embed_vector",),
    }
    if not config.tie_word_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


ACT_FNS = {
    "silu": jax.nn.silu,
    "gelu_tanh": partial(jax.nn.gelu, approximate=True),  # HF gelu_pytorch_tanh
}


def _is_qt(w) -> bool:
    """Duck-typed ``train/precision.py`` ``Quantized`` check: the serving
    engine stores its projection weights as int8 payload + per-block fp32
    scales under ``weight_dtype='int8'`` (serve/weights.py). Structural,
    not isinstance — ``train`` imports ``models`` (train/step.py), so the
    model family cannot import ``train.precision`` back."""
    return hasattr(w, "q") and hasattr(w, "scale")


def _wmat(h: jnp.ndarray, w, cdt) -> jnp.ndarray:
    """``h @ w`` in compute dtype for a float weight; block-dequant matmul
    (fp32 accumulate, then the same compute-dtype cast) for a Quantized
    one — no full fp32 weight tensor materializes on that path."""
    if _is_qt(w):
        return quantized_matmul(h, w).astype(cdt)
    return h @ w.astype(cdt)


def _rmsnorm(x: jnp.ndarray, scale: jnp.ndarray, eps: float,
             plus_one: bool = False) -> jnp.ndarray:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    scale = scale.astype(jnp.float32)
    if plus_one:            # Gemma stores w, applies (1 + w)
        scale = scale + 1.0
    return (x * scale).astype(dtype)


def _flat_rmsnorm(x: jnp.ndarray, scale: jnp.ndarray, eps: float,
                  tp_axis: Optional[str]) -> jnp.ndarray:
    """RMSNorm over the FULL flattened heads width (OLMo-2 q/k norm).
    Outside manual regions this is plain ``_rmsnorm`` (GSPMD inserts any
    needed collective itself); inside a manual-tp shard_map the local shard
    is ``[.., width/tp]``, so the sum-of-squares is psum'd across members
    and divided by the GLOBAL width before the local scale applies."""
    if tp_axis is None:
        return _rmsnorm(x, scale, eps)
    xf = x.astype(jnp.float32)
    ss = _psum(jnp.sum(xf * xf, axis=-1, keepdims=True), tp_axis)
    width = x.shape[-1] * jax.lax.psum(1, tp_axis)
    normed = xf * jax.lax.rsqrt(ss / width + eps)
    return (normed * scale.astype(jnp.float32)).astype(x.dtype)


def attention_extras(config):
    """Gemma-2 attention extras as (score_scale, logit_softcap) — None/None
    everywhere else. The ONE derivation of ``query_pre_attn_scalar ** -0.5``
    shared by the model dispatch and the Trainer's wrapper factories (both
    paths must bake the identical scale or flash/CP would silently diverge
    from xla)."""
    qpas = getattr(config, "query_pre_attn_scalar", None)
    return ((qpas ** -0.5) if qpas else None,
            getattr(config, "attn_logit_softcap", None))


def attention_sublayer(config, x: jnp.ndarray, attn_params: dict, norm_scale,
                       positions: jnp.ndarray, attn_impl,
                       standard_layout: bool = True,
                       tp_axis: Optional[str] = None,
                       kv_cache=None, return_kv: bool = False,
                       window_override=None, attend_override=None,
                       wmat_override=None):
    """norm -> rope'd GQA attention -> output proj (residual added by caller).

    Shared by the dense Llama block and the MoE family (config is duck-typed:
    needs num_heads/num_kv_heads/head_size/rope_theta/rms_norm_eps/dtype).

    ``tp_axis``: set when called inside a shard_map region where tp is a
    *manual* axis (the pipeline schedule) — weights arrive as per-member head
    shards (head counts are inferred from the weight shapes, not the config)
    and the output projection's partial sum is psum'd explicitly, the
    megatron Rowwise reduction GSPMD otherwise inserts.

    Decode support (the sampler's KV cache, ``models/sample.py``):
    ``kv_cache=(cached_k, cached_v, pos)`` writes this call's rope'd k/v at
    ``pos`` into the caches and attends q over the FULL cache (explicit
    kv_positions keep the causal mask exact; zero rows beyond ``pos`` are
    masked out by it). ``return_kv=True`` additionally returns the (rope'd,
    possibly cache-merged) k/v. Both default off — the training path is
    untouched.

    ``attend_override`` (the serving engine's paged-KV hook): a callable
    ``(q, k, v, *, window, scale, softcap) -> (attn, aux)`` replacing the
    cache merge + attend entirely — it receives the rope'd/normed per-head
    projections and the family-resolved attention extras, and whatever
    functional cache state it updates rides back through ``aux`` (returned
    in place of (k, v) when ``return_kv``). Mutually exclusive with
    ``kv_cache``.

    ``wmat_override`` (the multi-LoRA serving hook): a callable
    ``(name, h, w) -> out`` replacing each target projection's
    ``_wmat`` — the batched adapter delta adds there without ever
    materializing a merged weight. Default None keeps every training
    path byte-identical."""
    b, s, e = x.shape
    d = config.head_size
    cdt = config.dtype
    if wmat_override is None:
        def wmat_override(name, hh, ww):
            return _wmat(hh, ww, cdt)
    if norm_scale is None:  # post-norm wiring (OLMo-2): raw residual in;
        h = x               # the caller norms the OUTPUT instead
    else:
        h = _rmsnorm(x, norm_scale, config.rms_norm_eps,
                     getattr(config, "norm_plus_one", False))
    q, k, v = (wmat_override(w, h, attn_params[w])
               for w in ("wq", "wk", "wv"))
    if "bq" in attn_params:  # Qwen2-style QKV biases; shard-local under
        q = q + attn_params["bq"].astype(cdt)  # manual tp (bias carries the
        k = k + attn_params["bk"].astype(cdt)  # same heads/kv logical axis
        v = v + attn_params["bv"].astype(cdt)  # as its matmul output)
    qk_mode = getattr(config, "qk_norm", False)
    if qk_mode == "flat":  # OLMo-2: full-width RMSNorm BEFORE the head
        # reshape; the [hq]/[hkv] scales carry heads/kv logical axes so each
        # member's SCALE shard matches its local width — but the RMS itself
        # is a reduction over the full width, so under manual tp the
        # sum-of-squares must cross the shard boundary (shard-local mean
        # would be silently wrong numerics)
        q = _flat_rmsnorm(q, attn_params["q_norm"], config.rms_norm_eps,
                          tp_axis)
        k = _flat_rmsnorm(k, attn_params["k_norm"], config.rms_norm_eps,
                          tp_axis)
    q = q.reshape(b, s, -1, d)
    k = k.reshape(b, s, -1, d)
    v = v.reshape(b, s, -1, d)
    if qk_mode is True:  # Qwen3: per-head RMSNorm pre-rope; the [head_dim]
        # scale is head-independent, so it is replicated under manual tp
        # (elementwise per head — no collective needed)
        q = _rmsnorm(q, attn_params["q_norm"], config.rms_norm_eps)
        k = _rmsnorm(k, attn_params["k_norm"], config.rms_norm_eps)
    rs = getattr(config, "rope_scaling", None)
    q = apply_rope(q, positions, config.rope_theta, rs,
                   config.max_position_embeddings)
    k = apply_rope(k, positions, config.rope_theta, rs,
                   config.max_position_embeddings)
    window = getattr(config, "sliding_window", None)
    if window_override is not None:  # per-layer pattern (Gemma-2): a traced
        window = window_override     # scalar, already 0 -> "no band" resolved
    attn_scale, softcap = attention_extras(config)
    if attend_override is not None:
        attn, aux = attend_override(q, k, v, window=window, scale=attn_scale,
                                    softcap=softcap)
        out = wmat_override("wo", attn.reshape(b, s, -1), attn_params["wo"])
        if tp_axis is not None:
            out = _psum(out, tp_axis)
        return (out, aux) if return_kv else out
    if kv_cache is not None:
        ck, cv, pos = kv_cache
        k = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, pos, 0, 0))
        v = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, pos, 0, 0))
        kv_pos = jnp.broadcast_to(jnp.arange(ck.shape[1])[None, :],
                                  (b, ck.shape[1]))
        attn = multihead_attention(q, k, v, causal=True, positions=positions,
                                   kv_positions=kv_pos, impl="xla",
                                   standard_layout=False, window=window,
                                   scale=attn_scale, logit_softcap=softcap)
    elif callable(attn_impl):  # e.g. ring attention under context parallelism
        # Trainer-built wrappers (sharded flash, ring, ulysses) declare
        # accepts_window and take the per-call window — uniform bands come
        # through unchanged and traced per-layer schedules (Gemma-2) ride
        # each wrapper's dynamic band plumbing; softcap/scale are baked in
        # by the Trainer factories. Other callables keep the bare contract
        # (Trainer validation rejects them when extras are configured).
        if getattr(attn_impl, "accepts_window", False):
            attn = attn_impl(q, k, v, standard_layout=standard_layout,
                             window=window)
        else:
            attn = attn_impl(q, k, v, standard_layout=standard_layout)
    else:
        attn = multihead_attention(q, k, v, causal=True, positions=positions,
                                   kv_positions=positions, impl=attn_impl,
                                   standard_layout=standard_layout,
                                   window=window, scale=attn_scale,
                                   logit_softcap=softcap)
    out = wmat_override("wo", attn.reshape(b, s, -1), attn_params["wo"])
    if tp_axis is not None:
        out = _psum(out, tp_axis)
    if return_kv:
        return out, (k, v)
    return out


def mlp_sublayer(config, x: jnp.ndarray, layer: dict,
                 tp_axis: Optional[str] = None,
                 wmat_override=None) -> jnp.ndarray:
    """post-attn norm -> gated MLP (residual added by caller). Under
    post-norm wiring (no ``post_attn_norm`` leaf) the raw stream feeds the
    MLP and the caller norms the output."""
    cdt = config.dtype
    if wmat_override is None:
        def wmat_override(name, hh, ww):
            return _wmat(hh, ww, cdt)
    scale = layer.get("post_attn_norm")
    if scale is None:
        h = x
    else:
        h = _rmsnorm(x, scale, config.rms_norm_eps,
                     getattr(config, "norm_plus_one", False))
    gate = wmat_override("gate", h, layer["mlp"]["gate"])
    up = wmat_override("up", h, layer["mlp"]["up"])
    act_fn = ACT_FNS[getattr(config, "act_fn", "silu")]
    # tagged for REMAT_POLICIES["attn_mlp"]: saving the [B,S,I] inner
    # activation skips the gate/up matmul recompute in backward
    act = checkpoint_name(act_fn(gate) * up, "mlp_act")
    down = wmat_override("down", act, layer["mlp"]["down"])
    if tp_axis is not None:  # megatron Rowwise: down-proj partial sums
        down = _psum(down, tp_axis)
    return down


def _block(config: LlamaConfig, x: jnp.ndarray, layer: dict,
           positions: jnp.ndarray, attn_impl: str,
           activation_sharding: Optional[Any] = None,
           standard_layout: bool = True,
           tp_axis: Optional[str] = None,
           window_override=None) -> jnp.ndarray:
    def constrain(y):
        if activation_sharding is not None:
            return jax.lax.with_sharding_constraint(y, activation_sharding)
        return y

    plus_one = getattr(config, "norm_plus_one", False)
    if getattr(config, "post_norm", False):   # OLMo-2 wiring
        attn = attention_sublayer(config, x, layer["attn"], None,
                                  positions, attn_impl, standard_layout,
                                  tp_axis, window_override=window_override)
        x = constrain(x + _rmsnorm(attn, layer["attn_out_norm"],
                                   config.rms_norm_eps, plus_one))
        mlp = mlp_sublayer(config, x, layer, tp_axis)
        return constrain(x + _rmsnorm(mlp, layer["mlp_out_norm"],
                                      config.rms_norm_eps, plus_one))

    if getattr(config, "sandwich_norm", False):   # Gemma-2 wiring: norms on
        # both sides of each sublayer; mlp_sublayer's pre-norm reads the
        # post_attn_norm leaf (HF pre_feedforward_layernorm)
        attn = attention_sublayer(config, x, layer["attn"],
                                  layer["input_norm"], positions, attn_impl,
                                  standard_layout, tp_axis,
                                  window_override=window_override)
        x = constrain(x + _rmsnorm(attn, layer["attn_out_norm"],
                                   config.rms_norm_eps, plus_one))
        mlp = mlp_sublayer(config, x, layer, tp_axis)
        return constrain(x + _rmsnorm(mlp, layer["mlp_out_norm"],
                                      config.rms_norm_eps, plus_one))

    attn = attention_sublayer(config, x, layer["attn"], layer["input_norm"],
                              positions, attn_impl, standard_layout, tp_axis,
                              window_override=window_override)
    x = constrain(x + attn)
    return constrain(x + mlp_sublayer(config, x, layer, tp_axis))


def embed_tokens(config: LlamaConfig, params: dict, input_ids: jnp.ndarray,
                 positions: jnp.ndarray) -> jnp.ndarray:
    """Embedding sub-forward (pipeline stage-0 entry)."""
    del positions  # rope is applied inside blocks
    table = params["embed"]["embedding"]
    if _is_qt(table):  # int8 serve weights: gather rows THEN dequantize —
        # only the looked-up tokens, never the whole table
        x = quantized_take(table, input_ids).astype(config.dtype)
    else:
        x = jnp.take(table, input_ids, axis=0).astype(config.dtype)
    if getattr(config, "scale_embed", False):   # Gemma's sqrt(E) normalizer
        x = x * jnp.asarray(config.hidden_size ** 0.5, config.dtype)
    return x


def output_weights(config: LlamaConfig, params: dict) -> jnp.ndarray:
    """[E, V] output projection (tied or dedicated), in compute dtype."""
    if config.tie_word_embeddings:
        return params["embed"]["embedding"].T.astype(config.dtype)
    return params["lm_head"].astype(config.dtype)


def _output_container(config: LlamaConfig, params: dict):
    """The raw output-projection leaf (tied table or lm_head) plus whether
    the quantized matmul must run in transpose form (tied: blocks tile the
    contracted embed axis)."""
    if config.tie_word_embeddings:
        return params["embed"]["embedding"], True
    return params["lm_head"], False


def tp_embed(config: LlamaConfig, params: dict, input_ids: jnp.ndarray,
             positions: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Stage-0 embedding when tp is a manual axis (pipeline schedule):
    megatron vocab parallelism over the sharded table."""
    del positions  # rope is applied inside blocks
    from ..ops.vocab_parallel import vocab_parallel_embed

    x = vocab_parallel_embed(params["embed"]["embedding"].astype(config.dtype),
                             input_ids, axis)
    if getattr(config, "scale_embed", False):   # Gemma's sqrt(E) normalizer
        x = x * jnp.asarray(config.hidden_size ** 0.5, config.dtype)
    return x


def final_hidden(config: LlamaConfig, params: dict, x: jnp.ndarray) -> jnp.ndarray:
    """Final norm only — pair with ``output_weights`` for chunked losses."""
    return _rmsnorm(x, params["final_norm"], config.rms_norm_eps,
                    getattr(config, "norm_plus_one", False))


def lm_head_logits(config: LlamaConfig, params: dict, x: jnp.ndarray) -> jnp.ndarray:
    """Final norm + output projection (pipeline last-stage exit)."""
    w, transpose = _output_container(config, params)
    if _is_qt(w):  # fp32 accumulate either way; the fp32 [tokens, V]
        # accumulator of the transpose form IS the logits tensor
        logits = quantized_matmul(final_hidden(config, params, x), w,
                                  transpose=transpose)
    else:
        logits = jnp.dot(final_hidden(config, params, x),
                         output_weights(config, params),
                         preferred_element_type=jnp.float32)
    cap = getattr(config, "final_logit_softcap", None)
    if cap:   # Gemma-2 final logit capping
        logits = jnp.tanh(logits / cap) * cap
    return logits


def apply(
    config: LlamaConfig,
    params: dict,
    input_ids: jnp.ndarray,
    positions: Optional[jnp.ndarray] = None,
    *,
    remat: bool = False,
    remat_policy: Optional[Any] = None,
    attn_impl: str = "auto",
    activation_sharding: Optional[Any] = None,
    return_hidden: bool = False,
    layer_schedule=None,
) -> jnp.ndarray:
    """Forward pass -> logits [B, S, V] in float32 (or the final-normed
    hidden states [B, S, E] when ``return_hidden``, for chunked losses).

    ``positions`` must be passed explicitly when the sequence dim is sharded
    (sequence/context parallelism) — same constraint the reference hits at
    ``06-tensor-parallel/train_llm.py:210-212``.
    ``activation_sharding`` optionally constrains the inter-block residual
    stream (e.g. P('dp', 'tp', None) for sequence parallelism).
    ``layer_schedule`` (ops/overlap.py, --overlap-schedule): replaces the
    layer ``lax.scan`` with the explicit latency-hiding schedule — unrolled
    layers, manual per-layer fsdp all-gather/reduce-scatter, per-cell remat
    owned by the schedule (the ``remat``/``remat_policy`` args were baked in
    at schedule build).
    """
    standard_layout = positions is None
    if positions is None:
        positions = jnp.arange(input_ids.shape[1])[None, :]
    positions = jnp.broadcast_to(positions, input_ids.shape)

    x = embed_tokens(config, params, input_ids, positions)

    block = partial(_block, config, positions=positions, attn_impl=attn_impl,
                    activation_sharding=activation_sharding,
                    standard_layout=standard_layout)

    wins = _layer_window_column(config)
    if layer_schedule is not None:
        x = layer_schedule(block, x, params["layers"], wins)
        if return_hidden:
            return final_hidden(config, params, x)
        return lm_head_logits(config, params, x)
    if wins is not None:
        # per-layer sliding-window pattern (Gemma-2 alternates sliding /
        # full): the window rides the scan as a traced per-layer scalar;
        # 0 (= full attention) maps to a band wider than any sequence

        def scan_body(carry, xs):
            layer_params, w = xs
            return block(carry, layer_params, window_override=w), None

        scan_xs = (params["layers"], wins)
    else:
        def scan_body(carry, layer_params):
            return block(carry, layer_params), None

        scan_xs = params["layers"]

    if remat:
        policy = remat_policy or jax.checkpoint_policies.nothing_saveable
        scan_body = jax.checkpoint(scan_body, policy=policy, prevent_cse=False)

    x, _ = jax.lax.scan(scan_body, x, scan_xs)

    if return_hidden:
        return final_hidden(config, params, x)
    return lm_head_logits(config, params, x)


# ---------------------------------------------------------------------------
# KV-cached decode (the sampler's fast path, models/sample.py). Single-device
# utility: the cache is a functional pytree carried through lax.scan over
# layers — each decode step is one compiled program touching one token.
# Training paths are unaffected (separate entry points).
# ---------------------------------------------------------------------------

def _decode_residuals(config, x, layer, attn, wmat_override=None):
    """Shared residual wiring for the prefill/decode bodies (pre-, post-,
    and sandwich-norm variants); returns (new_x, None)."""
    plus_one = getattr(config, "norm_plus_one", False)
    if getattr(config, "post_norm", False) or getattr(config, "sandwich_norm",
                                                      False):
        x = x + _rmsnorm(attn, layer["attn_out_norm"], config.rms_norm_eps,
                         plus_one)
        x = x + _rmsnorm(mlp_sublayer(config, x, layer,
                                      wmat_override=wmat_override),
                         layer["mlp_out_norm"], config.rms_norm_eps, plus_one)
    else:
        x = x + attn
        x = x + mlp_sublayer(config, x, layer, wmat_override=wmat_override)
    return x, None


# ---------------------------------------------------------------------------
# Batched multi-LoRA (serve/adapters.py): the low-rank delta
# ``scale * (x @ A_g) @ B_g`` added per target projection as a RAGGED
# GROUPED GEMM over rows sorted by adapter (S-LoRA arXiv:2311.03285 /
# Punica arXiv:2310.18547 — the MoE dispatch pattern applied to the decode
# batch). The base projection is NEVER merged with the delta into a dense
# ``W + scale*A@B`` weight: over a quantized base the merged tensor does
# not even exist in fp, and per-adapter merges would materialize
# ``[G, in, out]`` copies of every target — the delta stays a separate
# rank-r bottleneck add (HLO-pinned in tests).
# ---------------------------------------------------------------------------

def _lora_sort(adapters, t: int, g: int):
    """The PR-3 dispatch triplet for a ``[S]`` per-slot adapter vector:
    stable sort order, its int32 inversion, and the per-group SORTED-ROW
    counts (slot histogram x the T tokens each slot contributes)."""
    ids = adapters.astype(jnp.int32)
    order = jnp.argsort(ids)
    inv = jnp.argsort(order)
    sizes = jnp.zeros((g,), jnp.int32).at[ids].add(jnp.int32(t))
    return order, inv, sizes


def _lora_wmat_override(config, lora, lstack, sort):
    """Per-layer projection hook: base ``_wmat`` plus the grouped-GEMM
    adapter delta for targets present in ``lstack`` (this layer's
    ``{t: {"a" [G, in, r], "b" [G, r, out]}}`` pool slices). Slot 0's
    rows are zeros, so base-only requests contribute an exact fp ``+0``
    — the adapter-0 == base-engine bitwise identity."""
    from ..ops.grouped_matmul import grouped_matmul

    order, inv, sizes = sort
    cdt = config.dtype
    scale = lora["scale"]
    impl = lora.get("impl", "auto")

    def ov(name, h, w):
        base = _wmat(h, w, cdt)
        pair = lstack.get(name)
        if pair is None:
            return base
        s, t, k = h.shape
        hs = h[order].reshape(s * t, k).astype(jnp.float32)
        d = grouped_matmul(hs, pair["a"], sizes, impl=impl)
        d = grouped_matmul(d, pair["b"], sizes, impl=impl)
        d = d.reshape(s, t, -1)[inv]
        return base + (jnp.float32(scale) * d).astype(base.dtype)

    return ov


def _lora_scan_xs(params, cache, wins, lora):
    """Scan columns for the lora-threaded layer scans: the usual
    (layers, k, v[, wins]) plus each target's per-layer A/B pool slices
    (stacks are ``[L, G, ...]`` — the layer axis leads, like every other
    scanned leaf)."""
    if wins is None:
        return (params["layers"], cache["k"], cache["v"], lora["stacks"])
    return (params["layers"], cache["k"], cache["v"], wins, lora["stacks"])


def _lora_unpack(inputs, wins):
    if wins is None:
        layer, ck, cv, lstack = inputs
        return layer, ck, cv, None, lstack
    return inputs


def _layer_window_column(config):
    """Per-layer window column for the layer scans — training AND decode
    share this one translation (None when uniform; 0 -> a band wider than
    any supported sequence)."""
    lw = getattr(config, "layer_windows", None)
    if not lw:
        return None
    bad = [w for w in lw if w < 0]
    if bad:
        # a window <= 0 reaching the kernels as a traced value would mask
        # every score and return all-zero attention with no error; 0 is the
        # sanctioned "full attention" encoding, anything below is a bug
        raise ValueError(f"layer_windows entries must be >= 0 "
                         f"(0 = full attention); got {bad}")
    return jnp.asarray([w if w else 2 ** 30 for w in lw], jnp.int32)


def _scan_kv_layers(body, x, params, cache, wins):
    """``lax.scan`` the per-layer decode ``body`` over (layer, k, v, window)
    columns — the one adapter shared by every family's prefill/decode scans.
    ``wins`` None (uniform window config) scans without the window column so
    the traced program stays identical to the pre-schedule form."""
    if wins is None:
        return jax.lax.scan(lambda c, inp: body(c, (*inp, None)), x,
                            (params["layers"], cache["k"], cache["v"]))
    return jax.lax.scan(body, x,
                        (params["layers"], cache["k"], cache["v"], wins))


def init_cache(config: LlamaConfig, batch: int, max_len: int) -> dict:
    """Zeroed per-layer KV cache, [L, B, max_len, kv_heads, head_dim]."""
    shape = (config.num_layers, batch, max_len, config.num_kv_heads,
             config.head_size)
    return {"k": jnp.zeros(shape, config.dtype),
            "v": jnp.zeros(shape, config.dtype)}


def prefill(config: LlamaConfig, params: dict, input_ids: jnp.ndarray,
            cache: dict, last_pos=None, lora=None):
    """Causal forward over the prompt, writing each layer's rope'd k/v into
    cache[:, :, :prompt_len]. Returns (logits [B, V] at ``last_pos`` —
    default the final position; the serving engine pads prompts to a bucket
    and passes the real last index as a traced scalar — and the cache).

    ``lora`` (multi-LoRA serving): ``{"scale", "adapters" [B] int32,
    "stacks" {t: {"a" [L, G, in, r], "b" [L, G, r, out]}}, "impl"}`` —
    each example's adapter delta is added per target projection through
    the same grouped-GEMM dispatch the paged step uses (rows = B x P,
    each example's P rows contiguous after the sort)."""
    b, p = input_ids.shape
    positions = jnp.broadcast_to(jnp.arange(p)[None, :], (b, p))
    x = embed_tokens(config, params, input_ids, positions)

    wins = _layer_window_column(config)
    sort = None
    if lora is not None:
        g = jax.tree.leaves(lora["stacks"])[0].shape[1]
        sort = _lora_sort(lora["adapters"], p, g)

    def body(x, inputs):
        if lora is None:
            layer, ck, cv, w = inputs
            ov = None
        else:
            layer, ck, cv, w, lstack = _lora_unpack(inputs, wins)
            ov = _lora_wmat_override(config, lora, lstack, sort)
        attn, (k, v) = attention_sublayer(
            config, x, layer["attn"],
            None if config.post_norm else layer["input_norm"], positions,
            "xla", return_kv=True, window_override=w, wmat_override=ov)
        x, _ = _decode_residuals(config, x, layer, attn, wmat_override=ov)
        nk = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, 0, 0, 0))
        nv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, 0, 0, 0))
        return x, (nk, nv)

    if lora is None:
        x, (ks, vs) = _scan_kv_layers(body, x, params, cache, wins)
    else:
        x, (ks, vs) = jax.lax.scan(body, x,
                                   _lora_scan_xs(params, cache, wins, lora))
    # slice BEFORE the head: projecting all P positions to [B, P, V] fp32
    # only to keep one row would cost P x the lm_head matmul and a
    # prompt-length-scaled logits buffer (norm + projection are per-position)
    x_last = (x[:, -1:] if last_pos is None
              else jax.lax.dynamic_slice_in_dim(x, last_pos, 1, axis=1))
    return (lm_head_logits(config, params, x_last)[:, 0],
            {"k": ks, "v": vs})


def decode_step(config: LlamaConfig, params: dict, token_ids: jnp.ndarray,
                pos, cache: dict):
    """One cached decode step: ``token_ids`` [B, 1] at position ``pos``
    (traced scalar — one compile serves the whole generation). Returns
    (logits [B, V], updated cache)."""
    b = token_ids.shape[0]
    positions = jnp.broadcast_to(jnp.asarray(pos)[None, None], (b, 1))
    x = embed_tokens(config, params, token_ids, positions)

    wins = _layer_window_column(config)

    def body(x, inputs):
        layer, ck, cv, w = inputs
        attn, (nk, nv) = attention_sublayer(
            config, x, layer["attn"],
            None if config.post_norm else layer["input_norm"], positions,
            "xla", kv_cache=(ck, cv, pos), return_kv=True, window_override=w)
        x, _ = _decode_residuals(config, x, layer, attn)
        return x, (nk, nv)

    x, (ks, vs) = _scan_kv_layers(body, x, params, cache, wins)
    return lm_head_logits(config, params, x)[:, -1], {"k": ks, "v": vs}


def paged_positions(token_ids: jnp.ndarray,
                    positions: jnp.ndarray) -> jnp.ndarray:
    """[S, T] absolute positions for a paged decode/chunk call: slot s's
    T tokens sit at ``positions[s] + 0..T-1`` (T == 1 is the decode step,
    T > 1 a prefill chunk). Shared by every family's paged entry point."""
    t = token_ids.shape[1]
    return positions[:, None] + jnp.arange(t, dtype=positions.dtype)[None, :]


def paged_logits_at(lm_head, config, params, x, last_index,
                    all_logits=False):
    """Slice the hidden states at the position whose logits the caller
    wants BEFORE the head projection (same rationale as ``prefill``: never
    project a whole chunk to [S, T, V] fp32 to keep one row). ``None``
    keeps the decode contract — the last position. ``all_logits=True``
    keeps EVERY position ([S, T, V]): the speculative-decoding
    verification forward (serve/engine.py ``verify_for``) needs one
    target distribution per drafted token — T there is the speculation
    depth k+1, not a prompt length, so the full projection is the point,
    not a waste."""
    if all_logits:
        return lm_head(config, params, x)
    x_last = (x[:, -1:] if last_index is None
              else jax.lax.dynamic_slice_in_dim(x, last_index, 1, axis=1))
    return lm_head(config, params, x_last)[:, 0]


def paged_decode_step(config: LlamaConfig, params: dict,
                      token_ids: jnp.ndarray, positions: jnp.ndarray,
                      cache: dict, attend, last_index=None,
                      all_logits=False, lora=None):
    """One step over a PAGED multi-request cache (serve/engine.py):
    ``token_ids`` [S, T] are each slot's next T tokens starting at
    PER-SLOT position ``positions`` [S] (the contiguous-cache
    ``decode_step`` shares one scalar ``pos`` across the batch — useless
    for continuous batching). T == 1 is the batched decode step; T > 1 is
    a chunked-prefill call (S == 1 in practice) whose queries attend over
    the committed history AND the chunk itself — ``last_index`` (traced)
    then selects the real last token's logits out of a padded chunk —
    or a speculative-decoding VERIFICATION step (S slots, T = k+1
    candidates each), which instead passes ``all_logits=True`` for the
    [S, T, V] logits at every position (one target distribution per
    drafted token). ``cache`` holds the page pools ``{"k","v"}:
    [L, n_pages, page, kvh, hd]`` and ``attend(q, k, v, kp, vp, *,
    window, scale, softcap)`` (built by serve/kv_pages.py) scatters the
    new k/v into the layer's pages and attends each slot over its own
    block table. Returns (logits [S, V] — or [S, T, V] under
    ``all_logits`` — and the updated cache).

    ``lora`` (multi-LoRA serving, see ``_lora_wmat_override``):
    ``{"scale", "adapters" [S] int32, "stacks", "impl"}`` — per-slot
    adapter deltas batched as one ragged grouped GEMM per target per
    layer, slots gather-sorted by adapter and int32-inversion unsorted.
    The SAME compiled program serves every adapter mix: the stacks and
    the adapter vector are array arguments, never trace constants."""
    pos2d = paged_positions(token_ids, positions)
    x = embed_tokens(config, params, token_ids, pos2d)

    wins = _layer_window_column(config)
    sort = None
    if lora is not None:
        g = jax.tree.leaves(lora["stacks"])[0].shape[1]
        sort = _lora_sort(lora["adapters"], token_ids.shape[1], g)

    def body(x, inputs):
        if lora is None:
            layer, kp, vp, w = inputs
            ov = None
        else:
            layer, kp, vp, w, lstack = _lora_unpack(inputs, wins)
            ov = _lora_wmat_override(config, lora, lstack, sort)

        def override(q, k, v, *, window, scale, softcap):
            return attend(q, k, v, kp, vp, window=window, scale=scale,
                          softcap=softcap)

        attn, (nkp, nvp) = attention_sublayer(
            config, x, layer["attn"],
            None if config.post_norm else layer["input_norm"], pos2d,
            "xla", return_kv=True, window_override=w,
            attend_override=override, wmat_override=ov)
        x, _ = _decode_residuals(config, x, layer, attn, wmat_override=ov)
        return x, (nkp, nvp)

    if lora is None:
        x, (ks, vs) = _scan_kv_layers(body, x, params, cache, wins)
    else:
        x, (ks, vs) = jax.lax.scan(body, x,
                                   _lora_scan_xs(params, cache, wins, lora))
    return (paged_logits_at(lm_head_logits, config, params, x, last_index,
                            all_logits),
            {"k": ks, "v": vs})


# ---------------------------------------------------------------------------
# Presets (shapes from the public model cards; the reference trains these via
# HF checkpoints — `05-training-llama-405b/README.md`, `06/README.md`).
# ---------------------------------------------------------------------------

# Llama-3.1 / 3.2 cards ship the llama3 band-wise rescale (the checkpoints'
# config.json rope_scaling); the presets carry it so long-context numerics
# match HF out of the box (reference trains these checkpoints through
# AutoModelForCausalLM, 05-training-llama-405b/train_llm.py:74-146)
_LLAMA3_ROPE_8X = freeze_rope_scaling({
    "rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
    "high_freq_factor": 4.0, "original_max_position_embeddings": 8192})
_LLAMA3_ROPE_32X = freeze_rope_scaling({
    "rope_type": "llama3", "factor": 32.0, "low_freq_factor": 1.0,
    "high_freq_factor": 4.0, "original_max_position_embeddings": 8192})

PRESETS = {
    "llama-debug": LlamaConfig(vocab_size=512, hidden_size=64, intermediate_size=128,
                               num_layers=2, num_heads=4, num_kv_heads=2,
                               max_position_embeddings=256),
    "tinyllama-1.1b": LlamaConfig(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                                  num_layers=22, num_heads=32, num_kv_heads=4),
    # single-chip benchmark config: ~650M params, head_dim 128 (MXU/flash
    # friendly), fits params+Adam in fp32 on a 16 GB chip at seq 2048
    "llama-650m": LlamaConfig(vocab_size=32000, hidden_size=1536, intermediate_size=6144,
                              num_layers=16, num_heads=12, num_kv_heads=4,
                              max_position_embeddings=4096),
    # the 1B-class experiment behind tinyllama's 33.6% MFU measurement
    # (BENCH.md): same param count, but 16 heads x 128 where tinyllama runs
    # 32 x 64 — half-width head tiles waste half of every 128x128 MXU pass,
    # so this preset isolates the head-dim lever at 1B scale
    "llama-1b-hd128": LlamaConfig(vocab_size=32000, hidden_size=2048,
                                  intermediate_size=8192, num_layers=16,
                                  num_heads=16, num_kv_heads=4,
                                  max_position_embeddings=4096),
    "llama-3.2-1b": LlamaConfig(vocab_size=128256, hidden_size=2048, intermediate_size=8192,
                                num_layers=16, num_heads=32, num_kv_heads=8,
                                rope_theta=500000.0, max_position_embeddings=131072,
                                rope_scaling=_LLAMA3_ROPE_32X,
                                tie_word_embeddings=True),
    "llama-3.2-3b": LlamaConfig(vocab_size=128256, hidden_size=3072, intermediate_size=8192,
                                num_layers=28, num_heads=24, num_kv_heads=8,
                                rope_theta=500000.0, max_position_embeddings=131072,
                                rope_scaling=_LLAMA3_ROPE_32X,
                                tie_word_embeddings=True),
    "llama-3.1-8b": LlamaConfig(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                                num_layers=32, num_heads=32, num_kv_heads=8,
                                rope_theta=500000.0, max_position_embeddings=131072,
                                rope_scaling=_LLAMA3_ROPE_8X),
    "llama-3.1-70b": LlamaConfig(vocab_size=128256, hidden_size=8192, intermediate_size=28672,
                                 num_layers=80, num_heads=64, num_kv_heads=8,
                                 rope_theta=500000.0, max_position_embeddings=131072,
                                 rope_scaling=_LLAMA3_ROPE_8X),
    "llama-3.1-405b": LlamaConfig(vocab_size=128256, hidden_size=16384, intermediate_size=53248,
                                  num_layers=126, num_heads=128, num_kv_heads=8,
                                  rope_theta=500000.0, max_position_embeddings=131072,
                                  rope_scaling=_LLAMA3_ROPE_8X),
    # Mistral dense is llama-architecture exactly (HF MistralForCausalLM uses
    # the same tensor names/layouts as LlamaForCausalLM); shapes are the
    # v0.3 card (no sliding window, 32768-token vocab)
    "mistral-7b": LlamaConfig(vocab_size=32768, hidden_size=4096, intermediate_size=14336,
                              num_layers=32, num_heads=32, num_kv_heads=8,
                              rope_theta=1e6, max_position_embeddings=32768),
    # Gemma = llama + GeGLU + (1+w) RMSNorm + sqrt(E)-scaled embeddings,
    # explicit head_dim 256, always-tied embeddings (gemma-2b is MQA: kv=1)
    "gemma-2b": LlamaConfig(vocab_size=256000, hidden_size=2048, intermediate_size=16384,
                            num_layers=18, num_heads=8, num_kv_heads=1, head_dim=256,
                            act_fn="gelu_tanh", norm_plus_one=True, scale_embed=True,
                            rms_norm_eps=1e-6, tie_word_embeddings=True,
                            max_position_embeddings=8192),
    "gemma-7b": LlamaConfig(vocab_size=256000, hidden_size=3072, intermediate_size=24576,
                            num_layers=28, num_heads=16, num_kv_heads=16, head_dim=256,
                            act_fn="gelu_tanh", norm_plus_one=True, scale_embed=True,
                            rms_norm_eps=1e-6, tie_word_embeddings=True,
                            max_position_embeddings=8192),
    # Gemma-2 = Gemma + sandwich norms, tanh softcaps (attention 50, final
    # 30), query_pre_attn_scalar score scale, and the alternating
    # sliding/full window pattern (sliding on even layers, window 4096)
    "gemma2-2b": LlamaConfig(vocab_size=256000, hidden_size=2304, intermediate_size=9216,
                             num_layers=26, num_heads=8, num_kv_heads=4, head_dim=256,
                             act_fn="gelu_tanh", norm_plus_one=True, scale_embed=True,
                             sandwich_norm=True, rms_norm_eps=1e-6,
                             tie_word_embeddings=True, attn_logit_softcap=50.0,
                             final_logit_softcap=30.0, query_pre_attn_scalar=256.0,
                             layer_windows=tuple(4096 if i % 2 == 0 else 0
                                                 for i in range(26)),
                             max_position_embeddings=8192),
    "gemma2-9b": LlamaConfig(vocab_size=256000, hidden_size=3584, intermediate_size=14336,
                             num_layers=42, num_heads=16, num_kv_heads=8, head_dim=256,
                             act_fn="gelu_tanh", norm_plus_one=True, scale_embed=True,
                             sandwich_norm=True, rms_norm_eps=1e-6,
                             tie_word_embeddings=True, attn_logit_softcap=50.0,
                             final_logit_softcap=30.0, query_pre_attn_scalar=256.0,
                             layer_windows=tuple(4096 if i % 2 == 0 else 0
                                                 for i in range(42)),
                             max_position_embeddings=8192),
    # Qwen2.5 dense = llama + QKV biases (attn_bias); small cards tie embeddings
    "qwen2.5-0.5b": LlamaConfig(vocab_size=151936, hidden_size=896, intermediate_size=4864,
                                num_layers=24, num_heads=14, num_kv_heads=2,
                                rope_theta=1e6, rms_norm_eps=1e-6, attn_bias=True,
                                tie_word_embeddings=True,
                                max_position_embeddings=32768),
    "qwen2.5-7b": LlamaConfig(vocab_size=152064, hidden_size=3584, intermediate_size=18944,
                              num_layers=28, num_heads=28, num_kv_heads=4,
                              rope_theta=1e6, rms_norm_eps=1e-6, attn_bias=True,
                              max_position_embeddings=32768),
    # Qwen3 dense = llama + per-head q/k RMSNorm (qk_norm) and NO qkv biases;
    # explicit head_dim 128 regardless of hidden/heads (public model cards)
    "qwen3-0.6b": LlamaConfig(vocab_size=151936, hidden_size=1024, intermediate_size=3072,
                              num_layers=28, num_heads=16, num_kv_heads=8,
                              head_dim=128, qk_norm=True, rope_theta=1e6,
                              rms_norm_eps=1e-6, tie_word_embeddings=True,
                              max_position_embeddings=40960),
    "qwen3-8b": LlamaConfig(vocab_size=151936, hidden_size=4096, intermediate_size=12288,
                            num_layers=36, num_heads=32, num_kv_heads=8,
                            head_dim=128, qk_norm=True, rope_theta=1e6,
                            rms_norm_eps=1e-6,
                            max_position_embeddings=40960),
    # OLMo-2 = llama + post-norm block wiring (norms on sublayer outputs)
    # + full-width q/k RMSNorm; MHA (kv == heads), public 1124-7B card
    "olmo2-7b": LlamaConfig(vocab_size=100352, hidden_size=4096, intermediate_size=11008,
                            num_layers=32, num_heads=32, num_kv_heads=32,
                            post_norm=True, qk_norm="flat",
                            rope_theta=500000.0, rms_norm_eps=1e-6,
                            max_position_embeddings=4096),
}
