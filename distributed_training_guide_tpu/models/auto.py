"""AutoModel-style config ingestion: HF ``config.json`` -> a native bundle.

The reference trains *any* HF causal LM via ``AutoModelForCausalLM``
(``01-single-gpu/train_llm.py:57``). The native families here cover thirteen
HF architectures; this module removes the remaining friction — needing a
registry preset for every size variant. ``-m hf:<dir>`` (or
``get_model("hf:<dir>")``) reads the checkpoint's own ``config.json``,
recognizes the architecture, and builds the exact family config — so any
Llama/Mistral/Qwen2/Qwen3/Gemma/Gemma-2/Phi-3/OLMo-2/GPT-2/Mixtral/
Qwen2-MoE/Qwen3-MoE/GPT-NeoX(Pythia) checkpoint trains (and converts, ``models/hf_convert.py``) without touching the
registry:

    python convert_llama.py <hf-dir> <conv> hf:<hf-dir>
    python train_llm.py -m hf:<hf-dir> --pretrained <conv> ...

Unsupported architectures fail loudly with the supported list.
"""
from __future__ import annotations

import json
from pathlib import Path


def _sliding_window_kw(cfg: dict, arch: str) -> dict:
    """``sliding_window`` from an HF config dict. Qwen2-style configs gate
    it behind ``use_sliding_window`` (default False — the key is present on
    every Qwen2 config but usually inert); everywhere else a non-null value
    is live. Values >= max_position are dropped (the band never binds).

    Qwen2/Qwen3 additionally keep the FIRST ``max_window_layers`` layers on
    FULL attention (sliding only afterwards): that mixed pattern maps onto
    ``layer_windows`` — the per-layer window column Gemma-2's alternating
    scheme rides — instead of the uniform ``sliding_window``."""
    window = cfg.get("sliding_window")
    if not window:
        return {}
    if window >= cfg.get("max_position_embeddings", 4096):
        return {}
    if arch in ("Qwen2ForCausalLM", "Qwen3ForCausalLM",
                "Qwen2MoeForCausalLM", "Qwen3MoeForCausalLM"):
        # the MoE flavors gate identically — HF Qwen2MoeConfig ships
        # sliding_window=4096 with use_sliding_window=False by default, and
        # treating that inert key as live would band every layer silently
        if not cfg.get("use_sliding_window"):
            return {}
        n = cfg["num_hidden_layers"]
        mwl = cfg.get("max_window_layers", n)
        if mwl and mwl < n:
            return {"layer_windows": tuple(
                0 if i < mwl else int(window) for i in range(n))}
    return {"sliding_window": int(window)}


def _rope_scaling_kw(cfg: dict, arch: str) -> dict:
    """Frozen ``rope_scaling`` kwargs from an HF config dict, validated at
    ingestion (an unsupported rope type must fail HERE, loudly, not produce
    silently-divergent logits). All six HF rope types are implemented
    (``ops/rope.py``); Phi-3-style configs keep
    ``original_max_position_embeddings`` at the top level, so fold it into
    the dict where longrope's short/long switch needs it."""
    from ..ops.rope import ROPE_TYPES, freeze_rope_scaling, rope_type_of

    scaling = cfg.get("rope_scaling")
    if not scaling:
        return {}
    rope_type = rope_type_of(scaling)
    if rope_type not in ROPE_TYPES:
        raise ValueError(f"{arch}: unsupported rope_scaling type "
                         f"{rope_type!r} (supported: {ROPE_TYPES})")
    scaling = dict(scaling)
    if ("original_max_position_embeddings" not in scaling
            and cfg.get("original_max_position_embeddings")):
        scaling["original_max_position_embeddings"] = (
            cfg["original_max_position_embeddings"])
    return {"rope_scaling": freeze_rope_scaling(scaling)}


def _llama_kwargs(cfg: dict) -> dict:
    kw = dict(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["hidden_size"],
        intermediate_size=cfg["intermediate_size"],
        num_layers=cfg["num_hidden_layers"],
        num_heads=cfg["num_attention_heads"],
        num_kv_heads=cfg.get("num_key_value_heads",
                             cfg["num_attention_heads"]),
        max_position_embeddings=cfg.get("max_position_embeddings", 4096),
        rope_theta=cfg.get("rope_theta", 10000.0),
        rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
        tie_word_embeddings=cfg.get("tie_word_embeddings", False),
    )
    if cfg.get("head_dim"):
        kw["head_dim"] = cfg["head_dim"]
    kw.update(_rope_scaling_kw(cfg, cfg.get("architectures", ["?"])[0]))
    return kw


_HF_ACTS = {"silu": "silu", "gelu_pytorch_tanh": "gelu_tanh",
            "gelu_tanh": "gelu_tanh"}   # exact 'gelu' is NOT implemented


def _build_llama(cfg: dict, arch: str):
    from .llama import LlamaConfig

    kw = _llama_kwargs(cfg)
    kw.update(_sliding_window_kw(cfg, arch))
    if arch == "Qwen2ForCausalLM":
        # default True: older Qwen2 configs omit the key because bias was
        # unconditional
        kw["attn_bias"] = cfg.get("attention_bias", True)
    else:
        kw["attn_bias"] = cfg.get("attention_bias", False)
    if arch == "Qwen3ForCausalLM":  # per-head q/k RMSNorm, always on
        kw["qk_norm"] = True
    if arch == "Olmo2ForCausalLM":
        # OLMo-2: post-norm block wiring (norms on sublayer OUTPUTS) and
        # FULL-WIDTH q/k RMSNorm applied before the head reshape
        kw.update(post_norm=True, qk_norm="flat")
    act = cfg.get("hidden_act", "silu")
    if arch == "GemmaForCausalLM":
        kw.update(norm_plus_one=True, scale_embed=True,
                  tie_word_embeddings=True)
        act = "gelu_pytorch_tanh"   # HF applies tanh-gelu whatever the key says
    if arch == "Gemma2ForCausalLM":
        # Gemma-2 = Gemma + sandwich norms (both sides of each sublayer),
        # tanh softcapping of attention scores and final logits, a score
        # scale from query_pre_attn_scalar, and an ALTERNATING per-layer
        # sliding-window pattern — the global sliding_window key is replaced
        # by layer_windows (0 = full attention on that layer)
        kw.pop("sliding_window", None)
        kw.update(norm_plus_one=True, scale_embed=True, sandwich_norm=True,
                  tie_word_embeddings=True,
                  attn_logit_softcap=cfg.get("attn_logit_softcapping"),
                  final_logit_softcap=cfg.get("final_logit_softcapping"),
                  query_pre_attn_scalar=cfg.get("query_pre_attn_scalar"))
        act = "gelu_pytorch_tanh"
        w = cfg.get("sliding_window")
        if w and w < cfg.get("max_position_embeddings", 8192):
            lt = cfg.get("layer_types")
            if lt:
                pattern = tuple(w if t == "sliding_attention" else 0
                                for t in lt)
            else:  # pre-layer_types configs: sliding on even layers
                pattern = tuple(w if i % 2 == 0 else 0
                                for i in range(cfg["num_hidden_layers"]))
            if any(pattern):
                kw["layer_windows"] = pattern
    if act not in _HF_ACTS:
        raise ValueError(f"{arch}: unsupported hidden_act {act!r} "
                         f"(supported: {sorted(_HF_ACTS)})")
    kw["act_fn"] = _HF_ACTS[act]
    return LlamaConfig(**kw)


def _build_gpt2(cfg: dict, arch: str):
    from .gpt2 import GPT2Config

    return GPT2Config(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["n_embd"],
        num_layers=cfg["n_layer"],
        num_heads=cfg["n_head"],
        max_position_embeddings=cfg.get("n_positions", 1024),
        layer_norm_eps=cfg.get("layer_norm_epsilon", 1e-5),
    )


def _build_mixtral(cfg: dict, arch: str):
    from .moe import MoELlamaConfig

    kw = dict(
        num_experts=cfg["num_local_experts"],
        experts_per_token=cfg["num_experts_per_tok"],
        **_llama_kwargs(cfg),
        **_sliding_window_kw(cfg, arch),
    )
    _reject_moe_layer_windows(kw, arch)
    if "router_aux_loss_coef" in cfg:   # HF Mixtral ships 0.02, not our 0.01
        kw["router_aux_coef"] = cfg["router_aux_loss_coef"]
    return MoELlamaConfig(**kw)


def _reject_moe_layer_windows(kw: dict, arch: str) -> None:
    if kw.pop("layer_windows", None) is not None:
        # the moe family's layer scan doesn't thread the per-layer window
        # column (dense llama does) — refuse rather than band every layer
        raise ValueError(
            f"{arch}: mixed full/sliding layer patterns (max_window_layers) "
            f"are not implemented for the MoE family; use a uniform-window "
            f"or windowless checkpoint")


def _build_qwen2_moe(cfg: dict, arch: str):
    from .moe import MoELlamaConfig

    if cfg.get("mlp_only_layers") or cfg.get("decoder_sparse_step", 1) != 1:
        raise ValueError(
            f"{arch}: mlp_only_layers={cfg.get('mlp_only_layers')} / "
            f"decoder_sparse_step={cfg.get('decoder_sparse_step')} mixes "
            f"dense and MoE layers, which this family does not implement "
            f"(uniform MoE blocks only)")
    kw = dict(
        num_experts=cfg["num_experts"],
        experts_per_token=cfg["num_experts_per_tok"],
        **_llama_kwargs(cfg),
        **_sliding_window_kw(cfg, arch),
    )
    _reject_moe_layer_windows(kw, arch)
    kw["intermediate_size"] = cfg["moe_intermediate_size"]
    kw["shared_expert_intermediate"] = cfg["shared_expert_intermediate_size"]
    kw["attn_bias"] = True                    # Qwen2 attention (QKV biases)
    kw["norm_topk_prob"] = cfg.get("norm_topk_prob", False)
    if "router_aux_loss_coef" in cfg:
        kw["router_aux_coef"] = cfg["router_aux_loss_coef"]
    return MoELlamaConfig(**kw)


def _build_qwen3_moe(cfg: dict, arch: str):
    from .moe import MoELlamaConfig

    if cfg.get("mlp_only_layers") or cfg.get("decoder_sparse_step", 1) != 1:
        # dense layers interleaved among MoE layers break the uniform
        # scan-over-layers block — fail loudly, don't silently route
        # everything through experts
        raise ValueError(
            f"{arch}: mlp_only_layers={cfg.get('mlp_only_layers')} / "
            f"decoder_sparse_step={cfg.get('decoder_sparse_step')} mixes "
            f"dense and MoE layers, which this family does not implement "
            f"(uniform MoE blocks only)")
    kw = dict(
        num_experts=cfg["num_experts"],
        experts_per_token=cfg["num_experts_per_tok"],
        **_llama_kwargs(cfg),
        **_sliding_window_kw(cfg, arch),
    )
    _reject_moe_layer_windows(kw, arch)
    # the per-expert FFN width is moe_intermediate_size (plain
    # intermediate_size is the dense-MLP width of the mlp_only_layers we
    # just rejected)
    kw["intermediate_size"] = cfg["moe_intermediate_size"]
    kw["qk_norm"] = True                      # Qwen3 attention
    kw["norm_topk_prob"] = cfg.get("norm_topk_prob", False)
    if "router_aux_loss_coef" in cfg:
        kw["router_aux_coef"] = cfg["router_aux_loss_coef"]
    return MoELlamaConfig(**kw)


def _build_neox(cfg: dict, arch: str):
    from .neox import NeoXConfig

    if cfg.get("tie_word_embeddings"):
        # the native NeoX family keeps embed_in/embed_out untied (every
        # public NeoX/Pythia card unties); a tied checkpoint would otherwise
        # surface as a confusing missing-embed_out error at LOAD time
        raise ValueError(
            f"{arch}: tie_word_embeddings=true is not supported by the "
            f"NeoX family (embed_out is a separate tensor here); untie the "
            f"checkpoint or export embed_out explicitly")
    act = cfg.get("hidden_act", "gelu")
    acts = {"gelu": "gelu", "gelu_new": "gelu_tanh",
            "gelu_pytorch_tanh": "gelu_tanh"}
    if act not in acts:
        raise ValueError(f"{arch}: unsupported hidden_act {act!r} "
                         f"(supported: {sorted(acts)})")
    return NeoXConfig(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["hidden_size"],
        intermediate_size=cfg["intermediate_size"],
        num_layers=cfg["num_hidden_layers"],
        num_heads=cfg["num_attention_heads"],
        max_position_embeddings=cfg.get("max_position_embeddings", 2048),
        rotary_pct=cfg.get("rotary_pct", 0.25),
        rope_theta=cfg.get("rotary_emb_base", 10000.0),
        layer_norm_eps=cfg.get("layer_norm_eps", 1e-5),
        use_parallel_residual=cfg.get("use_parallel_residual", True),
        act_fn=acts[act],
        **_rope_scaling_kw(cfg, arch),
    )


_ARCH_BUILDERS = {
    "LlamaForCausalLM": ("llama", _build_llama),
    "MistralForCausalLM": ("llama", _build_llama),
    "Qwen2ForCausalLM": ("llama", _build_llama),
    "Qwen3ForCausalLM": ("llama", _build_llama),
    "Olmo2ForCausalLM": ("llama", _build_llama),
    "GemmaForCausalLM": ("llama", _build_llama),
    "Gemma2ForCausalLM": ("llama", _build_llama),
    "GPT2LMHeadModel": ("gpt2", _build_gpt2),
    "MixtralForCausalLM": ("moe", _build_mixtral),
    "Qwen2MoeForCausalLM": ("moe", _build_qwen2_moe),
    "Qwen3MoeForCausalLM": ("moe", _build_qwen3_moe),
    "GPTNeoXForCausalLM": ("neox", _build_neox),
    # Phi-3 is llama-math with fused checkpoint tensors (qkv_proj,
    # gate_up_proj) — the conversion splits them (hf_convert._make_map_llama);
    # its longrope rope_scaling and sliding_window both map onto the native
    # config fields (ops/rope.py; flash kernel SWA)
    "Phi3ForCausalLM": ("llama", _build_llama),
}


def config_from_hf(config_path: str | Path):
    """(family, config) from an HF checkpoint dir or config.json path."""
    path = Path(config_path)
    if path.is_dir():
        path = path / "config.json"
    with open(path) as fp:
        cfg = json.load(fp)
    archs = cfg.get("architectures") or []
    arch = archs[0] if archs else cfg.get("model_type", "?")
    # accept model_type ONLY when architectures is absent (config-only
    # exports) — a present-but-unsupported arch (e.g. a classification
    # head) must hit the loud failure, not get remapped to causal LM
    by_type = {"llama": "LlamaForCausalLM", "mistral": "MistralForCausalLM",
               "qwen2": "Qwen2ForCausalLM", "qwen3": "Qwen3ForCausalLM",
               "gemma": "GemmaForCausalLM", "gemma2": "Gemma2ForCausalLM",
               "olmo2": "Olmo2ForCausalLM",
               "gpt2": "GPT2LMHeadModel", "mixtral": "MixtralForCausalLM",
               "qwen2_moe": "Qwen2MoeForCausalLM",
               "qwen3_moe": "Qwen3MoeForCausalLM",
               "gpt_neox": "GPTNeoXForCausalLM", "phi3": "Phi3ForCausalLM"}
    if not archs and cfg.get("model_type") in by_type:
        arch = by_type[cfg["model_type"]]
    if arch not in _ARCH_BUILDERS:
        raise ValueError(
            f"unsupported architecture {arch!r} in {path}; supported: "
            f"{', '.join(sorted(_ARCH_BUILDERS))}")
    family, build = _ARCH_BUILDERS[arch]
    return family, build(cfg, arch)
