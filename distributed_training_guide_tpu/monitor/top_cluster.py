"""Cluster-wide device monitor.

Parity with the reference's ``top-cluster.py`` (ssh + nvidia-smi poll,
``top-cluster.py:16-94``; hang heuristic = power-draw drop,
``diagnosing-errors/README.md:7-19``): poll every host for per-chip HBM usage
and an activity proxy, aggregate per node + cluster. TPU runtimes don't expose
power per chip the way nvidia-smi does; the analogous stall signal is
duty-cycle / HBM churn — we report bytes_in_use and peak since last poll from
``jax.local_devices()[i].memory_stats()``.

Modes:
  --local            one-shot stats for this host (also the ssh payload)
  --hosts FILE       poll each host over ssh every --interval seconds
"""
from __future__ import annotations

import argparse
import json
import subprocess
import time


def local_stats() -> dict:
    import jax

    devs = []
    for d in jax.local_devices():
        s = d.memory_stats() or {}
        devs.append({
            "id": d.id,
            "kind": getattr(d, "device_kind", d.platform),
            "hbm_gb": round(1e-9 * s.get("bytes_in_use", 0), 2),
            "hbm_peak_gb": round(1e-9 * s.get("peak_bytes_in_use", 0), 2),
            "hbm_limit_gb": round(1e-9 * s.get("bytes_limit", 0), 2),
        })
    return {"host": __import__("os").uname().nodename, "devices": devs}


def poll_host(host: str, timeout: float = 20.0) -> dict:
    cmd = ["ssh", "-o", "ConnectTimeout=5", host,
           "python -m distributed_training_guide_tpu.monitor.top_cluster --local"]
    try:
        out = subprocess.run(cmd, capture_output=True, timeout=timeout, text=True)
        return json.loads(out.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001
        return {"host": host, "error": str(e)}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--local", action="store_true")
    parser.add_argument("--hosts", default=None, help="file with one host per line")
    parser.add_argument("--interval", type=float, default=10.0)
    args = parser.parse_args()

    if args.local or not args.hosts:
        print(json.dumps(local_stats()))
        return

    hosts = [h.strip() for h in open(args.hosts) if h.strip()]
    while True:
        t0 = time.time()
        total_used = total_limit = n_dev = n_err = 0
        for host in hosts:
            stats = poll_host(host)
            if "error" in stats:
                n_err += 1
                print(f"{host:<24} ERROR {stats['error']}")
                continue
            used = sum(d["hbm_gb"] for d in stats["devices"])
            limit = sum(d["hbm_limit_gb"] for d in stats["devices"])
            total_used += used
            total_limit += limit
            n_dev += len(stats["devices"])
            print(f"{host:<24} {len(stats['devices'])} chips  "
                  f"hbm {used:7.1f}/{limit:7.1f} GB")
        print(f"{'CLUSTER':<24} {n_dev} chips  hbm {total_used:7.1f}/"
              f"{total_limit:7.1f} GB  unreachable={n_err}\n")
        time.sleep(max(0.0, args.interval - (time.time() - t0)))


if __name__ == "__main__":
    main()
