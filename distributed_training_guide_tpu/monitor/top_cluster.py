"""Cluster-wide device monitor with activity + hang detection.

Parity with the reference's ``top-cluster.py`` (ssh + nvidia-smi poll,
``top-cluster.py:16-94``) including its *hang heuristic*: the reference
watches power draw and calls a node wedged when power drops while a job is
resident (``diagnosing-errors/README.md:7-19``). TPU runtimes don't expose
per-chip power the way nvidia-smi does; the analogous activity signal here is
**allocator churn** — ``memory_stats()``'s ``num_allocs``/``bytes_in_use``
counters move every step while a training job is making progress, and freeze
when a collective deadlocks or the runtime wedges (memory stays *resident*,
so HBM alone cannot distinguish busy from hung — exactly why the reference
uses power, not memory).

Each poll computes a per-host activity signature; ``--alert-after N``
(default 3) consecutive identical signatures on a host with resident memory
raises a STALLED alert on that row and in the cluster summary line.

Modes:
  --local            one-shot stats for this host (also the ssh payload)
  --hosts FILE       poll each host over ssh every --interval seconds
"""
from __future__ import annotations

import argparse
import json
import subprocess
import time


def local_stats() -> dict:
    import jax

    devs = []
    for d in jax.local_devices():
        s = d.memory_stats() or {}
        devs.append({
            "id": d.id,
            "kind": getattr(d, "device_kind", d.platform),
            "hbm_gb": round(1e-9 * s.get("bytes_in_use", 0), 2),
            "hbm_peak_gb": round(1e-9 * s.get("peak_bytes_in_use", 0), 2),
            "hbm_limit_gb": round(1e-9 * s.get("bytes_limit", 0), 2),
            "num_allocs": s.get("num_allocs", 0),
        })
    return {"host": __import__("os").uname().nodename, "devices": devs}


def poll_host(host: str, timeout: float = 20.0) -> dict:
    cmd = ["ssh", "-o", "ConnectTimeout=5", host,
           "python -m distributed_training_guide_tpu.monitor.top_cluster --local"]
    try:
        out = subprocess.run(cmd, capture_output=True, timeout=timeout, text=True)
        return json.loads(out.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001
        return {"host": host, "error": str(e)}


class ClusterWatch:
    """Per-host activity tracking + stall detection (pure logic — the ssh
    polling loop feeds it, and the unit tests feed it fake hosts)."""

    def __init__(self, alert_after: int = 3):
        self.alert_after = alert_after
        self._last_sig: dict = {}
        self._static_polls: dict = {}

    def update(self, stats: dict) -> dict:
        """Digest one host's poll result -> row dict with keys host, status
        (ok | idle | stalled | error), hbm_gb, hbm_limit_gb, static_polls."""
        host = stats.get("host", "?")
        if "error" in stats:
            return {"host": host, "status": "error", "error": stats["error"]}
        used = sum(d["hbm_gb"] for d in stats["devices"])
        limit = sum(d["hbm_limit_gb"] for d in stats["devices"])
        sig = tuple((d["id"], d["num_allocs"], d["hbm_gb"], d["hbm_peak_gb"])
                    for d in stats["devices"])
        if self._last_sig.get(host) == sig:
            self._static_polls[host] = self._static_polls.get(host, 0) + 1
        else:
            self._static_polls[host] = 0
        self._last_sig[host] = sig

        static = self._static_polls[host]
        resident = used > 0.05  # a job's arrays are on the chips
        if resident and static >= self.alert_after:
            status = "stalled"
        elif static >= self.alert_after:
            status = "idle"
        else:
            status = "ok"
        return {"host": host, "status": status, "hbm_gb": used,
                "hbm_limit_gb": limit, "n_devices": len(stats["devices"]),
                "static_polls": static}


def format_row(row: dict) -> str:
    if row["status"] == "error":
        return f"{row['host']:<24} ERROR {row['error']}"
    line = (f"{row['host']:<24} {row['n_devices']} chips  "
            f"hbm {row['hbm_gb']:7.1f}/{row['hbm_limit_gb']:7.1f} GB")
    if row["status"] == "stalled":
        line += (f"  *** STALLED? no allocator activity for "
                 f"{row['static_polls']} polls (see diagnosing-errors/) ***")
    elif row["status"] == "idle":
        line += "  (idle)"
    return line


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--local", action="store_true")
    parser.add_argument("--hosts", default=None, help="file with one host per line")
    parser.add_argument("--interval", type=float, default=10.0)
    parser.add_argument("--alert-after", type=int, default=3,
                        help="polls without allocator activity before a "
                             "resident host is flagged STALLED")
    args = parser.parse_args()

    if args.local or not args.hosts:
        print(json.dumps(local_stats()))
        return

    hosts = [h.strip() for h in open(args.hosts) if h.strip()]
    watch = ClusterWatch(alert_after=args.alert_after)
    while True:
        t0 = time.time()
        total_used = total_limit = n_dev = n_err = n_stalled = 0
        for host in hosts:
            row = watch.update(poll_host(host))
            print(format_row(row))
            if row["status"] == "error":
                n_err += 1
                continue
            total_used += row["hbm_gb"]
            total_limit += row["hbm_limit_gb"]
            n_dev += row["n_devices"]
            n_stalled += row["status"] == "stalled"
        print(f"{'CLUSTER':<24} {n_dev} chips  hbm {total_used:7.1f}/"
              f"{total_limit:7.1f} GB  stalled={n_stalled} unreachable={n_err}\n")
        time.sleep(max(0.0, args.interval - (time.time() - t0)))


if __name__ == "__main__":
    main()
