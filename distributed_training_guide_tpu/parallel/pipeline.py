"""Pipeline parallelism: GPipe schedule over the ``pp`` mesh axis.

The reference has no pipeline parallelism (mentioned only as Llama-405B-paper
context, ``06-tensor-parallel/README.md:8``). The TPU build adds it as a
first-class axis, the shard_map way:

- the *stacked layer dimension* of every per-layer parameter is sharded over
  ``pp`` — stage s owns layers [s*L/pp, (s+1)*L/pp); embedding/head params
  are replicated across pp (their grads psum automatically through the
  shard_map transpose);
- the step runs a GPipe fill/drain schedule over T = M + pp - 1 ticks for M
  microbatches: each tick, every stage runs its layer slice on its resident
  activation, then hands the result to the next stage via ``ppermute``
  (neighbor ICI hop). Stage 0 injects the next microbatch's embeddings; the
  last stage computes head+loss under ``lax.cond`` (no wasted head matmuls on
  other stages);
- the wrapper is a *partial-manual* ``shard_map``: only ``pp`` is manual —
  dp/fsdp/tp/cp stay with GSPMD inside the stage, so pipeline composes with
  every other plan by rules-table union;
- backward is plain ``jax.grad`` through the schedule (ppermute transposes to
  the reverse permute), with optional per-tick remat.

Bubble fraction is (pp-1)/(M+pp-1) — choose microbatches >= 2*pp to keep it
under a third. 1F1B/interleaved schedules are the round-2 refinement.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.cross_entropy import causal_lm_loss


def _family_module(family: str):
    from ..models.registry import family_module

    return family_module(family)


def param_pipeline_specs(logical_axes_tree):
    """shard_map in_specs for params: layer-stacked leaves are manual over pp
    on their leading dim, everything else is replicated across pp."""
    def spec(ax):
        return P("pp") if ax and ax[0] == "layers" else P()

    return jax.tree.map(spec, logical_axes_tree,
                        is_leaf=lambda x: isinstance(x, tuple))


def make_pipeline_loss(
    bundle,
    plan,
    *,
    microbatches: Optional[int] = None,
    remat: bool = False,
    remat_policy=None,
    attn_impl: str = "auto",
    loss_fn: Callable = causal_lm_loss,
) -> Callable:
    """Returns loss(params, batch) running the GPipe schedule over plan.mesh's
    pp axis. batch: {'input_ids','labels'} of shape [B, S]; B must divide by
    microbatches, and B//microbatches by the data-axes size."""
    mesh = plan.mesh
    pp = mesh.shape["pp"]
    if mesh.shape["cp"] > 1:
        raise NotImplementedError("pp x cp composition is not supported yet")
    if mesh.shape["tp"] > 1 and mesh.shape["dp"] * mesh.shape["fsdp"] > 1:
        # XLA's SPMD partitioner hits a CHECK (spmd_partitioner_util.cc:495,
        # ExpandDeviceGroupsWithIota) when auto tp collectives run under a
        # manual-pp shard_map alongside a third nontrivial axis. pp x tp alone
        # and pp x (dp/fsdp) alone both work.
        raise NotImplementedError(
            "pp x tp currently requires dp == fsdp == 1 (XLA partitioner "
            "limitation); use pp x fsdp, or a pure pp x tp submesh")
    cfg = bundle.config
    mod = _family_module(bundle.family)
    n_layers = cfg.num_layers
    if n_layers % pp != 0:
        raise ValueError(f"num_layers={n_layers} not divisible by pp={pp}")
    M = microbatches or 2 * pp

    def stage_fn(layers_local, x, positions):
        block = functools.partial(mod._block, cfg, positions=positions,
                                  attn_impl=attn_impl)

        def body(carry, layer_params):
            return block(carry, layer_params), None

        if remat:
            body = jax.checkpoint(
                body, prevent_cse=False,
                policy=remat_policy or jax.checkpoint_policies.nothing_saveable)
        x, _ = jax.lax.scan(body, x, layers_local)
        return x

    def pp_body(params, ids_mb, labels_mb):
        # ids_mb/labels_mb: [M, mb, S]
        s = jax.lax.axis_index("pp")
        mb, seq = ids_mb.shape[1], ids_mb.shape[2]
        positions = jnp.broadcast_to(jnp.arange(seq)[None, :], (mb, seq))
        perm = [(i, i + 1) for i in range(pp - 1)]

        buf = jnp.zeros((mb, seq, cfg.hidden_size), cfg.dtype)
        loss_acc = jnp.zeros((), jnp.float32)

        for t in range(M + pp - 1):
            x0 = mod.embed_tokens(cfg, params, ids_mb[min(t, M - 1)], positions)
            is_first = (s == 0) & (t < M)
            x_in = jnp.where(is_first, x0, buf)
            y = stage_fn(params["layers"], x_in, positions)

            out_idx = t - (pp - 1)
            if 0 <= out_idx < M:  # static: drain ticks only
                # computed on every stage, masked to the last: the head may
                # contain auto-axis (fsdp/tp) collectives, and those must be
                # executed uniformly across pp ranks (lax.cond on a
                # pp-dependent predicate would diverge the comm pattern)
                logits = mod.lm_head_logits(cfg, params, y)
                mb_loss = loss_fn(logits, labels_mb[out_idx]).astype(jnp.float32)
                loss_acc = loss_acc + jnp.where(s == pp - 1, mb_loss, 0.0)
            if t < M + pp - 2:
                buf = jax.lax.ppermute(y, "pp", perm)

        return jax.lax.psum(loss_acc, "pp") / M

    param_specs = param_pipeline_specs(bundle.param_logical_axes(cfg))
    sharded = jax.shard_map(
        pp_body, mesh=mesh,
        in_specs=(param_specs, P(), P()),
        out_specs=P(),
        axis_names={"pp"},
        check_vma=False,
    )

    from jax.sharding import NamedSharding

    mb_sharding = NamedSharding(mesh, P(None, plan.data_axes, None))
    data_size = plan.data_parallel_size

    def loss(params, batch):
        ids = batch["input_ids"]
        labels = batch["labels"]
        b, seq = ids.shape
        if b % M != 0:
            raise ValueError(f"global batch {b} not divisible by microbatches={M}")
        if (b // M) % data_size != 0:
            raise ValueError(
                f"microbatch size {b // M} not divisible by data-parallel size "
                f"{data_size}; raise the batch or lower pp_microbatches")
        # keep each microbatch's batch dim sharded over the data axes — the
        # reshape would otherwise let GSPMD shard the scanned M dim
        ids_mb = jax.lax.with_sharding_constraint(
            ids.reshape(M, b // M, seq), mb_sharding)
        labels_mb = jax.lax.with_sharding_constraint(
            labels.reshape(M, b // M, seq), mb_sharding)
        return sharded(params, ids_mb, labels_mb)

    return loss
