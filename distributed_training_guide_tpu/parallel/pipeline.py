"""Pipeline parallelism: 1F1B schedule over the ``pp`` mesh axis.

The reference has no pipeline parallelism (mentioned only as Llama-405B-paper
context, ``06-tensor-parallel/README.md:8``). The TPU build adds it as a
first-class axis, the shard_map way:

- the *stacked layer dimension* of every per-layer parameter is sharded over
  ``pp`` — stage s owns layers [s*L/pp, (s+1)*L/pp);
- ``tp`` is a second *manual* axis inside the same shard_map: layer weights
  arrive as megatron head/mlp shards with explicit psums in the block
  (``models/llama.py``), the embedding table and output projection are
  vocab-sharded (``ops/vocab_parallel.py``), and the loss is the
  vocab-parallel cross-entropy — so pp x tp composes freely with dp/fsdp,
  which stay auto (GSPMD) inside the stage. (Round 1 kept tp auto and hit an
  XLA SPMD partitioner CHECK, spmd_partitioner_util.cc:495, whenever
  manual-pp + auto-tp met a third nontrivial axis.)
- the schedule is 1F1B-style, *hand-differentiated*: the program interleaves
  one forward tick and one backward tick per slot, passing activations
  downstream and cotangents upstream via ``ppermute`` and recomputing each
  stage's forward inside ``jax.vjp`` from a saved stage-input ring buffer
  (depth 2*pp-1, independent of the microbatch count M). Peak activation
  memory is O(pp) stage inputs instead of GPipe's O(M), and embedding / head
  + loss run under ``lax.cond`` on stage 0 / the last stage only — no wasted
  head matmuls on other stages (jax.grad over a GPipe loop cannot express
  either property: it stores every tick's residuals and reverses strictly).
  Bubble (fill/drain) ticks are also ``lax.cond``-skipped in both directions:
  in a masked-SPMD schedule the bubble would otherwise be *real* FLOPs on
  garbage activations rather than idle time.

Bubble fraction stays (pp-1)/(M+pp-1) — choose microbatches >= 2*pp to keep
it under a third.

Flash attention inside the pipeline: the batch-manual shard_map that makes
the Pallas kernel partition under pure-GSPMD plans
(``ops/flash_attention.make_sharded_flash_attention``) nests inside this
pp-manual region as a dp/fsdp-manual sub-region — it is built at trace
time against the context mesh (whose pp/tp axes are already Manual), so
the kernel runs on local batch shards instead of the partitioner's
gather-and-replicate fallback. Heads arrive pre-sharded as manual megatron
shards, so the nested wrapper declares only the batch axes
(``train/step.py`` passes ``head_axis=None`` under pp).

Context parallelism composes the same way: with cp > 1 the Trainer passes
the ring or Ulysses attention callable, whose cp(+batch)-manual shard_map
nests inside this region too (cp is auto here). The microbatch sequence
dim stays cp-sharded through the schedule; embedding, norms, and MLP are
pointwise over sequence, so only attention pays the cp collectives —
exactly as outside the pipeline.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.cross_entropy import causal_lm_loss
from ..ops.vocab_parallel import vocab_parallel_causal_lm_loss


def _family_module(family: str):
    from ..models.registry import family_module

    return family_module(family)


def _manual_spec(logical_axes: tuple, rules: dict) -> P:
    """Manual-axes PartitionSpec for one param leaf: 'layers' is manual over
    pp, tp-mapped logical axes are manual over tp, everything else is left to
    the auto (GSPMD) axes."""
    entries = []
    for name in logical_axes:
        if name == "layers":
            entries.append("pp")
        elif rules.get(name) == "tp":
            entries.append("tp")
        else:
            entries.append(None)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def param_pipeline_specs(logical_axes_tree, rules: Optional[dict] = None):
    """shard_map in_specs for the params pytree (manual axes: pp, tp)."""
    return jax.tree.map(lambda ax: _manual_spec(ax, rules or {}),
                        logical_axes_tree,
                        is_leaf=lambda x: isinstance(x, tuple))


def _grad_psum_axes(logical_axes: tuple, rules: dict) -> tuple:
    """Manual axes a replicated-on-a param's grad must be psum'd over."""
    spec = _manual_spec(logical_axes, rules)
    present = set(a for a in spec if a is not None)
    return tuple(a for a in ("pp", "tp") if a not in present)


def make_pipeline_value_and_grad(
    bundle,
    plan,
    *,
    microbatches: Optional[int] = None,
    remat: bool = False,
    remat_policy=None,
    attn_impl: str = "auto",
    loss_fn: Callable = causal_lm_loss,
    loss_chunks: int = 0,
) -> Callable:
    """Returns f(params, batch) -> (loss, grads) running the 1F1B schedule
    over plan.mesh's pp (and tp) axes. batch: {'input_ids','labels'} of shape
    [B, S]; B must divide by microbatches, and B//microbatches by the
    data-axes size."""
    mesh = plan.mesh
    pp = mesh.shape["pp"]
    tp = mesh.shape["tp"]
    cp = mesh.shape["cp"]
    if cp > 1 and not callable(attn_impl):
        raise ValueError(
            "pp x cp needs a context-parallel attention callable (ring or "
            "Ulysses, built by the Trainer from --context-impl); a plain "
            f"attn_impl={attn_impl!r} would silently gather the cp-sharded "
            "sequence inside every stage")
    cfg = bundle.config
    mod = _family_module(bundle.family)
    rules = plan.rules
    if tp > 1:
        if not hasattr(mod, "tp_embed"):
            raise NotImplementedError(
                f"pp x tp needs family {bundle.family!r} to provide manual "
                f"megatron shards (a tp_axis-aware _block + tp_embed)")
        if rules.get("heads") != "tp":
            raise ValueError(
                f"mesh has tp={tp} but plan {plan.strategy!r} maps no logical "
                f"axis to tp; use the 'pp_tp' / 'pp_tp_fsdp' strategy")
        if loss_fn is not causal_lm_loss:
            raise NotImplementedError(
                "pp x tp hardwires the vocab-parallel causal-LM loss; drop "
                "the custom loss_fn or tp")
        if loss_chunks > 0:
            raise NotImplementedError(
                "loss_chunks is redundant under pp x tp: the vocab-parallel "
                "head already never materializes full logits")
        n_kv = getattr(cfg, "num_kv_heads", cfg.num_heads)
        if n_kv % tp or cfg.num_heads % tp:
            raise ValueError(f"num_heads={cfg.num_heads}/num_kv_heads="
                             f"{n_kv} not divisible by tp={tp}")
        if cfg.vocab_size % tp:
            raise ValueError(
                f"vocab_size={cfg.vocab_size} not divisible by tp={tp}: the "
                f"manual vocab-parallel embed/head needs equal vocab shards "
                f"(gpt2's 50257 never divides — pad the vocab, e.g. "
                f"vocab_size=50304, or run pp with tp=1)")
    n_layers = cfg.num_layers
    if n_layers % pp != 0:
        raise ValueError(f"num_layers={n_layers} not divisible by pp={pp}")
    M = microbatches or 2 * pp
    vocab_tp = tp > 1  # vocab-parallel embed/head (family tp hooks, above)
    tp_axis = "tp" if tp > 1 else None

    # MoE stages carry the router aux loss out of the scan; dense stages
    # return a constant zero aux so the schedule has one shape everywhere
    moe_family = bundle.apply_with_aux is not None
    aux_coef = getattr(cfg, "router_aux_coef", 0.0) if moe_family else 0.0

    def stage_fn(layers_local, x, positions):
        tp_kw = {"tp_axis": tp_axis} if tp_axis else {}  # family _block kwarg
        block = functools.partial(mod._block, cfg, positions=positions,
                                  attn_impl=attn_impl, **tp_kw)

        if moe_family:
            def body(carry, layer_params):
                # moe carry: (x, aux_acc, dropped_acc); dropped is a metric
                # only — not plumbed through the pipeline schedule
                return block(carry, layer_params), None
        else:
            def body(carry, layer_params):
                x, aux = carry
                return (block(x, layer_params), aux), None

        if remat:
            body = jax.checkpoint(
                body, prevent_cse=False,
                policy=remat_policy or jax.checkpoint_policies.nothing_saveable)
        zero = jnp.zeros((), jnp.float32)
        carry0 = (x, zero, zero) if moe_family else (x, zero)
        out, _ = jax.lax.scan(body, carry0, layers_local)
        return out[0], out[1]

    def embed_fn(nl_params, ids, positions):
        # nl_params: the non-"layers" subtree of params
        if vocab_tp:
            return mod.tp_embed(cfg, nl_params, ids, positions, "tp")
        return mod.embed_tokens(cfg, nl_params, ids, positions)

    use_chunked = loss_chunks > 0 and not vocab_tp
    if use_chunked:
        from ..ops.cross_entropy import validate_chunked_loss_support

        validate_chunked_loss_support(mod, bundle.family, loss_fn)

    def head_loss_fn(nl_params, y, labels):
        if vocab_tp:
            # the family head is shape-agnostic: on this member's vocab shard
            # it yields local [mb, S, V/tp] logits
            logits_local = mod.lm_head_logits(cfg, nl_params, y)
            return vocab_parallel_causal_lm_loss(logits_local, labels, "tp")
        if use_chunked:
            # big-vocab path: per-tick [mb, S, V] logits never materialize
            from ..ops.cross_entropy import chunked_causal_lm_loss

            hidden = mod.final_hidden(cfg, nl_params, y)
            w_out = mod.output_weights(cfg, nl_params)
            return chunked_causal_lm_loss(hidden, w_out, labels,
                                          num_chunks=loss_chunks)
        logits = mod.lm_head_logits(cfg, nl_params, y)
        return loss_fn(logits, labels)

    def pp_body(params, ids_mb, labels_mb):
        # ids_mb/labels_mb: [M, mb, S]
        s = jax.lax.axis_index("pp")
        is_first = s == 0
        is_last = s == pp - 1
        mb, seq = ids_mb.shape[1], ids_mb.shape[2]
        positions = jnp.broadcast_to(jnp.arange(seq)[None, :], (mb, seq))
        fwd_perm = [(i, i + 1) for i in range(pp - 1)]
        bwd_perm = [(i + 1, i) for i in range(pp - 1)]

        layers = params["layers"]
        nl = {k: v for k, v in params.items() if k != "layers"}

        C = M + pp - 1                     # forward (= backward) tick count
        K = min(2 * pp - 1, C)             # saved-input ring-buffer depth

        run_all = cp > 1

        def sync_cond(pred, live, zero):
            """Stage-divergent dispatch. The dense path ``lax.cond``-skips
            the dead branch, so bubbles cost idle time, not FLOPs. Under cp
            the live branch carries collectives (ring ppermutes / Ulysses
            all-to-alls / GSPMD seq reshards) whose participation set spans
            pp stages — a pp-divergent cond strands the live stages at the
            rendezvous (CPU runtime aborts, a pod hangs). So with cp > 1
            the live branch runs on EVERY member and the caller masks the
            outputs or cotangents, which is exact: outputs are selected
            against the cond's zero branch, and gradients are linear in the
            cotangent, so masked cotangents contribute exact zeros."""
            if run_all:
                return live()
            return jax.lax.cond(pred, live, zero)

        act = functools.partial(jnp.zeros, dtype=cfg.dtype)
        buf = act((mb, seq, cfg.hidden_size))        # resident activation
        dy_recv = act((mb, seq, cfg.hidden_size))    # cotangent from downstream
        saved = act((K, mb, seq, cfg.hidden_size))   # stage inputs, ring buffer
        loss_acc = jnp.zeros((), jnp.float32)
        g_layers = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), layers)
        g_nl = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), nl)
        dy_head = act((mb, seq, cfg.hidden_size))

        def fwd_tick(t, buf, saved, loss_acc, dy_head, g_nl):
            if t < M:
                # embedding on stage 0 only; other stages' branch is free
                x0 = sync_cond(is_first,
                               lambda: embed_fn(nl, ids_mb[t], positions),
                               lambda: act((mb, seq, cfg.hidden_size)))
                x_in = jnp.where(is_first, x0, buf)
            else:
                x_in = buf
            saved = saved.at[t % K].set(x_in)
            # bubble (fill/drain) ticks hold no real microbatch — skip the
            # stage compute entirely instead of crunching garbage (in the
            # masked-SPMD formulation the bubble would otherwise be real
            # FLOPs, not idle time)
            valid_f = (t - s >= 0) & (t - s < M)
            y, aux_t = sync_cond(
                valid_f,
                lambda: stage_fn(layers, x_in, positions),
                lambda: (jnp.zeros_like(x_in), jnp.zeros((), jnp.float32)))
            if run_all:  # the masked-SPMD bubble cost is the price of pp x cp
                y = jnp.where(valid_f, y, 0)
                aux_t = jnp.where(valid_f, aux_t, 0)
            if aux_coef:
                # router aux loss of this stage's layers for its resident
                # microbatch (t-s). loss_acc is divided by M once at the end,
                # so only the per-layer mean goes here.
                loss_acc = loss_acc + aux_t * (aux_coef / n_layers)

            o = t - (pp - 1)
            if 0 <= o < M:
                # head + loss (+ its grads w.r.t. head params and y) on the
                # last stage only. The grads are computed here, where y is
                # live, and consumed by this slot's paired backward tick.
                def head_branch():
                    (l, (g, dy)) = jax.value_and_grad(
                        head_loss_fn, argnums=(0, 1))(nl, y, labels_mb[o])
                    if tp > 1:
                        # The vocab-parallel loss psums over tp and psum
                        # transposes to psum (check_vma=False), so every tp
                        # member's cotangent is tp x the true one; rescale at
                        # the source so sharded-leaf grads come out true and
                        # replicated-leaf grads are per-member partials (the
                        # reduce_grad psum then sums them to the true grad).
                        g = jax.tree.map(lambda a: a / tp, g)
                        dy = dy / tp
                    return l, g, dy

                def zero_branch():
                    return (jnp.zeros((), jnp.float32),
                            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), nl),
                            act((mb, seq, cfg.hidden_size)))

                mb_loss, g_head, dy = sync_cond(is_last, head_branch,
                                                zero_branch)
                if run_all:  # the seq-dim loss reduction carries cp reshards
                    mb_loss = jnp.where(is_last, mb_loss, 0)
                    g_head = jax.tree.map(
                        lambda a: jnp.where(is_last, a, 0), g_head)
                    dy = jnp.where(is_last, dy, 0)
                loss_acc = loss_acc + mb_loss
                g_nl = jax.tree.map(lambda a, b: a + b / M, g_nl, g_head)
                dy_head = dy
            if t < C - 1:
                buf = jax.lax.ppermute(y, "pp", fwd_perm)
            return buf, saved, loss_acc, dy_head, g_nl

        def bwd_tick(u, saved, dy_recv, dy_head, g_layers, g_nl):
            # stage s processes the backward of microbatch m = u-(pp-1-s),
            # whose input it saved at forward tick m+s = u-(pp-1)+2s
            m_idx = u - (pp - 1) + s       # per-device (s == pp-1 gives u)
            valid = (m_idx >= 0) & (m_idx < M)
            # the head cotangent enters scaled by the 1/M of the loss mean;
            # everything upstream then arrives pre-scaled via dy_recv
            dy = jnp.where(is_last, dy_head / M, dy_recv)
            idx = jnp.mod(u - (pp - 1) + 2 * s, K)  # out-of-window reads are
            # clamped zeros on invalid ticks — their branch never computes
            x_saved = jax.lax.dynamic_index_in_dim(saved, idx, axis=0,
                                                   keepdims=False)

            def bwd_live():
                _, vjp = jax.vjp(lambda lp, x: stage_fn(lp, x, positions),
                                 layers, x_saved)
                # second cotangent: the aux-loss path (zero for dense). The
                # aux is computed redundantly on every tp member (router and
                # its inputs are tp-replicated), so the per-member cotangent
                # carries 1/tp — the replicated-leaf grad psum in reduce_grad
                # then reconstructs exactly one copy.
                daux = jnp.asarray(aux_coef / (M * n_layers * tp), jnp.float32)
                if run_all:  # sync_cond masking, applied to the COTANGENTS
                    mask = valid.astype(jnp.float32)
                    return vjp((dy * mask.astype(dy.dtype), daux * mask))
                return vjp((dy, daux))

            def bwd_skip():  # bubble tick: no recompute, no cotangent
                return (jax.tree.map(lambda p: jnp.zeros(p.shape, p.dtype),
                                     layers), jnp.zeros_like(x_saved))

            d_layers, dx = sync_cond(valid, bwd_live, bwd_skip)
            g_layers = jax.tree.map(lambda a, b: a + b.astype(jnp.float32),
                                    g_layers, d_layers)

            # embedding backward on stage 0 (static microbatch index there)
            m0 = u - (pp - 1)
            if 0 <= m0 < M:
                def embed_bwd(cotangent):
                    _, evjp = jax.vjp(
                        lambda p: embed_fn(p, ids_mb[m0], positions), nl)
                    return evjp(cotangent)[0]

                g_embed = sync_cond(
                    is_first,
                    # sync_cond masking, applied to the cotangent
                    lambda: embed_bwd(jnp.where(is_first, dx, 0)
                                      if run_all else dx),
                    lambda: jax.tree.map(
                        lambda p: jnp.zeros(p.shape, jnp.float32), nl))
                g_nl = jax.tree.map(lambda a, b: a + b.astype(jnp.float32),
                                    g_nl, g_embed)
            if u < C - 1:
                dy_recv = jax.lax.ppermute(dx, "pp", bwd_perm)
            return dy_recv, g_layers, g_nl

        for t in range(C):
            buf, saved, loss_acc, dy_head, g_nl = fwd_tick(
                t, buf, saved, loss_acc, dy_head, g_nl)
            u = t - (pp - 1)
            if u >= 0:
                dy_recv, g_layers, g_nl = bwd_tick(
                    u, saved, dy_recv, dy_head, g_layers, g_nl)
        for u in range(M, C):
            dy_recv, g_layers, g_nl = bwd_tick(
                u, saved, dy_recv, dy_head, g_layers, g_nl)

        loss = jax.lax.psum(loss_acc, "pp") / M

        # replicated-param grads hold per-member partials; reduce them over
        # the manual axes their param is not sharded on
        nl_axes = {k: v for k, v in bundle.param_logical_axes(cfg).items()
                   if k != "layers"}
        layer_axes = bundle.param_logical_axes(cfg)["layers"]

        def reduce_grad(g, log_ax):
            for a in _grad_psum_axes(log_ax, rules):
                if mesh.shape[a] > 1:
                    g = jax.lax.psum(g, a)
            return g

        g_nl = jax.tree.map(reduce_grad, g_nl, nl_axes)
        g_layers = jax.tree.map(reduce_grad, g_layers, layer_axes)
        grads = {**g_nl, "layers": g_layers}
        return loss, grads

    logical = bundle.param_logical_axes(cfg)
    param_specs = param_pipeline_specs(logical, rules)
    sharded = jax.shard_map(
        pp_body, mesh=mesh,
        in_specs=(param_specs, P(), P()),
        out_specs=(P(), param_specs),
        axis_names={"pp", "tp"},
        check_vma=False,
    )

    # seq stays cp-sharded through the schedule when cp > 1 (the ring /
    # Ulysses attention callables re-anchor it at their shard_map boundary)
    mb_sharding = NamedSharding(
        mesh, P(None, plan.data_axes, "cp" if cp > 1 else None))
    data_size = plan.data_parallel_size

    def value_and_grad(params, batch):
        ids = batch["input_ids"]
        labels = batch["labels"]
        b, seq = ids.shape
        if b % M != 0:
            raise ValueError(f"global batch {b} not divisible by microbatches={M}")
        if (b // M) % data_size != 0:
            raise ValueError(
                f"microbatch size {b // M} not divisible by data-parallel size "
                f"{data_size}; raise the batch or lower pp_microbatches")
        # keep each microbatch's batch dim sharded over the data axes — the
        # reshape would otherwise let GSPMD shard the scanned M dim
        ids_mb = jax.lax.with_sharding_constraint(
            ids.reshape(M, b // M, seq), mb_sharding)
        labels_mb = jax.lax.with_sharding_constraint(
            labels.reshape(M, b // M, seq), mb_sharding)
        return sharded(params, ids_mb, labels_mb)

    return value_and_grad
