"""Sharding plans: every parallelism strategy as a logical->mesh axis mapping.

This file replaces four different torch wrapper APIs from the reference with
one mechanism. The reference needs:

- ``DistributedDataParallel``            (``02-distributed-data-parallel/train_llm.py:66-68``)
- ``ZeroRedundancyOptimizer``            (``02:87-89``)
- ``fully_shard`` (FSDP2)                (``04-fully-sharded-data-parallel/train_llm.py:83-95``)
- ``tp.parallelize_module`` Colwise/Rowwise/SequenceParallel plans (``06:79-121``)
- both at once on a 2-D mesh             (``07-2d-parallel/train_llm.py:77-123``)

Here each of those is a *rules table* mapping the model's logical parameter
axes (vocab/embed/heads/kv/mlp) to mesh axes (dp/fsdp/tp/cp). GSPMD then
inserts exactly the collectives the reference implements by hand in CUDA:
grad psum over dp/fsdp (DDP all-reduce), per-layer all-gather/reduce-scatter
of fsdp-sharded params (FSDP), and the TP all-gather / reduce-scatter pairs
from the reference's forward walk (SURVEY.md section 3.3).

A dimension that is not divisible by its assigned mesh axis falls back to
replication on that axis (torch DTensor errors instead; replication is always
correct, just less sharded).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis -> mesh axes, per strategy. A value may be a single mesh axis
# name or a tuple of them (sharded over both).
STRATEGIES: dict[str, dict[str, Any]] = {
    # chapter 01: one device
    "single": {},
    # chapter 02: replicated params, data sharded over (dp, fsdp)
    "ddp": {},
    # chapter 02 + ZeRO-1: params replicated, *optimizer state* sharded (the
    # optimizer-state rules below are applied by train/optimizer.py)
    "zero1": {},
    # ZeRO-2 (deepspeed stage 2): params replicated, optimizer state AND the
    # gradient-accumulation buffer sharded over the data axes — the grads'
    # reduce-scatter replaces DDP's all-reduce, and full grads never persist
    "zero2": {},
    # chapter 04: FULL_SHARD — every weight matrix sharded on its embed dim
    "fsdp": {
        "embed": "fsdp",
        "vocab": "fsdp",  # embedding + lm_head shard vocab (big dim, avoids
                          # resharding the embed dim used in every matmul)
    },
    # chapter 06: megatron TP + sequence parallelism for activations.
    # *_vector axes are the gpt2 biases — a column-parallel projection's
    # bias shards with its columns
    "tp": {
        "heads": "tp",
        "kv": "tp",
        "mlp": "tp",
        "vocab": "tp",
        "heads_vector": "tp",
        "kv_vector": "tp",
        "mlp_vector": "tp",
    },
    # chapter 07: 2-D = FSDP x TP on orthogonal axes
    "tp_fsdp": {
        "heads": "tp",
        "kv": "tp",
        "mlp": "tp",
        "vocab": "tp",
        "heads_vector": "tp",
        "kv_vector": "tp",
        "mlp_vector": "tp",
        "embed": "fsdp",
    },
    # chapter 09 (beyond the reference): pipeline stages own layer slices;
    # the stacked layer dim is the sharded one (parallel/pipeline.py)
    "pp": {"layers": "pp"},
    "pp_fsdp": {"layers": "pp", "embed": "fsdp", "vocab": "fsdp"},
    "pp_tp": {"layers": "pp", "heads": "tp", "kv": "tp", "mlp": "tp",
              "vocab": "tp", "heads_vector": "tp", "kv_vector": "tp",
              "mlp_vector": "tp"},
    # pp x tp x fsdp: tp is manual inside the pipeline shard_map (megatron
    # shards + vocab-parallel embed/head), fsdp stays auto on the embed dim
    "pp_tp_fsdp": {"layers": "pp", "heads": "tp", "kv": "tp", "mlp": "tp",
                   "vocab": "tp", "heads_vector": "tp", "kv_vector": "tp",
                   "mlp_vector": "tp",
                   "embed": "fsdp"},
    # chapter 10 (beyond the reference): MoE expert parallelism — the expert
    # dim of stacked expert weights lives on ep. With moe_dispatch="dense"
    # GSPMD derives the token all-to-all from the static capacity
    # dispatch/combine einsums; with "ragged" (dropless sorted dispatch) the
    # sort is data-dependent, so the Trainer threads a manual shard_map over
    # the data axes that exchanges sorted expert groups instead
    # (models/moe.py make_ragged_ep_dispatch) — same rules table either way
    "ep": {"experts": "ep"},
    "ep_fsdp": {"experts": "ep", "embed": "fsdp", "vocab": "fsdp"},
}

# logical axes that shard the optimizer state only (ZeRO-1, reference C3):
ZERO1_RULES = {"embed": ("dp", "fsdp"), "vocab": ("dp", "fsdp")}


def _dim_divisible(mesh: Mesh, axes, dim: int) -> bool:
    if axes is None:
        return True
    names = (axes,) if isinstance(axes, str) else tuple(axes)
    size = int(np.prod([mesh.shape[a] for a in names]))
    return size > 0 and dim % size == 0


def spec_for_leaf(mesh: Mesh, logical_axes: tuple, shape: tuple, rules: dict) -> P:
    """PartitionSpec for one parameter leaf; replicates non-divisible dims."""
    entries = []
    used: set = set()
    for ax_name, dim in zip(logical_axes, shape):
        mesh_axes = rules.get(ax_name)
        if mesh_axes is not None:
            names = (mesh_axes,) if isinstance(mesh_axes, str) else tuple(mesh_axes)
            if any(n in used for n in names) or not _dim_divisible(mesh, names, dim):
                mesh_axes = None
            else:
                used.update(names)
        entries.append(mesh_axes)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """Everything the train-step builder needs to lay out one strategy."""

    mesh: Mesh
    strategy: str
    rules: dict
    sequence_sharded: bool = False  # SP: shard the seq dim of activations on tp
    zero1: bool = False             # shard optimizer state over the data axes
    zero2: bool = False             # zero1 + shard persistent gradients too

    # ---- batch / data ------------------------------------------------------
    @property
    def data_axes(self) -> tuple:
        """Mesh axes that partition the global batch dim. ``ep`` is a data
        axis: tokens shard over it, and it is precisely the combination
        (tokens over ep) x (experts over ep) that makes GSPMD partition the
        MoE dispatch/combine einsums into the token all-to-all (GShard)."""
        return ("dp", "fsdp", "ep")

    def batch_spec(self, ndim: int = 2) -> P:
        seq = ("cp",) if self.mesh.shape["cp"] > 1 else None
        if ndim == 1:
            return P(self.data_axes)
        extra = [seq[0] if seq else None] + [None] * (ndim - 2)
        return P(self.data_axes, *extra)

    def batch_sharding(self, ndim: int = 2) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec(ndim))

    @property
    def data_parallel_size(self) -> int:
        return int(np.prod([self.mesh.shape[a] for a in self.data_axes]))

    def active_axes(self) -> tuple:
        """Mesh axes with size > 1. The serve-side sharded page pool
        (serve/sharding.py) keys its full-manual-region validation on
        this: its rules table mirrors the ``kv``->tp mapping above, and
        the pool is only shardable when tp is the sole active axis."""
        return tuple(a for a in self.mesh.axis_names
                     if int(self.mesh.shape[a]) > 1)

    # ---- activations -------------------------------------------------------
    def activation_sharding(self) -> Optional[NamedSharding]:
        """Residual-stream constraint [B, S, E] between blocks.

        With SP (reference's SequenceParallel norms, ``06:90,101,115``) the
        sequence dim is sharded on tp so norms/elementwise run on 1/tp of the
        tokens; XLA inserts the same all-gather before attention/mlp and
        reduce-scatter after that DTensor does.
        """
        if self.mesh.shape["cp"] > 1:
            # context parallelism: seq dim lives on cp everywhere; attention
            # crosses shards via the ring (ops/ring_attention.py)
            return NamedSharding(self.mesh, P(self.data_axes, "cp", None))
        if self.sequence_sharded and self.mesh.shape["tp"] > 1:
            return NamedSharding(self.mesh, P(self.data_axes, "tp", None))
        if self.strategy == "single":
            return None
        return NamedSharding(self.mesh, P(self.data_axes, None, None))

    def logits_sharding(self) -> Optional[NamedSharding]:
        """Loss-parallel layout [B, S, V]: keep the vocab dim tp-sharded
        through the cross-entropy (logsumexp becomes local-reduce + psum)
        instead of all-gathering full logits. The reference documents this as
        ``loss_parallel`` but ships with ``Replicate()``
        (``06-tensor-parallel/README.md:241-271``, ``06:117``)."""
        if self.rules.get("vocab") == "tp" and self.mesh.shape["tp"] > 1:
            seq = "cp" if self.mesh.shape["cp"] > 1 else None
            return NamedSharding(self.mesh, P(self.data_axes, seq, "tp"))
        return None

    # ---- params / optimizer state -----------------------------------------
    def param_shardings(self, logical_axes_tree, shape_tree) -> Any:
        """NamedSharding pytree for params (shape_tree: ShapeDtypeStructs)."""
        is_ax = lambda x: isinstance(x, tuple)
        return jax.tree.map(
            lambda ax, sd: NamedSharding(self.mesh, spec_for_leaf(self.mesh, ax, sd.shape, self.rules)),
            logical_axes_tree, shape_tree,
            is_leaf=is_ax,
        )

    def optimizer_state_rules(self) -> dict:
        """Rules for optimizer-state leaves (adds ZeRO-1 on top of params)."""
        if self.zero1:
            return {**self.rules, **ZERO1_RULES}
        return self.rules

    def grad_shardings(self, logical_axes_tree, shape_tree) -> Any:
        """Shardings for *persistent* gradient buffers (ZeRO-2): grads follow
        the optimizer-state layout, so under zero2 the accumulation buffer is
        reduce-scattered across the data axes instead of living replicated."""
        rules = self.optimizer_state_rules() if self.zero2 else self.rules
        is_ax = lambda x: isinstance(x, tuple)
        return jax.tree.map(
            lambda ax, sd: NamedSharding(self.mesh, spec_for_leaf(self.mesh, ax, sd.shape, rules)),
            logical_axes_tree, shape_tree,
            is_leaf=is_ax,
        )

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


def make_plan(strategy: str, mesh: Mesh, *, sequence_sharded: Optional[bool] = None,
              zero1: Optional[bool] = None,
              zero2: Optional[bool] = None) -> ShardingPlan:
    if strategy not in STRATEGIES:
        raise ValueError(f"unknown strategy {strategy!r}; choose from {sorted(STRATEGIES)}")
    if sequence_sharded is None:
        sequence_sharded = strategy in ("tp", "tp_fsdp")
    if zero2 is None:
        zero2 = strategy == "zero2"
    if zero1 is None:
        zero1 = strategy == "zero1" or zero2
    return ShardingPlan(mesh=mesh, strategy=strategy, rules=STRATEGIES[strategy],
                        sequence_sharded=sequence_sharded, zero1=zero1,
                        zero2=zero2)
