"""Device-mesh construction.

Parity with the reference's ``init_device_mesh("cuda", (dp, tp),
mesh_dim_names=("dp","tp"))`` (``06-tensor-parallel/train_llm.py:51-55``,
``07-2d-parallel/train_llm.py:49-53``), generalized: one mesh with four named
axes is the single abstraction behind every chapter —

    dp    pure data parallelism (replica groups; multi-slice runs put DCN here)
    pp    pipeline parallelism (layer stages; ppermute between neighbors)
    fsdp  parameter-sharded data parallelism (ZeRO-3 / FULL_SHARD axis)
    ep    expert parallelism (MoE expert dim; all-to-all dispatch)
    tp    tensor parallelism (fastest ICI axis — collectives per layer)
    cp    context parallelism (sequence-dim sharding for long context)

Axes of size 1 cost nothing, so every plan runs on the same mesh type.
``mesh_utils.create_device_mesh`` maps the logical mesh onto the physical ICI
torus so that the innermost (tp) axis lands on nearest-neighbor links.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_NAMES = ("dp", "pp", "fsdp", "ep", "tp", "cp")


def mesh_shape_for(n_devices: int, *, fsdp: int = 1, tp: int = 1, cp: int = 1,
                   pp: int = 1, ep: int = 1, dp: Optional[int] = None) -> tuple[int, ...]:
    """Fill in the dp axis so dp*pp*fsdp*ep*tp*cp == n_devices."""
    denom = pp * fsdp * ep * tp * cp
    if n_devices % denom != 0:
        raise ValueError(f"{n_devices} devices not divisible by pp*fsdp*ep*tp*cp={denom}")
    inferred_dp = n_devices // denom
    if dp is not None and dp != inferred_dp:
        raise ValueError(f"dp={dp} inconsistent: need {inferred_dp}")
    return (inferred_dp, pp, fsdp, ep, tp, cp)


def make_mesh(*, fsdp: int = 1, tp: int = 1, cp: int = 1, pp: int = 1, ep: int = 1,
              dp: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    shape = mesh_shape_for(len(devices), fsdp=fsdp, tp=tp, cp=cp, pp=pp, ep=ep, dp=dp)
    if math.prod(shape) == 1:
        import numpy as np

        return Mesh(np.asarray(devices).reshape(shape), AXIS_NAMES)
    try:
        device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        # CPU/virtual-device fallback: topology-unaware reshape
        import numpy as np

        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, AXIS_NAMES)
