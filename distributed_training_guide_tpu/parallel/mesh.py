"""Device-mesh construction.

Parity with the reference's ``init_device_mesh("cuda", (dp, tp),
mesh_dim_names=("dp","tp"))`` (``06-tensor-parallel/train_llm.py:51-55``,
``07-2d-parallel/train_llm.py:49-53``), generalized: one mesh with four named
axes is the single abstraction behind every chapter —

    dp    pure data parallelism (replica groups; multi-slice runs put DCN here)
    pp    pipeline parallelism (layer stages; ppermute between neighbors)
    fsdp  parameter-sharded data parallelism (ZeRO-3 / FULL_SHARD axis)
    ep    expert parallelism (MoE expert dim; all-to-all dispatch)
    tp    tensor parallelism (fastest ICI axis — collectives per layer)
    cp    context parallelism (sequence-dim sharding for long context)

Axes of size 1 cost nothing, so every plan runs on the same mesh type.
``mesh_utils.create_device_mesh`` maps the logical mesh onto the physical ICI
torus so that the innermost (tp) axis lands on nearest-neighbor links.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_NAMES = ("dp", "pp", "fsdp", "ep", "tp", "cp")


def mesh_shape_for(n_devices: int, *, fsdp: int = 1, tp: int = 1, cp: int = 1,
                   pp: int = 1, ep: int = 1, dp: Optional[int] = None) -> tuple[int, ...]:
    """Fill in the dp axis so dp*pp*fsdp*ep*tp*cp == n_devices."""
    denom = pp * fsdp * ep * tp * cp
    if n_devices % denom != 0:
        raise ValueError(f"{n_devices} devices not divisible by pp*fsdp*ep*tp*cp={denom}")
    inferred_dp = n_devices // denom
    if dp is not None and dp != inferred_dp:
        raise ValueError(f"dp={dp} inconsistent: need {inferred_dp}")
    return (inferred_dp, pp, fsdp, ep, tp, cp)


def make_mesh(*, fsdp: int = 1, tp: int = 1, cp: int = 1, pp: int = 1, ep: int = 1,
              dp: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None,
              multi_slice: Optional[bool] = None) -> Mesh:
    """Build the mesh, topology-aware.

    Multi-slice pods (several ICI islands joined by DCN — the TPU analogue of
    the reference's multi-node NCCL-over-ethernet setup) place the dp axis
    across slices via ``create_hybrid_device_mesh``: dp traffic (grad
    all-reduce, once per step) rides DCN while the chatty fsdp/tp/cp
    collectives stay inside a slice on ICI. Auto-detected from device
    metadata; force with ``multi_slice=``.
    """
    devices = list(devices) if devices is not None else jax.devices()
    shape = mesh_shape_for(len(devices), fsdp=fsdp, tp=tp, cp=cp, pp=pp, ep=ep, dp=dp)
    if math.prod(shape) == 1:
        import numpy as np

        return Mesh(np.asarray(devices).reshape(shape), AXIS_NAMES)

    slice_ids = {getattr(d, "slice_index", 0) for d in devices}
    if multi_slice is None:
        multi_slice = len(slice_ids) > 1
    if multi_slice:
        import logging

        logger = logging.getLogger(__name__)
        remaining = max(len(slice_ids), 1)
        dcn_shape = [1] * len(shape)
        ici_shape = list(shape)
        # place slices on the least-communication-heavy axes first:
        # dp (one all-reduce/step), pp (point-to-point), then fsdp/ep/cp;
        # tp stays on ICI unconditionally
        for name in ("dp", "pp", "fsdp", "ep", "cp"):
            axis_idx = AXIS_NAMES.index(name)
            g = math.gcd(shape[axis_idx], remaining)
            if g > 1:
                dcn_shape[axis_idx] = g
                ici_shape[axis_idx] = shape[axis_idx] // g
                remaining //= g
            if remaining == 1:
                break
        if remaining != 1:
            logger.warning(
                f"cannot factor {len(slice_ids)} slices onto mesh "
                f"{dict(zip(AXIS_NAMES, shape))}; building a topology-unaware "
                f"mesh (collectives may cross DCN suboptimally)")
        else:
            try:
                device_array = mesh_utils.create_hybrid_device_mesh(
                    ici_shape, dcn_shape, devices=devices)
                return Mesh(device_array, AXIS_NAMES)
            except Exception as e:
                logger.warning(
                    f"hybrid (ICI x DCN) mesh construction failed ({e}); "
                    f"falling back to a topology-unaware mesh — expect "
                    f"degraded cross-slice collective performance")

    try:
        device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        # CPU/virtual-device fallback: topology-unaware reshape
        import numpy as np

        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, AXIS_NAMES)
