from .mesh import make_mesh, mesh_shape_for
from .plans import ShardingPlan, make_plan, STRATEGIES

__all__ = ["make_mesh", "mesh_shape_for", "ShardingPlan", "make_plan", "STRATEGIES"]
