"""World-size renegotiation for the elastic supervisor.

The restart loop (``launch/supervisor.py``) inherited torchrun's model of
elasticity: when anything fails, restart everything and resume on the SAME
world. A production fleet loses slices and gains capacity *while running*
— and with restart-only elasticity a lost slice means "crash loop until
the slice returns". This module turns a slice loss into "shrink and
continue": membership comes from per-slice heartbeat files, a new world is
agreed through a barrier'd proposal file, and each surviving supervisor
re-execs its worker with the renegotiated mesh config (the checkpoint
reshards into the new mesh on restore — ``checkpoint/reshard.py``).

The protocol, all files under one shared ``--elastic-dir`` (the
coordination directory — on a pod, a shared filesystem; the same trust
the checkpoint/state.json machinery already places there):

- **Membership** (``members/<name>.json``): every participating slice's
  supervisor beats its member file (atomic tmp+rename, same discipline as
  ``utils/heartbeat.py``); liveness is payload-timestamp age. A slice
  that stops beating for ``liveness_timeout`` seconds is LOST; a file
  appearing (fresh) is a slice JOINING.
- **World agreement** (``world.proposal.json`` -> ``world.json``): the
  LEADER — the lexicographically-smallest live member, so leadership
  survives leader-slice loss — proposes ``{world_id, members, trigger}``;
  every other proposed member acks by writing ``world.ack.<name>.json``
  carrying the proposal's world_id (the id IS the fence: a stale ack
  from a previous incarnation names an old id and cannot count, the
  mtime-fence discipline of the supervisor's error files). When every
  member acked, the leader atomically publishes ``world.json``
  (tmp+rename) — the barrier. Members that fail to ack within the window
  are presumed dead and DROPPED: the leader re-proposes without them
  (bounded rounds), so a straggler cannot wedge the renegotiation it
  caused. A member that finds itself outside a published world is FENCED
  OUT and must exit — its in-flight work is already covered by the
  smaller world's restore.
- **Events** (``elastic.jsonl``): every renegotiation appends one line —
  old world, new world, trigger, wall time — so a post-mortem can
  reconstruct the membership timeline next to the run's state.json.

CPU-testable shape (this container's jax cannot run multiprocess CPU
computations — ROADMAP caveat b): the worker is a single process whose
device count is the WORLD total via ``--xla_force_host_platform_device_
count``, peer slices are real processes running ``python -m ...launch.
elastic --member <name> --dir <d>`` (beat + ack, no jax), and a slice
loss is the member dying (``DTG_FAULT_SLICE_LOSS=<name>@<beat>``). On a
real pod every slice runs a full supervisor+worker pair and the same
files drive the same agreement; the worker re-exec then carries
process-count/coordinator env instead of the forced device count.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import time
from pathlib import Path
from typing import Optional

from ..utils import faults

MEMBERS_DIR = "members"
WORLD_FILE = "world.json"
PROPOSAL_FILE = "world.proposal.json"
EVENTS_FILE = "elastic.jsonl"


class FencedOutError(RuntimeError):
    """This member is not part of the agreed world: the fleet moved on
    without it (it was presumed dead, or explicitly removed). The only
    correct response is to exit — rejoining happens by beating again and
    letting the leader renegotiate a larger world."""


def _write_json_atomic(path: Path, payload: dict) -> None:
    tmp = path.with_name(path.name + ".tmp")
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(tmp, "w") as fp:
        json.dump(payload, fp)
    os.replace(tmp, path)  # readers never see torn JSON


def _read_json(path: Path) -> Optional[dict]:
    try:
        with open(path) as fp:
            payload = json.load(fp)
    except (OSError, json.JSONDecodeError):
        return None
    return payload if isinstance(payload, dict) else None


def append_event(coord_dir: Path, event: dict) -> None:
    """One line to ``elastic.jsonl`` (wall-clock stamped): the membership
    timeline post-mortems reconstruct. Append-only, flushed per line."""
    path = Path(coord_dir) / EVENTS_FILE
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "a") as fp:
        fp.write(json.dumps({"wall_time": time.time(), **event}) + "\n")


def read_events(coord_dir: Path) -> list[dict]:
    out = []
    try:
        with open(Path(coord_dir) / EVENTS_FILE) as fp:
            for line in fp:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return out


class SliceMember:
    """One slice's presence in the coordination directory."""

    def __init__(self, coord_dir: Path, name: str):
        if "/" in name or not name:
            raise ValueError(f"member name must be a plain token, got "
                             f"{name!r}")
        self.coord_dir = Path(coord_dir)
        self.name = name
        self.path = self.coord_dir / MEMBERS_DIR / f"{name}.json"
        self.beats = 0

    def beat(self) -> None:
        self.beats += 1
        _write_json_atomic(self.path, {"name": self.name,
                                       "time": time.time(),
                                       "beats": self.beats,
                                       "pid": os.getpid()})

    def retire(self) -> None:
        """Clean departure (drain, not death): the file goes away, so the
        next liveness scan shrinks the world without waiting out the
        timeout."""
        try:
            self.path.unlink()
        except OSError:
            pass


def live_members(coord_dir: Path, liveness_timeout_s: float,
                 now: Optional[float] = None) -> list[str]:
    """Names whose member file's payload timestamp is fresh, sorted (the
    sort defines leadership: index 0 proposes)."""
    now = time.time() if now is None else now
    out = []
    mdir = Path(coord_dir) / MEMBERS_DIR
    try:
        entries = sorted(mdir.glob("*.json"))
    except OSError:
        return []
    for path in entries:
        payload = _read_json(path)
        if payload is None or "time" not in payload:
            continue
        if now - float(payload["time"]) <= liveness_timeout_s:
            out.append(payload.get("name", path.stem))
    return sorted(set(out))


class WorldNegotiator:
    """The agreement protocol for one member (leader or follower decided
    per-negotiation by who sorts first among the live)."""

    def __init__(self, coord_dir: Path, name: str, *,
                 ack_timeout_s: float = 10.0, poll_s: float = 0.05,
                 on_poll=None):
        self.coord_dir = Path(coord_dir)
        self.name = name
        self.ack_timeout_s = ack_timeout_s
        self.poll_s = poll_s
        # called on every wait-loop tick (the supervisor wires its own
        # membership beat here: an agreement round can outlast the
        # liveness timeout, and a negotiator that stops beating while it
        # waits would read as a lost slice to everyone else)
        self.on_poll = on_poll

    # ---- shared views ------------------------------------------------------
    def current(self) -> Optional[dict]:
        return _read_json(self.coord_dir / WORLD_FILE)

    def proposal(self) -> Optional[dict]:
        return _read_json(self.coord_dir / PROPOSAL_FILE)

    def _ack_path(self, member: str) -> Path:
        return self.coord_dir / f"world.ack.{member}.json"

    # ---- leader ------------------------------------------------------------
    def propose_and_agree(self, members: list[str], trigger: str) -> dict:
        """Barrier'd agreement: propose ``members`` (self always
        included), collect id-fenced acks from every OTHER member, publish
        ``world.json``. Ack stragglers are dropped and the next round
        proposes without them — the renegotiation a dead slice triggered
        can never be wedged by that same dead slice. Returns the published
        world; appends the renegotiation event."""
        members = sorted(set(members) | {self.name})
        old = self.current()
        world_id = int(old["world_id"]) + 1 if old else 1
        while True:
            proposal = {"world_id": world_id, "members": members,
                        "trigger": trigger, "proposed_by": self.name,
                        "proposed_at": time.time()}
            _write_json_atomic(self.coord_dir / PROPOSAL_FILE, proposal)
            waiting = [m for m in members if m != self.name]
            deadline = time.time() + self.ack_timeout_s
            while waiting and time.time() < deadline:
                if self.on_poll is not None:
                    self.on_poll()
                for m in list(waiting):
                    ack = _read_json(self._ack_path(m))
                    # the world_id in the ack payload is the fence: an ack
                    # file left by an earlier incarnation names an old id
                    if ack and int(ack.get("world_id", -1)) == world_id:
                        waiting.remove(m)
                if waiting:
                    time.sleep(self.poll_s)
            if not waiting:
                break
            # stragglers are presumed dead: drop them and re-propose (a
            # fresh world_id so their late acks to THIS round can't count)
            members = [m for m in members if m not in waiting]
            world_id += 1
            if members == [self.name]:
                # no one left to wait for — the next loop publishes
                # immediately (the single-member world)
                continue
        world = {"world_id": world_id, "members": members,
                 "trigger": trigger, "agreed_at": time.time()}
        _write_json_atomic(self.coord_dir / WORLD_FILE, world)
        for m in members:
            try:                      # consumed acks: best-effort cleanup
                self._ack_path(m).unlink()
            except OSError:
                pass
        try:
            (self.coord_dir / PROPOSAL_FILE).unlink()
        except OSError:
            pass
        append_event(self.coord_dir, {
            "event": "renegotiated", "trigger": trigger,
            "old_world": ({"world_id": old["world_id"],
                           "members": old["members"]} if old else None),
            "new_world": {"world_id": world_id, "members": members},
        })
        return world

    # ---- follower ----------------------------------------------------------
    def maybe_ack(self) -> Optional[int]:
        """Ack the live proposal if one names a newer world than the
        published one. Returns the acked world_id (or None). A proposal
        that EXCLUDES this member is not acked — ``follow`` raises
        FencedOutError when the exclusion publishes."""
        proposal = self.proposal()
        if proposal is None:
            return None
        current = self.current()
        if current and int(proposal["world_id"]) <= int(current["world_id"]):
            return None
        if self.name not in proposal.get("members", []):
            return None
        wid = int(proposal["world_id"])
        _write_json_atomic(self._ack_path(self.name),
                           {"world_id": wid, "member": self.name,
                            "acked_at": time.time()})
        return wid

    def follow(self, min_world_id: int, timeout_s: float, *,
               joining: bool = False) -> dict:
        """Follower barrier: ack proposals as they appear and wait for a
        published world newer than ``min_world_id``. A published world
        that EXCLUDES this member raises FencedOutError — unless
        ``joining``: a member that was never part of a world cannot be
        fenced by one that predates its join (a stale ``world.json`` on
        a reused coordination dir, or a scale-UP joiner arriving
        mid-run); it keeps beating and waits for the leader's membership
        poll to propose a world that admits it."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if self.on_poll is not None:
                self.on_poll()
            self.maybe_ack()
            world = self.current()
            if world and int(world["world_id"]) > min_world_id:
                if self.name in world.get("members", []):
                    return world
                if not joining:
                    raise FencedOutError(
                        f"member {self.name!r} is not part of world "
                        f"{world['world_id']} ({world['members']}); the "
                        f"fleet renegotiated without it")
            time.sleep(self.poll_s)
        raise TimeoutError(
            f"no world {'admitting ' + repr(self.name) if joining else ''}"
            f"newer than {min_world_id} published within "
            f"{timeout_s}s (leader dead and no one took over?)")


# ---- supervisor-side runtime -----------------------------------------------

@dataclasses.dataclass
class ElasticConfig:
    """The supervisor's ``--elastic-*`` knobs in one place."""
    coord_dir: Path
    member: str = "slice0"
    devices_per_slice: int = 1
    liveness_timeout_s: float = 5.0
    ack_timeout_s: float = 15.0
    settle_s: float = 1.0            # startup window for peers to appear
    global_batch: Optional[int] = None  # backs the {world_batch} token


class ElasticRuntime:
    """What the supervisor drives: beat membership, agree on worlds, and
    answer "did the world change under my running worker?"."""

    def __init__(self, cfg: ElasticConfig):
        self.cfg = cfg
        self.member = SliceMember(cfg.coord_dir, cfg.member)
        # the negotiator beats our member file on every wait tick: an
        # agreement round or a long follow can outlast the liveness
        # timeout, and going silent mid-negotiation would read as a lost
        # slice to every peer
        self.negotiator = WorldNegotiator(cfg.coord_dir, cfg.member,
                                          ack_timeout_s=cfg.ack_timeout_s,
                                          on_poll=self.member.beat)
        self.world: Optional[dict] = None

    # ---- views -------------------------------------------------------------
    def live(self) -> list[str]:
        return live_members(self.cfg.coord_dir, self.cfg.liveness_timeout_s)

    def is_leader(self, live: Optional[list[str]] = None) -> bool:
        """Process 0 of the agreement: the smallest live member name —
        computed per negotiation, so leadership survives leader loss."""
        live = self.live() if live is None else live
        return bool(live) and live[0] == self.cfg.member

    def world_devices(self) -> int:
        n = len(self.world["members"]) if self.world else 1
        return max(1, n) * self.cfg.devices_per_slice

    # ---- negotiation -------------------------------------------------------
    def establish(self, trigger: str) -> dict:
        """Negotiate into the next world (leader) or follow the leader's
        proposal (follower). Called at startup and after every membership
        change; raises FencedOutError when the agreed world excludes this
        member."""
        self.member.beat()
        if trigger == "start":
            # give peers one settle window to beat before the first world
            # is cut — without it the first supervisor up always agrees a
            # 1-member world and immediately renegotiates
            deadline = time.time() + self.cfg.settle_s
            seen = self.live()
            while time.time() < deadline:
                time.sleep(0.1)
                now_live = self.live()
                if now_live != seen:
                    seen, deadline = now_live, time.time() + self.cfg.settle_s
        prev_id = int(self.world["world_id"]) if self.world else 0
        # a member that has never been part of a world is JOINING: a
        # published world that excludes it (a stale world.json on a
        # reused dir, or a scale-up join mid-run) must not fence it —
        # it waits for the leader's membership poll to admit it, which
        # can take the leader a worker-SIGTERM's worth of time
        joining = self.world is None
        # overall grace >> one follow window: the leader may spend a full
        # worker-SIGTERM grace (30s) before it even proposes, and it may
        # itself die mid-negotiation — each short follow timeout
        # re-checks leadership, so a follower whose leader vanished takes
        # over instead of crashing on TimeoutError
        deadline = time.time() + max(
            120.0, 3 * (self.cfg.ack_timeout_s
                        + self.cfg.liveness_timeout_s))
        while True:
            live = self.live()
            if self.cfg.member not in live:  # our own beat should be fresh
                live = sorted(set(live) | {self.cfg.member})
            if self.is_leader(live):
                self.world = self.negotiator.propose_and_agree(live,
                                                               trigger)
                return self.world
            try:
                self.world = self.negotiator.follow(
                    prev_id, joining=joining,
                    timeout_s=min(self.cfg.ack_timeout_s
                                  + self.cfg.liveness_timeout_s,
                                  max(1.0, deadline - time.time())))
                return self.world
            except TimeoutError:
                if time.time() >= deadline:
                    raise

    def poll(self) -> Optional[str]:
        """One monitoring tick while the worker runs: beat membership, ack
        any live proposal (so the leader's barrier never waits on us), and
        return a renegotiation trigger when the world changed — a slice
        lost/joined (liveness vs the agreed membership) or another
        leader's newer proposal/world on disk."""
        self.member.beat()
        self.negotiator.maybe_ack()
        if self.world is None:
            return "start"
        current = self.negotiator.current()
        if current and int(current["world_id"]) > int(self.world["world_id"]):
            return "world_moved"       # agreed while we weren't looking
        proposal = self.negotiator.proposal()
        if proposal and int(proposal.get("world_id", 0)) \
                > int(self.world["world_id"]):
            return "proposal"
        live = set(self.live()) | {self.cfg.member}
        agreed = set(self.world["members"])
        if live - agreed:
            return "slice_joined"
        if agreed - live:
            return "slice_lost"
        return None

    def retire(self) -> None:
        self.member.retire()


def render_worker_cmd(cmd: list[str], world_devices: int,
                      global_batch: Optional[int] = None) -> list[str]:
    """Substitute the renegotiated mesh config into the worker command:
    ``{world_devices}`` -> the world's total device count, and
    ``{world_batch}`` -> ``global_batch // world_devices`` (requires
    ``--elastic-global-batch``) — the per-data-shard batch that keeps the
    GLOBAL batch invariant across world sizes, which is what makes a
    shrink-and-continue trajectory comparable to the uninterrupted run
    (``related-topics/elastic-training`` "Dynamic world size")."""
    out = []
    for arg in cmd:
        if "{world_batch}" in arg:
            if global_batch is None:
                raise ValueError(
                    "worker command uses {world_batch} but no "
                    "--elastic-global-batch was given")
            if global_batch % world_devices:
                raise ValueError(
                    f"--elastic-global-batch {global_batch} is not "
                    f"divisible by the world's {world_devices} devices")
            arg = arg.replace("{world_batch}",
                              str(global_batch // world_devices))
        out.append(arg.replace("{world_devices}", str(world_devices)))
    return out


def worker_world_env(env: dict, world: dict, world_devices: int) -> dict:
    """Mutate a worker env with the agreed world: the forced host-platform
    device count (replacing any previous force flag — the CPU-testable
    mesh lever) plus the DTG_WORLD_* facts for logging/tooling."""
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append(f"--xla_force_host_platform_device_count={world_devices}")
    env["XLA_FLAGS"] = " ".join(flags).strip()
    env["DTG_WORLD_ID"] = str(world["world_id"])
    env["DTG_WORLD_MEMBERS"] = ",".join(world["members"])
    env["DTG_WORLD_DEVICES"] = str(world_devices)
    return env


# ---- the member helper (a peer slice without a local worker) ---------------

def run_member(coord_dir: Path, name: str, *, interval_s: float = 0.2,
               max_beats: Optional[int] = None) -> int:
    """Beat + ack until fenced out (or the fault kills us): the process
    shape of a peer slice's supervisor as seen by the coordination dir.
    Used by the chaos drills (and usable by operators rehearsing one):
    ``DTG_FAULT_SLICE_LOSS=<name>@<beat>`` makes this member die WITHOUT
    retiring its file — the no-cleanup slice loss the liveness timeout
    exists for."""
    member = SliceMember(coord_dir, name)
    negotiator = WorldNegotiator(coord_dir, name)
    was_member = False
    while max_beats is None or member.beats < max_beats:
        if faults.slice_fault(name, member.beats):
            print(f"[elastic-member {name}] injected slice loss at beat "
                  f"{member.beats}", flush=True)
            return 1                  # no retire(): the file goes stale
        member.beat()
        negotiator.maybe_ack()
        world = negotiator.current()
        in_world = bool(world and name in world.get("members", []))
        was_member = was_member or in_world
        if was_member and world and not in_world:
            # exclusion fences only a member the fleet once HELD: a world
            # that predates this member's join (stale world.json on a
            # reused dir, or a scale-up join) must not fence the joiner —
            # it keeps beating until the leader's membership poll admits
            # it
            print(f"[elastic-member {name}] fenced out of world "
                  f"{world['world_id']}; exiting", flush=True)
            member.retire()
            return 0
        time.sleep(interval_s)
    member.retire()
    return 0


def main():
    parser = argparse.ArgumentParser(
        description="elastic coordination member helper (beat + ack)")
    parser.add_argument("--member", required=True,
                        help="this slice's member name")
    parser.add_argument("--dir", required=True,
                        help="the shared --elastic-dir coordination dir")
    parser.add_argument("--interval", type=float, default=0.2)
    parser.add_argument("--max-beats", type=int, default=None)
    args = parser.parse_args()
    raise SystemExit(run_member(Path(args.dir), args.member,
                                interval_s=args.interval,
                                max_beats=args.max_beats))


if __name__ == "__main__":
    main()
