"""Elastic supervisor: restart-on-failure for training workers, hardened.

Parity with torchrun's elasticity (reference ``related-topics/elastic-training/
README.md:5-16``): ``--max-restarts N`` restarts the worker when it fails, and
— like torchrun — recovery correctness comes from the normal resume path
(state.json + checkpoints + sampler fast-forward), not from preserving any
in-process state. That path is world-size-agnostic (``--nnodes=1:4``
equivalence): a restart that comes up on fewer hosts builds a smaller mesh
and the checkpoint reshards into it on restore — see
``related-topics/elastic-training/README.md`` "Dynamic world size" and
``tests/test_data_checkpoint.py::test_elastic_world_size_resume``. Per-attempt logs and error files are kept under
``<log_dir>/attempt_<n>/`` (torchrun's ``--redirects 3 --log-dir``,
``02-distributed-data-parallel/README.md:99-100``).

Restart policy (the part torchrun leaves to the operator):

- **Exponential backoff** between restarts (``--restart-backoff``, doubled
  per attempt up to ``--backoff-cap``): a crash loop against a sick
  filesystem or a recovering TPU runtime must not hammer it at full rate.
- **Poison-pill detection**: after a failure the supervisor reads the
  worker's error file(s) (``ERROR_FILE``, plus the per-rank ``.rankN``
  variants a gang writes) and classifies them (``launch/errors.py``). OOMs,
  shape/sharding errors, and guard-abort NaNs are deterministic functions of
  the config — restarting reproduces them, so the supervisor stops
  immediately instead of burning every attempt (``--restart-on-poison``
  opts back into blind restarts). Error files are unlinked before each
  (re)start and mtime-fenced against the worker's launch time, so a stale
  preset ``$ERROR_FILE`` from a previous incarnation can never classify.

Hang detection: each worker gets ``HEARTBEAT_FILE`` pointed into its attempt
dir; the training loop writes step+timestamp there every iteration
(``utils/heartbeat.py``), and that file going stale for
``--heartbeat-timeout`` seconds means the *loop* stopped — the collective
stall of ``diagnosing-errors/README.md:7-19`` — so the worker is SIGKILLed
and the normal restart policy applies. Workers that never write a heartbeat
(foreign commands, crash before step 1) fall back to the original log-size
heuristic.

Usage:
    python -m distributed_training_guide_tpu.launch.supervisor \
        --max-restarts 3 --log-dir ./logs -- python train_llm.py ...
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

from .errors import classify_error


def _error_file_candidates(error_file: Path) -> list[Path]:
    return [error_file] + sorted(
        error_file.parent.glob(error_file.name + ".rank*"))


def _fence_stale_error_files(error_file: Path) -> None:
    """Remove leftover error files BEFORE (re)starting a worker: when the
    operator presets ``$ERROR_FILE`` in the environment, the same path
    persists across attempts AND across supervisor incarnations, so a stale
    payload from a previous run would classify as a poison pill and wrongly
    stop the restart loop. Best-effort — an unremovable file is additionally
    fenced by mtime in ``_poison_reason``."""
    for path in _error_file_candidates(error_file):
        try:
            path.unlink()
        except OSError:
            pass


def _launch_stamp(attempt_dir: Path) -> float:
    """Filesystem timestamp of 'now', taken by touching a sentinel in the
    attempt dir: the fence below compares error-file mtimes against THIS
    (same filesystem, same clock), so an NFS server whose clock skews from
    the supervisor host can't make a genuine poison file look stale.
    Falls back to host time if the touch fails."""
    stamp = attempt_dir / ".launch_stamp"
    try:
        stamp.touch()
        return stamp.stat().st_mtime
    except OSError:
        return time.time()


def _poison_reason(error_file: Path, launched_at: float = 0.0) -> str | None:
    """First poison classification across the attempt's error files (the
    direct ERROR_FILE plus any per-rank suffixed files a gang produced).
    Files whose mtime predates ``launched_at`` are ignored: only errors the
    just-failed worker actually wrote may classify (the unlink fence above
    can fail on odd filesystems/permissions). ``launched_at`` comes from a
    sentinel touched on the same filesystem at launch, so the comparison is
    clock-consistent; a 2s slack absorbs coarse mtime granularity — worker
    writes are strictly after launch."""
    for path in _error_file_candidates(error_file):
        if not path.is_file():
            continue
        try:
            if path.stat().st_mtime < launched_at - 2.0:
                print(f"[supervisor] ignoring stale error file {path.name} "
                      f"(predates this worker's launch)", flush=True)
                continue
            with open(path) as fp:
                payload = json.load(fp)
        except (OSError, json.JSONDecodeError):
            continue
        reason = classify_error(payload)
        if reason:
            msg = payload.get("message", payload) if isinstance(payload, dict) else {}
            err = msg.get("error", "?") if isinstance(msg, dict) else str(msg)
            return f"{reason}: {err} ({path.name})"
    return None


def run_supervised(cmd: list[str], max_restarts: int, log_dir: Path,
                   heartbeat_timeout: float | None = None, *,
                   restart_backoff: float = 1.0, backoff_cap: float = 60.0,
                   stop_on_poison: bool = True) -> int:
    attempt = 0
    while True:
        attempt_dir = log_dir / f"attempt_{attempt}"
        attempt_dir.mkdir(parents=True, exist_ok=True)
        env = dict(os.environ)
        env.setdefault("ERROR_FILE", str(attempt_dir / "error.json"))
        env["HEARTBEAT_FILE"] = str(attempt_dir / "heartbeat.json")
        _fence_stale_error_files(Path(env["ERROR_FILE"]))
        stdout = open(attempt_dir / "stdout.log", "ab")
        stderr = open(attempt_dir / "stderr.log", "ab")
        print(f"[supervisor] attempt {attempt}: {' '.join(cmd)} -> {attempt_dir}",
              flush=True)
        launched_at = _launch_stamp(attempt_dir)
        proc = subprocess.Popen(cmd, env=env, stdout=stdout, stderr=stderr)

        try:
            if heartbeat_timeout:
                rc = _wait_with_heartbeat(proc, attempt_dir, heartbeat_timeout)
            else:
                rc = proc.wait()
        except KeyboardInterrupt:
            proc.send_signal(signal.SIGTERM)
            proc.wait()
            return 130
        finally:
            stdout.close()
            stderr.close()

        if rc == 0:
            print(f"[supervisor] attempt {attempt} exited cleanly", flush=True)
            return 0
        print(f"[supervisor] attempt {attempt} failed rc={rc} "
              f"(error file: {env['ERROR_FILE']})", flush=True)
        if stop_on_poison:
            reason = _poison_reason(Path(env["ERROR_FILE"]), launched_at)
            if reason:
                print(f"[supervisor] non-retryable failure ({reason}); "
                      f"not restarting — fix the config/data and relaunch",
                      flush=True)
                return rc
        if attempt >= max_restarts:
            print(f"[supervisor] max restarts ({max_restarts}) exhausted", flush=True)
            return rc
        delay = min(backoff_cap, restart_backoff * (2 ** attempt))
        if delay > 0:
            print(f"[supervisor] backing off {delay:.1f}s before attempt "
                  f"{attempt + 1}", flush=True)
            time.sleep(delay)
        attempt += 1


def _progress_stamp(attempt_dir: Path, logs: list[Path]) -> tuple:
    """Liveness observable for hang detection: the worker-written heartbeat
    file once it exists (the positive 'loop is advancing' signal), log sizes
    until then (legacy heuristic — quiet-but-healthy phases can false-
    positive, which is exactly why the heartbeat file exists)."""
    hb = attempt_dir / "heartbeat.json"
    try:
        st = hb.stat()
        return ("heartbeat", st.st_mtime_ns, st.st_size)
    except OSError:
        return ("logs", sum(p.stat().st_size for p in logs if p.exists()))


def _wait_with_heartbeat(proc: subprocess.Popen, attempt_dir: Path,
                         timeout: float) -> int:
    """Kill the worker if its liveness signal stops for `timeout` seconds
    (hang detection — the collective-stall case where the process never
    exits)."""
    logs = [attempt_dir / "stdout.log", attempt_dir / "stderr.log"]
    last_stamp = None
    last_change = time.time()
    while True:
        rc = proc.poll()
        if rc is not None:
            return rc
        stamp = _progress_stamp(attempt_dir, logs)
        now = time.time()
        if stamp != last_stamp:
            last_stamp, last_change = stamp, now
        elif now - last_change > timeout:
            kind = last_stamp[0] if last_stamp else "logs"
            print(f"[supervisor] no {kind} progress for {timeout}s -> "
                  f"SIGKILL (hang)", flush=True)
            proc.kill()
            return proc.wait() or -9
        time.sleep(min(5.0, timeout / 4))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--max-restarts", type=int, default=3)
    parser.add_argument("--log-dir", default="./supervisor-logs")
    parser.add_argument("--heartbeat-timeout", type=float, default=None,
                        help="seconds without heartbeat-file (or, before the "
                             "first beat, log) progress before declaring a "
                             "hang and killing the worker")
    parser.add_argument("--restart-backoff", type=float, default=1.0,
                        help="seconds before the first restart; doubles per "
                             "attempt up to --backoff-cap. 0 disables")
    parser.add_argument("--backoff-cap", type=float, default=60.0)
    parser.add_argument("--restart-on-poison", action="store_true",
                        help="restart even when the error file classifies as "
                             "a deterministic poison pill (OOM, shape/"
                             "sharding, guard abort) — default is to stop")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="-- followed by the worker command")
    args = parser.parse_args()
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        parser.error("no worker command given (use: supervisor [opts] -- cmd ...)")
    sys.exit(run_supervised(cmd, args.max_restarts, Path(args.log_dir),
                            args.heartbeat_timeout,
                            restart_backoff=args.restart_backoff,
                            backoff_cap=args.backoff_cap,
                            stop_on_poison=not args.restart_on_poison))


if __name__ == "__main__":
    main()
