"""Elastic supervisor: restart-on-failure for training workers, hardened.

Parity with torchrun's elasticity (reference ``related-topics/elastic-training/
README.md:5-16``): ``--max-restarts N`` restarts the worker when it fails, and
— like torchrun — recovery correctness comes from the normal resume path
(state.json + checkpoints + sampler fast-forward), not from preserving any
in-process state. That path is world-size-agnostic (``--nnodes=1:4``
equivalence): a restart that comes up on fewer hosts builds a smaller mesh
and the checkpoint reshards into it on restore — see
``related-topics/elastic-training/README.md`` "Dynamic world size" and
``tests/test_data_checkpoint.py::test_elastic_world_size_resume``. Per-attempt logs and error files are kept under
``<log_dir>/attempt_<n>/`` (torchrun's ``--redirects 3 --log-dir``,
``02-distributed-data-parallel/README.md:99-100``).

Restart policy (the part torchrun leaves to the operator):

- **Exponential backoff** between restarts (``--restart-backoff``, doubled
  per attempt up to ``--backoff-cap``): a crash loop against a sick
  filesystem or a recovering TPU runtime must not hammer it at full rate.
- **Poison-pill detection**: after a failure the supervisor reads the
  worker's error file(s) (``ERROR_FILE``, plus the per-rank ``.rankN``
  variants a gang writes) and classifies them (``launch/errors.py``). OOMs,
  shape/sharding errors, and guard-abort NaNs are deterministic functions of
  the config — restarting reproduces them, so the supervisor stops
  immediately instead of burning every attempt (``--restart-on-poison``
  opts back into blind restarts). Error files are unlinked before each
  (re)start and mtime-fenced against the worker's launch time, so a stale
  preset ``$ERROR_FILE`` from a previous incarnation can never classify.

Hang detection: each worker gets ``HEARTBEAT_FILE`` pointed into its attempt
dir; the training loop writes step+timestamp there every iteration
(``utils/heartbeat.py``), and that file going stale for
``--heartbeat-timeout`` seconds means the *loop* stopped — the collective
stall of ``diagnosing-errors/README.md:7-19`` — so the worker is SIGKILLed
and the normal restart policy applies. Workers that never write a heartbeat
(foreign commands, crash before step 1) fall back to the original log-size
heuristic.

World-size renegotiation (``--elastic-dir``, ``launch/elastic.py``): the
supervisor joins a membership directory shared by every slice's
supervisor, and a slice loss/gain becomes "SIGTERM the worker,
renegotiate the world (leader proposes, all ack, barrier'd world.json),
re-exec with the renegotiated mesh config" instead of a crash loop
against the missing slice. The worker command may carry
``{world_devices}`` / ``{world_batch}`` tokens (re-rendered per world),
its env gets the forced host-platform device count for the agreed world,
and every renegotiation appends to the coordination dir's
``elastic.jsonl`` (old world, new world, trigger, wall time) — the
membership timeline post-mortems read. Renegotiation restarts are NOT
failures: they don't consume ``--max-restarts`` and don't back off. The
resume itself is the normal restore path — the checkpoint reshards into
the new world's mesh (``checkpoint/reshard.py``).

Usage:
    python -m distributed_training_guide_tpu.launch.supervisor \
        --max-restarts 3 --log-dir ./logs -- python train_llm.py ...

    # elastic: 2 slices x 4 devices, global batch held at 8
    python -m ...launch.supervisor --elastic-dir /shared/coord \
        --slice-name slice0 --devices-per-slice 4 \
        --elastic-global-batch 8 -- \
        python train_llm.py -b "{world_batch}" ...
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

from .errors import classify_error


def _error_file_candidates(error_file: Path) -> list[Path]:
    return [error_file] + sorted(
        error_file.parent.glob(error_file.name + ".rank*"))


def _fence_stale_error_files(error_file: Path) -> None:
    """Remove leftover error files BEFORE (re)starting a worker: when the
    operator presets ``$ERROR_FILE`` in the environment, the same path
    persists across attempts AND across supervisor incarnations, so a stale
    payload from a previous run would classify as a poison pill and wrongly
    stop the restart loop. Best-effort — an unremovable file is additionally
    fenced by mtime in ``_poison_reason``."""
    for path in _error_file_candidates(error_file):
        try:
            path.unlink()
        except OSError:
            pass


def _launch_stamp(attempt_dir: Path) -> float:
    """Filesystem timestamp of 'now', taken by touching a sentinel in the
    attempt dir: the fence below compares error-file mtimes against THIS
    (same filesystem, same clock), so an NFS server whose clock skews from
    the supervisor host can't make a genuine poison file look stale.
    Falls back to host time if the touch fails."""
    stamp = attempt_dir / ".launch_stamp"
    try:
        stamp.touch()
        return stamp.stat().st_mtime
    except OSError:
        return time.time()


def _poison_reason(error_file: Path, launched_at: float = 0.0) -> str | None:
    """First poison classification across the attempt's error files (the
    direct ERROR_FILE plus any per-rank suffixed files a gang produced).
    Files whose mtime predates ``launched_at`` are ignored: only errors the
    just-failed worker actually wrote may classify (the unlink fence above
    can fail on odd filesystems/permissions). ``launched_at`` comes from a
    sentinel touched on the same filesystem at launch, so the comparison is
    clock-consistent; a 2s slack absorbs coarse mtime granularity — worker
    writes are strictly after launch."""
    for path in _error_file_candidates(error_file):
        if not path.is_file():
            continue
        try:
            if path.stat().st_mtime < launched_at - 2.0:
                print(f"[supervisor] ignoring stale error file {path.name} "
                      f"(predates this worker's launch)", flush=True)
                continue
            with open(path) as fp:
                payload = json.load(fp)
        except (OSError, json.JSONDecodeError):
            continue
        reason = classify_error(payload)
        if reason:
            msg = payload.get("message", payload) if isinstance(payload, dict) else {}
            err = msg.get("error", "?") if isinstance(msg, dict) else str(msg)
            return f"{reason}: {err} ({path.name})"
    return None


def _renegotiate(rt, trigger: str) -> bool:
    """Establish the next world after ``trigger``; False means this slice
    was fenced out of the fleet (the caller exits cleanly — its work is
    covered by the new, smaller world's restore)."""
    from .elastic import FencedOutError

    try:
        world = rt.establish(trigger)
    except FencedOutError as exc:
        print(f"[supervisor] fenced out of the fleet ({exc}); exiting",
              flush=True)
        rt.retire()
        return False
    print(f"[supervisor] world {world['world_id']} agreed "
          f"({trigger}): members {world['members']} -> "
          f"{rt.world_devices()} devices", flush=True)
    return True


def run_supervised(cmd: list[str], max_restarts: int, log_dir: Path,
                   heartbeat_timeout: float | None = None, *,
                   restart_backoff: float = 1.0, backoff_cap: float = 60.0,
                   stop_on_poison: bool = True, elastic=None) -> int:
    rt = None
    if elastic is not None:
        from .elastic import ElasticRuntime

        rt = ElasticRuntime(elastic)
        if not _renegotiate(rt, "start"):
            return 0
    attempt = 0          # FAILURES only — renegotiations are free
    incarnation = 0      # every launch gets its own log dir
    while True:
        attempt_dir = log_dir / f"attempt_{incarnation}"
        attempt_dir.mkdir(parents=True, exist_ok=True)
        env = dict(os.environ)
        env.setdefault("ERROR_FILE", str(attempt_dir / "error.json"))
        env["HEARTBEAT_FILE"] = str(attempt_dir / "heartbeat.json")
        _fence_stale_error_files(Path(env["ERROR_FILE"]))
        launch_cmd = cmd
        if rt is not None:
            from .elastic import render_worker_cmd, worker_world_env

            launch_cmd = render_worker_cmd(cmd, rt.world_devices(),
                                           elastic.global_batch)
            worker_world_env(env, rt.world, rt.world_devices())
        stdout = open(attempt_dir / "stdout.log", "ab")
        stderr = open(attempt_dir / "stderr.log", "ab")
        print(f"[supervisor] attempt {incarnation}: "
              f"{' '.join(launch_cmd)} -> {attempt_dir}", flush=True)
        launched_at = _launch_stamp(attempt_dir)
        proc = subprocess.Popen(launch_cmd, env=env, stdout=stdout,
                                stderr=stderr)

        trigger = None
        try:
            if rt is not None:
                trigger, rc = _wait_elastic(proc, attempt_dir,
                                            heartbeat_timeout, rt)
            elif heartbeat_timeout:
                rc = _wait_with_heartbeat(proc, attempt_dir, heartbeat_timeout)
            else:
                rc = proc.wait()
        except KeyboardInterrupt:
            proc.send_signal(signal.SIGTERM)
            proc.wait()
            if rt is not None:
                rt.retire()
            return 130
        finally:
            stdout.close()
            stderr.close()

        incarnation += 1
        if trigger is not None:
            # a renegotiation restart, NOT a failure: the world changed
            # under the worker — agree on the new one and re-exec with the
            # renegotiated mesh config (no attempt consumed, no backoff)
            print(f"[supervisor] attempt {incarnation - 1} stopped for "
                  f"renegotiation ({trigger})", flush=True)
            if not _renegotiate(rt, trigger):
                return 0
            continue
        if rc == 0:
            print(f"[supervisor] attempt {incarnation - 1} exited cleanly",
                  flush=True)
            if rt is not None:
                rt.retire()
            return 0
        print(f"[supervisor] attempt {incarnation - 1} failed rc={rc} "
              f"(error file: {env['ERROR_FILE']})", flush=True)
        if stop_on_poison:
            reason = _poison_reason(Path(env["ERROR_FILE"]), launched_at)
            if reason:
                print(f"[supervisor] non-retryable failure ({reason}); "
                      f"not restarting — fix the config/data and relaunch",
                      flush=True)
                if rt is not None:
                    rt.retire()   # deliberate stop = clean departure: the
                return rc         # fleet shrinks now, not a timeout later
        if attempt >= max_restarts:
            print(f"[supervisor] max restarts ({max_restarts}) exhausted", flush=True)
            if rt is not None:
                rt.retire()
            return rc
        delay = min(backoff_cap, restart_backoff * (2 ** attempt))
        if delay > 0:
            print(f"[supervisor] backing off {delay:.1f}s before attempt "
                  f"{incarnation}", flush=True)
            if rt is None:
                time.sleep(delay)
            else:
                # keep beating membership AND acking proposals through
                # the backoff: a silent backoff longer than the fleet's
                # liveness timeout would read as a lost slice, and a
                # beat without acks would get this live member dropped
                # as a straggler by any renegotiation that lands in the
                # window — both fence a healthy slice over a transient
                # worker crash
                end = time.time() + delay
                while time.time() < end:
                    rt.member.beat()
                    rt.negotiator.maybe_ack()
                    time.sleep(min(0.25, max(0.0, end - time.time())))
        attempt += 1
        if rt is not None:
            # the failure may BE a membership event (e.g. the gang lost a
            # peer slice and collapsed): re-check before relaunching so the
            # restart comes up on the world that actually exists
            change = rt.poll()
            if change is not None and not _renegotiate(rt, change):
                return 0


def _progress_stamp(attempt_dir: Path, logs: list[Path]) -> tuple:
    """Liveness observable for hang detection: the worker-written heartbeat
    file once it exists (the positive 'loop is advancing' signal), log sizes
    until then (legacy heuristic — quiet-but-healthy phases can false-
    positive, which is exactly why the heartbeat file exists)."""
    hb = attempt_dir / "heartbeat.json"
    try:
        st = hb.stat()
        return ("heartbeat", st.st_mtime_ns, st.st_size)
    except OSError:
        return ("logs", sum(p.stat().st_size for p in logs if p.exists()))


def _wait_with_heartbeat(proc: subprocess.Popen, attempt_dir: Path,
                         timeout: float) -> int:
    """Kill the worker if its liveness signal stops for `timeout` seconds
    (hang detection — the collective-stall case where the process never
    exits)."""
    logs = [attempt_dir / "stdout.log", attempt_dir / "stderr.log"]
    last_stamp = None
    last_change = time.time()
    while True:
        rc = proc.poll()
        if rc is not None:
            return rc
        stamp = _progress_stamp(attempt_dir, logs)
        now = time.time()
        if stamp != last_stamp:
            last_stamp, last_change = stamp, now
        elif now - last_change > timeout:
            kind = last_stamp[0] if last_stamp else "logs"
            print(f"[supervisor] no {kind} progress for {timeout}s -> "
                  f"SIGKILL (hang)", flush=True)
            proc.kill()
            return proc.wait() or -9
        time.sleep(min(5.0, timeout / 4))


def _wait_elastic(proc: subprocess.Popen, attempt_dir: Path,
                  heartbeat_timeout: float | None, rt) \
        -> tuple[str | None, int]:
    """The elastic wait loop: the normal hang detection, PLUS a
    membership tick (beat our member file, ack any live proposal, compare
    liveness against the agreed world). A world change SIGTERMs the
    worker and returns ``(trigger, rc)``; a normal exit returns
    ``(None, rc)``."""
    logs = [attempt_dir / "stdout.log", attempt_dir / "stderr.log"]
    last_stamp = None
    last_change = time.time()
    last_tick = 0.0
    while True:
        rc = proc.poll()
        if rc is not None:
            return None, rc
        now = time.time()
        if now - last_tick >= 0.25:
            last_tick = now
            trigger = rt.poll()
            if trigger is not None:
                print(f"[supervisor] membership changed ({trigger}); "
                      f"stopping worker for world renegotiation", flush=True)
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
                return trigger, proc.returncode
        if heartbeat_timeout:
            stamp = _progress_stamp(attempt_dir, logs)
            if stamp != last_stamp:
                last_stamp, last_change = stamp, now
            elif now - last_change > heartbeat_timeout:
                kind = last_stamp[0] if last_stamp else "logs"
                print(f"[supervisor] no {kind} progress for "
                      f"{heartbeat_timeout}s -> SIGKILL (hang)", flush=True)
                proc.kill()
                return None, proc.wait() or -9
        time.sleep(0.2)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--max-restarts", type=int, default=3)
    parser.add_argument("--log-dir", default="./supervisor-logs")
    parser.add_argument("--heartbeat-timeout", type=float, default=None,
                        help="seconds without heartbeat-file (or, before the "
                             "first beat, log) progress before declaring a "
                             "hang and killing the worker")
    parser.add_argument("--restart-backoff", type=float, default=1.0,
                        help="seconds before the first restart; doubles per "
                             "attempt up to --backoff-cap. 0 disables")
    parser.add_argument("--backoff-cap", type=float, default=60.0)
    parser.add_argument("--restart-on-poison", action="store_true",
                        help="restart even when the error file classifies as "
                             "a deterministic poison pill (OOM, shape/"
                             "sharding, guard abort) — default is to stop")
    parser.add_argument("--elastic-dir", default=None,
                        help="shared coordination dir: join the elastic "
                             "fleet (membership heartbeats + barrier'd "
                             "world agreement + elastic.jsonl events); a "
                             "slice loss renegotiates the world and "
                             "re-execs the worker instead of crash-looping")
    parser.add_argument("--slice-name", default="slice0",
                        help="this supervisor's member name in the fleet")
    parser.add_argument("--devices-per-slice", type=int, default=1,
                        help="devices each live slice contributes; the "
                             "world total drives {world_devices} and the "
                             "forced host-platform device count")
    parser.add_argument("--liveness-timeout", type=float, default=5.0,
                        help="seconds without a membership beat before a "
                             "slice counts as lost")
    parser.add_argument("--elastic-global-batch", type=int, default=None,
                        help="global batch to hold invariant across "
                             "worlds: {world_batch} in the worker command "
                             "renders as global_batch // world_devices")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="-- followed by the worker command")
    args = parser.parse_args()
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        parser.error("no worker command given (use: supervisor [opts] -- cmd ...)")
    elastic = None
    if args.elastic_dir:
        from .elastic import ElasticConfig

        elastic = ElasticConfig(
            coord_dir=Path(args.elastic_dir), member=args.slice_name,
            devices_per_slice=args.devices_per_slice,
            liveness_timeout_s=args.liveness_timeout,
            global_batch=args.elastic_global_batch)
    sys.exit(run_supervised(cmd, args.max_restarts, Path(args.log_dir),
                            args.heartbeat_timeout,
                            restart_backoff=args.restart_backoff,
                            backoff_cap=args.backoff_cap,
                            stop_on_poison=not args.restart_on_poison,
                            elastic=elastic))


if __name__ == "__main__":
    main()
