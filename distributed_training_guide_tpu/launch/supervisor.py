"""Elastic supervisor: restart-on-failure for training workers.

Parity with torchrun's elasticity (reference ``related-topics/elastic-training/
README.md:5-16``): ``--max-restarts N`` restarts the worker when it fails, and
— like torchrun — recovery correctness comes from the normal resume path
(state.json + checkpoints + sampler fast-forward), not from preserving any
in-process state. That path is world-size-agnostic (``--nnodes=1:4``
equivalence): a restart that comes up on fewer hosts builds a smaller mesh
and the checkpoint reshards into it on restore — see
``related-topics/elastic-training/README.md`` "Dynamic world size" and
``tests/test_data_checkpoint.py::test_elastic_world_size_resume``. Per-attempt logs and error files are kept under
``<log_dir>/attempt_<n>/`` (torchrun's ``--redirects 3 --log-dir``,
``02-distributed-data-parallel/README.md:99-100``).

On a TPU pod every host runs this supervisor; when any host's worker dies the
others' collectives stall, so each supervisor also kills its worker when the
coordinator declares a restart (here: worker exit or ``--heartbeat-timeout``
with no log progress — the power-draw-drop hang heuristic of
``diagnosing-errors/README.md:7-19`` in process form).

Usage:
    python -m distributed_training_guide_tpu.launch.supervisor \
        --max-restarts 3 --log-dir ./logs -- python train_llm.py ...
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from pathlib import Path


def run_supervised(cmd: list[str], max_restarts: int, log_dir: Path,
                   heartbeat_timeout: float | None = None) -> int:
    attempt = 0
    while True:
        attempt_dir = log_dir / f"attempt_{attempt}"
        attempt_dir.mkdir(parents=True, exist_ok=True)
        env = dict(os.environ)
        env.setdefault("ERROR_FILE", str(attempt_dir / "error.json"))
        stdout = open(attempt_dir / "stdout.log", "ab")
        stderr = open(attempt_dir / "stderr.log", "ab")
        print(f"[supervisor] attempt {attempt}: {' '.join(cmd)} -> {attempt_dir}",
              flush=True)
        proc = subprocess.Popen(cmd, env=env, stdout=stdout, stderr=stderr)

        try:
            if heartbeat_timeout:
                rc = _wait_with_heartbeat(proc, attempt_dir, heartbeat_timeout)
            else:
                rc = proc.wait()
        except KeyboardInterrupt:
            proc.send_signal(signal.SIGTERM)
            proc.wait()
            return 130
        finally:
            stdout.close()
            stderr.close()

        if rc == 0:
            print(f"[supervisor] attempt {attempt} exited cleanly", flush=True)
            return 0
        print(f"[supervisor] attempt {attempt} failed rc={rc} "
              f"(error file: {env['ERROR_FILE']})", flush=True)
        if attempt >= max_restarts:
            print(f"[supervisor] max restarts ({max_restarts}) exhausted", flush=True)
            return rc
        attempt += 1


def _wait_with_heartbeat(proc: subprocess.Popen, attempt_dir: Path,
                         timeout: float) -> int:
    """Kill the worker if its logs stop growing for `timeout` seconds (hang
    detection — the collective-stall case where the process never exits)."""
    logs = [attempt_dir / "stdout.log", attempt_dir / "stderr.log"]
    last_size = -1
    last_change = time.time()
    while True:
        rc = proc.poll()
        if rc is not None:
            return rc
        size = sum(p.stat().st_size for p in logs if p.exists())
        now = time.time()
        if size != last_size:
            last_size, last_change = size, now
        elif now - last_change > timeout:
            print(f"[supervisor] no log progress for {timeout}s -> SIGKILL (hang)",
                  flush=True)
            proc.kill()
            return proc.wait() or -9
        time.sleep(min(5.0, timeout / 4))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--max-restarts", type=int, default=3)
    parser.add_argument("--log-dir", default="./supervisor-logs")
    parser.add_argument("--heartbeat-timeout", type=float, default=None,
                        help="seconds of log silence before declaring a hang")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="-- followed by the worker command")
    args = parser.parse_args()
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        parser.error("no worker command given (use: supervisor [opts] -- cmd ...)")
    sys.exit(run_supervised(cmd, args.max_restarts, Path(args.log_dir),
                            args.heartbeat_timeout))


if __name__ == "__main__":
    main()
