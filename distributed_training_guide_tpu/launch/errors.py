"""Per-worker error capture.

Parity with torchelastic's ``@record`` decorator + ``TORCHELASTIC_ERROR_FILE``
(reference ``02-distributed-data-parallel/train_llm.py:16,31``,
``diagnosing-errors/README.md:53-66``): on an uncaught exception, write a
machine-readable error file (timestamp, process index, exception, traceback)
before re-raising, so the supervisor on any host can surface *which* worker
failed and why without grepping N logs.

Env: ``ERROR_FILE`` (falls back to ``TORCHELASTIC_ERROR_FILE`` so reference
launch commands port unchanged).
"""
from __future__ import annotations

import functools
import json
import os
import time
import traceback


def error_file_path() -> str | None:
    return os.environ.get("ERROR_FILE") or os.environ.get("TORCHELASTIC_ERROR_FILE")


def write_error_file(exc: BaseException, path: str | None = None) -> None:
    path = path or error_file_path()
    if not path:
        return
    try:
        import jax

        proc = jax.process_index()
    except Exception:
        proc = int(os.environ.get("PROCESS_ID", os.environ.get("RANK", 0)))
    payload = {
        "message": {
            "error": repr(exc),
            "traceback": traceback.format_exc(),
            "process_index": proc,
            "timestamp": int(time.time()),
            "hostname": os.uname().nodename,
            "pid": os.getpid(),
        }
    }
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as fp:
            json.dump(payload, fp, indent=2)
    except OSError:
        pass


def record(fn):
    """Decorator: write the error file on any uncaught exception (the
    reference's ``@record``)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as exc:  # noqa: BLE001 — deliberately broad
            write_error_file(exc)
            raise

    return wrapper
