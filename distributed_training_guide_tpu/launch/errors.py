"""Per-worker error capture.

Parity with torchelastic's ``@record`` decorator + ``TORCHELASTIC_ERROR_FILE``
(reference ``02-distributed-data-parallel/train_llm.py:16,31``,
``diagnosing-errors/README.md:53-66``): on an uncaught exception, write a
machine-readable error file (timestamp, process index, exception, traceback)
before re-raising, so the supervisor on any host can surface *which* worker
failed and why without grepping N logs.

Env: ``ERROR_FILE`` (falls back to ``TORCHELASTIC_ERROR_FILE`` so reference
launch commands port unchanged).
"""
from __future__ import annotations

import functools
import json
import os
import time
import traceback


def error_file_path() -> str | None:
    return os.environ.get("ERROR_FILE") or os.environ.get("TORCHELASTIC_ERROR_FILE")


def write_error_file(exc: BaseException, path: str | None = None) -> None:
    path = path or error_file_path()
    if not path:
        return
    try:
        import jax

        proc = jax.process_index()
    except Exception:
        proc = int(os.environ.get("PROCESS_ID", os.environ.get("RANK", 0)))
    payload = {
        "message": {
            "error": repr(exc),
            # format the EXCEPTION, not the ambient except-state:
            # traceback.format_exc() yields "NoneType: None" for callers
            # outside an active except block (e.g. the guard-abort path,
            # which constructs the exception before raising it)
            "traceback": "".join(traceback.format_exception(exc)),
            "process_index": proc,
            "timestamp": int(time.time()),
            "hostname": os.uname().nodename,
            "pid": os.getpid(),
        }
    }
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as fp:
            json.dump(payload, fp, indent=2)
    except OSError:
        pass


def record(fn):
    """Decorator: write the error file on any uncaught exception (the
    reference's ``@record``)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as exc:  # noqa: BLE001 — deliberately broad
            write_error_file(exc)
            raise

    return wrapper


# ---- failure classification (supervisor restart policy) ---------------------
# Poison pills: failures that are a deterministic function of (config, data,
# code) — restarting reproduces them, so the supervisor should stop instead of
# burning its restart budget (and the pod's queue slot). Two deliberate
# restrictions keep false poisons from breaking elasticity:
# - matched against the error *repr* only: tracebacks mention files like
#   jax/_src/sharding_impls.py for unrelated errors;
# - only patterns SPECIFIC to deterministic failures. Generic markers like
#   "INVALID_ARGUMENT" also prefix collateral errors on surviving ranks when
#   a peer dies mid-collective (e.g. "INVALID_ARGUMENT: Multiprocess
#   computations aren't implemented..." from a torn-down gang) — classifying
#   those as poison would refuse exactly the restart elasticity exists for.
POISON_PATTERNS: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("oom", ("RESOURCE_EXHAUSTED", "out of memory", "MemoryError",
             "hbm usage")),
    ("shape/sharding", ("not divisible", "divisible by", "NamedSharding",
                        "incompatible shapes", "shape mismatch")),
    ("non-finite", ("NonFiniteLossError",)),
)


def classify_error(payload: dict) -> str | None:
    """Reason string when the error file describes a poison pill, else None
    (= unknown/transient: restart is worth trying). Tolerates foreign error
    files where "message" is a plain string rather than our dict shape —
    the supervisor runs arbitrary worker commands."""
    msg = payload.get("message", payload) if isinstance(payload, dict) else {}
    if not isinstance(msg, dict):
        msg = {"error": str(msg)}
    text = str(msg.get("error", ""))
    lowered = text.lower()
    for reason, patterns in POISON_PATTERNS:
        if any(p.lower() in lowered for p in patterns):
            return reason
    return None
