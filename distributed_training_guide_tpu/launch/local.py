"""Single-host multi-process gang launcher.

Parity with ``torchrun --standalone --nproc-per-node N`` (reference
``02-distributed-data-parallel/README.md:96``, ``03-job-launchers/README.md``):
spawn N copies of a worker command on this host with the rendezvous env
contract ``launch/distributed.py`` consumes (``MASTER_ADDR``/``MASTER_PORT``,
``WORLD_SIZE``, ``RANK``), stream rank 0 through, and enforce **fail-fast gang
semantics**: the first worker to exit nonzero takes the whole gang down
(SIGTERM, then SIGKILL after a grace period). That is the local half of
torchrun's elastic agent — the restart-all half is ``launch/supervisor.py``
wrapping this launcher, so a crash of any rank becomes one nonzero gang exit
the supervisor restarts as a unit (reference ``related-topics/
elastic-training/README.md:5-16``).

On real TPU pods JAX runs one process per host and rendezvous comes from the
pod metadata, so this launcher is for: CPU/GPU-style multi-process hosts,
and — with ``--devices-per-proc K`` — simulating an N-process pod on one
machine with K virtual CPU devices per process (the regime the multi-process
tests run; ``tests/test_multiprocess.py``).

Usage:
    python -m distributed_training_guide_tpu.launch.local --nproc 2 \
        --devices-per-proc 4 -- python 02-.../train_llm.py ...
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

GRACE_SECONDS = 10.0


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_gang(
    cmd: list[str],
    nproc: int,
    *,
    port: int | None = None,
    devices_per_proc: int | None = None,
    log_dir: str | os.PathLike | None = None,
    env_extra: dict[str, str] | None = None,
    poll_interval: float = 0.2,
) -> int:
    """Run ``nproc`` copies of ``cmd`` as one gang; return the gang exit code.

    0 iff every rank exited 0. On the first nonzero exit the remaining ranks
    are terminated (collectives on the survivors would otherwise stall — the
    reference's NCCL-hang failure mode, ``diagnosing-errors/README.md:7-19``).
    Rank 0 inherits this process's stdout/stderr; other ranks write to
    ``<log_dir>/rank<i>.{out,err}`` (or are silenced without a log_dir).
    """
    port = port or free_port()
    procs: list[subprocess.Popen] = []
    files: list = []
    log_path = Path(log_dir) if log_dir else None
    if log_path:
        log_path.mkdir(parents=True, exist_ok=True)
    try:
        for rank in range(nproc):
            env = dict(os.environ)
            env.update(env_extra or {})
            env.update(MASTER_ADDR="127.0.0.1", MASTER_PORT=str(port),
                       WORLD_SIZE=str(nproc), RANK=str(rank))
            if env.get("ERROR_FILE"):   # per-rank error files, like torchelastic
                env["ERROR_FILE"] = f"{env['ERROR_FILE']}.rank{rank}"
            if devices_per_proc:
                env["JAX_PLATFORMS"] = "cpu"
                # append (not replace) so callers' dump/debug flags survive;
                # last occurrence of a repeated flag wins, so ours goes last
                env["XLA_FLAGS"] = (
                    env.get("XLA_FLAGS", "") +
                    f" --xla_force_host_platform_device_count={devices_per_proc}"
                ).strip()
            if rank == 0:
                stdout = stderr = None      # stream through
            elif log_path:
                stdout = open(log_path / f"rank{rank}.out", "ab")
                stderr = open(log_path / f"rank{rank}.err", "ab")
                files += [stdout, stderr]
            else:
                stdout = stderr = subprocess.DEVNULL
            procs.append(subprocess.Popen(cmd, env=env, stdout=stdout,
                                          stderr=stderr))

        gang_rc = 0
        while True:
            rcs = [p.poll() for p in procs]
            failed = [rc for rc in rcs if rc not in (None, 0)]
            if failed:
                gang_rc = failed[0]
                break
            if all(rc == 0 for rc in rcs):
                break
            time.sleep(poll_interval)
        return gang_rc
    finally:
        # runs on EVERY exit path — normal (no-op: all ranks reaped), gang
        # failure, spawn errors, or the launcher itself dying (SIGINT,
        # exception): spawned ranks must never be orphaned blocked in
        # rendezvous/collectives waiting for peers that will never come
        _terminate_survivors(procs)
        for f in files:
            f.close()


def _terminate_survivors(procs: list[subprocess.Popen]) -> None:
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.time() + GRACE_SECONDS
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def main():
    parser = argparse.ArgumentParser(
        description="single-host gang launcher (torchrun --standalone analogue)")
    parser.add_argument("--nproc", type=int, required=True)
    parser.add_argument("--port", type=int, default=None,
                        help="rendezvous port (default: pick a free one)")
    parser.add_argument("--devices-per-proc", type=int, default=None,
                        help="force CPU with this many virtual devices per "
                             "process (pod simulation)")
    parser.add_argument("--log-dir", default=None,
                        help="per-rank logs for ranks > 0 (rank 0 streams)")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="-- followed by the worker command")
    args = parser.parse_args()
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        parser.error("no worker command given (use: local [opts] -- cmd ...)")
    sys.exit(launch_gang(cmd, args.nproc, port=args.port,
                         devices_per_proc=args.devices_per_proc,
                         log_dir=args.log_dir))


if __name__ == "__main__":
    main()
