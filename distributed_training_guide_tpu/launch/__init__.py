from .distributed import maybe_initialize_distributed
from .local import launch_gang

__all__ = ["maybe_initialize_distributed", "launch_gang"]
