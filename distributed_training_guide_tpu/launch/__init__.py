from .distributed import maybe_initialize_distributed

__all__ = ["maybe_initialize_distributed"]
