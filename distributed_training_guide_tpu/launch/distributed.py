"""Multi-host runtime initialization.

The reference's process bootstrap is torchrun + ``dist.init_process_group``
reading ``RANK``/``WORLD_SIZE``/``MASTER_ADDR`` (``02-distributed-data-parallel/
train_llm.py:36-41``, ``03-job-launchers/README.md``). JAX is one process per
*host*; on TPU pods the runtime discovers coordinator/process-id/process-count
from the TPU metadata, so ``jax.distributed.initialize()`` needs no arguments.
For CPU/GPU clusters (or explicit control) we honor the same env contract the
reference uses, mapped to JAX names.

Env contract (all optional on TPU pods):
    COORDINATOR_ADDRESS (or MASTER_ADDR:MASTER_PORT)
    NUM_PROCESSES       (or WORLD_SIZE)
    PROCESS_ID          (or RANK)
"""
from __future__ import annotations

import logging
import os

import jax

LOGGER = logging.getLogger(__name__)


def maybe_initialize_distributed() -> None:
    """Idempotent; no-op for single-process runs.

    NB: must not touch ``jax.devices()``/``jax.process_count()`` before
    deciding — querying them initializes the local backend, after which
    ``jax.distributed.initialize`` raises.
    """
    try:
        if jax.distributed.is_initialized():
            return
    except AttributeError:  # older jax
        from jax._src import distributed as _dist

        if _dist.global_state.client is not None:
            return

    coord = os.environ.get("COORDINATOR_ADDRESS")
    if coord is None and os.environ.get("MASTER_ADDR"):
        coord = f"{os.environ['MASTER_ADDR']}:{os.environ.get('MASTER_PORT', '8476')}"
    nproc = os.environ.get("NUM_PROCESSES") or os.environ.get("WORLD_SIZE")
    pid = os.environ.get("PROCESS_ID") or os.environ.get("RANK")

    try:
        if coord and nproc is not None and pid is not None:
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=int(nproc),
                                       process_id=int(pid))
            LOGGER.info(f"distributed: initialized process {pid}/{nproc} via {coord}")
        elif os.environ.get("TPU_WORKER_HOSTNAMES") or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
            jax.distributed.initialize()  # TPU pod auto-discovery
            LOGGER.info(
                f"distributed: TPU pod auto-init, process "
                f"{jax.process_index()}/{jax.process_count()}")
    except Exception as e:  # single-host dev boxes: fall through
        LOGGER.warning(f"distributed init skipped: {e}")
