"""Causal-LM loss.

The reference relies on HF's internal loss (labels = input_ids, shift done by
the model — see data pipeline ``01-single-gpu/train_llm.py:234`` where
``labels = input_ids.copy()``). Here the shift lives in the loss so the model
stays a pure logits function. Log-softmax is computed in float32.

Padding/ignored positions use the HF convention: ``label == -100`` masks the
position out of the mean.
"""
from __future__ import annotations

import jax.numpy as jnp
import jax

IGNORE_INDEX = -100


def causal_lm_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean next-token cross-entropy.

    logits: [B, S, V]; labels: [B, S] (same tokens as inputs, shifted here).
    """
    logits = logits[:, :-1, :].astype(jnp.float32)
    targets = labels[:, 1:]
    valid = targets != IGNORE_INDEX
    safe_targets = jnp.where(valid, targets, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, safe_targets[..., None], axis=-1)[..., 0]
    nll = (logz - picked) * valid
    return nll.sum() / jnp.maximum(valid.sum(), 1)
