"""Causal-LM loss.

The reference relies on HF's internal loss (labels = input_ids, shift done by
the model — see data pipeline ``01-single-gpu/train_llm.py:234`` where
``labels = input_ids.copy()``). Here the shift lives in the loss so the model
stays a pure logits function. Log-softmax is computed in float32.

Padding/ignored positions use the HF convention: ``label == -100`` masks the
position out of the mean.
"""
from __future__ import annotations

import jax.numpy as jnp
import jax

IGNORE_INDEX = -100


def causal_lm_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean next-token cross-entropy.

    logits: [B, S, V]; labels: [B, S] (same tokens as inputs, shifted here).
    """
    logits = logits[:, :-1, :].astype(jnp.float32)
    targets = labels[:, 1:]
    valid = targets != IGNORE_INDEX
    safe_targets = jnp.where(valid, targets, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, safe_targets[..., None], axis=-1)[..., 0]
    nll = (logz - picked) * valid
    return nll.sum() / jnp.maximum(valid.sum(), 1)


def chunked_causal_lm_loss(hidden: jnp.ndarray, w_out: jnp.ndarray,
                           labels: jnp.ndarray, num_chunks: int = 8,
                           logits_sharding=None) -> jnp.ndarray:
    """Cross-entropy straight from the final hidden states, never
    materializing full [B, S, V] logits.

    The fp32 logits (+ their cotangent) are the activation-memory limiter for
    big-vocab models — llama-3 at V=128k, B=8, S=2048 is ~8.4 GB just for
    logits. Here the (shifted) sequence is processed in ``num_chunks`` scanned
    slices: each slice computes its own logits [B, S/chunks, V], reduces to
    (nll_sum, count) and drops them; ``jax.checkpoint`` on the body makes the
    backward recompute each slice's logits too, so peak memory falls by
    ~num_chunks at the cost of one extra lm_head matmul pass.

    hidden: [B, S, E]; w_out: [E, V]; labels: [B, S].
    """
    b, s, e = hidden.shape
    h = hidden[:, :-1, :]
    targets = labels[:, 1:]
    n = s - 1
    # pad to a multiple of num_chunks with ignored positions
    pad = (-n) % num_chunks
    if pad:
        h = jnp.pad(h, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)),
                          constant_values=IGNORE_INDEX)
    chunk = (n + pad) // num_chunks
    h = h.reshape(b, num_chunks, chunk, e).transpose(1, 0, 2, 3)      # [C,B,c,E]
    targets = targets.reshape(b, num_chunks, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def body(carry, xs):
        nll_sum, count = carry
        h_c, t_c = xs
        logits = jnp.einsum("bce,ev->bcv", h_c, w_out,
                            preferred_element_type=jnp.float32)
        if logits_sharding is not None:  # loss-parallel: vocab stays sharded
            logits = jax.lax.with_sharding_constraint(logits, logits_sharding)
        valid = t_c != IGNORE_INDEX
        safe = jnp.where(valid, t_c, 0)
        logz = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        nll = (logz - picked) * valid
        return (nll_sum + nll.sum(), count + valid.sum()), None

    (nll_sum, count), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
        (h, targets))
    return nll_sum / jnp.maximum(count, 1)


def validate_chunked_loss_support(family_mod, family: str, loss_fn) -> None:
    """Common preconditions for the chunked loss (checked by both the plain
    and the pipeline step builders)."""
    if not hasattr(family_mod, "output_weights"):
        raise NotImplementedError(
            f"loss_chunks unsupported for family {family!r}")
    if loss_fn is not causal_lm_loss:
        raise NotImplementedError(
            "loss_chunks hardwires the causal-LM loss; drop the custom "
            "loss_fn or the chunking")
