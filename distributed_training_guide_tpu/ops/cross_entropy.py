"""Causal-LM loss.

The reference relies on HF's internal loss (labels = input_ids, shift done by
the model — see data pipeline ``01-single-gpu/train_llm.py:234`` where
``labels = input_ids.copy()``). Here the shift lives in the loss so the model
stays a pure logits function. Log-softmax is computed in float32.

Padding/ignored positions use the HF convention: ``label == -100`` masks the
position out of the mean.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import jax

IGNORE_INDEX = -100


def causal_lm_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean next-token cross-entropy.

    logits: [B, S, V]; labels: [B, S] (same tokens as inputs, shifted here).
    """
    logits = logits[:, :-1, :].astype(jnp.float32)
    targets = labels[:, 1:]
    valid = targets != IGNORE_INDEX
    safe_targets = jnp.where(valid, targets, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, safe_targets[..., None], axis=-1)[..., 0]
    nll = (logz - picked) * valid
    return nll.sum() / jnp.maximum(valid.sum(), 1)


def chunked_causal_lm_loss(hidden: jnp.ndarray, w_out: jnp.ndarray,
                           labels: jnp.ndarray, num_chunks: int = 8,
                           logits_sharding=None) -> jnp.ndarray:
    """Cross-entropy straight from the final hidden states, never
    materializing full [B, S, V] logits.

    The fp32 logits (+ their cotangent) are the activation-memory limiter for
    big-vocab models — llama-3 at V=128k, B=8, S=2048 is ~8.4 GB just for
    logits. Here the (shifted) sequence is processed in ``num_chunks`` scanned
    slices: each slice computes its own logits [B, S/chunks, V], reduces to
    (nll_sum, count) and drops them; ``jax.checkpoint`` on the body makes the
    backward recompute each slice's logits too, so peak memory falls by
    ~num_chunks at the cost of one extra lm_head matmul pass.

    hidden: [B, S, E]; w_out: [E, V]; labels: [B, S].
    """
    b, s, e = hidden.shape
    h = hidden[:, :-1, :]
    targets = labels[:, 1:]
    n = s - 1
    # pad to a multiple of num_chunks with ignored positions
    pad = (-n) % num_chunks
    if pad:
        h = jnp.pad(h, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)),
                          constant_values=IGNORE_INDEX)
    chunk = (n + pad) // num_chunks
    h = h.reshape(b, num_chunks, chunk, e).transpose(1, 0, 2, 3)      # [C,B,c,E]
    targets = targets.reshape(b, num_chunks, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def body(carry, xs):
        nll_sum, count = carry
        h_c, t_c = xs
        logits = jnp.einsum("bce,ev->bcv", h_c, w_out,
                            preferred_element_type=jnp.float32)
        if logits_sharding is not None:  # loss-parallel: vocab stays sharded
            logits = jax.lax.with_sharding_constraint(logits, logits_sharding)
        valid = t_c != IGNORE_INDEX
        safe = jnp.where(valid, t_c, 0)
        logz = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        nll = (logz - picked) * valid
        return (nll_sum + nll.sum(), count + valid.sum()), None

    (nll_sum, count), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
        (h, targets))
    return nll_sum / jnp.maximum(count, 1)


def _fused_nll_kernel(vocab_axis: Optional[str]):
    """custom-VJP core of the fused hidden->loss: chunked NLL straight from
    (hidden rows, output weights) with the logits recomputed per chunk in
    backward — peak live logits are one ``[chunk, V_local]`` fp32 slice in
    BOTH passes, and the only residual beyond the inputs is the [rows] fp32
    logz vector.

    vs the ``jax.checkpoint``-based ``chunked_causal_lm_loss``: same forward
    math, but the backward skips the checkpoint replay's logsumexp/gather
    recompute (logz is a saved residual) and spells the softmax-minus-onehot
    cotangent directly; with ``vocab_axis`` set the chunk math runs
    vocab-parallel (ops/vocab_parallel.py psums), composing the chunked loss
    with a tp logits shard — the combination the separate paths could not
    express. Weight cotangents accumulate in fp32 across chunks and narrow
    once at the end.

    Operands (shard-local): h [C, c, E] chunked rows, w [E, V_local],
    t [C, c] GLOBAL target ids (-100 = ignore). Returns the local nll sum.
    """
    from .vocab_parallel import (shard_local_targets, sharded_logsumexp,
                                 sharded_pick)

    def chunk_logits(h_c, w):
        return jnp.dot(h_c, w, preferred_element_type=jnp.float32)

    @jax.custom_vjp
    def nll_sum(h, w, t):
        def body(acc, xs):
            acc, _ = fwd_chunk(acc, xs, w)
            return acc, None

        acc, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (h, t))
        return acc

    def fwd_chunk(acc, xs, w):
        h_c, t_c = xs
        logits = chunk_logits(h_c, w)
        valid = t_c != IGNORE_INDEX
        if vocab_axis is not None:
            logz = sharded_logsumexp(logits, vocab_axis)
            picked = sharded_pick(logits, t_c, valid, vocab_axis)
        else:
            logz = jax.nn.logsumexp(logits, axis=-1)
            safe = jnp.where(valid, t_c, 0)
            picked = jnp.take_along_axis(logits, safe[..., None],
                                         axis=-1)[..., 0]
        return acc + jnp.sum((logz - picked) * valid), logz

    def fwd(h, w, t):
        def body(carry, xs):
            acc, logz = fwd_chunk(carry, xs, w)
            return acc, logz

        acc, logzs = jax.lax.scan(body, jnp.zeros((), jnp.float32), (h, t))
        return acc, (h, w, t, logzs)

    def bwd(res, g):
        h, w, t, logzs = res
        v_local = w.shape[-1]
        if vocab_axis is not None:
            # the enclosing region's replicated scalar output splits its
            # cotangent 1/axis_size across the manual vocab axis
            # (check_vma=False adjoint of an out_spec that drops the axis).
            # w_local feeds every member's (identical) loss output, so its
            # true cotangent is the members' SUM — psum restores it. dh
            # needs no such correction: its exit collectives (the SP
            # gather's psum_scatter transpose, or the unmentioned-axis psum
            # on a replicated hidden) already sum the split pieces back.
            # Pinned at grad level vs the dense reference in
            # tests/test_overlap.py (the trajectory tests alone can't catch
            # a uniform scale — Adam updates are invariant to it).
            g_w = jax.lax.psum(g, vocab_axis)
        else:
            g_w = g

        def body(dw_acc, xs):
            h_c, t_c, logz_c = xs
            logits = chunk_logits(h_c, w)           # recompute, one chunk live
            p = jnp.exp(logits - logz_c[..., None])  # softmax w/ GLOBAL logz
            valid = t_c != IGNORE_INDEX
            if vocab_axis is not None:
                safe, in_shard = shard_local_targets(t_c, valid, v_local,
                                                     vocab_axis)
                onehot = ((jnp.arange(v_local) == safe[..., None]) & in_shard[..., None])
            else:
                safe = jnp.where(valid, t_c, 0)
                onehot = jnp.arange(v_local) == safe[..., None]
            dl = (p - onehot.astype(jnp.float32)) * (valid * g)[..., None]
            dh_c = jnp.dot(dl, w.T, preferred_element_type=jnp.float32)
            if vocab_axis is not None:   # sum over the full vocab dim
                dh_c = jax.lax.psum(dh_c, vocab_axis)
            dl_w = (dl if g_w is g else
                    (p - onehot.astype(jnp.float32)) * (valid * g_w)[..., None])
            dw_acc = dw_acc + jnp.dot(h_c.T, dl_w,
                                      preferred_element_type=jnp.float32)
            return dw_acc, dh_c.astype(h.dtype)

        dw, dh = jax.lax.scan(body, jnp.zeros(w.shape, jnp.float32),
                              (h, t, logzs))
        return dh, dw.astype(w.dtype), None

    nll_sum.defvjp(fwd, bwd)
    return nll_sum


def fused_linear_cross_entropy(hidden: jnp.ndarray, w_out: jnp.ndarray,
                               labels: jnp.ndarray, *, num_chunks: int = 8,
                               vocab_axis: Optional[str] = None):
    """Shard-local fused hidden->loss: shift, flatten to rows, pad to the
    chunk grid, run the custom-VJP kernel. Returns ``(nll_sum, count)`` as
    fp32 scalars — LOCAL sums; the caller owns the cross-shard mean (see
    ``ops.overlap.make_fused_loss`` for the shard_map wrapper).

    hidden [B, S, E]; w_out [E, V_local]; labels [B, S] with -100 ignored.
    """
    b, s, e = hidden.shape
    h = hidden[:, :-1, :].reshape(b * (s - 1), e)
    t = labels[:, 1:].reshape(b * (s - 1))
    n = h.shape[0]
    pad = (-n) % num_chunks
    if pad:
        h = jnp.pad(h, ((0, pad), (0, 0)))
        t = jnp.pad(t, (0, pad), constant_values=IGNORE_INDEX)
    chunk = (n + pad) // num_chunks
    h = h.reshape(num_chunks, chunk, e)
    t = t.reshape(num_chunks, chunk)
    nll = _fused_nll_kernel(vocab_axis)(h, w_out, t)
    count = jnp.sum(t != IGNORE_INDEX).astype(jnp.float32)
    return nll, count


def validate_chunked_loss_support(family_mod, family: str, loss_fn) -> None:
    """Common preconditions for the chunked loss (checked by both the plain
    and the pipeline step builders)."""
    if not hasattr(family_mod, "output_weights"):
        raise NotImplementedError(
            f"loss_chunks unsupported for family {family!r}")
    if loss_fn is not causal_lm_loss:
        raise NotImplementedError(
            "loss_chunks hardwires the causal-LM loss; drop the custom "
            "loss_fn or the chunking")
