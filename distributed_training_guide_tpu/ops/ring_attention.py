"""Zigzag ring attention: context parallelism over the ``cp`` mesh axis.

The reference name-checks context parallelism ("For long context lengths",
``06-tensor-parallel/README.md:7``) but never implements it — its long-context
story is flash-attn + activation checkpointing + a seq-length flag. For the
TPU build CP is first-class: the sequence dim of the *batch and activations*
is sharded over ``cp`` contiguously (plain GSPMD sharding — data pipeline,
RoPE and loss never see anything unusual), and attention — the only op that
crosses sequence shards — runs inside a shard_map where only ``cp`` is
manual:

- **zigzag load balance**: under causal masking, contiguous shards give rank
  cp-1 ~cp x the work of rank 0 (it attends to every earlier shard). Here the
  sequence is viewed as 2*cp chunks and two static ppermutes re-layout each
  rank's (q, k, v) to the zigzag pair (chunk r, chunk 2cp-1-r) before the
  ring, so every rank owns one early and one late chunk — per-rank live
  chunk-pairs are (r+1) + (2cp-r) = 2cp+1, identical for all ranks. Outputs
  are re-layouted back, so the wrapper is layout-transparent.
- **ring**: K/V zigzag blocks rotate via ``jax.lax.ppermute`` (neighbor ICI
  hops), overlapping transfer with compute; per-pair partial results merge
  with the standard (o, lse) online-softmax combine in fp32.
- **flash kernel per chunk pair**: each live (q-chunk, kv-chunk) pair runs
  the Pallas flash kernel (``flash_attention._flash_fwd``) — scores never
  materialize outside VMEM tiles, and GQA is kernel-native (no K/V
  expansion). Future pairs are *skipped* by ``lax.cond`` (no FLOPs issued);
  diagonal pairs use the kernel's causal mode.
- **hand-written ring backward** (``jax.custom_vjp``): the backward re-runs
  the ring with the *global* logsumexp and ``delta = rowsum(do*o)`` feeding
  ``flash_bwd_with_stats`` per pair — the flash-attention identity that
  makes per-chunk gradient contributions exact without any full attention
  matrix. dk/dv accumulators travel the ring *with* their K/V blocks and
  arrive home after a full cycle.

tp composes: heads (tp) and batch (dp/fsdp/ep) are *manual* axes of the
same shard_map — the Pallas calls inside the ring are Mosaic custom calls
the SPMD partitioner cannot shard, so leaving them auto would gather and
replicate every hop's chunks across dp/tp on a real pod. The body needs no
collectives over those axes (attention is independent per batch and head),
so only cp carries ppermutes. Round 1's partitioner CHECK came from
auto-tp *weights* inside a manual region; q/k/v here are already-projected
activations, which shard cleanly.

On non-TPU backends the same kernels run under ``interpret=True`` — the
test-suite goldens (forward and gradients vs the dense XLA reference) cover
exactly this code path.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, PartitionSpec as P

from .flash_attention import (_flash_fwd, _pack_band, check_static_window,
                              flash_bwd_with_stats)

NEG_INF = -1e30


def _zigzag_perms(cp: int):
    """Static ppermute lists for contiguous->zigzag relayout.

    Contiguous rank r holds chunks (2r, 2r+1); zigzag rank r holds chunks
    (r, 2cp-1-r). Chunk c's zigzag owner is c if c < cp else 2cp-1-c. Each
    rank's half-h block (chunk 2r+h) has one destination -> one static perm
    per half.
    """
    def owner(c):
        return c if c < cp else 2 * cp - 1 - c

    perm0 = [(r, owner(2 * r)) for r in range(cp)]
    perm1 = [(r, owner(2 * r + 1)) for r in range(cp)]
    inv0 = [(d, s) for (s, d) in perm0]
    inv1 = [(d, s) for (s, d) in perm1]
    return perm0, perm1, inv0, inv1


def _to_zigzag(x, idx, axis_name, cp):
    """[B, S_loc, ...] contiguous shard -> [B, 2, S_c, ...] zigzag chunks."""
    b, s_loc = x.shape[:2]
    s_c = s_loc // 2
    halves = x.reshape(b, 2, s_c, *x.shape[2:])
    perm0, perm1, _, _ = _zigzag_perms(cp)
    recv_a = jax.lax.ppermute(halves[:, 0], axis_name, perm0)
    recv_b = jax.lax.ppermute(halves[:, 1], axis_name, perm1)
    # chunk r has parity r%2 -> arrives via that perm; chunk 2cp-1-r has the
    # opposite parity (2cp-1-r == 1-r mod 2), so there is never a collision
    even = (idx % 2) == 0
    slot0 = jnp.where(even, recv_a, recv_b)
    slot1 = jnp.where(even, recv_b, recv_a)
    return jnp.stack([slot0, slot1], axis=1)


def _from_zigzag(x, idx, axis_name, cp):
    """Inverse of ``_to_zigzag``: [B, 2, S_c, ...] -> [B, S_loc, ...]."""
    _, _, inv0, inv1 = _zigzag_perms(cp)
    even = (idx % 2) == 0
    # undo the slot selection, then the permutes
    recv_a = jnp.where(even, x[:, 0], x[:, 1])
    recv_b = jnp.where(even, x[:, 1], x[:, 0])
    half0 = jax.lax.ppermute(recv_a, axis_name, inv0)
    half1 = jax.lax.ppermute(recv_b, axis_name, inv1)
    stacked = jnp.stack([half0, half1], axis=1)
    b = x.shape[0]
    return stacked.reshape(b, -1, *x.shape[3:])


def _merge(o, lse, o_i, lse_i):
    """Combine two normalized flash partials ([B,H,S,D] fp32, [B,H,S] fp32)."""
    mx = jnp.maximum(lse, lse_i)
    mx_safe = jnp.where(mx < NEG_INF / 2, 0.0, mx)  # both-empty rows
    w0 = jnp.exp(lse - mx_safe)
    w1 = jnp.exp(lse_i - mx_safe)
    tot = w0 + w1
    safe_tot = jnp.where(tot == 0.0, 1.0, tot)
    o_new = (o * w0[..., None] + o_i * w1[..., None]) / safe_tot[..., None]
    lse_new = jnp.where(tot == 0.0, NEG_INF, mx_safe + jnp.log(safe_tot))
    return o_new, lse_new


def _relation(kv_chunk, q_chunk, causal):
    """0 past (full attention) / 1 diagonal (causal) / 2 future (skip)."""
    if not causal:
        return jnp.int32(0)
    return jnp.where(kv_chunk == q_chunk, 1,
                     jnp.where(kv_chunk < q_chunk, 0, 2))


def _pair_live(kv_chunk, q_chunk, s_c, window):
    """Banded-mode chunk-pair skip predicate: a (q-chunk, kv-chunk) pair is
    dead when it is FUTURE (kv newer than q) or when every key in the kv
    chunk falls below the sliding-window band of every query in the q chunk
    — the zigzag analogue of the kernel's ``_band_live`` tile skip, at
    chunk granularity. ``window`` is a traced scalar (per-layer schedules);
    2**30 encodes "full attention this layer" and keeps every past pair
    live."""
    live = kv_chunk <= q_chunk
    # newest key in the kv chunk still inside the OLDEST query's window
    live &= (kv_chunk + 1) * s_c - 1 >= q_chunk * s_c - (window - 1)
    return live


def _build_ring(axis_name: str, cp: int, causal: bool, interpret: bool,
                use_scan: bool, scale=None, softcap=None):
    """Per-shard fwd/bwd ring bodies (flash kernel per chunk pair). The
    custom_vjp pairing them lives OUTSIDE the shard_map (make_ring_attention)
    so shard_map's own transpose machinery is never engaged.

    ``member`` (size-1 int32, the cp-sharded iota) carries this member's
    ring position instead of ``jax.lax.axis_index``: when the ring nests
    inside the pipeline's pp-manual region, Shardy lowers axis_index of an
    auto-queried axis as a manual computation over the *complement* axes —
    which re-binds pp and is rejected ("already bound by a parent"). A
    sharded iota argument carries the same value with no such lowering.

    ``use_scan``: roll the cp hops into one ``lax.scan`` iteration instead
    of Python-unrolling them. The per-pair relation codes are traced values
    either way (they derive from the member index), so the two forms are
    op-for-op identical per hop — the scan form just makes program size and
    trace/compile time O(1) in cp instead of O(cp), at the cost of one
    extra (unused) kv rotation on the final hop. ``make_ring_attention``
    picks scan automatically at large cp."""
    ring = [(i, (i + 1) % cp) for i in range(cp)]

    def _fwd_pairs(qz, k_blk, v_blk, o, lse, my_chunks, kv_chunks,
                   window=None, s_c=None):
        """The 4 (q-chunk, kv-chunk) flash calls of one hop, merged into
        the running (o, lse). Future pairs — and, in banded mode, pairs
        fully below the sliding-window band — skip inside the cond, merge
        included, so they issue no work. In banded mode (``window`` a
        traced scalar) every live pair runs the kernel causal with its
        GLOBAL chunk offsets riding the dynamic band operand: diagonal and
        past pairs share one program, and the in-kernel band mask is exact
        across chunk boundaries."""
        for a in range(2):
            for c in range(2):
                qa, kc, vc = qz[a], k_blk[c], v_blk[c]
                o_a, lse_a = o[a], lse[a]

                if window is not None:
                    band = _pack_band(window, my_chunks[a] * s_c,
                                      kv_chunks[c] * s_c)

                    def live_banded(qa=qa, kc=kc, vc=vc, o_a=o_a,
                                    lse_a=lse_a, band=band):
                        o_i, lse_i = _flash_fwd(
                            qa, kc, vc, True, None, 512, 512, interpret,
                            scale=scale, softcap=softcap, band=band)
                        return _merge(o_a, lse_a, o_i.astype(jnp.float32),
                                      lse_i)

                    o_a, lse_a = jax.lax.cond(
                        _pair_live(kv_chunks[c], my_chunks[a], s_c, window),
                        live_banded, lambda: (o_a, lse_a))
                else:
                    rel = _relation(kv_chunks[c], my_chunks[a], causal)

                    def live(masked, qa=qa, kc=kc, vc=vc, o_a=o_a,
                             lse_a=lse_a):
                        o_i, lse_i = _flash_fwd(qa, kc, vc, masked, None,
                                                512, 512, interpret,
                                                scale=scale, softcap=softcap)
                        return _merge(o_a, lse_a, o_i.astype(jnp.float32),
                                      lse_i)

                    o_a, lse_a = jax.lax.cond(
                        rel >= 2, lambda: (o_a, lse_a),
                        lambda: jax.lax.cond(rel == 1,
                                             functools.partial(live, True),
                                             functools.partial(live, False)))
                o = o.at[a].set(o_a)
                lse = lse.at[a].set(lse_a)
        return o, lse

    def ring_fwd_body(member, q, k, v, window=None):
        idx = member[0]
        b, s_loc, hq, d = q.shape
        hkv = k.shape[2]
        if s_loc % 2:
            raise ValueError(f"local sequence {s_loc} must be even (2*cp "
                             f"chunks); pad seq to a multiple of {2 * cp}")
        s_c = s_loc // 2

        # zigzag chunks in kernel layout [2, B, H, S_c, D]
        qz = _to_zigzag(q, idx, axis_name, cp).transpose(1, 0, 3, 2, 4)
        kz = _to_zigzag(k, idx, axis_name, cp).transpose(1, 0, 3, 2, 4)
        vz = _to_zigzag(v, idx, axis_name, cp).transpose(1, 0, 3, 2, 4)

        my_chunks = (idx, 2 * cp - 1 - idx)
        w = None if window is None else window[0]

        o = jnp.zeros((2, b, hq, s_c, d), jnp.float32)
        lse = jnp.full((2, b, hq, s_c), NEG_INF, jnp.float32)

        if use_scan:
            def hop(carry, i):
                k_blk, v_blk, o, lse = carry
                src = (idx - i) % cp
                o, lse = _fwd_pairs(qz, k_blk, v_blk, o, lse, my_chunks,
                                    (src, 2 * cp - 1 - src), w, s_c)
                k_blk = jax.lax.ppermute(k_blk, axis_name, ring)
                v_blk = jax.lax.ppermute(v_blk, axis_name, ring)
                return (k_blk, v_blk, o, lse), None

            (_, _, o, lse), _ = jax.lax.scan(hop, (kz, vz, o, lse),
                                             jnp.arange(cp))
        else:
            k_blk, v_blk = kz, vz
            for i in range(cp):
                src = (idx - i) % cp
                if i < cp - 1:
                    k_nxt = jax.lax.ppermute(k_blk, axis_name, ring)
                    v_nxt = jax.lax.ppermute(v_blk, axis_name, ring)
                o, lse = _fwd_pairs(qz, k_blk, v_blk, o, lse, my_chunks,
                                    (src, 2 * cp - 1 - src), w, s_c)
                if i < cp - 1:
                    k_blk, v_blk = k_nxt, v_nxt

        out = _from_zigzag(o.astype(q.dtype).transpose(1, 0, 3, 2, 4),
                           idx, axis_name, cp)
        # ONLY the primal output + seq-layout lse leave the map (cf. the
        # sharded-flash wrapper): a shard_map eqn is atomic under
        # jax.checkpoint's partial-eval, so zigzag-layout residual outputs
        # would force the whole fwd ring — cp-1 kv rotations and every
        # flash kernel — to re-run in backward just to rebuild relayouts.
        # The bwd body re-zigzags from the raw inputs + saved outputs
        # instead (a few ppermutes), which is what lets the
        # REMAT_POLICIES["attn"] tags actually skip the fwd ring.
        lse_seq = _from_zigzag(lse.transpose(1, 0, 3, 2), idx, axis_name, cp)
        return out, lse_seq

    def ring_bwd_body(member, q, k, v, out, lse_seq, do, window=None):
        in_dtype = q.dtype
        idx = member[0]
        my_chunks = (idx, 2 * cp - 1 - idx)
        w = None if window is None else window[0]

        # rebuild the zigzag/kernel layouts the fwd used (cheap ppermutes;
        # see the fwd-body note on why these are not residuals)
        qz = _to_zigzag(q, idx, axis_name, cp).transpose(1, 0, 3, 2, 4)
        kz = _to_zigzag(k, idx, axis_name, cp).transpose(1, 0, 3, 2, 4)
        vz = _to_zigzag(v, idx, axis_name, cp).transpose(1, 0, 3, 2, 4)
        o = (_to_zigzag(out, idx, axis_name, cp).transpose(1, 0, 3, 2, 4)
             .astype(jnp.float32))
        lse = _to_zigzag(lse_seq, idx, axis_name, cp).transpose(1, 0, 3, 2)

        doz = _to_zigzag(do, idx, axis_name, cp).transpose(1, 0, 3, 2, 4)
        doz = doz.astype(jnp.float32)
        # global softmax stats: the flash-bwd identity needs the FINAL lse and
        # delta = rowsum(do * o_final) — per-pair contributions then sum to
        # the exact gradient
        delta = jnp.einsum("abhsd,abhsd->abhs", doz, o)        # [2,B,H,S_c]

        dq = jnp.zeros(qz.shape, jnp.float32)
        dk = jnp.zeros(kz.shape, jnp.float32)
        dv = jnp.zeros(vz.shape, jnp.float32)

        s_c = qz.shape[3]

        def _bwd_pairs(k_blk, v_blk, dq, dk, dv, kv_chunks):
            """One hop's 4 flash-bwd calls; accumulation runs INSIDE the
            cond so skipped pairs cost nothing in the backward either.
            Banded mode mirrors the forward exactly: the same global-offset
            band rides the bwd kernels (the score recompute must reproduce
            the fwd mask for the flash-bwd identity to hold), and the same
            chunk-pair skip predicate keeps dead pairs free."""
            for a in range(2):
                for c in range(2):
                    qa, kc, vc = qz[a], k_blk[c], v_blk[c]
                    doa, lsea, dta = doz[a], lse[a], delta[a]
                    dq_a, dk_c, dv_c = dq[a], dk[c], dv[c]

                    if w is not None:
                        band = _pack_band(w, my_chunks[a] * s_c,
                                          kv_chunks[c] * s_c)

                        def live_banded(qa=qa, kc=kc, vc=vc, doa=doa,
                                        lsea=lsea, dta=dta, dq_a=dq_a,
                                        dk_c=dk_c, dv_c=dv_c, band=band):
                            dq_i, dk_i, dv_i = flash_bwd_with_stats(
                                qa, kc, vc, doa.astype(qa.dtype), lsea, dta,
                                causal=True, interpret=interpret,
                                scale=scale, softcap=softcap, band=band)
                            return (dq_a + dq_i.astype(jnp.float32),
                                    dk_c + dk_i.astype(jnp.float32),
                                    dv_c + dv_i.astype(jnp.float32))

                        dq_a, dk_c, dv_c = jax.lax.cond(
                            _pair_live(kv_chunks[c], my_chunks[a], s_c, w),
                            live_banded, lambda: (dq_a, dk_c, dv_c))
                    else:
                        rel = _relation(kv_chunks[c], my_chunks[a], causal)

                        def live(masked, qa=qa, kc=kc, vc=vc, doa=doa,
                                 lsea=lsea, dta=dta, dq_a=dq_a, dk_c=dk_c,
                                 dv_c=dv_c):
                            dq_i, dk_i, dv_i = flash_bwd_with_stats(
                                qa, kc, vc, doa.astype(qa.dtype), lsea, dta,
                                causal=masked, interpret=interpret,
                                scale=scale, softcap=softcap)
                            return (dq_a + dq_i.astype(jnp.float32),
                                    dk_c + dk_i.astype(jnp.float32),
                                    dv_c + dv_i.astype(jnp.float32))

                        dq_a, dk_c, dv_c = jax.lax.cond(
                            rel >= 2, lambda: (dq_a, dk_c, dv_c),
                            lambda: jax.lax.cond(
                                rel == 1, functools.partial(live, True),
                                functools.partial(live, False)))
                    dq = dq.at[a].set(dq_a)
                    dk = dk.at[c].set(dk_c)
                    dv = dv.at[c].set(dv_c)
            return dq, dk, dv

        if use_scan:
            def hop(carry, i):
                k_blk, v_blk, dq, dk, dv = carry
                src = (idx - i) % cp
                dq, dk, dv = _bwd_pairs(k_blk, v_blk, dq, dk, dv,
                                        (src, 2 * cp - 1 - src))
                # dk/dv travel with their K/V blocks: after the final
                # compute one more hop completes the cycle and delivers
                # them to their owners
                dk = jax.lax.ppermute(dk, axis_name, ring)
                dv = jax.lax.ppermute(dv, axis_name, ring)
                k_blk = jax.lax.ppermute(k_blk, axis_name, ring)
                v_blk = jax.lax.ppermute(v_blk, axis_name, ring)
                return (k_blk, v_blk, dq, dk, dv), None

            (_, _, dq, dk, dv), _ = jax.lax.scan(hop, (kz, vz, dq, dk, dv),
                                                 jnp.arange(cp))
        else:
            k_blk, v_blk = kz, vz
            for i in range(cp):
                src = (idx - i) % cp
                if i < cp - 1:
                    k_nxt = jax.lax.ppermute(k_blk, axis_name, ring)
                    v_nxt = jax.lax.ppermute(v_blk, axis_name, ring)
                dq, dk, dv = _bwd_pairs(k_blk, v_blk, dq, dk, dv,
                                        (src, 2 * cp - 1 - src))
                # dk/dv travel with their K/V blocks (see the scan form)
                dk = jax.lax.ppermute(dk, axis_name, ring)
                dv = jax.lax.ppermute(dv, axis_name, ring)
                if i < cp - 1:
                    k_blk, v_blk = k_nxt, v_nxt

        def back(x):
            return _from_zigzag(x.astype(in_dtype).transpose(1, 0, 3, 2, 4),
                                idx, axis_name, cp)

        return back(dq), back(dk), back(dv)

    return ring_fwd_body, ring_bwd_body


def make_ring_attention(mesh: Mesh, *, axis_name: str = "cp",
                        data_axes=("dp", "fsdp", "ep"), head_axis: str = "tp",
                        causal: bool = True,
                        hop_loop: str = "auto",
                        window=None,
                        scale=None,
                        logit_softcap=None) -> Callable:
    """Returns an attention callable with the ``multihead_attention``
    signature, internally a shard_map ring over ``axis_name``.

    Batch and head dims are manual too (over ``data_axes`` / ``head_axis``
    when those mesh axes are >1): the Pallas calls inside the ring are
    Mosaic custom calls, which the SPMD partitioner cannot shard — leaving
    dp/tp auto here would gather-and-replicate q/k/v chunks per hop on a
    real pod (same failure ``make_sharded_flash_attention`` guards on the
    cp=1 path). The body needs no collectives over those axes, so the ring
    logic is unchanged; only cp carries ppermutes. The round-1 partitioner
    CHECK that forced partial-manual was auto-*tp on weights* inside a
    manual region — q/k/v here are activations, already projected.

    ``window``: sliding-window attention (HF semantics) through the zigzag
    ring. Every live (q-chunk, kv-chunk) pair runs the kernel with its
    GLOBAL chunk offsets on the dynamic band operand, so the band mask is
    exact across chunk boundaries, and chunk pairs fully below the band are
    skipped at the hop level (``_pair_live``) on top of the kernel's own
    tile skipping. A per-call ``window`` (traced per-layer schedules,
    Gemma-2) overrides the factory default. ``scale``/``logit_softcap``:
    Gemma-2 score scale / tanh capping, threaded into every per-pair kernel
    call forward and backward (the (o, lse) merge is softcap-agnostic — the
    cap applies per score before each pair's softmax)."""
    from .flash_attention import (_UNSET, _in_manual_context,
                                  attention_divisibility_error,
                                  resolve_attention_manual_axes,
                                  resolve_wrapper_mesh)

    if window is not None and not causal:
        raise ValueError(
            "window (sliding-window attention) requires causal=True")
    check_static_window(window)
    cp = mesh.shape[axis_name]
    batch_axes, head_axis, tp, batch_div, b_spec, manual = \
        resolve_attention_manual_axes(mesh, data_axes, head_axis)
    manual = manual | {axis_name}
    interpret = jax.default_backend() != "tpu"
    spec = P(b_spec, axis_name, head_axis, None)   # [B, S_loc, H, D]
    lse_spec = P(b_spec, axis_name, head_axis)     # [B, S_loc, H]

    if hop_loop not in ("auto", "scan", "unrolled"):
        raise ValueError(f"hop_loop must be 'auto', 'scan', or 'unrolled'; "
                         f"got {hop_loop!r}")
    # program size (and trace/compile time) of the unrolled hops is O(cp) —
    # measured ~2x per cp doubling (08-context-parallel/README.md). The
    # scan form is O(1); per hop the two are op-for-op identical, so at
    # large cp scan is strictly better and 'auto' switches over.
    use_scan = cp >= 8 if hop_loop == "auto" else hop_loop == "scan"
    fwd_body, bwd_body = _build_ring(axis_name, cp, causal, interpret,
                                     use_scan, scale=scale,
                                     softcap=logit_softcap)

    def _maps(banded=False):
        # check_vma=False: pallas interpret mode (the CPU test path) trips
        # the vma checker inside its own lowering ("dynamic_slice requires
        # varying manual axes to match")
        sm = functools.partial(jax.shard_map, mesh=resolve_wrapper_mesh(mesh),
                               axis_names=manual, check_vma=False)
        member = P(axis_name)   # [cp] iota -> each member's ring position
        if banded:
            # the window rides as a replicated [1] int32 operand so traced
            # per-layer schedules (a lax.scan column) reach every member
            wspec = P(None)
            fwd = sm(lambda m, w, q, k, v: fwd_body(m, q, k, v, w),
                     in_specs=(member, wspec, spec, spec, spec),
                     out_specs=(spec, lse_spec))
            bwd = sm(lambda m, w, *a: bwd_body(m, *a, window=w),
                     in_specs=(member, wspec, spec, spec, spec, spec,
                               lse_spec, spec),
                     out_specs=(spec, spec, spec))
        else:
            fwd = sm(fwd_body, in_specs=(member, spec, spec, spec),
                     out_specs=(spec, lse_spec))
            bwd = sm(bwd_body,
                     in_specs=(member, spec, spec, spec, spec, lse_spec,
                               spec),
                     out_specs=(spec, spec, spec))
        return fwd, bwd

    # the custom_vjp sits OUTSIDE the shard_maps: jax.grad never transposes
    # through a partial-manual shard_map (which check_vma=False forbids) —
    # forward and backward are each a plain, non-differentiated shard_map
    @jax.custom_vjp
    def ring(q, k, v):
        members = jnp.arange(cp, dtype=jnp.int32)
        return _maps()[0](members, q, k, v)[0]

    def ring_vjp_fwd(q, k, v):
        members = jnp.arange(cp, dtype=jnp.int32)
        out, lse_seq = _maps()[0](members, q, k, v)
        # the REMAT_POLICIES["attn"] tags, as in the flash wrappers: with
        # these saved, backward runs only the bwd ring — never the fwd one
        out = checkpoint_name(out, "flash_out")
        lse_seq = checkpoint_name(lse_seq, "flash_lse")
        return out, (q, k, v, out, lse_seq)

    def ring_vjp_bwd(res, do):
        members = jnp.arange(cp, dtype=jnp.int32)
        return _maps()[1](members, *res, do)

    ring.defvjp(ring_vjp_fwd, ring_vjp_bwd)

    # banded twin: same rings with the [1] int32 window operand (integer-
    # valued, so its cotangent is float0 like the flash wrapper's band)
    @jax.custom_vjp
    def ring_banded(q, k, v, w):
        members = jnp.arange(cp, dtype=jnp.int32)
        return _maps(banded=True)[0](members, w, q, k, v)[0]

    def ring_banded_vjp_fwd(q, k, v, w):
        members = jnp.arange(cp, dtype=jnp.int32)
        out, lse_seq = _maps(banded=True)[0](members, w, q, k, v)
        out = checkpoint_name(out, "flash_out")
        lse_seq = checkpoint_name(lse_seq, "flash_lse")
        return out, (q, k, v, out, lse_seq, w)

    def ring_banded_vjp_bwd(res, do):
        *res_, w = res
        members = jnp.arange(cp, dtype=jnp.int32)
        grads = _maps(banded=True)[1](members, w, *res_, do)
        return (*grads, np.zeros(w.shape, jax.dtypes.float0))

    ring_banded.defvjp(ring_banded_vjp_fwd, ring_banded_vjp_bwd)
    # partial-manual shard_map only resolves its auto-axes shardings under
    # jit (the eager path rejects the specs), so every top-level call —
    # eager OR traced — goes through this jit. ONLY manual-context callers
    # (the pipeline) bypass it for the raw custom_vjp: this jit's cache must
    # hold concrete-mesh programs exclusively, never a context-mesh trace
    ring_eager = jax.jit(ring)
    ring_banded_eager = jax.jit(ring_banded)

    window_default = window

    def attention(q, k, v, standard_layout: bool = True, window=_UNSET,
                  **kwargs):
        wcall = window_default if window is _UNSET else window
        if not interpret and (q.shape[1] % (16 * cp) or q.shape[-1] % 64):
            # mirror flash_attention's loud guard: per-chunk seq must tile
            # (S/(2cp) % 8) and head_dim must fill MXU lanes, else Mosaic
            # fails opaquely
            raise ValueError(
                f"ring flash attention needs seq divisible by {16 * cp} "
                f"(8-token tiles per zigzag chunk) and head_dim divisible by "
                f"64; got seq={q.shape[1]}, head_dim={q.shape[-1]} — pad the "
                f"sequence or lower cp")
        if not standard_layout:
            raise ValueError(
                "ring attention assumes contiguous positions (rank r owns "
                "[r*S/cp, (r+1)*S/cp)); caller-supplied positions would "
                "desynchronize the causal mask — don't pass explicit "
                "positions under context parallelism")
        if wcall is not None and not causal:
            raise ValueError(
                "window (sliding-window attention) requires causal=True")
        check_static_window(wcall)
        hq, hkv = q.shape[2], k.shape[2]
        if hq % tp or hkv % tp or q.shape[0] % batch_div:
            raise ValueError(attention_divisibility_error(
                batch_axes, head_axis, tp, batch_div, hq, hkv, q.shape[0],
                "ring attention"))
        in_manual = _in_manual_context()
        if wcall is None:
            if in_manual:
                # nested in the pipeline's manual region — by construction
                # under the caller's jit already; the raw custom_vjp builds
                # its maps against the context mesh (the eager jit's cache
                # must never mix top-level and in-pipeline programs)
                return ring(q, k, v)
            return ring_eager(q, k, v)
        warr = jnp.reshape(jnp.asarray(wcall, jnp.int32), (1,))
        if in_manual:
            return ring_banded(q, k, v, warr)
        return ring_banded_eager(q, k, v, warr)

    attention.accepts_window = True
    return attention
