"""Zigzag ring attention: context parallelism over the ``cp`` mesh axis.

The reference name-checks context parallelism ("For long context lengths",
``06-tensor-parallel/README.md:7``) but never implements it — its long-context
story is flash-attn + activation checkpointing + a seq-length flag. For the
TPU build CP is first-class: the sequence dim of the *batch and activations*
is sharded over ``cp`` contiguously (plain GSPMD sharding — data pipeline,
RoPE and loss never see anything unusual), and attention — the only op that
crosses sequence shards — runs inside a shard_map where only ``cp`` is
manual:

- **zigzag load balance**: under causal masking, contiguous shards give rank
  cp-1 ~cp x the work of rank 0 (it attends to every earlier shard). Here the
  sequence is viewed as 2*cp chunks and two static ppermutes re-layout each
  rank's (q, k, v) to the zigzag pair (chunk r, chunk 2cp-1-r) before the
  ring, so every rank owns one early and one late chunk — per-rank live
  chunk-pairs are (r+1) + (2cp-r) = 2cp+1, identical for all ranks. Outputs
  are re-layouted back, so the wrapper is layout-transparent.
- **ring**: K/V zigzag blocks rotate via ``jax.lax.ppermute`` (neighbor ICI
  hops), overlapping transfer with compute; partial results merge with the
  online-softmax (m, l, acc) update in fp32.
- **no wasted compute**: each hop touches 4 (q-chunk, kv-chunk) pairs whose
  causal relation (past / diagonal / future) depends only on chunk ids —
  future pairs are *skipped* by ``lax.cond`` (no FLOPs issued), diagonal
  pairs apply the static in-chunk causal mask, past pairs run unmasked.
  Scores materialize per chunk pair ([S/2cp, S/2cp] fp32), not per shard
  pair.
- **GQA without expansion**: scores are computed with a grouped einsum
  ([B,Hkv,G,Sq,Sk]); K/V are never ``repeat``-ed, and the ring ships
  Hkv-sized blocks.

tp composes: only ``cp`` is manual in the shard_map, so the head dim stays
auto-sharded over tp by GSPMD inside the body (round 1's fully-manual ring
hit an XLA SPMD partitioner CHECK against tp-sharded head weights).

Backward is plain autodiff: cotangents ride the transposed ppermutes around
the reverse ring, and ``lax.cond`` differentiates per branch, so skipped
pairs are skipped in the backward too.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _chunk_pair_update(q_chunk, k_chunk, v_chunk, m, l, acc, *, relation, scale):
    """Online-softmax update of one (q-chunk, kv-chunk) pair.

    q_chunk: [B, S_c, Hkv, G, D] (grouped query heads); k/v_chunk:
    [B, S_c, Hkv, D]; m/l: [B, Hkv, G, S_c] fp32; acc: [B, Hkv, G, S_c, D].
    relation: traced int32 — 0 past (full), 1 diagonal (causal), 2 future
    (skip). Future pairs cost nothing: the skip branch of the cond is a no-op.
    """

    def compute(masked):
        s = jnp.einsum("bqhgd,bkhd->bhgqk", q_chunk, k_chunk.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * scale
        if masked:
            s_c = q_chunk.shape[1]
            tri = jnp.arange(s_c)[:, None] >= jnp.arange(s_c)[None, :]
            s = jnp.where(tri[None, None, None], s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_cur)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bhgqk,bkhd->bhgqd", p, v_chunk.astype(jnp.float32))
        return m_new, l_new, acc_new

    return jax.lax.cond(
        relation >= 2, lambda: (m, l, acc),
        lambda: jax.lax.cond(relation == 1,
                             functools.partial(compute, True),
                             functools.partial(compute, False)))


def _zigzag_perms(cp: int):
    """Static ppermute lists for contiguous->zigzag relayout.

    Contiguous rank r holds chunks (2r, 2r+1); zigzag rank r holds chunks
    (r, 2cp-1-r). Chunk c's zigzag owner is c if c < cp else 2cp-1-c. Each
    rank's half-h block (chunk 2r+h) has one destination -> one static perm
    per half.
    """
    def owner(c):
        return c if c < cp else 2 * cp - 1 - c

    perm0 = [(r, owner(2 * r)) for r in range(cp)]
    perm1 = [(r, owner(2 * r + 1)) for r in range(cp)]
    inv0 = [(d, s) for (s, d) in perm0]
    inv1 = [(d, s) for (s, d) in perm1]
    return perm0, perm1, inv0, inv1


def _to_zigzag(x, idx, axis_name, cp):
    """[B, S_loc, ...] contiguous shard -> [B, 2, S_c, ...] zigzag chunks."""
    b, s_loc = x.shape[:2]
    s_c = s_loc // 2
    halves = x.reshape(b, 2, s_c, *x.shape[2:])
    perm0, perm1, _, _ = _zigzag_perms(cp)
    recv_a = jax.lax.ppermute(halves[:, 0], axis_name, perm0)
    recv_b = jax.lax.ppermute(halves[:, 1], axis_name, perm1)
    # chunk r has parity r%2 -> arrives via that perm; chunk 2cp-1-r has the
    # opposite parity (2cp-1-r == 1-r mod 2), so there is never a collision
    even = (idx % 2) == 0
    slot0 = jnp.where(even, recv_a, recv_b)
    slot1 = jnp.where(even, recv_b, recv_a)
    return jnp.stack([slot0, slot1], axis=1)


def _from_zigzag(x, idx, axis_name, cp):
    """Inverse of ``_to_zigzag``: [B, 2, S_c, ...] -> [B, S_loc, ...]."""
    _, _, inv0, inv1 = _zigzag_perms(cp)
    even = (idx % 2) == 0
    # undo the slot selection, then the permutes
    recv_a = jnp.where(even, x[:, 0], x[:, 1])
    recv_b = jnp.where(even, x[:, 1], x[:, 0])
    half0 = jax.lax.ppermute(recv_a, axis_name, inv0)
    half1 = jax.lax.ppermute(recv_b, axis_name, inv1)
    stacked = jnp.stack([half0, half1], axis=1)
    b = x.shape[0]
    return stacked.reshape(b, -1, *x.shape[3:])


def _local_ring_attention(q, k, v, *, axis_name: str, cp: int, causal: bool):
    """Per-shard body. q: [B, S_local, Hq, D]; k/v: [B, S_local, Hkv, D]."""
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    if s_loc % 2:
        raise ValueError(f"local sequence {s_loc} must be even (2*cp chunks); "
                         f"pad seq to a multiple of {2 * cp}")
    s_c = s_loc // 2
    scale = 1.0 / (d ** 0.5)

    qz = _to_zigzag(q, idx, axis_name, cp)            # [B,2,S_c,Hq,D]
    kz = _to_zigzag(k, idx, axis_name, cp)            # [B,2,S_c,Hkv,D]
    vz = _to_zigzag(v, idx, axis_name, cp)
    qz = qz.reshape(b, 2, s_c, hkv, g, d).astype(jnp.float32)

    my_chunks = (idx, 2 * cp - 1 - idx)               # traced chunk ids

    # carries start as constants — mark them device-varying over the ring
    # axis so both lax.cond branches type-check under check_vma
    def vary(x):
        return jax.lax.pcast(x, (axis_name,), to="varying")

    m = vary(jnp.full((2, b, hkv, g, s_c), NEG_INF, jnp.float32))
    l = vary(jnp.zeros((2, b, hkv, g, s_c), jnp.float32))
    acc = vary(jnp.zeros((2, b, hkv, g, s_c, d), jnp.float32))

    ring = [(i, (i + 1) % cp) for i in range(cp)]
    k_blk, v_blk = kz, vz

    # cp is static (mesh shape): the unrolled loop lets XLA overlap each
    # hop's ppermute with the current hop's compute
    for i in range(cp):
        src = (idx - i) % cp                          # owner of current block
        if i < cp - 1:
            k_nxt = jax.lax.ppermute(k_blk, axis_name, ring)
            v_nxt = jax.lax.ppermute(v_blk, axis_name, ring)
        kv_chunks = (src, 2 * cp - 1 - src)
        for a in range(2):                            # my q chunk slot
            for c in range(2):                        # their kv chunk slot
                if causal:
                    # 0 past / 1 diagonal / 2 future, from chunk ids
                    rel = jnp.where(
                        kv_chunks[c] == my_chunks[a], 1,
                        jnp.where(kv_chunks[c] < my_chunks[a], 0, 2))
                else:
                    rel = jnp.int32(0)
                m_a, l_a, acc_a = _chunk_pair_update(
                    qz[:, a], k_blk[:, c], v_blk[:, c],
                    m[a], l[a], acc[a], relation=rel, scale=scale)
                m = m.at[a].set(m_a)
                l = l.at[a].set(l_a)
                acc = acc.at[a].set(acc_a)
        if i < cp - 1:
            k_blk, v_blk = k_nxt, v_nxt

    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = acc / safe_l[..., None]                     # [2,B,Hkv,G,S_c,D]
    out = out.transpose(1, 0, 4, 2, 3, 5).reshape(b, 2, s_c, hq, d)
    return _from_zigzag(out.astype(q.dtype), idx, axis_name, cp)


def make_ring_attention(mesh: Mesh, *, axis_name: str = "cp",
                        data_axes=("dp", "fsdp", "ep"), head_axis: str = "tp",
                        causal: bool = True) -> Callable:
    """Returns an attention callable with the ``multihead_attention``
    signature, internally a shard_map ring over ``axis_name``. Only ``cp`` is
    manual: batch and head dims keep their auto (GSPMD) shardings, so the
    ring composes with dp/fsdp/tp."""
    del data_axes, head_axis  # auto axes now — kept for API compat
    cp = mesh.shape[axis_name]
    spec = P(None, axis_name, None, None)

    body = functools.partial(_local_ring_attention, axis_name=axis_name,
                             cp=cp, causal=causal)
    ring = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={axis_name})

    def attention(q, k, v, standard_layout: bool = True, **kwargs):
        if not standard_layout:
            raise ValueError(
                "ring attention assumes contiguous positions (rank r owns "
                "[r*S/cp, (r+1)*S/cp)); caller-supplied positions would "
                "desynchronize the causal mask — don't pass explicit "
                "positions under context parallelism")
        return ring(q, k, v)

    return attention
