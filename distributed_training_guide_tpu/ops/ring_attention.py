"""Ring attention: context parallelism over the ``cp`` mesh axis.

The reference name-checks context parallelism ("For long context lengths",
``06-tensor-parallel/README.md:7``) but never implements it — its long-context
story is flash-attn + activation checkpointing + a seq-length flag. For the
TPU build CP is first-class: the sequence dim of the *batch and activations*
is sharded over ``cp``, and attention — the only op needing cross-shard
sequence interaction — runs as a ring:

- each cp rank keeps its local Q block resident;
- K/V blocks rotate around the ring via ``jax.lax.ppermute`` over ICI
  (neighbor exchanges — exactly what the torus is fastest at), overlapping
  each step's transfer with the current block's attention compute;
- partial results merge with the standard online-softmax (m, l, acc) update,
  fp32 accumulators;
- causal masking uses absolute positions (rank r owns positions
  [r*S_local, (r+1)*S_local)), so the math is identical to single-device
  causal attention — verified by the parity tests.

Integration: everything else in the model is sequence-sharded automatically by
GSPMD; only attention is wrapped in this ``shard_map``. The Trainer installs
it as the model's attention callable when the mesh has cp > 1.

Known inefficiency (round-2 target): with plain ring order, ranks early in the
sequence skip most blocks (causal) — zigzag/striped CP balances this.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _local_ring_attention(q, k, v, *, axis_name: str, cp: int, causal: bool):
    """Per-shard body under shard_map. q: [B, S_local, Hq, D]; k/v keep their
    kv-head count through the ring — GQA expansion happens per hop, after the
    transfer, so ppermute ships Hkv-sized blocks (4x less ICI traffic than
    rotating q-head-sized buffers for llama-3.1 shapes)."""
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, hq, d = q.shape
    hkv = k.shape[2]
    reps = hq // hkv

    scale = 1.0 / (d ** 0.5)
    qf = q.astype(jnp.float32).transpose(0, 2, 1, 3)        # [B,Hq,S,D]
    q_pos = idx * s_loc + jnp.arange(s_loc)

    perm = [(i, (i + 1) % cp) for i in range(cp)]

    m = jnp.full((b, hq, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, hq, s_loc), jnp.float32)
    acc = jnp.zeros((b, hq, s_loc, d), jnp.float32)
    k_blk, v_blk = k, v

    # cp is static (mesh shape): unrolled python loop lets XLA overlap each
    # hop's ppermute with the previous hop's compute, and the final iteration
    # genuinely skips the rotation instead of discarding it.
    for i in range(cp):
        src = (idx - i) % cp  # original owner of the block we hold now
        if i < cp - 1:
            k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
            v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        kf = k_blk.astype(jnp.float32)
        vf = v_blk.astype(jnp.float32)
        if reps > 1:
            kf = jnp.repeat(kf, reps, axis=2)
            vf = jnp.repeat(vf, reps, axis=2)
        kf = kf.transpose(0, 2, 1, 3)                        # [B,Hq,S,D]
        vf = vf.transpose(0, 2, 1, 3)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
        if causal:
            k_pos = src * s_loc + jnp.arange(s_loc)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_cur)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vf)
        m = m_new
        if i < cp - 1:
            k_blk, v_blk = k_nxt, v_nxt

    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / safe_l[..., None]).transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def make_ring_attention(mesh: Mesh, *, axis_name: str = "cp",
                        data_axes=("dp", "fsdp", "ep"), head_axis: str = "tp",
                        causal: bool = True) -> Callable:
    """Returns an attention callable with the ``multihead_attention``
    signature, internally a shard_map ring over ``axis_name``."""
    cp = mesh.shape[axis_name]
    spec = P(data_axes, axis_name, head_axis, None)

    body = functools.partial(_local_ring_attention, axis_name=axis_name,
                             cp=cp, causal=causal)
    ring = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)

    def attention(q, k, v, standard_layout: bool = True, **kwargs):
        if not standard_layout:
            raise ValueError(
                "ring attention assumes contiguous positions (rank r owns "
                "[r*S/cp, (r+1)*S/cp)); caller-supplied positions would "
                "desynchronize the causal mask — don't pass explicit "
                "positions under context parallelism")
        return ring(q, k, v)

    return attention
