"""Vocab-parallel embedding + cross-entropy (manual-collective forms).

Megatron-style vocab parallelism for use inside ``shard_map`` regions where
``tp`` is a *manual* axis (the pipeline schedule, ``parallel/pipeline.py``):
each tp member owns a contiguous vocab shard of the embedding table / output
projection and the collectives are written explicitly instead of inserted by
GSPMD. The reference documents the auto-partitioned analogue as
``loss_parallel`` (``06-tensor-parallel/README.md:241-271``) but ships with
replicated logits; the GSPMD version of that idea lives in
``plans.ShardingPlan.logits_sharding``.

All functions are no-ops over the axis when its size is 1, so callers can use
one code path for tp and no-tp meshes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .collectives import psum as _psum
from .cross_entropy import IGNORE_INDEX


def vocab_parallel_embed(table_local: jnp.ndarray, input_ids: jnp.ndarray,
                         axis: str) -> jnp.ndarray:
    """Embedding lookup from a vocab-sharded table: mask out-of-shard ids,
    gather locally, psum partial rows across the axis.

    table_local: [V/axis_size, E]; input_ids: [...]; returns [..., E].
    """
    v_local = table_local.shape[0]
    offset = jax.lax.axis_index(axis) * v_local
    local = input_ids - offset
    in_shard = (local >= 0) & (local < v_local)
    rows = jnp.take(table_local, jnp.clip(local, 0, v_local - 1), axis=0)
    rows = jnp.where(in_shard[..., None], rows, 0)
    return _psum(rows, axis)


def vocab_parallel_causal_lm_loss(logits_local: jnp.ndarray,
                                  labels: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Mean next-token cross-entropy over vocab-sharded logits.

    Same semantics as ``cross_entropy.causal_lm_loss`` (shift inside, -100
    ignored) but the vocab dim stays sharded throughout: the logsumexp is a
    local reduce + psum and the target logit a masked local gather + psum, so
    full [B, S, V] logits never exist on any device.

    logits_local: [B, S, V/axis_size]; labels: [B, S] (replicated on axis).
    """
    logits = logits_local[:, :-1, :].astype(jnp.float32)
    targets = labels[:, 1:]
    valid = targets != IGNORE_INDEX

    logz = sharded_logsumexp(logits, axis)
    picked = sharded_pick(logits, targets, valid, axis)

    nll = (logz - picked) * valid
    return nll.sum() / jnp.maximum(valid.sum(), 1)


def sharded_logsumexp(logits: jnp.ndarray, axis: str) -> jnp.ndarray:
    """logsumexp over a vocab-sharded last dim: local reduce + psum.

    The stabilizing max is constant w.r.t. AD (the exact gradient of
    logsumexp doesn't depend on the shift); pmax has no JVP rule, so the
    cross-shard max rides an all_gather of the (tiny) per-shard maxes.
    logits: [..., V/axis_size] fp32 -> [...]. Shared by the loss above and
    the fused hidden->loss kernel (ops/cross_entropy.py)."""
    m = jax.lax.stop_gradient(jnp.max(
        jax.lax.all_gather(jnp.max(logits, axis=-1), axis), axis=0))
    sumexp = jax.lax.psum(
        jnp.sum(jnp.exp(logits - m[..., None]), axis=-1), axis)
    return jnp.log(sumexp) + m


def shard_local_targets(targets: jnp.ndarray, valid: jnp.ndarray,
                        v_local: int, axis: str):
    """GLOBAL target ids -> (ids clipped into this member's vocab slice,
    in-shard mask). Shared by ``sharded_pick`` and the fused kernel's
    backward (one-hot against the local slice)."""
    offset = jax.lax.axis_index(axis) * v_local
    local_t = jnp.where(valid, targets, 0) - offset
    in_shard = (local_t >= 0) & (local_t < v_local)
    return jnp.clip(local_t, 0, v_local - 1), in_shard


def sharded_pick(logits: jnp.ndarray, targets: jnp.ndarray,
                 valid: jnp.ndarray, axis: str) -> jnp.ndarray:
    """The target's logit out of a vocab-sharded last dim: masked local
    gather + psum. logits [..., V/axis], targets/valid [...] -> [...]."""
    safe, in_shard = shard_local_targets(targets, valid, logits.shape[-1],
                                         axis)
    picked_local = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    return jax.lax.psum(jnp.where(in_shard, picked_local, 0.0), axis)
