"""Rotary position embeddings (half-rotation convention, Llama-style).

The reference consumes RoPE through HF ``LlamaRotaryEmbedding`` (it only has to
shim its ``reset_parameters``, ``04-fully-sharded-data-parallel/train_llm.py:32-44``).
Here it is a pure function: compute cos/sin from explicit ``positions`` — the
explicit-positions requirement is load-bearing for sequence parallelism, where
each shard sees a slice of the sequence (reference passes explicit
``position_ids`` for the same reason, ``06-tensor-parallel/train_llm.py:210-212``).
"""
from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float = 10000.0) -> jnp.ndarray:
    """Inverse frequencies, shape [head_dim // 2], float32."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta ** exponent)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float = 10000.0) -> jnp.ndarray:
    """Rotate ``x`` [..., seq, heads, head_dim] by position-dependent angles.

    ``positions`` is [..., seq] (int). Computation in float32, result cast back
    to ``x.dtype`` — rope in bf16 loses position resolution at long context.
    """
    head_dim = x.shape[-1]
    inv_freq = rope_frequencies(head_dim, theta)  # [D/2]
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., S, D/2]
    cos = jnp.cos(angles)[..., None, :]  # [..., S, 1, D/2]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)
