"""Rotary position embeddings (half-rotation convention, Llama-style).

The reference consumes RoPE through HF ``LlamaRotaryEmbedding`` (it only has to
shim its ``reset_parameters``, ``04-fully-sharded-data-parallel/train_llm.py:32-44``),
which means it inherits every ``rope_scaling`` flavor HF implements — and the
405B chapter's target checkpoint (Llama-3.1,
``05-training-llama-405b/train_llm.py:74-146``) *requires* the ``llama3``
frequency rescale for correct numerics. This module implements the same six
rope types HF's ``ROPE_INIT_FUNCTIONS`` dispatches on (default / linear /
dynamic NTK / yarn / longrope / llama3), as pure functions of the config dict.

Here RoPE is a pure function: compute cos/sin from explicit ``positions`` — the
explicit-positions requirement is load-bearing for sequence parallelism, where
each shard sees a slice of the sequence (reference passes explicit
``position_ids`` for the same reason, ``06-tensor-parallel/train_llm.py:210-212``).

Seq-length-dependent flavors (``dynamic``, ``longrope``'s short/long switch)
use ``max(positions) + 1`` — a *traced* scalar, so the compiled program handles
any batch, exactly like HF's ``@dynamic_rope_update`` recomputing from
``position_ids.max() + 1``. Under context parallelism this max is computed in
GSPMD-land OUTSIDE the attention shard_maps: ``positions`` is one global
(cp-sharded) array, so XLA lowers the reduction as a cp-collective max and
every sequence shard derives the SAME frequencies — no rejection needed
(pinned by the dynamic-rope cp parity test in tests/test_rope_scaling.py).
"""
from __future__ import annotations

import math
from typing import Any, Optional

import jax.numpy as jnp

ROPE_TYPES = ("default", "linear", "dynamic", "yarn", "longrope", "llama3")

# rope types whose frequencies depend on the runtime sequence length (traced
# from positions via a global max — a cp-collective under sequence sharding,
# see module docstring); everything else is static at trace time
SEQ_DEPENDENT_ROPE_TYPES = ("dynamic", "longrope")


def freeze_rope_scaling(scaling: Optional[dict]) -> Optional[tuple]:
    """HF ``rope_scaling`` dict -> hashable canonical form (sorted item
    tuple, list values tupled) so it can live on the frozen model configs."""
    if scaling is None or isinstance(scaling, tuple):
        return scaling

    def _freeze(v):
        return tuple(v) if isinstance(v, (list, tuple)) else v

    return tuple(sorted((k, _freeze(v)) for k, v in scaling.items()))


def _scaling_dict(scaling) -> dict:
    if isinstance(scaling, dict):
        return scaling
    return dict(scaling)


def rope_type_of(scaling) -> str:
    if not scaling:
        return "default"
    s = _scaling_dict(scaling)
    # "rope_type" is the current HF key; "type" the pre-4.43 one
    return s.get("rope_type") or s.get("type") or "default"


def rope_frequencies(head_dim: int, theta: float = 10000.0) -> jnp.ndarray:
    """Inverse frequencies, shape [head_dim // 2], float32."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta ** exponent)


def _llama3_frequencies(inv_freq: jnp.ndarray, s: dict) -> jnp.ndarray:
    """Llama-3.1 band-wise rescale: long wavelengths (past the original
    context) compressed by ``factor``, short ones untouched, a smooth
    interpolation between (HF ``_compute_llama3_parameters``)."""
    factor = s["factor"]
    low_freq_factor = s.get("low_freq_factor", 1.0)
    high_freq_factor = s.get("high_freq_factor", 4.0)
    old_context = s["original_max_position_embeddings"]

    low_freq_wavelen = old_context / low_freq_factor
    high_freq_wavelen = old_context / high_freq_factor
    wavelen = 2 * math.pi / inv_freq
    scaled = jnp.where(wavelen > low_freq_wavelen, inv_freq / factor, inv_freq)
    smooth = ((old_context / wavelen - low_freq_factor)
              / (high_freq_factor - low_freq_factor))
    smoothed = (1 - smooth) * scaled / factor + smooth * scaled
    is_medium = (wavelen <= low_freq_wavelen) & (wavelen >= high_freq_wavelen)
    return jnp.where(is_medium, smoothed, scaled)


def _yarn_frequencies(head_dim: int, theta: float, s: dict,
                      max_position: int) -> tuple[jnp.ndarray, float]:
    """YaRN: interpolate-vs-extrapolate per frequency band with a linear ramp
    between correction dims, plus the sqrt-log attention temperature (HF
    ``_compute_yarn_parameters``)."""
    factor = s["factor"]
    # original_max bounds the correction range only; ``factor`` stays the
    # dict's value (matches transformers' _compute_yarn_parameters)
    original_max = s.get("original_max_position_embeddings") or max_position

    def get_mscale(scale, mscale=1.0):
        if scale <= 1:
            return 1.0
        return 0.1 * mscale * math.log(scale) + 1.0

    attention_factor = s.get("attention_factor")
    if attention_factor is None:
        mscale, mscale_all = s.get("mscale"), s.get("mscale_all_dim")
        if mscale and mscale_all:  # deepseek-style split temperature
            attention_factor = get_mscale(factor, mscale) / get_mscale(
                factor, mscale_all)
        else:
            attention_factor = get_mscale(factor)

    beta_fast = s.get("beta_fast") or 32
    beta_slow = s.get("beta_slow") or 1

    def correction_dim(num_rotations):
        return (head_dim * math.log(original_max / (num_rotations * 2 * math.pi))
                ) / (2 * math.log(theta))

    low, high = correction_dim(beta_fast), correction_dim(beta_slow)
    if s.get("truncate", True):
        low, high = math.floor(low), math.ceil(high)
    low, high = max(low, 0), min(high, head_dim - 1)
    if low == high:
        high += 0.001  # avoid 0/0 on degenerate ranges (HF does the same)

    pos_freqs = theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                          / head_dim)
    extrapolation = 1.0 / pos_freqs
    interpolation = 1.0 / (factor * pos_freqs)
    ramp = jnp.clip(
        (jnp.arange(head_dim // 2, dtype=jnp.float32) - low) / (high - low),
        0, 1)
    extrapolation_factor = 1 - ramp
    inv_freq = (interpolation * (1 - extrapolation_factor)
                + extrapolation * extrapolation_factor)
    return inv_freq, float(attention_factor)


def _longrope_frequencies(head_dim: int, theta: float, s: dict,
                          max_position: int, seq_len) -> tuple[jnp.ndarray, float]:
    """Phi-3 longrope: per-dim rescale factors, the *short* set within the
    original context and the *long* set beyond it (seq-dependent, traced),
    with a sqrt-log attention temperature (HF ``_compute_longrope_parameters``)."""
    short = jnp.asarray(s["short_factor"], jnp.float32)
    long = jnp.asarray(s["long_factor"], jnp.float32)
    original_max = s.get("original_max_position_embeddings")
    if original_max:  # Phi-3 style: the max/original ratio overrides factor
        factor = max_position / original_max
    else:
        original_max = max_position
        factor = s.get("factor") or 1.0

    attention_factor = s.get("attention_factor")
    if attention_factor is None:
        if factor <= 1.0:
            attention_factor = 1.0
        else:
            attention_factor = math.sqrt(
                1 + math.log(factor) / math.log(original_max))

    base = theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    ext = jnp.where(seq_len > original_max, long, short)
    return 1.0 / (ext * base), float(attention_factor)


def scaled_rope_frequencies(
    head_dim: int,
    theta: float,
    scaling: Any = None,
    max_position: Optional[int] = None,
    seq_len=None,
) -> tuple[jnp.ndarray, float]:
    """(inv_freq [head_dim//2], attention_factor) for any HF rope type.

    ``scaling`` is the HF ``rope_scaling`` dict (or its frozen-tuple form);
    ``max_position`` the config's max_position_embeddings; ``seq_len`` a
    (possibly traced) current-sequence length, required by the
    seq-dependent types (``dynamic``, ``longrope``)."""
    rope_type = rope_type_of(scaling)
    if rope_type == "default":
        return rope_frequencies(head_dim, theta), 1.0
    s = _scaling_dict(scaling)
    if rope_type == "linear":
        return rope_frequencies(head_dim, theta) / s["factor"], 1.0
    if rope_type == "llama3":
        return _llama3_frequencies(rope_frequencies(head_dim, theta), s), 1.0
    if rope_type == "yarn":
        return _yarn_frequencies(head_dim, theta, s, max_position)
    if rope_type == "dynamic":
        # NTK-by-parts via theta rescale, pivoting at max_position (HF
        # semantics: scaling engages only past the configured context)
        factor = s["factor"]
        if seq_len is None:
            seq_len = max_position
        seq_len = jnp.maximum(jnp.asarray(seq_len, jnp.float32),
                              float(max_position))
        base = theta * ((factor * seq_len / max_position) - (factor - 1)) ** (
            head_dim / (head_dim - 2))
        exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
        return 1.0 / (base ** exponent), 1.0
    if rope_type == "longrope":
        if seq_len is None:
            seq_len = max_position
        return _longrope_frequencies(head_dim, theta, s, max_position, seq_len)
    raise ValueError(
        f"unsupported rope_scaling type {rope_type!r} (supported: {ROPE_TYPES})")


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray,
               theta: float = 10000.0, scaling: Any = None,
               max_position: Optional[int] = None) -> jnp.ndarray:
    """Rotate ``x`` [..., seq, heads, head_dim] by position-dependent angles.

    ``positions`` is [..., seq] (int). Computation in float32, result cast back
    to ``x.dtype`` — rope in bf16 loses position resolution at long context.
    ``scaling``/``max_position`` select an HF rope_scaling flavor (None =
    plain RoPE, the fast path)."""
    head_dim = x.shape[-1]
    if scaling is None:
        inv_freq, attn_factor = rope_frequencies(head_dim, theta), 1.0
    else:
        seq_len = None
        if rope_type_of(scaling) in SEQ_DEPENDENT_ROPE_TYPES:
            seq_len = jnp.max(positions) + 1  # traced, like HF's position_ids.max()
        inv_freq, attn_factor = scaled_rope_frequencies(
            head_dim, theta, scaling, max_position, seq_len)
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., S, D/2]
    cos = jnp.cos(angles)[..., None, :] * attn_factor  # [..., S, 1, D/2]
    sin = jnp.sin(angles)[..., None, :] * attn_factor
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)
