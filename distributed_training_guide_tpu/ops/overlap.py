"""Latency-hiding training schedules: explicit shard_map collectives the
XLA scheduler can slide across layer boundaries.

The unscheduled train step leaves communication to GSPMD: FSDP parameter
all-gathers are inserted *at use* inside the ``lax.scan`` over layers, grad
reduce-scatters materialize at the optimizer boundary, and both serialize
against compute — a collective inside a scan iteration structurally cannot
start during the previous iteration, whatever the latency-hiding scheduler
would like (the reference gets the overlap for free from FSDP2's implicit
prefetch + eager frees, ``04-fully-sharded-data-parallel/train_llm.py`` /
arXiv:2304.11277; ZeRO's byte accounting is arXiv:1910.02054).

``--overlap-schedule`` swaps that for an explicit schedule
(:class:`LayerSchedule`):

- the layer loop is UNROLLED into a flat program, so the scheduler may
  issue layer i+1's collectives while layer i computes;
- each layer's fsdp-sharded weights are all-gathered by a manual
  ``shard_map`` collective (``ops/collectives.all_gather``) with a custom
  VJP whose backward is a per-layer grad reduce-scatter
  (``psum_scatter`` with the cotangent widened to fp32 first, matching
  GSPMD's reduction dtype) — so layer i's reduce-scatter is issued inside
  layer i's backward cell and overlaps layer i-1's backward compute;
- every cell is ``jax.checkpoint``-wrapped; gather outputs are tagged
  ``fsdp_gather`` and excluded from every save policy, so the backward
  *re-gathers* each layer's weights (FSDP semantics — sharded params are
  the only persistent copy) and those re-gathers likewise overlap.

On TPU the overlap shows up as async ``all-gather-start``/``done`` pairs
spanning compute (pinned by tests/test_overlap.py via utils/hlo.py); the
flags below make the scheduler aggressive about it. Off-TPU the collectives
lower synchronously but the program is numerically identical — parity vs
the unscheduled path is the other half of the pin.

``make_fused_loss`` is the same idea applied to the loss: one hidden->loss
kernel (``ops.cross_entropy.fused_linear_cross_entropy``) under a manual
shard_map, composing the chunked loss with the tp/fsdp vocab shard so the
``[B*S, vocab]`` fp32 logits never exist on any device.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from .collectives import all_gather as _all_gather
from .collectives import psum as _psum
from .collectives import psum_scatter as _psum_scatter

# XLA flags the schedule relies on to turn the flat program's collectives
# into async start/done pairs hoisted across layer compute (TPU; harmless
# elsewhere). Recorded in bench detail so measured numbers carry their
# scheduler config; documented in related-topics/performance-tuning.
RECOMMENDED_XLA_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_enable_async_all_gather=true",
    "--xla_enable_async_collective_permute=true",
)

_GATHER_NAME = "fsdp_gather"


def _is_axes_leaf(x) -> bool:
    return isinstance(x, tuple) and all(isinstance(e, (str, type(None)))
                                        for e in x)


def _gather_with_rs_vjp(axis: str, dim: int):
    """All-gather along ``dim`` over ``axis`` whose backward is an explicit
    reduce-scatter. The cotangent is widened to fp32 for the reduction and
    narrowed back to the parameter dtype — the same accumulate-wide /
    store-narrow contract GSPMD applies to its grad reductions, so the
    scheduled path stays bit-comparable to the unscheduled one."""

    @jax.custom_vjp
    def gather(p):
        return _all_gather(p, axis, dim=dim)

    def fwd(p):
        return gather(p), None

    def bwd(_, ct):
        # the gather is cast-free, so ct.dtype == the parameter dtype
        return (_psum_scatter(ct.astype(jnp.float32), axis,
                              scatter_dimension=dim).astype(ct.dtype),)

    gather.defvjp(fwd, bwd)
    return gather


class LayerSchedule:
    """Explicit per-layer prefetch/reduce-scatter schedule for a model's
    stacked layer parameters (built by :func:`make_layer_schedule`; threaded
    into the families' ``apply(..., layer_schedule=...)``).

    Call as ``schedule(block, carry, layers, wins)`` in place of the layer
    ``lax.scan``: ``block(carry, layer_params[, window_override=w])`` is the
    family's block function; ``layers`` the stacked param tree; ``wins`` the
    optional per-layer window column.
    """

    def __init__(self, mesh, gather_specs: Sequence[Optional[tuple]],
                 *, axis: str, remat: bool, remat_policy: Any,
                 manual: Optional[set] = None):
        # gather_specs: per layer-tree leaf, None (pass through) or the
        # leaf's full per-layer PartitionSpec entries with ``axis`` on the
        # dim to gather (other entries — e.g. a tp shard — stay put)
        self._gather_idx = [i for i, s in enumerate(gather_specs)
                            if s is not None]
        self.axis = axis
        self.n_gathered = len(self._gather_idx)
        if remat:
            # the user's policy decides what survives; none of the named
            # policies save the (untagged-by-them) fsdp_gather outputs, so
            # backward re-gathers either way
            self._policy = remat_policy
        else:
            # no user remat: save everything EXCEPT gathered weights — the
            # sharded params stay the only persistent copy (FSDP semantics)
            # and backward re-gathers layer by layer
            self._policy = jax.checkpoint_policies.save_anything_except_these_names(
                _GATHER_NAME)
        if not self._gather_idx:
            self._sm = None
            return
        gathers = []
        in_specs = []
        out_specs = []
        for i in self._gather_idx:
            entries = list(gather_specs[i])
            dim = next(j for j, e in enumerate(entries)
                       if axis in ((e,) if isinstance(e, str) else (e or ())))
            in_specs.append(P(*entries))
            out = list(entries)
            out[dim] = (None if isinstance(out[dim], str) else
                        tuple(a for a in out[dim] if a != axis) or None)
            out_specs.append(P(*out))  # gathered on ``axis``; e.g. a tp
            gathers.append(_gather_with_rs_vjp(axis, dim))  # shard stays

        def body(*shards):
            return tuple(g(p) for g, p in zip(gathers, shards))

        # the manual set covers every ACTIVE data axis and every axis a
        # leaf spec names, not just the gather axis: (a) jax 0.4.37's
        # partitioner rejects programs mixing manual subgroups of different
        # shapes (the EP dispatch and fused-loss regions are manual over
        # all data axes + tp), and (b) with dp/ep manual and unnamed in the
        # weight specs, shard_map's transpose psums the weight cotangent
        # over them PER LAYER — the data-parallel grad reduction issued
        # layer by layer in backward instead of in bulk at the optimizer
        # boundary
        self._sm = jax.shard_map(
            body, mesh=mesh, axis_names=manual or {axis}, check_vma=False,
            in_specs=tuple(in_specs), out_specs=tuple(out_specs))

    def gather_layer(self, layer):
        """All-gather one layer's fsdp-sharded leaves (manual collectives);
        pass every other leaf through untouched. Outputs are tagged so remat
        policies drop them (backward re-gathers)."""
        if self._sm is None:
            return layer
        flat, treedef = jax.tree_util.tree_flatten(layer)
        gathered = self._sm(*[flat[i] for i in self._gather_idx])
        for i, g in zip(self._gather_idx, gathered):
            flat[i] = checkpoint_name(g, _GATHER_NAME)
        return jax.tree_util.tree_unflatten(treedef, flat)

    def __call__(self, block, carry, layers, wins=None):
        leaves = jax.tree.leaves(layers)
        n_layers = leaves[0].shape[0]

        def cell(carry, layer, w):
            layer = self.gather_layer(layer)
            if w is None:
                return block(carry, layer)
            return block(carry, layer, window_override=w)

        # prevent_cse=True (the default): in a flat program CSE would merge
        # the backward recompute with the forward, resurrecting the gathered
        # weights the policy just dropped
        cell = jax.checkpoint(cell, policy=self._policy)
        for i in range(n_layers):
            layer_i = jax.tree.map(lambda p: p[i], layers)
            w = None if wins is None else wins[i]
            carry = cell(carry, layer_i, w)
        return carry


def make_layer_schedule(plan, layer_axes, layer_shapes, *, remat: bool,
                        remat_policy: Any, axis: str = "fsdp"
                        ) -> LayerSchedule:
    """Build the schedule for a plan's stacked layer params.

    ``layer_axes`` / ``layer_shapes``: the ``params["layers"]`` subtrees of
    the bundle's logical axes and shape trees (leading axis "layers" — the
    unrolled dim). Leaves whose spec puts ``axis`` on a dim get the manual
    gather; everything else passes through, so plans with no fsdp-sharded
    params (ddp/zero1/ep) still get the flat unrolled program (collectives
    free to slide) with zero gathers.
    """
    from ..parallel.plans import spec_for_leaf

    mesh = plan.mesh
    ax_leaves = jax.tree.leaves(layer_axes, is_leaf=_is_axes_leaf)
    sd_leaves = jax.tree.leaves(layer_shapes)
    assert len(ax_leaves) == len(sd_leaves)
    specs: list[Optional[tuple]] = []
    manual = {axis} | {a for a in plan.data_axes if mesh.shape.get(a, 1) > 1}
    sharded = mesh.shape.get(axis, 1) > 1
    ep_active = mesh.shape.get("ep", 1) > 1
    for ax, sd in zip(ax_leaves, sd_leaves):
        leaf_spec = None
        if sharded and not (ep_active and "experts" in ax):
            # expert-stacked weights under an active ep axis are gathered
            # INSIDE the EP dispatch region (make_ragged_ep_dispatch's
            # embed_axis path) — gathering them out here would feed one
            # partial-manual region's output into another, which the jax
            # 0.4.37 partitioner rejects outright
            spec = spec_for_leaf(mesh, ax, sd.shape, plan.rules)
            entries = list(spec) + [None] * (len(sd.shape) - len(spec))
            entries = entries[1:]  # drop the leading stacked "layers" dim
            names = set()
            for e in entries:
                names.update((e,) if isinstance(e, str) else (e or ()))
            if axis in names:
                leaf_spec = tuple(entries)
                manual |= names  # e.g. tp: the shard rides through the
                #                  region; manual sets must agree program-wide
        specs.append(leaf_spec)
    return LayerSchedule(mesh, specs, axis=axis, remat=remat,
                         remat_policy=remat_policy, manual=manual)


# ---------------------------------------------------------------------------
# fused hidden -> loss
# ---------------------------------------------------------------------------

def make_fused_loss(plan, *, num_chunks: int = 8):
    """One hidden->loss kernel for the plan: a manual shard_map around
    ``fused_linear_cross_entropy`` composing the chunked loss with the
    plan's vocab shard, so full ``[B*S, V]`` fp32 logits never exist.

    - vocab on **tp** (megatron loss-parallel): the kernel runs the
      vocab-parallel logsumexp/pick with explicit tp psums; under sequence
      parallelism the tp-sharded seq dim is all-gathered first (its
      transpose reduce-scatters the hidden cotangent — the SP backward).
    - vocab on **fsdp** (the fsdp plan's lm_head): the weight shard is
      all-gathered inside the region (transpose = the lm_head grad
      reduce-scatter, the same schedule story as the layers) and each
      member runs the full-vocab chunked kernel on its batch rows.
    - unsharded vocab: pure local chunked kernel.

    Returns ``loss(hidden [B,S,E], w_out [E,V], labels [B,S]) -> scalar``.
    """
    from .cross_entropy import fused_linear_cross_entropy

    mesh = plan.mesh
    data_axes = tuple(a for a in plan.data_axes if mesh.shape.get(a, 1) > 1)
    vocab_rule = plan.rules.get("vocab")

    def _sharded(rule_axis):
        return vocab_rule == rule_axis and mesh.shape.get(rule_axis, 1) > 1

    tp_vocab = _sharded("tp")
    fsdp_vocab = _sharded("fsdp")
    seq_tp = plan.sequence_sharded and mesh.shape.get("tp", 1) > 1

    manual = set(data_axes)
    if tp_vocab or seq_tp:
        manual.add("tp")
    if fsdp_vocab:
        manual.add("fsdp")
    if not manual:
        def local_loss(hidden, w_out, labels):
            nll, cnt = fused_linear_cross_entropy(hidden, w_out, labels,
                                                  num_chunks=num_chunks)
            return nll / jnp.maximum(cnt, 1.0)

        return local_loss

    hidden_spec = P(data_axes or None, "tp" if seq_tp else None, None)
    w_spec = P(None, "tp" if tp_vocab else ("fsdp" if fsdp_vocab else None))
    labels_spec = P(data_axes or None, None)
    w_gather = _gather_with_rs_vjp("fsdp", 1) if fsdp_vocab else None

    def body(hidden, w_out, labels):
        if seq_tp:
            # SP: pull the full sequence in; the transpose reduce-scatters
            # the hidden cotangent back onto the tp seq shards
            hidden = _all_gather(hidden, "tp", dim=1)
        if w_gather is not None:
            w_out = checkpoint_name(w_gather(w_out), _GATHER_NAME)
        nll, cnt = fused_linear_cross_entropy(
            hidden, w_out, labels, num_chunks=num_chunks,
            vocab_axis="tp" if tp_vocab else None)
        if data_axes:
            # global mean: sum over the batch-owning axes only (tp members
            # hold the SAME rows post-psum — summing over tp would double
            # count)
            nll = _psum(nll, data_axes)
            cnt = _psum(cnt, data_axes)
        return nll / jnp.maximum(cnt, 1.0)

    return jax.shard_map(body, mesh=mesh, axis_names=manual, check_vma=False,
                         in_specs=(hidden_spec, w_spec, labels_spec),
                         out_specs=P())


def fused_loss_supported(plan, config, family_mod, loss_fn) -> Optional[str]:
    """Why the fused hidden->loss path can NOT run for this setup (None =
    supported). The Trainer falls back to the standard loss branches on a
    reason rather than silently changing semantics."""
    from .cross_entropy import causal_lm_loss

    if not hasattr(family_mod, "output_weights"):
        return "family has no output_weights"
    if loss_fn is not causal_lm_loss:
        return "custom loss_fn"
    if getattr(config, "final_logit_softcap", None):
        return "final_logit_softcap is applied by lm_head_logits, which the "\
               "fused hidden->loss kernel bypasses"
    if plan.mesh.shape.get("cp", 1) > 1:
        return "cp-sharded sequence"
    vocab_rule = plan.rules.get("vocab")
    if vocab_rule not in (None, "tp", "fsdp"):
        return f"vocab sharded on unsupported axis {vocab_rule!r}"
    if vocab_rule is not None:
        size = plan.mesh.shape.get(vocab_rule, 1)
        if size > 1 and config.vocab_size % size:
            return (f"vocab_size {config.vocab_size} not divisible by "
                    f"{vocab_rule}={size}")
    return None
