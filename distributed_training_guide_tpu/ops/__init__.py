from .attention import multihead_attention
from .rope import apply_rope, rope_frequencies
from .cross_entropy import causal_lm_loss

__all__ = ["multihead_attention", "apply_rope", "rope_frequencies", "causal_lm_loss"]
