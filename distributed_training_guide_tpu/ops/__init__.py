from .attention import multihead_attention
from .cross_entropy import causal_lm_loss, chunked_causal_lm_loss
from .grouped_matmul import grouped_matmul
from .rope import apply_rope, rope_frequencies

__all__ = [
    "multihead_attention",
    "grouped_matmul",
    "apply_rope",
    "rope_frequencies",
    "causal_lm_loss",
    "chunked_causal_lm_loss",
]
