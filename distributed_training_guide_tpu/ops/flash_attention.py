"""Flash attention as a Pallas TPU kernel (forward + custom-VJP backward).

This is the TPU-native replacement for the reference's external ``flash-attn``
CUDA wheel (``05-training-llama-405b/train_llm.py:93``, install note
``README.md:57``) — the one component the reference cannot express in Python
and the SURVEY.md build plan's deliberate custom-kernel deliverable.

Design (standard blockwise online-softmax, laid out for the MXU/VMEM):

- inputs are processed as [B, H, S, D]; the grid walks (batch, q-head,
  q-block, kv-block) with the kv-block innermost — TPU grids execute
  sequentially per core, so the online-softmax running state (m, l, acc)
  lives in VMEM scratch carried across kv-block steps;
- causal masking skips fully-masked kv blocks via ``pl.when`` (no compute
  issued) and applies an element mask only on diagonal blocks;
- GQA is native: q-head h reads kv-head ``h // (Hq // Hkv)`` through the
  BlockSpec index maps — no materialized ``repeat`` of K/V (the XLA reference
  path in ``attention.py`` groups heads instead);
- scores/softmax accumulate in fp32 regardless of input dtype;
- backward recomputes attention blockwise (flash-bwd): a dq kernel with the
  same walk, and a dk/dv kernel walking (batch, kv-head, group, kv-block,
  q-block) that also reduces over the GQA group on-chip. The logsumexp from
  the forward and ``delta = rowsum(dO * O)`` (cheap XLA einsum) are the only
  residuals — activation memory is O(B*H*S), not O(B*H*S^2).

``interpret=True`` runs the same kernels on CPU (used by the test suite's
numerics goldens against the XLA reference implementation).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard only for exotic setups
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30


def _pick_block(s: int, preferred: int = 512) -> int:
    for cand in (preferred, 256, 128, 64, 32, 16, 8):
        if s % cand == 0 and cand <= s:
            return cand
    return s


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _band_live(causal, window, iq, ik, block_q, block_k, q_off=0, k_off=0):
    """Block-level skip predicate: False when NO (q_pos, k_pos) pair in the
    (iq, ik) tile satisfies the causal/sliding-window band. The whole tile's
    compute is skipped via ``pl.when`` — this is where SWA's speedup comes
    from (tiles strictly below the band cost zero, so work is O(S*W) not
    O(S^2) once S >> window). ``window``/``q_off``/``k_off`` may be traced
    scalars (per-layer window schedules, ring chunk offsets) — program_id is
    runtime-valued anyway, so the predicate was never a compile-time skip."""
    if not causal:
        return True
    live = ik * block_k + k_off <= iq * block_q + block_q - 1 + q_off
    if window is not None:
        # newest key in the tile still inside the OLDEST query's window
        live &= (ik * block_k + block_k - 1 + k_off
                 >= iq * block_q + q_off - (window - 1))
    return live


def _band_mask(causal, window, iq, ik, block_q, block_k, shape,
               q_off=0, k_off=0):
    """Element mask for a live tile (None = nothing masked)."""
    if not causal:
        return None
    q_pos = iq * block_q + q_off + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    k_pos = ik * block_k + k_off + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    mask = q_pos >= k_pos
    if window is not None:
        mask &= (q_pos - k_pos) < window
    return mask


def _unpack_band(band_ref, window):
    """Kernel-side band parameters: (window, q_off, k_off).

    ``band_ref`` is the optional [3] int32 SMEM operand carrying a DYNAMIC
    band — [window, q_offset, k_offset] — used for traced per-layer windows
    (Gemma-2's alternating schedule rides a lax.scan) and the ring's global
    chunk offsets. When absent, ``window`` is the static compile-time int
    (or None = no band) and offsets are zero, exactly the pre-dynamic
    behavior."""
    if band_ref is not None:
        return band_ref[0], band_ref[1], band_ref[2]
    return window, 0, 0


def _softcap_fwd(s, softcap):
    """tanh logit capping (Gemma-2): cap * tanh(s / cap), scores-side."""
    return jnp.tanh(s / softcap) * softcap


def _fwd_kernel(*refs, scale, softcap, causal, window, banded, block_q,
                block_k, num_kv_blocks):
    if banded:  # inputs carry the trailing dynamic [3] band operand
        q_ref, k_ref, v_ref, band_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
        band_ref = None
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    window, q_off, k_off = _unpack_band(band_ref, window)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # band: kv block fully outside the causal/window band -> skip all compute
    live = _band_live(causal, window, iq, ik, block_q, block_k, q_off, k_off)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)          # [BQ, D]
        k = k_ref[0, 0].astype(jnp.float32)          # [BK, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if softcap is not None:  # Gemma-2: tanh cap BEFORE the mask
            s = _softcap_fwd(s, softcap)
        mask = _band_mask(causal, window, iq, ik, block_q, block_k, s.shape,
                          q_off, k_off)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, 0:1]                        # [BQ, 1]
        l_prev = l_scr[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                        # [BQ, BK]
        if window is not None and mask is not None:
            # a live SWA tile can hold FULLY-masked q rows (window's lower
            # edge crosses the tile): there m_new == NEG_INF and
            # exp(s - m_new) == exp(0) == 1 — zero those lanes explicitly.
            # (Pure causal never hits this: with block_q == block_k every
            # live tile's rows keep >= 1 unmasked key.)
            p = jnp.where(mask, p, 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)           # [BK, D]
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == num_kv_blocks - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        lse = m_scr[:, 0:1] + jnp.log(safe_l)
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:]).astype(jnp.float32)


def check_static_window(window):
    """A static ``window < 1`` masks EVERY score: the kernel's safe_l path
    (and the xla softmax) would return all-zero attention with no error —
    silently-dead attention. Raise instead, at every entry point. Traced
    windows can't be checked here; their sanctioned producer
    (``_layer_window_column``) validates its config inputs."""
    if isinstance(window, int) and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")


def _pack_band(window, q_off=0, k_off=0):
    """The kernels' [window, q_offset, k_offset] int32 band operand (SMEM).
    This layout — and the 2**30 "full attention" encoding packed for a None
    window — is the one dynamic-band contract shared by _resolve_band, the
    sharded wrapper's per-call override, and the ring's chunk-offset pairs."""
    return jnp.stack([jnp.asarray(2 ** 30 if window is None else window),
                      jnp.asarray(q_off),
                      jnp.asarray(k_off)]).astype(jnp.int32)


def _resolve_band(window):
    """Split a caller's window into the kernels' static ``window`` +
    optional dynamic [3] int32 band operand ([window, q_offset, k_offset];
    offsets zero here — the ring packs nonzero chunk offsets directly).

    Static path (window None or a Python int): no operand — the band is
    baked into the kernel, byte-identical to the pre-dynamic program.
    Dynamic path (traced window): the band rides a tiny SMEM operand. A
    traced window of 2**30 (= "full attention this layer",
    _layer_window_column's encoding of 0) is wider than any supported
    sequence, so the banded program degenerates to plain causal numerics."""
    if window is None or isinstance(window, int):
        return window, None
    return None, _pack_band(window)


def _band_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM if pltpu is not None else None)


def _flash_fwd(q, k, v, causal, window, block_q, block_k, interpret,
               scale=None, softcap=None, band=None):
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    groups = hq // hkv
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    nq, nk = sq // block_q, sk // block_k
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if band is None:
        window, band = _resolve_band(window)
    else:
        window = None  # caller-packed dynamic band (the custom_vjp/ring path)

    grid = (b, hq, nq, nk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, softcap=softcap, causal=causal,
        window=window, banded=band is not None, block_q=block_q,
        block_k=block_k, num_kv_blocks=nk)

    out_shape = (
        jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
        jax.ShapeDtypeStruct((b, hq, sq, 128), jnp.float32),  # lse (lane-padded)
    )
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda b_, h, iq, ik: (b_, h, iq, 0),
                     memory_space=_VMEM),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda b_, h, iq, ik, g=groups: (b_, h // g, ik, 0),
                     memory_space=_VMEM),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda b_, h, iq, ik, g=groups: (b_, h // g, ik, 0),
                     memory_space=_VMEM),
    ]
    args = [q, k, v]
    if band is not None:
        in_specs.append(_band_spec())
        args.append(band)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, iq, ik: (b_, h, iq, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, 1, block_q, 128), lambda b_, h, iq, ik: (b_, h, iq, 0),
                         memory_space=_VMEM),
        ),
        scratch_shapes=[
            _VMEM((block_q, 128), jnp.float32),
            _VMEM((block_q, 128), jnp.float32),
            _VMEM((block_q, d), jnp.float32),
        ],
        out_shape=out_shape,
        interpret=interpret,
    )(*args)
    return o, lse[..., 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_scores(q, k, lse, scale, softcap, mask):
    """Shared bwd-side score recompute: (p, softcap_grad) where ``p`` is the
    softmax probability rebuilt from the GLOBAL lse and ``softcap_grad`` the
    tanh chain factor (1 - tanh^2), None without capping. Masked lanes need
    no explicit zeroing: lse is finite (every causal row keeps its own key
    in-window), so exp(NEG_INF - lse) underflows to exactly 0."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    cap_grad = None
    if softcap is not None:
        t = jnp.tanh(s / softcap)
        s = t * softcap
        cap_grad = 1.0 - t * t   # d(cap*tanh(u/cap))/du, threaded into ds
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    return jnp.exp(s - lse), cap_grad


def _dq_kernel(*refs, scale, softcap, causal, window, banded, block_q,
               block_k, num_kv_blocks):
    if banded:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, band_ref,
         dq_ref, dq_scr) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr = refs
        band_ref = None
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    window, q_off, k_off = _unpack_band(band_ref, window)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live = _band_live(causal, window, iq, ik, block_q, block_k, q_off, k_off)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, 0:1]
        delta = delta_ref[0, 0][:, 0:1]

        mask = _band_mask(causal, window, iq, ik, block_q, block_k,
                          (block_q, block_k), q_off, k_off)
        p, cap_grad = _bwd_scores(q, k, lse, scale, softcap, mask)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        if cap_grad is not None:   # tanh backward: ds flows through the cap
            ds = ds * cap_grad
        ds = ds * scale
        dq_scr[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(ik == num_kv_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale, softcap, causal, window, banded, block_q,
                block_k, num_q_blocks, groups):
    # grid (b, hkv, ik, ig, iq): the kv-block ik is OUTER to the (group,
    # q-block) accumulation dims, so the scratch is initialized exactly when a
    # new dk/dv output block is first visited and flushed when last visited.
    if banded:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, band_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        band_ref = None
    ik = pl.program_id(2)
    ig = pl.program_id(3)   # GQA group member
    iq = pl.program_id(4)
    window, q_off, k_off = _unpack_band(band_ref, window)

    @pl.when((iq == 0) & (ig == 0))
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live = _band_live(causal, window, iq, ik, block_q, block_k, q_off, k_off)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)                   # [BQ, D]
        k = k_ref[0, 0].astype(jnp.float32)                   # [BK, D]
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, 0:1]
        delta = delta_ref[0, 0][:, 0:1]

        mask = _band_mask(causal, window, iq, ik, block_q, block_k,
                          (block_q, block_k), q_off, k_off)
        p, cap_grad = _bwd_scores(q, k, lse, scale, softcap, mask)
        dv_scr[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                                 # [BQ, BK]
        if cap_grad is not None:
            ds = ds * cap_grad
        ds = ds * scale
        dk_scr[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when((iq == num_q_blocks - 1) & (ig == groups - 1))
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def flash_bwd_with_stats(q, k, v, do, lse, delta, *, causal, window=None,
                         block_q=512, block_k=512, interpret=False,
                         scale=None, softcap=None, band=None):
    """Flash backward from caller-supplied softmax stats -> (dq, dk, dv).

    ``lse``/``delta`` ([B, Hq, Sq] fp32) are normally the forward's
    logsumexp and ``rowsum(do * o)``; ring attention passes the *global*
    (cross-chunk) stats here to get each chunk pair's exact gradient
    contribution without rebuilding the full attention matrix.
    ``scale``/``softcap``/``band`` mirror ``_flash_fwd``: the same score
    recompute (including the tanh cap, whose ``(1 - tanh^2)`` factor
    threads through ds) must run in backward for the identity to hold.
    """
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    groups = hq // hkv
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    nq, nk = sq // block_q, sk // block_k
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if band is None:
        window, band = _resolve_band(window)
    else:
        window = None

    lse_l = jnp.broadcast_to(lse[..., None], (*lse.shape, 128))
    delta_l = jnp.broadcast_to(delta[..., None], (*delta.shape, 128))

    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h, iq, ik: (b_, h, iq, 0),
                          memory_space=_VMEM)
    kv_spec = pl.BlockSpec((1, 1, block_k, d),
                           lambda b_, h, iq, ik, g_=groups: (b_, h // g_, ik, 0),
                           memory_space=_VMEM)
    stat_spec = pl.BlockSpec((1, 1, block_q, 128), lambda b_, h, iq, ik: (b_, h, iq, 0),
                             memory_space=_VMEM)

    dq_in_specs = [q_spec, kv_spec, kv_spec, q_spec, stat_spec, stat_spec]
    dq_args = [q, k, v, do, lse_l, delta_l]
    if band is not None:
        dq_in_specs.append(_band_spec())
        dq_args.append(band)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, softcap=softcap,
                          causal=causal, window=window,
                          banded=band is not None,
                          block_q=block_q, block_k=block_k, num_kv_blocks=nk),
        grid=(b, hq, nq, nk),
        in_specs=dq_in_specs,
        out_specs=q_spec,
        scratch_shapes=[_VMEM((block_q, d), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(*dq_args)

    # dk/dv: walk (b, kv-head, kv-block, group-member, q-block); q-side refs
    # index head = hkv * groups + ig
    def q_idx(b_, hkv_, ik, ig, iq, g_=groups):
        return (b_, hkv_ * g_ + ig, iq, 0)

    def kv_idx(b_, hkv_, ik, ig, iq):
        return (b_, hkv_, ik, 0)

    dkv_in_specs = [
        pl.BlockSpec((1, 1, block_q, d), q_idx, memory_space=_VMEM),
        pl.BlockSpec((1, 1, block_k, d), kv_idx, memory_space=_VMEM),
        pl.BlockSpec((1, 1, block_k, d), kv_idx, memory_space=_VMEM),
        pl.BlockSpec((1, 1, block_q, d), q_idx, memory_space=_VMEM),
        pl.BlockSpec((1, 1, block_q, 128), q_idx, memory_space=_VMEM),
        pl.BlockSpec((1, 1, block_q, 128), q_idx, memory_space=_VMEM),
    ]
    dkv_args = [q, k, v, do, lse_l, delta_l]
    if band is not None:
        dkv_in_specs.append(_band_spec())
        dkv_args.append(band)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, softcap=softcap,
                          causal=causal, window=window,
                          banded=band is not None, block_q=block_q,
                          block_k=block_k, num_q_blocks=nq, groups=groups),
        grid=(b, hkv, nk, groups, nq),
        in_specs=dkv_in_specs,
        out_specs=(
            pl.BlockSpec((1, 1, block_k, d), kv_idx, memory_space=_VMEM),
            pl.BlockSpec((1, 1, block_k, d), kv_idx, memory_space=_VMEM),
        ),
        scratch_shapes=[_VMEM((block_k, d), jnp.float32),
                        _VMEM((block_k, d), jnp.float32)],
        out_shape=(jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)),
        interpret=interpret,
    )(*dkv_args)

    return dq, dk, dv


def _flash_bwd(causal, window, block_q, block_k, interpret, scale, softcap,
               residuals, g):
    q, k, v, o, lse, band = residuals
    do = g
    delta = jnp.einsum("bhsd,bhsd->bhs", do.astype(jnp.float32),
                       o.astype(jnp.float32))                  # [B,H,S]
    grads = flash_bwd_with_stats(q, k, v, do, lse, delta, causal=causal,
                                 window=window, block_q=block_q,
                                 block_k=block_k, interpret=interpret,
                                 scale=scale, softcap=softcap, band=band)
    # the dynamic band is integer-valued: its cotangent type is float0
    dband = (None if band is None
             else np.zeros(band.shape, jax.dtypes.float0))
    return (*grads, dband)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash(q, k, v, band, causal, window, block_q, block_k, interpret,
           scale, softcap):
    o, _ = _flash_fwd(q, k, v, causal, window, block_q, block_k, interpret,
                      scale=scale, softcap=softcap, band=band)
    return o


def _flash_vjp_fwd(q, k, v, band, causal, window, block_q, block_k,
                   interpret, scale, softcap):
    o, lse = _flash_fwd(q, k, v, causal, window, block_q, block_k, interpret,
                        scale=scale, softcap=softcap, band=band)
    # checkpoint_name tags let a remat policy keep the kernel's backward
    # residuals (o + lse; q/k/v are cheap projections) so the forward kernel
    # is not re-run inside the backward pass — see train/step.py
    # REMAT_POLICIES["attn"]
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse, band)


_flash.defvjp(_flash_vjp_fwd, _flash_bwd)


def _in_manual_context() -> bool:
    """True when tracing inside a manual shard_map region (the pipeline):
    the attention wrappers must then build their shard_maps against the
    context AbstractMesh and skip their eager-entry jit (the caller's jit
    is already above us, and the eager jit's cache must never mix top-level
    and in-pipeline programs)."""
    m = jax.sharding.get_abstract_mesh()
    return bool(m.axis_names) and any(
        t == jax.sharding.AxisType.Manual for t in m.axis_types)


def resolve_wrapper_mesh(mesh):
    """Mesh an attention wrapper's shard_maps must be built against, resolved
    at TRACE time: inside another manual region (the pp pipeline) the context
    AbstractMesh marks pp/tp Manual and shard_map insists on an exact mesh
    match — nesting works iff the inner maps are built against that context
    mesh (their own manual axes stay the still-auto ones). At top level the
    context mesh is empty and the factory's concrete mesh applies."""
    return jax.sharding.get_abstract_mesh() if _in_manual_context() else mesh


def resolve_attention_manual_axes(mesh, batch_axes, head_axis):
    """Shared preamble for the manual-axes attention wrappers (this module's
    sharded flash, ``ring_attention``, and the Ulysses wrapper): keep only
    mesh axes of size > 1, and return (batch_axes, head_axis, tp, batch_div,
    b_spec, manual_set). ``head_axis`` may be one axis name or a tuple of
    names (Ulysses shards heads over ('tp', 'cp')); the normalized form is a
    tuple or None, and ``tp`` is the product of the head-axis sizes."""
    batch_axes = tuple(a for a in batch_axes
                       if a in mesh.shape and mesh.shape[a] > 1)
    if isinstance(head_axis, str):
        head_axis = (head_axis,)
    head_axis = tuple(a for a in (head_axis or ())
                      if mesh.shape.get(a, 1) > 1) or None
    tp = 1
    for a in head_axis or ():
        tp *= mesh.shape[a]
    batch_div = 1
    for a in batch_axes:
        batch_div *= mesh.shape[a]
    b_spec = batch_axes if batch_axes else None
    manual = set(batch_axes) | set(head_axis or ())
    return batch_axes, head_axis, tp, batch_div, b_spec, manual


def attention_divisibility_error(batch_axes, head_axis, tp, batch_div,
                                 hq, hkv, batch, kind):
    """Error text naming only the dimension(s) that actually failed."""
    problems = []
    if head_axis and (hq % tp or hkv % tp):
        problems.append(f"heads {hq}/{hkv} not divisible by "
                        f"{'x'.join(head_axis)}={tp}")
    if batch_axes and batch % batch_div:
        problems.append(f"batch {batch} not divisible by "
                        f"{'x'.join(batch_axes)}={batch_div}")
    return (f"{kind} shards attention over manual mesh axes (the Pallas "
            f"kernels cannot be auto-partitioned): "
            f"{'; '.join(problems)} — pad, or drop the unused mesh axis")


_UNSET = object()   # per-call window sentinel: "use the factory default"


def make_sharded_flash_attention(
    mesh,
    *,
    batch_axes=("dp", "fsdp", "ep"),
    head_axis: Optional[str] = "tp",
    causal: bool = True,
    window: Optional[int] = None,
    block_q: int = 512,
    block_k: int = 512,
    forced: bool = False,
    fallback=None,
    scale: Optional[float] = None,
    logit_softcap: Optional[float] = None,
):
    """Flash attention that PARTITIONS over batch/head mesh axes.

    ``fallback``: attention callable used instead of the plain-xla einsum
    when a shape is ineligible and ``forced`` is False — callers with their
    own sharding discipline (Ulysses) substitute their constraint-based
    path so 'auto' degrades to the RIGHT program, not an unconstrained one.

    The XLA SPMD partitioner cannot shard a Mosaic custom call: a bare
    ``flash_attention`` under a GSPMD mesh compiles, but the partitioner's
    fallback all-gathers q/k/v and runs the FULL kernel on every device
    (output sharding comes back replicated) — mesh_size x wasted attention
    FLOPs on a real pod. This factory returns an attention callable (the
    same contract as ``make_ring_attention``) whose pallas calls run inside
    a shard_map that is manual over exactly the axes that shard attention's
    data-parallel dims: batch over ``batch_axes``, heads over ``head_axis``.
    Attention has no cross-batch or cross-head interaction, so the body
    needs no collectives; the sequence dim stays unsharded (cp>1 uses the
    ring instead).

    Returns None when no relevant axis has size > 1 (single-device meshes:
    the plain kernel path is already optimal). Usable inside the pipeline's
    pp-manual shard_map too: the flash maps are built at trace time against
    the *context* mesh, so inside a manual region they nest as a
    dp/fsdp-manual sub-region over the still-auto data axes (pass
    ``head_axis=None`` there — heads arrive pre-sharded as manual megatron
    shards). Building against the factory's concrete mesh instead would
    fail: the trace context's AbstractMesh marks pp/tp Manual and shard_map
    requires an exact mesh match.

    The custom_vjp sits OUTSIDE the two shard_maps, like the ring's: grad
    cannot transpose through a partial-manual shard_map, so forward and
    backward are each a plain non-differentiated shard_map. Residuals are
    the RAW inputs plus the (checkpoint_name-tagged) primal output and lse
    — nothing residual-only leaves the fwd map, because a shard_map eqn is
    atomic under jax.checkpoint's partial-eval and rebuilding any such
    output would re-run the kernel (vjp_bwd re-derives the kernel layouts
    by transposing outside the map).
    """
    from jax.sharding import PartitionSpec as P

    check_static_window(window)
    batch_axes, head_axis, tp, batch_div, b_spec, manual = \
        resolve_attention_manual_axes(mesh, batch_axes, head_axis)
    if not manual:
        return None
    interpret = jax.default_backend() != "tpu"
    spec_bshd = P(b_spec, None, head_axis, None)   # q/k/v/do/out [B, S, H, D]
    spec_bhsd = P(b_spec, head_axis, None, None)   # residuals    [B, H, S, D]
    spec_bhs = P(b_spec, head_axis, None)          # lse          [B, H, S]

    def fwd_body(q, k, v):
        qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        o, lse = _flash_fwd(qt, kt, vt, causal, window, block_q, block_k,
                            interpret, scale=scale, softcap=logit_softcap)
        # ONLY the primal output + lse leave the map: a shard_map eqn is
        # atomic under jax.checkpoint's partial-eval, so any residual-only
        # output (the in-map transposes, or a separate kernel-layout o)
        # would force the whole map — pallas call included — to re-run in
        # backward just to rebuild values that are a transpose away.
        # vjp_fwd keeps the raw inputs + tagged outputs as residuals and
        # vjp_bwd re-transposes outside the map.
        return o.transpose(0, 2, 1, 3), lse

    def bwd_body(qt, kt, vt, o, lse, do):
        dq, dk, dv, _ = _flash_bwd(causal, window, block_q, block_k,
                                   interpret, scale, logit_softcap,
                                   (qt, kt, vt, o, lse, None),
                                   do.transpose(0, 2, 1, 3))
        return tuple(g.transpose(0, 2, 1, 3) for g in (dq, dk, dv))

    # dynamic-window twins: the per-layer window (Gemma-2's alternating
    # schedule) arrives as a traced scalar per call, packed into the [3]
    # band operand and riding the maps as a replicated arg — the kernels'
    # tile skipping is a runtime predicate either way
    def fwd_body_dyn(band, q, k, v):
        qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        o, lse = _flash_fwd(qt, kt, vt, causal, None, block_q, block_k,
                            interpret, scale=scale, softcap=logit_softcap,
                            band=band)
        return o.transpose(0, 2, 1, 3), lse

    def bwd_body_dyn(band, qt, kt, vt, o, lse, do):
        dq, dk, dv, _ = _flash_bwd(causal, None, block_q, block_k,
                                   interpret, scale, logit_softcap,
                                   (qt, kt, vt, o, lse, band),
                                   do.transpose(0, 2, 1, 3))
        return tuple(g.transpose(0, 2, 1, 3) for g in (dq, dk, dv))

    res_specs = (spec_bhsd, spec_bhsd, spec_bhsd, spec_bhsd, spec_bhs)
    band_spec = P(None)   # [3] int32, replicated across every manual axis

    def _maps(dyn=False):
        sm = functools.partial(jax.shard_map, mesh=resolve_wrapper_mesh(mesh),
                               axis_names=manual, check_vma=False)
        if dyn:
            fwd = sm(fwd_body_dyn, in_specs=(band_spec, *(spec_bshd,) * 3),
                     out_specs=(spec_bshd, spec_bhs))
            bwd = sm(bwd_body_dyn,
                     in_specs=(band_spec, *res_specs, spec_bshd),
                     out_specs=(spec_bshd,) * 3)
        else:
            fwd = sm(fwd_body, in_specs=(spec_bshd,) * 3,
                     out_specs=(spec_bshd, spec_bhs))
            bwd = sm(bwd_body, in_specs=(*res_specs, spec_bshd),
                     out_specs=(spec_bshd,) * 3)
        return fwd, bwd

    @jax.custom_vjp
    def sharded_flash(q, k, v):
        return _maps()[0](q, k, v)[0]

    def vjp_fwd(q, k, v):
        out, lse = _maps()[0](q, k, v)
        # same remat tags as the plain path (_flash_vjp_fwd): a
        # REMAT_POLICIES["attn"] policy keeps the attention output + lse so
        # backward never re-runs the forward kernel. The tag sits on the
        # PRIMAL output (the kernel-layout residual is a transpose of it,
        # rebuilt in vjp_bwd) — tagging a residual-only map output instead
        # would leave `out` unsaved and drag the map into the recompute
        out = checkpoint_name(out, "flash_out")
        lse = checkpoint_name(lse, "flash_lse")
        return out, (q, k, v, out, lse)

    def vjp_bwd(res, do):
        q, k, v, out, lse = res
        qt, kt, vt, o = (x.transpose(0, 2, 1, 3) for x in (q, k, v, out))
        return _maps()[1](qt, kt, vt, o, lse, do)

    sharded_flash.defvjp(vjp_fwd, vjp_bwd)

    @jax.custom_vjp
    def sharded_flash_dyn(q, k, v, band):
        return _maps(dyn=True)[0](band, q, k, v)[0]

    def vjp_fwd_dyn(q, k, v, band):
        out, lse = _maps(dyn=True)[0](band, q, k, v)
        out = checkpoint_name(out, "flash_out")
        lse = checkpoint_name(lse, "flash_lse")
        return out, (q, k, v, out, lse, band)

    def vjp_bwd_dyn(res, do):
        q, k, v, out, lse, band = res
        qt, kt, vt, o = (x.transpose(0, 2, 1, 3) for x in (q, k, v, out))
        grads = _maps(dyn=True)[1](band, qt, kt, vt, o, lse, do)
        return (*grads, np.zeros(band.shape, jax.dtypes.float0))

    sharded_flash_dyn.defvjp(vjp_fwd_dyn, vjp_bwd_dyn)
    # partial-manual shard_map resolves auto-axis shardings only under jit,
    # so every top-level call — eager OR traced — goes through this jit.
    # ONLY manual-context callers (the pipeline) bypass it for the raw
    # custom_vjp: this jit's cache must hold concrete-mesh programs
    # exclusively, never a context-mesh trace
    sharded_flash_eager = jax.jit(sharded_flash)
    sharded_flash_dyn_eager = jax.jit(sharded_flash_dyn)

    window_default = window

    def attention(q, k, v, standard_layout: bool = True, window=_UNSET,
                  **kwargs):
        # per-call window (traced per-layer schedules) overrides the
        # factory default; _UNSET keeps the baked-in band
        wcall = window_default if window is _UNSET else window
        if not standard_layout:
            # the callable contract carries no positions, so a correct mask
            # for packed/sharded-seq layouts is unbuildable here — fail loud
            # like the ring does rather than mask with arange silently
            raise ValueError(
                "sharded flash attention assumes the standard contiguous "
                "position layout; for packed sequences or explicit positions "
                "on a sharded mesh use attn_impl='xla'")
        hq, hkv, d = q.shape[2], k.shape[2], q.shape[-1]
        eligible = (causal
                    and hq % tp == 0 and hkv % tp == 0
                    and q.shape[0] % batch_div == 0
                    # tile divisibility binds only on compiled Mosaic; the
                    # interpret path (CPU tests) takes any shape
                    and (interpret or (q.shape[1] % 8 == 0
                                       and k.shape[1] % 8 == 0
                                       and d % 64 == 0)))
        if not eligible:
            if forced:
                raise ValueError(
                    f"sharded flash attention needs causal masking, heads "
                    f"divisible by {'x'.join(head_axis or ())}={tp}, batch "
                    f"divisible by {'x'.join(batch_axes)}={batch_div}, seq "
                    f"divisible by 8 and head_dim by 64; got "
                    f"heads={hq}/{hkv}, batch={q.shape[0]}, "
                    f"seq={q.shape[1]}, head_dim={d} — pad, or use "
                    f"impl='xla'")
            if fallback is not None:
                return fallback(q, k, v, standard_layout=standard_layout,
                                window=wcall, **kwargs)
            from .attention import multihead_attention

            return multihead_attention(q, k, v, causal=causal, window=wcall,
                                       scale=scale,
                                       logit_softcap=logit_softcap,
                                       impl="xla")
        in_manual = _in_manual_context()
        if wcall is window_default or (isinstance(wcall, int)
                                       and wcall == window_default):
            # static band (or none): the factory-baked maps
            if in_manual:  # nested in the pipeline: caller's jit is above us
                return sharded_flash(q, k, v)
            return sharded_flash_eager(q, k, v)
        # per-call override (traced per-layer window, an int differing from
        # the factory default, or None against a windowed factory): pack it
        # into the dynamic-band operand explicitly — _resolve_band would
        # treat a static int as "bake it in", which here would silently
        # replace the requested band with the 2**30 no-band encoding
        check_static_window(wcall)
        band = _pack_band(wcall)
        if in_manual:
            return sharded_flash_dyn(q, k, v, band)
        return sharded_flash_dyn_eager(q, k, v, band)

    attention.accepts_window = True
    return attention


def flash_attention(
    q: jnp.ndarray,   # [B, S, Hq, D]
    k: jnp.ndarray,   # [B, S, Hkv, D]
    v: jnp.ndarray,
    *,
    causal: bool = True,
    window=None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
    scale: Optional[float] = None,
    logit_softcap: Optional[float] = None,
) -> jnp.ndarray:
    """Blockwise fused attention; returns [B, S, Hq, D] in q.dtype.

    ``window``: sliding-window attention (HF ``sliding_window`` semantics —
    query i attends keys j with 0 <= i - j < window). kv tiles fully below
    the band are SKIPPED, so cost is O(S*window) once S >> window — the
    reference inherits the same trick from flash-attn's window_size
    (``05-training-llama-405b/train_llm.py:93``). A TRACED window (Gemma-2's
    per-layer schedule riding a lax.scan) rides a [3] int32 SMEM operand
    instead of the baked constant — tile skipping is a runtime predicate
    either way, so the banded cost model is unchanged.

    ``scale``: score-scale override (Gemma-2 ``query_pre_attn_scalar**-0.5``;
    default head_dim**-0.5). ``logit_softcap``: Gemma-2 tanh capping of the
    scaled scores, with the exact ``(1 - tanh^2)`` term in backward."""
    if window is not None and not causal:
        raise ValueError("window (sliding-window attention) requires causal=True")
    check_static_window(window)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    d = q.shape[-1]
    if not interpret and (q.shape[1] % 8 or k.shape[1] % 8 or d % 64):
        # without a tile-divisible block the kernel would fall back to one
        # full-sequence block — certain VMEM blowup / opaque Mosaic errors on
        # TPU. The "auto" dispatcher (ops/attention.py) guards this; a forced
        # impl="flash" fails loudly instead.
        raise ValueError(
            f"flash_attention needs seq divisible by 8 and head_dim by 64; "
            f"got seq_q={q.shape[1]}, seq_k={k.shape[1]}, head_dim={d} — "
            f"pad the sequence or use impl='xla'")
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    static_window, band = _resolve_band(window)
    o = _flash(qt, kt, vt, band, causal, static_window, block_q, block_k,
               interpret, scale, logit_softcap)
    return o.transpose(0, 2, 1, 3)
