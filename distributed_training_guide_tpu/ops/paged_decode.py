"""Paged flash attention over block tables: ONE Pallas kernel family for
every serve-plane forward — decode (T=1), speculative verify (T=k+1),
and chunked prefill (T=chunk) — at query-tile size ``block_q = T``.

The XLA reference path in ``serve/kv_pages.paged_attend`` gathers every
slot's block table into a contiguous ``[S, M*page, Hkv, D]`` logical view
before attending — per forward that is an O(n_slots * max_len) HBM
round-trip (read the pages, WRITE the gathered copy, read it back),
whatever the live context actually is. This kernel is the PagedAttention
analog of ``ops/flash_attention.py`` (Kwon et al., arXiv:2309.06180;
FlashAttention-2, Dao arXiv:2307.08691): the grid walks
(slot, kv-head, kv-page), the block table rides as a SCALAR-PREFETCH
operand so each kv BlockSpec DMAs the slot's next *physical* page
directly from the pool, and the online-softmax partial (m, l, acc) is
carried across page steps in VMEM scratch — the same accumulation
``_fwd_kernel`` uses. Nothing context-sized is ever materialized: reads
are O(live pages) per forward and the only write is the [S, T, Hq, D]
output.

Scope — the whole [S, T] serve contract, one kernel form:

- **T == 1** is the batched decode step (the original block_q==1
  specialist, bitwise unchanged: the query tile is the [groups, hd] GQA
  group and each page step's math is identical op for op).
- **T > 1** carries a ``[T*groups, hd]`` query tile per (slot, kv-head):
  slot s's row r is its token ``r // groups`` at absolute position
  ``lengths[s] + r // groups``, so the shared band machinery
  (`_band_live` at block_q=T for the tile skip, `_band_mask` generalized
  per query row by ``_rows_band_mask``) drives each row's causal
  frontier independently — within-tile causality included, because the
  caller scatters the T new tokens into the pool BEFORE the attend and
  the mask is pure position arithmetic. This is the speculative
  verification forward (``ModelPrograms.verify_for``, T = k+1 candidates
  per slot) and the chunked-prefill chunk ([1, T] attending over its own
  tokens plus the committed history) — both previously exiled to the
  ~3x-byte gather path, and both now reading the context exactly once
  per forward with the read amortized over T tokens.

Feature parity with the serving attend contract rides the multi-token
form unchanged (Gemma-2 verifies and chunk-prefills through this):
``window`` (static, or traced per-layer schedules riding the same [3]
int32 band operand the training kernels use), ``scale``, ``softcap``.
Positions past a query row's own (trash-page rows, a final chunk's
``n_valid`` pad tail, stale rejected-draft garbage) are cut by the
per-row causal mask exactly as in the gather path — pad query rows
compute ignored garbage over the SAME pool bytes the gather view would
read, so flash-vs-gather parity holds on every row, not just live ones.

QUANTIZED pools (``serve/kv_pages.py`` ``kv_dtype="int8"``): pass the
per-(position, kv-head) fp32 scales as ``k_scale``/``v_scale``
``[P, page, Hkv]`` and the kernel dequantizes IN the tile loop — the
scale blocks ride their own block-table BlockSpec, so step (s, h, m)
DMAs physical page ``tables[s, m]``'s payload AND its scale row in the
same prefetch-driven pattern, multiplies them in fp32 inside the
online-softmax accumulation, and still writes only the float output.
The read drops to ~1/4 of the fp32 bytes (int8 payload + 4 B/vector
scales) with no float pool ever materialized — at any T.

``interpret=True`` runs the kernel on CPU — the tier-1 parity grids in
``tests/test_paged_decode.py`` pin it against the XLA gather path at
1e-5 across GQA/window/scale/softcap, shuffled physical layouts, and
multi-token tiles with ``n_valid`` tails.

Under the SHARDED page pool (``serve/sharding.py``) this kernel runs
inside a full-manual shard_map with a per-chip pool slice: GSPMD cannot
partition a ``pallas_call``, so the manual region is what takes the
kernel from "replicated over a replicated pool" to "each chip reads its
own kvh/tp heads' pages". Nothing here changes — the grid's kv-head axis
is just smaller (possibly 1), block tables/lengths arrive replicated,
and the GQA group count is per-KV-head and therefore shard-invariant;
the chunk and verify programs ride the same manual region the decode
does.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .flash_attention import (NEG_INF, _band_live, _pack_band,
                              check_static_window)

try:  # pltpu imports on CPU builds; guard only for exotic setups
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None


def _rows_band_mask(window, m_idx, block_q, groups, page, q_off):
    """``ops/flash_attention._band_mask`` generalized to the paged query
    tile's ``[block_q * groups, page]`` row layout: the GQA group axis is
    folded into rows, so query row r is the slot's token ``r // groups``
    at absolute position ``q_off + r // groups``, and key column j is
    position ``m_idx * page + j``. Same (causal, ``< window``) band,
    driven per query row — each row's causal frontier is its own
    ``length + t``. ``window`` is the kernel's [3] SMEM band value (2**30
    encodes "no window"), so the band term is always applied."""
    shape = (block_q * groups, page)
    q_pos = q_off + jax.lax.broadcasted_iota(jnp.int32, shape, 0) // groups
    k_pos = m_idx * page + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    return (q_pos >= k_pos) & ((q_pos - k_pos) < window)


def _attend_kernel(lens_ref, tabs_ref, band_ref, q_ref, k_ref, v_ref, *rest,
                   scale, softcap, page, num_page_blocks, quantized,
                   block_q, groups):
    """Grid (slot, kv_head, page_block); page_block innermost so the
    (m, l, acc) scratch carries the online softmax across the slot's
    pages. The query tile is ``[block_q * groups, hd]`` — block_q tokens
    per slot with the GQA group folded into rows — and the tile's first
    token sits at ``lengths[slot]``, which drives the shared band
    machinery per row. block_q == 1 is the original decode specialist,
    op for op. Under ``quantized`` two more inputs follow k/v: the
    page's k/v scale rows, DMA'd through the same block-table index map
    and multiplied into the int8 payload right here in the tile loop."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    s_idx = pl.program_id(0)
    m_idx = pl.program_id(2)
    q_pos = lens_ref[s_idx]          # the FIRST new token's position; row
                                     # r sits at q_pos + r // groups
    window = band_ref[0]             # [window, q_off, k_off] contract;
                                     # 2**30 encodes "no window"

    @pl.when(m_idx == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # page fully outside every row's (causal, window) band -> no compute:
    # the newest row's frontier is q_pos + block_q - 1, the oldest row's
    # window edge is q_pos - (window - 1) — exactly _band_live at
    # block_q = T. Dead tiles past the slot's table alias the trash page
    # (table rows are 0-filled), so consecutive skipped steps
    # re-reference one block.
    live = _band_live(True, window, 0, m_idx, block_q, page, q_off=q_pos)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)          # [T*G, D]
        k = k_ref[0, :, 0, :].astype(jnp.float32)    # [page, D]
        if quantized:   # in-tile dequant: int8 payload x per-vector scale
            k = k * ks_ref[0, :, 0][:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if softcap is not None:  # Gemma-2: tanh cap BEFORE the mask
            s = jnp.tanh(s / softcap) * softcap
        # [T*G, page] mask: each query row's own causal/window frontier
        mask = _rows_band_mask(window, m_idx, block_q, groups, page, q_pos)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, 0:1]                       # [T*G, 1]
        l_prev = l_scr[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                       # [T*G, page]
        # a live page can still be fully masked for some rows (the
        # window's lower edge, or an early row of a tile kept live by a
        # later one): exp(NEG_INF - NEG_INF) = 1 would poison l — zero
        # masked lanes explicitly, as the training kernel does for SWA
        # tiles
        p = jnp.where(mask, p, 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, :, 0, :].astype(jnp.float32)    # [page, D]
        if quantized:
            v = v * vs_ref[0, :, 0][:, None]
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(m_idx == num_page_blocks - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


def paged_decode_eligible(head_dim: int, page_size: int,
                          quantized: bool = False) -> bool:
    """Mosaic tile-divisibility gate for the COMPILED kernel (the interpret
    path takes any shape): head_dim on the lane axis, page on sublanes.
    int8 payloads pack (32, 128) native tiles, so the quantized gate is
    stricter on the sublane (page) axis — conservative until the TPU
    pool drains the queued kvq rungs. T-independent by construction (the
    query-tile row count only sizes VMEM scratch), which is what lets
    ``attend_impl='auto'`` resolve decode, verify, and chunk forwards to
    the SAME family: a shape either takes the kernel for all three or
    for none."""
    if quantized:
        return head_dim % 64 == 0 and page_size % 32 == 0
    return head_dim % 64 == 0 and page_size % 8 == 0


def paged_flash_attend(
    q: jnp.ndarray,          # [S, T, Hq, D] query tile per slot
                             # (rank 3 [S, Hq, D] = the T == 1 decode form)
    k_pages: jnp.ndarray,    # [P, page, Hkv, D] — ONE layer's page pool
    v_pages: jnp.ndarray,    # (int8 payload when k_scale/v_scale given)
    tables: jnp.ndarray,     # [S, M] int32 physical page ids (0 = trash)
    lengths: jnp.ndarray,    # [S] int32 — the FIRST query token's
                             # position; slot s's token t sits at
                             # lengths[s] + t, kv positions <= it are live
    *,
    k_scale: Optional[jnp.ndarray] = None,   # [P, page, Hkv] fp32 — the
    v_scale: Optional[jnp.ndarray] = None,   # quantized pool's scales
    window=None,
    scale: Optional[float] = None,
    softcap: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Flash attention through the block table at query-tile size T;
    returns [S, T, Hq, D] (or [S, Hq, D] for a rank-3 q) in q.dtype
    (the output dtype is the QUERY's — a quantized pool still emits
    float attention).

    The caller has already scattered the T new tokens' k/v into the
    pages (``serve/kv_pages.paged_attend`` owns that write, trash-page
    routing of ``n_valid`` pad tails included), so positions
    ``lengths[s] .. lengths[s] + T - 1`` are resident and the per-row
    causal mask keeps everything past each row's own position (trash
    page, stale garbage, later draft rows) out — identical semantics to
    the XLA gather reference, without the gathered view.
    ``k_scale``/``v_scale`` (both or neither) switch on the in-kernel
    dequant of an int8 pool.
    """
    check_static_window(window)
    quantized = k_scale is not None or v_scale is not None
    if quantized and (k_scale is None or v_scale is None):
        raise ValueError("pass both k_scale and v_scale (or neither) — a "
                         "half-quantized pool cannot exist")
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
    s, t, hq, d = q.shape
    _, page, hkv, _ = k_pages.shape
    m = tables.shape[1]
    if hkv < 1 or hq % hkv:
        # a silent floor-division here would drop query heads (the
        # reshape below masks it for some shapes); seen when a sharded
        # caller splits q and the pool on mismatched axes
        raise ValueError(
            f"query heads ({hq}) must be a positive multiple of kv heads "
            f"({hkv}); mismatched head sharding?")
    groups = hq // hkv
    tg = t * groups
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not interpret and not paged_decode_eligible(d, page,
                                                   quantized=quantized):
        raise ValueError(
            f"paged flash attend (compiled) needs head_dim % 64 == 0 and "
            f"page_size % {32 if quantized else 8} == 0; got head_dim={d}, "
            f"page_size={page} — use impl='xla' or adjust page_size")
    band = _pack_band(window)     # [window|2**30, 0, 0] int32 — the same
                                  # dynamic-band contract as the training
                                  # kernels; traced per-layer windows ride it
    # fold (token, group) into one row axis per (slot, kv-head): row
    # r = t * groups + g, so the kernel recovers the token as r // groups.
    # For T == 1 the transpose is a no-op and qr is byte-identical to the
    # original decode layout [s, hkv, groups, d].
    qr = (q.reshape(s, t, hkv, groups, d)
           .transpose(0, 2, 1, 3, 4).reshape(s, hkv, tg, d))

    kernel = functools.partial(_attend_kernel, scale=scale, softcap=softcap,
                               page=page, num_page_blocks=m,
                               quantized=quantized, block_q=t, groups=groups)
    # the point of the kernel: the kv BlockSpecs read THROUGH the block
    # table — step (s, h, m) DMAs physical page tables[s, m]; a quantized
    # pool's scale rows ride the SAME index map as two more operands
    table_kv = pl.BlockSpec((1, page, 1, d),
                            lambda s_, h, m_, lens, tabs, band_:
                            (tabs[s_, m_], 0, h, 0))
    table_scale = pl.BlockSpec((1, page, 1),
                               lambda s_, h, m_, lens, tabs, band_:
                               (tabs[s_, m_], 0, h))
    in_specs = [
        pl.BlockSpec((1, 1, tg, d),
                     lambda s_, h, m_, lens, tabs, band_: (s_, h, 0, 0)),
        table_kv,
        table_kv,
    ]
    operands = [qr, k_pages, v_pages]
    if quantized:
        in_specs += [table_scale, table_scale]
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,          # lengths, tables, band
        grid=(s, hkv, m),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, tg, d),
                               lambda s_, h, m_, lens, tabs, band_:
                               (s_, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((tg, 128), jnp.float32),   # running max
            pltpu.VMEM((tg, 128), jnp.float32),   # running sum
            pltpu.VMEM((tg, d), jnp.float32),     # output accumulator
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, hkv, tg, d), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), tables.astype(jnp.int32), band, *operands)
    out = (out.reshape(s, hkv, t, groups, d)
              .transpose(0, 2, 1, 3, 4).reshape(s, t, hq, d))
    return out[:, 0] if squeeze else out


# The block_q == 1 name the decode path shipped under; same kernel, same
# contract — kept so existing callers/tests read naturally.
paged_flash_decode = paged_flash_attend
