"""Attention dispatch: XLA reference path + Pallas flash path.

The reference gets fused attention from the external ``flash-attn`` CUDA wheel
(``05-training-llama-405b/train_llm.py:93``); the TPU-native equivalent is a
Pallas kernel (``ops/flash_attention.py``). This module is the dispatcher: the
XLA einsum path is the numerics reference and the fallback for platforms where
the Mosaic kernel is unavailable; the flash path is the production TPU kernel.

Shapes follow the JAX convention: q [B, S, Hq, D], k/v [B, S, Hkv, D] with
grouped-query attention when Hkv < Hq.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from .flash_attention import check_static_window


def _xla_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool,
    positions: Optional[jnp.ndarray],
    kv_positions: Optional[jnp.ndarray],
    window=None,
    scale: Optional[float] = None,
    logit_softcap: Optional[float] = None,
) -> jnp.ndarray:
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    groups = hq // hkv
    if scale is None:
        scale = 1.0 / jnp.sqrt(jnp.asarray(d, dtype=jnp.float32))

    qg = q.reshape(b, sq, hkv, groups, d)
    # scores in fp32: softmax in bf16 is numerically unacceptable at long seq
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32)
    scores = scores * scale

    if logit_softcap is not None:  # Gemma-2: tanh cap BEFORE the mask
        scores = jnp.tanh(scores / logit_softcap) * logit_softcap

    if causal:
        if positions is None:
            positions = jnp.arange(sq)[None, :]
        if kv_positions is None:
            kv_positions = jnp.arange(sk)[None, :]
        qp = positions[:, None, None, :, None]
        kp = kv_positions[:, None, None, None, :]
        mask = qp >= kp
        if window is not None:  # HF sliding_window band: 0 <= i - j < window
            mask &= (qp - kp) < window
        scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    # tagged so REMAT_POLICIES["attn"] can keep the [B,S,H,D] output: layers
    # downstream then never re-run this attention forward. (This path's own
    # backward still rebuilds scores/probs — the [S,S] recompute is only
    # fully eliminated on the flash path, whose lse residual is also tagged.)
    return checkpoint_name(out.reshape(b, sq, hq, d).astype(q.dtype),
                           "attn_out")


def multihead_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    positions: Optional[jnp.ndarray] = None,
    kv_positions: Optional[jnp.ndarray] = None,
    impl: str = "auto",
    standard_layout: bool = True,
    window=None,
    scale: Optional[float] = None,
    logit_softcap: Optional[float] = None,
) -> jnp.ndarray:
    """Scaled-dot-product attention with GQA.

    impl: "xla" (einsum reference), "flash" (Pallas kernel), or "auto"
    (flash on TPU when causal, tile-aligned, and the caller confirms the
    standard contiguous position layout via ``standard_layout`` — sequence-
    sharded/CP callers pass False and get the mask-aware xla path).
    ``window``: sliding-window attention, on both paths. Static ints bake
    the band into the flash kernel; a TRACED window (per-layer patterns,
    Gemma-2) rides the kernel's dynamic band operand — either way
    out-of-band kv tiles are skipped for an O(S*window) cost.
    ``scale``: score scale override (Gemma-2's query_pre_attn_scalar**-0.5;
    default head_dim**-0.5). ``logit_softcap``: Gemma-2 tanh capping of the
    scaled scores — both paths, with the (1 - tanh^2) backward term on the
    flash path.
    """
    if window is not None and not causal:
        # the band is defined relative to the causal diagonal; the xla path
        # builds its window mask inside the `if causal:` block and would
        # otherwise silently IGNORE the window (the flash kernel raises) —
        # both paths must fail loudly on this combination
        raise ValueError(
            "window (sliding-window attention) requires causal=True — a "
            "non-causal banded mask is not implemented on either path")
    check_static_window(window)
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        aligned = (q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0
                   and q.shape[-1] % 64 == 0)
        impl = ("flash" if (on_tpu and aligned and causal and standard_layout)
                else "xla")
    if impl == "flash":
        from .flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, window=window,
                               scale=scale, logit_softcap=logit_softcap)
    return _xla_attention(q, k, v, causal, positions, kv_positions, window,
                          scale, logit_softcap)
