"""Block-dequant matmul for int8 serve-plane weights.

The serving engine stores its largest params as ``train/precision.py``
``Quantized`` leaves — int8 payload plus per-block fp32 absmax scales over
the TRAILING axis (Dettmers, arXiv:2110.02861) — and this module is the one
place the dequant happens: fused into the matmul's block loop, one
``[K, block]`` fp32 transient at a time, so the full fp32 weight tensor
never materializes in the lowered program (the engine HLO pins assert
this, like the int8 kv-pool aval pins).

Two contraction forms:

- **standard** (``x [.., K] @ w [K, N]``, blocks tile N): the scale of a
  weight column depends on its (row, column-block), so it cannot factor
  out of the contraction over K — each column block is dequantized to a
  ``[K, bs]`` fp32 transient immediately before its ``[M, K] @ [K, bs]``
  partial matmul.
- **transpose** (``x [.., E] @ w[V, E].T``, blocks tile E — the tied
  lm_head): here the block IS a slice of the contraction axis, so the
  scale factors out per block: ``out += (x[:, blk] @ q[:, blk].T) *
  scale[:, b]`` with an ``[M, V]`` fp32 accumulator (that accumulator is
  the logits — activation-sized, not weight-sized).

The XLA reference walks blocks with ``lax.scan`` (compact while-loop HLO,
works for real-model block counts; it is the gather-form CPU-parity
reference, the same role the gather attend plays for the paged flash
kernel). The Pallas kernel maps one grid step per block with the scale
column riding the same BlockSpec index — the int8-KV scale-prefetch
pattern from ``ops/paged_decode.py`` — and runs in interpret mode on CPU
CI. Dispatch mirrors ``paged_decode``: ``impl="auto"`` lowers to Pallas
only on a TPU backend when the tile geometry is eligible.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["quantized_matmul", "quantized_matmul_eligible", "quantized_take"]


def _geometry(q: jax.Array, scale: jax.Array) -> tuple[int, int]:
    """(nblocks, block_size) from the container shapes — the recovery rule
    guaranteed by ``train/precision.py``'s ``block_geometry``."""
    d, nb = q.shape[-1], scale.shape[-1]
    return nb, -(-d // nb)


def _check(w) -> tuple[jax.Array, jax.Array]:
    q, scale = w.q, w.scale
    if getattr(w, "sqrt_domain", False):
        raise ValueError("quantized_matmul expects linear-domain weights; "
                         "sqrt_domain containers are an optimizer-moment "
                         "encoding (train/precision.py)")
    if q.ndim != 2:
        raise ValueError(f"quantized_matmul takes a 2-D weight, got "
                         f"q.shape={q.shape} (slice the layer scan axis "
                         f"before calling)")
    return q, scale


# ---------------------------------------------------------------------------
# XLA reference (the CPU-parity path; also the tp path under GSPMD)
# ---------------------------------------------------------------------------

def _matmul_xla(x2d: jax.Array, q: jax.Array, scale: jax.Array) -> jax.Array:
    """standard form: [M, K] @ dequant([K, N]) -> [M, N] fp32."""
    k, n = q.shape
    nb, bs = _geometry(q, scale)
    pad = nb * bs - n
    if pad:  # int8 zero columns dequantize to 0.0 — harmless, sliced off
        q = jnp.pad(q, ((0, 0), (0, pad)))
    qb = q.reshape(k, nb, bs).transpose(1, 0, 2)          # [nb, K, bs] int8
    sb = scale.T[:, :, None]                              # [nb, K, 1]  fp32
    xf = x2d.astype(jnp.float32)

    def step(_, inp):
        qblk, sblk = inp
        wblk = qblk.astype(jnp.float32) * sblk            # [K, bs] transient
        return None, xf @ wblk

    _, ys = jax.lax.scan(step, None, (qb, sb))            # [nb, M, bs]
    out = ys.transpose(1, 0, 2).reshape(x2d.shape[0], nb * bs)
    return out[:, :n] if pad else out


def _matmul_t_xla(x2d: jax.Array, q: jax.Array, scale: jax.Array) -> jax.Array:
    """transpose form: [M, E] @ dequant([V, E]).T -> [M, V] fp32."""
    v, e = q.shape
    nb, bs = _geometry(q, scale)
    pad = nb * bs - e
    xf = x2d.astype(jnp.float32)
    if pad:  # zero-padded activations meet zero-padded weights: no-op terms
        q = jnp.pad(q, ((0, 0), (0, pad)))
        xf = jnp.pad(xf, ((0, 0), (0, pad)))
    qb = q.reshape(v, nb, bs).transpose(1, 0, 2)          # [nb, V, bs] int8
    xb = xf.reshape(-1, nb, bs).transpose(1, 0, 2)        # [nb, M, bs] fp32
    sb = scale.T                                          # [nb, V]    fp32

    def step(acc, inp):
        qblk, xblk, sblk = inp
        # scale is a function of the contracted block here, so it factors
        # out of the per-block partial product
        return acc + (xblk @ qblk.astype(jnp.float32).T) * sblk[None, :], None

    acc0 = jnp.zeros((x2d.shape[0], v), jnp.float32)
    out, _ = jax.lax.scan(step, acc0, (qb, xb, sb))
    return out


# ---------------------------------------------------------------------------
# Pallas kernel (standard form): one grid step per weight block, the scale
# column prefetched by the same BlockSpec index as its int8 payload block
# ---------------------------------------------------------------------------

def _dequant_matmul_kernel(x_ref, q_ref, s_ref, o_ref):
    wblk = q_ref[...].astype(jnp.float32) * s_ref[...]    # [K, bs] in VMEM
    o_ref[...] = jnp.dot(x_ref[...].astype(jnp.float32), wblk,
                         preferred_element_type=jnp.float32)


def _matmul_pallas(x2d: jax.Array, q: jax.Array, scale: jax.Array,
                   interpret: bool) -> jax.Array:
    m, k = x2d.shape
    n = q.shape[-1]
    nb, bs = _geometry(q, scale)
    return pl.pallas_call(
        _dequant_matmul_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((m, k), lambda b: (0, 0)),       # whole activations
            pl.BlockSpec((k, bs), lambda b: (0, b)),      # int8 block b
            pl.BlockSpec((k, 1), lambda b: (0, b)),       # its scale column
        ],
        out_specs=pl.BlockSpec((m, bs), lambda b: (0, b)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=interpret,
    )(x2d, q, scale)


def quantized_matmul_eligible(w, *, transpose: bool = False) -> bool:
    """True when the Pallas kernel's tile geometry fits this container:
    no padded tail block, lane-dim blocks (bs % 128), and an int8-tileable
    contraction dim (K % 32 — the int8 min tile is (32, 128) per the TPU
    guide). The transpose form has no kernel yet — XLA carries it."""
    try:
        q, scale = _check(w)
    except ValueError:
        return False
    if transpose:
        return False
    k, n = q.shape
    nb, bs = _geometry(q, scale)
    return nb * bs == n and bs % 128 == 0 and k % 32 == 0


def quantized_take(w, ids: jax.Array) -> jax.Array:
    """Embedding lookup against a quantized table: gather int8 rows and
    their scale rows, dequantize only the gathered tokens (fp32 out)."""
    q, scale = _check(w)
    nb, bs = _geometry(q, scale)
    rows = jnp.take(q, ids, axis=0).astype(jnp.float32)       # [.., d]
    srows = jnp.take(scale, ids, axis=0)                      # [.., nb]
    srows = jnp.repeat(srows, bs, axis=-1)[..., :q.shape[-1]]
    return rows * srows


def quantized_matmul(x: jax.Array, w, *, transpose: bool = False,
                     impl: str = "auto",
                     interpret: Optional[bool] = None) -> jax.Array:
    """``x @ dequant(w)`` (or ``x @ dequant(w).T`` with ``transpose``),
    block-dequantizing inside the contraction loop. Returns fp32 (callers
    cast to compute dtype; the lm_head keeps fp32 logits).

    ``w`` is any Quantized-like container with ``.q`` (int8, blocks on the
    trailing axis) and ``.scale`` (fp32) — duck-typed so the model family
    modules need not import ``train.precision`` (train imports models).
    """
    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(f"impl must be auto|xla|pallas, got {impl!r}")
    q, scale = _check(w)
    lead, kdim = x.shape[:-1], x.shape[-1]
    contract = q.shape[-1] if transpose else q.shape[0]
    if kdim != contract:
        raise ValueError(f"contraction mismatch: x[.., {kdim}] vs "
                         f"quantized weight {q.shape}"
                         f"{'.T' if transpose else ''}")
    x2d = x.reshape(-1, kdim)
    if impl == "auto":
        use_pallas = (jax.default_backend() == "tpu"
                      and quantized_matmul_eligible(w, transpose=transpose))
    else:
        use_pallas = impl == "pallas"
    if use_pallas:
        if transpose:
            raise NotImplementedError("pallas quantized_matmul has no "
                                      "transpose (tied lm_head) form; use "
                                      "impl='xla'")
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        out = _matmul_pallas(x2d, q, scale, interpret)
    elif transpose:
        out = _matmul_t_xla(x2d, q, scale)
    else:
        out = _matmul_xla(x2d, q, scale)
    return out.reshape(*lead, out.shape[-1])
