"""Ulysses (all-to-all) context parallelism: the ring's head-sharded dual.

The reference name-checks context parallelism but never implements it
(``06-tensor-parallel/README.md:7``); chapter 08 builds the ring. This
module adds the other standard CP scheme (DeepSpeed-Ulysses, Jacobs et al.
2023): outside attention the sequence dim is sharded over ``cp`` exactly as
for the ring, but *during* attention the layout flips — heads shard over
cp (x tp) and every device sees the FULL sequence for its head slice. The
layout flip is an all-to-all on entry and exit, which on TPU is cheap
ICI traffic that XLA/GSPMD inserts from the sharding change alone.

Trade-offs vs the ring (``--context-impl`` picks per run):

- Ulysses: 2 all-to-alls total, plain flash kernel per device (no per-hop
  merge math), but needs ``num_kv_heads % (cp*tp) == 0`` — GQA models cap
  cp at the kv-head count and it cannot scale past heads.
- Ring: cp-1 neighbor ppermutes overlapped with compute, works for any
  head count and arbitrarily long sequences, but pays the zigzag
  relayout + online-softmax merges.

TPU-native formulation — there is no hand-written all-to-all anywhere:

- flash path: ``make_sharded_flash_attention`` with the head dim manual
  over ``(tp, cp)``. The wrapper's shard_map in_specs declare heads
  cp-sharded and seq unsharded; since the caller's activations are
  seq-sharded, XLA materializes the all-to-all at the shard_map boundary.
- xla path (and 'auto' off-TPU): two ``with_sharding_constraint`` calls
  around the einsum reference implementation — the pure-GSPMD version of
  the same thing (the einsum path needs no manual axes at all).

Sharding-semantics note: under GSPMD everything stays *global* — positions
are the default arange, the causal mask is exact, and no zigzag balancing
is needed (every device owns full rows of the attention matrix for its
heads, so causal work is balanced by construction).
"""
from __future__ import annotations

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .flash_attention import (make_sharded_flash_attention,
                              resolve_attention_manual_axes)


def make_ulysses_attention(mesh: Mesh, *, axis_name: str = "cp",
                           data_axes=("dp", "fsdp", "ep"),
                           head_axis="tp", causal: bool = True,
                           window=None, impl: str = "auto",
                           scale=None, logit_softcap=None):
    """Attention callable (``make_ring_attention`` contract) running the
    Ulysses layout flip over ``axis_name``. ``impl`` as in
    ``multihead_attention``: 'flash' forces the manual-axes kernel wrapper,
    'xla' the constraint-based einsum path, 'auto' picks flash on TPU.

    ``window``/``scale``/``logit_softcap`` (Gemma-2 per-layer windows,
    ``query_pre_attn_scalar``, tanh softcapping) pass straight through:
    every device sees the FULL sequence for its head slice, so the band
    mask and per-score cap stay exact without any cross-chunk math. A
    per-call ``window`` (traced per-layer schedules) overrides the factory
    default on both paths."""
    import jax

    from .flash_attention import _UNSET

    head_axes = (head_axis,) if isinstance(head_axis, str) else tuple(head_axis or ())
    # resolve_attention_manual_axes (called by both paths below) drops
    # size-1 axes, so the raw concatenation is safe to pass through
    ulysses_heads = (*head_axes, axis_name)

    auto = impl == "auto"
    if auto:
        impl = "flash" if jax.default_backend() == "tpu" else "xla"

    # the constraint-based xla path is built unconditionally: under 'auto'
    # it is also the fallback when a GQA model's kv heads don't divide
    # cp*tp — consistent with 'auto' semantics elsewhere (the sharded-flash
    # factory degrades instead of raising); an explicit impl='flash' still
    # fails loud on ineligible shapes
    batch_axes, heads_t, tp, _, b_spec, _ = resolve_attention_manual_axes(
        mesh, data_axes, ulysses_heads)
    inner = NamedSharding(mesh, P(b_spec, None, heads_t, None))
    outer = NamedSharding(mesh, P(b_spec, axis_name,
                                  tuple(a for a in (heads_t or ())
                                        if a != axis_name) or None, None))

    window_default = window

    def attention(q, k, v, standard_layout: bool = True, window=_UNSET,
                  **kwargs):
        if not standard_layout:
            raise ValueError(
                "ulysses attention assumes the standard contiguous position "
                "layout; don't pass explicit positions under context "
                "parallelism")
        from .attention import multihead_attention

        wcall = window_default if window is _UNSET else window
        qc, kc, vc = (jax.lax.with_sharding_constraint(x, inner)
                      for x in (q, k, v))
        # window passes straight through: every device sees the FULL
        # sequence for its head slice, so the band mask stays exact (a
        # traced per-layer window just rides the xla mask comparisons)
        out = multihead_attention(qc, kc, vc, causal=causal, window=wcall,
                                  scale=scale, logit_softcap=logit_softcap,
                                  impl="xla")
        # flip back to the sequence sharding the surrounding blocks carry
        return jax.lax.with_sharding_constraint(out, outer)

    attention.accepts_window = True

    if impl == "flash":
        flash = make_sharded_flash_attention(
            mesh, batch_axes=data_axes, head_axis=ulysses_heads,
            causal=causal, window=window, forced=not auto,
            fallback=attention if auto else None,
            scale=scale, logit_softcap=logit_softcap)
        assert flash is not None  # cp > 1 guarantees a manual axis
        return flash

    return attention
