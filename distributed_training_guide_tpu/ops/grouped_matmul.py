"""Grouped (ragged) matmul: the expert-compute primitive of dropless MoE.

``grouped_matmul(lhs [M, K], rhs [G, K, N], group_sizes [G]) -> [M, N]``
multiplies row-block ``g`` of ``lhs`` (rows ``offs[g]:offs[g+1]`` where
``offs = cumsum(group_sizes)``) by ``rhs[g]``. Rows beyond
``sum(group_sizes)`` produce zeros (and receive zero gradient) — callers
exploit that contract for expert-parallel local slices, where a worst-case
static buffer carries a garbage tail.

Three implementations behind one dispatch:

- ``pallas``: a Mosaic kernel in the MegaBlocks spirit (Gale et al.,
  arXiv:2211.15841): the sorted token axis is tiled and the grid iterates a
  precomputed (group, row-tile) *work list* built from the per-expert
  offset/size metadata, so compute visits only tiles a group actually
  intersects — no ``[E, capacity]`` padding FLOPs, no per-group dense pass.
  Differentiable via custom_vjp (d_lhs is another grouped matmul against
  ``rhs`` transposed; d_rhs is the transposed grouped matmul ``tgmm``).
- ``scan``: a ``lax.scan`` over groups (mask the sorted rows to the group's
  contiguous range, dense matmul, accumulate) — O(G) more FLOPs than ideal
  but O(M*(K+N)) *memory*, pure jnp, differentiable. The off-TPU default:
  correctness everywhere without the dense expansion below.
- ``ragged``: ``jax.lax.ragged_dot`` (XLA's native ragged contraction,
  differentiable as-is). NOTE: on backends without a native lowering
  (CPU today) it decomposes to a dense ``[G, M, K]`` broadcast + batched
  dot — O(G*M) transient memory, the very padding blowup dropless dispatch
  exists to remove — which is why it is not the auto fallback.
- ``einsum``: segment-one-hot masked einsum, O(G x) padding FLOPs and
  contraction-order-dependent transients — the numerics cross-check in
  tests.

The Pallas kernels keep the whole K (contraction) dim resident per tile —
fine for transformer hidden/FFN widths (K * block * 4 B must fit VMEM); a
K-tiled variant is a follow-up if a model outgrows that.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_HAS_RAGGED_DOT = hasattr(jax.lax, "ragged_dot")


def _resolve_impl(impl: str) -> str:
    if impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "scan"
    if impl not in ("pallas", "scan", "ragged", "einsum"):
        raise ValueError(f"unknown grouped_matmul impl {impl!r}; use "
                         f"'auto', 'pallas', 'scan', 'ragged', or 'einsum'")
    if impl == "ragged" and not _HAS_RAGGED_DOT:
        raise ValueError("impl='ragged' needs jax.lax.ragged_dot, which this "
                         "jax build lacks; use 'scan' (or 'auto')")
    return impl


# ---------------------------------------------------------------------------
# XLA fallbacks (autodiff works through both as-is)
# ---------------------------------------------------------------------------

def _gmm_scan(lhs, rhs, group_sizes, out_dtype):
    """scan over groups: mask the sorted rows to the group's contiguous
    range, one dense matmul each, accumulate. O(M*(K+N)) transients — the
    memory-safe XLA formulation (decode's no_drop path compiles through
    this off-TPU, where ``ragged_dot`` would re-materialize the [G, M, K]
    dense expansion)."""
    m = lhs.shape[0]
    ends = jnp.cumsum(group_sizes)
    starts = ends - group_sizes
    rows = jnp.arange(m, dtype=group_sizes.dtype)

    def body(acc, inp):
        start, end, w = inp
        mask = (rows >= start) & (rows < end)
        masked = jnp.where(mask[:, None], lhs, 0)
        return acc + jnp.dot(masked, w,
                             preferred_element_type=jnp.float32), None

    acc0 = jnp.zeros((m, rhs.shape[2]), jnp.float32)
    out, _ = jax.lax.scan(body, acc0, (starts, ends, rhs))
    return out.astype(out_dtype)


def _gmm_einsum(lhs, rhs, group_sizes, out_dtype):
    """Segment-one-hot masked einsum. O(G) more FLOPs than ideal — the
    correctness fallback, not the fast path."""
    m, g = lhs.shape[0], rhs.shape[0]
    ends = jnp.cumsum(group_sizes)
    # row r belongs to group searchsorted(ends, r, 'right'); tail rows (r >=
    # ends[-1]) resolve to G, whose one_hot row is all-zero -> zero output
    seg = jnp.searchsorted(ends, jnp.arange(m, dtype=group_sizes.dtype),
                           side="right")
    onehot = jax.nn.one_hot(seg, g, dtype=lhs.dtype)
    return jnp.einsum("mk,mg,gkn->mn", lhs, onehot, rhs,
                      preferred_element_type=jnp.float32).astype(out_dtype)


def _tgmm_einsum(lhs, dy, group_sizes, g, out_dtype):
    """Transposed grouped matmul: d_rhs[g] = lhs_g^T @ dy_g -> [G, K, N]."""
    m = lhs.shape[0]
    ends = jnp.cumsum(group_sizes)
    seg = jnp.searchsorted(ends, jnp.arange(m, dtype=group_sizes.dtype),
                           side="right")
    onehot = jax.nn.one_hot(seg, g, dtype=lhs.dtype)
    return jnp.einsum("mg,mk,mn->gkn", onehot, lhs, dy,
                      preferred_element_type=jnp.float32).astype(out_dtype)


# ---------------------------------------------------------------------------
# Pallas kernel (MegaBlocks-style work list over the sorted token axis)
# ---------------------------------------------------------------------------

def _work_list(group_sizes, m, bm, nw):
    """Static-size (group, row-tile) work list + metadata scalars.

    Groups are contiguous row ranges of the sorted buffer, so the number of
    (group, tile) intersections is at most m_tiles + G (each group spans
    ceil(size/bm) tiles plus at most one boundary tile) — ``nw`` is that
    bound. Padding entries repeat the last real pair (so they trigger no
    accumulator init/flush edges) and are masked off via ``n_valid``.
    Enumeration is group-major; because groups tile a contiguous axis, the
    emitted row-tile sequence is non-decreasing, which is what lets the
    kernels treat "previous work item had a different tile/group" as the
    accumulator edge."""
    g = group_sizes.shape[0]
    sizes = group_sizes.astype(jnp.int32)
    offs = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(sizes)])
    first_tile = offs[:-1] // bm
    last_tile = jnp.maximum(offs[1:] - 1, offs[:-1]) // bm
    spans = jnp.where(sizes > 0, last_tile - first_tile + 1, 0)
    base = jnp.cumsum(spans)                       # inclusive
    n_valid = base[-1]
    w = jnp.arange(nw, dtype=jnp.int32)
    wg_raw = jnp.searchsorted(base, w, side="right").astype(jnp.int32)
    wg_c = jnp.minimum(wg_raw, g - 1)
    start = base[wg_c] - spans[wg_c]               # exclusive base of group
    wm_raw = first_tile[wg_c] + (w - start)
    valid = w < n_valid
    # padding repeats the last valid (group, tile) pair; all-empty input
    # degenerates to pair (0, 0), whose contribution the valid mask kills
    last = jnp.minimum(jnp.maximum(n_valid - 1, 0), nw - 1)
    wg = jnp.where(valid, wg_c, wg_c[last])
    wm = jnp.where(valid, wm_raw, wm_raw[last])
    return offs, wg, wm, jnp.asarray(n_valid, jnp.int32)[None]


def _gmm_kernel(offs_ref, wg_ref, wm_ref, nvalid_ref, lhs_ref, rhs_ref,
                out_ref, acc_ref, *, bm, nw):
    w = pl.program_id(1)
    g = wg_ref[w]
    mt = wm_ref[w]
    is_first = jnp.logical_or(w == 0, wm_ref[jnp.maximum(w - 1, 0)] != mt)

    @pl.when(is_first)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    rows = mt * bm + jax.lax.broadcasted_iota(jnp.int32, (bm,), 0)
    member = ((rows >= offs_ref[g]) & (rows < offs_ref[g + 1])
              & (w < nvalid_ref[0]))
    x = jnp.where(member[:, None], lhs_ref[...], 0)
    acc_ref[...] += jnp.dot(x, rhs_ref[0],
                            preferred_element_type=jnp.float32)

    is_last = jnp.logical_or(w == nw - 1,
                             wm_ref[jnp.minimum(w + 1, nw - 1)] != mt)

    @pl.when(is_last)
    def _():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def _tgmm_kernel(offs_ref, wg_ref, wm_ref, nvalid_ref, lhs_ref, dy_ref,
                 out_ref, acc_ref, *, bm, nw):
    w = pl.program_id(1)
    g = wg_ref[w]
    mt = wm_ref[w]
    is_first = jnp.logical_or(w == 0, wg_ref[jnp.maximum(w - 1, 0)] != g)

    @pl.when(is_first)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    rows = mt * bm + jax.lax.broadcasted_iota(jnp.int32, (bm,), 0)
    member = ((rows >= offs_ref[g]) & (rows < offs_ref[g + 1])
              & (w < nvalid_ref[0]))
    x = jnp.where(member[:, None], lhs_ref[...], 0)
    acc_ref[...] += jnp.dot(x.T, dy_ref[...],
                            preferred_element_type=jnp.float32)

    is_last = jnp.logical_or(w == nw - 1,
                             wg_ref[jnp.minimum(w + 1, nw - 1)] != g)

    @pl.when(is_last)
    def _():
        out_ref[0] = acc_ref[...].astype(out_ref.dtype)


def _pallas_gmm_raw(lhs, rhs, group_sizes, out_dtype, bm, bn, interpret):
    from jax.experimental.pallas import tpu as pltpu

    m, k = lhs.shape
    g, _, n = rhs.shape
    m_tiles = pl.cdiv(m, bm)
    n_tiles = pl.cdiv(n, bn)
    nw = m_tiles + g
    offs, wg, wm, n_valid = _work_list(group_sizes, m, bm, nw)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(n_tiles, nw),
        in_specs=[
            pl.BlockSpec((bm, k), lambda ni, w, offs, wg, wm, nv: (wm[w], 0)),
            pl.BlockSpec((1, k, bn),
                         lambda ni, w, offs, wg, wm, nv: (wg[w], 0, ni)),
        ],
        out_specs=pl.BlockSpec((bm, bn),
                               lambda ni, w, offs, wg, wm, nv: (wm[w], ni)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_gmm_kernel, bm=bm, nw=nw),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        interpret=interpret,
    )(offs, wg, wm, n_valid, lhs, rhs)
    # row-tiles past the last group are never visited (their memory is
    # whatever the buffer held); the contract says zeros
    total = jnp.sum(group_sizes).astype(jnp.int32)
    return jnp.where(jnp.arange(m, dtype=jnp.int32)[:, None] < total, out, 0)


def _pallas_tgmm_raw(lhs, dy, group_sizes, g, out_dtype, bm, bn, interpret):
    """d_rhs [G, K, N] = per-group lhs_g^T @ dy_g (the 'tgmm')."""
    from jax.experimental.pallas import tpu as pltpu

    m, k = lhs.shape
    n = dy.shape[1]
    m_tiles = pl.cdiv(m, bm)
    n_tiles = pl.cdiv(n, bn)
    nw = m_tiles + g
    offs, wg, wm, n_valid = _work_list(group_sizes, m, bm, nw)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(n_tiles, nw),
        in_specs=[
            pl.BlockSpec((bm, k), lambda ni, w, offs, wg, wm, nv: (wm[w], 0)),
            pl.BlockSpec((bm, bn), lambda ni, w, offs, wg, wm, nv: (wm[w], ni)),
        ],
        out_specs=pl.BlockSpec((1, k, bn),
                               lambda ni, w, offs, wg, wm, nv: (wg[w], 0, ni)),
        scratch_shapes=[pltpu.VMEM((k, bn), jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_tgmm_kernel, bm=bm, nw=nw),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((g, k, n), out_dtype),
        interpret=interpret,
    )(offs, wg, wm, n_valid, lhs, dy)
    # empty groups own no work item, so their out block is never written
    return jnp.where((group_sizes > 0)[:, None, None], out, 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _pallas_gmm(lhs, rhs, group_sizes, out_dtype, bm, bn, interpret):
    return _pallas_gmm_raw(lhs, rhs, group_sizes, out_dtype, bm, bn, interpret)


def _pallas_gmm_fwd(lhs, rhs, group_sizes, out_dtype, bm, bn, interpret):
    out = _pallas_gmm_raw(lhs, rhs, group_sizes, out_dtype, bm, bn, interpret)
    return out, (lhs, rhs, group_sizes)


def _pallas_gmm_bwd(out_dtype, bm, bn, interpret, res, dy):
    lhs, rhs, group_sizes = res
    dy = dy.astype(jnp.float32)
    # d_lhs: the same grouped matmul against rhs^T — rows outside every
    # group get zero gradient (matching their zero primal output)
    dlhs = _pallas_gmm_raw(dy, rhs.astype(jnp.float32).transpose(0, 2, 1),
                           group_sizes, lhs.dtype, bm, bn, interpret)
    drhs = _pallas_tgmm_raw(lhs.astype(jnp.float32), dy, group_sizes,
                            rhs.shape[0], rhs.dtype, bm, bn, interpret)
    return dlhs, drhs, None


_pallas_gmm.defvjp(_pallas_gmm_fwd, _pallas_gmm_bwd)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def grouped_matmul(
    lhs: jnp.ndarray,          # [M, K] rows sorted by group
    rhs: jnp.ndarray,          # [G, K, N] one matrix per group
    group_sizes: jnp.ndarray,  # [G] int, sum <= M
    *,
    impl: str = "auto",
    block_rows: int = 512,
    block_cols: int = 512,
    interpret: Optional[bool] = None,
    preferred_element_type=None,
) -> jnp.ndarray:
    """Ragged grouped GEMM over a group-sorted row buffer -> [M, N].

    ``impl``: "pallas" (Mosaic work-list kernel; ``interpret=True`` runs it
    off-TPU for tests), "scan" (masked group-scan, O(M) memory), "ragged"
    (``jax.lax.ragged_dot``), "einsum" (masked one-hot), or "auto" (pallas
    on TPU, else scan). Rows at index >= ``sum(group_sizes)`` yield zeros
    and propagate zero gradient.
    """
    if lhs.ndim != 2 or rhs.ndim != 3 or group_sizes.ndim != 1:
        raise ValueError(f"grouped_matmul expects lhs [M,K], rhs [G,K,N], "
                         f"group_sizes [G]; got {lhs.shape}, {rhs.shape}, "
                         f"{group_sizes.shape}")
    if lhs.shape[1] != rhs.shape[1] or rhs.shape[0] != group_sizes.shape[0]:
        raise ValueError(f"grouped_matmul shape mismatch: lhs {lhs.shape}, "
                         f"rhs {rhs.shape}, group_sizes {group_sizes.shape}")
    impl = _resolve_impl(impl)
    out_dtype = preferred_element_type or jnp.promote_types(lhs.dtype,
                                                            rhs.dtype)
    group_sizes = group_sizes.astype(jnp.int32)
    if impl == "scan":
        return _gmm_scan(lhs, rhs, group_sizes, out_dtype)
    if impl == "ragged":
        return jax.lax.ragged_dot(
            lhs, rhs, group_sizes,
            preferred_element_type=preferred_element_type)
    if impl == "einsum":
        return _gmm_einsum(lhs, rhs, group_sizes, out_dtype)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bm = min(block_rows, lhs.shape[0])
    bn = min(block_cols, rhs.shape[2])
    return _pallas_gmm(lhs, rhs, group_sizes, jnp.dtype(out_dtype), bm, bn,
                       interpret)
