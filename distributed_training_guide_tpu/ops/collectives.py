"""Manual-collective helpers shared by shard_map regions.

All helpers carry the same environment guard: jaxlib's non-TPU runtimes
abort on sub-fp32 collectives (observed on this container's CPU backend as
a hlo_instruction.cc CHECK "Invalid binary instruction opcode copy" on a
bf16 all-reduce), which would otherwise kill the virtual-mesh test suite.
``sub_fp32_guard`` factors that upcast-around-the-collective into one
decorator: off-TPU, bf16/fp16 operands are widened to fp32 for the
collective and narrowed back; on TPU the native low-precision collective
runs (half the ICI bytes). The guard is exact for data-movement collectives
(all-gather / ppermute) and changes only the reduction arithmetic width for
psum / psum_scatter — fp32 accumulation off-TPU, never worse than native.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def sub_fp32_guard(collective):
    """Decorate a collective ``f(x, axis, **kw)`` with the non-TPU sub-fp32
    upcast: run the wrapped op in fp32 and cast back when ``x`` is bf16/fp16
    and the backend is not TPU."""

    @functools.wraps(collective)
    def guarded(x: jnp.ndarray, axis, **kw):
        if (jax.default_backend() != "tpu"
                and x.dtype in (jnp.bfloat16, jnp.float16)):
            return collective(x.astype(jnp.float32), axis, **kw).astype(x.dtype)
        return collective(x, axis, **kw)

    return guarded


@sub_fp32_guard
def psum(x: jnp.ndarray, axis) -> jnp.ndarray:
    return jax.lax.psum(x, axis)


@sub_fp32_guard
def psum_scatter(x: jnp.ndarray, axis, *, scatter_dimension: int = 0) -> jnp.ndarray:
    """``jax.lax.psum_scatter(tiled=True)`` under the shared guard."""
    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_dimension,
                                tiled=True)


@sub_fp32_guard
def all_gather(x: jnp.ndarray, axis, *, dim: int = 0) -> jnp.ndarray:
    """Tiled all-gather along ``dim`` (the latency-hiding schedules'
    parameter prefetch primitive, ops/overlap.py)."""
    return jax.lax.all_gather(x, axis, axis=dim, tiled=True)


@sub_fp32_guard
def ppermute(x: jnp.ndarray, axis, *, perm) -> jnp.ndarray:
    """``jax.lax.ppermute`` under the shared guard (the double-buffered EP
    ring's hop primitive, models/moe.py)."""
    return jax.lax.ppermute(x, axis, perm=perm)
