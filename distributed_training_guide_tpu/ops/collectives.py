"""Manual-collective helpers shared by shard_map regions.

``psum``: like ``jax.lax.psum`` but upcasting sub-fp32 floats to fp32 on
non-TPU backends — jaxlib 0.9's CPU runtime aborts on a bf16 all-reduce
(hlo_instruction.cc CHECK "Invalid binary instruction opcode copy"), which
would otherwise kill the virtual-mesh test suite. On TPU the native bf16
all-reduce is used (half the ICI bytes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def psum(x: jnp.ndarray, axis) -> jnp.ndarray:
    if jax.default_backend() != "tpu" and x.dtype in (jnp.bfloat16, jnp.float16):
        return jax.lax.psum(x.astype(jnp.float32), axis).astype(x.dtype)
    return jax.lax.psum(x, axis)


def psum_scatter(x: jnp.ndarray, axis, *, scatter_dimension: int = 0) -> jnp.ndarray:
    """``jax.lax.psum_scatter(tiled=True)`` with the same sub-fp32 upcast
    guard as ``psum`` (the reduction arithmetic hits the identical CPU
    runtime abort); on TPU the native low-precision reduce-scatter runs."""
    if jax.default_backend() != "tpu" and x.dtype in (jnp.bfloat16, jnp.float16):
        return jax.lax.psum_scatter(
            x.astype(jnp.float32), axis, scatter_dimension=scatter_dimension,
            tiled=True).astype(x.dtype)
    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_dimension,
                                tiled=True)
