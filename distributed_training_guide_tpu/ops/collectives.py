"""Manual-collective helpers shared by shard_map regions.

``psum``: like ``jax.lax.psum`` but upcasting sub-fp32 floats to fp32 on
non-TPU backends — jaxlib 0.9's CPU runtime aborts on a bf16 all-reduce
(hlo_instruction.cc CHECK "Invalid binary instruction opcode copy"), which
would otherwise kill the virtual-mesh test suite. On TPU the native bf16
all-reduce is used (half the ICI bytes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def psum(x: jnp.ndarray, axis) -> jnp.ndarray:
    if jax.default_backend() != "tpu" and x.dtype in (jnp.bfloat16, jnp.float16):
        return jax.lax.psum(x.astype(jnp.float32), axis).astype(x.dtype)
    return jax.lax.psum(x, axis)
