"""Distributed batch loader.

Parity with the reference's ``DataLoader + DistributedSampler``
(``02-distributed-data-parallel/train_llm.py:76-84``):

- deterministic per-epoch shuffle keyed by (seed, epoch) — ``set_epoch``
  (``02:137``);
- ``drop_last`` partitioning into global batches;
- each process only materializes the shards its local devices own, assembled
  into one global ``jax.Array`` via ``make_array_from_callback`` (the JAX
  analogue of per-rank sampler index partitioning — under a (dp, tp) mesh the
  tp group automatically reads identical data because the batch dim is only
  sharded over the data axes, which the reference has to hand-arrange with a
  mesh-aware sampler, ``06-tensor-parallel/train_llm.py:141-147``);
- epoch fast-forward for resume (``01:133-135``) via ``start_step``.

Double-buffered host->device prefetch hides dispatch latency (reference C26,
``related-topics/optimizing-data-loading``).
"""
from __future__ import annotations

from typing import Iterator, Optional

import jax
import numpy as np


class ShardedBatchLoader:
    def __init__(
        self,
        dataset: np.ndarray,          # [num_seqs, seq_len] int32
        global_batch_size: int,
        sharding,                      # NamedSharding for [B, S] (or [A, B, S])
        *,
        grad_accum: int = 1,
        seed: int = 0,
        shuffle: bool = True,
        prefetch: int = 2,
        native: bool = False,
    ):
        if global_batch_size % max(grad_accum, 1) != 0:
            raise ValueError("global_batch_size must be divisible by grad_accum")
        self.dataset = dataset
        self.global_batch_size = global_batch_size
        self.sharding = sharding
        self.grad_accum = grad_accum
        self.seed = seed
        self.shuffle = shuffle
        self.prefetch = prefetch
        self.epoch = 0
        self._native = None
        self._native_path = None
        if native:
            if not shuffle:
                import logging

                logging.getLogger(__name__).warning(
                    "native loader has no unshuffled mode; using python assembly")
            else:
                self._native = self._make_native()

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return len(self.dataset) // self.global_batch_size

    def _epoch_order(self) -> np.ndarray:
        order = np.arange(len(self.dataset))
        if self.shuffle:
            np.random.RandomState(self.seed + 1000003 * self.epoch).shuffle(order)
        return order

    def _leading_shape(self) -> tuple:
        if self.grad_accum > 1:
            return (self.grad_accum, self.global_batch_size // self.grad_accum)
        return (self.global_batch_size,)

    def _make_global_array(self, np_batch: np.ndarray) -> jax.Array:
        """Global array from an already-assembled host batch (the native
        path: the C++ loader hands back the full batch by contract)."""
        np_batch = np_batch.reshape(self._leading_shape() + np_batch.shape[-1:])
        return jax.make_array_from_callback(
            np_batch.shape, self.sharding, lambda idx: np_batch[idx])

    def _assemble_batch(self, idx: np.ndarray) -> jax.Array:
        """Global array materializing ONLY the rows this process's devices
        own (reference C26, ``related-topics/optimizing-data-loading/
        README.md:24-102``): the callback fancy-indexes the — possibly
        disk-backed — dataset per addressable shard, so per-host RAM is the
        local share of each batch, never the global batch (and never the
        corpus, when the dataset is a memmap)."""
        # sorted for memmap read locality only: which sequences form the
        # batch is shuffled (the caller's epoch order); their within-batch
        # order is deliberately left ascending — example->device-slot
        # assignment carries no semantics (grads sum over the batch)
        idx_nd = np.sort(idx).reshape(self._leading_shape())
        seq = self.dataset.shape[1]

        def fetch(shard_index):
            sel = idx_nd[shard_index[:-1]]
            rows = np.asarray(self.dataset[sel.ravel()], dtype=np.int32)
            return rows.reshape(sel.shape + (seq,))[..., shard_index[-1]]

        return jax.make_array_from_callback(
            idx_nd.shape + (seq,), self.sharding, fetch)

    def _native_compatible_backing(self):
        """Path of the dataset's own backing file when the C++ loader can
        mmap it directly (raw int32 token-file layout covering the whole
        file) — the zero-copy path; None forces a temp-file copy."""
        import os

        ds = self.dataset
        filename = getattr(ds, "filename", None)
        if (isinstance(ds, np.memmap) and filename
                and ds.dtype == np.int32 and ds.flags["C_CONTIGUOUS"]
                and getattr(ds, "offset", 1) == 0
                and ds.size * 4 == os.path.getsize(filename)):
            return filename
        return None

    def _make_native(self):
        """Back batch assembly with the C++ loader (csrc/token_loader.cpp):
        mmap + worker threads + bounded prefetch, no GIL. A memmap dataset in
        the raw token-file layout (``--mmap-data``) is mmap'd IN PLACE — no
        second on-disk copy of the corpus (reference C26)."""
        import tempfile

        from .native_loader import NativeTokenLoader, native_available, write_token_file

        if not native_available():
            import logging

            logging.getLogger(__name__).warning(
                "native loader unavailable (no g++); using python assembly")
            return None
        path = self._native_compatible_backing()
        if path is None:
            tmp = tempfile.NamedTemporaryFile(suffix=".tokens.bin", delete=False)
            tmp.close()  # the C++ side reopens by path; don't leak the fd
            self._native_path = tmp.name   # ours: unlinked on close()
            write_token_file(self.dataset, tmp.name)
            path = tmp.name
        return NativeTokenLoader(path, seq_len=self.dataset.shape[1],
                                 batch=self.global_batch_size, seed=self.seed,
                                 prefetch=max(self.prefetch, 2))

    def close(self) -> None:
        if self._native is not None:
            self._native.close()
            self._native = None
        if self._native_path is not None:
            import os

            try:
                os.unlink(self._native_path)
            except OSError:
                pass
            self._native_path = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def epoch_batches(self, start_step: int = 0) -> Iterator[dict]:
        """Yields {'input_ids', 'labels'} global jax.Arrays; skips the first
        ``start_step`` batches while preserving data order (resume)."""
        if self._native is not None:
            # same pending-queue H2D overlap as the python path, on top of the
            # C++ assembly prefetch
            pending: list[dict] = []
            for np_batch in self._native.epoch_batches(self.epoch, start_step):
                ids = self._make_global_array(np_batch)
                pending.append({"input_ids": ids, "labels": ids})
                if len(pending) > self.prefetch:
                    yield pending.pop(0)
            yield from pending
            return
        order = self._epoch_order()
        n = len(self)
        pending: list[dict] = []
        for step in range(start_step, n):
            idx = order[step * self.global_batch_size:(step + 1) * self.global_batch_size]
            ids = self._assemble_batch(idx)
            pending.append({"input_ids": ids, "labels": ids})
            if len(pending) > self.prefetch:
                yield pending.pop(0)
        yield from pending
