"""Distributed batch loader.

Parity with the reference's ``DataLoader + DistributedSampler``
(``02-distributed-data-parallel/train_llm.py:76-84``):

- deterministic per-epoch shuffle keyed by (seed, epoch) — ``set_epoch``
  (``02:137``);
- ``drop_last`` partitioning into global batches;
- each process only materializes the shards its local devices own, assembled
  into one global ``jax.Array`` via ``make_array_from_callback`` (the JAX
  analogue of per-rank sampler index partitioning — under a (dp, tp) mesh the
  tp group automatically reads identical data because the batch dim is only
  sharded over the data axes, which the reference has to hand-arrange with a
  mesh-aware sampler, ``06-tensor-parallel/train_llm.py:141-147``);
- epoch fast-forward for resume (``01:133-135``) via ``start_step``.

Double-buffered host->device prefetch hides dispatch latency (reference C26,
``related-topics/optimizing-data-loading``).
"""
from __future__ import annotations

from typing import Iterator, Optional

import jax
import numpy as np


class ShardedBatchLoader:
    def __init__(
        self,
        dataset: np.ndarray,          # [num_seqs, seq_len] int32
        global_batch_size: int,
        sharding,                      # NamedSharding for [B, S] (or [A, B, S])
        *,
        grad_accum: int = 1,
        seed: int = 0,
        shuffle: bool = True,
        prefetch: int = 2,
        native: bool = False,
    ):
        if global_batch_size % max(grad_accum, 1) != 0:
            raise ValueError("global_batch_size must be divisible by grad_accum")
        self.dataset = dataset
        self.global_batch_size = global_batch_size
        self.sharding = sharding
        self.grad_accum = grad_accum
        self.seed = seed
        self.shuffle = shuffle
        self.prefetch = prefetch
        self.epoch = 0
        self._native = None
        self._native_path = None
        if native:
            if not shuffle:
                import logging

                logging.getLogger(__name__).warning(
                    "native loader has no unshuffled mode; using python assembly")
            else:
                self._native = self._make_native()

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return len(self.dataset) // self.global_batch_size

    def _epoch_order(self) -> np.ndarray:
        order = np.arange(len(self.dataset))
        if self.shuffle:
            np.random.RandomState(self.seed + 1000003 * self.epoch).shuffle(order)
        return order

    def _make_global_array(self, np_batch: np.ndarray) -> jax.Array:
        if self.grad_accum > 1:
            b = self.global_batch_size // self.grad_accum
            np_batch = np_batch.reshape(self.grad_accum, b, np_batch.shape[-1])
        return jax.make_array_from_callback(
            np_batch.shape, self.sharding, lambda idx: np_batch[idx])

    def _make_native(self):
        """Back batch assembly with the C++ loader (csrc/token_loader.cpp):
        mmap + worker threads + bounded prefetch, no GIL."""
        import tempfile

        from .native_loader import NativeTokenLoader, native_available, write_token_file

        if not native_available():
            import logging

            logging.getLogger(__name__).warning(
                "native loader unavailable (no g++); using python assembly")
            return None
        tmp = tempfile.NamedTemporaryFile(suffix=".tokens.bin", delete=False)
        tmp.close()  # the C++ side reopens by path; don't leak the fd
        self._native_path = tmp.name
        write_token_file(self.dataset, tmp.name)
        return NativeTokenLoader(tmp.name, seq_len=self.dataset.shape[1],
                                 batch=self.global_batch_size, seed=self.seed,
                                 prefetch=max(self.prefetch, 2))

    def close(self) -> None:
        if self._native is not None:
            self._native.close()
            self._native = None
        if self._native_path is not None:
            import os

            try:
                os.unlink(self._native_path)
            except OSError:
                pass
            self._native_path = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def epoch_batches(self, start_step: int = 0) -> Iterator[dict]:
        """Yields {'input_ids', 'labels'} global jax.Arrays; skips the first
        ``start_step`` batches while preserving data order (resume)."""
        if self._native is not None:
            # same pending-queue H2D overlap as the python path, on top of the
            # C++ assembly prefetch
            pending: list[dict] = []
            for np_batch in self._native.epoch_batches(self.epoch, start_step):
                ids = self._make_global_array(np_batch)
                pending.append({"input_ids": ids, "labels": ids})
                if len(pending) > self.prefetch:
                    yield pending.pop(0)
            yield from pending
            return
        order = self._epoch_order()
        n = len(self)
        pending: list[dict] = []
        for step in range(start_step, n):
            idx = order[step * self.global_batch_size:(step + 1) * self.global_batch_size]
            # sorted for memmap read locality only: which sequences form the
            # batch is shuffled (order above); their within-batch order is
            # deliberately left ascending — example->device-slot assignment
            # carries no semantics in this loop (grads sum over the batch)
            np_batch = self.dataset[np.sort(idx)]
            ids = self._make_global_array(np_batch)
            pending.append({"input_ids": ids, "labels": ids})
            if len(pending) > self.prefetch:
                yield pending.pop(0)
        yield from pending
