from .pipeline import load_and_preprocess_data
from .loader import ShardedBatchLoader
from .tokenizer import get_tokenizer, ByteTokenizer

__all__ = ["load_and_preprocess_data", "ShardedBatchLoader", "get_tokenizer", "ByteTokenizer"]
