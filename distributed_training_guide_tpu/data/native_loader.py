"""ctypes bindings + on-demand build for the C++ token loader (csrc/).

The reference's data path gets its native speed from torch's C++ DataLoader
workers; this is our equivalent: ``csrc/token_loader.cpp`` mmaps a flat int32
token file and assembles shuffled batches on C++ threads (no GIL), with a
bounded prefetch queue. The Python side stays a thin iterator.

The shared library is compiled once with g++ on first use and cached next to
the source. Anything without a toolchain falls back to the pure-Python loader
(``data/loader.py``) — same semantics, different shuffle order.
"""
from __future__ import annotations

import ctypes
import logging
import subprocess
import tempfile
from pathlib import Path
from typing import Iterator, Optional

import numpy as np

LOGGER = logging.getLogger(__name__)

_CSRC = Path(__file__).parent.parent / "csrc"
_LIB: Optional[ctypes.CDLL] = None
_BUILD_FAILED = False


def _build_library(force: bool = False) -> Optional[Path]:
    src = _CSRC / "token_loader.cpp"
    out = _CSRC / "libtokenloader.so"
    if not force and out.exists() and out.stat().st_mtime > src.stat().st_mtime:
        return out
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", str(out), str(src), "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return out
    except (OSError, subprocess.SubprocessError) as e:
        LOGGER.warning(f"native loader build failed ({e}); using python loader")
        return None


def get_library() -> Optional[ctypes.CDLL]:
    global _LIB, _BUILD_FAILED
    if _LIB is not None or _BUILD_FAILED:
        return _LIB
    path = _build_library()
    if path is None:
        _BUILD_FAILED = True
        return None
    try:
        lib = ctypes.CDLL(str(path))
    except OSError:
        # stale/foreign binary (e.g. different arch) — rebuild once, then
        # fall back to the python loader
        path = _build_library(force=True)
        try:
            lib = ctypes.CDLL(str(path)) if path else None
        except OSError:
            lib = None
        if lib is None:
            _BUILD_FAILED = True
            return None
    lib.tl_open.restype = ctypes.c_void_p
    lib.tl_open.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
                            ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
    lib.tl_num_batches.restype = ctypes.c_int64
    lib.tl_num_batches.argtypes = [ctypes.c_void_p]
    lib.tl_num_sequences.restype = ctypes.c_int64
    lib.tl_num_sequences.argtypes = [ctypes.c_void_p]
    lib.tl_start_epoch.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
    lib.tl_next_batch.restype = ctypes.c_int
    lib.tl_next_batch.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_int32)]
    lib.tl_close.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return lib


def native_available() -> bool:
    return get_library() is not None


def write_token_file(dataset: np.ndarray, path: str | Path) -> Path:
    """Flat int32 token file — the native loader's (and mmap-friendly) format."""
    path = Path(path)
    np.ascontiguousarray(dataset, dtype=np.int32).tofile(path)
    return path


class NativeTokenLoader:
    """Iterator over [batch, seq_len] int32 batches assembled in C++.

    Deterministic per (seed, epoch); supports resume via ``start_step`` like
    the python loader (the two use different shuffle orders — pick one backend
    per experiment).
    """

    def __init__(self, token_file: str | Path, seq_len: int, batch: int,
                 seed: int = 0, threads: int = 2, prefetch: int = 4):
        lib = get_library()
        if lib is None:
            raise RuntimeError("native loader unavailable (no g++?)")
        self._lib = lib
        self._handle = lib.tl_open(str(token_file).encode(), seq_len, batch,
                                   seed, threads, prefetch)
        if not self._handle:
            raise OSError(f"tl_open failed for {token_file}")
        self.seq_len = seq_len
        self.batch = batch

    def __len__(self) -> int:
        return self._lib.tl_num_batches(self._handle)

    @property
    def num_sequences(self) -> int:
        return self._lib.tl_num_sequences(self._handle)

    def epoch_batches(self, epoch: int = 0, start_step: int = 0) -> Iterator[np.ndarray]:
        self._lib.tl_start_epoch(self._handle, epoch, start_step)
        out = np.empty((self.batch, self.seq_len), dtype=np.int32)
        ptr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        while self._lib.tl_next_batch(self._handle, ptr):
            yield out.copy()

    def close(self) -> None:
        if self._handle:
            self._lib.tl_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
