"""Data pipeline: text -> fixed-length token sequences.

Mirrors the reference's pipeline semantics (``01-single-gpu/train_llm.py:192-245``):
tokenize the corpus, concatenate everything, chunk into ``seq_length`` blocks,
``labels = input_ids`` (the loss shifts). Three sources:

1. ``synthetic[:n_tokens]`` — deterministic random tokens, zero-egress (tests,
   benchmarks; the analogue of the reference's tiny smoke configs).
2. a local ``.txt``/``.jsonl`` file path — tokenized + chunked.
3. an HF ``datasets`` name — the reference's exact surface
   (``--dataset-name tatsu-lab/alpaca``), used when the hub/cache is reachable.

Output is a single int32 array [num_sequences, seq_length]: TPU-friendly
(static shapes, zero-copy mmap-able) instead of a Python dataset of dicts.
"""
from __future__ import annotations

import json
import logging
from pathlib import Path

import numpy as np

LOGGER = logging.getLogger(__name__)


def _chunk(token_stream: np.ndarray, seq_length: int) -> np.ndarray:
    n = (len(token_stream) // seq_length) * seq_length
    if n == 0:
        raise ValueError(f"corpus too small: {len(token_stream)} tokens < seq_length={seq_length}")
    return token_stream[:n].astype(np.int32).reshape(-1, seq_length)


def synthetic_dataset(n_tokens: int, vocab_size: int, seq_length: int, seed: int = 0) -> np.ndarray:
    rng = np.random.RandomState(seed)
    # Markov-ish structure so the loss actually decreases during smoke runs
    base = rng.randint(0, vocab_size, size=n_tokens, dtype=np.int64)
    repeat_mask = rng.rand(n_tokens) < 0.5
    stream = np.where(repeat_mask, np.roll(base, 1), base)
    return _chunk(stream, seq_length)


def _tokenize_batch(tokenizer, texts: list[str]) -> list[list[int]]:
    """Normalize HF (flat list for a single string) vs batch conventions by
    always tokenizing a list of strings -> list of id-lists."""
    out = tokenizer(texts)["input_ids"]
    if out and isinstance(out[0], int):  # defensive: flat list
        out = [out]
    return out


def _from_local_file(path: Path, tokenizer, seq_length: int) -> np.ndarray:
    if path.suffix == ".jsonl":
        texts = [json.loads(line).get("text", "") for line in path.read_text().splitlines() if line]
    else:
        texts = [path.read_text()]
    ids: list[int] = []
    for id_list in _tokenize_batch(tokenizer, texts):
        ids.extend(id_list)
    return _chunk(np.asarray(ids, dtype=np.int64), seq_length)


def _from_hf(dataset_name: str, subset, tokenizer, seq_length: int) -> np.ndarray:
    import datasets  # HF

    data = datasets.load_dataset(dataset_name, subset)
    split = data["train"]
    column = "text" if "text" in split.column_names else split.column_names[0]

    def tokenize_fn(examples):
        return tokenizer(examples[column])

    tokenized = split.map(tokenize_fn, batched=True, remove_columns=split.column_names,
                          desc="tokenizing")
    stream = np.concatenate([np.asarray(x, dtype=np.int64) for x in tokenized["input_ids"]])
    return _chunk(stream, seq_length)


def load_and_preprocess_data(
    dataset_name: str,
    tokenizer,
    seq_length: int,
    *,
    dataset_subset: str | None = None,
    max_position_embeddings: int | None = None,
    seed: int = 0,
) -> np.ndarray:
    """Returns [num_sequences, seq_length] int32."""
    if max_position_embeddings:
        # clamp to what the model can attend to (cf. 01-single-gpu/train_llm.py:216-218)
        seq_length = min(seq_length, max_position_embeddings)

    if dataset_name.startswith("synthetic"):
        n_tokens = 1_000_000
        if ":" in dataset_name:
            n_tokens = int(dataset_name.split(":", 1)[1])
        vocab = getattr(tokenizer, "vocab_size", 259)
        return synthetic_dataset(n_tokens, vocab, seq_length, seed)

    path = Path(dataset_name)
    if path.exists():
        return _from_local_file(path, tokenizer, seq_length)

    return _from_hf(dataset_name, dataset_subset, tokenizer, seq_length)
