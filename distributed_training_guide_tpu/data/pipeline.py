"""Data pipeline: text -> fixed-length token sequences.

Mirrors the reference's pipeline semantics (``01-single-gpu/train_llm.py:192-245``):
tokenize the corpus, concatenate everything, chunk into ``seq_length`` blocks,
``labels = input_ids`` (the loss shifts). Three sources:

1. ``synthetic[:n_tokens]`` — deterministic random tokens, zero-egress (tests,
   benchmarks; the analogue of the reference's tiny smoke configs).
2. a local ``.txt``/``.jsonl`` file path — tokenized + chunked.
3. an HF ``datasets`` name — the reference's exact surface
   (``--dataset-name tatsu-lab/alpaca``), used when the hub/cache is reachable.

Output is a single int32 array [num_sequences, seq_length]: TPU-friendly
(static shapes, zero-copy mmap-able) instead of a Python dataset of dicts.
"""
from __future__ import annotations

import json
import logging
import re
from pathlib import Path

import numpy as np

LOGGER = logging.getLogger(__name__)


def _chunk(token_stream: np.ndarray, seq_length: int) -> np.ndarray:
    n = (len(token_stream) // seq_length) * seq_length
    if n == 0:
        raise ValueError(f"corpus too small: {len(token_stream)} tokens < seq_length={seq_length}")
    return token_stream[:n].astype(np.int32).reshape(-1, seq_length)


def synthetic_dataset(n_tokens: int, vocab_size: int, seq_length: int, seed: int = 0) -> np.ndarray:
    rng = np.random.RandomState(seed)
    # Markov-ish structure so the loss actually decreases during smoke runs
    base = rng.randint(0, vocab_size, size=n_tokens, dtype=np.int64)
    repeat_mask = rng.rand(n_tokens) < 0.5
    stream = np.where(repeat_mask, np.roll(base, 1), base)
    return _chunk(stream, seq_length)


def _tokenize_batch(tokenizer, texts: list[str]) -> list[list[int]]:
    """Normalize HF (flat list for a single string) vs batch conventions by
    always tokenizing a list of strings -> list of id-lists."""
    out = tokenizer(texts)["input_ids"]
    if out and isinstance(out[0], int):  # defensive: flat list
        out = [out]
    return out


def _from_local_file(path: Path, tokenizer, seq_length: int) -> np.ndarray:
    if path.suffix == ".jsonl":
        texts = [json.loads(line).get("text", "") for line in path.read_text().splitlines() if line]
    else:
        texts = [path.read_text()]
    ids: list[int] = []
    for id_list in _tokenize_batch(tokenizer, texts):
        ids.extend(id_list)
    return _chunk(np.asarray(ids, dtype=np.int64), seq_length)


def _from_hf(dataset_name: str, subset, tokenizer, seq_length: int) -> np.ndarray:
    import datasets  # HF

    data = datasets.load_dataset(dataset_name, subset)
    split = data["train"]
    column = "text" if "text" in split.column_names else split.column_names[0]

    def tokenize_fn(examples):
        return tokenizer(examples[column])

    tokenized = split.map(tokenize_fn, batched=True, remove_columns=split.column_names,
                          desc="tokenizing")
    stream = np.concatenate([np.asarray(x, dtype=np.int64) for x in tokenized["input_ids"]])
    return _chunk(stream, seq_length)


def _spill_to_memmap(arr: np.ndarray, mmap_dir: str | Path,
                     cache_key: str) -> np.ndarray:
    """Write the corpus once as a raw int32 token file and hand back a
    read-only memmap view: training-time host RAM holds only the batch rows
    actually fetched (data/loader.py fetches per addressable shard), and the
    native loader mmaps this same file zero-copy. The raw layout (no .npy
    header) is deliberate — it is csrc/token_loader.cpp's format."""
    import os

    mmap_dir = Path(mmap_dir)
    mmap_dir.mkdir(parents=True, exist_ok=True)
    path = mmap_dir / f"{cache_key}.tokens.bin"
    expect = arr.size * 4
    if not path.exists() or path.stat().st_size != expect:
        # pid-unique tmp: concurrent writers (a gang's ranks, or hosts on
        # shared storage) each complete their own atomic replace of the
        # SAME deterministic content — duplicated work, never a torn file.
        # For corpora big enough for that duplication to hurt, wrap the
        # call in procguards.process0_first().
        tmp = path.with_name(f"{path.name}.{os.getpid()}.tmp")
        np.ascontiguousarray(arr, dtype=np.int32).tofile(tmp)
        tmp.replace(path)   # atomic: a crashed writer never leaves a torso
        LOGGER.info(f"spilled corpus to {path} ({expect >> 20} MiB)")
    return np.memmap(path, dtype=np.int32, mode="r", shape=arr.shape)


def load_and_preprocess_data(
    dataset_name: str,
    tokenizer,
    seq_length: int,
    *,
    dataset_subset: str | None = None,
    max_position_embeddings: int | None = None,
    seed: int = 0,
    mmap_dir: str | Path | None = None,
) -> np.ndarray:
    """Returns [num_sequences, seq_length] int32.

    With ``mmap_dir`` the token array is disk-backed (built once, reused
    across runs keyed on dataset/seq/seed): each host's RAM then holds only
    the batch-shard rows its devices consume, not the corpus — the footprint
    VERDICT r3 flagged for the 405B recipe's data path."""
    if max_position_embeddings:
        # clamp to what the model can attend to (cf. 01-single-gpu/train_llm.py:216-218)
        seq_length = min(seq_length, max_position_embeddings)

    if dataset_name.startswith("synthetic"):
        n_tokens = 1_000_000
        if ":" in dataset_name:
            n_tokens = int(dataset_name.split(":", 1)[1])
        vocab = getattr(tokenizer, "vocab_size", 259)
        data = synthetic_dataset(n_tokens, vocab, seq_length, seed)
    else:
        path = Path(dataset_name)
        if path.exists():
            data = _from_local_file(path, tokenizer, seq_length)
        else:
            data = _from_hf(dataset_name, dataset_subset, tokenizer, seq_length)

    if mmap_dir is not None:
        # the key must pin everything that changes token CONTENT — subset
        # and tokenizer identity included, since num_sequences (and thus
        # file size, the only other staleness check) can collide across
        # corpora at the same seq_length
        if tokenizer is None:
            tok_id = "none"
        else:
            tok_id = getattr(tokenizer, "name_or_path", None) or type(tokenizer).__name__
        key = re.sub(r"[^A-Za-z0-9._-]+", "_",
                     f"{dataset_name}-{dataset_subset or ''}-{tok_id}"
                     f"-s{seq_length}-r{seed}")
        data = _spill_to_memmap(data, mmap_dir, key)
    return data
