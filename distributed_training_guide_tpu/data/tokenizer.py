"""Tokenizer resolution.

The reference uses HF ``AutoTokenizer`` (``01-single-gpu/train_llm.py:197``).
We keep that surface when the HF cache/network is available, and add a
hermetic byte-level fallback so the framework (and its tests) run with zero
egress — the TPU testbeds this targets are often airgapped.
"""
from __future__ import annotations


class ByteTokenizer:
    """UTF-8 byte tokenizer: vocab = 256 bytes + BOS/EOS/PAD."""

    vocab_size = 259
    bos_token_id = 256
    eos_token_id = 257
    pad_token_id = 258
    model_max_length = 1 << 30

    def __call__(self, texts):
        if isinstance(texts, str):
            texts = [texts]
        return {"input_ids": [list(t.encode("utf-8")) + [self.eos_token_id] for t in texts]}

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")


def get_tokenizer(model_name: str):
    """HF tokenizer if locally cached, else the byte fallback.

    ``hf:<dir>`` model names resolve to the checkpoint dir itself, which
    holds tokenizer.json — handled here so every caller (chapter CLIs, the
    engine) gets the right tokenizer without knowing about the prefix."""
    if model_name.startswith("hf:"):
        model_name = model_name[3:]
    try:
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(model_name, local_files_only=True)
    except Exception:
        return ByteTokenizer()
