"""Chapter 7 — 2-D parallelism: FSDP x TP on one mesh.

TPU-native counterpart of ``07-2d-parallel/train_llm.py``. The reference
composes two wrapper systems — the TP plan first, then ``fully_shard(...,
mesh=mesh["dp"])`` over the orthogonal axis (``07:77-123``). Here 2-D is one
rules table ("tp_fsdp"): head/kv/mlp/vocab dims on tp, embed dims on fsdp —
two entries in the same NamedSharding. This is the payoff of the design: the
chapter diff vs chapter 6 is one flag, exactly the pedagogical point the
reference makes by keeping its loop identical.

Smoke run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python train_llm.py -m llama-debug -d synthetic:200000 -s 128 -b 2 \
        --tensor-parallel 2 --num-epochs 1 --log-freq 5
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax

from distributed_training_guide_tpu.launch import maybe_initialize_distributed
from distributed_training_guide_tpu.launch.errors import record
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train.cli import get_parser, run_training


@record
def main():
    parser = get_parser()
    parser.add_argument("--tensor-parallel", type=int, default=1)
    args = parser.parse_args()
    maybe_initialize_distributed()

    def plan_factory():
        n = len(jax.devices())
        tp = args.tensor_parallel
        return make_plan("tp_fsdp", make_mesh(tp=tp, fsdp=n // tp))

    run_training(args, plan_factory)


if __name__ == "__main__":
    main()
