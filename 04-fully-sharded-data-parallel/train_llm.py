"""Chapter 4 — fully-sharded data parallelism (FSDP / ZeRO-3).

TPU-native counterpart of ``04-fully-sharded-data-parallel/train_llm.py``.
The reference's ``fully_shard`` machinery (``04:83-95``) — per-layer parameter
sharding, all-gather before each layer's forward/backward, reduce-scatter of
grads, meta-device deferred init, ``reshard_after_forward``, explicit
``model.unshard()`` prefetch — collapses to a sharding plan here:

- every weight's embed dim carries ``P('fsdp')``; XLA all-gathers each layer's
  params ahead of use inside the scanned block (the scheduler hides it behind
  the previous layer's compute, replacing explicit prefetch, ``04:188``) and
  reduce-scatters grads into the sharded optimizer update;
- "meta-device init then materialize shards" (``04:76-95``) is simply
  ``jit(init, out_shardings=...)`` — paramaters are *born* sharded;
- ``reshard_after_forward`` is the remat flag: ``--checkpoint-activations``
  re-gathers during backward instead of keeping activations live;
- mixed precision (``MixedPrecisionPolicy(param_dtype=bf16, reduce_dtype=fp32)``,
  ``04:85``) is the model's param_dtype=fp32 / compute dtype=bf16 policy with
  fp32 grad reduction.

Smoke run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python train_llm.py -m llama-debug -d synthetic:200000 -s 128 -b 1 \
        --num-epochs 1 --log-freq 5
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax

from distributed_training_guide_tpu.launch import maybe_initialize_distributed
from distributed_training_guide_tpu.launch.errors import record
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train.cli import get_parser, run_training


@record
def main():
    parser = get_parser()
    parser.add_argument("--cpu-offload", action="store_true",
                        help="keep params AND optimizer state in host memory between steps (reference CPUOffloadPolicy, 04:85)")
    args = parser.parse_args()
    maybe_initialize_distributed()

    def plan_factory():
        n = len(jax.devices())
        return make_plan("fsdp", make_mesh(fsdp=n))

    run_training(args, plan_factory,
                 offload_opt_state=args.cpu_offload,
                 offload_params=args.cpu_offload)


if __name__ == "__main__":
    main()
