"""Open-loop load harness (serve/loadgen.py) + SLO control plane
(serve/controller.py), pure logic — no compiles: Poisson/trace schedule
determinism, the arrival-burst fault knob, scenario/goodput accounting,
controller hysteresis + cooldowns, two-phase drain-before-remove
scale-down (with the chaos-abandon races), the degradation ladder's
declared order and unwind, the staleness fence, and a randomized
property drill over chaotic stats traces. Real-engine chaos drills live
in test_chaos_serve.py; the measured rungs in bench.py --check-load.
"""
import dataclasses
import random

import pytest

from distributed_training_guide_tpu.serve.controller import SLO, Controller
from distributed_training_guide_tpu.serve.loadgen import (
    LoadReport, build_schedule, default_scenarios, percentile,
    poisson_arrivals, run_open_loop, summarize, trace_arrivals)
from distributed_training_guide_tpu.serve.router import Replica, Router
from distributed_training_guide_tpu.serve.scheduler import (RefusalError,
                                                            Request,
                                                            RequestResult)
from distributed_training_guide_tpu.utils import faults

pytestmark = [pytest.mark.serve, pytest.mark.loadgen, pytest.mark.control]


# ---- arrival schedules ------------------------------------------------------

def test_poisson_arrivals_deterministic_monotone_and_rate_shaped():
    a = poisson_arrivals(8.0, 10.0, seed=3)
    b = poisson_arrivals(8.0, 10.0, seed=3)
    assert a == b, "the trace is a pure function of (rate, duration, seed)"
    assert a != poisson_arrivals(8.0, 10.0, seed=4)
    assert all(0 <= t < 10.0 for t in a)
    assert a == sorted(a)
    # ~80 expected arrivals; a factor-2 band is loose enough to never
    # flake on a fixed seed and tight enough to catch a rate bug
    assert 40 <= len(a) <= 160
    with pytest.raises(ValueError):
        poisson_arrivals(0.0, 1.0)


def test_arrival_burst_fault_multiplies_rate_in_window(monkeypatch):
    monkeypatch.setenv(faults.ENV_ARRIVAL_BURST, "6@1.0:2.0")
    arrivals = poisson_arrivals(10.0, 3.0, seed=0)
    per_second = [sum(1 for t in arrivals if s <= t < s + 1)
                  for s in range(3)]
    # seconds 0 and 2 run at 10 rps, second 1 at 60 rps — the burst
    # second must dominate both flanks decisively (deterministic seed)
    assert per_second[1] > 2 * max(per_second[0], per_second[2])
    monkeypatch.delenv(faults.ENV_ARRIVAL_BURST)
    base = poisson_arrivals(10.0, 3.0, seed=0)
    assert arrivals != base, "the knob must actually reshape the trace"


def test_trace_arrivals_sorts_and_rejects_negative():
    assert trace_arrivals([3.0, 1.0, 2.0]) == [1.0, 2.0, 3.0]
    assert trace_arrivals([]) == []
    with pytest.raises(ValueError):
        trace_arrivals([1.0, -0.5])


# ---- scenarios + schedule ---------------------------------------------------

def test_default_scenarios_always_fit_the_engine_budget():
    """Every sampled request must fit max_len (prompt + generation):
    refusals in a sweep should be backpressure, never a bad request."""
    rng = random.Random(0)
    for max_len, page in ((32, 4), (128, 16)):
        scenarios = default_scenarios(max_len=max_len, page_size=page,
                                      vocab=256, deadline_s=1.0)
        names = {s.name for s in scenarios}
        assert {"chat", "long_prompt", "long_gen", "urgent",
                "batch"} <= names
        for s in scenarios:
            for i in range(50):
                req = s.sample(rng, 256, i)
                assert len(req.prompt_ids) + req.max_new_tokens <= max_len
                assert all(0 < t < 256 for t in req.prompt_ids)
                assert req.priority == s.priority
        chat = next(s for s in scenarios if s.name == "chat")
        assert chat.shared_prefix, "chat turns share a system prompt"
        urgent = next(s for s in scenarios if s.name == "urgent")
        batch = next(s for s in scenarios if s.name == "batch")
        assert urgent.deadline_s < batch.deadline_s
        assert urgent.priority > batch.priority


def test_build_schedule_is_deterministic_and_preserves_arrivals():
    scenarios = default_scenarios(max_len=32, page_size=4, vocab=128)
    arrivals = poisson_arrivals(5.0, 4.0, seed=1)
    s1 = build_schedule(arrivals, scenarios, vocab=128, seed=2)
    s2 = build_schedule(arrivals, scenarios, vocab=128, seed=2)
    assert [t for t, _ in s1] == arrivals
    assert [(t, r.prompt_ids, r.max_new_tokens, r.priority)
            for t, r in s1] \
        == [(t, r.prompt_ids, r.max_new_tokens, r.priority)
            for t, r in s2]


# ---- report accounting ------------------------------------------------------

def _result(rid, reason="eos", submitted=0.0, first=0.5, finished=1.0,
            n_gen=4):
    return RequestResult(request_id=rid, prompt_ids=[1, 2],
                         generated_ids=list(range(n_gen)),
                         finish_reason=reason, submitted_at=submitted,
                         admitted_at=submitted, finished_at=finished,
                         first_token_at=first)


def test_summarize_goodput_and_tails():
    schedule = [(float(i), Request(prompt_ids=[1, 2])) for i in range(6)]
    results = {
        0: _result(0, "eos", submitted=0.0, first=0.2, finished=1.0),
        1: _result(1, "length", submitted=1.0, first=1.4, finished=2.0),
        2: _result(2, "deadline", submitted=2.0, first=0.0, n_gen=0),
        3: _result(3, "resubmit_exhausted", submitted=3.0, first=3.1,
                   n_gen=2),
    }
    rep = summarize(schedule, results, [(4.0, "queue_full"),
                                        (5.0, "shed_low_priority")],
                    wall_s=10.0)
    assert rep.offered == 6 and rep.submitted == 4 and rep.refused == 2
    assert rep.completed == 2 and rep.deadline_met == 2
    assert rep.deadline_missed == 1 and rep.resubmit_exhausted == 1
    assert rep.goodput_rps == pytest.approx(0.2)
    assert rep.refusal_rate == pytest.approx(2 / 6, abs=1e-3)
    assert rep.refused_by_reason == {"queue_full": 1,
                                     "shed_low_priority": 1}
    # TTFT measured from client submit (the resubmission bugfix's
    # observable): request 1 submitted at 1.0, first token 1.4
    assert rep.ttft_p50_s in (pytest.approx(0.2), pytest.approx(0.4))
    assert isinstance(rep.as_dict(), dict)


def test_percentile_nearest_rank():
    vals = [0.1, 0.2, 0.3, 0.4]
    assert percentile([], 0.99) == 0.0
    assert percentile(vals, 0.0) == 0.1
    assert percentile(vals, 1.0) == 0.4
    assert percentile(vals, 0.5) in vals, "never invents a value"


# ---- the controller over a fake fleet ---------------------------------------

class CtlEngine:
    """Engine-shaped stats source the controller (via a real Router)
    observes: every knob the control law reads is a writable field."""

    def __init__(self, page_size=4, n_slots=4):
        self.page_size, self.n_slots = page_size, n_slots
        self.queued = 0
        self.active = 0
        self.finished = 0
        self.missed = 0
        self.working = False
        self.decode_steps = self.decode_tokens = 0
        self.draining = False
        self.closed = False
        self._ids = iter(range(10 ** 6))

    def stats(self):
        return {"n_slots": self.n_slots, "queued": self.queued,
                "active_slots": self.active, "pool_occupancy": 0.0,
                "pages_capacity": 10, "pages_free": 10, "pages_held": 0,
                "finished": self.finished,
                "deadline_missed_queued": self.missed,
                "draining": self.draining, "max_queue": 64}

    def submit(self, request):
        return next(self._ids)

    def resubmit(self, request, generated=(), first_token_at=0.0,
                 submitted_at=None):
        return next(self._ids)

    def partial_tokens(self):
        return {}

    def step(self):
        return []

    @property
    def has_work(self):
        return self.working

    def drain(self):
        self.draining = True

    def close(self):
        self.closed = True


def _ctl_fleet(n=2, t=None, **ctl_kw):
    t = t if t is not None else [0.0]
    clock = lambda: t[0]  # noqa: E731
    replicas = [Replica(f"r{i}", CtlEngine(), clock=clock)
                for i in range(n)]
    router = Router(replicas, clock=clock,
                    heartbeat_timeout_s=10 ** 9)
    spawned = iter(range(100))
    ctl_kw.setdefault(
        "spawn", lambda: Replica(f"n{next(spawned)}", CtlEngine(),
                                 clock=clock))
    ctl = Controller(router, **ctl_kw)
    return router, ctl, t


def _tick(router, ctl, t, dt=0.1):
    """One observation: advance time, drive the fleet (stats_seq moves),
    then let the controller look."""
    t[0] += dt
    router.step()
    ctl.step()


def test_steady_trace_inside_dead_band_actuates_nothing():
    router, ctl, t = _ctl_fleet(2, hold_up=2, hold_down=3, cooldown_s=0.0)
    for rep in router.replicas.values():
        rep.engine.queued = 1            # between queue_low and queue_high
        rep.engine.active = 3            # slot_occ 6/8 > low -> not under
    for _ in range(50):
        _tick(router, ctl, t)
    assert ctl.actions == []
    assert ctl.state == "steady"
    assert ctl.counters["observations"] == 50


def test_overload_scales_up_after_hold_up_and_records_cold_start():
    router, ctl, t = _ctl_fleet(1, hold_up=3, cooldown_s=0.0,
                                max_replicas=2)
    router.replicas["r0"].engine.queued = 50
    _tick(router, ctl, t)
    _tick(router, ctl, t)
    assert ctl.counters["scale_up"] == 0, "hysteresis: 2 < hold_up"
    _tick(router, ctl, t)
    assert ctl.counters["scale_up"] == 1
    assert len(router.replicas) == 2
    assert ctl.cold_starts and ctl.cold_starts[0] >= 0.0
    up = [a for a in ctl.actions if a["kind"] == "scale_up"]
    assert up and "cold_start_s" in up[0]
    # the spawned replica is routable: keyless traffic prefers it (idle)
    rid = router.submit(Request(prompt_ids=[1, 2]))
    assert router._records[rid].replica == up[0]["target"]


def test_cooldown_gates_membership_and_ladder_fills_the_gap():
    router, ctl, t = _ctl_fleet(1, hold_up=2, cooldown_s=5.0,
                                max_replicas=3)
    router.replicas["r0"].engine.queued = 50
    _tick(router, ctl, t)
    _tick(router, ctl, t)
    assert ctl.counters["scale_up"] == 1
    # overload persists inside the cooldown: membership is gated, so the
    # fleet degrades (shed) instead of flapping replicas
    for rep in router.replicas.values():
        rep.engine.queued = 50
    _tick(router, ctl, t)
    _tick(router, ctl, t)
    assert ctl.counters["scale_up"] == 1
    assert ctl.state == "shed"
    assert router.min_priority == ctl.slo.shed_below_priority
    # past the cooldown the next persistent overload scales up again
    t[0] += 10.0
    _tick(router, ctl, t)
    _tick(router, ctl, t)
    assert ctl.counters["scale_up"] == 2


def test_shed_refuses_low_priority_at_the_front_door():
    router, ctl, t = _ctl_fleet(1, hold_up=1, cooldown_s=0.0,
                                max_replicas=1)
    router.replicas["r0"].engine.queued = 50
    _tick(router, ctl, t)
    assert ctl.state == "shed"
    with pytest.raises(RefusalError) as exc:
        router.submit(Request(prompt_ids=[1, 2], priority=0))
    assert exc.value.reason == "shed_low_priority"
    assert exc.value.http_status == 429
    assert exc.value.retry_after_s > 0
    # priority at/above the bar still admits
    router.submit(Request(prompt_ids=[1, 2], priority=1))
    assert router.stats()["refused"]["shed_low_priority"] == 1


def test_degradation_ladder_order_and_unwind():
    """shed -> backpressure under persistent overload at max capacity;
    unwind in REVERSE as calm holds — and never a rung that touches
    running sequences (the only actuators are admission knobs)."""
    router, ctl, t = _ctl_fleet(1, hold_up=2, hold_down=3, cooldown_s=0.0,
                                max_replicas=1)
    eng = router.replicas["r0"].engine
    eng.queued = 50
    for _ in range(4):
        _tick(router, ctl, t)
    assert [a["kind"] for a in ctl.actions] == ["shed_on",
                                                "backpressure_on"]
    assert ctl.state == "backpressure"
    assert router.retry_after_floor_s == ctl.slo.retry_after_floor_s
    # ... and the tightened hint reaches refused clients
    eng.queued = 1                       # calm (dead band)
    for _ in range(3):
        _tick(router, ctl, t)
    assert ctl.state == "shed"
    assert router.retry_after_floor_s == 0.0
    for _ in range(3):
        _tick(router, ctl, t)
    assert ctl.state == "steady"
    assert router.min_priority is None
    assert [a["kind"] for a in ctl.actions] == [
        "shed_on", "backpressure_on", "backpressure_off", "shed_off"]


def test_scale_down_is_two_phase_drain_then_remove():
    router, ctl, t = _ctl_fleet(2, hold_down=3, cooldown_s=0.0)
    victim_engine = None
    for rep in router.replicas.values():
        rep.engine.queued = 0
    router.replicas["r1"].engine.working = True   # r1 still busy
    for _ in range(3):
        _tick(router, ctl, t)
    # underload held: the least-loaded live replica drains, nothing is
    # removed while it has work
    assert ctl.state == "draining"
    victim = ctl.stats()["draining_victim"]
    victim_engine = router.replicas[victim].engine
    assert victim_engine.draining
    assert len(router.replicas) == 2
    assert ctl.counters["scale_down"] == 0
    _tick(router, ctl, t)
    if victim_engine.working:
        assert len(router.replicas) == 2, "drain incomplete -> no remove"
    victim_engine.working = False
    victim_engine.queued = 0
    _tick(router, ctl, t)
    assert ctl.counters["scale_down"] == 1
    assert victim not in router.replicas
    assert victim_engine.closed, "removed replica's engine is closed"
    assert ctl.state == "steady"
    kinds = [a["kind"] for a in ctl.actions]
    assert kinds.index("drain") < kinds.index("scale_down")


def test_scale_down_abandoned_when_chaos_kills_the_victim():
    router, ctl, t = _ctl_fleet(2, hold_down=2, cooldown_s=0.0)
    router.replicas["r0"].engine.working = True
    router.replicas["r1"].engine.working = True
    for _ in range(2):
        _tick(router, ctl, t)
    assert ctl.state == "draining"
    victim = ctl.stats()["draining_victim"]
    router.replicas[victim].kill()       # chaos wins the race
    _tick(router, ctl, t)                # router fences; controller sees
    assert ctl.state == "steady"
    assert ctl.counters["scale_down_abandoned"] == 1
    assert ctl.counters["scale_down"] == 0, \
        "never remove a corpse that was not drained"


def test_stale_snapshot_is_counted_and_inert():
    router, ctl, t = _ctl_fleet(1, hold_up=1, cooldown_s=0.0,
                                max_replicas=4)
    router.replicas["r0"].engine.queued = 50
    _tick(router, ctl, t)
    n_up = ctl.counters["scale_up"]
    # nobody drives the fleet between polls: stats_seq frozen -> the one
    # legal actuation is NOTHING, however loud the stale numbers are
    for _ in range(10):
        t[0] += 0.1
        ctl.step()
    assert ctl.counters["stale_snapshots"] == 10
    assert ctl.counters["scale_up"] == n_up


def test_actuation_never_targets_fenced_replicas():
    router, ctl, t = _ctl_fleet(3, hold_down=2, cooldown_s=0.0,
                                min_replicas=1)
    router.replicas["r1"].state = "fenced"
    for _ in range(4):
        _tick(router, ctl, t)
    for action in ctl.actions:
        assert action["target"] != "r1"
    assert ctl.stats()["draining_victim"] != "r1"


def test_controller_property_chaotic_traces_respect_invariants():
    """Satellite property drill: drive random load/chaos traces and pin
    (1) membership-channel starts (drain / scale_up) respect cooldown_s
    against the previous membership action, (2) remove_replica only ever
    fires on a drained, idle victim (asserted at the call), (3) the
    controller never raises, whatever chaos does to the fleet."""
    for trial in range(12):
        rng = random.Random(100 + trial)
        t = [0.0]
        clock = lambda: t[0]  # noqa: E731
        replicas = [Replica(f"r{i}", CtlEngine(), clock=clock)
                    for i in range(3)]
        router = Router(replicas, clock=clock, heartbeat_timeout_s=10 ** 9)
        removed_log = []
        original_remove = router.remove_replica

        def checked_remove(name):
            rep = router.replicas[name]
            assert not rep.engine.has_work, \
                "remove_replica on a replica with live work"
            assert rep.engine.draining, "remove without a completed drain"
            removed_log.append(name)
            return original_remove(name)

        router.remove_replica = checked_remove
        spawned = iter(range(100))
        cooldown = rng.choice([0.0, 0.3, 1.0])
        ctl = Controller(
            router, cooldown_s=cooldown,
            hold_up=rng.randint(1, 3), hold_down=rng.randint(1, 4),
            max_replicas=4,
            spawn=lambda: Replica(f"n{next(spawned)}", CtlEngine(),
                                  clock=clock))
        for _ in range(80):
            t[0] += rng.choice([0.05, 0.1, 0.4])
            for rep in list(router.replicas.values()):
                if rep.state != "live":
                    continue
                rep.engine.queued = rng.choice([0, 0, 1, 2, 6, 40])
                rep.engine.working = rng.random() < 0.3
                if rng.random() < 0.03:
                    rep.kill()           # chaos
            router.step()
            ctl.step()                   # must never raise
        membership = [a for a in ctl.actions
                      if a["kind"] in ("drain", "scale_up")]
        anchors = [a for a in ctl.actions
                   if a["kind"] in ("drain", "scale_up", "scale_down")]
        for action in membership:
            prior = [a for a in anchors if a["t"] < action["t"]]
            if prior:
                assert action["t"] - prior[-1]["t"] >= cooldown - 1e-9, \
                    f"membership action inside cooldown: {action}"
        assert ctl.counters["scale_down"] == len(removed_log)


# ---- the open-loop driver over fakes ---------------------------------------

class LoopEngine(CtlEngine):
    """Completes every submitted request after a fixed number of steps —
    enough machinery for run_open_loop's bookkeeping to be pinned
    without a compile."""

    def __init__(self, delay_steps=2, **kw):
        super().__init__(**kw)
        self.delay_steps = delay_steps
        self.pending = []                # (ready_at_step, rid, request)
        self.step_n = 0

    def submit(self, request):
        rid = next(self._ids)
        self.pending.append((self.step_n + self.delay_steps, rid, request))
        return rid

    def resubmit(self, request, generated=(), first_token_at=0.0,
                 submitted_at=None):
        return self.submit(request)

    @property
    def has_work(self):
        return bool(self.pending)

    def step(self):
        self.step_n += 1
        done, keep = [], []
        for ready, rid, req in self.pending:
            if self.step_n >= ready:
                done.append(RequestResult(
                    request_id=rid, prompt_ids=list(req.prompt_ids),
                    generated_ids=[7, 8], finish_reason="eos",
                    submitted_at=0.0, admitted_at=0.0, finished_at=0.1,
                    first_token_at=0.05))
            else:
                keep.append((ready, rid, req))
        self.pending = keep
        self.finished += len(done)
        return done


def test_run_open_loop_submits_on_schedule_and_collects_results():
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731

    def sleep(dt):
        t[0] += dt

    engine = LoopEngine()
    schedule = [(0.0, Request(prompt_ids=[1, 2])),
                (0.5, Request(prompt_ids=[3, 4])),
                (1.0, Request(prompt_ids=[5, 6]))]
    report = run_open_loop(engine, schedule, clock=clock, sleep=sleep)
    assert report.offered == 3 and report.submitted == 3
    assert report.completed == 3 and report.refused == 0
    assert not report.timed_out
    assert report.goodput_rps > 0


def test_run_open_loop_counts_refusals_and_never_blocks_on_them():
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731

    class Refusing(LoopEngine):
        def submit(self, request):
            if request.priority == 0:
                raise RefusalError("queue_full", "full", http_status=429)
            return super().submit(request)

    engine = Refusing()
    schedule = [(0.0, Request(prompt_ids=[1], priority=1)),
                (0.1, Request(prompt_ids=[2], priority=0)),
                (0.2, Request(prompt_ids=[3], priority=1))]
    report = run_open_loop(engine, schedule, clock=clock,
                           sleep=lambda dt: t.__setitem__(0, t[0] + dt))
    assert report.refused == 1 and report.submitted == 2
    assert report.refused_by_reason == {"queue_full": 1}
    assert report.completed == 2


def test_run_open_loop_gives_up_at_max_wall():
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731

    class Stuck(LoopEngine):
        def step(self):
            self.step_n += 1
            t[0] += 0.01                 # time passes, nothing finishes
            return []

    report = run_open_loop(Stuck(), [(0.0, Request(prompt_ids=[1]))],
                           clock=clock, sleep=lambda dt: None,
                           max_wall_s=0.5)
    assert report.timed_out
    assert report.completed == 0
