"""LoRA adapters: exactness at init, base freezing, merge, sharded plans.

Beyond the reference (full-parameter training only). The contract under
test: ``lora_bundle`` starts EXACTLY at the base function (B=0), a masked
optimizer updates only adapter leaves, ``merge_lora`` folds the deltas into
base-layout params that reproduce the wrapped model's logits, and the
adapter leaves shard consistently with their base matrices under fsdp/tp.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.models.lora import (
    lora_bundle, load_pretrained_lora, mask_optimizer, merge_lora,
    num_trainable_params)
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine


def _ids(vocab=512, shape=(2, 32)):
    return jnp.asarray(np.random.RandomState(0).randint(0, vocab, shape))


def test_lora_starts_at_base():
    base = get_model("llama-debug", dtype=jnp.float32)
    wrapped = lora_bundle(base, rank=4)
    params = wrapped.init(wrapped.config, jax.random.key(0))
    ids = _ids()
    ours = wrapped.apply(wrapped.config, params, ids)
    theirs = base.apply(base.config, params["base"], ids)
    np.testing.assert_array_equal(np.asarray(ours), np.asarray(theirs))
    assert num_trainable_params(wrapped) > 0
    assert num_trainable_params(wrapped) < base.num_params() // 10


def test_lora_freezes_base_and_trains_adapters():
    base = get_model("llama-debug", dtype=jnp.float32)
    wrapped = lora_bundle(base, rank=4, targets=("wq", "wv", "down"))
    trainer = Trainer(bundle=wrapped,
                      optimizer=mask_optimizer(adamw_cosine(1e-2)),
                      plan=make_plan("single",
                                     make_mesh(devices=jax.devices()[:1])),
                      donate=False)
    state = trainer.init_state(0)
    before = jax.tree.map(np.asarray, state.params)
    batch = {k: _ids() for k in ("input_ids", "labels")}
    state2, m = trainer.step_fn(state, batch)
    assert np.isfinite(float(m["loss"]))
    after = jax.tree.map(np.asarray, state2.params)

    # base: bit-identical (masked out of the update entirely)
    for b, a in zip(jax.tree.leaves(before["base"]),
                    jax.tree.leaves(after["base"])):
        np.testing.assert_array_equal(b, a)
    # adapters: B must move (its grad is nonzero at B=0; A's is zero there)
    moved = any(
        np.abs(b - a).max() > 0
        for b, a in zip(jax.tree.leaves(before["lora"]),
                        jax.tree.leaves(after["lora"])))
    assert moved, "no adapter leaf changed after an optimizer step"


def test_lora_merge_reproduces_wrapped_logits():
    base = get_model("llama-debug", dtype=jnp.float32)
    wrapped = lora_bundle(base, rank=4, alpha=8.0)
    params = wrapped.init(wrapped.config, jax.random.key(1))
    # give B real values so the merge is nontrivial
    params = {
        "base": params["base"],
        "lora": jax.tree.map(
            lambda x: x + 0.01 * np.random.RandomState(2).randn(*x.shape)
            .astype(np.float32), params["lora"]),
    }
    ids = _ids()
    wrapped_logits = np.asarray(wrapped.apply(wrapped.config, params, ids))
    merged = merge_lora(wrapped, params)
    merged_logits = np.asarray(base.apply(base.config, merged, ids))
    np.testing.assert_allclose(merged_logits, wrapped_logits,
                               rtol=1e-5, atol=1e-5)
    # and the adapters actually bind: merged != original base
    base_logits = np.asarray(base.apply(base.config, params["base"], ids))
    assert np.abs(merged_logits - base_logits).max() > 1e-4


def test_lora_sharded_fsdp_tp(eight_devices):
    """Adapters inherit their matrix's in/out logical axes: under tp_fsdp,
    A(wq) shards embed over fsdp and B(wq) shards heads over tp; a full
    optimizer step runs and the base stays frozen across the mesh."""
    base = get_model("llama-debug", dtype=jnp.float32, num_heads=4,
                     num_kv_heads=2)
    wrapped = lora_bundle(base, rank=4)
    plan = make_plan("tp_fsdp", make_mesh(dp=2, tp=2, fsdp=2))
    trainer = Trainer(bundle=wrapped,
                      optimizer=mask_optimizer(adamw_cosine(1e-2)),
                      plan=plan, donate=False)
    sh = trainer.param_shardings
    a_spec = sh["lora"]["wq"]["a"].spec
    b_spec = sh["lora"]["wq"]["b"].spec
    assert "fsdp" in str(a_spec), a_spec    # embed dim -> fsdp
    assert "tp" in str(b_spec), b_spec      # heads dim -> tp

    state = trainer.init_state(0)
    before = jax.tree.map(np.asarray, state.params["base"])
    batch = {k: jax.device_put(_ids(shape=(8, 32)),
                               trainer.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    state2, m = trainer.step_fn(state, batch)
    assert np.isfinite(float(m["loss"]))
    for b, a in zip(jax.tree.leaves(before),
                    jax.tree.leaves(jax.tree.map(np.asarray,
                                                 state2.params["base"]))):
        np.testing.assert_array_equal(b, a)


def test_lora_cli_flag(tmp_path, eight_devices):
    from tests.test_cli_integration import make_args
    from distributed_training_guide_tpu.train.cli import run_training

    args = make_args(tmp_path, lora_rank=4, lora_targets="wq,wv")
    out = run_training(args, lambda: make_plan("ddp", make_mesh()))
    assert np.isfinite(out["last_info"]["running_loss"])


def test_lora_pretrained_checkpoint_flow(tmp_path):
    """The standard finetune flow: convert a torch checkpoint, load the BASE
    through the sharded streaming loader + fresh adapters, verify the
    wrapped model's step-0 logits equal torch's."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from distributed_training_guide_tpu.models.hf_convert import (
        convert_hf_checkpoint)

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    base = get_model("llama-debug", vocab_size=128, dtype=jnp.float32)
    wrapped = lora_bundle(base, rank=4)
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=base)
    trainer = Trainer(bundle=wrapped,
                      optimizer=mask_optimizer(adamw_cosine(1e-3)),
                      plan=make_plan("single",
                                     make_mesh(devices=jax.devices()[:1])),
                      donate=False)
    params = load_pretrained_lora(wrapped, trainer.param_shardings,
                                  tmp_path / "conv")
    ids = np.random.RandomState(0).randint(0, 128, (2, 24))
    ours = np.asarray(wrapped.apply(wrapped.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_lora_checkpoint_resume_bit_exact(tmp_path, eight_devices):
    """Orbax save/restore through the LoRA TrainState: the multi_transform
    optimizer state (inner adam moments for adapters, empty for the frozen
    base) must round-trip, and the resumed trajectory must be bit-exact vs
    uninterrupted — the same contract every dense family has."""
    from tests.test_cli_integration import make_args
    from distributed_training_guide_tpu.train.cli import run_training

    def run(save_dir, max_steps, name):
        args = make_args(save_dir, lora_rank=4, max_steps=max_steps,
                         experiment_name=name, ckpt_freq=2)
        return run_training(args, lambda: make_plan("ddp", make_mesh()))

    golden = run(tmp_path / "a", 4, "uninterrupted")
    run(tmp_path / "b", 2, "resumed")          # stop at step 2
    resumed = run(tmp_path / "b", 4, "resumed")  # restore + continue to 4
    assert resumed["host_state"]["global_step"] == 4
    np.testing.assert_array_equal(resumed["last_info"]["running_loss"],
                                  golden["last_info"]["running_loss"])


@pytest.mark.parametrize("preset,over", [
    ("qwen3-0.6b", dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                        max_position_embeddings=128)),
    ("olmo2-7b", dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      max_position_embeddings=128)),
    ("gemma2-2b", dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                       num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                       layer_windows=(8, 0), query_pre_attn_scalar=16.0,
                       max_position_embeddings=128)),
])
def test_lora_composes_with_family_wirings(preset, over):
    """LoRA over the non-vanilla llama wirings people actually finetune:
    Qwen3 (qk-norm), OLMo-2 (post-norm), Gemma-2 (sandwich + softcaps +
    per-layer windows). Step-0 exactness, frozen base, adapters move."""
    base = get_model(preset, dtype=jnp.float32, **over)
    wrapped = lora_bundle(base, rank=4)
    params = wrapped.init(wrapped.config, jax.random.key(0))
    ids = _ids(vocab=256)
    np.testing.assert_array_equal(
        np.asarray(wrapped.apply(wrapped.config, params, ids)),
        np.asarray(base.apply(base.config, params["base"], ids)))

    trainer = Trainer(bundle=wrapped,
                      optimizer=mask_optimizer(adamw_cosine(1e-2)),
                      plan=make_plan("single",
                                     make_mesh(devices=jax.devices()[:1])),
                      donate=False)
    state = trainer.init_state(0)
    before = jax.tree.map(np.asarray, state.params)
    batch = {k: ids for k in ("input_ids", "labels")}
    state2, m = trainer.step_fn(state, batch)
    assert np.isfinite(float(m["loss"]))
    for b, a in zip(jax.tree.leaves(before["base"]),
                    jax.tree.leaves(jax.tree.map(np.asarray,
                                                 state2.params["base"]))):
        np.testing.assert_array_equal(b, a)


def test_lora_rejects_non_llama_and_bad_targets():
    with pytest.raises(ValueError, match="llama family"):
        lora_bundle(get_model("gpt2-debug"), rank=4)
    with pytest.raises(ValueError, match="unknown lora targets"):
        lora_bundle(get_model("llama-debug"), rank=4, targets=("wz",))
