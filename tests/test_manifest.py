"""Checkpoint integrity: manifest write/verify, keep-n retention, corruption
fallback, orphan sweep, and transient-save retry (ISSUE 1 tentpole part 1).

Uses a tiny hand-built pytree (not a full Trainer) wherever possible so the
mechanics are pinned without paying a model compile; the end-to-end drills on
real TrainStates live in test_chaos.py.
"""
import json
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.checkpoint import (
    CheckpointCorruptionError, CheckpointIO, load_manifest, manifest_path,
    verify_manifest, write_manifest)
from distributed_training_guide_tpu.utils.faults import corrupt_checkpoint_dir


def small_state(scale=1.0):
    return {"w": jnp.arange(16, dtype=jnp.float32) * scale,
            "b": jnp.ones((4,), jnp.float32) * scale}


def abstract_small_state():
    sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    return {"w": jax.ShapeDtypeStruct((16,), jnp.float32, sharding=sharding),
            "b": jax.ShapeDtypeStruct((4,), jnp.float32, sharding=sharding)}


def save_step(io, step, scale=None):
    host = {"epoch": 0, "global_step": step, "epoch_step": step,
            "running_loss": 0.0}
    io.save(small_state(scale if scale is not None else float(step)), host)


# ---- manifest primitives ----------------------------------------------------

def test_manifest_roundtrip_and_verify(tmp_path):
    d = tmp_path / "checkpoint-1"
    (d / "sub").mkdir(parents=True)
    (d / "a.bin").write_bytes(b"hello world" * 100)
    (d / "sub" / "b.bin").write_bytes(b"\x00" * 1000)
    write_manifest(d, 1, {"global_step": 1})
    man = load_manifest(tmp_path, "checkpoint-1")
    assert man["step"] == 1
    assert man["host_state"] == {"global_step": 1}
    assert {f["path"] for f in man["files"]} == {"a.bin", "sub/b.bin"}
    assert verify_manifest(d, man) == []

    # bit flip -> checksum mismatch (size unchanged, the nasty case)
    raw = bytearray((d / "a.bin").read_bytes())
    raw[0] ^= 0xFF
    (d / "a.bin").write_bytes(bytes(raw))
    problems = verify_manifest(d, man)
    assert problems and "checksum mismatch: a.bin" in problems[0]

    # truncation -> size mismatch reported without checksumming
    (d / "sub" / "b.bin").write_bytes(b"\x00" * 999)
    assert any("size mismatch: sub/b.bin" in p for p in verify_manifest(d, man))

    # deletion -> missing file
    (d / "a.bin").unlink()
    assert any("missing file: a.bin" in p for p in verify_manifest(d, man))


def test_load_manifest_absent_or_garbage(tmp_path):
    assert load_manifest(tmp_path, "checkpoint-9") is None
    manifest_path(tmp_path, "checkpoint-9").write_text("{not json")
    assert load_manifest(tmp_path, "checkpoint-9") is None


def test_sampled_crc_over_threshold(tmp_path, monkeypatch):
    """Files beyond SAMPLE_THRESHOLD get a size-capped sampled CRC (head +
    tail + strided interior windows) that still catches truncation and
    head/tail corruption; --checkpoint-full-crc restores the full scan."""
    from distributed_training_guide_tpu.checkpoint import manifest as mmod

    monkeypatch.setattr(mmod, "SAMPLE_THRESHOLD", 4096)
    d = tmp_path / "checkpoint-1"
    d.mkdir()
    big = bytes(range(256)) * 64          # 16 KiB > patched threshold
    (d / "big.bin").write_bytes(big)
    (d / "small.bin").write_bytes(b"tiny")
    write_manifest(d, 1, {"global_step": 1})
    man = load_manifest(tmp_path, "checkpoint-1")
    entries = {f["path"]: f for f in man["files"]}
    assert entries["big.bin"].get("crc_mode") == "sampled"
    assert 0 < entries["big.bin"]["sampled_bytes"] <= len(big)
    assert "crc_mode" not in entries["small.bin"]   # small files: full CRC
    assert verify_manifest(d, man) == []

    # head corruption is inside the first sampled window -> caught
    raw = bytearray(big)
    raw[0] ^= 0xFF
    (d / "big.bin").write_bytes(bytes(raw))
    assert any("checksum mismatch: big.bin" in p for p in verify_manifest(d, man))
    # tail corruption -> caught (last window is always sampled)
    raw = bytearray(big)
    raw[-1] ^= 0xFF
    (d / "big.bin").write_bytes(bytes(raw))
    assert any("checksum mismatch: big.bin" in p for p in verify_manifest(d, man))
    # truncation -> size mismatch, no CRC needed
    (d / "big.bin").write_bytes(big[:-10])
    assert any("size mismatch: big.bin" in p for p in verify_manifest(d, man))

    # full_crc: every entry exhaustive regardless of size
    (d / "big.bin").write_bytes(big)
    write_manifest(d, 1, {"global_step": 1}, full_crc=True)
    man_full = load_manifest(tmp_path, "checkpoint-1")
    assert all("crc_mode" not in f for f in man_full["files"])
    assert verify_manifest(d, man_full) == []


def test_sampled_crc_offsets_deterministic_in_size():
    """Verification must recompute the exact byte set from the recorded
    size alone — the offset schedule is a pure function of the size."""
    from distributed_training_guide_tpu.checkpoint.manifest import _sample_offsets

    for size in (1, 100, 1 << 20, (64 << 20) + 12345, 5 << 30):
        offs = _sample_offsets(size)
        assert offs == _sample_offsets(size)
        assert offs[0] == 0 and offs[-1] == max(size - (1 << 20), 0)
        assert all(0 <= o <= max(size - 1, 0) or o == 0 for o in offs)


# ---- retention + fallback ---------------------------------------------------

def test_keep_n_retention_chain(tmp_path):
    io = CheckpointIO(tmp_path, keep_n=2)
    for step in (1, 2, 3):
        save_step(io, step)
    io.close()
    dirs = sorted(p.name for p in tmp_path.iterdir()
                  if p.is_dir() and p.name.startswith("checkpoint-"))
    assert dirs == ["checkpoint-2", "checkpoint-3"]   # 1 pruned, 2 retained
    state = json.loads((tmp_path / "state.json").read_text())
    assert state["checkpoint"] == "checkpoint-3"
    assert state["retained"] == ["checkpoint-3", "checkpoint-2"]
    # manifests track the dirs: pruned one is gone too
    assert load_manifest(tmp_path, "checkpoint-3") is not None
    assert load_manifest(tmp_path, "checkpoint-2") is not None
    assert load_manifest(tmp_path, "checkpoint-1") is None


def test_restore_falls_back_past_corrupt_latest(tmp_path, caplog):
    io = CheckpointIO(tmp_path, keep_n=2)
    save_step(io, 1)
    save_step(io, 2)
    io.close()
    corrupt_checkpoint_dir(tmp_path / "checkpoint-2")

    io2 = CheckpointIO(tmp_path)
    restored, host = io2.restore(abstract_small_state())
    assert host["global_step"] == 1                   # fell back to step 1
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.arange(16, dtype=np.float32) * 1.0)
    assert any("skipping checkpoint checkpoint-2" in r.message
               for r in caplog.records)


def test_restore_raises_when_whole_chain_corrupt(tmp_path):
    io = CheckpointIO(tmp_path, keep_n=2)
    save_step(io, 1)
    save_step(io, 2)
    io.close()
    corrupt_checkpoint_dir(tmp_path / "checkpoint-1")
    corrupt_checkpoint_dir(tmp_path / "checkpoint-2")
    with pytest.raises(CheckpointCorruptionError, match="checkpoint-2"):
        CheckpointIO(tmp_path).restore(abstract_small_state())


def test_restore_legacy_state_json_without_manifest(tmp_path):
    """Pre-retention layouts (state.json with only `checkpoint`, no manifest
    file) must keep restoring — upgrades can't strand old experiments."""
    io = CheckpointIO(tmp_path, keep_n=1)
    save_step(io, 4)
    io.close()
    manifest_path(tmp_path, "checkpoint-4").unlink()
    state = json.loads((tmp_path / "state.json").read_text())
    del state["retained"]
    (tmp_path / "state.json").write_text(json.dumps(state))

    io2 = CheckpointIO(tmp_path)
    assert io2.can_resume()
    restored, host = io2.restore(abstract_small_state())
    assert host["global_step"] == 4
    assert "checkpoint" not in host and "retained" not in host


# ---- orphan sweep -----------------------------------------------------------

def test_orphan_sweep_on_first_save(tmp_path):
    """A dir committed by Orbax but never referenced by state.json (crash
    between save and finalize) is collected when the next WRITER starts
    saving; referenced dirs and non-checkpoint entries are untouched."""
    io = CheckpointIO(tmp_path, keep_n=2)
    save_step(io, 1)
    io.close()
    orphan = tmp_path / "checkpoint-99"
    orphan.mkdir()
    (orphan / "shard").write_bytes(b"x" * 64)
    write_manifest(orphan, 99, {"global_step": 99})
    stray_manifest = manifest_path(tmp_path, "checkpoint-77")
    stray_manifest.write_text("{}")
    keepme = tmp_path / "not-a-checkpoint"
    keepme.mkdir()

    io2 = CheckpointIO(tmp_path, keep_n=2)
    assert orphan.exists()                  # opening an IO deletes NOTHING
    save_step(io2, 2)
    io2.close()
    assert not orphan.exists()
    assert not manifest_path(tmp_path, "checkpoint-99").exists()
    assert not stray_manifest.exists()
    assert (tmp_path / "checkpoint-1").exists()       # retained: kept
    assert (tmp_path / "checkpoint-2").exists()
    assert keepme.exists()


def test_restore_only_consumer_never_deletes(tmp_path):
    """A read-only CheckpointIO (export / engine load / crash inspection)
    must not collect unreferenced dirs: to a reader, an in-flight async
    save from a live writer is indistinguishable from an orphan."""
    io = CheckpointIO(tmp_path, keep_n=2)
    save_step(io, 1)
    io.close()
    inflight = tmp_path / "checkpoint-50"   # committed, not yet published
    inflight.mkdir()
    (inflight / "shard").write_bytes(b"y")
    reader = CheckpointIO(tmp_path)
    _, host = reader.restore(abstract_small_state())
    assert host["global_step"] == 1
    assert inflight.exists()                # untouched by init + restore


# ---- save retry -------------------------------------------------------------

def test_save_retries_transient_fs_errors(tmp_path, monkeypatch):
    io = CheckpointIO(tmp_path, save_retries=2, retry_backoff_s=0.01)
    real_save = io._checkpointer.save
    calls = {"n": 0}

    def flaky_save(path, state, **kw):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError("simulated EIO: lost NFS lease")
        return real_save(path, state, **kw)

    monkeypatch.setattr(io._checkpointer, "save", flaky_save)
    save_step(io, 1)
    io.close()
    assert calls["n"] == 3                            # 2 failures + success
    restored, host = CheckpointIO(tmp_path).restore(abstract_small_state())
    assert host["global_step"] == 1


def test_save_retry_budget_exhausted_raises(tmp_path, monkeypatch):
    io = CheckpointIO(tmp_path, save_retries=1, retry_backoff_s=0.01)

    def always_fail(path, state, **kw):
        raise OSError("disk on fire")

    monkeypatch.setattr(io._checkpointer, "save", always_fail)
    with pytest.raises(OSError, match="disk on fire"):
        save_step(io, 1)
    assert not (tmp_path / "state.json").exists()     # nothing published
