"""Streaming request layer (serve/api.py + scheduler deadlines and
priorities): per-token SSE delivery with TTFT < total latency (the
acceptance pin), clean deadline eviction at iteration boundaries,
priority-ordered admission, structured refusal bodies (429/400 with
reason + queue depth), and the lock-free /healthz metrics snapshot.
"""
import dataclasses
import http.client
import json
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.serve import (RefusalError, Request,
                                                  ServeEngine)
from distributed_training_guide_tpu.serve.api import generate_many, serve_http

pytestmark = [pytest.mark.serve, pytest.mark.stream]


@pytest.fixture(scope="module")
def llama():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    return bundle, bundle.init(bundle.config, jax.random.key(0))


def _fresh(req):
    return dataclasses.replace(req, request_id=None)


def _read_sse_events(resp):
    """Read SSE events (with client-side arrival timestamps) until the
    stream closes; http.client decodes the chunked framing."""
    events = []
    buf = b""
    while True:
        line = resp.readline()
        if not line:
            break
        buf += line
        if buf.endswith(b"\n\n"):
            for part in buf.strip().split(b"\n"):
                if part.startswith(b"data: "):
                    events.append((time.monotonic(),
                                   json.loads(part[len(b"data: "):])))
            buf = b""
    return events


# ---- streaming --------------------------------------------------------------

def test_partial_tokens_mid_generation(llama):
    """The engine-level half of the TTFT pin, deterministically: after
    the prefill iteration the first token is already visible through
    ``partial_tokens`` while the request is still generating."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=32)
    rid = eng.submit(Request(prompt_ids=[3, 17, 42], max_new_tokens=8))
    eng.step()                       # admission + prefill + first sample
    assert eng.has_work, "request must still be generating"
    partial = eng.partial_tokens()
    assert rid in partial and len(partial[rid]) >= 1
    full = []
    while eng.has_work:
        full.extend(eng.step())
    assert full[0].generated_ids[:len(partial[rid])] == partial[rid], \
        "streamed prefix must be exactly the final tokens' prefix"


def test_streaming_sse_first_token_before_completion(llama):
    """The acceptance pin: the streaming endpoint delivers one SSE event
    per token, the FIRST of them strictly before the stream completes,
    the server-side TTFT strictly below total latency, and the streamed
    ids equal the non-streaming (batch-1) generation."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=64)
    server, worker = serve_http(eng, port=0)
    port = server.server_address[1]
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        conn.request("POST", "/generate", json.dumps(
            {"prompt_ids": [3, 17, 42], "max_new_tokens": 16,
             "stream": True}))
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type") == "text/event-stream"
        events = _read_sse_events(resp)
        conn.close()

        token_events = [(t, e) for t, e in events if "token_id" in e]
        done_events = [(t, e) for t, e in events if e.get("done")]
        assert len(token_events) == 16
        assert len(done_events) == 1
        t_done, done = done_events[0]
        t_first = token_events[0][0]
        assert t_first < t_done, \
            "first token event must arrive before the stream completes"
        assert 0 < done["ttft_s"] < done["latency_s"], \
            f"TTFT {done['ttft_s']} must undercut latency " \
            f"{done['latency_s']}"
        assert [e["token_id"] for _, e in token_events] \
            == done["generated_ids"]
        # and the streamed generation is the same math as offline batch-1
        offline = generate_many(
            ServeEngine(bundle, params, n_slots=1, page_size=4,
                        max_len=64),
            [Request(prompt_ids=[3, 17, 42], max_new_tokens=16)])
        assert done["token_ids"] == offline[0].token_ids
    finally:
        server.shutdown()
        worker.stop()


def test_result_carries_ttft_and_itl(llama):
    """Every RequestResult prices the streaming metrics, streamed or
    not: 0 < ttft_s < latency_s and a finite mean inter-token gap."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=32)
    res = generate_many(eng, [Request(prompt_ids=[3, 17],
                                      max_new_tokens=6)])[0]
    assert 0 < res.ttft_s < res.latency_s
    assert 0 < res.itl_s < res.latency_s
    stats = eng.stats()
    assert stats["ttft_s_avg"] > 0 and stats["itl_s_avg"] > 0


# ---- deadlines --------------------------------------------------------------

def test_deadline_expires_cleanly_at_iteration_boundary(llama):
    """A running request past its deadline is evicted CLEANLY: partial
    tokens returned with finish_reason 'deadline' (a strict prefix of
    its batch-1 generation), pages freed, and a co-resident request is
    untouched."""
    bundle, params = llama
    full = generate_many(
        ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=64),
        [Request(prompt_ids=[3, 17, 42], max_new_tokens=24)])[0]

    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=64)
    rid_dead = eng.submit(Request(prompt_ids=[3, 17, 42],
                                  max_new_tokens=24, deadline_s=1e-6))
    rid_live = eng.submit(Request(prompt_ids=[5, 6], max_new_tokens=6))
    done = {}
    it = 0
    while eng.has_work:
        for r in eng.step():
            done[r.request_id] = r
        it += 1
        assert it < 500
    dead = done[rid_dead]
    assert dead.finish_reason == "deadline"
    assert len(dead.generated_ids) < 24
    n = len(dead.generated_ids)
    assert dead.generated_ids == full.generated_ids[:n], \
        "deadline eviction must return a clean prefix, never garbage"
    assert done[rid_live].finish_reason == "length"
    assert done[rid_live].token_ids == generate_many(
        ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=64),
        [Request(prompt_ids=[5, 6], max_new_tokens=6)])[0].token_ids
    assert eng.stats()["deadline_expired"] == 1
    pool = eng.scheduler.pool
    assert pool.n_free + eng.scheduler.cache_pages_held() == pool.capacity


def test_queued_deadline_expires_without_admission():
    """A QUEUED entry past its deadline leaves the queue at the boundary
    without ever taking a slot or a page — scheduler-level, fake clock."""
    from distributed_training_guide_tpu.serve import PagePool, Scheduler

    now = [0.0]
    sched = Scheduler(n_slots=1, pool=PagePool(8, 4), max_len=16,
                      max_pages_per_slot=4, clock=lambda: now[0])
    sched.submit(Request(prompt_ids=[1, 2], max_new_tokens=4,
                         deadline_s=5.0))
    now[0] = 6.0
    results = sched.expire_deadlines()
    assert len(results) == 1
    assert results[0].finish_reason == "deadline"
    assert results[0].generated_ids == []
    assert not sched.queue and sched.pool.n_free == sched.pool.capacity


# ---- priorities -------------------------------------------------------------

def test_priority_orders_admission_fifo_within_class(llama):
    """With one slot busy, a later high-priority submit overtakes earlier
    low-priority ones; equal priorities stay FIFO. (Admission order is
    observed through admitted_at.)"""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=32)
    running = eng.submit(Request(prompt_ids=[9, 9], max_new_tokens=8))
    eng.step()                      # occupy the only slot
    low_a = eng.submit(Request(prompt_ids=[3], max_new_tokens=2))
    low_b = eng.submit(Request(prompt_ids=[4], max_new_tokens=2))
    high = eng.submit(Request(prompt_ids=[5], max_new_tokens=2,
                              priority=5))
    done = {}
    it = 0
    while eng.has_work:
        for r in eng.step():
            done[r.request_id] = r
        it += 1
        assert it < 500
    assert done[high].admitted_at < done[low_a].admitted_at \
        < done[low_b].admitted_at
    assert done[running].finish_reason == "length"


def test_preemption_victim_is_lowest_priority_youngest():
    """Scheduler-level: growth under exhaustion preempts the lowest
    priority first (youngest within a class), never the high-priority
    grower."""
    from distributed_training_guide_tpu.serve import PagePool, Scheduler

    pool = PagePool(7, 4)           # 6 usable
    sched = Scheduler(n_slots=3, pool=pool, max_len=32,
                      max_pages_per_slot=8, prefix_cache=False)
    for seed, prio in ((0, 5), (1, 0), (2, 0)):
        sched.submit(Request(prompt_ids=[seed + 1] * 7, max_new_tokens=8,
                             priority=prio))
    adms = sched.try_admit()
    assert len(adms) == 3           # 2 pages each = 6 pages, pool full
    for adm in adms:
        sched.commit_tokens(adm.slot_idx, 7)
    # every slot's 8th token crosses into page 3: growth must preempt —
    # the victim must be a priority-0 sequence (youngest first), never
    # the priority-5 one, which must survive with its grown page
    for slot in sched.slots:
        slot.cache_len = 8
    sched.grow_for_decode()
    live = [s for s in sched.slots if s is not None]
    assert any(s.request.priority == 5 for s in live), \
        "the high-priority sequence must survive growth pressure"
    assert sched.stats["preempted"] >= 1
    assert sched.queue and \
        all(e.request.priority == 0 for e in sched.queue), \
        "every preempted entry must be a priority-0 sequence"


# ---- refusals ---------------------------------------------------------------

def test_refusal_bodies_carry_reason_and_queue_depth(llama):
    """HTTP refusals are structured: 429 for backpressure (queue_full)
    and 400 for impossible requests, with machine-readable reason +
    queue depth in the body; stats count refusals by reason."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=16,
                      max_queue=2)
    server, worker = serve_http(eng, port=0)
    port = server.server_address[1]
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)

        def post(payload):
            conn.request("POST", "/generate", json.dumps(payload))
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read())

        status, body = post({"prompt_ids": [], "max_new_tokens": 2})
        assert status == 400 and body["reason"] == "empty_prompt"
        assert "queue_depth" in body
        status, body = post({"prompt_ids": [3] * 20,
                             "max_new_tokens": 20})
        assert status == 400 and body["reason"] == "context_too_long"

        # 8 near-simultaneous clients against max_queue=2 and one slot:
        # admission drains at most one per iteration, so a burst must
        # split into served 200s and 429 backpressure refusals
        outcomes = []
        outcomes_lock = threading.Lock()

        def client(seed):
            c = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
            c.request("POST", "/generate", json.dumps(
                {"prompt_ids": [3 + seed, 17], "max_new_tokens": 12,
                 "seed": seed}))
            resp = c.getresponse()
            body = json.loads(resp.read())
            with outcomes_lock:
                outcomes.append((resp.status, body))
            c.close()

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        conn.close()
        served = [b for s, b in outcomes if s == 200]
        refused_429 = [b for s, b in outcomes if s == 429]
        assert len(served) + len(refused_429) == 8
        assert served, "some burst requests must be served"
        assert refused_429, "bounded queue never produced a 429"
        for body in refused_429:
            assert body["reason"] == "queue_full"
            assert body["queue_depth"] >= 2
        refused = eng.stats()["refused"]
        assert refused["empty_prompt"] == 1
        assert refused["context_too_long"] == 1
        assert refused["queue_full"] == len(refused_429)
    finally:
        server.shutdown()
        worker.stop()


def test_refusal_error_surface(llama):
    """Library-level: RefusalError carries reason/status/detail, and the
    engine's vocab check routes through it."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=16,
                      max_queue=1)
    with pytest.raises(RefusalError) as exc_info:
        eng.submit(Request(prompt_ids=[bundle.config.vocab_size]))
    assert exc_info.value.reason == "bad_prompt"
    assert exc_info.value.http_status == 400
    eng.submit(Request(prompt_ids=[3], max_new_tokens=2))
    with pytest.raises(RefusalError) as exc_info:
        eng.submit(Request(prompt_ids=[4], max_new_tokens=2))
    assert exc_info.value.reason == "queue_full"
    assert exc_info.value.http_status == 429
    assert exc_info.value.detail["queue_depth"] == 1


# ---- lock-free health -------------------------------------------------------

def test_healthz_answers_while_engine_lock_is_held(llama):
    """/healthz must not block on the engine lock (the run loop holds it
    for a whole decode iteration): hold the lock from the test and
    require a timely, complete health response."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16)
    server, worker = serve_http(eng, port=0)
    port = server.server_address[1]
    try:
        with worker.lock:      # simulate an in-flight decode iteration
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
            t0 = time.monotonic()
            conn.request("GET", "/healthz")
            health = json.loads(conn.getresponse().read())
            elapsed = time.monotonic() - t0
            conn.close()
        assert elapsed < 5.0
        assert health["ok"] is True
        # the full stats snapshot rides the probe
        for key in ("queued", "pool_occupancy", "prefix_hit_rate",
                    "pages_free", "ttft_s_avg", "refused"):
            assert key in health
    finally:
        server.shutdown()
        worker.stop()
