"""Data loader determinism/resume + checkpoint round-trip tests."""
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from distributed_training_guide_tpu.data import ShardedBatchLoader
from distributed_training_guide_tpu.data.pipeline import synthetic_dataset
from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine
from distributed_training_guide_tpu.checkpoint import CheckpointIO, abstract_train_state
from distributed_training_guide_tpu.train.state import host_state_dict


def _loader(plan, gb=8, accum=1):
    data = synthetic_dataset(10_000, 512, 16, seed=3)
    ndim = 3 if accum > 1 else 2
    if accum > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(plan.mesh, P(None, *plan.batch_spec(2)))
    else:
        sharding = plan.batch_sharding(2)
    return ShardedBatchLoader(data, gb, sharding, grad_accum=accum, seed=0)


def test_loader_deterministic_and_resume(eight_devices):
    plan = make_plan("ddp", make_mesh())
    loader = _loader(plan)
    a = [np.asarray(b["input_ids"]) for b in loader.epoch_batches()]
    b = [np.asarray(b["input_ids"]) for b in loader.epoch_batches()]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    # resume from step 3 reproduces the tail exactly (reference 01:133-135)
    c = [np.asarray(b["input_ids"]) for b in loader.epoch_batches(start_step=3)]
    for x, y in zip(a[3:], c):
        np.testing.assert_array_equal(x, y)
    # different epoch reshuffles
    loader.set_epoch(1)
    d = next(iter(loader.epoch_batches()))
    assert not np.array_equal(a[0], np.asarray(d["input_ids"]))


def test_loader_grad_accum_reshape_matches_flat(eight_devices):
    """grad_accum>1 reshapes each global batch to [A, B/A, S] with a
    leading scanned microbatch axis — same rows, same order as the flat
    batch, just refactored (pins _assemble_batch's leading-shape path)."""
    plan = make_plan("ddp", make_mesh())
    flat = _loader(plan, gb=16, accum=1)
    accum = _loader(plan, gb=16, accum=2)   # microbatch 8 = dp size
    for fb, ab in zip(flat.epoch_batches(), accum.epoch_batches()):
        f = np.asarray(fb["input_ids"])
        a = np.asarray(ab["input_ids"])
        assert a.shape == (2, 8, 16)
        np.testing.assert_array_equal(a.reshape(16, 16), f)


def test_loader_sharded_batch(eight_devices):
    plan = make_plan("ddp", make_mesh())
    loader = _loader(plan)
    batch = next(iter(loader.epoch_batches()))
    ids = batch["input_ids"]
    assert ids.shape == (8, 16)
    assert ids.addressable_shards[0].data.shape == (1, 16)  # 8-way batch shard


class _CountingDataset:
    """Proxy recording every row-fetch the loader makes — the observable
    for 'materializes only addressable shard rows' (VERDICT r3 item 6)."""

    def __init__(self, arr):
        self._arr = arr
        self.requests: list = []

    @property
    def shape(self):
        return self._arr.shape

    @property
    def dtype(self):
        return self._arr.dtype

    def __len__(self):
        return len(self._arr)

    def __getitem__(self, key):
        if isinstance(key, np.ndarray):
            self.requests.append(int(key.size))
        return self._arr[key]


def test_loader_fetches_per_shard_not_per_batch(eight_devices):
    """Batch assembly fancy-indexes the dataset once per addressable shard
    (1 row each on the 8-way mesh), never materializing the global batch as
    one fetch — the property that makes per-host footprint ~1/dp when
    processes own disjoint shards (pinned cross-process by
    test_multiprocess.py::test_gang_loader_materializes_only_local_shards)."""
    plan = make_plan("ddp", make_mesh())
    proxy = _CountingDataset(synthetic_dataset(10_000, 512, 16, seed=3))
    loader = ShardedBatchLoader(proxy, 8, plan.batch_sharding(2), seed=0)
    batch = next(iter(loader.epoch_batches()))
    assert batch["input_ids"].shape == (8, 16)
    assert proxy.requests and max(proxy.requests) == 1  # per-shard fetches


def test_mmap_corpus_and_zero_copy_native(tmp_path, eight_devices):
    """--mmap-data path: the spilled corpus round-trips exactly, re-spilling
    is a cache hit, loader output is unchanged vs the in-RAM array, and the
    native loader mmaps the backing file directly (no temp copy)."""
    from distributed_training_guide_tpu.data.pipeline import load_and_preprocess_data

    plain = load_and_preprocess_data("synthetic:50000", None, 16, seed=3)
    data = load_and_preprocess_data("synthetic:50000", None, 16, seed=3,
                                    mmap_dir=tmp_path)
    assert isinstance(data, np.memmap)
    np.testing.assert_array_equal(np.asarray(data), plain)
    backing = Path(data.filename)
    stamp = backing.stat().st_mtime_ns
    again = load_and_preprocess_data("synthetic:50000", None, 16, seed=3,
                                     mmap_dir=tmp_path)
    assert Path(again.filename) == backing
    assert backing.stat().st_mtime_ns == stamp      # reused, not rewritten

    plan = make_plan("ddp", make_mesh())
    sharding = plan.batch_sharding(2)
    mm_batches = [np.asarray(b["input_ids"]) for b in
                  ShardedBatchLoader(data, 8, sharding, seed=0).epoch_batches()]
    ram_batches = [np.asarray(b["input_ids"]) for b in
                   ShardedBatchLoader(plain, 8, sharding, seed=0).epoch_batches()]
    for x, y in zip(mm_batches, ram_batches):
        np.testing.assert_array_equal(x, y)

    # zero-copy native: the loader must reuse the backing file in place
    mm_loader = ShardedBatchLoader(data, 8, sharding, seed=0, native=True)
    if mm_loader._native is not None:              # g++ present
        assert mm_loader._native_path is None      # no temp copy written
        copy_loader = ShardedBatchLoader(plain, 8, sharding, seed=0, native=True)
        assert copy_loader._native_path is not None  # RAM array still copies
        a = [np.asarray(b["input_ids"]) for b in mm_loader.epoch_batches()]
        b = [np.asarray(c["input_ids"]) for c in copy_loader.epoch_batches()]
        assert len(a) == len(b) > 0
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        mm_loader.close()
        copy_loader.close()


def test_checkpoint_roundtrip_resharded(tmp_path, eight_devices):
    """Save under fsdp sharding, restore under tp sharding — covers the
    reference's sharded-DCP format plus elastic re-sharding on resume."""
    bundle = get_model("llama-debug", dtype=jnp.float32)
    opt = adamw_cosine(1e-3)
    t1 = Trainer(bundle=bundle, optimizer=opt,
                 plan=make_plan("fsdp", make_mesh(fsdp=8)), donate=False)
    state = t1.init_state(0)
    batch_sh = t1.batch_shardings()
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 512, (8, 16)))
    batch = {k: jax.device_put(ids, batch_sh[k]) for k in ("input_ids", "labels")}
    state, _ = t1.step_fn(state, batch)

    io = CheckpointIO(tmp_path / "exp")
    host = host_state_dict()
    host["global_step"] = 1
    io.save(state, host)
    assert io.can_resume()

    t2 = Trainer(bundle=bundle, optimizer=opt,
                 plan=make_plan("tp", make_mesh(tp=4)), donate=False)
    restored, host2 = io.restore(abstract_train_state(t2))
    assert host2["global_step"] == 1
    assert int(restored.step) == 1
    for a, b in zip(jax.tree.leaves(jax.device_get(state.params)),
                    jax.tree.leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # restored state must be immediately trainable under the new plan
    batch2 = {k: jax.device_put(ids, t2.batch_shardings()[k]) for k in ("input_ids", "labels")}
    _, metrics = t2.step_fn(restored, batch2)
    assert np.isfinite(float(metrics["loss"]))


def test_elastic_world_size_resume(tmp_path, eight_devices):
    """Dynamic world size (reference: torchrun --nnodes=1:4,
    related-topics/elastic-training/README.md:10-16): train on 8 devices,
    lose half the pod, resume on 4 — the restart builds its mesh from the
    live devices, ``abstract_train_state`` targets the NEW shardings, and
    Orbax re-slices the checkpoint into them. The continued trajectory must
    match the uninterrupted 8-device run (same global batch), not merely
    produce finite losses."""
    bundle = get_model("llama-debug", dtype=jnp.float32)
    opt = adamw_cosine(1e-3)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 512, (8, 16)))

    def step(t, state, n):
        batch = {k: jax.device_put(ids, t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = []
        for _ in range(n):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
        return state, losses

    # golden: 4 uninterrupted steps on the full 8-device mesh
    tg = Trainer(bundle=bundle, optimizer=opt,
                 plan=make_plan("fsdp", make_mesh(fsdp=8)), donate=False)
    _, golden = step(tg, tg.init_state(0), 4)

    # elastic: 2 steps on 8 devices, checkpoint, "lose" 4 devices, resume
    t8 = Trainer(bundle=bundle, optimizer=opt,
                 plan=make_plan("fsdp", make_mesh(fsdp=8)), donate=False)
    state, first = step(t8, t8.init_state(0), 2)
    io = CheckpointIO(tmp_path / "exp")
    host = host_state_dict()
    host["global_step"] = 2
    io.save(state, host)

    t4 = Trainer(bundle=bundle, optimizer=opt,
                 plan=make_plan("fsdp",
                                make_mesh(devices=jax.devices()[:4], fsdp=4)),
                 donate=False)
    restored, host2 = io.restore(abstract_train_state(t4))
    assert host2["global_step"] == 2
    leaf = jax.tree.leaves(restored.params)[0]
    assert len(leaf.sharding.mesh.devices.ravel()) == 4  # really resharded
    _, cont = step(t4, restored, 2)
    np.testing.assert_allclose(first + cont, golden, rtol=2e-4)


def test_async_checkpoint(tmp_path, eight_devices):
    """Async save: state.json publishes only at finalize; an unflushed save
    is invisible (the previous checkpoint stays resumable)."""
    bundle = get_model("llama-debug", dtype=jnp.float32)
    opt = adamw_cosine(1e-3)
    t = Trainer(bundle=bundle, optimizer=opt,
                plan=make_plan("fsdp", make_mesh(fsdp=8)), donate=False)
    state = t.init_state(0)

    io = CheckpointIO(tmp_path / "exp", async_save=True)
    h1 = host_state_dict()
    h1["global_step"] = 1
    io.save(state, h1)            # in flight; not yet published
    io.flush()
    assert io.can_resume()

    h2 = host_state_dict()
    h2["global_step"] = 2
    io.save(state, h2)            # in flight, never flushed
    # a new reader (crash simulation) must still see step 1
    io2 = CheckpointIO(tmp_path / "exp")
    restored, host = io2.restore(abstract_train_state(t))
    assert host["global_step"] == 1
    io.close()                    # now step 2 publishes
    _, host = io2.restore(abstract_train_state(t))
    assert host["global_step"] == 2


def test_checkpoint_roundtrip_with_host_offload(tmp_path, eight_devices):
    """Orbax restore honors pinned_host storage shardings (offloaded state
    checkpoints and resumes like device state)."""
    import jax.numpy as jnp

    from distributed_training_guide_tpu.checkpoint import (CheckpointIO,
                                                           abstract_train_state)
    from distributed_training_guide_tpu.models import get_model
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan
    from distributed_training_guide_tpu.train import Trainer, adamw_cosine
    from distributed_training_guide_tpu.train.state import host_state_dict

    bundle = get_model("llama-debug", dtype=jnp.float32)
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan("fsdp", make_mesh(fsdp=8)), donate=False,
                offload_opt_state=True, offload_params=True)
    state = t.init_state(0)
    ids = np.random.RandomState(0).randint(0, 512, (8, 32))
    batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    state, m1 = t.step_fn(state, batch)

    io = CheckpointIO(tmp_path / "off")
    io.save(state, host_state_dict())
    io.close()
    restored, _ = CheckpointIO(tmp_path / "off").restore(abstract_train_state(t))
    assert restored.params["final_norm"].sharding.memory_kind == "pinned_host"
    # bit-exact resume: the next step from restored state matches
    _, ma = t.step_fn(state, batch)
    _, mb = t.step_fn(restored, batch)
    assert float(ma["loss"]) == float(mb["loss"])
