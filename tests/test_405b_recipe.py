"""C8 (405B recipe) evidence without 405B hardware: the REAL Llama-3.1-405B
training step — actual config (126 layers, hidden 16384, vocab 128256), the
chapter-05 fsdp x tp plan, remat, bf16 compute — must trace and SPMD-lower
on the virtual 8-device mesh with fully abstract parameters. This catches
shape/sharding/partitioning bugs in the recipe (the class round 1 hit as an
XLA partitioner CHECK) while materializing zero bytes of the 1.6 TB state.

Reference counterpart: ``05-training-llama-405b/train_llm.py`` (the recipe
itself; the reference has no analogous pre-flight check).
"""
import jax
import numpy as np

from distributed_training_guide_tpu.checkpoint import abstract_train_state
from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine


def test_405b_train_step_lowers(eight_devices):
    bundle = get_model("llama-3.1-405b")
    plan = make_plan("tp_fsdp", make_mesh(tp=2, fsdp=4))
    trainer = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-4), plan=plan,
                      remat=True, remat_policy="attn", donate=False)

    state = abstract_train_state(trainer)
    seq, global_batch = 4096, 8
    batch = {
        k: jax.ShapeDtypeStruct((global_batch, seq), np.int32, sharding=sh)
        for k, sh in trainer.batch_shardings().items()
    }
    lowered = trainer.step_fn.lower(state, batch)

    # the 405B embedding table's shard spec must make it into the lowered
    # program: [V, E] with vocab over tp and embed over fsdp appears as a
    # shardy annotation (this is what a rules-table regression would drop)
    text = lowered.as_text()
    assert '[{"tp"}, {"fsdp"}]' in text, "embed table sharding missing"
    assert text.count("sdy.sharding") > 100  # every param leaf is annotated
    n_params = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(state.params))
    assert abs(n_params - 405.8e9) / 405.8e9 < 0.01
