"""C8 (405B recipe) evidence without 405B hardware: the REAL Llama-3.1-405B
training step — actual config (126 layers, hidden 16384, vocab 128256), the
chapter-05 fsdp x tp plan, remat, bf16 compute — must trace and SPMD-lower
on the virtual 8-device mesh with fully abstract parameters. This catches
shape/sharding/partitioning bugs in the recipe (the class round 1 hit as an
XLA partitioner CHECK) while materializing zero bytes of the 1.6 TB state.

Reference counterpart: ``05-training-llama-405b/train_llm.py`` (the recipe
itself; the reference has no analogous pre-flight check).
"""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.checkpoint import abstract_train_state
from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.utils import hlo as hlo_util
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine


def test_405b_train_step_lowers(eight_devices):
    bundle = get_model("llama-3.1-405b")
    plan = make_plan("tp_fsdp", make_mesh(tp=2, fsdp=4))
    trainer = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-4), plan=plan,
                      remat=True, remat_policy="attn", donate=False)

    state = abstract_train_state(trainer)
    seq, global_batch = 4096, 8
    batch = {
        k: jax.ShapeDtypeStruct((global_batch, seq), np.int32, sharding=sh)
        for k, sh in trainer.batch_shardings().items()
    }
    lowered = trainer.step_fn.lower(state, batch)

    # the 405B embedding table's shard spec must make it into the lowered
    # program: [V, E] with vocab over tp and embed over fsdp appears as a
    # shardy annotation (this is what a rules-table regression would drop)
    text = lowered.as_text()
    assert '[{"tp"}, {"fsdp"}]' in text, "embed table sharding missing"
    assert text.count("sdy.sharding") > 100  # every param leaf is annotated
    n_params = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(state.params))
    assert abs(n_params - 405.8e9) / 405.8e9 < 0.01


def test_405b_weight_logistics_at_reduced_scale(tmp_path, eight_devices):
    """The 405B recipe's weight logistics exercised END TO END at reduced
    scale (VERDICT r3 item 4): a multi-file sharded safetensors checkpoint
    (>=4 shards, like the real 191-file 405B export) through the REAL
    ``convert_llama.py`` CLI, loaded via the REAL chapter-05 entry point's
    ``--pretrained`` on the fsdp x tp mesh — plus logits parity of the
    sharded load against torch. Reference counterpart:
    ``05-training-llama-405b/train_llm.py:74-146`` (download + rank-0 load +
    broadcast)."""
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")

    # HF twin of the llama-debug registry preset
    hf_cfg = transformers.LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf", safe_serialization=True,
                          max_shard_size="100KB")
    shards = sorted((tmp_path / "hf").glob("*.safetensors"))
    assert len(shards) >= 4, [s.name for s in shards]     # genuinely multi-file

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    conv = subprocess.run(
        [sys.executable, os.path.join(repo, "05-training-llama-405b",
                                      "convert_llama.py"),
         str(tmp_path / "hf"), str(tmp_path / "conv"), "llama-debug"],
        capture_output=True, text=True, timeout=600, env=dict(
            os.environ, JAX_PLATFORMS="cpu"))
    assert conv.returncode == 0, conv.stderr[-3000:]
    assert (tmp_path / "conv" / "manifest.json").exists()

    # sharded load on the chapter's fsdp x tp mesh: logits parity vs torch
    from distributed_training_guide_tpu.models.hf_convert import load_pretrained

    bundle = get_model("llama-debug", dtype=np.float32)
    plan = make_plan("tp_fsdp", make_mesh(tp=2, fsdp=4))
    shapes = jax.eval_shape(lambda: bundle.init(bundle.config, jax.random.key(0)))
    shardings = plan.param_shardings(bundle.param_logical_axes(bundle.config),
                                     shapes)
    params = load_pretrained(bundle, shardings, tmp_path / "conv")
    wq = params["layers"]["attn"]["wq"]
    assert any(s is not None for s in wq.sharding.spec)   # actually sharded
    ids = np.random.RandomState(0).randint(0, 512, (2, 24))
    ours = np.asarray(bundle.apply(bundle.config, params, ids))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)

    # the real chapter-05 entry: --pretrained + training steps on that mesh
    run = subprocess.run(
        [sys.executable, os.path.join(repo, "05-training-llama-405b",
                                      "train_llm.py"),
         "-m", "llama-debug", "-d", "synthetic:60000", "-s", "64", "-b", "1",
         "--tensor-parallel", "2", "--num-epochs", "1", "--log-freq", "1",
         "--max-steps", "2", "--save-dir", str(tmp_path / "out"),
         "--pretrained", str(tmp_path / "conv")],
        capture_output=True, text=True, timeout=600, cwd=repo,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 XLA_FLAGS="--xla_force_host_platform_device_count=8"))
    out = run.stdout + run.stderr
    assert run.returncode == 0, out[-3000:]
    assert "Loading pretrained weights" in out
    assert "running_loss" in out


_RSS_SCRIPT = """
import gc, json, os, threading, time

import numpy as np
import torch
import transformers

import distributed_training_guide_tpu  # asserts cpu platform
from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.models.hf_convert import convert_hf_checkpoint

OUT = os.environ["RSS_TMP"]

# ~190 MB of fp32 weights; largest single tensor ~12.6 MB (embed/lm_head)
kw = dict(vocab_size=4096, hidden_size=768, intermediate_size=2048,
          num_layers=6, num_heads=8, num_kv_heads=4,
          max_position_embeddings=256)
hf_cfg = transformers.LlamaConfig(
    num_hidden_layers=kw["num_layers"], num_attention_heads=kw["num_heads"],
    num_key_value_heads=kw["num_kv_heads"], tie_word_embeddings=False,
    **{k: kw[k] for k in ("vocab_size", "hidden_size", "intermediate_size",
                          "max_position_embeddings")})
torch.manual_seed(0)
model = transformers.LlamaForCausalLM(hf_cfg)
model.save_pretrained(os.path.join(OUT, "hf"), safe_serialization=True)
total_bytes = sum(p.numel() * 4 for p in model.parameters())
del model
gc.collect()


def rss_anon() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("RssAnon"):
                return int(line.split()[1]) * 1024
    return -1


baseline = rss_anon()
peak = [baseline]
stop = threading.Event()


def sampler():
    while not stop.is_set():
        peak[0] = max(peak[0], rss_anon())
        time.sleep(0.005)


t = threading.Thread(target=sampler, daemon=True)
t.start()
bundle = get_model("llama-debug", **kw)
convert_hf_checkpoint(os.path.join(OUT, "hf"), os.path.join(OUT, "conv"),
                      bundle=bundle)
stop.set()
t.join()
print("RSS:" + json.dumps({"total_bytes": total_bytes, "baseline": baseline,
                           "peak_delta": peak[0] - baseline}))
"""


def test_405b_conversion_streams_one_tensor_at_a_time(tmp_path):
    """The converter's 'peak host RAM is one tensor' claim, measured: over a
    ~190 MB model, peak ANON rss during conversion grows by no more than a
    few tensors (<60 MB) — never the model. (Anon rss is the right meter:
    the output memmap's dirty pages are file-backed and reclaimable; the
    reference's rank-0 full state dict is anonymous RAM, all 764 GB of it.)"""
    env = dict(os.environ, JAX_PLATFORMS="cpu", RSS_TMP=str(tmp_path))
    proc = subprocess.run([sys.executable, "-c", _RSS_SCRIPT], env=env,
                          capture_output=True, text=True, timeout=900,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-3000:]
    line = next(l for l in proc.stdout.splitlines() if l.startswith("RSS:"))
    rss = json.loads(line[len("RSS:"):])
    assert rss["total_bytes"] > 150e6          # the model really is ~190 MB
    assert rss["baseline"] > 0                 # RssAnon available
    assert rss["peak_delta"] < 60e6, (
        f"conversion peaked {rss['peak_delta'] / 1e6:.0f} MB anon over "
        f"baseline for a {rss['total_bytes'] / 1e6:.0f} MB model — "
        f"streaming is broken")


_POD_SCRIPT = """
import json
import jax
import jax.numpy as jnp
import numpy as np
from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine
from distributed_training_guide_tpu.train.preflight import run_preflight

assert len(jax.devices()) == 256
bundle = get_model("llama-3.1-405b")
plan = make_plan("tp_fsdp", make_mesh(tp=8, fsdp=32))
trainer = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-4), plan=plan,
                  remat=True, remat_policy="attn", donate=False)
report = run_preflight(trainer, global_batch=32, seq_length=4096)
report["mesh"] = dict(report["mesh"])

# beyond abstract lowering: the SAME pod-shape program structure must also
# EXECUTE — one real optimizer step of the debug family on the identical
# tp=8 x fsdp=32 mesh and plan (vocab padded so 8-way vocab shards divide)
small = get_model("llama-debug", dtype=jnp.float32, vocab_size=512,
                  num_heads=8, num_kv_heads=8)
t2 = Trainer(bundle=small, optimizer=adamw_cosine(1e-3), plan=plan,
             remat=True, remat_policy="attn", donate=False)
state = t2.init_state(0)
ids = np.random.RandomState(0).randint(0, 512, (32, 64))
batch = {k: jax.device_put(jnp.asarray(ids), t2.batch_shardings()[k])
         for k in ("input_ids", "labels")}
state, metrics = t2.step_fn(state, batch)
report["pod_exec_loss"] = float(metrics["loss"])
print("REPORT:" + json.dumps(report))
"""


def test_405b_preflight_at_pod_shape():
    """The chapter's OWN recommended config — fsdp=32 x tp=8 on a v5p-512
    host group (``05-training-llama-405b/train_llm.py`` docstring) — must
    lower, and the preflight's per-device budget must fit v5p HBM (95 GB)
    with remat=attn. Runs in a subprocess: the pod shape needs 256 virtual
    devices, and the device count is fixed per process. Reference anchor:
    the reference proves its recipe by running it on 64xH100
    (``/root/reference/05-training-llama-405b/README.md:268-276``); this is
    the equivalent evidence available without a pod."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=256",
    )
    proc = subprocess.run(
        [sys.executable, "-c", _POD_SCRIPT], env=env, text=True,
        capture_output=True, timeout=1200,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-4000:]
    line = next(l for l in proc.stdout.splitlines() if l.startswith("REPORT:"))
    report = json.loads(line[len("REPORT:"):])

    assert report["lowered"] and report["n_devices"] == 256
    assert report["mesh"]["tp"] == 8 and report["mesh"]["fsdp"] == 32
    state = report["per_device_state_total_bytes"]
    grads = report["per_device_grad_bytes_transient"]
    V5P_HBM = 95e9
    # params (fp32 master) + Adam moments + transient fp32 grads: must leave
    # >= 25% of the chip for activations/temp at the chapter's microbatch --
    # ~25.4 GB expected (1.6 TB state + 0.4 TB grads over 256 chips)
    assert state + grads < 0.75 * V5P_HBM, (
        f"per-device state {state / 2**30:.1f} GiB + grads "
        f"{grads / 2**30:.1f} GiB leaves <25% of v5p HBM for activations")
    # the pod-shape program structure executed for real (debug family,
    # same mesh + plan + remat): finite loss out of one optimizer step
    assert np.isfinite(report["pod_exec_loss"])

    # comm roofline (VERDICT-r4 item 7): the quantitative basis for the
    # >=40%-MFU-on-v5p north star this single-chip environment can produce.
    # At fsdp=32 x tp=8, batch 32, seq 4096 the ring-collective bytes sit
    # well under the compute time — comm-overlapped ceiling ~100%, serial
    # (zero overlap, worst case) still above the 40% target
    comm = report["comm"]
    t = comm["per_collective_bytes_per_chip"]
    assert t["fsdp_allgather_weights"] > 0
    assert t["fsdp_reducescatter_grads"] > 0
    assert t["tp_allreduce_activations"] > 0
    assert t["dp_allreduce_grads"] == 0          # no dp axis in this plan
    assert comm["mfu_ceiling_overlapped"] >= 0.95
    assert comm["mfu_ceiling_serial"] >= 0.40


def test_comm_model_kinds_match_compiled_hlo(eight_devices):
    """The analytical comm model's collective KINDS must appear in the real
    optimized HLO for the same plan (small scale, 2x2x2 mesh): nonzero
    fsdp rows <-> all-gather + reduce-scatter ops, nonzero tp rows <->
    all-reduce ops. Guards the model against drifting from what GSPMD
    actually emits."""
    from distributed_training_guide_tpu.checkpoint import abstract_train_state
    from distributed_training_guide_tpu.models import get_model
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan
    from distributed_training_guide_tpu.train import Trainer, adamw_cosine
    from distributed_training_guide_tpu.train.preflight import comm_roofline

    bundle = get_model("llama-debug", dtype=jnp.float32, num_heads=4,
                       num_kv_heads=2)
    plan = make_plan("tp_fsdp", make_mesh(dp=2, tp=2, fsdp=2))
    trainer = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3), plan=plan,
                      donate=False)
    comm = comm_roofline(trainer, global_batch=8, seq_length=64,
                         device_kind="v5p")
    t = comm["per_collective_bytes_per_chip"]
    assert all(t[k] > 0 for k in ("fsdp_allgather_weights",
                                  "fsdp_reducescatter_grads",
                                  "tp_allreduce_activations",
                                  "dp_allreduce_grads"))

    state = abstract_train_state(trainer)
    batch = {k: jax.ShapeDtypeStruct((8, 64), np.int32, sharding=sh)
             for k, sh in trainer.batch_shardings().items()}
    hlo = trainer.step_fn.lower(state, batch).compile().as_text()
    assert hlo_util.find_collectives(hlo, kinds=("all-gather",)), \
        "fsdp weight all-gather missing from HLO"
    assert hlo_util.find_collectives(hlo, kinds=("all-reduce",)), \
        "tp/dp all-reduce missing from HLO"

    # grad-reduction guard on an fsdp-ONLY plan (no tp axis -> no megatron
    # all-reduces to mask the check): the fsdp grad reduction must appear,
    # as reduce-scatter or as XLA's all-reduce+slice spelling
    plan_f = make_plan("fsdp", make_mesh(fsdp=8))
    t_f = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3), plan=plan_f,
                  donate=False)
    comm_f = comm_roofline(t_f, global_batch=8, seq_length=64,
                           device_kind="v5p")
    assert comm_f["per_collective_bytes_per_chip"]["tp_allreduce_activations"] == 0
    state_f = abstract_train_state(t_f)
    batch_f = {k: jax.ShapeDtypeStruct((8, 64), np.int32, sharding=sh)
               for k, sh in t_f.batch_shardings().items()}
    hlo_f = t_f.step_fn.lower(state_f, batch_f).compile().as_text()
    assert hlo_util.find_collectives(
        hlo_f, kinds=("reduce-scatter", "all-reduce")), (
        "fsdp grad reduction missing from HLO in every spelling")


def test_banded_attention_preflight_pricing():
    """Windowed configs must be priced O(S*window), not dense O(S^2), in
    the preflight roofline (the banded kernel skips out-of-band kv tiles —
    a 2k-window 16k-seq config does ~1/8 the attention FLOPs). Pins the
    kv-length translation (uniform window, per-layer schedules, window >=
    seq) and that the roofline's compute time actually shrinks."""
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan
    from distributed_training_guide_tpu.train import Trainer, adamw_cosine
    from distributed_training_guide_tpu.train.preflight import comm_roofline
    from distributed_training_guide_tpu.utils.mfu import (
        banded_attention_kv_length, transformer_flops_per_token)

    # the kv-length translation
    full = get_model("llama-debug").config
    assert banded_attention_kv_length(full, 1024) == 1024
    swa = get_model("llama-debug", sliding_window=128).config
    assert banded_attention_kv_length(swa, 1024) == 128
    assert banded_attention_kv_length(swa, 64) == 64  # window wider than seq
    gemma_ish = get_model("llama-debug", layer_windows=(128, 0)).config
    # alternating 128-band / full at seq 1024 -> mean (128 + 1024) / 2
    assert banded_attention_kv_length(gemma_ish, 1024) == (128 + 1024) / 2

    # banded pricing flows into FLOPs/token and the roofline's t_compute
    dense_fpt = transformer_flops_per_token(1000, 2, 64, 1024)
    banded_fpt = transformer_flops_per_token(1000, 2, 64, 1024,
                                             attn_kv_len=128.0)
    assert banded_fpt < dense_fpt
    assert banded_fpt == transformer_flops_per_token(1000, 2, 64, 128)

    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))

    def roofline(**overrides):
        bundle = get_model("llama-debug", max_position_embeddings=1024,
                           **overrides)
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3), plan=plan,
                    donate=False)
        return comm_roofline(t, global_batch=4, seq_length=1024,
                             device_kind="v5p")

    dense = roofline()
    banded = roofline(sliding_window=128)
    assert dense["attn_kv_len"] == 1024 and banded["attn_kv_len"] == 128
    assert banded["t_compute_s"] < dense["t_compute_s"]
