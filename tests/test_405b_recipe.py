"""C8 (405B recipe) evidence without 405B hardware: the REAL Llama-3.1-405B
training step — actual config (126 layers, hidden 16384, vocab 128256), the
chapter-05 fsdp x tp plan, remat, bf16 compute — must trace and SPMD-lower
on the virtual 8-device mesh with fully abstract parameters. This catches
shape/sharding/partitioning bugs in the recipe (the class round 1 hit as an
XLA partitioner CHECK) while materializing zero bytes of the 1.6 TB state.

Reference counterpart: ``05-training-llama-405b/train_llm.py`` (the recipe
itself; the reference has no analogous pre-flight check).
"""
import json
import os
import subprocess
import sys

import jax
import numpy as np

from distributed_training_guide_tpu.checkpoint import abstract_train_state
from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine


def test_405b_train_step_lowers(eight_devices):
    bundle = get_model("llama-3.1-405b")
    plan = make_plan("tp_fsdp", make_mesh(tp=2, fsdp=4))
    trainer = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-4), plan=plan,
                      remat=True, remat_policy="attn", donate=False)

    state = abstract_train_state(trainer)
    seq, global_batch = 4096, 8
    batch = {
        k: jax.ShapeDtypeStruct((global_batch, seq), np.int32, sharding=sh)
        for k, sh in trainer.batch_shardings().items()
    }
    lowered = trainer.step_fn.lower(state, batch)

    # the 405B embedding table's shard spec must make it into the lowered
    # program: [V, E] with vocab over tp and embed over fsdp appears as a
    # shardy annotation (this is what a rules-table regression would drop)
    text = lowered.as_text()
    assert '[{"tp"}, {"fsdp"}]' in text, "embed table sharding missing"
    assert text.count("sdy.sharding") > 100  # every param leaf is annotated
    n_params = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(state.params))
    assert abs(n_params - 405.8e9) / 405.8e9 < 0.01


_POD_SCRIPT = """
import json
import jax
import jax.numpy as jnp
import numpy as np
from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine
from distributed_training_guide_tpu.train.preflight import run_preflight

assert len(jax.devices()) == 256
bundle = get_model("llama-3.1-405b")
plan = make_plan("tp_fsdp", make_mesh(tp=8, fsdp=32))
trainer = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-4), plan=plan,
                  remat=True, remat_policy="attn", donate=False)
report = run_preflight(trainer, global_batch=32, seq_length=4096)
report["mesh"] = dict(report["mesh"])

# beyond abstract lowering: the SAME pod-shape program structure must also
# EXECUTE — one real optimizer step of the debug family on the identical
# tp=8 x fsdp=32 mesh and plan (vocab padded so 8-way vocab shards divide)
small = get_model("llama-debug", dtype=jnp.float32, vocab_size=512,
                  num_heads=8, num_kv_heads=8)
t2 = Trainer(bundle=small, optimizer=adamw_cosine(1e-3), plan=plan,
             remat=True, remat_policy="attn", donate=False)
state = t2.init_state(0)
ids = np.random.RandomState(0).randint(0, 512, (32, 64))
batch = {k: jax.device_put(jnp.asarray(ids), t2.batch_shardings()[k])
         for k in ("input_ids", "labels")}
state, metrics = t2.step_fn(state, batch)
report["pod_exec_loss"] = float(metrics["loss"])
print("REPORT:" + json.dumps(report))
"""


def test_405b_preflight_at_pod_shape():
    """The chapter's OWN recommended config — fsdp=32 x tp=8 on a v5p-512
    host group (``05-training-llama-405b/train_llm.py`` docstring) — must
    lower, and the preflight's per-device budget must fit v5p HBM (95 GB)
    with remat=attn. Runs in a subprocess: the pod shape needs 256 virtual
    devices, and the device count is fixed per process. Reference anchor:
    the reference proves its recipe by running it on 64xH100
    (``/root/reference/05-training-llama-405b/README.md:268-276``); this is
    the equivalent evidence available without a pod."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=256",
    )
    proc = subprocess.run(
        [sys.executable, "-c", _POD_SCRIPT], env=env, text=True,
        capture_output=True, timeout=1200,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-4000:]
    line = next(l for l in proc.stdout.splitlines() if l.startswith("REPORT:"))
    report = json.loads(line[len("REPORT:"):])

    assert report["lowered"] and report["n_devices"] == 256
    assert report["mesh"]["tp"] == 8 and report["mesh"]["fsdp"] == 32
    state = report["per_device_state_total_bytes"]
    grads = report["per_device_grad_bytes_transient"]
    V5P_HBM = 95e9
    # params (fp32 master) + Adam moments + transient fp32 grads: must leave
    # >= 25% of the chip for activations/temp at the chapter's microbatch --
    # ~25.4 GB expected (1.6 TB state + 0.4 TB grads over 256 chips)
    assert state + grads < 0.75 * V5P_HBM, (
        f"per-device state {state / 2**30:.1f} GiB + grads "
        f"{grads / 2**30:.1f} GiB leaves <25% of v5p HBM for activations")
    # the pod-shape program structure executed for real (debug family,
    # same mesh + plan + remat): finite loss out of one optimizer step
    assert np.isfinite(report["pod_exec_loss"])
