"""Engine-generation swaps (serve/elastic.py) and runtime fleet
membership (Router.add/remove/swap_replica): every in-flight request
crosses a capacity change token-identical to batch-1 (or exits as a
strict prefix with the structured ``shrink_evicted`` reason), and the
pool invariants hold per iteration on BOTH generations."""
import dataclasses

import jax
import jax.numpy as jnp
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.serve.api import generate_many
from distributed_training_guide_tpu.serve.disagg import DisaggEngine
from distributed_training_guide_tpu.serve.elastic import (
    new_generation, swap_engine, swap_generation)
from distributed_training_guide_tpu.serve.engine import ServeEngine
from distributed_training_guide_tpu.serve.router import (Replica, Router,
                                                         local_fleet)
from distributed_training_guide_tpu.serve.scheduler import (RefusalError,
                                                            Request)
from distributed_training_guide_tpu.utils import faults

pytestmark = [pytest.mark.serve, pytest.mark.elastic]


@pytest.fixture(scope="module")
def bundle_params():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(0))
    return bundle, params


def _requests(n=6, max_new=24, long_prompt=False):
    reqs = []
    for i in range(n):
        prompt = ([3 + (j + i) % 200 for j in range(40)] if long_prompt
                  else [3 + i, 17, 42])
        reqs.append(Request(prompt_ids=prompt, max_new_tokens=max_new,
                            seed=i, temperature=0.7 if i % 2 else 0.0))
    return reqs


def _batch1_refs(bundle, params, reqs, programs=None):
    eng = ServeEngine(bundle, params, n_slots=1, page_size=16, max_len=128,
                      programs=programs)
    return [generate_many(eng, [dataclasses.replace(r, request_id=None)])[0]
            for r in reqs]


def _cache_refs(sched) -> dict:
    out = {}
    if sched.cache is None:
        return out
    stack = [sched.cache.root]
    while stack:
        node = stack.pop()
        for child in node.children.values():
            out[child.page] = out.get(child.page, 0) + 1
            stack.append(child)
    return out


def _audit_engine(eng) -> None:
    """refcount == holders and free + held == capacity, per pool (the
    repo-wide scheduling invariant, re-pinned across generations).
    Same-host in-transit handoff records hold refs in the shared pool;
    duplicate cache views (the disagg pair shares one PrefixCache) are
    counted once."""
    if isinstance(eng, DisaggEngine):
        pairs = [(eng.prefill.sched, eng.pool),
                 (eng.decode.sched, eng.decode_pool)]
        in_transit = [(h, eng.pool) for h in eng.handoff.pending]
    else:
        pairs = [(eng.scheduler, eng.scheduler.pool)]
        in_transit = []
    by_pool: dict = {}
    seen_caches: set = set()
    for sched, pool in pairs:
        held = by_pool.setdefault(id(pool), (pool, {}))[1]
        for slot in sched.slots:
            if slot is None:
                continue
            assert 0 not in slot.pages, "trash page in a live table"
            for p in slot.pages:
                held[p] = held.get(p, 0) + 1
        if sched.cache is not None and id(sched.cache) not in seen_caches:
            seen_caches.add(id(sched.cache))
            for p, n in _cache_refs(sched).items():
                held[p] = held.get(p, 0) + n
    for h, pool in in_transit:
        held = by_pool.setdefault(id(pool), (pool, {}))[1]
        for p in h.pages:
            held[p] = held.get(p, 0) + 1
    for pool, held in by_pool.values():
        for p, n in held.items():
            assert pool.refcount(p) == n, \
                f"page {p}: {n} holders, refcount {pool.refcount(p)}"
        assert pool.n_free + len(held) == pool.capacity, \
            (pool.n_free, len(held), pool.capacity)


def _finish(eng, done, max_iters=3000):
    it = 0
    while eng.has_work:
        for res in eng.step():
            done[res.request_id] = res
        _audit_engine(eng)
        it += 1
        assert it < max_iters, "engine stalled"
    return done


# ---------------------------------------------------------------------------
# monolith swaps
# ---------------------------------------------------------------------------

def test_swap_grow_midstream_token_identity(bundle_params):
    """Grow n_slots 4 -> 8 with residents decoding, one mid-chunk
    prefill, and a queue: every request finishes token-identical to
    batch-1, invariants audited per iteration on the new generation, and
    the old generation ends empty (free == capacity)."""
    bundle, params = bundle_params
    old = ServeEngine(bundle, params, n_slots=4, page_size=16,
                      max_len=128, prefill_chunk=16)
    reqs = _requests(8, long_prompt=True)
    refs = _batch1_refs(bundle, params, reqs, programs=old.programs)
    ids = [old.submit(dataclasses.replace(r, request_id=None))
           for r in reqs]
    done: dict = {}
    for _ in range(5):                      # residents + pending chunks
        for res in old.step():
            done[res.request_id] = res
    new, evicted, stats = swap_engine(old, n_slots=8)
    assert not evicted
    assert stats["seated"] + stats["requeued"] >= 1
    assert old.draining and not old.has_work
    assert old.scheduler.pool.n_free == old.scheduler.pool.capacity
    _audit_engine(new)
    _finish(new, done)
    assert len(done) == len(reqs)
    for rid, ref in zip(ids, refs):
        assert done[rid].generated_ids == ref.generated_ids, rid


def test_swap_shrink_requeue_and_replay_identity(bundle_params):
    """Shrink below residency (4 slots -> 2, pool sized down): excess
    residents take the requeue-and-replay path and STILL finish
    token-identical — replay is bitwise recompute."""
    bundle, params = bundle_params
    old = ServeEngine(bundle, params, n_slots=4, page_size=16, max_len=128)
    reqs = _requests(6)
    refs = _batch1_refs(bundle, params, reqs, programs=old.programs)
    ids = [old.submit(dataclasses.replace(r, request_id=None))
           for r in reqs]
    done: dict = {}
    for _ in range(5):
        for res in old.step():
            done[res.request_id] = res
    new, evicted, stats = swap_engine(
        old, n_slots=2, n_pages=1 + 2 * old.max_pages)
    assert not evicted
    assert stats["requeued"] >= 2           # shrink forced requeues
    _finish(new, done)
    assert len(done) == len(reqs)
    for rid, ref in zip(ids, refs):
        assert done[rid].generated_ids == ref.generated_ids, rid


def test_swap_shrink_forced_eviction_strict_prefix(bundle_params):
    """A request whose WORST CASE cannot fit the new generation at all
    finishes at the swap with finish_reason='shrink_evicted' and a
    STRICT PREFIX of its batch-1 stream — never silently dropped, never
    divergent. Requests that still fit continue normally."""
    bundle, params = bundle_params
    old = ServeEngine(bundle, params, n_slots=2, page_size=16, max_len=128)
    big = Request(prompt_ids=[3, 17, 42], max_new_tokens=100, seed=0)
    small = Request(prompt_ids=[5, 19, 44], max_new_tokens=16, seed=1)
    refs = _batch1_refs(bundle, params, [big, small],
                        programs=old.programs)
    ids = [old.submit(dataclasses.replace(r, request_id=None))
           for r in (big, small)]
    for _ in range(6):
        old.step()
    new, evicted, stats = swap_engine(old, max_len=64)
    assert stats["evicted"] == 1 and len(evicted) == 1
    res = evicted[0]
    assert res.request_id == ids[0]
    assert res.finish_reason == "shrink_evicted"
    assert 0 < len(res.generated_ids) < len(refs[0].generated_ids)
    assert res.generated_ids == \
        refs[0].generated_ids[:len(res.generated_ids)]
    done = {res.request_id: res}
    _finish(new, done)
    assert done[ids[1]].generated_ids == refs[1].generated_ids


def test_swap_payload_drop_fault_falls_back_to_replay(bundle_params,
                                                      monkeypatch):
    """DTG_FAULT_SWAP_DROP_SEQ: the Nth exported resident's payload is
    torn — the swap requeues it (recompute + bitwise replay) instead of
    seating it, and the continuation is still token-identical."""
    bundle, params = bundle_params
    old = ServeEngine(bundle, params, n_slots=4, page_size=16, max_len=128)
    reqs = _requests(4)
    refs = _batch1_refs(bundle, params, reqs, programs=old.programs)
    ids = [old.submit(dataclasses.replace(r, request_id=None))
           for r in reqs]
    done: dict = {}
    for _ in range(4):
        for res in old.step():
            done[res.request_id] = res
    monkeypatch.setenv(faults.ENV_SWAP_DROP_SEQ, "0")
    new, evicted, stats = swap_engine(old, n_slots=4)
    assert stats["payload_dropped"] == 1
    assert stats["requeued"] >= 1
    _finish(new, done)
    for rid, ref in zip(ids, refs):
        assert done[rid].generated_ids == ref.generated_ids, rid


def test_swap_id_space_no_collision(bundle_params):
    """Post-swap submits must never collide with carried-over request
    ids (ensure_ids_above): every result id is unique and every request
    completes."""
    bundle, params = bundle_params
    old = ServeEngine(bundle, params, n_slots=2, page_size=16, max_len=128)
    reqs = _requests(4, max_new=8)
    ids = [old.submit(dataclasses.replace(r, request_id=None))
           for r in reqs]
    for _ in range(3):
        old.step()
    new, evicted, _ = swap_engine(old, n_slots=4)
    more = [new.submit(Request(prompt_ids=[9, 9, 9 + i],
                               max_new_tokens=4, seed=10 + i))
            for i in range(3)]
    assert len(set(ids + more)) == len(ids + more), (ids, more)
    done: dict = {}
    _finish(new, done)
    assert set(done) == set(ids + more)


def test_swap_with_speculation(bundle_params):
    """A speculating engine (ngram drafter, lookahead-grown pages) swaps
    mid-stream: dead lookahead k/v is dropped, not moved, and the
    continuation stays token-identical (spec-on == spec-off == across-
    swap)."""
    bundle, params = bundle_params
    old = ServeEngine(bundle, params, n_slots=4, page_size=16,
                      max_len=256, speculate="ngram", spec_k=4)
    block = [7, 11, 13, 17, 19, 23, 29, 31]
    prompt = (block * 6)[:48]
    reqs = [Request(prompt_ids=prompt + [40 + i], max_new_tokens=24,
                    seed=i) for i in range(4)]
    refs = _batch1_refs(bundle, params, reqs, programs=old.programs)
    ids = [old.submit(dataclasses.replace(r, request_id=None))
           for r in reqs]
    done: dict = {}
    for _ in range(4):
        for res in old.step():
            done[res.request_id] = res
    new, evicted, stats = swap_engine(old, n_slots=6)
    assert not evicted
    assert new.drafter is old.drafter        # the drafter rides along
    _finish(new, done)
    for rid, ref in zip(ids, refs):
        assert done[rid].generated_ids == ref.generated_ids, rid


def test_new_generation_carries_sizing_faithfully(bundle_params):
    """The serving knobs carry over across a swap unless overridden: an
    EXPLICITLY under-sized pool (the backpressure configuration) stays
    under-sized, a default full-residency pool re-derives for a new
    slot count, and max_model_len does not inflate to the next page
    boundary."""
    bundle, params = bundle_params
    # explicit small pool survives a same-size swap
    old = ServeEngine(bundle, params, n_slots=4, page_size=16,
                      max_len=100, n_pages=20)
    new = new_generation(old)
    assert new.scheduler.pool.n_pages == 20
    assert new.max_model_len == old.max_model_len == 100
    # default pool re-derives for a grown slot count
    old2 = ServeEngine(bundle, params, n_slots=4, page_size=16,
                       max_len=100)
    new2 = new_generation(old2, n_slots=8)
    assert new2.scheduler.pool.n_pages == 1 + 8 * new2.max_pages
    assert new2.max_model_len == 100
    # repeated swaps are a fixed point, not a drift
    new3 = new_generation(new_generation(old))
    assert new3.scheduler.pool.n_pages == 20
    assert new3.max_model_len == 100
    # disagg: both pools carried under cross_host explicit sizing
    d = DisaggEngine(bundle, params, n_slots=2, n_prefill_slots=1,
                     page_size=16, max_len=100, transport="cross_host",
                     n_pages=18, n_prefill_pages=12)
    d2 = new_generation(d)
    assert d2.decode_pool.n_pages == 18
    assert d2.pool.n_pages == 12
    assert d2.max_model_len == 100
    d.close()
    d2.close()


def test_new_generation_rejects_baked_knobs(bundle_params):
    bundle, params = bundle_params
    old = ServeEngine(bundle, params, n_slots=2, page_size=16, max_len=64)
    with pytest.raises(ValueError, match="baked into the shared"):
        new_generation(old, kv_dtype="int8")
    with pytest.raises(ValueError, match="ModelPrograms"):
        swap_generation(old, ServeEngine(bundle, params, n_slots=2,
                                         page_size=16, max_len=64))


# ---------------------------------------------------------------------------
# disaggregated swaps
# ---------------------------------------------------------------------------

def test_swap_disagg_with_in_transit_handoffs(bundle_params):
    """DisaggEngine generation swap with sequences in EVERY station:
    decoding residents (payload-seated), in-transit handoffs (requeued —
    a full decode side keeps the handoff queue non-empty), prefill
    queue. All finish token-identical on the new generation."""
    bundle, params = bundle_params
    old = DisaggEngine(bundle, params, n_slots=2, n_prefill_slots=1,
                       page_size=16, max_len=128)
    reqs = _requests(6, max_new=16)
    refs = _batch1_refs(bundle, params, reqs, programs=old.programs)
    ids = [old.submit(dataclasses.replace(r, request_id=None))
           for r in reqs]
    done: dict = {}
    for _ in range(4):                 # fill decode slots + the handoff
        for res in old.step():
            done[res.request_id] = res
    new, evicted, stats = swap_engine(old, n_slots=4)
    assert not evicted
    assert isinstance(new, DisaggEngine)
    assert old.pool.n_free == old.pool.capacity
    assert old.decode_pool.n_free == old.decode_pool.capacity
    _finish(new, done)
    assert len(done) == len(reqs)
    for rid, ref in zip(ids, refs):
        assert done[rid].generated_ids == ref.generated_ids, rid


# ---------------------------------------------------------------------------
# fleet membership at runtime
# ---------------------------------------------------------------------------

def _drive(router, done, iters):
    import time

    for _ in range(iters):
        for res in router.step():
            done[res.request_id] = res
        if router._backlog:
            time.sleep(0.01)           # let resubmit backoff elapse


def test_router_add_remove_swap_under_live_load(bundle_params):
    """The fleet-membership seam end to end: a generation swap of one
    replica, a replica added mid-flight, and a replica removed (drain +
    resubmit via the fencing path — not a kill) — every request finishes
    token-identical, and the counters record the membership churn."""
    bundle, params = bundle_params
    refs_src = _requests(8, max_new=20)
    router = local_fleet(bundle, params, 2, n_slots=4, page_size=16,
                         max_len=128)
    programs = router.replicas["r0"].engine.programs
    refs = _batch1_refs(bundle, params, refs_src, programs=programs)
    ids = [router.submit(dataclasses.replace(r, request_id=None))
           for r in refs_src]
    done: dict = {}
    _drive(router, done, 4)
    evicted = router.swap_replica("r0", n_slots=6)
    assert evicted == []
    assert router.counters["generation_swaps"] == 1
    _drive(router, done, 2)
    router.add_replica(Replica("r2", ServeEngine(
        bundle, params, programs=programs, n_slots=4, page_size=16,
        max_len=128)))
    router.remove_replica("r1")
    assert sorted(router.replicas) == ["r0", "r2"]
    assert router.counters["replicas_added"] == 1
    assert router.counters["replicas_removed"] == 1
    it = 0
    while router.has_work and it < 2000:
        _drive(router, done, 1)
        it += 1
    assert len(done) == len(ids)
    for rid, ref in zip(ids, refs):
        assert done[rid].generated_ids == ref.generated_ids, rid


def test_router_membership_validation(bundle_params):
    bundle, params = bundle_params
    router = local_fleet(bundle, params, 2, n_slots=2, page_size=16,
                         max_len=64)
    programs = router.replicas["r0"].engine.programs
    with pytest.raises(ValueError, match="already in"):
        router.add_replica(Replica("r0", ServeEngine(
            bundle, params, programs=programs, n_slots=2, page_size=16,
            max_len=64)))
    with pytest.raises(ValueError, match="page_size"):
        router.add_replica(Replica("r9", ServeEngine(
            bundle, params, n_slots=2, page_size=32, max_len=64)))
    with pytest.raises(ValueError, match="no replica"):
        router.remove_replica("ghost")
    router.remove_replica("r1")
    with pytest.raises(ValueError, match="last live replica"):
        router.remove_replica("r0")
    with pytest.raises(ValueError, match="page_size"):
        router.swap_replica("r0", page_size=32)


def test_router_remove_is_drain_not_kill(bundle_params):
    """remove_replica with work in flight: the removed replica's
    requests resubmit (resubmitted counter) and complete elsewhere with
    replayed prefixes — token identity holds, and nothing was fenced
    (this was intent, not failure)."""
    bundle, params = bundle_params
    router = local_fleet(bundle, params, 2, n_slots=2, page_size=16,
                         max_len=128)
    programs = router.replicas["r0"].engine.programs
    reqs = _requests(6, max_new=20)
    refs = _batch1_refs(bundle, params, reqs, programs=programs)
    ids = [router.submit(dataclasses.replace(r, request_id=None))
           for r in reqs]
    done: dict = {}
    _drive(router, done, 3)
    before = router.counters["resubmitted"]
    router.remove_replica("r1")
    assert router.counters["resubmitted"] >= before
    assert router.counters["fenced"] == 0
    it = 0
    while router.has_work and it < 2000:
        _drive(router, done, 1)
        it += 1
    assert len(done) == len(ids)
    for rid, ref in zip(ids, refs):
        assert done[rid].generated_ids == ref.generated_ids, rid
