"""HF numerics parity: convert a real HF torch checkpoint, compare logits.

This is the "matching HF model numerics in JAX" hard part (SURVEY.md section 7):
build a tiny ``LlamaForCausalLM`` / ``GPT2LMHeadModel`` with torch (CPU),
``save_pretrained`` to safetensors, stream-convert with
``convert_hf_checkpoint``, load via the sharded loader, and require our
pure-JAX forward to match torch's logits.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.models.hf_convert import (
    convert_hf_checkpoint, load_pretrained)
from distributed_training_guide_tpu.parallel import make_mesh, make_plan


def _replicated_shardings(bundle, plan):
    shapes = jax.eval_shape(lambda: bundle.init(bundle.config, jax.random.key(0)))
    return plan.param_shardings(bundle.param_logical_axes(bundle.config), shapes)


def _one_train_step(bundle, plan, params, ids):
    """Pretrained params -> fresh TrainState -> one optimizer step (the
    reference 05:118-126 path); returns the scalar loss."""
    from distributed_training_guide_tpu.train import Trainer, adamw_cosine

    trainer = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-4), plan=plan,
                      donate=False)
    state = trainer.init_state_from_params(params)
    batch = {k: jax.device_put(jnp.asarray(ids), trainer.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    _, metrics = trainer.step_fn(state, batch)
    return float(metrics["loss"])


def test_llama_parity(tmp_path):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    bundle = get_model("llama-debug", vocab_size=128, dtype=jnp.float32)
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan), tmp_path / "conv")

    ids = np.random.RandomState(0).randint(0, 128, (2, 24))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)

    assert np.isfinite(_one_train_step(bundle, plan, params, ids))


def test_gpt2_parity(tmp_path):
    hf_cfg = transformers.GPT2Config(
        vocab_size=160, n_embd=64, n_layer=2, n_head=4, n_positions=128)
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    bundle = get_model("gpt2-debug", vocab_size=160, max_position_embeddings=128,
                       dtype=jnp.float32)
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan), tmp_path / "conv")

    ids = np.random.RandomState(0).randint(0, 160, (2, 24))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_mistral_parity(tmp_path):
    """Mistral dense rides the Llama family unchanged (same HF tensor names
    and layouts); this pins that a MistralForCausalLM checkpoint converts
    and matches through the whole stream-convert -> sharded-load path."""
    hf_cfg = transformers.MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
        sliding_window=None, tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.MistralForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    bundle = get_model("mistral-7b", vocab_size=128, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, max_position_embeddings=256,
                       rope_theta=10000.0, rms_norm_eps=1e-5,
                       dtype=jnp.float32)
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan),
                             tmp_path / "conv")

    ids = np.random.RandomState(0).randint(0, 128, (2, 24))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_qwen2_parity(tmp_path):
    """Qwen2 dense = Llama + QKV projection biases (attn_bias): pins the
    bias leaves end to end — conversion of the HF bias rows, the bias add
    in the attention sublayer, and tied embeddings (the small Qwen cards)."""
    hf_cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=True)
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    # HF inits biases to zero; randomize so the parity check actually
    # exercises the bias path
    with torch.no_grad():
        for layer in model.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj):
                proj.bias.normal_(0.0, 0.5)
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    bundle = get_model("qwen2.5-0.5b", vocab_size=128, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, max_position_embeddings=256,
                       rope_theta=10000.0, rms_norm_eps=1e-5,
                       dtype=jnp.float32)
    assert bundle.config.attn_bias and bundle.config.tie_word_embeddings
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan),
                             tmp_path / "conv")
    assert np.abs(np.asarray(params["layers"]["attn"]["bq"])).max() > 0

    ids = np.random.RandomState(0).randint(0, 128, (2, 24))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_qwen3_parity(tmp_path):
    """Qwen3 dense = Llama + per-head q/k RMSNorm before rope (qk_norm) and
    an explicit head_dim decoupled from hidden/heads. Randomizes the norm
    scales (HF inits them to ones — identity would not exercise the path)
    and pins logits end to end through hf: ingestion."""
    hf_cfg = transformers.Qwen3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, max_position_embeddings=256, rope_theta=10000.0,
        rms_norm_eps=1e-5, tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.Qwen3ForCausalLM(hf_cfg).eval()
    with torch.no_grad():
        for layer in model.model.layers:
            layer.self_attn.q_norm.weight.normal_(1.0, 0.3)
            layer.self_attn.k_norm.weight.normal_(1.0, 0.3)
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    bundle = get_model(f"hf:{tmp_path / 'hf'}", dtype=jnp.float32)
    assert bundle.config.qk_norm and not bundle.config.attn_bias
    assert bundle.config.head_dim == 32
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan),
                             tmp_path / "conv")
    assert np.abs(np.asarray(params["layers"]["attn"]["q_norm"]) - 1).max() > 0

    ids = np.random.RandomState(0).randint(0, 128, (2, 24))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)

    # pretrained -> one optimizer step through the qk_norm leaves
    assert np.isfinite(_one_train_step(bundle, plan, params, ids))


def test_olmo2_parity(tmp_path):
    """OLMo-2 = llama math with two real wiring changes: POST-norm blocks
    (x + norm(attn(x)), x + norm(mlp(x)) — no pre-norms) and FULL-WIDTH q/k
    RMSNorm applied before the head reshape. Randomizes the norm scales and
    pins logits end to end through hf: ingestion."""
    hf_cfg = transformers.Olmo2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.Olmo2ForCausalLM(hf_cfg).eval()
    with torch.no_grad():
        for layer in model.model.layers:
            layer.self_attn.q_norm.weight.normal_(1.0, 0.3)
            layer.self_attn.k_norm.weight.normal_(1.0, 0.3)
            layer.post_attention_layernorm.weight.normal_(1.0, 0.3)
            layer.post_feedforward_layernorm.weight.normal_(1.0, 0.3)
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    bundle = get_model(f"hf:{tmp_path / 'hf'}", dtype=jnp.float32)
    assert bundle.config.post_norm and bundle.config.qk_norm == "flat"
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan),
                             tmp_path / "conv")
    assert "attn_out_norm" in params["layers"]
    assert params["layers"]["attn"]["q_norm"].shape[-1] == 64  # full width

    ids = np.random.RandomState(0).randint(0, 128, (2, 24))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)

    # pretrained -> one optimizer step through the post-norm wiring
    assert np.isfinite(_one_train_step(bundle, plan, params, ids))


def test_gemma_parity(tmp_path):
    """Gemma = llama + three real architecture knobs: GeGLU (tanh-gelu
    gate), (1+w) RMSNorm scaling, sqrt(hidden)-scaled embeddings — plus MQA
    (kv_heads=1), explicit head_dim != hidden/heads, and always-tied
    embeddings. Pins all of them through conversion end to end."""
    hf_cfg = transformers.GemmaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=1,
        head_dim=32, max_position_embeddings=256, rope_theta=10000.0,
        rms_norm_eps=1e-6, hidden_act="gelu_pytorch_tanh",
        tie_word_embeddings=True)
    torch.manual_seed(0)
    model = transformers.GemmaForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    bundle = get_model("gemma-2b", vocab_size=128, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=1, head_dim=32,
                       max_position_embeddings=256, dtype=jnp.float32)
    assert bundle.config.norm_plus_one and bundle.config.scale_embed
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan),
                             tmp_path / "conv")

    ids = np.random.RandomState(0).randint(0, 128, (2, 24))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)

    # pretrained -> one training step (MQA + GeGLU through the optimizer path)
    assert np.isfinite(_one_train_step(bundle, plan, params, ids))


def test_qwen2_max_window_layers_parity(tmp_path):
    """Qwen2 with use_sliding_window=True and max_window_layers < L: the
    FIRST layer runs full attention, the second bands at window 16. seq 48
    > window means the two layers genuinely differ — pins the layer_windows
    ingestion path for the qwen flavor (Gemma-2 pins the alternating one)."""
    hf_cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
        sliding_window=16, use_sliding_window=True, max_window_layers=1,
        attn_implementation="eager", tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    bundle = get_model(f"hf:{tmp_path / 'hf'}", dtype=jnp.float32)
    assert bundle.config.layer_windows == (0, 16)
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan),
                             tmp_path / "conv")

    ids = np.random.RandomState(0).randint(0, 128, (2, 48))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_gemma2_parity(tmp_path):
    """Gemma-2 = Gemma + four REAL mechanism changes, all pinned here at
    once: sandwich norms (both sides of each sublayer), tanh softcapping of
    attention scores and final logits, the query_pre_attn_scalar score
    scale, and the ALTERNATING per-layer sliding/full window pattern. seq
    48 > window 16 means the even (sliding) layers genuinely band while the
    odd (full) layers don't — a uniform-window implementation cannot pass."""
    hf_cfg = transformers.Gemma2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, max_position_embeddings=256, rope_theta=10000.0,
        rms_norm_eps=1e-6, query_pre_attn_scalar=24.0,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        sliding_window=16, attn_implementation="eager",
        hidden_activation="gelu_pytorch_tanh", tie_word_embeddings=True)
    torch.manual_seed(0)
    model = transformers.Gemma2ForCausalLM(hf_cfg).eval()
    with torch.no_grad():
        for layer in model.model.layers:
            layer.post_attention_layernorm.weight.normal_(0.0, 0.3)
            layer.pre_feedforward_layernorm.weight.normal_(0.0, 0.3)
            layer.post_feedforward_layernorm.weight.normal_(0.0, 0.3)
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    bundle = get_model(f"hf:{tmp_path / 'hf'}", dtype=jnp.float32)
    c = bundle.config
    assert c.sandwich_norm and c.attn_logit_softcap == 50.0
    assert c.final_logit_softcap == 30.0 and c.query_pre_attn_scalar == 24.0
    assert c.layer_windows == (16, 0) and c.sliding_window is None
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan),
                             tmp_path / "conv")
    assert "attn_out_norm" in params["layers"]
    assert "post_attn_norm" in params["layers"]   # the pre-FFN norm slot

    ids = np.random.RandomState(0).randint(0, 128, (2, 48))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)

    # the FLASH path (the production default on TPU — interpret mode runs
    # the same kernels here): softcap, query_pre_attn_scalar and the
    # alternating per-layer windows all inside the Pallas kernel, at seq 48
    # > window 16 so the banded layers genuinely band
    ours_flash = np.asarray(bundle.apply(bundle.config, params,
                                         jnp.asarray(ids), attn_impl="flash"))
    np.testing.assert_allclose(ours_flash, theirs, rtol=2e-4, atol=2e-4)

    # pretrained -> one optimizer step through the sandwich wiring
    assert np.isfinite(_one_train_step(bundle, plan, params, ids))


@pytest.mark.parametrize("parallel_residual", [True, False])
def test_neox_parity(tmp_path, parallel_residual):
    """GPT-NeoX/Pythia: the parallel-residual block (x + attn(ln1 x) +
    mlp(ln2 x)), partial rotary (rotary_pct=0.25), fused per-head-interleaved
    QKV (de-interleaved at conversion to the tp-shardable [E,3,h*d] layout),
    exact-gelu MLP, untied embed_in/embed_out. Pins both residual wirings
    end to end through stream-convert -> sharded-load -> logits."""
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=512, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=256, rotary_pct=0.25, rotary_emb_base=10000,
        layer_norm_eps=1e-5, hidden_act="gelu",
        use_parallel_residual=parallel_residual, tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    bundle = get_model("neox-debug", use_parallel_residual=parallel_residual,
                       dtype=jnp.float32)
    assert bundle.config.rotary_ndims == 4  # 0.25 * head_size(16)
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan),
                             tmp_path / "conv")

    ids = np.random.RandomState(0).randint(0, 512, (2, 24))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)

    if parallel_residual:  # pretrained -> one optimizer step, once
        assert np.isfinite(_one_train_step(bundle, plan, params, ids))


def test_phi3_parity(tmp_path):
    """Phi-3 = llama math with FUSED checkpoint tensors: one qkv_proj
    ([hq+2*hkv, E] rows) and one gate_up_proj ([2F, E]). Pins the
    multi-leaf split path in the converter (one source tensor filling
    three/two native leaves), end to end via hf: ingestion."""
    hf_cfg = transformers.Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
        sliding_window=None, tie_word_embeddings=False,
        pad_token_id=0, bos_token_id=1, eos_token_id=2)
    torch.manual_seed(0)
    model = transformers.Phi3ForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    bundle = get_model(f"hf:{tmp_path / 'hf'}", dtype=jnp.float32)
    assert bundle.family == "llama" and not bundle.config.attn_bias
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan),
                             tmp_path / "conv")

    ids = np.random.RandomState(0).randint(0, 128, (2, 24))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_auto_hf_config_ingestion(tmp_path, caplog):
    """The AutoModelForCausalLM analogue (reference 01:57): ``-m hf:<dir>``
    builds the family config from the checkpoint's own config.json. Pins the
    arch dispatch for all seven supported architectures, full convert+logits
    parity through an hf: bundle, and the loud unsupported-arch failure."""
    from distributed_training_guide_tpu.models.auto import config_from_hf

    # arch dispatch + field mapping, one per family flavor
    cases = [
        (transformers.MistralConfig(vocab_size=64, hidden_size=32,
                                    intermediate_size=64, num_hidden_layers=2,
                                    num_attention_heads=4, num_key_value_heads=2,
                                    sliding_window=None), "llama",
         lambda c: c.num_kv_heads == 2 and not c.attn_bias),
        (transformers.Qwen2Config(vocab_size=64, hidden_size=32,
                                  intermediate_size=64, num_hidden_layers=2,
                                  num_attention_heads=4, num_key_value_heads=2),
         "llama", lambda c: c.attn_bias),
        (transformers.GemmaConfig(vocab_size=64, hidden_size=32,
                                  intermediate_size=64, num_hidden_layers=2,
                                  num_attention_heads=4, num_key_value_heads=1,
                                  head_dim=16), "llama",
         lambda c: c.norm_plus_one and c.scale_embed and c.head_dim == 16),
        (transformers.Qwen3Config(vocab_size=64, hidden_size=32,
                                  intermediate_size=64, num_hidden_layers=2,
                                  num_attention_heads=4, num_key_value_heads=2,
                                  head_dim=16), "llama",
         lambda c: c.qk_norm and not c.attn_bias and c.head_dim == 16),
        (transformers.Olmo2Config(vocab_size=64, hidden_size=32,
                                  intermediate_size=64, num_hidden_layers=2,
                                  num_attention_heads=4, num_key_value_heads=2),
         "llama", lambda c: c.post_norm and c.qk_norm == "flat"),
        (transformers.Gemma2Config(vocab_size=64, hidden_size=32,
                                   intermediate_size=64, num_hidden_layers=4,
                                   num_attention_heads=4, num_key_value_heads=2,
                                   head_dim=16, sliding_window=8,
                                   max_position_embeddings=256),
         "llama", lambda c: (c.sandwich_norm and c.attn_logit_softcap
                             and c.layer_windows == (8, 0, 8, 0))),
        (transformers.GPT2Config(vocab_size=64, n_embd=32, n_layer=2,
                                 n_head=4), "gpt2",
         lambda c: c.num_layers == 2),
        # Llama-arch checkpoints CAN carry QKV biases (attention_bias=true):
        # they must not be silently dropped
        (transformers.LlamaConfig(vocab_size=64, hidden_size=32,
                                  intermediate_size=64, num_hidden_layers=2,
                                  num_attention_heads=4, num_key_value_heads=2,
                                  attention_bias=True), "llama",
         lambda c: c.attn_bias),
        (transformers.MixtralConfig(vocab_size=64, hidden_size=32,
                                    intermediate_size=64, num_hidden_layers=2,
                                    num_attention_heads=4, num_key_value_heads=2,
                                    num_local_experts=4, num_experts_per_tok=2,
                                    router_aux_loss_coef=0.02),
         "moe", lambda c: (c.num_experts == 4 and c.experts_per_token == 2
                           and c.router_aux_coef == 0.02)),
        (transformers.GPTNeoXConfig(vocab_size=64, hidden_size=32,
                                    intermediate_size=64, num_hidden_layers=2,
                                    num_attention_heads=4, rotary_pct=0.25,
                                    use_parallel_residual=True),
         "neox", lambda c: (c.use_parallel_residual and c.rotary_pct == 0.25
                            and c.act_fn == "gelu")),
    ]
    for i, (hf_cfg, want_family, check) in enumerate(cases):
        d = tmp_path / f"cfg{i}"
        d.mkdir()
        hf_cfg.save_pretrained(d)
        family, config = config_from_hf(d)
        assert family == want_family, hf_cfg.architectures
        assert config.vocab_size == 64 and check(config), config

    # end-to-end: save real weights, build the bundle via hf:, convert, match
    hf_cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    bundle = get_model(f"hf:{tmp_path / 'hf'}", dtype=jnp.float32)
    assert bundle.config.attn_bias and bundle.config.hidden_size == 64
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan),
                             tmp_path / "conv")
    ids = np.random.RandomState(0).randint(0, 128, (2, 16))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)

    # sliding_window is SUPPORTED: a live window lands on the config (the
    # flash kernel's banded path; numerics pinned in tests/test_swa.py) …
    mist = tmp_path / "mist_swa"
    mist.mkdir()
    transformers.MistralConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        sliding_window=4096, max_position_embeddings=32768).save_pretrained(mist)
    _, mcfg = config_from_hf(mist)
    assert mcfg.sliding_window == 4096
    # …but Qwen2's is gated behind use_sliding_window (default False: the
    # key is present-but-inert on every Qwen2 config)
    qwen_swa = tmp_path / "qwen_swa"
    qwen_swa.mkdir()
    transformers.Qwen2Config(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        sliding_window=4096, use_sliding_window=False,
        max_position_embeddings=32768).save_pretrained(qwen_swa)
    _, qcfg = config_from_hf(qwen_swa)
    assert qcfg.sliding_window is None
    # ...and a LIVE Qwen2 window with max_window_layers < num_layers (the
    # first mwl layers stay FULL attention) maps onto the per-layer
    # layer_windows column (numerics pinned in test_qwen2_max_window_layers_parity)
    qwen_mixed = tmp_path / "qwen_mixed"
    qwen_mixed.mkdir()
    transformers.Qwen2Config(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        sliding_window=4096, use_sliding_window=True, max_window_layers=2,
        max_position_embeddings=32768).save_pretrained(qwen_mixed)
    _, qmcfg = config_from_hf(qwen_mixed)
    assert qmcfg.layer_windows == (0, 0, 4096, 4096)
    assert qmcfg.sliding_window is None

    # rope_scaling is SUPPORTED: ingestion freezes the dict onto the config
    # (full numerics parity is pinned in tests/test_rope_scaling.py)
    rope = tmp_path / "llama_rope"
    rope.mkdir()
    transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "original_max_position_embeddings": 8192,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0},
        max_position_embeddings=131072).save_pretrained(rope)
    neox_rope = tmp_path / "neox_rope"
    neox_rope.mkdir()
    transformers.GPTNeoXConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        rope_scaling={"rope_type": "linear", "factor": 2.0}).save_pretrained(
            neox_rope)
    _, rcfg = config_from_hf(rope)
    assert dict(rcfg.rope_scaling)["rope_type"] == "llama3"
    assert rcfg.max_position_embeddings == 131072
    _, ncfg = config_from_hf(neox_rope)
    assert dict(ncfg.rope_scaling)["factor"] == 2.0
    # ...but an rope type we do NOT implement still fails loudly at ingestion
    bad_rope = tmp_path / "bad_rope"
    bad_rope.mkdir()
    (bad_rope / "config.json").write_text(json.dumps({
        "architectures": ["LlamaForCausalLM"], "vocab_size": 64,
        "hidden_size": 32, "intermediate_size": 64, "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "rope_scaling": {"rope_type": "su", "factor": 2.0}}))
    with pytest.raises(ValueError, match="unsupported rope_scaling"):
        config_from_hf(bad_rope)

    # loud failure on an unsupported architecture
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "config.json").write_text(
        '{"architectures": ["FalconForCausalLM"], "model_type": "falcon"}')
    with pytest.raises(ValueError, match="unsupported architecture"):
        config_from_hf(bad)
    # ...including a supported model_type with an UNsupported head: the
    # model_type fallback must not remap a classification checkpoint
    bad2 = tmp_path / "bad2"
    bad2.mkdir()
    (bad2 / "config.json").write_text(
        '{"architectures": ["LlamaForSequenceClassification"], '
        '"model_type": "llama"}')
    with pytest.raises(ValueError, match="unsupported architecture"):
        config_from_hf(bad2)


def test_qwen3_moe_parity(tmp_path):
    """Qwen3-MoE = Qwen3 attention (per-head qk_norm) + the Mixtral-style
    routed FFN with TWO spelling changes (mlp.experts.N.gate_proj names,
    mlp.gate router) and the norm_topk_prob flag OFF by default (raw softmax
    mass as combine weights). capacity_factor = E makes drops impossible so
    the dense HF dispatch is reproducible exactly."""
    hf_cfg = transformers.Qwen3MoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=32,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=False,
        decoder_sparse_step=1, mlp_only_layers=[],
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.Qwen3MoeForCausalLM(hf_cfg).eval()
    with torch.no_grad():
        for layer in model.model.layers:
            layer.self_attn.q_norm.weight.normal_(1.0, 0.3)
            layer.self_attn.k_norm.weight.normal_(1.0, 0.3)
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    bundle = get_model(f"hf:{tmp_path / 'hf'}", dtype=jnp.float32,
                       capacity_factor=4.0)
    assert bundle.family == "moe" and bundle.config.qk_norm
    assert bundle.config.intermediate_size == 96   # moe_intermediate_size
    assert not bundle.config.norm_topk_prob
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan),
                             tmp_path / "conv")

    ids = np.random.RandomState(0).randint(0, 128, (2, 24))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids),
                                   attn_impl="xla"))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)

    # dense-MoE interleaving must fail loudly, not silently misroute
    from distributed_training_guide_tpu.models.auto import config_from_hf

    mixed = tmp_path / "mixed"
    mixed.mkdir()
    transformers.Qwen3MoeConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=48, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2,
        num_experts=4, num_experts_per_tok=2,
        mlp_only_layers=[0, 1]).save_pretrained(mixed)
    with pytest.raises(ValueError, match="mlp_only_layers"):
        config_from_hf(mixed)


def test_qwen2_moe_parity(tmp_path):
    """Qwen2-MoE = Qwen2 attention (QKV biases) + routed FFN + the SHARED
    expert: a dense gated MLP on every token whose output is scaled by
    sigmoid(x @ shared_expert_gate) and added to the routed combine.
    capacity_factor = E for exactness vs HF's dense dispatch."""
    hf_cfg = transformers.Qwen2MoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=96, shared_expert_intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=False,
        decoder_sparse_step=1, mlp_only_layers=[],
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.Qwen2MoeForCausalLM(hf_cfg).eval()
    with torch.no_grad():   # exercise the bias + scalar-gate paths for real
        for layer in model.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj):
                proj.bias.normal_(0.0, 0.5)
            layer.mlp.shared_expert_gate.weight.normal_(0.0, 0.5)
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    bundle = get_model(f"hf:{tmp_path / 'hf'}", dtype=jnp.float32,
                       capacity_factor=4.0)
    c = bundle.config
    assert c.attn_bias and c.shared_expert_intermediate == 112
    assert c.intermediate_size == 96 and not c.norm_topk_prob
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan),
                             tmp_path / "conv")
    assert np.abs(np.asarray(params["layers"]["moe"]["shared_gate"])).max() > 0

    ids = np.random.RandomState(0).randint(0, 128, (2, 24))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids),
                                   attn_impl="xla"))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)

    # HF Qwen2MoeConfig DEFAULTS ship sliding_window=4096 with
    # use_sliding_window=False — the inert key must NOT band any layer
    # (review-r5 finding: the arch gate must cover the MoE flavors too)
    from distributed_training_guide_tpu.models.auto import config_from_hf

    inert = tmp_path / "inert"
    inert.mkdir()
    transformers.Qwen2MoeConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=48, shared_expert_intermediate_size=56,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_experts=4, num_experts_per_tok=2, sliding_window=4096,
        use_sliding_window=False,
        max_position_embeddings=32768).save_pretrained(inert)
    _, icfg = config_from_hf(inert)
    assert icfg.sliding_window is None
    # ...and a LIVE mixed pattern on a MoE arch is rejected loudly (the
    # moe scan has no per-layer window column)
    mixed = tmp_path / "mixed_moe"
    mixed.mkdir()
    transformers.Qwen2MoeConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=48, shared_expert_intermediate_size=56,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        num_experts=4, num_experts_per_tok=2, sliding_window=4096,
        use_sliding_window=True, max_window_layers=2,
        max_position_embeddings=32768).save_pretrained(mixed)
    with pytest.raises(ValueError, match="max_window_layers"):
        config_from_hf(mixed)


def test_mixtral_parity(tmp_path):
    """The MoE family against HF MixtralForCausalLM: same softmax-all ->
    top-k -> renormalize routing, so with capacity_factor = E (zero
    capacity drops) the two forwards must agree. Pins the (layer, expert)
    stacked conversion of the per-expert w1/w2/w3 Linears."""
    hf_cfg = transformers.MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=256, rope_theta=10000.0, rms_norm_eps=1e-5,
        sliding_window=None, tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.MixtralForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path / "hf", safe_serialization=True)

    # capacity_factor = num_experts guarantees no token is ever dropped
    # (worst case: every token routes both choices to one expert), so the
    # capacity mechanism cannot diverge from HF's dense dispatch
    bundle = get_model("moe-debug", vocab_size=128, dtype=jnp.float32,
                       capacity_factor=4.0)
    convert_hf_checkpoint(tmp_path / "hf", tmp_path / "conv", bundle=bundle)
    plan = make_plan("single", make_mesh(devices=jax.devices()[:1]))
    params = load_pretrained(bundle, _replicated_shardings(bundle, plan),
                             tmp_path / "conv")

    ids = np.random.RandomState(0).randint(0, 128, (2, 24))
    ours = np.asarray(bundle.apply(bundle.config, params, jnp.asarray(ids),
                                   attn_impl="xla"))
    with torch.no_grad():
        theirs = model(torch.tensor(ids)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)
