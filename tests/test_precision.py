"""Precision-policy runtime (train/precision.py): block-quantized 8-bit Adam
moments, bf16 storage with fp32-computed updates, and the policy threading
through step/sharding/guards/preflight/checkpoint.

Acceptance pins from ISSUE 2: adam8bit tracks fp32 AdamW within 2% relative
loss after 50 steps; preflight reports >= 3.5x optimizer-state reduction for
adam8bit and >= 1.9x total-state for bf16-master; quantized state survives a
checkpoint round-trip bit-exactly; an fp32 checkpoint restores into an
adam8bit run by re-quantizing with a logged warning; the guard `skip` policy
reverts quantized moments.
"""
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import (Quantized, Trainer,
                                                  adamw_cosine,
                                                  dequantize_blockwise,
                                                  quantize_blockwise,
                                                  resolve_policy)

pytestmark = pytest.mark.precision


def _run(policy, steps=10, lr=1e-3, seed=0, **trainer_kw):
    bundle = get_model("llama-debug")
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(lr), precision=policy,
                **trainer_kw)
    state = t.init_state(seed)
    ids = np.random.RandomState(seed).randint(0, bundle.config.vocab_size,
                                              (8, 64))
    batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    losses = []
    for _ in range(steps):
        state, m = t.step_fn(state, batch)
        losses.append(float(m["loss"]))
    return t, state, losses, batch


def _quantized_leaves(tree):
    return [l for l in jax.tree.leaves(
        tree, is_leaf=lambda n: isinstance(n, Quantized))
        if isinstance(l, Quantized)]


def _tree_bytes(tree):
    return sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(tree))


# ---- quantization primitive -------------------------------------------------

def test_quantize_roundtrip_error_bound_per_block():
    """Absmax int8: per-element error <= half a quantization step of ITS
    block (scale/2), across ragged trailing dims and wild dynamic range."""
    key = jax.random.key(0)
    for d in (64, 100, 300, 512):
        x = (jax.random.normal(jax.random.key(d), (3, d))
             * jnp.exp(3 * jax.random.normal(key, (3, d))))
        qt = quantize_blockwise(x, 128)
        assert qt.q.shape == x.shape and qt.q.dtype == jnp.int8
        dq = dequantize_blockwise(qt)
        bs = -(-d // qt.scale.shape[-1])
        step = np.repeat(np.asarray(qt.scale), bs, axis=-1)[..., :d]
        err = np.abs(np.asarray(dq) - np.asarray(x, np.float32))
        assert (err <= step / 2 + 1e-12).all()


def test_quantize_sqrt_domain_alignment():
    """nu (second moment) quantizes in the sqrt domain: an element survives
    in nu exactly when it survives in mu — otherwise mu/(sqrt(0)+eps)
    explodes for mid-magnitude elements."""
    g = np.zeros((256,), np.float32)
    g[0] = 1.0          # the block outlier
    g[1] = 1e-2         # survives mu linear quant (1e-2 > 1/254)...
    qt_nu = quantize_blockwise(jnp.asarray(g) ** 2, 256, sqrt_domain=True)
    nu = np.asarray(dequantize_blockwise(qt_nu, sqrt_domain=True))
    assert nu[1] > 0    # ...so it must survive in nu too
    assert (nu >= 0).all()
    # linear quantization of g^2 would have zeroed it: documents the hazard
    lin = np.asarray(dequantize_blockwise(quantize_blockwise(
        jnp.asarray(g) ** 2, 256)))
    assert lin[1] == 0


def test_resolve_policy_names_and_composition():
    assert resolve_policy("fp32").is_noop
    comp = resolve_policy("bf16-master+adam8bit")
    assert comp.quantize_moments and comp.param_dtype == jnp.bfloat16
    assert comp.accum_dtype == jnp.bfloat16
    with pytest.raises(ValueError, match="precision policy"):
        resolve_policy("fp16-master")


# ---- trajectory parity (acceptance pin: 2% over 50 steps) -------------------

def test_adam8bit_matches_fp32_loss_trajectory():
    _, s0, l0, _ = _run("fp32", steps=50, donate=False)
    _, s8, l8, _ = _run("adam8bit", steps=50, donate=False)
    rel = abs(l8[-1] - l0[-1]) / abs(l0[-1])
    assert rel < 0.02, (l0[-1], l8[-1], rel)
    assert l8[-1] < l8[0] - 0.5           # actually trained, not just agreed
    # the whole point: both moments stored int8 + per-block fp32 scales
    qs = _quantized_leaves(s8.opt_state)
    assert qs and all(q.q.dtype == jnp.int8 and q.scale.dtype == jnp.float32
                      for q in qs)
    # byte math: opt state well under half of AdamW's 2x-fp32 mirror
    param_bytes = _tree_bytes(s0.params)
    assert _tree_bytes(s8.opt_state) < 0.6 * param_bytes


def test_bf16_master_trains_and_halves_state():
    _, s0, l0, _ = _run("fp32", steps=20, donate=False)
    _, sb, lb, _ = _run("bf16-master", steps=20, donate=False)
    assert abs(lb[-1] - l0[-1]) / abs(l0[-1]) < 0.02
    assert jax.tree.leaves(sb.params)[0].dtype == jnp.bfloat16
    assert _tree_bytes(sb.params) * 2 == _tree_bytes(s0.params)
    # moments stored bf16 (the fp32 master is transient inside the step)
    mu = sb.opt_state[0].mu
    assert all(l.dtype == jnp.bfloat16 for l in jax.tree.leaves(mu))


# ---- sharding ---------------------------------------------------------------

def test_quantized_state_shards_under_zero1(eight_devices):
    """ZeRO-1: the int8 payload shards exactly like the moment it encodes,
    and the per-block scales ride alongside (not replicated) when the block
    tiling divides."""
    t, state, losses, _ = _run("adam8bit", steps=2, donate=False,
                               plan=make_plan("zero1", make_mesh()))
    assert np.isfinite(losses).all()
    mu = state.opt_state[0].mu["layers"]["attn"]["wq"]
    assert isinstance(mu, Quantized)
    assert any(s is not None for s in mu.q.sharding.spec)
    assert any(s is not None for s in mu.scale.sharding.spec)


def test_composed_policy_with_zero2_accum(eight_devices):
    """bf16-master+adam8bit under ZeRO-2 with grad accumulation: the accum
    buffer takes the policy dtype and the sharded step still trains."""
    bundle = get_model("llama-debug")
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                precision="bf16-master+adam8bit",
                plan=make_plan("zero2", make_mesh()), grad_accum=2,
                donate=False)
    state = t.init_state(0)
    ids = np.random.RandomState(0).randint(0, 512, (2, 8, 64))
    batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    l0 = None
    for _ in range(3):
        state, m = t.step_fn(state, batch)
        l0 = l0 if l0 is not None else float(m["loss"])
    assert float(m["loss"]) < l0


# ---- guards -----------------------------------------------------------------

def test_guard_skip_reverts_quantized_moments(monkeypatch):
    from distributed_training_guide_tpu.utils.faults import ENV_NAN_LOSS_STEP

    monkeypatch.setenv(ENV_NAN_LOSS_STEP, "1")
    t, s1, _, batch = _run("adam8bit", steps=1, donate=False,
                           guard_policy="skip")
    before = [np.asarray(x) for x in
              jax.tree.leaves(jax.device_get(s1.opt_state))]
    s2, m2 = t.step_fn(s1, batch)           # state.step==1: poisoned
    assert float(m2["notfinite"]) == 1.0
    after = [np.asarray(x) for x in
             jax.tree.leaves(jax.device_get(s2.opt_state))]
    for a, b in zip(before, after):         # int8 payloads AND fp32 scales
        np.testing.assert_array_equal(a, b)
    assert int(s2.step) == 2                # schedule still advances


# ---- preflight accounting (acceptance pins: 3.5x opt / 1.9x total) ----------

def test_preflight_prices_the_policy():
    from distributed_training_guide_tpu.train.preflight import run_preflight

    bundle = get_model("llama-debug")

    def report(policy):
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                    precision=policy)
        return run_preflight(t, global_batch=8, seq_length=64)

    r32 = report("fp32")
    assert r32["precision"]["opt_state_reduction"] == 1.0
    r8 = report("adam8bit")
    assert r8["precision"]["opt_state_reduction"] >= 3.5
    assert (r8["per_device_opt_state_bytes"]
            < r32["per_device_opt_state_bytes"] / 3.5)
    rb = report("bf16-master")
    assert rb["precision"]["total_state_reduction"] >= 1.9
    rc = report("bf16-master+adam8bit")
    assert (rc["precision"]["total_state_reduction"]
            > rb["precision"]["total_state_reduction"])


# ---- checkpoints ------------------------------------------------------------

def test_quantized_checkpoint_roundtrip_bit_exact(tmp_path):
    from distributed_training_guide_tpu.checkpoint import (CheckpointIO,
                                                           restore_train_state)
    from distributed_training_guide_tpu.train.state import host_state_dict

    t, state, _, batch = _run("adam8bit", steps=1, donate=False)
    io = CheckpointIO(tmp_path / "exp")
    host = host_state_dict()
    host["global_step"] = 1
    io.save(state, host)
    restored, _ = restore_train_state(io, t)
    for a, b in zip(jax.tree.leaves(jax.device_get(state.opt_state)),
                    jax.tree.leaves(jax.device_get(restored.opt_state))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # continuing from the restored state is bit-identical to continuing live
    _, m_live = t.step_fn(state, batch)
    _, m_rest = t.step_fn(restored, batch)
    assert float(m_live["loss"]) == float(m_rest["loss"])


def test_fp32_checkpoint_requantizes_into_adam8bit(tmp_path, caplog):
    """Restoring a pre-policy (fp32) checkpoint into an adam8bit run falls
    back to the fp32 layout, re-quantizes with a logged warning, and keeps
    the PR-1 manifest/host-state chain intact."""
    from distributed_training_guide_tpu.checkpoint import (CheckpointIO,
                                                           restore_train_state)
    from distributed_training_guide_tpu.train.state import host_state_dict

    t32, s32, _, _ = _run("fp32", steps=1, donate=False)
    io = CheckpointIO(tmp_path / "exp")
    host = host_state_dict()
    host["global_step"] = 1
    io.save(s32, host)

    t8, _, _, batch = _run("adam8bit", steps=1, donate=False)
    with caplog.at_level(logging.WARNING):
        restored, host2 = restore_train_state(io, t8)
    assert any("re-encoding" in r.message for r in caplog.records)
    assert host2["global_step"] == 1
    qs = _quantized_leaves(restored.opt_state)
    assert qs, "moments were not re-quantized"
    # params carried over exactly (fp32 -> fp32), training continues finite
    for a, b in zip(jax.tree.leaves(jax.device_get(s32.params)),
                    jax.tree.leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    _, m = t8.step_fn(restored, batch)
    assert np.isfinite(float(m["loss"]))


def test_policy_checkpoint_into_fp32_run_fails_loudly(tmp_path):
    """Dropping --precision-policy on restart must NOT silently fall back
    through the retention chain: the manifest's policy stamp turns the
    layout mismatch into an error naming both policies."""
    from distributed_training_guide_tpu.checkpoint import (CheckpointIO,
                                                           restore_train_state)
    from distributed_training_guide_tpu.train.state import host_state_dict

    t8, s8, _, _ = _run("adam8bit", steps=1, donate=False)
    io = CheckpointIO(tmp_path / "exp")
    host = host_state_dict()
    host["global_step"] = 1
    host["precision_policy"] = "adam8bit"  # what cli/engine save paths stamp
    io.save(s8, host)

    t32, _, _, _ = _run("fp32", steps=1, donate=False)
    with pytest.raises(ValueError, match="adam8bit.*fp32"):
        restore_train_state(io, t32)


def test_fp32_policy_is_bit_identical_to_unwrapped():
    """The default policy must be a true no-op: same optimizer object, same
    state structure, so every pre-policy test/checkpoint stays valid."""
    bundle = get_model("llama-debug")
    tx = adamw_cosine(1e-3)
    t = Trainer(bundle=bundle, optimizer=tx)
    assert t.optimizer is tx
    assert t.base_optimizer is tx
