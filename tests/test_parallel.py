"""Strategy parity goldens on the virtual 8-device CPU mesh.

The key invariant the reference establishes chapter-by-chapter (and verifies
only by eyeballing wandb loss curves, ``06-tensor-parallel/README.md:293-295``):
every parallelism strategy computes the *same* optimization trajectory as the
single-device baseline. Here that is an automated golden: identical seeds and
global batch => identical loss/params across single/ddp/zero1/fsdp/tp/2d.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine

SEQ = 32
GLOBAL_BATCH = 8


def make_trainer(strategy, grad_accum=1, **mesh_kw):
    bundle = get_model("llama-debug", dtype=jnp.float32)  # fp32 for exact parity
    if strategy == "single":
        mesh = make_mesh(devices=jax.devices()[:1])
    else:
        mesh = make_mesh(**mesh_kw)
    plan = make_plan(strategy, mesh)
    return Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3), plan=plan,
                   grad_accum=grad_accum, donate=False)


def make_batch(trainer, accum=1):
    rng = np.random.RandomState(0)
    shape = (accum, GLOBAL_BATCH, SEQ) if accum > 1 else (GLOBAL_BATCH, SEQ)
    ids = rng.randint(0, trainer.bundle.config.vocab_size, size=shape)
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids)}
    shardings = trainer.batch_shardings()
    return {k: jax.device_put(v, shardings[k]) for k, v in batch.items()}


def run_steps(trainer, n=2, accum=1):
    state = trainer.init_state(0)
    batch = make_batch(trainer, accum)
    losses = []
    for _ in range(n):
        state, metrics = trainer.step_fn(state, batch)
        losses.append(float(metrics["loss"]))
    return losses, state


@pytest.fixture(scope="module")
def golden():
    losses, state = run_steps(make_trainer("single"))
    params = jax.tree.map(np.asarray, jax.device_get(state.params))
    return losses, params


STRATEGY_MESHES = [
    ("ddp", {}),
    ("zero1", {}),
    ("fsdp", {"fsdp": 8}),
    ("tp", {"tp": 4}),
    ("tp_fsdp", {"fsdp": 2, "tp": 2}),
]


@pytest.mark.parametrize("strategy,mesh_kw", STRATEGY_MESHES, ids=[s for s, _ in STRATEGY_MESHES])
def test_strategy_matches_single_device(strategy, mesh_kw, golden, eight_devices):
    golden_losses, golden_params = golden
    losses, state = run_steps(make_trainer(strategy, **mesh_kw))
    np.testing.assert_allclose(losses, golden_losses, rtol=1e-4)
    # distributed reductions reorder fp32 sums; Adam's eps region amplifies
    # ~1e-7 grad noise to ~1e-5 param noise — tolerance reflects that.
    for a, b in zip(jax.tree.leaves(golden_params),
                    jax.tree.leaves(jax.tree.map(np.asarray, jax.device_get(state.params)))):
        np.testing.assert_allclose(a, b, rtol=1e-2, atol=1e-4)


def test_gpt2_tp_matches_single_device(eight_devices):
    """gpt2 under auto (GSPMD) tensor parallelism: exercises the [l,e,3,e]
    fused-QKV layout and the column-sharded biases (*_vector -> tp rules)
    that the llama goldens above cannot cover (llama has no biases)."""
    bundle = get_model("gpt2-debug", dtype=jnp.float32)

    def run(strategy, mesh):
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                    plan=make_plan(strategy, mesh), donate=False)
        state = t.init_state(0)
        ids = np.random.RandomState(0).randint(0, 512, (GLOBAL_BATCH, SEQ))
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = []
        for _ in range(2):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
        return losses

    golden = run("single", make_mesh(devices=jax.devices()[:1]))
    for strategy, mesh_kw in (("tp", {"tp": 4}), ("tp_fsdp", {"fsdp": 2, "tp": 2})):
        got = run(strategy, make_mesh(**mesh_kw))
        np.testing.assert_allclose(got, golden, rtol=1e-4, err_msg=strategy)


def test_neox_tp_fsdp_matches_single_device(eight_devices):
    """NeoX under auto (GSPMD) tensor parallelism: the parallel-residual
    block sums the attention and MLP row-parallel outputs into ONE residual
    update, and partial rotary (rotary_pct) slices each head's dims — the
    trajectory must still match single-device exactly."""
    bundle = get_model("neox-debug", dtype=jnp.float32)
    assert bundle.config.use_parallel_residual

    def run(strategy, mesh):
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                    plan=make_plan(strategy, mesh), donate=False)
        state = t.init_state(0)
        ids = np.random.RandomState(0).randint(0, 512, (GLOBAL_BATCH, SEQ))
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = []
        for _ in range(2):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
        return losses

    golden = run("single", make_mesh(devices=jax.devices()[:1]))
    for strategy, mesh_kw in (("fsdp", {"fsdp": 8}), ("tp", {"tp": 4}),
                              ("tp_fsdp", {"fsdp": 2, "tp": 2})):
        got = run(strategy, make_mesh(**mesh_kw))
        np.testing.assert_allclose(got, golden, rtol=1e-4, err_msg=strategy)


def test_qwen_bias_tp_matches_single_device(eight_devices):
    """Qwen2-style attn_bias under tensor parallelism: the bq/bk/bv leaves
    carry the heads/kv logical axes, so tp shards them column-wise with
    their matmuls — trajectory must still match single-device."""
    bundle = get_model("qwen2.5-0.5b", vocab_size=512, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, max_position_embeddings=256,
                       dtype=jnp.float32)
    assert bundle.config.attn_bias

    def run(strategy, mesh):
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                    plan=make_plan(strategy, mesh), donate=False)
        state = t.init_state(0)
        if strategy == "tp":   # bias shards over its only (heads) dim
            bq = state.params["layers"]["attn"]["bq"]
            assert "tp" in jax.tree.leaves(tuple(bq.sharding.spec)), bq.sharding
        ids = np.random.RandomState(0).randint(0, 512, (GLOBAL_BATCH, SEQ))
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = []
        for _ in range(2):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
        return losses

    golden = run("single", make_mesh(devices=jax.devices()[:1]))
    got = run("tp", make_mesh(tp=4))
    np.testing.assert_allclose(got, golden, rtol=1e-4)


def test_olmo2_post_norm_tp_matches_single_device(eight_devices):
    """OLMo-2 wiring under tensor parallelism: the FULL-WIDTH q/k norm
    scales carry heads/kv logical axes (the kv_vector rule), so tp shards
    them column-wise with their projections, and the post-norm residuals
    ride the tp psum outputs — trajectory must match single-device."""
    bundle = get_model("olmo2-7b", vocab_size=512, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, max_position_embeddings=256,
                       dtype=jnp.float32)
    assert bundle.config.post_norm and bundle.config.qk_norm == "flat"

    def run(strategy, mesh):
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                    plan=make_plan(strategy, mesh), donate=False)
        state = t.init_state(0)
        if strategy == "tp":   # flat norms shard over their heads/kv dim
            kn = state.params["layers"]["attn"]["k_norm"]
            assert "tp" in jax.tree.leaves(tuple(kn.sharding.spec)), kn.sharding
        ids = np.random.RandomState(0).randint(0, 512, (GLOBAL_BATCH, SEQ))
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = []
        for _ in range(2):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
        return losses

    golden = run("single", make_mesh(devices=jax.devices()[:1]))
    got = run("tp", make_mesh(tp=2))
    np.testing.assert_allclose(got, golden, rtol=1e-4)


def test_gemma2_sandwich_tp_matches_single_device(eight_devices):
    """Gemma-2 under tensor parallelism: sandwich norms ride the psum'd
    sublayer outputs, the traced per-layer window mask and softcap run on
    the xla path under GSPMD sharding — trajectory must match single-device."""
    bundle = get_model("gemma2-2b", vocab_size=512, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, head_dim=16, layer_windows=(8, 0),
                       query_pre_attn_scalar=24.0,
                       max_position_embeddings=256, dtype=jnp.float32)
    assert bundle.config.sandwich_norm and bundle.config.attn_logit_softcap

    def run(strategy, mesh):
        t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                    plan=make_plan(strategy, mesh), donate=False)
        state = t.init_state(0)
        ids = np.random.RandomState(0).randint(0, 512, (GLOBAL_BATCH, SEQ))
        batch = {k: jax.device_put(jnp.asarray(ids), t.batch_shardings()[k])
                 for k in ("input_ids", "labels")}
        losses = []
        for _ in range(2):
            state, m = t.step_fn(state, batch)
            losses.append(float(m["loss"]))
        return losses

    golden = run("single", make_mesh(devices=jax.devices()[:1]))
    got = run("tp", make_mesh(tp=2))
    np.testing.assert_allclose(got, golden, rtol=1e-4)


def test_params_actually_sharded(eight_devices):
    trainer = make_trainer("fsdp", fsdp=8)
    state = trainer.init_state(0)
    wq = state.params["layers"]["attn"]["wq"]
    # embed dim (axis 1 of [L, E, H]) sharded 8-ways
    assert wq.sharding.spec[1] == "fsdp"
    shard_shape = wq.addressable_shards[0].data.shape
    assert shard_shape[1] == wq.shape[1] // 8


def test_zero1_shards_opt_state_not_params(eight_devices):
    trainer = make_trainer("zero1")
    state = trainer.init_state(0)
    wq = state.params["layers"]["attn"]["wq"]
    assert all(s is None for s in wq.sharding.spec)  # params replicated
    mu_wq = state.opt_state[0].mu["layers"]["attn"]["wq"]
    assert any(s is not None for s in mu_wq.sharding.spec)  # opt state sharded


def test_grad_accumulation_matches(eight_devices):
    t1 = make_trainer("ddp")
    t2 = make_trainer("ddp", grad_accum=2)
    s1 = t1.init_state(0)
    s2 = t2.init_state(0)
    rng = np.random.RandomState(0)
    big = 16  # microbatch of 8 still fills the 8-way dp mesh
    ids = jnp.asarray(rng.randint(0, t1.bundle.config.vocab_size, size=(big, SEQ)))
    batch = {k: jax.device_put(ids, t1.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    split = {k: jax.device_put(np.asarray(ids).reshape(2, big // 2, SEQ),
                               t2.batch_shardings()[k])
             for k in ("input_ids", "labels")}
    s1, m1 = t1.step_fn(s1, batch)
    s2, m2 = t2.step_fn(s2, split)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                    jax.tree.leaves(jax.device_get(s2.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_host_offload_matches_golden(golden, eight_devices):
    """Full C5 host offload (params + opt state in pinned_host) is a pure
    storage-placement change: trajectory identical, params actually on host."""
    bundle = get_model("llama-debug", dtype=jnp.float32)
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan("fsdp", make_mesh(fsdp=8)), donate=False,
                offload_opt_state=True, offload_params=True)
    losses, state = run_steps(t)
    np.testing.assert_allclose(losses, golden[0], rtol=2e-4)
    assert state.params["final_norm"].sharding.memory_kind == "pinned_host"
    kinds = {getattr(l.sharding, "memory_kind", None)
             for l in jax.tree.leaves(state.opt_state) if hasattr(l, "sharding")}
    assert "pinned_host" in kinds


def test_zero2_matches_golden_and_shards_grads(golden, eight_devices):
    """DeepSpeed stage 2 semantics: params replicated, opt state sharded,
    persistent (accumulated) grads sharded over the data axes."""
    bundle = get_model("llama-debug", dtype=jnp.float32)
    t = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                plan=make_plan("zero2", make_mesh()), grad_accum=2,
                donate=False)
    losses, state = run_steps(t, accum=2)
    # same trajectory as single-device at equal total tokens is NOT expected
    # (2x tokens/step with accum=2) — instead compare against ddp with the
    # same accumulation
    t_ddp = Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                    plan=make_plan("ddp", make_mesh()), grad_accum=2,
                    donate=False)
    losses_ddp, _ = run_steps(t_ddp, accum=2)
    np.testing.assert_allclose(losses, losses_ddp, rtol=2e-4)
    # params replicated, optimizer moments sharded
    wq = state.params["layers"]["attn"]["wq"]
    assert wq.sharding.spec == ()or wq.sharding.is_fully_replicated
    mu_leaves = [l for l in jax.tree.leaves(state.opt_state) if hasattr(l, "sharding") and l.ndim > 0]
    assert any(not l.sharding.is_fully_replicated for l in mu_leaves)
