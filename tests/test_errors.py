"""Unit tests for launch/errors.py: the @record error-file contract, the
outside-except traceback fix, env fallbacks, and poison-pill classification
(ISSUE 1 satellites). No jax compile, fast."""
import json

import pytest

from distributed_training_guide_tpu.launch.errors import (
    classify_error, error_file_path, record, write_error_file)


def read_payload(path):
    payload = json.loads(path.read_text())
    assert set(payload) == {"message"}
    msg = payload["message"]
    for key in ("error", "traceback", "process_index", "timestamp",
                "hostname", "pid"):
        assert key in msg, key
    return msg


def test_record_writes_well_formed_error_file(tmp_path, monkeypatch):
    err = tmp_path / "logs" / "error.json"   # parent dir must be created too
    monkeypatch.setenv("ERROR_FILE", str(err))

    @record
    def boom():
        raise ValueError("kaboom from the worker")

    with pytest.raises(ValueError):
        boom()
    msg = read_payload(err)
    assert "kaboom from the worker" in msg["error"]
    # a REAL traceback naming the raise site, not torchelastic's un-captured
    # "NoneType: None"
    assert "boom" in msg["traceback"] and "ValueError" in msg["traceback"]


def test_write_error_file_outside_except_block(tmp_path, monkeypatch):
    """Direct calls with a constructed (never-raised) exception — the guard
    abort path — must still record the exception, not 'NoneType: None'
    (traceback.format_exc reads the *ambient* exception state, which is
    empty outside an except block)."""
    err = tmp_path / "error.json"
    monkeypatch.setenv("ERROR_FILE", str(err))
    write_error_file(RuntimeError("constructed, never raised"))
    msg = read_payload(err)
    assert "constructed, never raised" in msg["error"]
    assert "NoneType: None" not in msg["traceback"]
    assert "RuntimeError" in msg["traceback"]


def test_torchelastic_env_fallback(tmp_path, monkeypatch):
    monkeypatch.delenv("ERROR_FILE", raising=False)
    monkeypatch.setenv("TORCHELASTIC_ERROR_FILE", str(tmp_path / "te.json"))
    assert error_file_path() == str(tmp_path / "te.json")
    write_error_file(KeyError("ported launch command"))
    assert "ported launch command" in read_payload(tmp_path / "te.json")["error"]


def test_write_error_file_noop_without_env(monkeypatch):
    monkeypatch.delenv("ERROR_FILE", raising=False)
    monkeypatch.delenv("TORCHELASTIC_ERROR_FILE", raising=False)
    write_error_file(RuntimeError("nowhere to go"))   # must not raise


# ---- classification ---------------------------------------------------------

def payload_for(error_repr, traceback=""):
    return {"message": {"error": error_repr, "traceback": traceback}}


@pytest.mark.parametrize("error,reason", [
    ("XlaRuntimeError('RESOURCE_EXHAUSTED: Out of memory allocating "
     "123456 bytes')", "oom"),
    ("ValueError('8 devices not divisible by tensor x pipeline = 3')",
     "shape/sharding"),
    ("NonFiniteLossError('non-finite training step 7: ...')", "non-finite"),
])
def test_classify_poison(error, reason):
    assert classify_error(payload_for(error)) == reason


def test_classify_transient_is_none():
    assert classify_error(payload_for(
        "RuntimeError('injected failure after step-3 checkpoint (test)')")) is None
    assert classify_error(payload_for(
        "ConnectionError('coordinator unreachable')")) is None
    assert classify_error({}) is None


def test_classify_tolerates_foreign_error_file_shapes():
    """The supervisor runs arbitrary commands; a worker may write
    {"message": "<string>"} instead of our nested dict — classification must
    still work (and not crash the supervisor mid-failure-handling)."""
    assert classify_error({"message": "RESOURCE_EXHAUSTED: oom"}) == "oom"
    assert classify_error({"message": "it broke"}) is None
    assert classify_error("not even a dict") is None


def test_classify_collateral_gang_teardown_is_not_poison():
    """When one rank of a fail-fast gang dies, SURVIVORS write collateral
    errors (collective torn down mid-flight) that carry generic Xla markers
    like INVALID_ARGUMENT. Those must classify as transient — stopping the
    restart loop on a victim's error would break exactly the elasticity the
    supervisor exists for (observed live: jax 0.4.37 CPU gangs)."""
    assert classify_error(payload_for(
        'XlaRuntimeError("INVALID_ARGUMENT: Multiprocess computations '
        "aren't implemented on the CPU backend.\")")) is None
    assert classify_error(payload_for(
        "XlaRuntimeError('INVALID_ARGUMENT: Sharding contains unknown "
        "device')")) is None


def test_classify_ignores_traceback_text():
    """Poison patterns must match the error repr only: every jax traceback
    walks files named *sharding*.py, and matching there would turn any
    transient failure into a no-restart verdict."""
    p = payload_for("TimeoutError('barrier timed out')",
                    traceback="File jax/_src/sharding_impls.py line 1 ...")
    assert classify_error(p) is None
