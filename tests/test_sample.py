"""Sampler correctness: the jit decode step must equal a naive per-step
argmax reference (position indexing into the fixed buffer is where an
off-by-one would hide)."""
import jax
import jax.numpy as jnp
import numpy as np

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.models.sample import make_sampler, main


def test_greedy_matches_naive_reference():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(0))
    prompt = [3, 17, 42]
    steps = 5
    out = make_sampler(bundle)(params, prompt, steps)
    assert out[:3] == prompt and len(out) == len(prompt) + steps

    # naive reference: grow a python list, argmax the last position's
    # logits over the same zero-padded buffer the sampler uses
    ids = list(prompt)
    for t in range(steps):
        buf = np.zeros((1, len(prompt) + steps), np.int32)
        buf[0, :len(ids)] = ids
        logits = np.asarray(bundle.apply(bundle.config, params,
                                         jnp.asarray(buf)))
        ids.append(int(np.argmax(logits[0, len(ids) - 1])))
    assert out == ids


def test_temperature_sampling_is_seeded_and_in_vocab():
    bundle = get_model("gpt2-debug", dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(1))
    sample = make_sampler(bundle, temperature=0.8)
    a = sample(params, [5, 6], 6, rng=jax.random.key(7))
    b = sample(params, [5, 6], 6, rng=jax.random.key(7))
    assert a == b                       # same seed, same draw
    assert all(0 <= t < bundle.config.vocab_size for t in a)


def test_kv_cache_matches_recompute():
    """The cached decode (prefill + one-token steps over the cache) must
    produce the same greedy tokens as the full-recompute sampler, and the
    prefill logits must match the plain forward's last position."""
    bundle = get_model("llama-debug", dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(0))
    prompt = [3, 17, 42, 7]
    steps = 6

    slow = make_sampler(bundle)(params, prompt, steps)
    fast = make_sampler(bundle, kv_cache=True)(params, prompt, steps)
    assert fast == slow

    from distributed_training_guide_tpu.models import llama

    cache = llama.init_cache(bundle.config, 1, len(prompt) + steps)
    ids = jnp.asarray(prompt, jnp.int32)[None, :]
    logit, cache = llama.prefill(bundle.config, params, ids, cache)
    full = bundle.apply(bundle.config, params, ids)
    np.testing.assert_allclose(np.asarray(logit), np.asarray(full[:, -1]),
                               rtol=1e-5, atol=1e-5)
    assert cache["k"].shape == (2, 1, len(prompt) + steps,
                                bundle.config.num_kv_heads,
                                bundle.config.head_size)


def test_kv_cache_gqa_qwen_bias_family():
    """The cache path through a GQA + QKV-bias config (the biases ride the
    projections before rope; kv_heads < heads exercises grouped attention
    over the cache)."""
    bundle = get_model("qwen2.5-0.5b", vocab_size=256, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, max_position_embeddings=128,
                       dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(2))
    prompt = [9, 11]
    slow = make_sampler(bundle)(params, prompt, 5)
    fast = make_sampler(bundle, kv_cache=True)(params, prompt, 5)
    assert fast == slow


def test_kv_cache_neox_matches_recompute():
    """The NeoX cache path: parallel-residual blocks and PARTIAL rotary
    (only the first rotary_ndims of each head rotate) through prefill +
    cached decode must reproduce the recompute sampler's greedy tokens."""
    bundle = get_model("neox-debug", dtype=jnp.float32)
    assert 0 < bundle.config.rotary_ndims < bundle.config.head_size
    params = bundle.init(bundle.config, jax.random.key(3))
    prompt = [8, 21, 5]
    slow = make_sampler(bundle)(params, prompt, 6)
    fast = make_sampler(bundle, kv_cache=True)(params, prompt, 6)
    assert fast == slow

    # sequential-residual wiring too
    seq_bundle = get_model("neox-debug", use_parallel_residual=False,
                           dtype=jnp.float32)
    seq_params = seq_bundle.init(seq_bundle.config, jax.random.key(4))
    slow = make_sampler(seq_bundle)(seq_params, prompt, 4)
    fast = make_sampler(seq_bundle, kv_cache=True)(seq_params, prompt, 4)
    assert fast == slow


def test_kv_cache_gpt2_matches_recompute():
    """gpt2's cache path: no rope (the learned position row is added at
    embed, including for the single decode token) — cached greedy tokens
    must equal the recompute sampler's."""
    bundle = get_model("gpt2-debug", dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(5))
    prompt = [7, 19]
    slow = make_sampler(bundle)(params, prompt, 6)
    fast = make_sampler(bundle, kv_cache=True)(params, prompt, 6)
    assert fast == slow


def test_kv_cache_qwen3_qk_norm_matches_recompute():
    """Qwen3's per-head q/k RMSNorm rides attention_sublayer, so the cache
    path (k written post-norm+rope, like HF's cache) must reproduce the
    recompute sampler's greedy tokens."""
    bundle = get_model("qwen3-0.6b", vocab_size=256, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, head_dim=16,
                       max_position_embeddings=128, dtype=jnp.float32)
    assert bundle.config.qk_norm
    params = bundle.init(bundle.config, jax.random.key(7))
    prompt = [4, 31]
    slow = make_sampler(bundle)(params, prompt, 5)
    fast = make_sampler(bundle, kv_cache=True)(params, prompt, 5)
    assert fast == slow


def test_kv_cache_olmo2_post_norm_matches_recompute():
    """OLMo-2's post-norm wiring through the cache path: the decode body's
    residuals norm the sublayer OUTPUTS; cached greedy must equal recompute."""
    bundle = get_model("olmo2-7b", vocab_size=256, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, max_position_embeddings=128,
                       dtype=jnp.float32)
    assert bundle.config.post_norm and bundle.config.qk_norm == "flat"
    params = bundle.init(bundle.config, jax.random.key(8))
    prompt = [6, 17, 2]
    slow = make_sampler(bundle)(params, prompt, 5)
    fast = make_sampler(bundle, kv_cache=True)(params, prompt, 5)
    assert fast == slow


def test_kv_cache_gemma2_matches_recompute():
    """Gemma-2's cache path: sandwich norms, softcaps, score scale, and the
    per-layer window column threaded through the decode scans — cached
    greedy must equal the recompute sampler past the sliding window."""
    bundle = get_model("gemma2-2b", vocab_size=256, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=2, head_dim=16,
                       layer_windows=(8, 0), query_pre_attn_scalar=24.0,
                       max_position_embeddings=128, dtype=jnp.float32)
    assert bundle.config.sandwich_norm and bundle.config.layer_windows
    params = bundle.init(bundle.config, jax.random.key(9))
    prompt = list(range(2, 14))            # prompt longer than the window
    slow = make_sampler(bundle)(params, prompt, 6)
    fast = make_sampler(bundle, kv_cache=True)(params, prompt, 6)
    assert fast == slow


def test_kv_cache_moe_matches_recompute():
    """The MoE cache path: routed FFN per decoded token (drop-free expert
    dispatch in prefill/decode) through the shared cache contract. The
    recompute side uses capacity_factor = num_experts so IT is drop-free
    too — with zero drops on both sides, per-token routing is independent
    of the other buffer rows and cached greedy must equal recompute."""
    bundle = get_model("moe-debug", dtype=jnp.float32, capacity_factor=4.0)
    params = bundle.init(bundle.config, jax.random.key(6))
    prompt = [12, 3, 44]
    slow = make_sampler(bundle)(params, prompt, 6)
    fast = make_sampler(bundle, kv_cache=True)(params, prompt, 6)
    assert fast == slow

    # prefill logits == plain forward last position (router included)
    from distributed_training_guide_tpu.models import moe

    cache = moe.init_cache(bundle.config, 1, len(prompt) + 2)
    ids = jnp.asarray(prompt, jnp.int32)[None, :]
    logit, cache = moe.prefill(bundle.config, params, ids, cache)
    full = bundle.apply(bundle.config, params, ids)
    np.testing.assert_allclose(np.asarray(logit), np.asarray(full[:, -1]),
                               rtol=1e-5, atol=1e-5)


def test_kv_cache_qwen2_moe_shared_expert_matches_recompute():
    """The shared expert (+ QKV biases) through the MoE cache path: the
    sigmoid-gated dense branch runs per decoded token alongside the
    drop-free routed dispatch — cached greedy must equal recompute."""
    bundle = get_model("qwen1.5-moe-a2.7b", vocab_size=256, hidden_size=64,
                       intermediate_size=48, shared_expert_intermediate=80,
                       num_layers=2, num_heads=4, num_kv_heads=2,
                       num_experts=4, experts_per_token=2,
                       max_position_embeddings=128, capacity_factor=4.0,
                       dtype=jnp.float32)
    assert bundle.config.shared_expert_intermediate and bundle.config.attn_bias
    params = bundle.init(bundle.config, jax.random.key(11))
    prompt = [9, 40, 3]
    slow = make_sampler(bundle)(params, prompt, 6)
    fast = make_sampler(bundle, kv_cache=True)(params, prompt, 6)
    assert fast == slow


def test_sampler_library_length_guard():
    """make_sampler used as a LIBRARY must refuse prompt+steps past the
    position table (both modes) — the CLI-only check left silent jit
    clamping (ADVICE r4)."""
    import pytest

    bundle = get_model("gpt2-debug", dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(0))
    max_pos = bundle.config.max_position_embeddings
    for kv in (False, True):
        with pytest.raises(ValueError, match="max_position_embeddings"):
            make_sampler(bundle, kv_cache=kv)(params, [1, 2], max_pos)


def test_cli_hermetic_path(capsys):
    main(["-m", "llama-debug", "--prompt-ids", "1,2,3", "--steps", "4"])
    out = capsys.readouterr().out.strip().split(",")
    assert len(out) == 7 and all(t.isdigit() for t in out)


def test_cli_refuses_past_position_table():
    """The CLI has no guard of its own anymore (it drifted against the
    library's): make_sampler's check_length surfaces through main()."""
    import pytest

    with pytest.raises(ValueError, match="max_position_embeddings"):
        main(["-m", "gpt2-debug", "--prompt-ids", "1,2",
              "--steps", "4000"])


def test_cli_text_prompt_via_byte_tokenizer_fallback(capsys):
    """--prompt with no HF tokenizer cached falls back to ByteTokenizer,
    whose batched [[ids]] output must be unwrapped, not crash."""
    main(["-m", "llama-debug", "--prompt", "hi", "--steps", "2"])
    assert capsys.readouterr().out.strip()
