"""Sampler correctness: the jit decode step must equal a naive per-step
argmax reference (position indexing into the fixed buffer is where an
off-by-one would hide)."""
import jax
import jax.numpy as jnp
import numpy as np

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.models.sample import make_sampler, main


def test_greedy_matches_naive_reference():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(0))
    prompt = [3, 17, 42]
    steps = 5
    out = make_sampler(bundle)(params, prompt, steps)
    assert out[:3] == prompt and len(out) == len(prompt) + steps

    # naive reference: grow a python list, argmax the last position's
    # logits over the same zero-padded buffer the sampler uses
    ids = list(prompt)
    for t in range(steps):
        buf = np.zeros((1, len(prompt) + steps), np.int32)
        buf[0, :len(ids)] = ids
        logits = np.asarray(bundle.apply(bundle.config, params,
                                         jnp.asarray(buf)))
        ids.append(int(np.argmax(logits[0, len(ids) - 1])))
    assert out == ids


def test_temperature_sampling_is_seeded_and_in_vocab():
    bundle = get_model("gpt2-debug", dtype=jnp.float32)
    params = bundle.init(bundle.config, jax.random.key(1))
    sample = make_sampler(bundle, temperature=0.8)
    a = sample(params, [5, 6], 6, rng=jax.random.key(7))
    b = sample(params, [5, 6], 6, rng=jax.random.key(7))
    assert a == b                       # same seed, same draw
    assert all(0 <= t < bundle.config.vocab_size for t in a)


def test_cli_hermetic_path(capsys):
    main(["-m", "llama-debug", "--prompt-ids", "1,2,3", "--steps", "4"])
    out = capsys.readouterr().out.strip().split(",")
    assert len(out) == 7 and all(t.isdigit() for t in out)


def test_cli_refuses_past_position_table():
    import pytest

    with pytest.raises(SystemExit, match="max_position_embeddings"):
        main(["-m", "gpt2-debug", "--prompt-ids", "1,2",
              "--steps", "4000"])


def test_cli_text_prompt_via_byte_tokenizer_fallback(capsys):
    """--prompt with no HF tokenizer cached falls back to ByteTokenizer,
    whose batched [[ids]] output must be unwrapped, not crash."""
    main(["-m", "llama-debug", "--prompt", "hi", "--steps", "2"])
    assert capsys.readouterr().out.strip()
