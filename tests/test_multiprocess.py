"""True multi-process execution tests (VERDICT r3 item 3).

The reference actually runs N processes (``torchrun --standalone``, reference
``02-distributed-data-parallel/README.md:96``); through round 3 every test in
this repo was one process with 8 virtual devices, so ``launch/distributed.py``,
the procguards barriers, per-process shard materialization in
``data/loader.py``, and multihost Orbax save/restore had never run in the
regime they exist for. These tests spawn REAL gangs — 2 processes x 4 virtual
CPU devices, rendezvousing through jax.distributed's TCP coordinator via the
``MASTER_ADDR``/``WORLD_SIZE``/``RANK`` env contract — and drive the real
chapter entry points end to end:

- ddp training whose loss trajectory matches the same config single-process
  (the global computation is process-layout-invariant);
- fsdp (params sharded ACROSS processes) training;
- checkpoint save + cross-restart resume, bit-exact vs uninterrupted;
- process0_first ordering with real barriers;
- supervisor restart-all around a gang where one rank crashes, resuming
  from the last checkpoint (torchrun elasticity, reference
  ``related-topics/elastic-training/README.md:5-16``).

Each gang is a fresh OS process group, so steps are compiled per gang; a
shared persistent XLA compile cache keeps the suite's wall time sane.
"""
import ast
import json
import os
import subprocess
import sys
import tempfile
from pathlib import Path

import jax
import pytest

# jax < 0.5 hard-fails any sharded computation spanning processes on CPU
# ("INVALID_ARGUMENT: Multiprocess computations aren't implemented on the
# CPU backend" out of the first jitted program) — the gang-TRAINING tests
# cannot pass there and each burns a full gang spawn before failing, starving
# the rest of the tier-1 time budget. Barrier/loader scenarios (no sharded
# compute) still run. Drop this gate when the environment's jax moves >= 0.5.
_JAX_VERSION = tuple(int(p) for p in jax.__version__.split(".")[:2])
requires_mp_compute = pytest.mark.skipif(
    _JAX_VERSION < (0, 5),
    reason="jax<0.5 CPU backend cannot run multiprocess computations")

REPO = Path(__file__).parent.parent
CH02 = REPO / "02-distributed-data-parallel" / "train_llm.py"
CH04 = REPO / "04-fully-sharded-data-parallel" / "train_llm.py"
MP_COMPILE_CACHE = os.path.join(tempfile.gettempdir(), "dtg_tpu_mp_compile_cache")

TRAIN_FLAGS = ["-m", "llama-debug", "-d", "synthetic:60000", "-s", "64",
               "-b", "1", "--num-epochs", "2", "--log-freq", "1"]


def _clean_env(**extra) -> dict:
    """Worker env: the launcher overrides the conftest's 8-device XLA_FLAGS
    with per-process counts; the shared compile cache spans gangs."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_COMPILATION_CACHE_DIR"] = MP_COMPILE_CACHE
    env.update(extra)
    return env


def run_gang(worker_cmd: list, *, nproc: int = 2, devices: int = 4,
             timeout: int = 600, env: dict | None = None,
             log_dir: str | None = None) -> tuple:
    """Launch a gang via the real ``launch.local`` CLI; returns
    (rc, rank0_text, [rankN_text...])."""
    cmd = [sys.executable, "-m", "distributed_training_guide_tpu.launch.local",
           "--nproc", str(nproc), "--devices-per-proc", str(devices)]
    if log_dir:
        cmd += ["--log-dir", log_dir]
    cmd += ["--"] + worker_cmd
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout,
                          env=env or _clean_env(), cwd=REPO)
    rank0 = proc.stdout + proc.stderr
    others = []
    if log_dir:
        for rank in range(1, nproc):
            text = ""
            for suffix in ("out", "err"):
                p = Path(log_dir) / f"rank{rank}.{suffix}"
                if p.exists():
                    text += p.read_text()
            others.append(text)
    return proc.returncode, rank0, others


def parse_info_lines(text: str) -> list:
    """The training loop logs metric dicts (``INFO:{'global_step': ...}``);
    pull them back out of the process logs."""
    infos = []
    for line in text.splitlines():
        at = line.find("INFO:{")
        if at >= 0:
            try:
                d = ast.literal_eval(line[at + 5:])
            except (ValueError, SyntaxError):
                continue
            if isinstance(d, dict) and "global_step" in d:  # skip env dumps
                infos.append(d)
    return infos


def losses_by_step(text: str) -> dict:
    return {i["global_step"]: i["running_loss"] for i in parse_info_lines(text)}


def mp_results(text: str) -> list:
    return [json.loads(line.split("MPRESULT ", 1)[1])
            for line in text.splitlines() if line.startswith("MPRESULT ")]


@pytest.fixture(scope="module")
def warm_cache():
    os.makedirs(MP_COMPILE_CACHE, exist_ok=True)


def single_process_losses(script, flags: list, save_dir) -> dict:
    """Golden: the same chapter entry on 1 process x 8 virtual devices."""
    sp = subprocess.run(
        [sys.executable, str(script), *flags, "--save-dir", str(save_dir)],
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env=_clean_env(JAX_PLATFORMS="cpu",
                       XLA_FLAGS="--xla_force_host_platform_device_count=8"))
    assert sp.returncode == 0, (sp.stdout + sp.stderr)[-3000:]
    return losses_by_step(sp.stdout + sp.stderr)


@requires_mp_compute
def test_gang_ddp_matches_single_process(tmp_path, warm_cache):
    """2 procs x 4 devices and 1 proc x 8 devices build the same dp=8 mesh
    over the same global batch: the logged loss trajectory must agree. This
    is the process-layout invariance the reference gets from DDP's defined
    semantics — here it also proves the loader's per-process shard
    materialization feeds the same global arrays."""
    worker = [sys.executable, str(CH02), *TRAIN_FLAGS, "--max-steps", "6",
              "--save-dir", str(tmp_path / "mp")]
    rc, rank0, (rank1,) = run_gang(worker, log_dir=str(tmp_path / "logs"))
    assert rc == 0, rank0[-3000:]
    mp_losses = losses_by_step(rank0)
    assert set(mp_losses) == {1, 2, 3, 4, 5, 6}

    # ranks log the same (replicated) loss values
    rank1_losses = losses_by_step(rank1)
    assert rank1_losses == mp_losses

    # single-process golden at the same global config
    sp_losses = single_process_losses(
        CH02, [*TRAIN_FLAGS, "--max-steps", "6"], tmp_path / "sp")
    assert set(sp_losses) == set(mp_losses)
    for step, loss in mp_losses.items():
        # identical global math; only collective reduction order may differ
        assert abs(loss - sp_losses[step]) < 1e-4, (step, loss, sp_losses[step])


@requires_mp_compute
def test_gang_fence_every_matches_per_step(tmp_path, warm_cache):
    """--fence-every across a REAL process boundary: each process banks its
    own device-loss reads and drains at the (log-freq) boundary; the logged
    running_loss windows must equal a per-step-fenced single-process run.
    log-freq 3 (not 1) so the fence group actually runs at depth 3."""
    assert TRAIN_FLAGS[-2:] == ["--log-freq", "1"]
    flags = TRAIN_FLAGS[:-1] + ["3"]
    worker = [sys.executable, str(CH02), *flags, "--max-steps", "6",
              "--fence-every", "3", "--save-dir", str(tmp_path / "mp")]
    rc, rank0, (rank1,) = run_gang(worker, log_dir=str(tmp_path / "logs"))
    assert rc == 0, rank0[-3000:]
    mp_losses = losses_by_step(rank0)
    assert set(mp_losses) == {3, 6}
    assert losses_by_step(rank1) == mp_losses

    sp_losses = single_process_losses(
        CH02, [*flags, "--max-steps", "6"], tmp_path / "sp")
    assert set(sp_losses) == set(mp_losses)
    for step, loss in mp_losses.items():
        assert abs(loss - sp_losses[step]) < 1e-4, (step, loss, sp_losses)


@requires_mp_compute
def test_gang_fsdp_trains_with_cross_process_shards(tmp_path, warm_cache):
    """fsdp shards every parameter over all 8 devices, i.e. ACROSS the two
    processes: init, step collectives, and the loader all have to handle
    arrays where each process owns only half the shards."""
    worker = [sys.executable, str(CH04), *TRAIN_FLAGS, "--max-steps", "4",
              "--checkpoint-activations", "--save-dir", str(tmp_path / "out")]
    rc, rank0, (rank1,) = run_gang(worker, log_dir=str(tmp_path / "logs"))
    assert rc == 0, rank0[-3000:]
    losses = losses_by_step(rank0)
    assert set(losses) == {1, 2, 3, 4}
    # 4 steps at the default lr is noise-level: assert sane, not "learning"
    assert all(5.0 < v < 7.5 for v in losses.values()), losses
    assert losses_by_step(rank1) == losses
    assert "strategy=fsdp" in rank0


@requires_mp_compute
def test_gang_tp_spans_process_boundary(tmp_path, warm_cache):
    """tp=8 on a 2-process x 4-device gang: every tensor-parallel group
    crosses the process boundary, so the per-layer megatron all-reduces run
    over the inter-process transport (the DCN analogue) — the sharding
    regime chapter 6 documents but no single-process test can produce."""
    worker = [sys.executable, str(REPO / "06-tensor-parallel" / "train_llm.py"),
              *TRAIN_FLAGS, "--max-steps", "3", "--tensor-parallel", "8",
              "--save-dir", str(tmp_path / "out")]
    rc, rank0, (rank1,) = run_gang(worker, log_dir=str(tmp_path / "logs"))
    assert rc == 0, rank0[-3000:]
    losses = losses_by_step(rank0)
    assert set(losses) == {1, 2, 3}
    assert all(5.0 < v < 7.5 for v in losses.values()), losses
    assert losses_by_step(rank1) == losses
    assert "'tp': 8" in rank0


@requires_mp_compute
def test_gang_ring_cp_spans_process_boundary(tmp_path, warm_cache):
    """cp=8 on a 2-process x 4-device gang: the zigzag ring's ppermute hops
    cross the process boundary every cycle — the long-context regime a
    real pod runs (ring over ICI/DCN), never reachable single-process."""
    worker = [sys.executable, str(REPO / "08-context-parallel" / "train_llm.py"),
              *TRAIN_FLAGS, "--max-steps", "3", "--context-parallel", "8",
              "--attn-impl", "xla", "--save-dir", str(tmp_path / "out")]
    rc, rank0, (rank1,) = run_gang(worker, log_dir=str(tmp_path / "logs"))
    assert rc == 0, rank0[-3000:]
    losses = losses_by_step(rank0)
    assert set(losses) == {1, 2, 3}
    assert all(5.0 < v < 7.5 for v in losses.values()), losses
    assert losses_by_step(rank1) == losses
    assert "'cp': 8" in rank0


@requires_mp_compute
def test_gang_pipeline_stage_per_process(tmp_path, warm_cache):
    """pp=2 on a 2-process x 4-device gang with the pp axis outermost:
    each pipeline stage lives on one process, so every 1F1B activation /
    cotangent handoff crosses the process boundary — how a pod actually
    runs pipeline parallelism (stages over DCN)."""
    worker = [sys.executable, str(REPO / "09-pipeline-parallel" / "train_llm.py"),
              *TRAIN_FLAGS, "-b", "4",   # microbatch (gb/4) must cover dp=4
              "--max-steps", "3", "--pipeline-parallel", "2",
              "--save-dir", str(tmp_path / "out")]
    rc, rank0, (rank1,) = run_gang(worker, log_dir=str(tmp_path / "logs"))
    assert rc == 0, rank0[-3000:]
    losses = losses_by_step(rank0)
    assert set(losses) == {1, 2, 3}
    assert all(5.0 < v < 7.5 for v in losses.values()), losses
    assert losses_by_step(rank1) == losses
    assert "'pp': 2" in rank0


@requires_mp_compute
def test_gang_moe_ep_spans_process_boundary(tmp_path, warm_cache):
    """ep=8 on a 2-process x 4-device gang: the MoE token all-to-all
    dispatches across the process boundary (each process hosts half the
    experts). With ddp/fsdp (all-reduce/all-gather), tp (per-layer
    reductions), and ring cp (ppermute) above, this completes the
    cross-process coverage of every collective family the framework emits."""
    worker = [sys.executable,
              str(REPO / "10-mixture-of-experts" / "train_llm.py"),
              "-m", "moe-debug", "-d", "synthetic:60000", "-s", "64",
              "-b", "1", "--num-epochs", "2", "--log-freq", "1",
              "--max-steps", "3", "--expert-parallel", "8",
              "--save-dir", str(tmp_path / "out")]
    rc, rank0, (rank1,) = run_gang(worker, log_dir=str(tmp_path / "logs"))
    assert rc == 0, rank0[-3000:]
    losses = losses_by_step(rank0)
    assert set(losses) == {1, 2, 3}
    assert all(5.0 < v < 7.5 for v in losses.values()), losses
    assert losses_by_step(rank1) == losses
    assert "'ep': 8" in rank0


@requires_mp_compute
def test_gang_checkpoint_resume_bitexact(tmp_path, warm_cache):
    """Multihost Orbax save (every process writes its shards, process 0
    swings state.json behind a barrier) + restore in a FRESH gang, compared
    bit-exact against an uninterrupted run — the reference's resume contract
    (01:94) upgraded to the multi-process regime."""
    exp = ["--ckpt-freq", "3", "-e", "resume", "--save-dir", str(tmp_path)]

    worker3 = [sys.executable, str(CH02), *TRAIN_FLAGS, "--max-steps", "3", *exp]
    rc, out3, _ = run_gang(worker3, log_dir=str(tmp_path / "l1"))
    assert rc == 0, out3[-3000:]
    assert "Resumed=False" in out3
    assert (tmp_path / "resume" / "state.json").exists()

    worker6 = [sys.executable, str(CH02), *TRAIN_FLAGS, "--max-steps", "6", *exp]
    rc, out6, _ = run_gang(worker6, log_dir=str(tmp_path / "l2"))
    assert rc == 0, out6[-3000:]
    assert "Resumed=True" in out6
    resumed = losses_by_step(out6)
    assert set(resumed) == {4, 5, 6}      # fast-forwarded past steps 1-3

    # uninterrupted 6-step gang in a fresh experiment dir
    gold = [sys.executable, str(CH02), *TRAIN_FLAGS, "--max-steps", "6",
            "--ckpt-freq", "3", "-e", "gold", "--save-dir", str(tmp_path)]
    rc, outg, _ = run_gang(gold, log_dir=str(tmp_path / "l3"))
    assert rc == 0, outg[-3000:]
    golden = losses_by_step(outg)
    for step in (4, 5, 6):
        assert resumed[step] == golden[step], (step, resumed[step], golden[step])


def test_gang_procguards_ordering(tmp_path, warm_cache):
    """process0_first over real processes: rank 1 must observe the file rank
    0 wrote inside the guard, despite rank 0 sleeping first."""
    worker = [sys.executable, str(REPO / "tests" / "mp_worker.py"), "guard",
              "--dir", str(tmp_path)]
    rc, rank0, (rank1,) = run_gang(worker, log_dir=str(tmp_path / "logs"))
    assert rc == 0, rank0[-3000:]
    results = {r["rank"]: r for r in mp_results(rank0) + mp_results(rank1)}
    assert results[0]["world"] == 2
    assert results[1]["saw_marker_on_entry"] is True


def test_gang_loader_materializes_only_local_shards(tmp_path, warm_cache):
    """The per-host data-footprint claim, measured: over a full epoch each
    process fetches exactly its 1/nproc share of every batch's rows from the
    corpus (so a disk-backed corpus costs each host ~batch/nproc RAM), and
    every addressable shard's content matches direct corpus indexing."""
    worker = [sys.executable, str(REPO / "tests" / "mp_worker.py"), "loader",
              "--dir", str(tmp_path)]
    rc, rank0, (rank1,) = run_gang(worker, log_dir=str(tmp_path / "logs"))
    assert rc == 0, rank0[-3000:]
    results = {r["rank"]: r for r in mp_results(rank0) + mp_results(rank1)}
    assert set(results) == {0, 1}
    for r in results.values():
        assert r["content_ok"] is True
        assert r["n_batches"] > 50
        # exactly half of every batch's rows, never the global batch
        assert r["rows_fetched"] == r["n_batches"] * r["global_batch"] // 2


@requires_mp_compute
def test_supervisor_restarts_gang_and_resumes(tmp_path, warm_cache):
    """The torchrun-elasticity loop end to end: rank 1 crashes after the
    step-3 checkpoint; fail-fast takes the gang down; the supervisor
    restarts it as a unit; the restarted gang resumes from the checkpoint
    and finishes. Also pins the @record error-file contract per rank."""
    work = tmp_path / "work"
    work.mkdir()
    sup_logs = tmp_path / "sup"
    cmd = [sys.executable, "-m",
           "distributed_training_guide_tpu.launch.supervisor",
           "--max-restarts", "2", "--log-dir", str(sup_logs), "--",
           sys.executable, "-m", "distributed_training_guide_tpu.launch.local",
           "--nproc", "2", "--devices-per-proc", "4",
           "--log-dir", str(tmp_path / "ranks"), "--",
           sys.executable, str(REPO / "tests" / "mp_worker.py"),
           "crash_train", "--dir", str(work)]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=900,
                          env=_clean_env(), cwd=REPO)
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-3000:]
    assert "attempt 0 failed" in proc.stdout
    assert "attempt 1 exited cleanly" in proc.stdout

    # rank 1's injected failure was captured machine-readably (@record)
    err_file = sup_logs / "attempt_0" / "error.json.rank1"
    assert err_file.exists()
    payload = json.loads(err_file.read_text())
    assert "injected failure" in payload["message"]["error"]
    assert payload["message"]["process_index"] == 1

    # the restarted gang resumed from the step-3 checkpoint and finished
    attempt1_out = (sup_logs / "attempt_1" / "stdout.log").read_text() + \
        (sup_logs / "attempt_1" / "stderr.log").read_text()
    assert "Resumed=True" in attempt1_out
    results = mp_results(attempt1_out)
    assert results and results[0]["global_step"] == 8
