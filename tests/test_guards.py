"""Step-level non-finite guards (train/guards.py) + the deterministic NaN
fault: in-jit detection/skip-select semantics, host-side policy enforcement,
and the engine's step_guards config surface (ISSUE 1 tentpole part 2)."""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.parallel import make_mesh, make_plan
from distributed_training_guide_tpu.train import Trainer, adamw_cosine
from distributed_training_guide_tpu.train.guards import (
    GuardMonitor, NonFiniteLossError)
from distributed_training_guide_tpu.utils.faults import ENV_NAN_LOSS_STEP


def make_trainer(**kw):
    bundle = get_model("llama-debug", dtype=jnp.float32)
    return Trainer(bundle=bundle, optimizer=adamw_cosine(1e-3),
                   plan=make_plan("ddp", make_mesh()), **kw)


def batch_for(t, seed=0):
    ids = jnp.asarray(np.random.RandomState(seed).randint(0, 512, (8, 16)))
    return {k: jax.device_put(ids, t.batch_shardings()[k])
            for k in ("input_ids", "labels")}


def leaves(state):
    return [np.asarray(x) for x in jax.tree.leaves(jax.device_get(state.params))]


def test_skip_policy_reverts_poisoned_update(eight_devices, monkeypatch):
    """NaN injected at state.step==1 (the second call): the skip policy must
    keep params/opt-state bit-identical to the pre-step values, advance the
    step counter, and recover on the next (finite) step."""
    monkeypatch.setenv(ENV_NAN_LOSS_STEP, "1")
    t = make_trainer(guard_policy="skip", donate=False)
    batch = batch_for(t)
    s1, m1 = t.step_fn(t.init_state(0), batch)
    assert float(m1["notfinite"]) == 0.0

    before = leaves(s1)
    s2, m2 = t.step_fn(s1, batch)
    assert float(m2["notfinite"]) == 1.0
    assert not np.isfinite(float(m2["loss"]))         # honest metric
    for a, b in zip(before, leaves(s2)):
        np.testing.assert_array_equal(a, b)           # update dropped
    assert int(s2.step) == 2                          # schedule still advances

    s3, m3 = t.step_fn(s2, batch)
    assert float(m3["notfinite"]) == 0.0
    assert np.isfinite(float(m3["loss"]))
    assert any(not np.array_equal(a, b)
               for a, b in zip(before, leaves(s3)))   # training resumed


def test_guard_off_keeps_metric_surface(eight_devices):
    t = make_trainer(donate=False)
    _, m = t.step_fn(t.init_state(0), batch_for(t))
    assert "notfinite" not in m                       # zero-cost when off


def test_monitor_abort_writes_error_file(tmp_path, monkeypatch):
    err = tmp_path / "error.json"
    monkeypatch.setenv("ERROR_FILE", str(err))
    mon = GuardMonitor("abort")
    with pytest.raises(NonFiniteLossError, match="step 7"):
        mon.observe(1.0, step=7, metrics={"loss": float("nan")})
    msg = json.loads(err.read_text())["message"]
    assert "NonFiniteLossError" in msg["error"]
    assert "step 7" in msg["error"]
    assert "NoneType: None" not in msg["traceback"]   # satellite fix


def test_monitor_skip_threshold(tmp_path, monkeypatch):
    monkeypatch.setenv("ERROR_FILE", str(tmp_path / "e.json"))
    mon = GuardMonitor("skip", max_consecutive_skips=2)
    assert mon.observe(1.0, step=1) is True
    assert mon.observe(1.0, step=2) is True
    assert mon.observe(0.0, step=3) is False          # finite resets the run
    assert mon.observe(1.0, step=4) is True
    assert mon.observe(1.0, step=5) is True
    with pytest.raises(NonFiniteLossError, match="consecutive"):
        mon.observe(1.0, step=6)
    assert mon.total_skipped == 5
    assert (tmp_path / "e.json").exists()


def test_monitor_off_is_inert():
    mon = GuardMonitor("off")
    assert mon.observe(1.0, step=1) is False
    assert not mon.enabled


def test_trainer_rejects_unknown_policy():
    with pytest.raises(ValueError, match="guard policy"):
        make_trainer(guard_policy="panic")


def test_engine_step_guards_config(eight_devices, monkeypatch):
    """The DeepSpeed-surface spelling: step_guards in the engine config wires
    the in-jit guard and the host monitor; a NaN step reports skipped=1 and
    leaves the next step trainable."""
    monkeypatch.setenv(ENV_NAN_LOSS_STEP, "1")
    from distributed_training_guide_tpu.train.engine import initialize

    engine = initialize({
        "model": "llama-debug",
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "step_guards": {"policy": "skip", "max_consecutive_skips": 3},
    })
    ids = np.random.RandomState(0).randint(0, 512, (engine.global_batch_size, 32))
    batch_sh = engine.trainer.batch_shardings()
    batch = {k: jax.device_put(ids, batch_sh[k]) for k in ("input_ids", "labels")}
    m1 = engine.train_batch(batch)
    assert m1["notfinite"] == 0.0 and m1["guard_skipped"] == 0.0
    m2 = engine.train_batch(batch)                    # state.step==1: poisoned
    assert m2["notfinite"] == 1.0 and m2["guard_skipped"] == 1.0
    m3 = engine.train_batch(batch)
    assert m3["notfinite"] == 0.0 and np.isfinite(m3["loss"])
    engine.close()


def test_engine_caches_checkpoint_io(tmp_path, eight_devices):
    """save/load_checkpoint reuse ONE CheckpointIO per destination (retention
    and async state live on the IO object; a throwaway per call would leak
    its Orbax resources and re-run the orphan sweep every save), and close()
    releases them."""
    from distributed_training_guide_tpu.train.engine import initialize

    engine = initialize({"model": "llama-debug",
                         "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
    engine.save_checkpoint(tmp_path / "eng")
    io_first = engine._ios[str(tmp_path / "eng")]
    engine.save_checkpoint(tmp_path / "eng")
    engine.load_checkpoint(tmp_path / "eng")
    assert engine._ios[str(tmp_path / "eng")] is io_first   # reused
    assert len(engine._ios) == 1
    engine.save_checkpoint(tmp_path / "other")
    assert len(engine._ios) == 2
    engine.close()
    assert engine._ios == {}
