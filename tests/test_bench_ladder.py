"""bench.py parent-orchestration tests (no device, no subprocesses).

The degradation ladder is what turns a sick shared pool into a nonzero
official number (BENCH.md "Round-2 hardening"), so its control flow — walk
on stall, retry pass, OOM classification, best-so-far selection, the
attn-vs-all bonus A/B — is pinned here with a scripted fake `_run_child`.
The reference has no analogue (its quality strategy is runnable examples,
SURVEY.md section 4); this guards the driver-facing measurement path.
"""
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


def _mfu(value, steps=10, partial=False, **detail):
    out = {"metric": "mfu", "value": value, "unit": "fraction_of_peak_bf16",
           "vs_baseline": round(value / 0.335, 3),
           "detail": {"steps_timed": steps, **detail}}
    if partial:
        out["partial"] = True
    return out


_real_git_head = bench._git_head
_real_commit_in_history = bench._commit_in_history


@pytest.fixture(autouse=True)
def _isolated_caches(tmp_path, monkeypatch):
    """Keep test runs away from the REAL evidence cache (.bench_last_good.json
    holds the measured headline; a fake 0.52 must never clobber it). The git
    provenance helpers are stubbed: they shell out to git, whose subprocess
    wait loop calls the time.sleep these tests monkeypatch to count probe
    gating."""
    monkeypatch.setattr(bench, "LAST_GOOD_PATH", str(tmp_path / "last_good.json"))
    monkeypatch.setattr(bench, "FLASH_GOOD_PATH", str(tmp_path / "flash_good.json"))
    monkeypatch.setattr(bench, "SWEEP_LOG_PATH", str(tmp_path / "sweep.jsonl"))
    monkeypatch.setattr(bench, "_git_head", lambda: "f" * 40)
    monkeypatch.setattr(bench, "_commit_in_history", lambda c: c == "f" * 40)


class FakeChildren:
    """Scripted responses: probe -> platform line (or a scripted failure);
    rung -> pop from queue; flash check -> fixed record. Each rung response
    is (lines, kind); each probe response is True (healthy) or False."""

    def __init__(self, rung_responses, platform="tpu", probe_responses=None):
        self.rung_responses = list(rung_responses)
        self.probe_responses = list(probe_responses or [])
        self.platform = platform
        self.calls = []

    def __call__(self, mode_args, budget):
        self.calls.append(mode_args)
        assert budget > 0
        if mode_args == ["--probe"]:
            ok = self.probe_responses.pop(0) if self.probe_responses else True
            if not ok:
                return [], "stalled"
            return [{"platform": self.platform, "n_devices": 1}], "ok"
        if mode_args == ["--check-flash"]:
            return [{"flash_ms": 70.0, "xla_ms": 95.0, "ok": True}], "ok"
        if mode_args == ["--check-decode"]:
            return [{"metric": "decode_tput", "value": 321.0,
                     "model": "llama-debug"}], "ok"
        assert mode_args[0] == "--rung"
        if not self.rung_responses:
            return [], "stalled"
        return self.rung_responses.pop(0)


def _run_main(monkeypatch, capsys, fake, argv=("--watchdog", "0")):
    monkeypatch.setattr(bench, "_run_child", fake)
    monkeypatch.setattr(sys, "argv", ["bench.py", *argv])
    code = 0
    try:
        bench.main()
    except SystemExit as e:
        code = e.code or 0
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    return lines[-1], code


def test_headline_success_records_ab_and_flash(monkeypatch, capsys):
    """Healthy pool: rung 1 full success -> bonus 'all'-policy A/B runs, and
    the verified headline stays final when the A/B is slower."""
    fake = FakeChildren([
        ([_mfu(0.50)], "ok"),          # headline rung (remat_policy=attn)
        ([_mfu(0.48)], "ok"),          # bonus A/B at ladder[1] (policy=all)
    ])
    final, code = _run_main(monkeypatch, capsys, fake)
    assert code == 0 and final["value"] == 0.50
    statuses = [e["status"] for e in final["detail"]["ladder"]]
    assert statuses == ["ok", "ok"]
    assert final["detail"]["flash_check"]["ok"] is True
    assert final["detail"]["decode_tput"]["value"] == 321.0  # serving rung
    # never reached rungs 3/4: 1 probe + 2 rungs + flash + decode checks
    assert len(fake.calls) == 5


def test_stalled_flash_check_attaches_cached_record(monkeypatch, capsys):
    """The flash A/B runs LAST on leftover budget, so it is the likeliest
    child to stall; a clean earlier record (commit-stamped, same device)
    must back the failed run instead of evidence silently vanishing."""
    healthy = FakeChildren([([_mfu(0.50)], "ok"), ([_mfu(0.48)], "ok")])
    _run_main(monkeypatch, capsys, healthy)
    assert bench._load_flash_good()["ok"] is True  # cache written

    class FlashStalls(FakeChildren):
        def __call__(self, mode_args, budget):
            if mode_args == ["--check-flash"]:
                self.calls.append(mode_args)
                return [], "stalled"
            return super().__call__(mode_args, budget)

    stalled = FlashStalls([([_mfu(0.51)], "ok"), ([_mfu(0.48)], "ok")])
    final, code = _run_main(monkeypatch, capsys, stalled)
    fc = final["detail"]["flash_check"]
    assert code == 0 and fc["error"] == "stalled"
    assert fc["last_good"]["ok"] is True
    assert fc["last_good"]["git_commit"] == "f" * 40

    # a COMPLETED check whose numerics failed is reported fresh but must
    # never overwrite the cached healthy evidence
    class FlashNumericsFail(FakeChildren):
        def __call__(self, mode_args, budget):
            if mode_args == ["--check-flash"]:
                self.calls.append(mode_args)
                return [{"flash_ms": 70.0, "xla_ms": 95.0, "ok": False}], "ok"
            return super().__call__(mode_args, budget)

    bad = FlashNumericsFail([([_mfu(0.52)], "ok"), ([_mfu(0.48)], "ok")])
    final, _ = _run_main(monkeypatch, capsys, bad)
    assert final["detail"]["flash_check"]["ok"] is False  # reported honestly
    assert bench._load_flash_good()["ok"] is True         # cache untouched


def test_ab_result_displaces_only_when_complete_and_better(monkeypatch, capsys):
    fake = FakeChildren([
        ([_mfu(0.48)], "ok"),
        ([_mfu(0.52)], "ok"),          # A/B wins -> becomes final
    ])
    final, _ = _run_main(monkeypatch, capsys, fake)
    assert final["value"] == 0.52

    fake = FakeChildren([
        ([_mfu(0.48)], "ok"),
        ([_mfu(0.55, partial=True)], "stalled"),  # better but PARTIAL
    ])
    final, _ = _run_main(monkeypatch, capsys, fake)
    assert final["value"] == 0.48   # partial A/B may not displace verified


def test_stall_walks_down_the_ladder(monkeypatch, capsys):
    """Rung 1 dies mid-run after partial emission; rung 2 completes. Pass 1
    stops there — and a smaller complete result wins over a bigger partial."""
    fake = FakeChildren([
        ([_mfu(0.51, steps=3, partial=True)], "stalled"),
        ([_mfu(0.47)], "ok"),
    ])
    final, code = _run_main(monkeypatch, capsys, fake)
    assert code == 0
    assert final["value"] == 0.51   # best-so-far partial is still the max
    assert final["detail"]["ladder"][0]["status"] == "partial_then_stalled"
    assert final["detail"]["ladder"][1]["status"] == "ok"


def test_oom_is_classified_and_walk_continues(monkeypatch, capsys):
    fake = FakeChildren([
        ([], "oom"),
        ([_mfu(0.45)], "ok"),
    ])
    final, _ = _run_main(monkeypatch, capsys, fake)
    assert final["value"] == 0.45
    assert final["detail"]["ladder"][0]["status"] == "oom_attempt_1"


def test_total_stall_then_retry_pass_lands(monkeypatch, capsys):
    """Nothing lands in pass 1 (4 stalls); pass 2's first retry succeeds —
    the compile-cache-makes-retries-cheap design."""
    fake = FakeChildren([
        ([], "stalled"), ([], "stalled"), ([], "stalled"), ([], "stalled"),
        ([_mfu(0.49)], "ok"),
    ])
    final, code = _run_main(monkeypatch, capsys, fake)
    assert code == 0 and final["value"] == 0.49
    assert final["detail"]["ladder"][4]["status"] == "ok"


def test_everything_dead_emits_zero_and_rc2(monkeypatch, capsys):
    fake = FakeChildren([])  # every rung response: stalled, forever
    final, code = _run_main(monkeypatch, capsys, fake)
    assert code == 2
    assert final["value"] == 0.0
    assert "stalled" in json.dumps(final["detail"]["ladder"])


def test_pool_down_gate_sleeps_instead_of_burning_rungs(monkeypatch, capsys):
    """Dead pool at start: the parent sleep-polls the probe and launches NO
    rung until a probe succeeds (round-3 hardening: rung budgets must not be
    burned stalling against a pool the probe already shows is dead)."""
    sleeps = []
    monkeypatch.setattr(bench.time, "sleep", sleeps.append)
    fake = FakeChildren([([_mfu(0.50)], "ok"), ([_mfu(0.48)], "ok")],
                        probe_responses=[False, False, True])
    final, code = _run_main(monkeypatch, capsys, fake)
    assert code == 0 and final["value"] == 0.50
    assert len(sleeps) == 2          # one sleep per failed probe
    probe_calls = [c for c in fake.calls if c == ["--probe"]]
    rung_idx = next(i for i, c in enumerate(fake.calls) if c[0] == "--rung")
    assert len(probe_calls) == 3 and rung_idx == 3  # all probes before rung 1
    assert [p["ok"] for p in final["detail"]["probes"]] == [False, False, True]


def test_stalled_rung_regates_on_probe(monkeypatch, capsys):
    """A rung stall mid-ladder re-gates: the pool must answer a probe before
    the next rung is launched."""
    sleeps = []
    monkeypatch.setattr(bench.time, "sleep", sleeps.append)
    fake = FakeChildren([([], "stalled"), ([_mfu(0.47)], "ok")],
                        probe_responses=[True, False, True])
    final, code = _run_main(monkeypatch, capsys, fake)
    assert code == 0 and final["value"] == 0.47
    # initial probe ok; rung1 stalled; gate probe fails once then succeeds
    assert len([c for c in fake.calls if c == ["--probe"]]) == 3
    assert len(sleeps) == 1


def test_outage_zero_carries_last_good_evidence(monkeypatch, capsys):
    """The round-2 failure mode: pool dead for the whole window. The zero
    line must carry the cached best measurement (value/config/timestamp) so
    the official record is never evidence-free."""
    seeded = {"value": 0.505, "unit": "fraction_of_peak_bf16", "ts": 1.0,
              "utc": "2026-07-29T14:20:00Z",
              "git_commit": bench._git_head(),
              "config": {"model": "llama-650m", "step_ms": 695.0}}
    with open(bench.LAST_GOOD_PATH, "w") as f:
        json.dump(seeded, f)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    fake = FakeChildren([])  # rungs stall forever; probes ok
    final, code = _run_main(monkeypatch, capsys, fake)
    assert code == 2 and final["value"] == 0.0
    assert final["detail"]["last_good"]["value"] == 0.505
    assert final["detail"]["last_good"]["config"]["model"] == "llama-650m"


def test_success_persists_last_good_and_never_degrades(monkeypatch, capsys):
    fake = FakeChildren([([_mfu(0.50)], "ok"), ([_mfu(0.48)], "ok")])
    final, _ = _run_main(monkeypatch, capsys, fake)
    assert bench._load_last_good()["value"] == 0.50
    # a later, worse run must not clobber the best evidence...
    fake = FakeChildren([([_mfu(0.43)], "ok"), ([_mfu(0.41)], "ok")])
    final, _ = _run_main(monkeypatch, capsys, fake)
    assert bench._load_last_good()["value"] == 0.50
    # ...and the degraded line itself points at the better cached number
    assert final["detail"]["last_good"]["value"] == 0.50
    # a better run does take over
    fake = FakeChildren([([_mfu(0.52)], "ok"), ([_mfu(0.48)], "ok")])
    _run_main(monkeypatch, capsys, fake)
    assert bench._load_last_good()["value"] == 0.52


def test_last_good_provenance_gates_attachment(monkeypatch):
    """ADVICE r3 (medium): the evidence cache must not resurface in a tree or
    on hardware it was not measured in. Unstamped legacy records, records
    stamped with a commit outside this tree's history, and records from a
    different device kind all fail closed; a matching record attaches."""
    base = {"value": 0.505, "unit": "fraction_of_peak_bf16", "ts": 1.0,
            "config": {"model": "llama-650m", "device": "TPU v5 lite"}}
    zero = lambda: {"metric": "mfu", "value": 0.0, "detail": {}}

    def seed(**overrides):
        with open(bench.LAST_GOOD_PATH, "w") as f:
            json.dump({**base, **overrides}, f)

    seed()  # legacy: no git_commit at all
    assert "last_good" not in bench._attach_last_good(zero())["detail"]
    seed(git_commit="0" * 40)  # commit not in this tree's history
    assert "last_good" not in bench._attach_last_good(zero())["detail"]
    seed(git_commit=bench._git_head())
    assert bench._attach_last_good(zero())["detail"]["last_good"]["value"] == 0.505
    # same valid commit, but the current line ran on different hardware
    out = {"metric": "mfu", "value": 0.1, "detail": {"device": "H100"}}
    assert "last_good" not in bench._attach_last_good(out)["detail"]
    # ...and on matching hardware it attaches
    out = {"metric": "mfu", "value": 0.1, "detail": {"device": "TPU v5 lite"}}
    assert bench._attach_last_good(out)["detail"]["last_good"]["value"] == 0.505


def test_foreign_commit_cache_is_displaced_not_wedged():
    """A record stamped with a commit outside this tree's history could never
    attach anywhere here — it must not block legitimate new saves."""
    with open(bench.LAST_GOOD_PATH, "w") as f:
        json.dump({"value": 0.505, "git_commit": "0" * 40,
                   "config": {"model": "llama-650m"}}, f)
    rec = bench._save_last_good(_mfu(0.35, model="llama-650m",
                                     device="TPU v5 lite"))
    assert rec["value"] == 0.35           # displaced the unattachable 0.505
    assert bench._load_last_good()["value"] == 0.35
    # a VALID higher cache still wins over a lower new result
    rec2 = bench._save_last_good(_mfu(0.30, device="TPU v5 lite"))
    assert rec2["value"] == 0.35


def test_other_hardware_run_never_touches_the_headline_cache():
    """A valid-commit record from different hardware is still the evidence
    for the driver's TPU bench: a CPU dev-box run must neither destroy it
    (even with a tiny value) nor overwrite it (even with a bigger one)."""
    with open(bench.LAST_GOOD_PATH, "w") as f:
        json.dump({"value": 0.505, "git_commit": bench._git_head(),
                   "config": {"device": "TPU v5 lite"}}, f)
    for value in (0.0008, 0.9):
        rec = bench._save_last_good(_mfu(value, device="cpu"))
        assert rec["value"] == 0.505
        assert bench._load_last_good()["config"]["device"] == "TPU v5 lite"


def test_git_helpers_against_real_repo():
    """The unstubbed helpers: HEAD resolves to a 40-hex commit that is in its
    own history; an all-zeros hash is not."""
    head = _real_git_head()
    assert head and len(head) == 40
    assert _real_commit_in_history(head)
    assert not _real_commit_in_history("0" * 40)


def test_save_last_good_stamps_commit_and_rejects_partial(monkeypatch):
    """ADVICE r3 (low): a mid-kill partial measurement must never become the
    persisted best-evidence record; complete saves are stamped with HEAD."""
    rec = bench._save_last_good(_mfu(0.44, model="llama-650m"))
    assert rec["value"] == 0.44 and rec["git_commit"] == bench._git_head()
    assert bench._save_last_good(_mfu(0.60, partial=True))["value"] == 0.44
    assert bench._load_last_good()["value"] == 0.44


def test_watchdog_never_persists_partial_best(monkeypatch):
    """The watchdog emission path strips the partial flag for the final line;
    the strip must happen AFTER the persistence decision."""
    import threading
    # monkeypatch (not bare assignment) so the fakes are restored even when
    # an assertion fails — _Best is module-global state shared across tests
    monkeypatch.setattr(bench._Best, "result", dict(_mfu(0.58, steps=2,
                                                         partial=True)))
    monkeypatch.setattr(bench._Best, "emitted", False)
    monkeypatch.setattr(bench._Best, "ladder", [])
    emitted = []

    class _Exit(Exception):
        pass

    def fake_exit(code):   # stop on_timeout like the real _exit would
        raise _Exit(code)

    monkeypatch.setattr(bench.os, "_exit", fake_exit)
    monkeypatch.setattr(bench, "_emit", emitted.append)
    captured = {}

    def fake_timer(seconds, fn):
        captured["fn"] = fn
        return type("T", (), {"daemon": True, "start": lambda self: None})()

    monkeypatch.setattr(threading, "Timer", fake_timer)
    bench._install_parent_watchdog(0.0)
    with pytest.raises(_Exit):
        captured["fn"]()   # fire the watchdog synchronously
    assert bench._load_last_good() is None     # partial never persisted
    assert len(emitted) == 1                   # only the best-result line
    assert "partial" not in emitted[0]         # ...emitted with the flag stripped
    assert emitted[0]["value"] == 0.58


def test_sweep_is_probe_gated_and_resumable(monkeypatch, capsys):
    """--sweep: completed experiments are skipped on re-run; a complete
    result lands in the sweep log and updates the last-good cache."""
    queue = [dict(name="exp_a", model="llama-650m", batch=8, seq=2048,
                  remat=True, remat_policy="attn_mlp"),
             dict(name="exp_b", model="llama-650m", batch=16, seq=2048,
                  remat=True, remat_policy="attn", optimizer="adafactor")]
    monkeypatch.setattr(bench, "SWEEP_QUEUE", queue)
    with open(bench.SWEEP_LOG_PATH, "w") as f:   # exp_a already done
        f.write(json.dumps({"name": "exp_a",
                            "config_hash": bench._exp_hash(queue[0]),
                            "result": _mfu(0.49)}) + "\n")
    sleeps = []
    monkeypatch.setattr(bench.time, "sleep", sleeps.append)
    fake = FakeChildren([([_mfu(0.52)], "ok")], probe_responses=[False, True])
    _, code = _run_main(monkeypatch, capsys, fake,
                        argv=("--watchdog", "0", "--sweep"))
    assert code == 0
    rung_calls = [c for c in fake.calls if c[0] == "--rung"]
    assert len(rung_calls) == 1      # exp_a skipped, exp_b run
    assert json.loads(rung_calls[0][1])["optimizer"] == "adafactor"
    assert len(sleeps) == 1          # gated on the failed probe
    with open(bench.SWEEP_LOG_PATH) as f:
        recs = [json.loads(l) for l in f]
    assert recs[-1]["name"] == "exp_b" and recs[-1]["result"]["value"] == 0.52
    assert bench._load_last_good()["value"] == 0.52


def test_sweep_hash_binding_and_oom_retirement(monkeypatch, capsys):
    """Records bind to their config hash: a complete result from an OLDER
    config under a reused name does not skip the current experiment, and two
    OOMs at the exact current config retire it (emitting retired_oom)."""
    queue = [dict(name="exp_a", model="llama-650m", batch=8, seq=2048,
                  remat=True, remat_policy="attn_mlp"),
             dict(name="exp_b", model="llama-650m", batch=16, seq=2048,
                  remat=True, remat_policy="attn")]
    monkeypatch.setattr(bench, "SWEEP_QUEUE", queue)
    stale_exp_a = dict(queue[0], batch=4)      # older config, same name
    with open(bench.SWEEP_LOG_PATH, "w") as f:
        f.write(json.dumps({"name": "exp_a",
                            "config_hash": bench._exp_hash(stale_exp_a),
                            "result": _mfu(0.40)}) + "\n")
        for _ in (1, 2):                       # exp_b: deterministic OOM x2
            f.write(json.dumps({"name": "exp_b", "kind": "oom",
                                "config_hash": bench._exp_hash(queue[1]),
                                "result": None}) + "\n")
    fake = FakeChildren([([_mfu(0.50)], "ok")])
    monkeypatch.setattr(bench, "_run_child", fake)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--watchdog", "0", "--sweep"])
    try:
        bench.main()
    except SystemExit as e:
        assert (e.code or 0) == 0
    out_lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()
                 if l.startswith("{")]
    rung_calls = [c for c in fake.calls if c[0] == "--rung"]
    assert len(rung_calls) == 1                  # exp_a re-run, exp_b skipped
    assert json.loads(rung_calls[0][1])["remat_policy"] == "attn_mlp"
    retired = [l for l in out_lines if l.get("status") == "retired_oom"]
    assert [l["sweep"] for l in retired] == ["exp_b"]


def test_sweep_pool_exhausted_backs_off_without_burning_attempts(
        monkeypatch, capsys):
    """A bare-capacity rejection (pool_exhausted) sleeps and relaunches
    instead of consuming one of the two real attempts."""
    queue = [dict(name="exp_a", model="llama-650m", batch=8, seq=2048,
                  remat=True, remat_policy="attn")]
    monkeypatch.setattr(bench, "SWEEP_QUEUE", queue)
    open(bench.SWEEP_LOG_PATH, "w").close()
    sleeps = []
    monkeypatch.setattr(bench.time, "sleep", sleeps.append)
    fake = FakeChildren([([], "pool_exhausted"), ([], "pool_exhausted"),
                         ([_mfu(0.51)], "ok")])
    monkeypatch.setattr(bench, "_run_child", fake)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--watchdog", "0", "--sweep"])
    try:
        bench.main()
    except SystemExit as e:
        assert (e.code or 0) == 0
    rung_calls = [c for c in fake.calls if c[0] == "--rung"]
    assert len(rung_calls) == 3        # 2 backoffs + the real (first) attempt
    assert len(sleeps) == 2            # one backoff sleep per rejection
    with open(bench.SWEEP_LOG_PATH) as f:
        recs = [json.loads(l) for l in f]
    assert recs[-1]["attempt"] == 1    # backoffs did not consume attempts
    assert recs[-1]["result"]["value"] == 0.51


def test_explicit_flags_build_single_rung(monkeypatch, capsys):
    """--optimizer/--fence-every/--loss-chunks build a one-rung ladder whose
    spec carries the flags through to the child verbatim."""
    fake = FakeChildren([([_mfu(0.50)], "ok")])
    final, _ = _run_main(
        monkeypatch, capsys, fake,
        argv=("--watchdog", "0", "--optimizer", "lion", "--fence-every", "4",
              "--loss-chunks", "8", "--skip-flash-check"))
    rung_calls = [c for c in fake.calls if c[0] == "--rung"]
    assert len(rung_calls) == 1
    spec = json.loads(rung_calls[0][1])
    assert (spec["optimizer"], spec["fence_every"], spec["loss_chunks"]) == \
        ("lion", 4, 8)
    assert spec["remat"] is True   # explicit flags on tpu default to remat
    assert final["value"] == 0.50
