"""Worker entry for the true multi-process tests (tests/test_multiprocess.py).

Launched by ``launch.local`` gangs (N real processes, K virtual CPU devices
each) to exercise code paths that only exist with ``jax.process_count() > 1``:
procguards barrier ordering, and a crash-once training run for the
supervisor's restart-all elasticity (reference ``related-topics/
elastic-training/README.md:5-16``). Training scenarios drive the REAL
``train.cli.run_training`` loop — not a test double — so multihost Orbax
save/restore and per-process batch-shard materialization run exactly as the
chapter entry points run them.
"""
import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import distributed_training_guide_tpu  # noqa: F401  (re-asserts JAX_PLATFORMS=cpu)
from distributed_training_guide_tpu.launch import maybe_initialize_distributed
from distributed_training_guide_tpu.launch.errors import record


def _emit(payload: dict) -> None:
    print("MPRESULT " + json.dumps(payload), flush=True)


def scenario_guard(args) -> None:
    """process0_first must hold back non-0 processes until process 0 finished
    its block (the only-rank0-downloads pattern, reference 02:272-280)."""
    import jax

    from distributed_training_guide_tpu.utils.procguards import (
        is_process0, process0_first, sync_processes)

    marker = Path(args.dir) / "proc0_done.txt"
    saw_marker_on_entry = None
    with process0_first():
        if is_process0():
            time.sleep(1.0)   # without the barrier, rank 1 would overtake this
            marker.write_text("warm cache")
        else:
            saw_marker_on_entry = marker.exists()
    sync_processes("guard_scenario_done")
    _emit({"rank": jax.process_index(),
           "world": jax.process_count(),
           "saw_marker_on_entry": saw_marker_on_entry})


def scenario_loader(args) -> None:
    """Per-host data footprint evidence (VERDICT r3 item 6): iterate a full
    epoch over a dp=8 batch sharding split across 2 processes and count the
    dataset rows this process actually fetches — it must be exactly its
    1/nproc share of every batch, and each addressable shard's content must
    match direct indexing of the corpus."""
    import jax
    import numpy as np

    from distributed_training_guide_tpu.data import ShardedBatchLoader
    from distributed_training_guide_tpu.data.pipeline import synthetic_dataset
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan

    arr = synthetic_dataset(20_000, 512, 16, seed=3)

    class Counting:
        shape, dtype = arr.shape, arr.dtype

        def __len__(self):
            return len(arr)

        def __getitem__(self, key):
            if isinstance(key, np.ndarray):
                self.rows = getattr(self, "rows", 0) + int(key.size)
            return arr[key]

    proxy = Counting()
    gb = 16
    plan = make_plan("ddp", make_mesh())
    loader = ShardedBatchLoader(proxy, gb, plan.batch_sharding(2),
                                seed=0, shuffle=False)
    content_ok = True
    n_batches = 0
    for step, batch in enumerate(loader.epoch_batches()):
        ids = batch["input_ids"]
        want = arr[step * gb:(step + 1) * gb]      # shuffle=False: in order
        for shard in ids.addressable_shards:
            if not np.array_equal(np.asarray(shard.data), want[shard.index]):
                content_ok = False
        n_batches += 1
    _emit({"rank": jax.process_index(), "rows_fetched": proxy.rows,
           "n_batches": n_batches, "global_batch": gb,
           "world": jax.process_count(), "content_ok": content_ok})


def scenario_crash_train(args) -> None:
    """Training run that injects one failure on rank 1 after the step-3
    checkpoint landed; a restarted gang resumes from it and finishes."""
    import jax

    from distributed_training_guide_tpu.parallel import make_mesh, make_plan
    from distributed_training_guide_tpu.train.cli import get_parser, run_training

    sentinel = Path(args.dir) / "crashed_once"
    first_incarnation = not sentinel.exists()
    max_steps = 4 if first_incarnation else 8

    train_args = get_parser().parse_args([
        "-m", "llama-debug", "-d", "synthetic:60000", "-s", "64", "-b", "1",
        "--num-epochs", "2", "--max-steps", str(max_steps), "--log-freq", "1",
        "--ckpt-freq", "3", "--save-dir", args.dir, "-e", "elastic",
    ])
    out = run_training(train_args,
                       lambda: make_plan("ddp", make_mesh()))

    if first_incarnation and jax.process_index() == 1:
        sentinel.write_text("injected")
        raise RuntimeError("injected failure after step-3 checkpoint (test)")

    _emit({"rank": jax.process_index(),
           "global_step": out["host_state"]["global_step"],
           "running_loss": out["last_info"]["running_loss"]})


@record
def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("scenario", choices=["guard", "crash_train", "loader"])
    parser.add_argument("--dir", required=True)
    args = parser.parse_args()
    maybe_initialize_distributed()
    {"guard": scenario_guard, "crash_train": scenario_crash_train,
     "loader": scenario_loader}[args.scenario](args)


if __name__ == "__main__":
    main()
