"""Fused decode horizons (``decode_horizon=K``): ONE compiled program runs
K decode iterations as an in-device scan, so a steady decode pays one host
dispatch — and one [n_slots, K] readback — per K tokens.

The claims under test:
- K > 1 is TOKEN-IDENTICAL to K = 1 for every completion (sampling keys
  are fold_in(seed, absolute position), so the horizon changes when the
  host observes tokens, never which tokens exist) — greedy and sampled,
  fp32 and int8 KV, llama and moe.
- A lane that finishes mid-horizon (EOS or budget) emits a strict prefix
  and its remaining in-horizon writes land ONLY in the trash page.
- Scheduler events (preemption, deadline eviction) happen at horizon
  boundaries and replay/evict bitwise — the pool invariants hold after
  every iteration of a chaos trace at K=4.
- speculate + decode_horizon>1 is rejected loudly everywhere it could be
  configured.
- The lowered horizon program's only cache avals are pool-shaped in/out
  (fusing K steps costs zero extra pool memory).
- The dispatch-amortization gauges plumb through engine stats, kv_report,
  and the router aggregate; spec_acceptance_rate is OMITTED (not 0.0)
  when nothing was drafted.

Everything runs debug-size models, inside tier-1.
"""
import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.serve import Request, ServeEngine
from distributed_training_guide_tpu.serve.api import generate_many
from distributed_training_guide_tpu.serve.disagg import DisaggEngine
from distributed_training_guide_tpu.utils import hlo as hlo_util

pytestmark = pytest.mark.multistep


@pytest.fixture(scope="module")
def llama():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    return bundle, bundle.init(bundle.config, jax.random.key(0))


def _fresh(req):
    return dataclasses.replace(req, request_id=None)


def _ref_engine(bundle, params, **kw):
    return ServeEngine(bundle, params, n_slots=1, prefix_cache=False, **kw)


def _drain(eng, max_iters=3000):
    out, it = [], 0
    while eng.has_work:
        out.extend(eng.step())
        it += 1
        assert it < max_iters, "engine stalled"
    return out


# ---- token identity ---------------------------------------------------------

@pytest.mark.parametrize(
    "name,kv_dtype",
    [("llama-debug", None),
     pytest.param("llama-debug", "int8", marks=pytest.mark.kvquant),
     ("moe-debug", None),
     pytest.param("moe-debug", "int8", marks=pytest.mark.kvquant)],
    ids=["llama-fp32", "llama-kv8", "moe-fp32", "moe-kv8"])
def test_batch1_identity_grid(name, kv_dtype):
    """The construction claim, batch-1: K in {2, 5} against the K=1 run of
    the same engine config, greedy AND temperature>0. max_new_tokens=7
    makes every K hit a short FINAL horizon (budget-clamped), so the tail
    path is in the grid, not just the steady K-step."""
    over = {"capacity_factor": 4.0} if name == "moe-debug" else {}
    bundle = get_model(name, dtype=jnp.float32, **over)
    params = bundle.init(bundle.config, jax.random.key(0))
    reqs = [Request(prompt_ids=[5, 9, 13], max_new_tokens=7, seed=0),
            Request(prompt_ids=[5, 9, 13], max_new_tokens=7,
                    temperature=0.9, top_k=8, seed=1)]

    def run(k):
        eng = ServeEngine(bundle, params, n_slots=1, page_size=4,
                          max_len=16, kv_dtype=kv_dtype, decode_horizon=k)
        return [r.token_ids
                for r in generate_many(eng, [_fresh(r) for r in reqs])]

    want = run(1)
    for k in (2, 5):
        assert run(k) == want, f"{name}/kv={kv_dtype}: K={k} diverged"


def test_disagg_horizon_identity_and_gauges(llama):
    """The disaggregated decode engine under a horizon: token-identical to
    its own K=1 run, with the dispatch gauges showing the amortization."""
    bundle, params = llama
    reqs = [Request(prompt_ids=[3 + i, 17, 42], max_new_tokens=8,
                    temperature=0.8 if i % 2 else 0.0, seed=i)
            for i in range(4)]

    def run(k):
        eng = DisaggEngine(bundle, params, n_slots=2, n_prefill_slots=1,
                           page_size=4, max_len=16, decode_horizon=k)
        res = generate_many(eng, [_fresh(r) for r in reqs])
        return [r.token_ids for r in res], eng.stats()

    want, st1 = run(1)
    got, st4 = run(4)
    assert got == want
    assert st4["decode_horizon"] == 4 and st1["decode_horizon"] == 1
    assert st4["host_dispatches"] < st1["host_dispatches"]
    assert st4["tokens_per_dispatch"] > st1["tokens_per_dispatch"]
    assert st4["horizon_effective"] > 1.5
    rep = DisaggEngine(bundle, params, n_slots=2, n_prefill_slots=1,
                       page_size=4, max_len=16,
                       decode_horizon=4).kv_report()
    assert rep["decode_horizon"] == 4
    assert rep["dispatches_per_step"] == 0.25


# ---- mid-horizon finishes ---------------------------------------------------

def test_eos_mid_horizon_strict_prefix_and_trash_containment(llama):
    """EOS fires INSIDE a 5-step horizon: the result is the strict prefix
    of the eos-free greedy stream ending at the eos token, and every pool
    page the slot never owned is bitwise untouched afterwards — the dead
    lane's remaining in-horizon writes landed only in the trash page."""
    bundle, params = llama
    free = generate_many(
        ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=32),
        [Request(prompt_ids=[5, 9, 13], max_new_tokens=10)])[0]
    # the eos must FIRST occur mid-stream (an earlier duplicate would
    # finish the request before the horizon even dispatches)
    idx = next(i for i in range(1, 10)
               if free.generated_ids[i] not in free.generated_ids[:i])
    eos = free.generated_ids[idx]        # dies mid-horizon-1

    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=32,
                      decode_horizon=5)
    before_k = np.asarray(eng.pages["k"])
    before_v = np.asarray(eng.pages["v"])
    eng.submit(Request(prompt_ids=[5, 9, 13], max_new_tokens=10,
                       eos_id=eos))
    touched, done, it = set(), [], 0
    while eng.has_work:
        done.extend(eng.step())
        for slot in eng.scheduler.slots:
            if slot is not None:
                touched.update(slot.pages)
        it += 1
        assert it < 200
    [res] = done
    assert res.finish_reason == "eos"
    assert res.generated_ids == free.generated_ids[:idx + 1]
    after_k = np.asarray(eng.pages["k"])
    after_v = np.asarray(eng.pages["v"])
    for p in range(eng.scheduler.pool.n_pages):
        if p in touched or p == 0:       # page 0 IS the trash page
            continue
        assert np.array_equal(before_k[:, p], after_k[:, p]), \
            f"page {p} written past EOS outside the trash page"
        assert np.array_equal(before_v[:, p], after_v[:, p]), \
            f"page {p} written past EOS outside the trash page"


# ---- boundary events --------------------------------------------------------

def test_preemption_at_horizon_boundaries_replays_bitwise(llama):
    """A pool far below worst case under K=4: preemptions fire (at horizon
    boundaries — the only place host state is authoritative), and every
    request — greedy AND sampled — replays to tokens identical to the
    batch-1 K=1 reference, with zero leaked pages."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=4, page_size=4, max_len=16,
                      n_pages=7, decode_horizon=4)
    reqs = [Request(prompt_ids=[3 + i, 17, 42][:1 + i % 3],
                    max_new_tokens=6 + (i % 5),
                    temperature=0.8 if i % 2 else 0.0, seed=i)
            for i in range(8)]
    res = generate_many(eng, [_fresh(r) for r in reqs],
                        max_iterations=3000)
    assert eng.scheduler.stats["preempted"] > 0
    ref_eng = _ref_engine(bundle, params, page_size=4, max_len=16)
    for got, req in zip(res, reqs):
        ref = generate_many(ref_eng, [_fresh(req)])[0]
        assert got.token_ids == ref.token_ids, \
            f"seed={req.seed} diverged across horizon-boundary preemption"
    pool = eng.scheduler.pool
    assert pool.n_free + eng.scheduler.cache_pages_held() == pool.capacity


def test_deadline_eviction_at_horizon_boundary_is_strict_prefix(llama):
    """A deadline expiring mid-stream under K=4 evicts at the next horizon
    boundary: finish_reason 'deadline', tokens a strict prefix of the
    undeadlined run, and the co-resident request unaffected."""
    bundle, params = llama
    baseline = generate_many(
        _ref_engine(bundle, params, page_size=4, max_len=64),
        [Request(prompt_ids=[7, 11], max_new_tokens=60, seed=1)])[0]

    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=64,
                      decode_horizon=4)
    keep = Request(prompt_ids=[5, 9, 13], max_new_tokens=8, seed=0)
    doomed = Request(prompt_ids=[7, 11], max_new_tokens=60,
                     deadline_s=0.05, seed=1)
    kid = eng.submit(keep)
    did = eng.submit(doomed)
    eng.step()                            # admit + first horizon
    time.sleep(0.08)                      # deadline passes mid-stream
    done = {r.request_id: r for r in _drain(eng)}
    assert done[did].finish_reason == "deadline"
    n = len(done[did].generated_ids)
    assert n < 60
    assert done[did].generated_ids == baseline.generated_ids[:n]
    ref = generate_many(_ref_engine(bundle, params, page_size=4,
                                    max_len=64), [_fresh(keep)])[0]
    assert done[kid].token_ids == ref.token_ids


def test_scheduler_chaos_trace_invariants_at_k4(llama):
    """The PR-3 property trace re-run under decode_horizon=4: random
    submit/step events on a tight pool with chunked prefill, asserting
    after EVERY iteration — including ones with a dispatched-but-unbooked
    horizon block in flight — that page refcounts equal holder counts,
    the trash page never enters a live table, free + held + cached pages
    balance to capacity, and every completion is token-identical to the
    K=1 batch-1 reference."""
    bundle, params = llama
    rng = np.random.default_rng(42)
    eng = ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=16,
                      n_pages=7, prefill_chunk=4, decode_horizon=4)
    sched, pool = eng.scheduler, eng.scheduler.pool
    done, submitted = [], []
    for it in range(400):
        if rng.random() < 0.3 and len(submitted) < 20:
            n_prompt = int(rng.integers(1, 10))
            req = Request(
                prompt_ids=[int(rng.integers(3, 500))
                            for _ in range(n_prompt)],
                max_new_tokens=int(rng.integers(4, 17 - n_prompt)),
                temperature=float(rng.choice([0.0, 0.9])),
                seed=len(submitted))
            submitted.append((eng.submit(req), req))
        done.extend(eng.step())

        held: dict = {}
        for slot in sched.slots:
            if slot is None:
                continue
            assert 0 not in slot.pages, "trash page in a live table"
            assert len(set(slot.pages)) == len(slot.pages)
            for p in slot.pages:
                held[p] = held.get(p, 0) + 1
        for p, n in _cache_page_refs(sched).items():
            held[p] = held.get(p, 0) + n
        for p, n in held.items():
            assert pool.refcount(p) == n, \
                f"page {p}: {n} holders but refcount {pool.refcount(p)}"
        assert pool.n_free + len(held) == pool.capacity
        if len(done) == len(submitted) and not eng.has_work and it > 100:
            break
    done.extend(_drain(eng))
    assert len(done) == len(submitted)
    assert sched.stats["preempted"] > 0        # the trace hit pressure
    by_id = {r.request_id: r for r in done}
    ref_eng = _ref_engine(bundle, params, page_size=4, max_len=16)
    for rid, req in submitted:
        ref = generate_many(ref_eng, [_fresh(req)])[0]
        assert by_id[rid].token_ids == ref.token_ids, f"seed={req.seed}"


def _cache_page_refs(sched) -> dict:
    refs: dict = {}
    if sched.cache is None:
        return refs
    stack = [sched.cache.root]
    while stack:
        node = stack.pop()
        for child in node.children.values():
            refs[child.page] = refs.get(child.page, 0) + 1
            stack.append(child)
    return refs


# ---- speculation exclusion --------------------------------------------------

def test_spec_plus_horizon_rejected_loudly(llama):
    """speculate= keeps K=1 this release: every path that could combine a
    drafter with a horizon>1 raises with an actionable message — ctor
    (both engines), set_decode_horizon under a live OR parked drafter,
    and set_speculation(True) under a horizon."""
    bundle, params = llama
    with pytest.raises(ValueError, match="decode_horizon"):
        ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=16,
                    speculate="ngram", decode_horizon=2)
    with pytest.raises(ValueError, match="decode_horizon"):
        DisaggEngine(bundle, params, n_slots=2, n_prefill_slots=1,
                     page_size=4, max_len=16, speculate="ngram",
                     decode_horizon=2)
    eng = ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=16,
                      speculate="ngram")
    with pytest.raises(ValueError, match="set_decode_horizon"):
        eng.set_decode_horizon(2)
    eng.set_speculation(False)            # parked, not gone
    with pytest.raises(ValueError, match="set_decode_horizon"):
        eng.set_decode_horizon(2)
    plain = ServeEngine(bundle, params, n_slots=1, page_size=4,
                        max_len=16, decode_horizon=4)
    with pytest.raises(ValueError, match="set_speculation"):
        plain.set_speculation(True)
    assert plain.set_decode_horizon(1) == 1   # and DOWN is always legal
    assert plain.set_decode_horizon(8) == 8


# ---- lowering pin -----------------------------------------------------------

def test_horizon_hlo_cache_avals_pool_shaped_only(llama):
    """The lowered K=4 horizon's cache tensors are exactly pool-shaped in
    and out — NO [K, ...pool] stacked cache anywhere (the scan's stacked
    output is only the [n_slots, K] token block), so fusing K steps costs
    zero extra pool memory."""
    bundle, params = llama
    cfg = bundle.config
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                      decode_horizon=4)
    arr = eng.scheduler.decode_arrays()
    lowered = eng.programs.horizon_for(4).lower(
        eng.params, eng.pages["k"], eng.pages["v"],
        jnp.asarray(arr["tokens"]), jnp.asarray(arr["lengths"]),
        jnp.asarray(arr["tables"]), jnp.asarray(arr["seeds"]),
        jnp.asarray(arr["temps"]), jnp.asarray(arr["top_ks"]),
        jnp.asarray(arr["top_ps"]), jnp.asarray(arr["actives"]),
        jnp.asarray(arr["budgets"]), jnp.asarray(arr["eos_ids"]),
        *eng.programs.lora_call_args(jnp.asarray(arr["adapters"])))
    text = lowered.as_text()
    pool_shape = (cfg.num_layers, eng.scheduler.pool.n_pages, 4,
                  cfg.num_kv_heads, cfg.head_size)
    assert hlo_util.has_aval(text, "f32", pool_shape), \
        "pool-shaped cache aval missing from the lowered horizon"
    assert not hlo_util.has_aval(text, "f32", (4,) + pool_shape), \
        "a K-stacked pool materialized in the horizon program"
    assert (hlo_util.has_aval(text, "i32", (2, 4))
            or hlo_util.has_aval(text, "s32", (2, 4))), \
        "[n_slots, K] token block missing from the lowered horizon"


# ---- gauge plumbing ---------------------------------------------------------

def test_stats_gauges_kv_report_and_spec_metric_omission(llama):
    """host_dispatches / tokens_per_dispatch / horizon_effective on engine
    stats; decode_horizon priced into kv_report; spec_acceptance_rate
    OMITTED — not 0.0 — when nothing was ever drafted."""
    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                      decode_horizon=4)
    # 1 prefill token + 8 decode steps = exactly two K=4 horizons
    generate_many(eng, [Request(prompt_ids=[5, 9, 13],
                                max_new_tokens=9, seed=0)])
    st = eng.stats()
    assert st["decode_horizon"] == 4
    assert st["host_dispatches"] == 2
    assert st["horizon_effective"] == 4.0
    assert st["tokens_per_dispatch"] == 4.0
    assert "spec_acceptance_rate" not in st, \
        "acceptance must be omitted, not 0.0, when nothing was drafted"
    rep = eng.kv_report()
    assert rep["decode_horizon"] == 4
    assert rep["dispatches_per_step"] == 0.25
    assert rep["horizon_block_bytes"] == 2 * 4 * 4


def test_router_aggregates_horizon_gauges(llama):
    """The fleet level: raw host_dispatches/horizon_ksum SUM across
    replicas and the ratios re-derive from the sums (averaging the
    per-replica ratios would be wrong under uneven traffic); the fleet
    spec_acceptance_rate stays omitted when no replica drafted."""
    from distributed_training_guide_tpu.serve.router import Replica, Router
    bundle, params = llama
    engines = [ServeEngine(bundle, params, n_slots=2, page_size=4,
                           max_len=16, decode_horizon=k) for k in (2, 4)]
    for i, eng in enumerate(engines):
        generate_many(eng, [Request(prompt_ids=[5 + i, 9, 13],
                                    max_new_tokens=9, seed=i)])
    router = Router([Replica(f"r{i}", e) for i, e in enumerate(engines)])
    st = router.stats()
    want_disp = sum(e.stats()["host_dispatches"] for e in engines)
    want_ksum = sum(e.horizon_ksum for e in engines)
    assert st["host_dispatches"] == want_disp
    assert st["horizon_ksum"] == want_ksum
    assert st["horizon_effective"] == round(want_ksum / want_disp, 3)
    assert st["tokens_per_dispatch"] == round(
        sum(e.stats()["decode_tokens"] for e in engines) / want_disp, 3)
    assert "spec_acceptance_rate" not in st
