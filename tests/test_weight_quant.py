"""Int8 serve-plane weights (``weight_dtype="int8"``): block-wise
quantized params (serve/weights.py) dequantized inside the matmul loop
(ops/quantized_matmul.py).

What is pinned here, and why these meters:

- ROUND-TRIP + MATMUL PARITY with documented bounds: per-element
  quantization error is <= scale/2 = that block's absmax/254 (~0.4% of
  the block absmax). The standard-form quantized matmul computes each
  output column from ONE dequantized ``[K, bs]`` block, the identical
  contraction ``x @ dequant(w)`` performs — parity is 1e-5, not a
  quantization bound. The transpose form (tied lm_head) accumulates per
  block, so its bound is loose only in summation order (1e-4). The
  interpret-mode Pallas kernel reads the SAME bytes as the XLA scan —
  their difference is kernel error, not quantization.
- FORWARD PARITY split in two: int8-vs-fp logits stay inside LOGIT_ATOL
  across the llama feature grid (GQA, sliding window, softcap), and
  int8-vs-SNAPPED-fp (the same rounded weights served from fp storage)
  stays inside machine-epsilon territory — the storage path must add
  nothing beyond the rounding it stores.
- BYTE + HLO PINS: llama-debug int8 weights (scales included) are
  0.2847x the fp32 tree — comfortably past the >= 1.9x-smaller
  acceptance pin (<= 0.53x) — and analytic ``weight_bytes_by_dtype``
  matches the resident arrays byte for byte, publish payloads included.
  The lowered decode contains NO f32 aval of any full weight-tensor
  shape: dequant transients are one trailing block wide by construction
  (``weight_block_size`` keeps >= 2 blocks per leaf).
- PUBLISH: an fp-layout publish re-quantizes under ONE compiled program
  — decode-after-publish is bitwise equal to a fresh engine built from
  the published params and every jit cache size stays flat; a stale
  layout fails loudly naming the leaf.
- FLEET: ``weight_dtype`` is baked into the shared ModelPrograms like
  ``kv_dtype`` (rejected as a generation-swap override), routers refuse
  mixed-precision fleets at construction AND add_replica (the
  all-or-nothing publish contract), and ``spawn_like`` clones inherit
  the fleet's weight_dtype + kv_dtype — the cold-start bugfix pin.
- QUALITY METERS: spec acceptance under int8 weights within 0.02 of the
  snapped-fp control (the same meter kvq runs for pages; the rounding's
  own effect on this random-init model is recorded ungated by bench's
  wq_spec_accept), and the QLoRA loop — int8-snapped frozen base + fp
  LoRA (post.qlora_base, arXiv:2305.14314) — tracks the fp lora_only
  control's reward trajectory while publishing retrace-free. The int8
  random-trace re-run lives in test_serve.py (parameterized).
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_training_guide_tpu.models import get_model
from distributed_training_guide_tpu.models.llama import LlamaConfig
from distributed_training_guide_tpu.models import llama as llama_mod
from distributed_training_guide_tpu.ops.quantized_matmul import (
    quantized_matmul, quantized_matmul_eligible, quantized_take)
from distributed_training_guide_tpu.serve.api import generate_many
from distributed_training_guide_tpu.serve.engine import ServeEngine
from distributed_training_guide_tpu.serve.scheduler import Request
from distributed_training_guide_tpu.serve.weights import (
    WEIGHT_BLOCK, is_quantizable_path, params_nbytes, store_weights,
    weight_block_size, weight_bytes_by_dtype, weight_dtype_name,
    weight_tree_bytes)
from distributed_training_guide_tpu.train.precision import (
    Quantized, dequantize_blockwise, quantize_blockwise)
from distributed_training_guide_tpu.utils import hlo as hlo_util

pytestmark = [pytest.mark.serve, pytest.mark.wquant]

# documented bound for int8-vs-fp LOGITS on N(0, 0.02) random-init params
# (block absmax/254 per weight compounds through 2 layers to <~1e-2
# observed; 5e-2 is the same ~5x margin the kv-quant grid uses)
LOGIT_ATOL = 5e-2
# int8-vs-snapped-fp: same rounded weights, fp32 compute both sides — the
# storage path may only differ in summation order (the transpose form's
# per-block accumulator)
MECHANISM_ATOL = 1e-4


@pytest.fixture(scope="module")
def llama():
    bundle = get_model("llama-debug", dtype=jnp.float32)
    return bundle, bundle.init(bundle.config, jax.random.key(0))


def _fresh(req):
    return dataclasses.replace(req, request_id=None)


def _snapped(params):
    """The same int8 grid served from fp storage (quantize -> dequantize
    of exactly the leaves store_weights selects)."""
    from distributed_training_guide_tpu.post import qlora_base

    return qlora_base(params)


# ---- policy: names, block sizes, leaf selection -----------------------------

def test_weight_dtype_name_block_size_and_leaf_selection():
    cfg = get_model("llama-debug", dtype=jnp.float32).config
    assert weight_dtype_name(cfg, None) == "fp32"     # param_dtype inherit
    assert weight_dtype_name(cfg, "float32") == "fp32"
    assert weight_dtype_name(cfg, "bfloat16") == "bf16"
    assert weight_dtype_name(cfg, "int8") == "int8"
    with pytest.raises(ValueError, match="weight_dtype"):
        weight_dtype_name(cfg, "fp8")
    # block clamp: every leaf must split into >= 2 blocks (the per-leaf
    # no-full-fp32-transient guarantee)
    assert weight_block_size(512) == WEIGHT_BLOCK
    assert weight_block_size(64) == WEIGHT_BLOCK
    assert weight_block_size(48) == 24
    assert weight_block_size(3) == 1
    assert is_quantizable_path("layers/attn/wq")
    assert is_quantizable_path("embed/embedding")
    assert is_quantizable_path("lm_head")
    assert not is_quantizable_path("layers/input_norm")
    assert not is_quantizable_path("final_norm")
    # non-llama families refuse before compile, never serve half-quantized
    with pytest.raises(ValueError, match="llama family only"):
        store_weights({"w": jnp.ones((4, 4))}, "int8", family="gpt2")
    with pytest.raises(ValueError, match="llama family only"):
        weight_tree_bytes({"w": jax.ShapeDtypeStruct((4, 4), jnp.float32)},
                          "int8", "moe")


def test_store_weights_layout_and_roundtrip_bound(llama):
    """int8 selects exactly the projection leaves; norms keep their param
    dtype; every quantized leaf's round-trip error obeys the per-block
    absmax/254 bound."""
    bundle, params = llama
    stored = store_weights(params, "int8", family="llama")
    for proj in ("wq", "wk", "wv", "wo"):
        assert isinstance(stored["layers"]["attn"][proj], Quantized)
    for proj in ("gate", "up", "down"):
        assert isinstance(stored["layers"]["mlp"][proj], Quantized)
    assert isinstance(stored["embed"]["embedding"], Quantized)
    assert isinstance(stored["lm_head"], Quantized)
    for norm in ("input_norm", "post_attn_norm"):
        leaf = stored["layers"][norm]
        assert not isinstance(leaf, Quantized)
        assert leaf.dtype == params["layers"][norm].dtype
    qt = stored["layers"]["mlp"]["gate"]           # [L, 64, 128], bs=32
    assert qt.q.dtype == jnp.int8 and qt.q.shape == (2, 64, 128)
    assert qt.scale.dtype == jnp.float32 and qt.scale.shape == (2, 64, 4)
    src = np.asarray(params["layers"]["mlp"]["gate"], np.float32)
    back = np.asarray(dequantize_blockwise(qt))
    amax = np.abs(src.reshape(2, 64, 4, 32)).max(-1, keepdims=True)
    bound = np.broadcast_to(amax / 254 + 1e-9, (2, 64, 4, 32))
    np.testing.assert_array_less(np.abs(back - src).reshape(bound.shape),
                                 bound)
    # fp32/bf16 are plain storage casts of inexact leaves
    bf = store_weights(params, "bf16", family="llama")
    assert bf["lm_head"].dtype == jnp.bfloat16


def test_weight_bytes_tables_match_resident_and_ratio_pin(llama):
    """Analytic bytes == actual resident bytes for every dtype row, and
    the int8 row clears the acceptance pin: >= 1.9x smaller than fp32
    (ratio <= 0.53), publish payloads shrinking with it."""
    bundle, params = llama
    shapes = jax.eval_shape(lambda: bundle.init(bundle.config,
                                                jax.random.key(0)))
    table = weight_bytes_by_dtype(shapes, "llama")
    assert set(table) == {"fp32", "bf16", "int8"}
    for name in ("fp32", "bf16", "int8"):
        stored = store_weights(params, name, family="llama")
        assert params_nbytes(stored) == table[name], name
    assert table["int8"] / table["fp32"] <= 0.53   # 1.9x-smaller pin
    assert table["bf16"] == table["fp32"] // 2
    # no int8 row without a leaf-selection rule for the family
    assert "int8" not in weight_bytes_by_dtype(shapes, "gpt2")
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                      weight_dtype="int8")
    rep = eng.weight_report()
    assert rep["weight_dtype"] == "int8"
    assert rep["weight_bytes"] == table["int8"] == eng.weight_bytes()
    assert rep["bytes_vs_fp32"] <= 0.53
    assert rep["publish_payload_bytes"] == table["int8"]
    assert rep["publish_payload_bytes_fp"] == table["fp32"]
    fp_eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16)
    assert fp_eng.weight_report()["weight_dtype"] == "fp32"
    assert fp_eng.weight_bytes() / eng.weight_bytes() >= 1.9


# ---- quantized matmul -------------------------------------------------------

def test_quantized_matmul_standard_transpose_take_and_errors():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((5, 64)), jnp.float32)
    for k, n, bs in [(64, 64, 32), (64, 512, 32), (64, 33, 32), (7, 10, 5)]:
        w = rng.standard_normal((k, n)).astype(np.float32)
        qt = quantize_blockwise(jnp.asarray(w), block_size=bs)
        xk = jnp.asarray(rng.standard_normal((5, k)), jnp.float32)
        want = np.asarray(xk @ dequantize_blockwise(qt))
        got = np.asarray(quantized_matmul(xk, qt, impl="xla"))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    # transpose form (tied lm_head): blocks tile the CONTRACTED axis,
    # scale factors out per block — parity bound is summation order only
    wt = rng.standard_normal((48, 64)).astype(np.float32)
    qtt = quantize_blockwise(jnp.asarray(wt), block_size=32)
    want = np.asarray(x @ dequantize_blockwise(qtt).T)
    got = np.asarray(quantized_matmul(x, qtt, transpose=True, impl="xla"))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=MECHANISM_ATOL)
    # leading dims flatten and restore
    x3 = x.reshape(1, 5, 64)
    qe = quantize_blockwise(jnp.asarray(
        rng.standard_normal((64, 96)).astype(np.float32)), block_size=32)
    assert quantized_matmul(x3, qe).shape == (1, 5, 96)
    # embedding gather dequantizes only the gathered rows
    table = quantize_blockwise(jnp.asarray(
        rng.standard_normal((32, 48)).astype(np.float32)), block_size=16)
    ids = jnp.asarray([[3, 31, 0]])
    np.testing.assert_allclose(
        np.asarray(quantized_take(table, ids)),
        np.asarray(dequantize_blockwise(table))[np.asarray(ids)],
        rtol=1e-6, atol=1e-6)
    with pytest.raises(ValueError, match="impl"):
        quantized_matmul(x, qtt, impl="cuda")
    with pytest.raises(ValueError, match="2-D"):
        quantized_matmul(x, Quantized(q=jnp.zeros((2, 4, 64), jnp.int8),
                                      scale=jnp.ones((2, 4, 2))))
    with pytest.raises(ValueError, match="contraction mismatch"):
        quantized_matmul(x, qe.__class__(q=qe.q[:32], scale=qe.scale[:32]))

    class _SqrtShim:
        def __init__(self, qt):
            self.q, self.scale, self.sqrt_domain = qt.q, qt.scale, True

    with pytest.raises(ValueError, match="sqrt_domain"):
        quantized_matmul(x, _SqrtShim(qtt))


def test_quantized_matmul_pallas_interpret_parity_and_eligibility():
    """The interpret-mode kernel reads the same int8 bytes + scale
    columns as the XLA scan — parity is kernel correctness. Eligibility
    mirrors the TPU int8 tile floor: lane-dim blocks (bs % 128) over an
    int8-tileable contraction dim (K % 32), no padded tail."""
    rng = np.random.default_rng(1)
    w = rng.standard_normal((64, 256)).astype(np.float32)
    qt = quantize_blockwise(jnp.asarray(w), block_size=128)
    assert quantized_matmul_eligible(qt)
    assert not quantized_matmul_eligible(qt, transpose=True)  # XLA carries it
    x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)
    ref = np.asarray(quantized_matmul(x, qt, impl="xla"))
    got = np.asarray(quantized_matmul(x, qt, impl="pallas", interpret=True))
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)
    # bs=32 blocks are under the 128 lane tile; K=7 breaks the int8
    # sublane; a padded tail block can't ride the BlockSpec grid
    assert not quantized_matmul_eligible(
        quantize_blockwise(jnp.asarray(w), block_size=32))
    assert not quantized_matmul_eligible(quantize_blockwise(
        jnp.asarray(rng.standard_normal((7, 256)), jnp.float32),
        block_size=128))
    with pytest.raises(NotImplementedError, match="transpose"):
        quantized_matmul(x, quantize_blockwise(jnp.asarray(w.T).astype(
            jnp.float32), block_size=32), transpose=True, impl="pallas")


# ---- forward parity grid ----------------------------------------------------

def _variant(**kw):
    base = dict(vocab_size=96, hidden_size=64, intermediate_size=96,
                num_layers=2, num_heads=4, num_kv_heads=2,
                max_position_embeddings=32, dtype=jnp.float32,
                param_dtype=jnp.float32)
    base.update(kw)
    return LlamaConfig(**base)


FORWARD_GRID = [
    ("gqa4-2", _variant()),
    ("gqa8-1", _variant(num_heads=8, num_kv_heads=1)),
    ("window", _variant(sliding_window=5)),
    ("softcap", _variant(attn_logit_softcap=20.0, final_logit_softcap=30.0,
                         query_pre_attn_scalar=16.0)),
    ("tied", _variant(tie_word_embeddings=True)),
]


@pytest.mark.parametrize("name,cfg", FORWARD_GRID, ids=[n for n, _ in
                                                        FORWARD_GRID])
def test_int8_forward_parity_grid(name, cfg):
    """Full-forward logits across the llama feature grid: int8-vs-fp
    inside the documented quantization bound, and int8-vs-snapped-fp
    inside summation-order epsilon — the storage path adds nothing
    beyond the rounding it stores."""
    params = llama_mod.init(cfg, jax.random.key(2))
    stored = store_weights(params, "int8", family="llama")
    ids = jnp.asarray([[5, 11, 3, 60, 8, 1, 44, 9]])
    fp = np.asarray(llama_mod.apply(cfg, params, ids))
    q8 = np.asarray(llama_mod.apply(cfg, stored, ids))
    snap = np.asarray(llama_mod.apply(cfg, _snapped(params), ids))
    assert float(np.max(np.abs(q8 - fp))) < LOGIT_ATOL
    assert float(np.max(np.abs(q8 - snap))) < MECHANISM_ATOL


# ---- engine-level pins ------------------------------------------------------

def test_int8_engine_batch1_spec_and_chunk_identity(llama):
    """Engine invariants WITHIN the int8-weights config: co-batched
    completions equal their batch-1 runs, spec-on == spec-off (verify
    reads the same quantized params as decode), and the chunked-prefill
    program agrees with its own batch-1 twin."""
    bundle, params = llama
    reqs = [Request(prompt_ids=[3 + i, 17, 42], max_new_tokens=8,
                    temperature=0.9 if i % 2 else 0.0, seed=i)
            for i in range(4)]
    eng = ServeEngine(bundle, params, n_slots=4, page_size=4, max_len=32,
                      weight_dtype="int8")
    res = generate_many(eng, reqs)
    ref = ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=32,
                      weight_dtype="int8")
    for r, req in zip(res, reqs):
        assert r.token_ids == generate_many(ref, [_fresh(req)])[0].token_ids
    assert eng.weight_dtype == "int8"
    # spec-on == spec-off under quantized weights
    block = [7, 11, 13, 17, 19, 23, 29, 31]
    sreqs = [Request(prompt_ids=(block * 6)[:48] + [40 + i],
                     max_new_tokens=24, seed=i) for i in range(3)]

    def run(speculate):
        e = ServeEngine(bundle, params, n_slots=3, page_size=8, max_len=128,
                        weight_dtype="int8", speculate=speculate, spec_k=6)
        return [r.token_ids
                for r in generate_many(e, [_fresh(r) for r in sreqs])]

    assert run("ngram") == run(None), "spec-on != spec-off under int8"
    # chunked prefill, program-relative identity (same config both sides)
    creqs = [Request(prompt_ids=[3 + (j % 40) for j in range(12)],
                     max_new_tokens=6, seed=9)]
    chunk = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=32,
                        prefill_chunk=4, weight_dtype="int8")
    cref = ServeEngine(bundle, params, n_slots=1, page_size=4, max_len=32,
                       prefill_chunk=4, prefix_cache=False,
                       weight_dtype="int8")
    assert ([r.token_ids for r in generate_many(chunk, creqs)]
            == [r.token_ids
                for r in generate_many(cref, [_fresh(creqs[0])])])


def test_int8_spec_acceptance_meter_vs_snapped_fp(llama):
    """THE quality meter (bench wq_spec_accept's CI pin): acceptance on
    the lookup-friendly workload under int8 weights within 0.02 of the
    snapped-fp control — same rounded policy, fp storage — so the gated
    variable is the storage + in-kernel-dequant path, not the rounding
    (whose effect on this random-init model bench records ungated)."""
    bundle, params = llama
    block = [7, 11, 13, 17, 19, 23, 29, 31]
    prompt = (block * 6)[:48]
    reqs = [Request(prompt_ids=prompt + [40 + i], max_new_tokens=48,
                    seed=i) for i in range(4)]

    def run(p, weight_dtype):
        eng = ServeEngine(bundle, p, n_slots=4, page_size=8, max_len=128,
                          weight_dtype=weight_dtype, speculate="ngram",
                          spec_k=6)
        generate_many(eng, [_fresh(r) for r in reqs])
        return eng.stats()["spec_acceptance_rate"]

    acc8 = run(params, "int8")
    acc_snap = run(_snapped(params), None)
    assert acc8 > 0.0
    assert abs(acc8 - acc_snap) <= 0.02, \
        f"int8 weight storage moved spec acceptance by " \
        f"{acc8 - acc_snap:+.3f} vs the snapped-fp control"


def test_int8_decode_hlo_no_fp32_weight_avals(llama):
    """The lowered decode never materializes a full fp32 weight tensor:
    no f32 aval of any stacked projection / embed / lm_head shape (the
    dequant transient is one trailing block wide), with the int8
    payloads present as s8/i8 avals."""
    bundle, params = llama
    cfg = bundle.config
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                      weight_dtype="int8")
    arr = eng.scheduler.decode_arrays()
    text = eng._decode_fn.lower(
        eng.params, eng.pages["k"], eng.pages["v"],
        jnp.asarray(arr["tokens"]), jnp.asarray(arr["lengths"]),
        jnp.asarray(arr["tables"]), jnp.asarray(arr["seeds"]),
        jnp.asarray(arr["temps"]), jnp.asarray(arr["top_ks"]),
        jnp.asarray(arr["top_ps"]), jnp.asarray(arr["actives"])).as_text()
    e, f, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    hq = cfg.num_heads * cfg.head_size
    hkv = cfg.num_kv_heads * cfg.head_size
    l = cfg.num_layers
    full_weight_shapes = [
        (l, e, hq), (l, e, hkv), (l, hq, e),     # wq / wk|wv / wo stacks
        (l, e, f), (l, f, e),                    # gate|up / down stacks
        (v, e), (e, v),                          # embed / lm_head
    ]
    for shape in full_weight_shapes:
        assert not hlo_util.has_aval(text, "f32", shape), \
            f"full fp32 weight aval {shape} in the int8 decode"
    assert (hlo_util.has_aval(text, "i8", (l, e, hq))
            or hlo_util.has_aval(text, "s8", (l, e, hq))), \
        "int8 weight payload aval missing from the lowered decode"
    assert isinstance(eng.params["lm_head"], Quantized)


def test_publish_fp_requant_bitwise_vs_fresh_and_cache_flat(llama):
    """The trainer->engine seam under quantized storage: an fp-layout
    publish re-quantizes through one compiled program — decode after the
    publish is bitwise a fresh int8 engine built from the published
    params, jit caches stay flat, and a stale layout fails loudly."""
    bundle, params = llama
    p1 = bundle.init(bundle.config, jax.random.key(7))
    reqs = [Request(prompt_ids=[3 + i, 17, 42], max_new_tokens=8, seed=i)
            for i in range(3)]
    eng = ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=32,
                      weight_dtype="int8")
    generate_many(eng, [_fresh(r) for r in reqs])          # warm everything
    sizes0 = eng.programs.jit_cache_sizes()
    count0 = eng.programs.publish_count
    assert eng.publish_params(p1) == count0 + 1            # fp layout
    assert eng.programs.jit_cache_sizes() == sizes0, \
        "fp publish retraced a serving program"
    got = [r.token_ids for r in generate_many(eng, [_fresh(r)
                                                    for r in reqs])]
    fresh = ServeEngine(bundle, p1, n_slots=3, page_size=4, max_len=32,
                        weight_dtype="int8")
    want = [r.token_ids for r in generate_many(fresh, [_fresh(r)
                                                       for r in reqs])]
    assert got == want, "publish->decode != fresh engine on the params"
    assert eng.programs.jit_cache_sizes() == sizes0
    # second fp publish reuses the same requant program
    eng.publish_params(params)
    assert eng.programs.jit_cache_sizes() == sizes0
    # the compiled (quantized) layout publishes through the classic path
    eng.publish_params(store_weights(p1, "int8", family="llama"))
    # a stale fp layout fails loudly, naming the leaf
    bad = jax.tree.map(lambda x: x, p1)
    bad["lm_head"] = bad["lm_head"][:, :-1]
    with pytest.raises(ValueError, match="fp publish layout expects"):
        eng.publish_params(bad)
    wrong_dtype = jax.tree.map(lambda x: x.astype(jnp.bfloat16), p1)
    with pytest.raises(ValueError, match="fp publish layout expects"):
        eng.publish_params(wrong_dtype)


def test_weight_dtype_baked_router_agreement_and_spawn_inherits(llama):
    """weight_dtype rides the shared ModelPrograms exactly like kv_dtype:
    a generation swap cannot override it, a router refuses a
    mixed-precision fleet (construction and add_replica), and spawn_like
    cold-start clones inherit the fleet's weight_dtype AND kv_dtype —
    the bugfix pin for control-plane scale-ups."""
    from distributed_training_guide_tpu.serve.elastic import (new_generation,
                                                              spawn_like)
    from distributed_training_guide_tpu.serve.router import (Replica, Router,
                                                             local_fleet)

    bundle, params = llama
    eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16,
                      weight_dtype="int8")
    with pytest.raises(ValueError, match="baked"):
        new_generation(eng, weight_dtype="bf16")
    with pytest.raises(ValueError, match="baked"):
        new_generation(eng, weight_dtype=None)
    fp_eng = ServeEngine(bundle, params, n_slots=2, page_size=4, max_len=16)
    with pytest.raises(ValueError, match="disagree on weight_dtype"):
        Router([Replica("a", eng), Replica("b", fp_eng)])
    router = local_fleet(bundle, params, n_replicas=2, n_slots=2,
                         page_size=4, max_len=16, weight_dtype="int8")
    assert router.weight_dtype == "int8"
    with pytest.raises(ValueError, match="weight_dtype"):
        router.add_replica(Replica("odd-one", fp_eng))
    # the spawn-inherits-config pin: the clone shares the fleet's
    # programs, so both storage dtypes carry over without restating them
    spawned = spawn_like(router, name="r9")
    assert spawned.engine.weight_dtype == "int8"
    assert spawned.engine.kv_dtype == router.kv_dtype
    assert spawned.engine.programs is \
        next(iter(router.replicas.values())).engine.programs
    router.add_replica(spawned)                    # and it is routable
    assert "r9" in router.replicas


def test_qlora_base_idempotent_and_loop_tracks_fp_control(llama):
    """QLoRA (arXiv:2305.14314): (a) qlora_base snaps the base onto the
    SAME int8 grid the engine stores — requantizing the snapped base
    reproduces payload and scales bitwise, so adapters train against the
    policy actually served; (b) the lora_only loop over an int8-weights
    engine publishes retrace-free and its reward trajectory stays within
    the documented noise floor of the fp lora_only control (0.1 at this
    rollout count — the band-reward std over 12x8 sampled tokens)."""
    from distributed_training_guide_tpu.models.lora import lora_bundle
    from distributed_training_guide_tpu.post import (PostTrainingLoop,
                                                     ProgrammaticScorer,
                                                     band_reward,
                                                     merged_params,
                                                     qlora_base)
    from distributed_training_guide_tpu.train.optimizer import adamw_cosine
    from distributed_training_guide_tpu.train.step import Trainer

    bundle, params = llama
    snapped = qlora_base(params)
    s1 = store_weights(params, "int8", family="llama")
    s2 = store_weights(snapped, "int8", family="llama")
    for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(s2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    norm = snapped["final_norm"]
    np.testing.assert_array_equal(np.asarray(norm),       # passthrough
                                  np.asarray(params["final_norm"]))

    def arm(quantized):
        wrapped = lora_bundle(bundle, rank=4, alpha=8.0)
        init = wrapped.init(wrapped.config, jax.random.key(0))
        if quantized:
            init = {"base": qlora_base(init["base"]), "lora": init["lora"]}
        trainer = Trainer(bundle=wrapped, optimizer=adamw_cosine(0.1),
                          lora_only=True, guard_policy="skip")
        state = trainer.init_state_from_params(init)
        engine = ServeEngine(bundle, merged_params(trainer, state),
                             n_slots=4, page_size=16, max_len=32,
                             weight_dtype="int8" if quantized else None)
        loop = PostTrainingLoop(
            trainer, engine, ProgrammaticScorer(band_reward(64)),
            [[3, 10, 17]] * 12, state=state, max_new_tokens=8,
            temperature=1.0, base_seed=0)
        loop.run(1)                          # iteration 0 pays the compiles
        sizes0 = engine.programs.jit_cache_sizes()
        hist = loop.history + loop.run(2)
        assert engine.programs.jit_cache_sizes() == sizes0, \
            "a QLoRA publish retraced a serving program"
        assert loop.publishes == 3
        return [m["reward_mean"] for m in hist]

    qlora_traj = arm(quantized=True)
    fp_traj = arm(quantized=False)
    assert all(np.isfinite(qlora_traj))
    gap = max(abs(a - b) for a, b in zip(qlora_traj, fp_traj))
    assert gap <= 0.1, \
        f"QLoRA reward trajectory drifted {gap:.3f} from the fp control " \
        f"(trajectories {qlora_traj} vs {fp_traj})"


@pytest.mark.slow
def test_int8_weights_sharded_tp2(llama, eight_devices):
    """tp=2 over quantized params: the int8 payload inherits its leaf's
    sharding, scales shard their trailing block axis only when every
    shard holds whole blocks — and the sharded engine stays
    token-identical to the replicated int8 engine."""
    from distributed_training_guide_tpu.parallel import make_mesh, make_plan

    bundle, params = llama
    plan = make_plan("tp", make_mesh(tp=2, devices=eight_devices[:2]))
    reqs = [Request(prompt_ids=[3 + i, 17, 42], max_new_tokens=6, seed=i)
            for i in range(3)]
    eng = ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=32,
                      plan=plan, weight_dtype="int8")
    res = generate_many(eng, [_fresh(r) for r in reqs])
    repl = ServeEngine(bundle, params, n_slots=3, page_size=4, max_len=32,
                      weight_dtype="int8")
    ref = generate_many(repl, [_fresh(r) for r in reqs])
    assert [r.token_ids for r in res] == [r.token_ids for r in ref]
    sharded = [leaf for leaf in jax.tree.leaves(
        eng.params, is_leaf=lambda x: isinstance(x, Quantized))
        if isinstance(leaf, Quantized)
        and leaf.q.addressable_shards[0].data.shape != leaf.q.shape]
    assert sharded, "tp plan left every quantized payload replicated"
    for leaf in sharded:
        qshard = leaf.q.addressable_shards[0].data.shape
        sshard = leaf.scale.addressable_shards[0].data.shape
        d, nb = leaf.q.shape[-1], leaf.scale.shape[-1]
        bs = -(-d // nb)
        if qshard[-1] != leaf.q.shape[-1]:     # trailing-sharded payload
            assert qshard[-1] % bs == 0, \
                "a shard split a quantization block"
            assert sshard[-1] == nb // (d // qshard[-1])
